"""PG-level value types: versions, log entries, missing set, shards.

Modeled on the reference's osd_types (ref: src/osd/osd_types.h —
eversion_t, pg_log_entry_t, pg_missing_t, pg_shard_t), trimmed to what
the TPU build's data path consumes.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional


@dataclass(frozen=True, order=True)
class EVersion:
    """(epoch, version) — totally ordered (ref: osd_types.h eversion_t)."""
    epoch: int = 0
    version: int = 0

    def __bool__(self) -> bool:
        return self != ZERO_VERSION

    def __str__(self) -> str:
        return f"{self.epoch}'{self.version}"


ZERO_VERSION = EVersion(0, 0)


@dataclass(frozen=True, order=True)
class PGShard:
    """Which OSD holds which EC shard (ref: osd_types.h pg_shard_t)."""
    osd: int
    shard: int = -1     # NO_SHARD for replicated

    def __str__(self) -> str:
        return f"osd.{self.osd}" + \
            (f"(s{self.shard})" if self.shard != -1 else "")


# log entry op kinds (ref: osd_types.h pg_log_entry_t::{MODIFY,...})
MODIFY = "modify"
DELETE = "delete"
CLONE = "clone"
ERROR = "error"
LOST_REVERT = "lost_revert"


@dataclass
class PGLogEntry:
    """One log record (ref: osd_types.h pg_log_entry_t)."""
    op: str
    soid: str
    version: EVersion
    prior_version: EVersion = ZERO_VERSION
    reqid: str = ""
    #: rollback info present (the reference attaches per-op rollback
    #: blobs via can_rollback(); here a flag + optional payload)
    rollbackable: bool = False

    def is_update(self) -> bool:
        return self.op in (MODIFY, CLONE, LOST_REVERT)

    def is_delete(self) -> bool:
        return self.op == DELETE

    def is_error(self) -> bool:
        return self.op == ERROR

    def is_clone(self) -> bool:
        return self.op == CLONE

    def can_rollback(self) -> bool:
        return self.rollbackable

    def __str__(self) -> str:
        return f"{self.version}({self.prior_version}) {self.op} {self.soid}"


@dataclass
class MissingItem:
    """(ref: osd_types.h pg_missing_item)."""
    need: EVersion
    have: EVersion = ZERO_VERSION
    is_delete: bool = False


class PGMissing:
    """Objects a shard lacks, by version (ref: src/osd/osd_types.h
    pg_missing_t / pg_missing_set; add_next_event semantics from
    osd_types.h pg_missing_set::add_next_event)."""

    def __init__(self, may_include_deletes: bool = True):
        self.items: dict[str, MissingItem] = {}
        self.may_include_deletes = may_include_deletes

    def is_missing(self, soid: str,
                   need: Optional[EVersion] = None) -> bool:
        item = self.items.get(soid)
        if item is None:
            return False
        return need is None or item.need == need

    def num_missing(self) -> int:
        return len(self.items)

    def add(self, soid: str, need: EVersion,
            have: EVersion = ZERO_VERSION,
            is_delete: bool = False) -> None:
        self.items[soid] = MissingItem(need, have, is_delete)

    def rm(self, soid: str) -> None:
        self.items.pop(soid, None)

    def revise_need(self, soid: str, need: EVersion,
                    is_delete: bool = False) -> None:
        item = self.items.get(soid)
        if item is None:
            self.items[soid] = MissingItem(need, ZERO_VERSION, is_delete)
        else:
            self.items[soid] = replace(item, need=need,
                                       is_delete=is_delete)

    def revise_have(self, soid: str, have: EVersion) -> None:
        item = self.items.get(soid)
        if item is not None:
            self.items[soid] = replace(item, have=have)

    def add_next_event(self, e: PGLogEntry) -> None:
        """Track a newly-learned log event (ref: osd_types.h
        pg_missing_set::add_next_event)."""
        if e.is_error():
            return
        existing = self.items.get(e.soid)
        if e.is_delete() and not self.may_include_deletes:
            self.rm(e.soid)
            return
        if existing is not None:
            # already missing an older version; still need the newest
            self.items[e.soid] = replace(
                existing, need=e.version, is_delete=e.is_delete())
        else:
            self.items[e.soid] = MissingItem(
                need=e.version, have=e.prior_version,
                is_delete=e.is_delete())

    def got(self, soid: str, version: EVersion) -> None:
        item = self.items.get(soid)
        if item is not None and item.need <= version:
            self.rm(soid)

    def __repr__(self) -> str:
        return f"PGMissing({self.items})"


# wire registration (ref: osd_types.h eversion_t/pg_log_entry_t/
# pg_missing_item each carry ENCODE_START versions)
from ..msg.encoding import register_struct as _reg  # noqa: E402

for _cls in (EVersion, PGShard, PGLogEntry, MissingItem):
    _reg(_cls, version=1, compat=1)
