"""Object classes: exec op + built-in lock/refcount/version classes
(ref: src/osd/ClassHandler.cc, src/objclass/objclass.h,
src/cls/{lock,refcount,version})."""
import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("meta", pg_num=8)
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k2m1",
                   "profile": {"plugin": "tpu", "k": "2", "m": "1",
                               "crush-failure-domain": "osd"}})
    r.pool_create("ecm", pg_num=8, pool_type="erasure",
                  erasure_code_profile="k2m1")
    yield c, r
    c.shutdown()


@pytest.fixture()
def io(cluster):
    _, r = cluster
    return r.open_ioctx("meta")


def test_unknown_class_or_method(io):
    with pytest.raises(RadosError, match="EOPNOTSUPP"):
        io.exec("o", "nope", "x")
    with pytest.raises(RadosError, match="EOPNOTSUPP"):
        io.exec("o", "lock", "nope")


def test_exec_rejected_on_ec_pool(cluster):
    _, r = cluster
    e = r.open_ioctx("ecm")
    with pytest.raises(RadosError, match="EOPNOTSUPP"):
        e.exec("o", "lock", "get_info", {"name": "l"})


# ---------------------------------------------------------------- lock

def test_lock_exclusive_lifecycle(io):
    oid = "locked"
    io.exec(oid, "lock", "lock",
            {"name": "owner", "type": "exclusive",
             "client": "client.A", "cookie": "c1", "desc": "test"})
    # the lock op created the object (like the reference's lock_obj)
    assert io.stat(oid)["size"] == 0
    info = io.exec(oid, "lock", "get_info", {"name": "owner"})
    assert info["type"] == "exclusive"
    assert [l["client"] for l in info["lockers"]] == ["client.A"]
    # another client is excluded
    with pytest.raises(RadosError, match="EBUSY"):
        io.exec(oid, "lock", "lock",
                {"name": "owner", "type": "exclusive",
                 "client": "client.B", "cookie": "c2"})
    # renew by the same (client, cookie) is fine
    io.exec(oid, "lock", "lock",
            {"name": "owner", "type": "exclusive",
             "client": "client.A", "cookie": "c1"})
    # unlock, then B can take it
    io.exec(oid, "lock", "unlock",
            {"name": "owner", "client": "client.A", "cookie": "c1"})
    io.exec(oid, "lock", "lock",
            {"name": "owner", "type": "exclusive",
             "client": "client.B", "cookie": "c2"})
    with pytest.raises(RadosError, match="ENOENT"):
        io.exec(oid, "lock", "unlock",
                {"name": "owner", "client": "client.A", "cookie": "c1"})


def test_lock_shared_and_break(io):
    oid = "shlock"
    for cl in ("client.A", "client.B"):
        io.exec(oid, "lock", "lock",
                {"name": "s", "type": "shared", "client": cl,
                 "cookie": "k"})
    info = io.exec(oid, "lock", "get_info", {"name": "s"})
    assert len(info["lockers"]) == 2
    # shared blocks exclusive
    with pytest.raises(RadosError, match="EBUSY"):
        io.exec(oid, "lock", "lock",
                {"name": "s", "type": "exclusive",
                 "client": "client.C", "cookie": "k"})
    # break one locker out
    io.exec(oid, "lock", "break_lock",
            {"name": "s", "locker": "client.A", "cookie": "k"})
    info = io.exec(oid, "lock", "get_info", {"name": "s"})
    assert [l["client"] for l in info["lockers"]] == ["client.B"]
    assert io.exec(oid, "lock", "list_locks", {}) == ["s"]


# ------------------------------------------------------------ refcount

def test_refcount_lifecycle(io):
    oid = "refobj"
    io.write_full(oid, b"shared data")
    io.exec(oid, "refcount", "get", {"tag": "t1"})
    io.exec(oid, "refcount", "get", {"tag": "t2"})
    assert io.exec(oid, "refcount", "read", {})["refs"] == ["t1", "t2"]
    io.exec(oid, "refcount", "put", {"tag": "t1"})
    assert io.exec(oid, "refcount", "read", {})["refs"] == ["t2"]
    # last put removes the object (ref: cls_rc_refcount_put)
    io.exec(oid, "refcount", "put", {"tag": "t2"})
    with pytest.raises(RadosError, match="ENOENT"):
        io.read(oid)


# ------------------------------------------------------------- version

def test_version_gating(io):
    oid = "ver"
    io.write_full(oid, b"v")
    io.exec(oid, "version", "set", {"ver": 5})
    assert io.exec(oid, "version", "read", {})["ver"] == 5
    io.exec(oid, "version", "inc", {})
    assert io.exec(oid, "version", "read", {})["ver"] == 6
    io.exec(oid, "version", "check", {"ver": 6, "cond": "eq"})
    with pytest.raises(RadosError, match="ECANCELED"):
        io.exec(oid, "version", "check", {"ver": 7, "cond": "eq"})
    # conditional inc: gate holds -> bump; gate fails -> ECANCELED
    io.exec(oid, "version", "inc", {"ver": 6, "cond": "eq"})
    with pytest.raises(RadosError, match="ECANCELED"):
        io.exec(oid, "version", "inc", {"ver": 6, "cond": "eq"})
    assert io.exec(oid, "version", "read", {})["ver"] == 7


def test_cls_mutations_are_atomic_and_replicated(cluster, io):
    """A cls write lands on every acting replica (it goes through the
    normal repop fan-out)."""
    c, r = cluster
    oid = "replock"
    io.exec(oid, "lock", "lock",
            {"name": "n", "type": "exclusive", "client": "x",
             "cookie": ""})
    pid = r.pool_lookup("meta")
    m = r.objecter.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, _ = m.pg_to_up_acting_osds(raw)
    import json
    for osd in acting:
        shard = c.osds[osd].pgs[pg].shard
        st = json.loads(shard.getxattr(oid, "lock.n"))
        assert list(st["lockers"]) == ["x/"]
