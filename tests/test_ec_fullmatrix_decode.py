"""Device-resident survivor selection: the full-matrix decode path.

The staging-free decode consumes all n = k+m chunk slots in ARRIVAL
layout against the zero-column (nerrs x n) decode matrix
(matrix_code.make_decode_matrix_full) — "the selection IS the matrix".
These tests pin it byte-identical to the ISA-ordered
make_decode_matrix path and the numpy oracle across EVERY erasure
pattern (data, coding, and mixed erasures up to m) for k=8,m=4 and
k=4,m=2, plus the singular-submatrix EIO behavior and the HBM decode-
kernel cache bound (ref construction: ErasureCodeIsa.cc:252-306; the
formulation this replaces is BENCH_r05's host survivor gather).
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import gf
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.matrix_code import (DecodeTableCache,
                                     make_decode_matrix,
                                     make_decode_matrix_full)

CONFIGS = [(8, 4), (4, 2)]


def _all_patterns(k, m):
    n = k + m
    for r in range(1, m + 1):
        yield from itertools.combinations(range(n), r)


def _arrival_layout(em, k, m, erasures, rng, nbytes=64):
    """(n, N) chunk array with parity rows and GARBAGE in erased
    slots — what a degraded read actually holds."""
    n = k + m
    data = rng.integers(0, 256, (k, nbytes), dtype=np.uint8)
    parity = gf.gf_matmul_bytes(em[k:], data)
    allc = np.concatenate([data, parity], axis=0)
    garbled = allc.copy()
    for e in erasures:
        garbled[e] = rng.integers(0, 256, nbytes, dtype=np.uint8)
    return allc, garbled


@pytest.mark.parametrize("k,m", CONFIGS)
def test_full_matrix_equals_isa_path_and_oracle_all_patterns(k, m):
    """Exhaustive (numpy) sweep: for EVERY erasure pattern the
    zero-column full matrix applied to the arrival layout (garbage in
    erased slots) reproduces exactly what the dense ISA-ordered matrix
    produces on gathered survivors — and both rebuild the oracle
    chunks."""
    n = k + m
    em = gf.isa_rs_matrix(k, m)
    rng = np.random.default_rng(k * 100 + m)
    for erasures in _all_patterns(k, m):
        erasures = list(erasures)
        decode_index = [i for i in range(n) if i not in erasures][:k]
        dmat = make_decode_matrix(em, k, decode_index, erasures)
        full = make_decode_matrix_full(em, k, n, decode_index, erasures)
        # structure: zero outside decode_index, dense rows inside
        mask = np.zeros(n, dtype=bool)
        mask[decode_index] = True
        assert not full[:, ~mask].any(), erasures
        np.testing.assert_array_equal(full[:, decode_index], dmat)
        allc, garbled = _arrival_layout(em, k, m, erasures, rng)
        got_full = gf.gf_matmul_bytes(full, garbled)
        got_dense = gf.gf_matmul_bytes(dmat, garbled[decode_index])
        np.testing.assert_array_equal(got_full, got_dense)
        np.testing.assert_array_equal(got_full, allc[erasures])


@pytest.mark.parametrize("k,m", CONFIGS)
def test_decode_batch_full_device_parity_sampled(k, m):
    """Device path (XLA gather + Pallas-interpret kernel) vs the
    staged decode_batch on representative patterns: data-only,
    coding-only, mixed, and max-erasure (each pattern is its own
    compiled kernel, so the exhaustive sweep stays numpy-side)."""
    from ceph_tpu.ec import registry
    from ceph_tpu.ec.kernels.bitmatmul import GFDecodeFull
    n = k + m
    tpu = registry.factory("tpu", {"k": str(k), "m": str(m)})
    rng = np.random.default_rng(5)
    patterns = [[0], [k], [1, k + 1], list(range(m))]
    for erasures in patterns:
        erasures = sorted(set(erasures))[:m]
        decode_index = [i for i in range(n) if i not in erasures][:k]
        em = np.asarray(tpu.encode_matrix)
        allc0, garbled0 = _arrival_layout(em, k, m, erasures, rng,
                                          nbytes=2048)
        allc1, garbled1 = _arrival_layout(em, k, m, erasures, rng,
                                          nbytes=2048)
        batch = np.stack([garbled0, garbled1])        # (S=2, n, N)
        want = np.stack([allc0[erasures], allc1[erasures]])
        got = np.asarray(tpu.decode_batch_full(erasures, batch))
        np.testing.assert_array_equal(got, want)
        # staged path agreement on the same survivors
        staged = np.asarray(tpu.decode_batch(
            decode_index, erasures, batch[:, decode_index, :]))
        np.testing.assert_array_equal(got, staged)
        # fused Pallas kernel (interpret mode) off the same matrix
        full = make_decode_matrix_full(em, k, n, decode_index,
                                       erasures)
        valid = np.ones(n, dtype=bool)
        valid[erasures] = False
        mm = GFDecodeFull(full, valid, use_pallas=True)
        np.testing.assert_array_equal(
            np.asarray(mm(batch, interpret=True)), want)


def test_full_matrix_rejects_nonzero_invalid_columns():
    """A nonzero column over a slot the validity mask marks erased
    would fold garbage into the rebuild — caller bug, hard error."""
    from ceph_tpu.ec.kernels.bitmatmul import selection_from_matrix
    mat = np.zeros((2, 6), dtype=np.uint8)
    mat[:, [0, 1, 2, 3]] = 1
    valid = np.array([1, 1, 1, 0, 1, 1], dtype=bool)  # col 3 erased
    with pytest.raises(ValueError, match="validity mask"):
        selection_from_matrix(mat, valid)
    # consistent mask passes and selects exactly the nonzero columns
    valid[3] = True
    assert selection_from_matrix(mat, valid) == [0, 1, 2, 3]


def test_singular_survivor_matrix_is_eio():
    """A singular survivor submatrix must surface as EIO through both
    the dense and the full-matrix construction (ref: the isa plugin's
    gf_invert_matrix failure -> -EIO)."""
    k, m = 2, 2
    # deliberately degenerate: duplicate coding rows make the survivor
    # submatrix {2, 3} singular
    em = np.array([[1, 0],
                   [0, 1],
                   [1, 1],
                   [1, 1]], dtype=np.uint8)
    with pytest.raises(ErasureCodeError, match="EIO"):
        make_decode_matrix(em, k, [2, 3], [0, 1])
    with pytest.raises(ErasureCodeError, match="EIO"):
        make_decode_matrix_full(em, k, 4, [2, 3], [0, 1])


def test_decode_batch_full_too_few_valid_is_eio():
    from ceph_tpu.ec import registry
    tpu = registry.factory("tpu", {"k": "4", "m": "2"})
    valid = np.array([1, 1, 1, 0, 0, 1], dtype=bool)   # 4 valid...
    data = np.zeros((1, 6, 64), dtype=np.uint8)
    with pytest.raises(ErasureCodeError, match="EIO"):
        # ...but one of them is also erased -> only 3 usable
        tpu.decode_batch_full([0], data, valid=valid)


def test_decode_table_cache_cost_weighted_eviction():
    """The decode-kernel LRU is a COST bound, not an entry count:
    full-width entries charge n, dense entries k, and the oldest
    entries evict when the budget is exceeded (the HBM-resident
    kernel cache cannot grow unbounded across erasure patterns)."""
    c = DecodeTableCache(capacity=10)
    c.put("d1", "densemat1", cost=4)
    c.put("d2", "densemat2", cost=4)
    c.put("full-1", "fullmat1", cost=6)      # 14 > 10: evicts d1
    assert c.get("d1") is None
    assert c.get("d2") == "densemat2"        # refreshed (MRU)
    assert c.get("full-1") == "fullmat1"
    assert c.total_cost() == 10
    # full-width entries cost more, so fitting a second one evicts
    # BOTH older entries (16 -> 12 -> 6): the bound is bytes, not count
    c.put("full-2", "fullmat2", cost=6)
    assert c.get("d2") is None
    assert c.get("full-1") is None
    assert c.get("full-2") == "fullmat2"
    assert c.total_cost() == 6
    # a single over-budget entry still caches (never thrash to empty)
    c.put("huge", "hugemat", cost=99)
    assert c.get("huge") == "hugemat"
    assert len(c) >= 1


def test_tpu_plugin_decode_cache_bounded_across_patterns():
    """Driving many distinct erasure signatures through the plugin
    must not grow the HBM kernel cache past its width budget."""
    from ceph_tpu.ec import registry
    tpu = registry.factory("tpu", {"k": "4", "m": "2"})
    tpu._decode_mm.capacity = 4 * 6          # room for ~6 dense entries
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (1, 4, 64), dtype=np.uint8)
    n = 6
    for erasures in itertools.combinations(range(n), 2):
        decode_index = [i for i in range(n) if i not in erasures][:4]
        survivors = rng.integers(0, 256, (1, 4, 64), dtype=np.uint8)
        tpu.decode_batch(decode_index, list(erasures), survivors)
    assert tpu._decode_mm.total_cost() <= tpu._decode_mm.capacity
    assert len(tpu._decode_mm) <= 6
    del data


def test_decode_batches_full_pipeline_matches_single_dispatch():
    """The double-buffered H2D pipeline yields exactly what one-shot
    decode_batch_full produces, in order."""
    from ceph_tpu.ec import registry
    k, m = 4, 2
    tpu = registry.factory("tpu", {"k": str(k), "m": str(m)})
    em = np.asarray(tpu.encode_matrix)
    rng = np.random.default_rng(9)
    erasures = [1, 4]
    batches = []
    wants = []
    for _ in range(3):
        allc, garbled = _arrival_layout(em, k, m, erasures, rng,
                                        nbytes=256)
        batches.append(np.stack([garbled]))
        wants.append(np.stack([allc[erasures]]))
    outs = [np.asarray(o) for o in
            tpu.decode_batches_full(erasures, batches)]
    assert len(outs) == 3
    for got, want in zip(outs, wants):
        np.testing.assert_array_equal(got, want)
