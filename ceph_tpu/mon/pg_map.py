"""PGMap: cluster-wide PG/usage statistics + health checks.

The mon-side aggregation of per-OSD stat reports (ref: src/mon/
PGMap.{h,cc} — per-pg pg_stat_t and per-osd osd_stat_t digests;
health evaluation src/mon/PGMap.cc get_health_checks and
src/osd/OSDMap.cc check_health; check names src/mon/health_check.h).

OSDs send MPGStats periodically (the reference routes these through
the mgr's DaemonServer into MgrStatMonitor); the mon keeps the digest
in memory and serves `status` / `df` / `health` / `pg stat` from it —
a restarted mon repopulates within one report interval.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class OSDStatReport:
    """One OSD's periodic report (ref: osd_stat_t + pg_stat_t map)."""
    osd: int = -1
    epoch: int = 0
    stamp: float = 0.0
    #: pgid-str -> {"state": str, "num_objects": int, "bytes": int,
    #:              "acting": [..], "up": [..]}
    pg_stats: dict = field(default_factory=dict)
    kb_total: int = 0
    kb_used: int = 0
    kb_avail: int = 0
    perf: dict = field(default_factory=dict)
    #: {count, oldest_age} of aged in-flight ops from the daemon's
    #: OpTracker (the SLOW_OPS health feed)
    slow_ops: dict = field(default_factory=dict)


class PGMap:
    """(ref: src/mon/PGMap.h:214)."""

    def __init__(self):
        self.osd_reports: dict[int, OSDStatReport] = {}

    def ingest(self, rep: OSDStatReport) -> None:
        cur = self.osd_reports.get(rep.osd)
        if cur is None or rep.stamp >= cur.stamp:
            self.osd_reports[rep.osd] = rep

    def forget(self, osd: int) -> None:
        self.osd_reports.pop(osd, None)

    # ---------------------------------------------------------- digests
    # All digests take the authoritative up-set so a downed OSD's last
    # report (capacity, stale primary claims) drops out of every answer
    # the moment the map marks it down, whichever path marked it.
    def primary_pgs(self, up: set[int] | None = None) -> dict[str, dict]:
        """pgid -> the primary's stat entry (the authoritative one,
        like the reference where only primaries report a PG)."""
        pgs: dict[str, dict] = {}
        for osd, rep in self.osd_reports.items():
            if up is not None and osd not in up:
                continue
            for pgid, st in rep.pg_stats.items():
                if st.get("primary", False) or pgid not in pgs:
                    pgs[pgid] = st
        return pgs

    @staticmethod
    def pg_states(pgs: dict) -> dict[str, int]:
        """state string -> pg count."""
        out: dict[str, int] = {}
        for st in pgs.values():
            out[st["state"]] = out.get(st["state"], 0) + 1
        return out

    def df(self, pgs: dict, up: set[int] | None = None) -> dict:
        """RAW usage + per-pool logical stats (ref: PGMap::dump_fs_stats
        / dump_pool_stats_full)."""
        reps = [r for o, r in self.osd_reports.items()
                if up is None or o in up]
        pools: dict[int, dict] = {}
        for pgid, st in pgs.items():
            pool = int(pgid.split(".")[0])
            p = pools.setdefault(pool, {"objects": 0, "bytes": 0,
                                        "store_bytes": 0,
                                        "snaptrim_pgs": 0})
            p["objects"] += st.get("num_objects", 0)
            p["bytes"] += st.get("bytes", 0)
            # physical bytes incl. snap clones (falls back to the
            # logical count for reports predating the field) — the
            # snaptrim leak-vs-reclaim trend reads from this
            p["store_bytes"] += st.get("store_bytes",
                                       st.get("bytes", 0))
            if "snaptrim" in st.get("state", ""):
                p["snaptrim_pgs"] += 1
        return {"total_kb": sum(r.kb_total for r in reps),
                "used_kb": sum(r.kb_used for r in reps),
                "avail_kb": sum(r.kb_avail for r in reps),
                "pools": pools}

    @staticmethod
    def totals(pgs: dict) -> dict:
        return {"num_pgs": len(pgs),
                "num_objects": sum(s.get("num_objects", 0)
                                   for s in pgs.values()),
                "bytes": sum(s.get("bytes", 0) for s in pgs.values())}


def health_checks(osdmap, pgmap: PGMap, quorum: list[int],
                  mon_ranks: list[int], now: float,
                  stale_after: float = 60.0,
                  pgs: dict | None = None,
                  slow_ops: dict | None = None) -> dict[str, dict]:
    """name -> {severity, summary} (ref: health_check_map_t,
    src/mon/health_check.h; producers OSDMap::check_health
    src/osd/OSDMap.cc:5623 and PGMap::get_health_checks)."""
    checks: dict[str, dict] = {}
    down_in = [o for o in range(osdmap.max_osd)
               if osdmap.exists(o) and not osdmap.is_up(o)
               and osdmap.is_in(o)]
    if down_in:
        checks["OSD_DOWN"] = {
            "severity": "HEALTH_WARN",
            "summary": f"{len(down_in)} osds down",
            "detail": [f"osd.{o} is down" for o in down_in]}
    missing = [r for r in mon_ranks if r not in quorum]
    if missing:
        checks["MON_DOWN"] = {
            "severity": "HEALTH_WARN",
            "summary": f"{len(missing)}/{len(mon_ranks)} mons down, "
                       f"quorum {quorum}",
            "detail": [f"mon.{r} is not in quorum" for r in missing]}
    if pgs is None:
        pgs = pgmap.primary_pgs({o for o in range(osdmap.max_osd)
                                 if osdmap.is_up(o)})
    degraded, recovering = [], []
    for pgid, st in pgs.items():
        state = st.get("state", "")
        if "degraded" in state:
            degraded.append(pgid)
        if "recover" in state:
            recovering.append(pgid)
    if degraded:
        checks["PG_DEGRADED"] = {
            "severity": "HEALTH_WARN",
            "summary": f"Degraded data redundancy: "
                       f"{len(degraded)} pgs degraded",
            "detail": [f"pg {p} is degraded" for p in sorted(degraded)]}
    # SLOW_OPS: any daemon reporting aged in-flight ops (ref: the
    # health_check OSDMap/MDSMonitor derive from per-daemon op
    # trackers under osd_op_complaint_time; cleared the moment every
    # reporter's count drains to 0).  `slow_ops` merges the feeds:
    # OSDs via their MPGStats report, MDSs via beacons, the mon's own
    # tracker directly.
    slow = {ent: s for ent, s in (slow_ops or {}).items()
            if int(s.get("count", 0)) > 0}
    osd_slow = {f"osd.{o}": r.slow_ops
                for o, r in pgmap.osd_reports.items()
                if osdmap.is_up(o)
                and int(r.slow_ops.get("count", 0)) > 0}
    slow.update(osd_slow)
    if slow:
        total = sum(int(s["count"]) for s in slow.values())
        oldest = max(float(s.get("oldest_age", 0.0))
                     for s in slow.values())
        checks["SLOW_OPS"] = {
            "severity": "HEALTH_WARN",
            "summary": f"{total} slow ops, oldest one blocked for "
                       f"{oldest:.0f} sec, daemons "
                       f"{sorted(slow)} have slow ops.",
            "detail": [f"{ent}: {s['count']} ops blocked, oldest "
                       f"{float(s.get('oldest_age', 0.0)):.1f}s"
                       for ent, s in sorted(slow.items())]}
    stale = {o: now - r.stamp for o, r in pgmap.osd_reports.items()
             if osdmap.is_up(o) and now - r.stamp > stale_after}
    if stale:
        checks["OSD_STALE_REPORT"] = {
            "severity": "HEALTH_WARN",
            "summary": f"{len(stale)} osds have not reported recently",
            "detail": [f"osd.{o} last report {age:.0f}s old"
                       for o, age in sorted(stale.items())]}
    return checks


def health_status(checks: dict) -> str:
    if any(c["severity"] == "HEALTH_ERR" for c in checks.values()):
        return "HEALTH_ERR"
    return "HEALTH_WARN" if checks else "HEALTH_OK"
