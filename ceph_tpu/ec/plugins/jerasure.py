"""jerasure-compatible CPU plugin (numpy backend).

Matches the technique set and chunk-size semantics of the jerasure plugin
(ref: src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}):

* techniques: reed_sol_van (Vandermonde systematized), reed_sol_r6_op
  (RAID-6 P+Q), cauchy_orig, cauchy_good (improved Cauchy), and the
  GF(2) bitmatrix family liberation / blaum_roth / liber8tion
  (ceph_tpu.ec.bitmatrix: published constructions, build-time MDS
  verification, fixture-pinned layouts);
* matrix codes at w=8 (the Ceph default, byte fast path) and w=16/32
  (wide-word fields over gf-complete's standard polynomials, via
  ceph_tpu.ec.gfw);
* chunk size: object padded to a multiple of k*w*sizeof(int) (w*16-aligned
  per-chunk when jerasure-per-chunk-alignment=true); cauchy variants align
  to k*w*packetsize*sizeof(int) with packetsize default 2048
  (ref: ErasureCodeJerasure.cc:80-102 get_chunk_size, :174-184,:300 get_alignment).

jerasure's bitmatrix/schedule encode (cauchy) computes the same GF(2^8)
linear map as the plain matrix product, so chunk bytes here are identical
to the reference for all four techniques.
"""
from __future__ import annotations

import numpy as np

from .. import gf
from ..interface import ErasureCodeProfile, ErasureCodeError, to_int, to_bool, \
    sanity_check_k_m
from ..matrix_code import MatrixErasureCode
from ..registry import ErasureCodePlugin

LARGEST_VECTOR_WORDSIZE = 16  # ref: ErasureCodeJerasure.cc:30
SIZEOF_INT = 4


class ErasureCodeJerasure(MatrixErasureCode):
    DEFAULT_K = "2"
    DEFAULT_M = "1"
    DEFAULT_W = "8"
    technique = "reed_sol_van"

    def __init__(self) -> None:
        super().__init__()
        self.w = 8
        self.per_chunk_alignment = False

    def init(self, profile: ErasureCodeProfile) -> None:
        profile.setdefault("plugin", "jerasure")
        profile.setdefault("technique", self.technique)
        self.parse(profile)
        self.prepare()
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        self.w = to_int("w", profile, self.DEFAULT_W)
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            self.chunk_mapping = []
            raise ErasureCodeError("bad mapping size")
        sanity_check_k_m(self.k, self.m)
        if self.w not in (8, 16, 32):
            raise ErasureCodeError(
                f"w={self.w} not supported (matrix codes take 8/16/32)")
        self.per_chunk_alignment = to_bool(
            "jerasure-per-chunk-alignment", profile, "false")

    def _field(self):
        """GF(2^w) field for wide w; None selects the byte fast path."""
        if self.w == 8:
            return None
        from .. import gfw
        return gfw.field(self.w)

    def _prepare_coding(self, byte_builder, wide_builder) -> None:
        """Shared field dispatch for every matrix technique: pick the
        byte-path or wide-field coding-matrix builder and prepend the
        identity."""
        self.field = self._field()
        coding = byte_builder() if self.field is None \
            else wide_builder(self.field)
        self._prepare(np.vstack([np.eye(self.k, dtype=coding.dtype),
                                 coding]))

    def get_alignment(self) -> int:
        # ref: ErasureCodeJerasure.cc:174-184
        if self.per_chunk_alignment:
            return self.w * LARGEST_VECTOR_WORDSIZE
        alignment = self.k * self.w * SIZEOF_INT
        if (self.w * SIZEOF_INT) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def get_chunk_size(self, object_size: int) -> int:
        # ref: ErasureCodeJerasure.cc:80-102
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = (object_size + self.k - 1) // self.k
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    def prepare(self) -> None:
        raise NotImplementedError


class ReedSolomonVandermonde(ErasureCodeJerasure):
    technique = "reed_sol_van"

    def prepare(self) -> None:
        self._prepare_coding(
            lambda: gf.jerasure_vandermonde_coding_matrix(self.k, self.m),
            lambda f: f.vandermonde_coding_matrix(self.k, self.m))


class ReedSolomonRAID6(ErasureCodeJerasure):
    technique = "reed_sol_r6_op"

    def parse(self, profile: ErasureCodeProfile) -> None:
        profile.pop("m", None)
        super().parse(profile)
        self.m = 2

    def prepare(self) -> None:
        self._prepare_coding(
            lambda: gf.jerasure_r6_coding_matrix(self.k),
            lambda f: f.r6_coding_matrix(self.k))


class Cauchy(ErasureCodeJerasure):
    DEFAULT_K = "7"
    DEFAULT_M = "3"
    DEFAULT_PACKETSIZE = "2048"

    def __init__(self) -> None:
        super().__init__()
        self.packetsize = 2048

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.packetsize = to_int("packetsize", profile, self.DEFAULT_PACKETSIZE)

    def get_alignment(self) -> int:
        # ref: ErasureCodeJerasure.cc:280-293
        if self.per_chunk_alignment:
            alignment = self.w * self.packetsize
            modulo = alignment % LARGEST_VECTOR_WORDSIZE
            if modulo:
                alignment += LARGEST_VECTOR_WORDSIZE - modulo
            return alignment
        alignment = self.k * self.w * self.packetsize * SIZEOF_INT
        if (self.w * self.packetsize * SIZEOF_INT) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * self.packetsize * LARGEST_VECTOR_WORDSIZE
        return alignment


class CauchyOrig(Cauchy):
    technique = "cauchy_orig"

    def prepare(self) -> None:
        self._prepare_coding(
            lambda: gf.cauchy_original_coding_matrix(self.k, self.m),
            lambda f: f.cauchy_original_coding_matrix(self.k, self.m))


class CauchyGood(Cauchy):
    technique = "cauchy_good"

    def prepare(self) -> None:
        self._prepare_coding(
            lambda: gf.cauchy_good_coding_matrix(self.k, self.m),
            lambda f: f.cauchy_good_coding_matrix(self.k, self.m))


class Bitmatrix(ErasureCodeJerasure):
    """Base for the GF(2) bitmatrix RAID-6 techniques
    (ref: ErasureCodeJerasure.h:152-252 Liberation/BlaumRoth/
    Liber8tion; schedule encode ErasureCodeJerasure.cc:266).

    Chunks are w packets; coding applies a (2w x kw) 0/1 matrix by
    XOR (the schedule form) — see ceph_tpu.ec.bitmatrix for the
    constructions, the MDS verification, and the MXU bit-plane form.
    Matrices follow the published structure; jerasure bit-parity is
    NOT claimed (sources not vendored) — layouts are pinned by the
    committed fixtures instead (tests/test_ec_bitmatrix.py).
    """
    DEFAULT_K = "2"
    DEFAULT_W = "7"
    DEFAULT_PACKETSIZE = "2048"

    def __init__(self) -> None:
        super().__init__()
        self.packetsize = 2048
        self.generator = None       # ((k+2)w x kw) over GF(2)

    def parse(self, profile: ErasureCodeProfile) -> None:
        profile.pop("m", None)
        # bypass the matrix-code w in (8,16,32) restriction
        MatrixErasureCode.parse(self, profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = 2
        self.w = to_int("w", profile, self.DEFAULT_W)
        sanity_check_k_m(self.k, self.m)
        self.packetsize = to_int("packetsize", profile,
                                 self.DEFAULT_PACKETSIZE)
        self.per_chunk_alignment = to_bool(
            "jerasure-per-chunk-alignment", profile, "false")
        self._check_w()

    def _check_w(self) -> None:
        raise NotImplementedError

    def _build_generator(self):
        raise NotImplementedError

    def prepare(self) -> None:
        self.generator = self._build_generator()
        # encode-time XOR schedule (ref: jerasure_schedule_encode)
        from ..bitmatrix import bitmatrix_schedule
        self.schedule = bitmatrix_schedule(
            self.generator[self.k * self.w:])

    def get_alignment(self) -> int:
        # packets of w rows (ref: Liberation::get_alignment shape)
        if self.per_chunk_alignment:
            alignment = self.w * self.packetsize
            modulo = alignment % LARGEST_VECTOR_WORDSIZE
            if modulo:
                alignment += LARGEST_VECTOR_WORDSIZE - modulo
            return alignment
        return self.k * self.w * self.packetsize

    # -- coding --------------------------------------------------------
    def _packets(self, chunks: dict, idxs, plen: int) -> np.ndarray:
        rows = np.empty((len(idxs) * self.w, plen), dtype=np.uint8)
        for n, i in enumerate(idxs):
            rows[n * self.w:(n + 1) * self.w] = np.asarray(
                chunks[i], dtype=np.uint8).reshape(self.w, plen)
        return rows

    def encode_chunks(self, want_to_encode, encoded: dict) -> None:
        from ..bitmatrix import bitmatrix_apply
        k, w = self.k, self.w
        plen = len(encoded[0]) // w
        data = self._packets(encoded, range(k), plen)
        coding = bitmatrix_apply(self.generator[k * w:], data)
        for j in range(2):
            encoded[k + j][:] = coding[j * w:(j + 1) * w].reshape(-1)

    def decode_chunks(self, want_to_read, chunks: dict,
                      decoded: dict) -> None:
        from ..bitmatrix import bitmatrix_apply, gf2_inv, gf2_matmul
        k, w = self.k, self.w
        avail = sorted(chunks)
        if len(avail) < k:
            raise ErasureCodeError(
                f"EIO: need {k} chunks to decode, have {len(avail)}")
        survivors = avail[:k]
        erased = sorted(set(want_to_read) - set(chunks))
        if not erased:
            return
        plen = len(next(iter(chunks.values()))) // w
        sub = np.vstack([
            self.generator[c * w:(c + 1) * w] for c in survivors])
        inv = gf2_inv(sub)
        if inv is None:
            raise ErasureCodeError("EIO: singular survivor bitmatrix")
        rows = np.vstack([
            self.generator[e * w:(e + 1) * w] for e in erased])
        dec = gf2_matmul(rows, inv)
        out = bitmatrix_apply(dec, self._packets(chunks, survivors,
                                                 plen))
        for n, e in enumerate(erased):
            decoded[e][:] = out[n * w:(n + 1) * w].reshape(-1)


class Liberation(Bitmatrix):
    technique = "liberation"

    def _check_w(self) -> None:
        if self.w < 2 or any(self.w % d == 0 for d in range(2, self.w)):
            raise ErasureCodeError(f"liberation requires prime w "
                                   f"(w={self.w})")
        if self.k > self.w:
            raise ErasureCodeError("liberation requires k <= w")

    def _build_generator(self):
        from ..bitmatrix import liberation_bitmatrix
        return liberation_bitmatrix(self.k, self.w)


class BlaumRoth(Bitmatrix):
    technique = "blaum_roth"

    def _check_w(self) -> None:
        p = self.w + 1
        if p < 3 or any(p % d == 0 for d in range(2, p)):
            raise ErasureCodeError(f"blaum_roth requires w+1 prime "
                                   f"(w={self.w})")
        if self.k > self.w:
            raise ErasureCodeError("blaum_roth requires k <= w")

    def _build_generator(self):
        from ..bitmatrix import blaum_roth_bitmatrix
        return blaum_roth_bitmatrix(self.k, self.w)


class Liber8tion(Bitmatrix):
    technique = "liber8tion"
    DEFAULT_W = "8"

    def parse(self, profile: ErasureCodeProfile) -> None:
        profile.pop("w", None)
        super().parse(profile)

    def _check_w(self) -> None:
        self.w = 8
        if self.k > 8:
            raise ErasureCodeError("liber8tion requires k <= 8")

    def _build_generator(self):
        from ..bitmatrix import liber8tion_bitmatrix
        return liber8tion_bitmatrix(self.k)


TECHNIQUES = {
    "reed_sol_van": ReedSolomonVandermonde,
    "reed_sol_r6_op": ReedSolomonRAID6,
    "cauchy_orig": CauchyOrig,
    "cauchy_good": CauchyGood,
    "liberation": Liberation,
    "blaum_roth": BlaumRoth,
    "liber8tion": Liber8tion,
}


class _JerasureFactory:
    """Dispatch on profile['technique'] like ErasureCodePluginJerasure::factory
    (ref: src/erasure-code/jerasure/ErasureCodePluginJerasure.cc)."""

    def __call__(self) -> ErasureCodeJerasure:
        return _TechniqueDispatch()


class _TechniqueDispatch(ErasureCodeJerasure):
    """Thin shim: picks the concrete technique class at init() time."""

    def __new__(cls):
        return object.__new__(cls)

    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.setdefault("technique", "reed_sol_van")
        impl_cls = TECHNIQUES.get(technique)
        if impl_cls is None:
            raise ErasureCodeError(
                f"ENOENT: technique={technique!r} is not supported")
        self.__class__ = impl_cls
        impl_cls.__init__(self)
        impl_cls.init(self, profile)


PLUGIN = ErasureCodePlugin("jerasure", _JerasureFactory())
