"""green: locks come from the lockdep factory."""
from ceph_tpu.common.lockdep import make_lock

a = make_lock("fixture.a")
b = make_lock("fixture.b")
