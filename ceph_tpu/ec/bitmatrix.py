"""GF(2) bitmatrix RAID-6 codes: liberation / blaum_roth / liber8tion.

The jerasure bit-matrix technique family (ref: src/erasure-code/
jerasure/ErasureCodeJerasure.h:152-252 — ErasureCodeJerasureLiberation
/ BlaumRoth / Liber8tion; schedule encode ErasureCodeJerasure.cc:266).
These are m=2 codes over GF(2): each chunk is w *packets*, and coding
is a (2w x kw) 0/1 matrix applied to the packet vector — XORs only, no
field multiplies.  That makes them the native dialect of this repo's
bit-plane MXU formulation: the same mod-2 matmul the GF(2^8) kernel
runs, with the companion matrix replaced by the code's bitmatrix.

Constructions (all public algorithms):

* **blaum_roth** — the Blaum-Roth array code over the polynomial ring
  R = GF(2)[x] / M_p(x), M_p = 1 + x + ... + x^w with p = w+1 prime
  (Blaum & Roth, "On Lowest Density MDS Codes", IEEE-IT 1999; the
  construction is fully determined, so these matrices match any
  faithful implementation): Q's column j is the multiply-by-x^j
  matrix in R.
* **liberation** — Plank's RAID-6 Liberation codes (FAST'08) in the
  paper's closed form: w prime, X_0 = I, X_j = the j-step cyclic
  shift of I plus one bump bit at (j(w-1)/2 mod w, +j-1); minimum
  density, verified MDS for every k <= w at w in {3,5,7,11,13}.
* **liber8tion** — the w=8 slot: companion-matrix powers over GF(2^8)
  (structurally MDS) standing in for the paper's machine-searched
  minimal-density tables, which only exist in the unvendored jerasure
  sources — see liber8tion_bitmatrix for the honest trade.

Every constructed code is verified MDS at build time: all C(k+2, 2)
double-erasure patterns must leave an invertible kw x kw survivor
matrix over GF(2).
"""
from __future__ import annotations

import functools

import numpy as np

from .interface import ErasureCodeError


# ------------------------------------------------------ GF(2) algebra

def gf2_inv(mat: np.ndarray) -> np.ndarray | None:
    """Inverse over GF(2) via Gauss-Jordan; None if singular."""
    n = mat.shape[0]
    a = mat.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = None
        for r in range(col, n):
            if a[r, col]:
                piv = r
                break
        if piv is None:
            return None
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


def gf2_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return (a.astype(np.uint8) @ b.astype(np.uint8)) % 2


def bitmatrix_apply(bm: np.ndarray, packets: np.ndarray) -> np.ndarray:
    """(R x C) 0/1 matrix applied to C byte-string packets (C, L):
    output packet r = XOR of selected input packets.  Bytes are 8
    independent GF(2) streams, so XOR-reduce IS the mod-2 matmul
    (the device form runs the same product on the MXU)."""
    out = np.zeros((bm.shape[0], packets.shape[1]), dtype=np.uint8)
    for r in range(bm.shape[0]):
        sel = np.nonzero(bm[r])[0]
        if len(sel):
            out[r] = np.bitwise_xor.reduce(packets[sel], axis=0)
    return out


def bitmatrix_schedule(bm: np.ndarray) -> list[tuple[int, int]]:
    """Flatten a bitmatrix into an XOR op list [(dst_row, src_row)]
    (ref: jerasure_schedule_encode — the schedule form the reference
    executes; here it doubles as documentation of the XOR count)."""
    ops = []
    for r in range(bm.shape[0]):
        for c in np.nonzero(bm[r])[0]:
            ops.append((int(r), int(c)))
    return ops


def gf2_matmul_device(bm, packets):
    """Device form: one int8 matmul + mod-2 on the MXU — the bitmatrix
    IS the companion matrix (bit-plane dialect of the GF(2^8) kernel).
    packets (C, L) uint8 -> (R, L) uint8."""
    import jax.numpy as jnp
    b = jnp.asarray(bm, dtype=jnp.int8)
    d = jnp.asarray(packets, dtype=jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((d[:, None, :] >> shifts[None, :, None]) & 1).astype(jnp.int8)
    c, p, n = bits.shape
    acc = jnp.matmul(b, bits.reshape(c, p * n),
                     preferred_element_type=jnp.int32) & 1
    planes = acc.reshape(bm.shape[0], 8, n).astype(jnp.uint8)
    weights = (jnp.uint8(1) << shifts)
    return (planes * weights[None, :, None]).sum(
        axis=1).astype(jnp.uint8)


# ----------------------------------------------------- constructions

def _shift_matrix(w: int, j: int) -> np.ndarray:
    """sigma^j: ones at (i, (i + j) mod w)."""
    m = np.zeros((w, w), dtype=np.uint8)
    for i in range(w):
        m[i, (i + j) % w] = 1
    return m


def _generator(k: int, w: int, xs: list[np.ndarray]) -> np.ndarray:
    """[(k+2)w x kw] generator: identity data rows, P = XOR of all
    columns, Q per-column X_j."""
    g = np.zeros(((k + 2) * w, k * w), dtype=np.uint8)
    g[:k * w, :k * w] = np.eye(k * w, dtype=np.uint8)
    for j in range(k):
        g[k * w:(k + 1) * w, j * w:(j + 1) * w] = np.eye(
            w, dtype=np.uint8)
        g[(k + 1) * w:, j * w:(j + 1) * w] = xs[j]
    return g


def is_mds(k: int, w: int, g: np.ndarray) -> bool:
    """Every double-erasure leaves an invertible survivor matrix."""
    n = k + 2
    for a in range(n):
        for b in range(a + 1, n):
            rows = [c for c in range(n) if c not in (a, b)][:k]
            sub = np.vstack([g[c * w:(c + 1) * w] for c in rows])
            if gf2_inv(sub) is None:
                return False
    return True


@functools.lru_cache(maxsize=64)
def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """Q_j = multiply-by-x^j in GF(2)[x]/(1 + x + ... + x^w); p = w+1
    must be prime, k <= w (Blaum-Roth 1999)."""
    p = w + 1
    if any(p % d == 0 for d in range(2, p)) or p < 3:
        raise ErasureCodeError(f"blaum_roth requires w+1 prime, w={w}")
    if k > w:
        raise ErasureCodeError(f"blaum_roth requires k <= w ({k} > {w})")
    # multiply-by-x in the ring: x * x^i = x^{i+1}; x^w = 1 + x + ...
    # + x^{w-1} (since M_p(x) = 0).  Column i of X holds x * x^i.
    X = np.zeros((w, w), dtype=np.uint8)
    for i in range(w - 1):
        X[i + 1, i] = 1
    X[:, w - 1] = 1                 # x^w reduces to all-ones
    xs = [np.eye(w, dtype=np.uint8)]
    for _ in range(1, k):
        xs.append(gf2_matmul(X, xs[-1]))
    g = _generator(k, w, xs)
    if not is_mds(k, w, g):         # the construction guarantees this
        raise ErasureCodeError("blaum_roth construction not MDS "
                               f"(k={k}, w={w})")
    return g


@functools.lru_cache(maxsize=64)
def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """Plank's Liberation construction (FAST'08, closed form): X_0 = I;
    X_j = sigma^j plus one bump bit at row r = j(w-1)/2 mod w, column
    (r + j - 1) mod w.  w prime, k <= w; verified MDS at build time
    (holds for every k <= w at w in {3,5,7,11,13})."""
    if w < 2 or any(w % d == 0 for d in range(2, w)):
        raise ErasureCodeError(f"liberation requires prime w, w={w}")
    if k > w:
        raise ErasureCodeError(f"liberation requires k <= w ({k} > {w})")
    xs = []
    for j in range(k):
        x = _shift_matrix(w, j)
        if j > 0:
            r = (j * (w - 1) // 2) % w
            x[r, (r + j - 1) % w] ^= 1
        xs.append(x)
    g = _generator(k, w, xs)
    if not is_mds(k, w, g):
        raise ErasureCodeError(
            f"liberation construction not MDS (k={k}, w={w})")
    return g


@functools.lru_cache(maxsize=8)
def liber8tion_bitmatrix(k: int) -> np.ndarray:
    """w=8, m=2, k <= 8 bitmatrix RAID-6 (the liber8tion slot).

    The paper's minimal-density X_j tables were found by machine search
    and only exist in the jerasure sources (not vendored in the
    reference checkout), so this uses companion-matrix powers over
    GF(2^8) instead: X_j = C^j with C the multiply-by-x matrix of
    x^8 + x^4 + x^3 + x^2 + 1 (gf-complete's w=8 polynomial).  MDS is
    structural — X_i ^ X_j = C^i (I ^ C^(j-i)) is invertible for all
    i != j because C generates a field.  Same interface, same w=8
    packet layout, honestly higher XOR density than the paper's
    tables; layouts pinned by committed fixtures."""
    if k > 8:
        raise ErasureCodeError(f"liber8tion requires k <= 8, k={k}")
    C = np.zeros((8, 8), dtype=np.uint8)
    for i in range(7):
        C[i + 1, i] = 1
    for r in (0, 2, 3, 4):          # x^8 = x^4 + x^3 + x^2 + 1 (0x1D)
        C[r, 7] = 1
    xs = [np.eye(8, dtype=np.uint8)]
    for _ in range(1, k):
        xs.append(gf2_matmul(C, xs[-1]))
    g = _generator(k, 8, xs)
    if not is_mds(k, 8, g):
        raise ErasureCodeError(f"liber8tion bitmatrix not MDS (k={k})")
    return g
