"""cephck engine + rule tests.

Every rule must demonstrate its bug: at least one red fixture it
flags and one green fixture it stays silent on
(tests/fixtures/cephck/).  On top of the corpus, the whole tree must
scan clean under the committed baseline — the same gate
scripts/check_green.sh --static ships on.
"""
import json
import pathlib
import subprocess
import sys

import pytest

from ceph_tpu.analysis import ALL_RULES
from ceph_tpu.analysis.engine import (BaselineError, Engine,
                                      load_baseline, repo_root,
                                      sarif_report)

ROOT = repo_root(pathlib.Path(__file__).resolve())
FIXTURES = ROOT / "tests" / "fixtures" / "cephck"

#: rule id -> fixture stem (red = must flag, green = must not)
RULE_FIXTURES = {
    "raw-lock": "raw_lock",
    "wire-drift": "wire_drift",
    "unregistered-message": "unregistered_message",
    "txn-atomicity": "osd/txn_atomicity",
    "silent-thread": "silent_thread",
    "jax-timing": "jax_timing",
    "jit-static": "jit_static",
    "bare-except": "bare_except",
    # device-contract family (cephck v2) — the host-sync and
    # implicit-transfer rules are scoped to the EC/CRUSH hot path, so
    # their fixtures live under ec/ (same trick as osd/txn_atomicity)
    "host-sync-hot-path": "ec/host_sync",
    "jit-retrace-churn": "jit_retrace",
    "tracer-leak": "tracer_leak",
    "implicit-transfer": "ec/implicit_transfer",
    # concurrency family (racecheck's static half)
    "guarded-by": "guarded_by",
    "blocking-in-dispatch": "blocking_dispatch",
    # error-contract family (errcheck's static half)
    "swallowed-error": "swallowed_error",
    "errno-conflation": "errno_conflation",
    "reply-on-all-paths": "reply_on_all_paths",
    "bare-retry": "bare_retry",
}


def scan(path: pathlib.Path, baseline=None) -> list:
    eng = Engine([cls() for cls in ALL_RULES], ROOT,
                 suppressions=baseline or [])
    return list(eng.check_file(path)), eng


def rules_hit(path: pathlib.Path) -> set:
    findings, _ = scan(path)
    return {f.rule for f in findings}


def test_every_rule_has_fixtures():
    assert {r.id for r in (cls() for cls in ALL_RULES)} == \
        set(RULE_FIXTURES)


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_red_fixture_flags(rule):
    red = FIXTURES / f"{RULE_FIXTURES[rule]}_red.py"
    assert rule in rules_hit(red), f"{red.name} must trip {rule}"


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_green_fixture_passes(rule):
    green = FIXTURES / f"{RULE_FIXTURES[rule]}_green.py"
    assert rule not in rules_hit(green), \
        f"{green.name} must NOT trip {rule}"


def test_red_fixtures_are_otherwise_clean():
    """A red fixture demonstrates ITS bug, not a pile of them — any
    other rule firing on it means the fixture (or a rule) drifted."""
    for rule, stem in RULE_FIXTURES.items():
        extra = rules_hit(FIXTURES / f"{stem}_red.py") - {rule}
        assert not extra, f"{stem}_red.py also trips {extra}"


def test_green_fixtures_are_fully_clean():
    for stem in RULE_FIXTURES.values():
        hit = rules_hit(FIXTURES / f"{stem}_green.py")
        assert not hit, f"{stem}_green.py trips {hit}"


# ------------------------------------------------------- rule details

def test_wire_drift_catches_removal_retype_and_compat():
    findings, _ = scan(FIXTURES / "wire_drift_red.py")
    msgs = {f.symbol: f.message for f in findings
            if f.rule == "wire-drift"}
    # dropping a mid-list field shifts every later one: reported as a
    # positional mismatch at the first diverging slot
    assert "breaks positional decode" in msgs["SnapTrim"]
    assert "retyped" in msgs["SnapTrimReply"]
    assert "compat" in msgs["SnapTrimPurged"]


def test_wire_drift_append_needs_version_bump(tmp_path):
    """Appending a field is the LEGAL evolution — but only with a
    version bump; same-version append is drift."""
    src = (FIXTURES / "wire_drift_green.py").read_text()
    appended = src.replace("    from_osd: int = -1\n",
                           "    from_osd: int = -1\n"
                           "    extra: int = 0\n", 1)
    bad = tmp_path / "append_same_version.py"
    bad.write_text(appended)
    findings, _ = scan(bad)
    assert any(f.rule == "wire-drift" and "version bump" in f.message
               for f in findings)
    good = tmp_path / "append_bumped.py"
    good.write_text(appended + '\n_VERSIONS = {"SnapTrim": (2, 1)}\n')
    findings, _ = scan(good)
    assert not [f for f in findings if f.rule == "wire-drift"]


def test_inline_ignore_waives_a_finding(tmp_path):
    p = tmp_path / "ign.py"
    p.write_text("try:\n    pass\n"
                 "except:  # cephck: ignore[bare-except]\n    pass\n")
    findings, _ = scan(p)
    assert not findings


# ------------------------------------------- cross-module pass (v2)

def test_host_sync_flags_callee_through_call_graph():
    """The cross-module half: the loop itself is sync-free, but it
    calls a helper that .item()s — flagged at the CALLSITE."""
    findings, _ = scan(FIXTURES / "ec" / "host_sync_red.py")
    msgs = [f.message for f in findings
            if f.rule == "host-sync-hot-path"]
    assert any("callee host-syncs" in m for m in msgs), msgs


def test_host_sync_scoped_to_hot_path(tmp_path):
    """The same source OUTSIDE ec//crush//osd-EC paths is silent —
    the rule polices the hot path, not the whole tree."""
    src = (FIXTURES / "ec" / "host_sync_red.py").read_text()
    p = tmp_path / "not_hot.py"
    p.write_text(src)
    assert "host-sync-hot-path" not in rules_hit(p)


def test_project_context_resolves_imported_jit(tmp_path):
    """implicit-transfer recognizes a jit wrapper IMPORTED from
    another scanned module — the cross-module jit registry."""
    from ceph_tpu.analysis.engine import collect_files  # noqa: F401
    pkg = tmp_path / "ec"
    pkg.mkdir()
    (pkg / "kern.py").write_text(
        "import jax\n\n\n"
        "@jax.jit\n"
        "def gf_mul(a, b):\n"
        "    return a @ b\n")
    (pkg / "plug.py").write_text(
        "import jax\n"
        "import numpy as np\n\n"
        "from ec.kern import gf_mul\n\n\n"
        "def encode(data):\n"
        "    table = np.zeros((8, 8), dtype=np.int8)\n"
        "    return gf_mul(table, data)\n")
    eng = Engine([cls() for cls in ALL_RULES], tmp_path)
    eng.run([str(pkg)])
    hits = [f for f in eng.findings if f.rule == "implicit-transfer"]
    assert len(hits) == 1 and hits[0].path.endswith("plug.py"), \
        [f.render() for f in eng.findings]


def test_guarded_by_flags_minority_access_and_covers_helpers():
    findings, _ = scan(FIXTURES / "guarded_by_red.py")
    hits = [f for f in findings if f.rule == "guarded-by"]
    # exactly the drain() accesses — the locked majority and the
    # covered-helper pattern stay silent (green fixture proves the
    # latter end to end)
    assert hits and all(f.symbol == "PGMetaTable.drain" for f in hits)
    assert all("self._lock" in f.message for f in hits)


def test_blocking_in_dispatch_local_and_cross_function():
    findings, _ = scan(FIXTURES / "blocking_dispatch_red.py")
    msgs = [f.message for f in findings
            if f.rule == "blocking-in-dispatch"]
    assert any("time.sleep" in m for m in msgs), msgs
    assert any("reaches" in m and "wait" in m for m in msgs), msgs


def test_format_github_emits_workflow_annotations():
    red = FIXTURES / "bare_except_red.py"
    proc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis",
         "--format", "github", str(red)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("::error "))
    assert "file=tests/fixtures/cephck/bare_except_red.py" in line
    assert "title=cephck bare-except" in line


def test_format_json_matches_legacy_json_flag():
    red = FIXTURES / "bare_except_red.py"
    out = {}
    for flag in (["--json"], ["--format", "json"]):
        proc = subprocess.run(
            [sys.executable, "-m", "ceph_tpu.analysis",
             *flag, str(red)],
            cwd=ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        out[tuple(flag)] = json.loads(proc.stdout)
    assert out[("--json",)] == out[("--format", "json")]
    assert out[("--json",)]["findings"][0]["rule"] == "bare-except"


def test_jit_retrace_flags_per_call_static():
    findings, _ = scan(FIXTURES / "jit_retrace_red.py")
    msgs = [f.message for f in findings
            if f.rule == "jit-retrace-churn"]
    assert any("per-call value" in m for m in msgs), msgs
    assert any("compile-per-call" in m for m in msgs), msgs


def test_tracer_leak_flags_self_and_module_state():
    findings, _ = scan(FIXTURES / "tracer_leak_red.py")
    msgs = [f.message for f in findings if f.rule == "tracer-leak"]
    assert any("self.last" in m for m in msgs), msgs
    assert any("_DEBUG_TAPS" in m for m in msgs), msgs


# --------------------------------------- error-contract family details

def test_swallowed_error_flags_pass_and_continue():
    findings, _ = scan(FIXTURES / "swallowed_error_red.py")
    hits = [f for f in findings if f.rule == "swallowed-error"]
    assert len(hits) == 2, [f.render() for f in hits]


def test_errno_conflation_flags_all_three_shapes():
    findings, _ = scan(FIXTURES / "errno_conflation_red.py")
    msgs = [f.message for f in findings if f.rule == "errno-conflation"]
    assert any("return []" in m for m in msgs), msgs
    assert any("size = 0" in m for m in msgs), msgs
    assert any("ENOENT-shaped" in m for m in msgs), msgs


def test_errno_conflation_scoped_out_of_tests(tmp_path):
    """The same source under tests/ (outside the fixture corpus) is
    silent — the error-contract rules police daemon code."""
    src = (FIXTURES / "errno_conflation_red.py").read_text()
    # scoping is by repo-relative path: simulate a tests/ location
    sub = tmp_path / "tests"
    sub.mkdir()
    q = sub / "x.py"
    q.write_text(src)
    eng = Engine([cls() for cls in ALL_RULES], tmp_path)
    hits = [f for f in eng.check_file(q)
            if f.rule in ("errno-conflation", "swallowed-error",
                          "bare-retry", "reply-on-all-paths")]
    assert not hits, [f.render() for f in hits]


def test_reply_on_all_paths_flags_missing_branch_and_bare_return():
    findings, _ = scan(FIXTURES / "reply_on_all_paths_red.py")
    msgs = [f.message for f in findings
            if f.rule == "reply-on-all-paths"]
    assert any("without sending a reply" in m for m in msgs), msgs
    assert any("bare `return`" in m for m in msgs), msgs
    assert any("fall off the end" in m for m in msgs), msgs


def test_bare_retry_points_at_backoff():
    findings, _ = scan(FIXTURES / "bare_retry_red.py")
    msgs = [f.message for f in findings if f.rule == "bare-retry"]
    assert len(msgs) == 2, msgs
    assert all("Backoff" in m for m in msgs), msgs


# --------------------------------------------------- baseline contract

def test_baseline_requires_reasons(tmp_path):
    b = tmp_path / "b.json"
    b.write_text(json.dumps({"suppressions": [
        {"rule": "raw-lock", "path": "x.py"}]}))
    with pytest.raises(BaselineError):
        load_baseline(b)
    b.write_text(json.dumps({"suppressions": [
        {"rule": "raw-lock", "path": "x.py", "reason": "why\nnot"}]}))
    with pytest.raises(BaselineError):
        load_baseline(b)


def test_committed_baseline_is_valid():
    entries = load_baseline(ROOT / ".cephck-baseline.json")
    assert all(e.reason for e in entries)


def test_baseline_suppresses(tmp_path):
    red = FIXTURES / "bare_except_red.py"
    baseline = load_baseline_from({"suppressions": [
        {"rule": "bare-except",
         "path": "tests/fixtures/cephck/bare_except_red.py",
         "reason": "fixture exercise"}]}, tmp_path)
    findings, eng = scan(red, baseline)
    assert not findings and len(eng.suppressed) == 1


def load_baseline_from(data, tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(data))
    return load_baseline(p)


# ------------------------------------------------------ the ship gate

def test_tree_scans_clean():
    """The acceptance gate itself: the full-tree scan is clean under
    the committed baseline (unsuppressed findings fail the build via
    scripts/check_green.sh --static).  In-process — the CLI wrapper
    is covered separately by test_cli_exit_codes."""
    eng = Engine([cls() for cls in ALL_RULES], ROOT,
                 suppressions=load_baseline(
                     ROOT / ".cephck-baseline.json"))
    rc = eng.run(["ceph_tpu", "tests", "scripts", "bench.py"])
    assert rc == 0, "\n".join(f.render() for f in eng.findings)
    assert not eng.errors, eng.errors
    assert not eng.stale_suppressions(), [
        (s.rule, s.path) for s in eng.stale_suppressions()]


def test_stale_suppression_fails_and_prune_rewrites(tmp_path):
    """Baseline hygiene: a suppression nothing matches FAILS the run
    (exit 1); --prune-baseline rewrites the file dropping exactly the
    stale entries, so the blindfold can only shrink."""
    green = FIXTURES / "bare_except_green.py"
    b = tmp_path / "baseline.json"
    live = {"rule": "bare-except",
            "path": "tests/fixtures/cephck/bare_except_red.py",
            "reason": "fixture exercise"}
    stale = {"rule": "raw-lock",
             "path": "tests/fixtures/cephck/bare_except_green.py",
             "reason": "no longer true"}
    b.write_text(json.dumps({"suppressions": [live, stale]}))
    proc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis",
         "--baseline", str(b), str(green)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale suppression" in proc.stderr
    proc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis",
         "--baseline", str(b), "--prune-baseline", str(green)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    kept = json.loads(b.read_text())["suppressions"]
    # the stale entry went; the (unscanned, hence not-stale) live
    # entry survives the rewrite untouched
    assert kept == [live], kept


def test_cli_exit_codes():
    """CLI contract: 1 on findings, 0 on a clean file."""
    red = FIXTURES / "bare_except_red.py"
    green = FIXTURES / "bare_except_green.py"
    proc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis", str(red)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "bare-except" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis", str(green)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_sarif_output_schema_and_escaping():
    """--format sarif: a valid SARIF 2.1.0 log whose results point at
    the right file/line, with rule metadata for every fired rule and
    json-level escaping of hostile message content."""
    red = FIXTURES / "bare_except_red.py"
    proc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis", "--format", "sarif",
         str(red)],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    log = json.loads(proc.stdout)          # must parse as-is
    assert log["version"] == "2.1.0"
    assert log["$schema"].endswith("sarif-2.1.0.json")
    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "cephck"
    results = run["results"]
    assert results, "red fixture must produce results"
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    for res in results:
        assert res["level"] == "error"
        # ruleIndex must agree with the driver rules table
        assert rule_ids[res["ruleIndex"]] == res["ruleId"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(
            "bare_except_red.py")
        assert loc["region"]["startLine"] >= 1
    assert any(r["ruleId"] == "bare-except" for r in results)
    assert run["invocations"][0]["executionSuccessful"] is True


def test_sarif_report_escapes_hostile_messages():
    """Messages carrying quotes, newlines, %-sequences and non-ascii
    must survive the emit -> parse round trip byte-exact (json.dumps
    owns the escaping; this pins that no manual mangling creeps in)."""
    import dataclasses as _dc
    from ceph_tpu.analysis.engine import Finding
    nasty = 'quote " backslash \\ newline \n percent %0A tab \t \u00e9'
    f = Finding(rule="bare-except", path='a "b"/c.py', line=3,
                symbol="f", message=nasty)
    rules = [cls() for cls in ALL_RULES]
    log = sarif_report(rules, [f], errors=["boom \n %25"],
                       stale=[])
    text = json.dumps(log)
    back = json.loads(text)
    res = back["runs"][0]["results"][0]
    assert res["message"]["text"] == nasty
    assert res["locations"][0]["physicalLocation"][
        "artifactLocation"]["uri"] == 'a "b"/c.py'
    notes = back["runs"][0]["invocations"][0][
        "toolExecutionNotifications"]
    assert notes[0]["message"]["text"] == "boom \n %25"
    assert back["runs"][0]["invocations"][0][
        "executionSuccessful"] is False
    # only fired rules appear in the driver table, with descriptions
    table = back["runs"][0]["tool"]["driver"]["rules"]
    assert [r["id"] for r in table] == ["bare-except"]
    assert table[0]["shortDescription"]["text"]


def test_no_raw_locks_outside_lockdep():
    """Belt + suspenders for the acceptance criterion: zero raw
    threading.Lock/RLock/Condition constructions outside
    common/lockdep.py (grep-level, independent of the rule code)."""
    import re
    pat = re.compile(r"threading\.(R?Lock|Condition)\(")
    offenders = []
    for d in ("ceph_tpu", "tests", "scripts"):
        for f in (ROOT / d).rglob("*.py"):
            if "fixtures" in f.parts or "__pycache__" in f.parts:
                continue
            if f.name == "lockdep.py":
                continue
            for i, line in enumerate(f.read_text().splitlines(), 1):
                if pat.search(line):
                    offenders.append(f"{f}:{i}")
    assert not offenders, offenders
