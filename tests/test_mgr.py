"""MgrDaemon balancer loop: optimize -> mon commands -> map epochs ->
distribution improves (ref: src/pybind/mgr/balancer/module.py serve/
execute loop)."""
import numpy as np
import pytest

from ceph_tpu.osd.balancer import Balancer
from ceph_tpu.testing import MiniCluster


def make_cluster():
    c = MiniCluster(n_osd=8, osds_per_host=2, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    r.pool_create("p", pg_num=64)
    c.pump()
    return c, r


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_mgr_balances_cluster():
    c, r = make_cluster()
    mgr = c.start_mgr(max_deviation=1, max_iterations=60)
    before = Balancer().score(c.mon.osdmap)
    sent = mgr.tick()
    c.pump()          # mon applies commands, publishes new epochs
    assert sent > 0
    assert len(c.mon.osdmap.pg_upmap_items) > 0
    after = Balancer().score(c.mon.osdmap)
    assert after["stddev"] < before["stddev"]
    assert after["max_deviation"] <= 2.0
    # mgr received the new epochs through its subscription
    assert mgr.osdmap.epoch == c.mon.osdmap.epoch
    # steady state: a second tick finds little or nothing
    sent2 = mgr.tick()
    c.pump()
    assert sent2 <= max(2, sent // 10)
    st = mgr.status()
    assert st["active"] and st["mode"] == "upmap"
    assert st["last_optimize"]["commands"] == sent2
    c.shutdown()


def test_mgr_inactive_noop():
    c, r = make_cluster()
    mgr = c.start_mgr()
    mgr.active = False
    assert mgr.tick() == 0
    assert not c.mon.osdmap.pg_upmap_items
    c.shutdown()


@pytest.mark.slow   # jit-compile-heavy on current jax; full-suite only (tier-1 budget)
def test_mgr_osd_daemons_see_balanced_map():
    """The upmaps the mgr installs actually move PG ownership on the
    OSD daemons (end-to-end through mon publish)."""
    c, r = make_cluster()
    mgr = c.start_mgr(max_deviation=1, max_iterations=60)
    mgr.tick()
    c.pump()
    e = c.mon.osdmap.epoch
    for d in c.osds.values():
        assert d.osdmap.epoch == e
        assert d.osdmap.pg_upmap_items == c.mon.osdmap.pg_upmap_items
    # IO still works on the rebalanced layout
    io = r.open_ioctx("p")
    io.write_full("post-balance", b"ok" * 200)
    assert io.read("post-balance") == b"ok" * 200
    c.shutdown()
