"""green: block_until_ready before the clock stops."""
import time

import jax
import jax.numpy as jnp


@jax.jit
def kernel(x):
    return (x @ x).sum()


def bench(x):
    jax.block_until_ready(kernel(x))     # warm
    t0 = time.perf_counter()
    jax.block_until_ready(kernel(x))
    return time.perf_counter() - t0
