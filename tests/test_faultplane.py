"""FaultPlane: deterministic link-level fault injection (drop /
partition / delay / reorder / dup), its LocalNetwork wiring, and the
drops ledger + perf export (ref: ms_inject_socket_failures and the qa
netem partition helpers, unified; ISSUE 17)."""
import time

import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.msg import LocalNetwork, Messenger
from ceph_tpu.msg.messages import Ping


class Msg:
    """Minimal message for raw-plane tests."""
    def __init__(self, n=0, type_name="X"):
        self.n = n
        self.type_name = type_name

    def __repr__(self):
        return f"Msg({self.n})"


def plane(seed=0, clock=None):
    from ceph_tpu.msg.faults import FaultPlane
    return FaultPlane(seed=seed) if clock is None \
        else FaultPlane(seed=seed, clock=clock)


def drive(p, n=30, src="a", dst="b", type_name="X"):
    got = []
    for i in range(n):
        p.intercept(src, dst, Msg(i, type_name),
                    lambda s, d, m: got.append(m.n))
    return got


# ---------------------------------------------------------- determinism
def test_same_seed_same_fault_sequence_and_digest():
    runs = []
    for _ in range(2):
        p = plane(seed=42)
        p.add_rule("a", "b", drop=0.4)
        runs.append((drive(p), p.digest()))
    assert runs[0] == runs[1]
    # a different seed draws a different stream
    p = plane(seed=43)
    p.add_rule("a", "b", drop=0.4)
    assert (drive(p), p.digest()) != runs[0]


def test_digest_insensitive_to_cross_link_interleaving():
    """Traffic order ACROSS links must not perturb the digest — only
    each link's own sequence matters (real-time timers elsewhere in
    the cluster cannot break replay)."""
    pa = plane(seed=1)
    pa.add_rule("*", "*", drop=0.3)
    pb = plane(seed=1)
    pb.add_rule("*", "*", drop=0.3)
    # run A: all of link1 then all of link2; run B: interleaved
    for i in range(10):
        pa.intercept("x", "y", Msg(i), lambda *a: None)
    for i in range(10):
        pa.intercept("y", "x", Msg(i), lambda *a: None)
    for i in range(10):
        pb.intercept("x", "y", Msg(i), lambda *a: None)
        pb.intercept("y", "x", Msg(i), lambda *a: None)
    assert pa.digest() == pb.digest()


def test_probabilistic_drop_produces_bursts():
    """The old 1-in-N modulus could never drop two consecutive
    messages; the seeded probability draw can."""
    p = plane(seed=0)
    p.add_rule("a", "b", drop=0.5)
    delivered = set(drive(p, 100))
    gaps = [i for i in range(99)
            if i not in delivered and i + 1 not in delivered]
    assert gaps                      # at least one 2-message burst


# ------------------------------------------------------------ partition
def test_asymmetric_partition_is_one_directional():
    p = plane()
    p.partition(["a"], ["b"], symmetric=False)
    assert drive(p, 5, "a", "b") == []           # a -> b black-holed
    assert drive(p, 5, "b", "a") == [0, 1, 2, 3, 4]  # reverse flows
    assert p.counts["partition"] == 5


def test_heal_restores_and_releases_held():
    t = [100.0]
    p = plane(clock=lambda: t[0])
    ids = p.partition(["a"], ["b"])
    rid = p.add_rule("c", "d", delay=5.0)
    held = []
    p.intercept("c", "d", Msg(7), lambda s, d, m: held.append(m.n))
    assert held == [] and p.pending() == 1
    p.deliver_cb = lambda s, d, m: held.append(m.n)
    p.heal(ids + [rid])              # targeted heal flushes the hold
    assert held == [7] and p.pending() == 0
    assert drive(p, 2, "a", "b") == [0, 1]
    assert not p.rules()


def test_isolate_cuts_both_directions():
    p = plane()
    p.isolate("osd.3")
    assert drive(p, 3, "osd.3", "mon.0") == []
    assert drive(p, 3, "mon.0", "osd.3") == []
    assert drive(p, 3, "osd.1", "mon.0") == [0, 1, 2]


# --------------------------------------------------------- delay/reorder
def test_delay_holds_until_clock_passes():
    t = [50.0]
    p = plane(clock=lambda: t[0])
    p.add_rule("a", "b", delay=2.0)
    got = []
    deliver = lambda s, d, m: got.append(m.n)   # noqa: E731
    p.intercept("a", "b", Msg(1), deliver)
    assert got == [] and p.pending() == 1
    assert p.flush(deliver) == 0                # too early
    t[0] = 52.5
    assert p.flush(deliver) == 1
    assert got == [1]


def test_jittered_delay_is_seeded():
    for _ in range(2):
        t = [0.0]
        p = plane(seed=9, clock=lambda: t[0])
        p.add_rule("a", "b", delay=1.0, jitter=1.0)
        p.intercept("a", "b", Msg(0), lambda *a: None)
    # the drawn delay rides the digest (recorded to 6dp)
    d1 = p.digest()
    t = [0.0]
    p2 = plane(seed=9, clock=lambda: t[0])
    p2.add_rule("a", "b", delay=1.0, jitter=1.0)
    p2.intercept("a", "b", Msg(0), lambda *a: None)
    assert p2.digest() == d1


def test_reorder_window_releases_shuffled_deterministically():
    def run():
        p = plane(seed=5)
        p.add_rule("a", "b", reorder=4)
        return drive(p, 8), p.digest()
    (order1, d1), (order2, d2) = run(), run()
    assert order1 == order2 and d1 == d2
    assert sorted(order1) == list(range(8))     # nothing lost
    assert order1 != list(range(8))             # actually shuffled


def test_partial_reorder_window_latches_out():
    t = [10.0]
    p = plane(seed=5, clock=lambda: t[0])
    p.add_rule("a", "b", reorder=10)
    got = drive(p, 3)
    assert got == [] and p.pending() == 3
    t[0] += 1.0                                 # past REORDER_LATCH_S
    released = []
    p.flush(lambda s, d, m: released.append(m.n))
    assert sorted(released) == [0, 1, 2]


def test_dup_delivers_twice():
    p = plane(seed=0)
    p.add_rule("a", "b", dup=1.0)
    assert drive(p, 3) == [0, 0, 1, 1, 2, 2]
    assert p.counts["dup"] == 3


def test_type_filter_scopes_the_rule():
    p = plane()
    p.partition(["a"], ["b"], symmetric=False, types=("Ping",))
    assert drive(p, 2, type_name="Ping") == []
    assert drive(p, 2, type_name="MOSDOp") == [0, 1]


# ----------------------------------------------------- network wiring
def test_localnetwork_drop_ring_bounded_total_monotonic():
    from ceph_tpu.msg.messenger import DROP_RING
    cfg = global_config()
    net = LocalNetwork()
    a = Messenger.create(net, "a", "local", threaded=False)
    Messenger.create(net, "b", "local", threaded=False)
    try:
        cfg.set("ms_inject_socket_failures", 1)   # p=1: drop all
        n = DROP_RING + 50
        for i in range(n):
            a.connect("b").send_message(Ping(epoch=i))
        assert net.drops_total == n               # exact, monotonic
        assert len(net.dropped) == DROP_RING      # ring bounded
    finally:
        cfg.set("ms_inject_socket_failures", 0)


def test_partition_is_silent_no_resets():
    """Partitions black-hole without handle_reset — detection must be
    timeout-driven, like a real netsplit (shim drops DO reset)."""
    net = LocalNetwork()
    a = Messenger.create(net, "a", "local", threaded=False)
    b = Messenger.create(net, "b", "local", threaded=False)
    resets = []
    class D:
        def ms_dispatch(self, m): return True
        def ms_handle_reset(self, peer): resets.append(peer)
    a.add_dispatcher(D())
    b.add_dispatcher(D())
    net.faults.partition(["a"], ["b"])
    assert a.connect("b").send_message(Ping()) is False
    assert resets == []
    assert net.drops_total == 1


def test_drops_total_exported_through_perf_dump():
    """satellite (a): the fabric's drop ledger rides the OSD perf
    counters up to the mon's `osd perf dump`."""
    from ceph_tpu.testing import MiniCluster
    c = MiniCluster(n_osd=3, threaded=False)
    c.pump()
    c.wait_all_up()
    try:
        # heartbeat peers come from PG membership: need a pool
        r = c.rados()
        r.pool_create("p", pg_num=8)
        c.pump()
        rid = c.network.faults.add_rule(
            "osd.*", "osd.*", drop=1.0, types=("Ping",))
        now = 50_000.0
        c.tick(now)                     # heartbeats -> dropped pings
        c.network.faults.heal([rid])
        assert c.network.drops_total > 0
        now += 11.0
        c.tick(now)                     # pg-stats report carries perf
        rc, _, out = c.mon.handle_command({"prefix": "osd perf dump"})
        assert rc == 0
        vals = [r.get("msgr_drops_total") for r in out.values()]
        assert any(v == c.network.drops_total for v in vals), out
    finally:
        c.shutdown()
