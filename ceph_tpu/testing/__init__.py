"""Test/QA harnesses (the qa/ tier analogues)."""
from .cluster import MiniCluster
from .thrasher import OSDThrasher

__all__ = ["MiniCluster", "OSDThrasher"]
