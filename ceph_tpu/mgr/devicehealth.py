"""devicehealth-lite: device error metrics -> life expectancy ->
health warnings (VERDICT r4 #9; ref:
src/pybind/mgr/devicehealth/module.py — the reference scrapes SMART
data via smartctl and predicts device life; this framework's devices
are the OSDs' BlueStore instances, whose at-rest checksum machinery
IS the health feed: csum mismatches and read errors are exactly what
a dying medium produces).

Per tick: pull `osd perf dump` from the mon, fold each OSD's
`bluestore_csum_errors` / `bluestore_read_errors` into a per-device
record with a synthetic life-expectancy estimate, and when a device
crosses the warning threshold raise a DEVICE_HEALTH check (merged
into `ceph health` via the mon's module-health report), emit a
progress event, and log to the cluster log."""
from __future__ import annotations

import time

from ..common.log import dout

#: error-count thresholds for the synthetic life model (the reference
#: predicts from SMART trends; our media errors are rarer and harsher)
WARN_ERRORS = 1           # any media error is worth a warning
FAIL_ERRORS = 16          # persistent rot: expect imminent failure


class DeviceHealth:
    """(ref: devicehealth/module.py Module)."""

    def __init__(self, mgr):
        self.mgr = mgr
        #: device name -> record (one device per OSD: "osd.N-dev")
        self.devices: dict[str, dict] = {}
        self._warned: set[str] = set()

    # ------------------------------------------------------------ tick
    def tick(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        rc, _, perf = self.mgr.mon_command({"prefix": "osd perf dump"})
        if rc != 0 or not isinstance(perf, dict):
            return
        checks_detail = []
        for daemon, counters in sorted(perf.items()):
            csum = int(counters.get("bluestore_csum_errors", 0))
            rerr = int(counters.get("bluestore_read_errors", 0))
            errors = csum + rerr
            dev = f"{daemon}-dev"
            if errors >= FAIL_ERRORS:
                health, life = "FAILING", "<1w"
            elif errors >= WARN_ERRORS:
                health, life = "WARNING", "<6w"
            else:
                health, life = "GOOD", ">52w"
            self.devices[dev] = {
                "device": dev, "daemon": daemon,
                "csum_errors": csum, "read_errors": rerr,
                "health": health, "life_expectancy": life,
                "stamp": now}
            if health != "GOOD":
                checks_detail.append(
                    f"{dev} ({daemon}): {errors} media errors, "
                    f"life expectancy {life}")
                if dev not in self._warned:
                    self._warned.add(dev)
                    self._on_new_unhealthy(dev, daemon, errors, life)
            else:
                self._warned.discard(dev)
        checks = {}
        if checks_detail:
            checks["DEVICE_HEALTH"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(checks_detail)} devices reporting "
                           "media errors",
                "detail": checks_detail}
        # replace this module's slice: recovered devices clear their
        # check; other modules' slices (RECENT_CRASH) stay intact
        self.mgr.set_health_checks("devicehealth", checks)

    def _on_new_unhealthy(self, dev: str, daemon: str, errors: int,
                          life: str) -> None:
        dout("mgr", 1).write("devicehealth: %s unhealthy (%d errors)",
                             dev, errors)
        self.mgr.mon_command({
            "prefix": "log", "level": "warn", "who": "mgr.devicehealth",
            "logtext": f"device {dev} on {daemon} reports {errors} "
                       f"media errors, life expectancy {life}"})
        if getattr(self.mgr, "progress", None) is not None:
            ev_id = f"devicehealth-{dev}"
            self.mgr.progress.update(
                ev_id, f"devicehealth: {dev} degraded "
                f"(life expectancy {life})", 0.0)
            self.mgr.progress.complete(ev_id)

    # ------------------------------------------------------- queries
    def ls(self) -> list[dict]:
        """`ceph device ls` (ref: devicehealth's device listing)."""
        return [self.devices[d] for d in sorted(self.devices)]

    def get_health(self, dev: str) -> dict | None:
        return self.devices.get(dev)
