"""cephfs-lite: a POSIX-ish file namespace on RADOS.

Single-rank metadata server + libcephfs-like client
(ref: src/mds + src/client, radically reduced: one rank, no caps/
locks/fragmentation — but the same storage shapes: dentry-omap
directory objects in a metadata pool, write-ahead journal, striped
file data objects `{ino}.{objno}` in a data pool)."""
from .client import CephFS, FileHandle
from .mds import MDSDaemon

__all__ = ["MDSDaemon", "CephFS", "FileHandle"]
