"""Cache-proof timing: unique input per rep + scalar readback."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp

from ceph_tpu.ec import gf
from ceph_tpu.ec.kernels import bitmatmul

k, m = 8, 4
chunk = 128 * 1024
rng = np.random.default_rng(0)
mat = gf.isa_rs_matrix(k, m)[k:]
B = jnp.asarray(gf.expand_to_bitmatrix(mat).astype(np.int8))


@jax.jit
def step_xla(B, data, i):
    out = bitmatmul.gf_matmul_xla(B, data ^ i)
    return jnp.sum(out, dtype=jnp.int32)


@jax.jit
def step_pallas(B, data, i):
    out = bitmatmul.gf_matmul_pallas(B, data ^ i)
    return jnp.sum(out, dtype=jnp.int32)


for stripes in (64, 256):
    data = jnp.asarray(rng.integers(0, 256, (stripes, k, chunk), dtype=np.uint8))
    for label, fn in (("xla", step_xla), ("pallas", step_pallas)):
        float(fn(B, data, jnp.uint8(255)))  # warm
        reps = 10
        t0 = time.perf_counter()
        for i in range(reps):
            s = float(fn(B, data, jnp.uint8(i)))
        dt = (time.perf_counter() - t0) / reps
        total_in = stripes * k * chunk
        total_out = stripes * m * chunk
        print(f"stripes={stripes:4d} {label:6s}: {dt*1e3:8.3f} ms  "
              f"in {total_in/dt/1e9:8.2f} GB/s  io {(total_in+total_out)/dt/1e9:8.2f} GB/s")
