"""Secure wire mode: authenticated encryption for TCP frames.

The msgr-v2 secure-mode analogue (ref: src/msg/async/crypto_onwire.cc
— AES-GCM over the frame payload once the cephx handshake yields a
session key; frames_v2.h SECURE mode).  The environment has no AES
primitive (no `cryptography` package; hashlib/hmac only), so the
cipher is built from the standard primitives instead:

* **keystream**: HMAC-SHA256 as a PRF in counter mode —
  KS_i = HMAC(k_enc, nonce || i); ciphertext = plaintext XOR KS.
  A PRF in CTR mode is a standard stream-cipher construction (the
  same shape as AES-CTR with the PRF swapped).
* **integrity**: encrypt-then-MAC with an independent key —
  tag = HMAC(k_mac, nonce || ciphertext), truncated to 16 bytes
  (the AES-GCM tag length).  Verified before any decode touches the
  bytes.
* **keys**: both enc and mac keys derive from the cluster secret under
  a fixed role label, and ALL endpoints share them (the transport
  passes one role, so there is no per-direction or per-connection key
  separation — stream uniqueness comes entirely from the random
  96-bit per-frame nonce).  Safe because the PRF keystream depends on
  the full nonce: there is no GCM-style nonce-reuse catastrophe —
  a collision degrades to a two-time-pad on that frame pair only, and
  96-bit random collisions are negligible.  Per-session keys (the
  reference derives them from the auth handshake) are the obvious
  upgrade path via the `role` parameter.

This is honest-about-primitives security: confidentiality + integrity
+ the same wire layout role as the reference's secure mode, not a
claim of AES-GCM bit-compatibility.
"""
from __future__ import annotations

import hashlib
import hmac
import os
import struct

TAG_LEN = 16
NONCE_LEN = 12
_BLOCK = hashlib.sha256().digest_size


class SecureSession:
    """Per-connection-direction frame sealer/opener."""

    def __init__(self, secret: str | bytes, role: str):
        if isinstance(secret, str):
            secret = secret.encode()
        self.k_enc = hmac.new(secret, b"ms-secure-enc|" + role.encode(),
                              hashlib.sha256).digest()
        self.k_mac = hmac.new(secret, b"ms-secure-mac|" + role.encode(),
                              hashlib.sha256).digest()

    # -- keystream ------------------------------------------------------
    def _keystream(self, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        for i in range((n + _BLOCK - 1) // _BLOCK):
            out += hmac.new(self.k_enc,
                            nonce + struct.pack("!Q", i),
                            hashlib.sha256).digest()
        return bytes(out[:n])

    def _xor(self, data: bytes, nonce: bytes) -> bytes:
        ks = self._keystream(nonce, len(data))
        return bytes(a ^ b for a, b in zip(data, ks)) \
            if len(data) < 4096 else _xor_np(data, ks)

    # -- frame seal/open ------------------------------------------------
    def seal(self, plaintext: bytes) -> bytes:
        """nonce || ciphertext || tag (the SECURE frame body)."""
        nonce = os.urandom(NONCE_LEN)
        ct = self._xor(plaintext, nonce)
        tag = hmac.new(self.k_mac, nonce + ct,
                       hashlib.sha256).digest()[:TAG_LEN]
        return nonce + ct + tag

    def open(self, blob: bytes) -> bytes | None:
        """Verify + decrypt; None on any mismatch (the caller treats it
        like a corrupt frame and drops the connection)."""
        if len(blob) < NONCE_LEN + TAG_LEN:
            return None
        nonce = blob[:NONCE_LEN]
        ct = blob[NONCE_LEN:-TAG_LEN]
        tag = blob[-TAG_LEN:]
        want = hmac.new(self.k_mac, nonce + ct,
                        hashlib.sha256).digest()[:TAG_LEN]
        if not hmac.compare_digest(want, tag):
            return None
        return self._xor(ct, nonce)


def _xor_np(data: bytes, ks: bytes) -> bytes:
    import numpy as np
    a = np.frombuffer(data, dtype=np.uint8)
    b = np.frombuffer(ks, dtype=np.uint8)
    return (a ^ b).tobytes()
