"""red: three ways to drift from the wire schema lockfile.

SnapTrim here is missing the committed `clone` field (removal),
SnapTrimReply retypes `committed`, and SnapTrimPurged's _VERSIONS
entry declares compat > version.
"""
from dataclasses import dataclass
from typing import Any

from ceph_tpu.msg.messenger import Message

_VERSIONS = {"SnapTrimPurged": (1, 2)}


@dataclass
class SnapTrim(Message):
    pgid: Any = None
    tid: int = 0
    oid: str = ""
    snap: int = 0
    from_osd: int = -1


@dataclass
class SnapTrimReply(Message):
    pgid: Any = None
    tid: int = 0
    from_osd: int = -1
    committed: int = 1


@dataclass
class SnapTrimPurged(Message):
    pgid: Any = None
    snaps: Any = None
    purged: Any = None
    from_osd: int = -1
