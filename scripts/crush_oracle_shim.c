/* Dev-time oracle shim: exposes the reference CRUSH C core
 * (/root/reference/src/crush — builder.c/mapper.c/crush.c/hash.c) through a
 * flat C ABI so scripts/gen_crush_fixtures.py can drive it via ctypes and
 * pin fixture vectors for the Python/JAX engines.
 *
 * Build (see scripts/build_crush_oracle.sh):
 *   gcc -O2 -shared -fPIC -I. -I$REF/src -I$REF/src/crush \
 *       crush_oracle_shim.c $REF/src/crush/{builder,mapper,crush,hash}.c \
 *       -o /tmp/crush_oracle/libcrush_oracle.so -lm
 *
 * This file contains no reference code — only calls into its public API.
 */
#include <stdlib.h>
#include <string.h>

#include "crush/crush.h"
#include "crush/builder.h"
#include "crush/mapper.h"
#include "crush/hash.h"

struct crush_map *oracle_create(void)
{
	return crush_create();
}

void oracle_set_tunables(struct crush_map *m, int local_tries,
			 int local_fallback_tries, int total_tries,
			 int descend_once, int vary_r, int stable)
{
	m->choose_local_tries = local_tries;
	m->choose_local_fallback_tries = local_fallback_tries;
	m->choose_total_tries = total_tries;
	m->chooseleaf_descend_once = descend_once;
	m->chooseleaf_vary_r = vary_r;
	m->chooseleaf_stable = stable;
}

int oracle_add_bucket(struct crush_map *m, int alg, int type, int n,
		      int *items, int *weights, int want_id)
{
	struct crush_bucket *b;
	int id = 0;

	b = crush_make_bucket(m, alg, CRUSH_HASH_RJENKINS1, type, n,
			      items, weights);
	if (!b)
		return 0x7fffffff;
	if (crush_add_bucket(m, want_id, b, &id) < 0)
		return 0x7fffffff;
	return id;
}

int oracle_add_rule(struct crush_map *m, int n, int *ops, int *arg1,
		    int *arg2)
{
	struct crush_rule *r = crush_make_rule(n, 0, 1, 1, 10);
	int i;

	if (!r)
		return -1;
	for (i = 0; i < n; i++)
		crush_rule_set_step(r, i, ops[i], arg1[i], arg2[i]);
	return crush_add_rule(m, r, -1);
}

void oracle_finalize(struct crush_map *m)
{
	crush_finalize(m);
}

int oracle_do_rule(struct crush_map *m, int ruleno, int x, int *result,
		   int result_max, unsigned *weights, int weight_max)
{
	char *work = malloc(crush_work_size(m, result_max));
	int n;

	crush_init_workspace(m, work);
	n = crush_do_rule(m, ruleno, x, result, result_max,
			  weights, weight_max, work, NULL);
	free(work);
	return n;
}

/* Bulk single-threaded mapping loop: the baseline timing surface for
 * PLACEMENT_BENCH's vs_baseline (the osdmaptool --test-map-pgs
 * workload on one core; the reference threads this via
 * ParallelPGMapper, src/osd/OSDMapMapping.h).  Workspace allocated
 * once; returns an output checksum so the loop cannot be elided. */
long long oracle_map_bulk(struct crush_map *m, int ruleno,
			  const int *xs, int n, int result_max,
			  unsigned *weights, int weight_max,
			  int *out)
{
	int result[64];
	char *work = malloc(crush_work_size(m, result_max));
	long long acc = 0;
	int i, j, cnt;

	if (result_max > 64)
		result_max = 64;
	for (i = 0; i < n; i++) {
		crush_init_workspace(m, work);
		cnt = crush_do_rule(m, ruleno, xs[i], result, result_max,
				    weights, weight_max, work, NULL);
		for (j = 0; j < cnt; j++) {
			acc += result[j];
			if (out)
				out[(long long)i * result_max + j] =
					result[j];
		}
		if (out)
			for (; j < result_max; j++)
				out[(long long)i * result_max + j] = -1;
	}
	free(work);
	return acc;
}

unsigned oracle_hash32_2(unsigned a, unsigned b)
{
	return crush_hash32_2(CRUSH_HASH_RJENKINS1, a, b);
}

unsigned oracle_hash32_3(unsigned a, unsigned b, unsigned c)
{
	return crush_hash32_3(CRUSH_HASH_RJENKINS1, a, b, c);
}
