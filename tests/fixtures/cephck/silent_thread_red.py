"""red: a daemon loop that swallows its own death."""
import threading


def _loop():
    failures = 0
    while True:
        try:
            work()
        except Exception:
            failures += 1     # counted but never surfaced anywhere a
            # supervisor looks: the thread still dies silently


def work():
    raise RuntimeError


t = threading.Thread(target=_loop, daemon=True)
