"""red: a daemon loop that swallows its own death."""
import threading


def _loop():
    while True:
        try:
            work()
        except Exception:
            pass          # the thread dies silently


def work():
    raise RuntimeError


t = threading.Thread(target=_loop, daemon=True)
