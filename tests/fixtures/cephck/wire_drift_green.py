"""green: field lists byte-identical to the committed schema."""
from dataclasses import dataclass
from typing import Any

from ceph_tpu.msg.messenger import Message


@dataclass
class SnapTrim(Message):
    pgid: Any = None
    tid: int = 0
    oid: str = ""
    snap: int = 0
    clone: int = 0
    from_osd: int = -1


@dataclass
class SnapTrimReply(Message):
    pgid: Any = None
    tid: int = 0
    from_osd: int = -1
    committed: bool = True
