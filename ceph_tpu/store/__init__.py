"""Object storage engine layer (ref: src/os/).

`ObjectStore` is the abstract transactional API (ObjectStore.h:66);
`MemStore` is the in-memory implementation used by the OSD shards and
tests (model: src/os/memstore/MemStore.cc); `JournaledStore` adds an
on-disk write-ahead journal + snapshot (FileStore/FileJournal shape)
for durable one-process-per-daemon deployments.
"""
from .objectstore import ObjectStore, Transaction, ObjectId, StoreError
from .memstore import MemStore
from .journaled import JournaledStore

__all__ = ["ObjectStore", "Transaction", "ObjectId", "StoreError",
           "MemStore", "JournaledStore"]
