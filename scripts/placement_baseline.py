#!/usr/bin/env python
"""CPU placement baseline: the reference CRUSH C core mapping the
BASELINE scale (1M PGs x 10k OSDs straw2) single-threaded.

Builds the same osdmaptool --createsimple topology as
scripts/placement_bench.py inside the compiled reference core
(/tmp/crush_oracle/libcrush_oracle.so — scripts/build_crush_oracle.sh)
and times `crush_do_rule` over every PG in one C-side loop
(`oracle_map_bulk`), so no Python/ctypes per-call overhead taints the
number (ref: src/tools/osdmaptool.cc --test-map-pgs driving
src/crush/mapper.c:900 on one core; the reference threads the same
loop via ParallelPGMapper, src/osd/OSDMapMapping.h:18).

Prints one JSON line: {"baseline_mappings_per_s": ...}.  Run with
--update-bench to fold the number into PLACEMENT_BENCH.json as
`baseline_mappings_per_s` + `vs_baseline`.
"""
import argparse
import ctypes
import json
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from ceph_tpu.crush.types import (CRUSH_BUCKET_STRAW2,  # noqa: E402
                                  CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                  CRUSH_RULE_EMIT, CRUSH_RULE_TAKE)

ORACLE_SO = "/tmp/crush_oracle/libcrush_oracle.so"
#: jewel tunables, matching CrushMap.set_tunables_profile("jewel")
JEWEL = (0, 0, 50, 1, 1, 1)


def build_oracle(n_osd: int, osds_per_host: int = 20):
    lib = ctypes.CDLL(ORACLE_SO)
    lib.oracle_create.restype = ctypes.c_void_p
    lib.oracle_add_bucket.restype = ctypes.c_int
    lib.oracle_add_rule.restype = ctypes.c_int
    lib.oracle_map_bulk.restype = ctypes.c_longlong
    h = ctypes.c_void_p(lib.oracle_create())
    lib.oracle_set_tunables(h, *[ctypes.c_int(v) for v in JEWEL])

    def add_bucket(alg, type_, items, weights):
        n = len(items)
        ia = (ctypes.c_int * n)(*items)
        wa = (ctypes.c_int * n)(*weights)
        return lib.oracle_add_bucket(h, alg, type_, n, ia, wa, 0)

    # mirror OSDMap.build_simple: hosts of `osds_per_host`, one root
    host_ids = []
    for base in range(0, n_osd, osds_per_host):
        items = list(range(base, min(base + osds_per_host, n_osd)))
        host_ids.append(add_bucket(CRUSH_BUCKET_STRAW2, 1, items,
                                   [0x10000] * len(items)))
    hw = [0x10000 * osds_per_host] * len(host_ids)
    root = add_bucket(CRUSH_BUCKET_STRAW2, 10, host_ids, hw)
    steps = [(CRUSH_RULE_TAKE, root, 0),
             (CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1),
             (CRUSH_RULE_EMIT, 0, 0)]
    n = len(steps)
    ops = (ctypes.c_int * n)(*[s[0] for s in steps])
    a1 = (ctypes.c_int * n)(*[s[1] for s in steps])
    a2 = (ctypes.c_int * n)(*[s[2] for s in steps])
    ruleno = lib.oracle_add_rule(h, n, ops, a1, a2)
    lib.oracle_finalize(h)
    return lib, h, ruleno


def run(n_osd: int, pg_num: int, size: int = 3,
        verify_sample: int = 64) -> dict:
    from ceph_tpu.osd.types import PGPool
    pool = PGPool(pg_num=pg_num, pgp_num=pg_num, size=size)
    pss = np.arange(pg_num, dtype=np.int64)
    pps = pool.raw_pg_to_pps_batch(pss, 0).astype(np.int32)

    lib, h, ruleno = build_oracle(n_osd)
    weights = (ctypes.c_uint * n_osd)(*([0x10000] * n_osd))
    xs = pps.ctypes.data_as(ctypes.POINTER(ctypes.c_int))

    # warm pass on a slice (page in the map), then the timed full loop
    lib.oracle_map_bulk(h, ruleno, xs, min(4096, pg_num), size,
                        weights, n_osd, None)
    t0 = time.perf_counter()
    acc = lib.oracle_map_bulk(h, ruleno, xs, pg_num, size, weights,
                              n_osd, None)
    dt = time.perf_counter() - t0

    # cross-check a sample against the framework's scalar engine
    # (itself fixture-validated against this very C core)
    from ceph_tpu.osd.osdmap import OSDMap
    m = OSDMap()
    m.build_simple(n_osd, osds_per_host=20, pg_pool=pool)
    from ceph_tpu.crush import mapper as scalar
    rng = np.random.default_rng(0)
    out = np.empty(verify_sample * size, dtype=np.int32)
    idx = rng.choice(pg_num, size=verify_sample, replace=False)
    sample_xs = pps[idx].astype(np.int32).copy()
    lib.oracle_map_bulk(
        h, ruleno,
        sample_xs.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        verify_sample, size, weights, n_osd,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    pyrule = m.crush.find_rule(m.pools[0].crush_rule, pool.type, size)
    for i, ps in enumerate(idx):
        want = scalar.do_rule(m.crush, pyrule, int(pps[ps]), size,
                              m.osd_weight)
        got = [int(o) for o in out[i * size:(i + 1) * size]][:len(want)]
        assert got == list(want), (ps, got, want)

    return {
        "baseline_mappings_per_s": round(pg_num / dt, 1),
        "seconds": round(dt, 3),
        "n_osd": n_osd, "pg_num": pg_num, "size": size,
        "checksum": int(acc),
        "engine": "reference crush C core, 1 thread (-O2)",
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-osd", type=int, default=10_000)
    ap.add_argument("--pg-num", type=int, default=1 << 20)
    ap.add_argument("--update-bench", action="store_true",
                    help="fold baseline + vs_baseline into "
                         "PLACEMENT_BENCH.json")
    a = ap.parse_args()
    out = run(a.n_osd, a.pg_num)
    print(json.dumps(out))
    if a.update_bench:
        root = pathlib.Path(__file__).resolve().parent.parent
        path = root / "PLACEMENT_BENCH.json"
        rec = json.loads(path.read_text())
        rec["detail"]["baseline_mappings_per_s"] = \
            out["baseline_mappings_per_s"]
        rec["detail"]["baseline_engine"] = out["engine"]
        rec["vs_baseline"] = round(
            rec["value"] / out["baseline_mappings_per_s"], 3)
        path.write_text(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
