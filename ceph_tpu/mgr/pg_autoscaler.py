"""pg_autoscaler: grow pool pg_num toward the per-OSD PG target.

The mgr module (ref: src/pybind/mgr/pg_autoscaler/module.py —
`_get_pool_status` computes a per-pool target from the capacity share
and `mon_target_pg_per_osd`, `_maybe_adjust` applies it when the
current pg_num is off by the threshold factor 3).  Reduced faithfully:

* target_pg(pool) = next_pow2(share * n_osd_in * mon_target_pg_per_osd
  / replication_factor), share = the pool's byte share of stored data
  (equal split while nothing is stored yet — the `bulk` flag analogue);
* applied only when target >= threshold * pg_num (default 3.0, the
  reference's hysteresis) — and only upward: the framework supports
  splitting (OSD-side collection split, daemon._split_pgs) but not
  merging, matching pg_num reduction being refused by the mon;
* `osd pool set pg_num` first (cheap local collection split keeping
  children on the parent's placement seed), then the NEXT tick grows
  pgp_num to match — the placement reseed whose data movement the
  peering statechart chases via prior-interval queries + reservation-
  throttled backfill (osd/peering.py; the reference likewise splits
  with pg_num first and walks pgp_num up afterwards).
"""
from __future__ import annotations

from ..common.log import dout
from ..common.options import global_config


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class PGAutoscaler:
    """Runs inside MgrDaemon ticks (ref: pg_autoscaler serve loop)."""

    def __init__(self, mgr, threshold: float = 3.0,
                 max_pg_num: int = 1 << 14):
        self.mgr = mgr
        self.threshold = threshold
        self.max_pg_num = max_pg_num
        self.last_plan: list[dict] = []

    # ------------------------------------------------------------ plan
    def plan(self, osdmap, pool_bytes: dict[int, int] | None = None
             ) -> list[dict]:
        """Per-pool targets (ref: _get_pool_status)."""
        n_in = sum(1 for o in range(osdmap.max_osd) if osdmap.is_in(o))
        if not n_in or not osdmap.pools:
            return []
        target_per_osd = global_config()["mon_target_pg_per_osd"]
        pool_bytes = pool_bytes or {}
        total = sum(pool_bytes.get(p, 0) for p in osdmap.pools)
        out = []
        for pid, pool in osdmap.pools.items():
            if total > 0:
                share = pool_bytes.get(pid, 0) / total
                # floor: even an empty pool keeps a minimum footprint
                share = max(share, 0.1 / len(osdmap.pools))
            else:
                share = 1.0 / len(osdmap.pools)
            repl = max(1, pool.size)
            raw = share * n_in * target_per_osd / repl
            target = min(self.max_pg_num, next_pow2(max(4, int(raw))))
            out.append({
                "pool_id": pid,
                "pool_name": osdmap.pool_names.get(pid, str(pid)),
                "pg_num": pool.pg_num,
                "target": target,
                "would_adjust": target >= self.threshold * pool.pg_num,
            })
        return out

    # ----------------------------------------------------------- apply
    def tick(self, pool_bytes: dict[int, int] | None = None) -> int:
        """Plan + apply (ref: _maybe_adjust).  Returns commands sent.

        pgp_num follows pg_num one step behind (ref: the reference's
        gradual pgp_num increase honoring the misplaced-ratio target):
        the tick after a split, placement reseeds and the peering
        statechart's prior-interval backfill migrates the split data;
        the step-behind cadence keeps split (cheap, local) and reseed
        (data movement, reservation-throttled) in separate epochs."""
        osdmap = self.mgr.osdmap
        if osdmap.epoch == 0:
            return 0
        self.last_plan = self.plan(osdmap, pool_bytes)
        sent = 0
        for p in self.last_plan:
            pool = osdmap.pools.get(p["pool_id"])
            if pool is not None and pool.pgp_num < pool.pg_num:
                # both pool types: the peering statecharts chase a
                # reseed through prior-interval queries + backfill
                # (replicated osd/peering.py; EC osd/ec_peering.py)
                dout("mgr", 1).write(
                    "pg_autoscaler: pool %s pgp_num %d -> %d (reseed)",
                    p["pool_name"], pool.pgp_num, pool.pg_num)
                self.mgr._command({"prefix": "osd pool set",
                                   "pool": p["pool_name"],
                                   "var": "pgp_num",
                                   "val": str(pool.pg_num)})
                sent += 1
                continue
            if not p["would_adjust"]:
                continue
            dout("mgr", 1).write(
                "pg_autoscaler: pool %s pg_num %d -> %d",
                p["pool_name"], p["pg_num"], p["target"])
            self.mgr._command({"prefix": "osd pool set",
                               "pool": p["pool_name"],
                               "var": "pg_num",
                               "val": str(p["target"])})
            sent += 1
        return sent

    def status(self) -> list[dict]:
        return list(self.last_plan)
