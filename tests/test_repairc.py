"""Repair-schedule compiler (ceph_tpu.ec.repairc; ISSUE 20): the
exhaustive parity sweep pinning every compiled repair program
byte-identical to the plugin's interpreted decode, the per-signature
program cache (compile-once, cost-weighted eviction), the zero-probe
linearity guard, and the locality/read-fraction contracts of the
plans themselves."""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import factory
from ceph_tpu.ec.repairc import (RepairPlan, RepairProgram,
                                 RepairProgramCache, cache_of,
                                 compile_program, program_for)
from ceph_tpu.osd import ecutil

#: the three codes the OSD routes through the compiler, with the
#: fraction of the k-full-chunk baseline a single-failure plan reads
PLUGINS = [
    ("jerasure", {"technique": "reed_sol_van", "k": "4", "m": "2"},
     1.0),                      # k whole chunks, but decoded DIRECTLY
    ("clay", {"k": "4", "m": "2"}, 5 / 8),      # d/(k*q) = 5/(4*2)
    ("lrc", {"k": "4", "m": "2", "l": "3"}, 3 / 4),     # l/k
]


def _object(ec, nstripes=3, seed=7):
    """Encode a random object; returns (sinfo, shard streams, data)."""
    k = ec.get_data_chunk_count()
    cs = ec.get_chunk_size(k * 128)
    sinfo = ecutil.StripeInfo(k, k * cs)
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, nstripes * sinfo.stripe_width,
                        dtype=np.uint8).tobytes()
    return sinfo, ecutil.encode(sinfo, ec, data), data


def _helper_bufs(plan, shards, cs):
    """Slice each helper's chunk stream down to the plan's extents —
    exactly the bytes ECSubRead ships (per stripe, plan order)."""
    byte_ext = plan.byte_extents(cs)
    out = {}
    for h in plan.helper_ids():
        ext = ecutil.expand_stream_extents(byte_ext[h], cs,
                                           len(shards[h]))
        out[h] = b"".join(shards[h][o:o + c] for o, c in ext)
    return out


@pytest.mark.parametrize("plugin,profile,frac", PLUGINS)
def test_parity_sweep_all_signatures(plugin, profile, frac):
    """EVERY single and double erasure signature with a plan: the
    compiled program's output — numpy oracle AND device kernel — must
    equal the original shards byte-for-byte."""
    ec = factory(plugin, dict(profile))
    k, n = ec.get_data_chunk_count(), ec.get_chunk_count()
    sinfo, shards, _ = _object(ec)
    cs = sinfo.chunk_size
    planned = 0
    for r in (1, 2):
        for lost in itertools.combinations(range(n), r):
            avail = set(range(n)) - set(lost)
            plan = ecutil.repair_plan(ec, set(lost), avail)
            if r == 1:
                assert plan is not None, (plugin, lost)
            if plan is None:
                continue        # no partial plan: full-chunk fallback
            planned += 1
            assert set(plan.lost) == set(lost)
            bufs = _helper_bufs(plan, shards, cs)
            for backend in ("numpy", None):
                streams = ecutil.compiled_repair_streams(
                    ec, plan, cs, bufs, backend=backend)
                for s in lost:
                    assert streams[s] == shards[s], \
                        (plugin, lost, backend)
    assert planned >= n         # every single failure at minimum
    if plugin == "jerasure":
        # matrix codes plan every double signature too
        assert planned == n + n * (n - 1) // 2


@pytest.mark.parametrize("plugin,profile,frac", PLUGINS)
def test_single_failure_read_fraction(plugin, profile, frac):
    """The plan's helper-read volume is the code's advertised fraction
    of the k-full-chunk baseline (the recovery_bytes saving)."""
    ec = factory(plugin, dict(profile))
    k, n = ec.get_data_chunk_count(), ec.get_chunk_count()
    for lost in range(n):
        plan = ecutil.repair_plan(ec, {lost}, set(range(n)) - {lost})
        assert plan.read_fraction(k) == pytest.approx(frac), lost


def test_lrc_plan_stays_in_local_group():
    """A single lrc failure reads ONLY the lost shard's local parity
    group — l helpers, never the k survivors of a global decode."""
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    n = ec.get_chunk_count()
    for lost in range(n):
        plan = ecutil.repair_plan(ec, {lost}, set(range(n)) - {lost})
        group = ec.local_layer(lost).chunks_as_set
        assert lost in group
        assert set(plan.helper_ids()) == group - {lost}
        assert len(plan.helper_ids()) < ec.get_data_chunk_count()


def test_compile_once_per_signature():
    """The cache compiles each signature exactly once; repeats hit."""
    ec = factory("jerasure",
                 {"technique": "reed_sol_van", "k": "4", "m": "2"})
    n = ec.get_chunk_count()
    sinfo, shards, _ = _object(ec)
    cs = sinfo.chunk_size
    for _ in range(3):
        for lost in range(n):
            plan = ecutil.repair_plan(ec, {lost},
                                      set(range(n)) - {lost})
            bufs = _helper_bufs(plan, shards, cs)
            streams = ecutil.compiled_repair_streams(ec, plan, cs,
                                                     bufs)
            assert streams[lost] == shards[lost]
    stats = cache_of(ec).stats()
    assert len(stats["compiles"]) == n
    assert all(c == 1 for c in stats["compiles"].values()), stats
    assert stats["hits"] >= 2 * n


def test_cache_cost_weighted_eviction():
    """Programs evict LRU by matrix-byte cost; a re-request after
    eviction recompiles (compile count 2 is legitimate then)."""
    ec = factory("jerasure",
                 {"technique": "reed_sol_van", "k": "4", "m": "2"})
    n = ec.get_chunk_count()
    plans = [ecutil.repair_plan(ec, {i}, set(range(n)) - {i})
             for i in range(n)]
    one_cost = compile_program(ec, plans[0]).cost()
    cache = RepairProgramCache(capacity=2 * one_cost)
    for p in plans[:3]:
        cache.get_or_compile(ec, p)
    assert len(cache) == 2                      # plans[0] evicted
    assert cache.total_cost() <= 2 * one_cost
    # plans[1] is LRU-refreshed by a hit; inserting plans[3] must
    # evict plans[2], not it
    cache.get_or_compile(ec, plans[1])
    cache.get_or_compile(ec, plans[3])
    sigs = [p.signature() for p in plans]
    stats = cache.stats()
    assert stats["compiles"][sigs[1]] == 1      # still resident
    cache.get_or_compile(ec, plans[2])          # evicted: recompile
    stats = cache.stats()
    assert stats["compiles"][sigs[2]] == 2
    assert stats["compiles"][sigs[0]] == 1


def test_zero_probe_linearity_guard():
    """A plugin whose repair is affine (non-zero output for all-zero
    input) must be refused at compile time, not miscompiled."""
    class Affine:
        def decode(self, want, chunks, chunk_size):
            return {i: np.ones(chunk_size, dtype=np.uint8)
                    for i in want}
    plan = RepairPlan.make([0], {1: [(0, 1)], 2: [(0, 1)]},
                           sub_chunk_no=1)
    with pytest.raises(ErasureCodeError, match="not GF-linear"):
        compile_program(Affine(), plan)


def test_program_shape_and_signature():
    """Plan normalization + the program's gather/scatter algebra."""
    plan = RepairPlan.make([3, 1], {0: [(0, 2)], 2: [(1, 1)]},
                           sub_chunk_no=2)
    assert plan.lost == (1, 3)
    assert plan.signature() == "-1-3+0@0:2+2@1:1/2"
    assert plan.total_planes() == 3
    assert plan.output_planes() == 4
    assert plan.byte_extents(8) == {0: [(0, 8)], 2: [(4, 4)]}
    with pytest.raises(ValueError):
        plan.byte_extents(7)    # not sub-chunk aligned
    with pytest.raises(ValueError):
        RepairPlan.make([0], {0: [(0, 1)]}, 1)  # lost as own helper
    with pytest.raises(ValueError):
        RepairPlan.make([0], {1: [(0, 0)]}, 1)  # empty extent
    # identity program: rebuild = helper plane passthrough
    prog = RepairProgram(
        RepairPlan.make([0], {1: [(0, 1)]}, 1),
        np.eye(1, dtype=np.uint8))
    assert prog.run({1: b"abcd"}, 2, backend="numpy") == {0: b"abcd"}
    with pytest.raises(ValueError):
        prog.run({1: b"abc"}, 2, backend="numpy")   # misaligned


def test_clay_single_failure_vs_interpreted_reference():
    """Clay's compiled repair equals the interpreted repair-plane path
    (repair_shard_stream) as well as the original bytes — the two
    reference semantics agree with the compiled one."""
    ec = factory("clay", {"k": "4", "m": "2"})
    n = ec.get_chunk_count()
    sinfo, shards, _ = _object(ec)
    cs = sinfo.chunk_size
    for lost in range(n):
        plan = ecutil.repair_plan(ec, {lost}, set(range(n)) - {lost})
        bufs = _helper_bufs(plan, shards, cs)
        compiled = ecutil.compiled_repair_streams(ec, plan, cs, bufs)
        interp = ecutil.repair_shard_stream(ec, cs, lost, bufs)
        assert compiled[lost] == interp == shards[lost]


def test_lrc_locality_rule_maps_groups_to_fault_domains():
    """crush-locality lines local parity groups up with CRUSH fault
    domains: the generated rule picks one rack per group and spreads
    that group's chunks across hosts inside it — so a single-host loss
    repairs entirely within one rack (the l ≪ k read stays local)."""
    from ceph_tpu.crush.wrapper import CrushWrapper
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3",
                         "crush-locality": "rack",
                         "crush-failure-domain": "host"})
    n = ec.get_chunk_count()
    # 3 racks x 4 hosts x 1 osd; rack of osd.i is i // 4
    cw = CrushWrapper()
    cw.add_bucket("default", "root")
    for r in range(3):
        rack = f"rack{r}"
        cw.add_bucket(rack, "rack")
        for h in range(4):
            osd = r * 4 + h
            host = f"host{osd}"
            cw.add_bucket(host, "host")
            cw.insert_item(osd, 1.0, f"osd.{osd}", host)
            rb = cw.crush.bucket(cw.get_item_id(rack))
            hid = cw.get_item_id(host)
            rb.items.append(hid)
            w = cw.crush.bucket(hid).weight
            rb.item_weights.append(w)
            rb.weight += w
        root = cw.crush.bucket(cw.get_item_id("default"))
        rid_ = cw.get_item_id(rack)
        root.items.append(rid_)
        root.item_weights.append(cw.crush.bucket(rid_).weight)
        root.weight += cw.crush.bucket(rid_).weight
    rid = ec.create_rule("lrc_rule", cw)
    for x in range(8):
        osds = cw.do_rule(rid, x, n)
        assert len(osds) == n and len(set(osds)) == n
        assert all(o >= 0 for o in osds)
        # each local group's 4 chunks land in ONE rack, and the two
        # groups land in DIFFERENT racks
        racks = [{o // 4 for o in osds[g:g + 4]} for g in (0, 4)]
        assert all(len(r) == 1 for r in racks), (x, osds)
        assert racks[0] != racks[1], (x, osds)


def test_program_for_shares_per_instance_cache():
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    n = ec.get_chunk_count()
    plan = ecutil.repair_plan(ec, {0}, set(range(n)) - {0})
    assert program_for(ec, plan) is program_for(ec, plan)
    # a second plugin instance compiles its own program
    ec2 = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    assert program_for(ec2, plan) is not program_for(ec, plan)
