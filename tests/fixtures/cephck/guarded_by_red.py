"""RED: one accessor skips the lock every other access site takes.

The persist_log shape: _table is mutated under self._lock in every
writer and reader EXCEPT drain(), which clobbers it bare — the
guarded-by inference must flag exactly that minority access.
"""
from ceph_tpu.common.lockdep import make_lock


class PGMetaTable:
    def __init__(self):
        self._lock = make_lock("fixture.pgmeta")
        self._table = {}

    def put(self, k, v):
        with self._lock:
            self._table[k] = v

    def get(self, k):
        with self._lock:
            return self._table.get(k)

    def merge(self, other):
        with self._lock:
            self._table.update(other)
            return len(self._table)

    def snapshot(self):
        with self._lock:
            return dict(self._table)

    def size(self):
        with self._lock:
            return len(self._table)

    def drain(self):
        # BUG: no lock — races every locked accessor above
        out = dict(self._table)
        self._table = {}
        return out
