"""Journal library + rbd-mirror-lite (ref: src/journal/ Journaler/
ObjectRecorder/JournalTrimmer; src/tools/rbd_mirror/ + librbd
journaling — closing VERDICT r2 'journal lib: no')."""
import numpy as np
import pytest

from ceph_tpu.journal import Journaler
from ceph_tpu.rbd import RBD, Image
from ceph_tpu.rbd.mirror import ImageMirror
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("primary", pg_num=8)
    r.pool_create("backup", pg_num=8)
    yield c
    c.shutdown()


def test_journal_append_replay_commit_trim(cluster):
    io = cluster.rados().open_ioctx("primary")
    j = Journaler(io, "t1", "master", object_size=256)
    j.create()
    j.register_client()
    for i in range(20):
        j.append("ev", {"n": i, "blob": b"x" * 50})
    got = []
    pos = j.replay(lambda tag, d: got.append((tag, d["n"])))
    assert [n for _t, n in got] == list(range(20))
    j.commit(pos)
    # a second client replays independently from its own position
    j2 = Journaler(io, "t1", "peer", object_size=256)
    j2.register_client()
    got2 = []
    pos2 = j2.replay(lambda tag, d: got2.append(d["n"]))
    assert got2 == list(range(20))
    j2.commit(pos2)
    # trim removes whole objects all clients passed
    removed = j.trim()
    assert removed > 0
    # new entries continue after the trim
    j.append("ev", {"n": 99, "blob": b""})
    more = []
    j.replay(lambda tag, d: more.append(d["n"]), from_pos=pos)
    assert more == [99]
    assert set(j.clients()) == {"master", "peer"}


def test_journal_torn_tail(cluster):
    from ceph_tpu.journal import data_obj
    io = cluster.rados().open_ioctx("primary")
    j = Journaler(io, "torn", "master")
    j.create()
    j.register_client()
    j.append("ok", {"v": 1})
    # simulate a crash mid-append: garbage after the valid frame
    io.append(data_obj("torn", 0), b"\x00\x01\x02torn!")
    got = []
    j.replay(lambda t, d: got.append(d["v"]))
    assert got == [1]


def test_rbd_mirror_replicates_image(cluster):
    r = cluster.rados()
    src = r.open_ioctx("primary")
    dst = r.open_ioctx("backup")
    RBD().create(src, "vm", size=1 << 20, order=16, journaling=True)
    img = Image(src, "vm")
    rng = np.random.default_rng(6)
    b1 = rng.integers(0, 256, 70_000, dtype=np.uint8).tobytes()
    img.write(0, b1)
    img.write(3 << 16, b"tail-block" * 100)
    m = ImageMirror(src, dst, "vm")
    applied = m.sync()
    assert applied >= 2
    rep = Image(dst, "vm")
    assert rep.read(0, 70_000) == b1
    assert rep.read(3 << 16, 1000) == (b"tail-block" * 100)[:1000]
    rep.close()
    # incremental: new writes + discard + snapshot flow on next sync
    img.write(100, b"UPDATED")
    img.discard(3 << 16, 1 << 16)
    img.snap_create("s1")
    assert m.sync() >= 3
    rep = Image(dst, "vm")
    assert rep.read(100, 7) == b"UPDATED"
    assert rep.read(3 << 16, 100) == b"\0" * 100
    assert [s["name"] for s in rep.snap_list()] == ["s1"]
    rep.close()
    # nothing new -> no-op sync
    assert m.sync() == 0
    img.snap_remove("s1")
    assert m.sync() == 1
    img.close()

# ----------------------------- failover (VERDICT r3 #8) --------------

def test_mirror_failover_promote_demote_resync(cluster):
    """The full disaster story: primary dies with unreplicated writes,
    the secondary force-promotes and serves, the old primary comes
    back, demotes, is detected as split-brained, resyncs from the
    journal position, and replication continues — no acked-at-the-
    new-primary data lost."""
    from ceph_tpu.rbd.mirror import (ImageMirror, SplitBrainError,
                                     demote, mirror_enable,
                                     mirror_state, promote)
    r = cluster.rados()
    ioa = r.open_ioctx("primary")
    iob = r.open_ioctx("backup")
    name = "failover-vm"
    RBD().create(ioa, name, size=1 << 20, order=16, journaling=True)
    mirror_enable(ioa, name)
    a = Image(ioa, name)
    a.write(0, b"replicated-base " * 1000)
    m = ImageMirror(ioa, iob, name)
    m.sync()
    # the primary takes ONE more write nobody replicates, then "dies"
    a.write(1 << 17, b"DOOMED-UNREPLICATED" * 10)
    a.close()
    # disaster failover: force-promote the secondary
    promote(iob, name, force=True)
    b = Image(iob, name)
    b.write(1 << 18, b"written-on-new-primary" * 10)
    assert b.read(0, 16) == b"replicated-base "
    b.close()
    # the old primary returns and demotes; local writes now refuse
    demote(ioa, name)
    a = Image(ioa, name)
    with pytest.raises(Exception):
        a.write(0, b"nope")
    a.close()
    # reverse replication detects the split-brain
    m2 = ImageMirror(iob, ioa, name)
    with pytest.raises(SplitBrainError):
        m2.sync()
    # resync rebuilds the old primary from the current one
    copied = m2.resync()
    assert copied > 0
    a = Image(ioa, name)
    assert a.read(1 << 18, 22) == b"written-on-new-primary"
    assert a.read(0, 16) == b"replicated-base "
    # the divergent write is gone — that is what split-brain means
    assert a.read(1 << 17, 6) != b"DOOMED"
    a.close()
    # replication continues from the journal position
    b = Image(iob, name)
    b.write(0, b"post-resync-write")
    b.close()
    assert m2.sync() >= 1
    a = Image(ioa, name)
    assert a.read(0, 17) == b"post-resync-write"
    a.close()
    st = mirror_state(ioa, name)
    assert st is not None and not st["primary"]
    assert mirror_state(iob, name)["primary"]


def test_mirror_orderly_failback(cluster):
    """Clean handoff: demote the primary, drain the journal, promote
    the secondary WITHOUT force — chains extend, no split-brain on
    the reverse path."""
    from ceph_tpu.rbd.mirror import (ImageMirror, demote,
                                     mirror_enable, mirror_state,
                                     promote)
    r = cluster.rados()
    ioa = r.open_ioctx("primary")
    iob = r.open_ioctx("backup")
    name = "orderly-vm"
    RBD().create(ioa, name, size=1 << 19, order=16, journaling=True)
    mirror_enable(ioa, name)
    a = Image(ioa, name)
    a.write(0, b"generation-one")
    a.close()
    m = ImageMirror(ioa, iob, name)
    m.sync()
    # orderly: demote a, drain, promote b cleanly
    demote(ioa, name)
    m.sync()                                   # drain + adopt chain
    promote(iob, name, force=False)
    b = Image(iob, name)
    b.write(0, b"generation-two!")
    b.close()
    # reverse direction: no split-brain (the old primary drained)
    m2 = ImageMirror(iob, ioa, name)
    assert m2.sync() >= 1
    a = Image(ioa, name)
    assert a.read(0, 15) == b"generation-two!"
    a.close()
    assert not mirror_state(ioa, name)["primary"]
    assert mirror_state(iob, name)["primary"]

def test_clean_promote_requires_drained_demotion(cluster):
    """promote(force=False) refuses until a post-demotion sync drained
    the old primary — undrained writes must not be silently lost."""
    from ceph_tpu.rbd.image import RBDError
    from ceph_tpu.rbd.mirror import (ImageMirror, demote,
                                     mirror_enable, promote)
    r = cluster.rados()
    ioa = r.open_ioctx("primary")
    iob = r.open_ioctx("backup")
    name = "drain-vm"
    RBD().create(ioa, name, size=1 << 19, order=16, journaling=True)
    mirror_enable(ioa, name)
    a = Image(ioa, name)
    a.write(0, b"synced")
    a.close()
    m = ImageMirror(ioa, iob, name)
    m.sync()
    # demote WITHOUT draining the last write
    a = Image(ioa, name)
    a.write(100, b"undrained")
    a.close()
    demote(ioa, name)
    with pytest.raises(RBDError) as ei:
        promote(iob, name, force=False)
    assert "demoted/drained" in str(ei.value) or \
        ei.value.errno == 16
    # drain, then the clean promote succeeds
    m.sync()
    promote(iob, name, force=False)
    b = Image(iob, name)
    assert b.read(100, 9) == b"undrained"
    b.close()

def test_failover_abort_repromotes_drained_old_primary(cluster):
    """A demoted image whose own journal is fully consumed may cleanly
    re-promote (aborted handoff) — but NOT while undrained."""
    from ceph_tpu.rbd.image import RBDError
    from ceph_tpu.rbd.mirror import (ImageMirror, demote,
                                     mirror_enable, promote)
    r = cluster.rados()
    ioa = r.open_ioctx("primary")
    iob = r.open_ioctx("backup")
    name = "abort-vm"
    RBD().create(ioa, name, size=1 << 19, order=16, journaling=True)
    mirror_enable(ioa, name)
    a = Image(ioa, name)
    a.write(0, b"payload")
    a.close()
    m = ImageMirror(ioa, iob, name)
    m.sync()
    demote(ioa, name)
    # drained: the same image re-promotes without force
    promote(ioa, name, force=False)
    a = Image(ioa, name)
    a.write(32, b"more")       # primary again, writable
    a.close()
    # demote with an UNdrained tail: re-promote refused
    demote(ioa, name)
    with pytest.raises(RBDError):
        promote(ioa, name, force=False)
    m.sync()
    promote(ioa, name, force=False)


# -- error-contract regressions (errcheck audit fixes) ------------------

def test_head_pos_propagates_non_enoent(cluster, monkeypatch):
    """A non-ENOENT stat failure on the journal head must propagate:
    reading EIO as 'caught up, size 0' would let a replayer commit a
    position it never reached (the errno-conflation class)."""
    from ceph_tpu.client.rados import RadosError
    from ceph_tpu.rbd.mirror import _head_pos
    io = cluster.rados().open_ioctx("primary")
    j = Journaler(io, "headpos", "master")
    j.create()
    j.register_client()
    j.append("ev", {"v": 1})
    active, size = _head_pos(j)
    assert size > 0

    def eio_stat(oid):
        raise RadosError("EIO", f"injected for {oid}")
    monkeypatch.setattr(j.io, "stat", eio_stat)
    with pytest.raises(RadosError, match="EIO"):
        _head_pos(j)

    def enoent_stat(oid):
        raise RadosError("ENOENT", oid)
    monkeypatch.setattr(j.io, "stat", enoent_stat)
    # a true miss IS "empty head": size 0, no raise
    assert _head_pos(j) == (active, 0)


def test_load_meta_corrupt_header_is_eio_not_enoent(cluster):
    """A corrupt image header must surface as EIO, not ENOENT: callers
    that recreate on 'does not exist' would overwrite a live (damaged)
    image.  A genuinely missing image still maps to ENOENT."""
    from ceph_tpu.rbd.image import RBDError, header_name
    from ceph_tpu.rbd.mirror import _load_meta
    io = cluster.rados().open_ioctx("primary")
    RBD().create(io, "hdr-vm", size=1 << 18, order=16, journaling=True)
    assert _load_meta(io, "hdr-vm")["size"] == 1 << 18
    # scribble over the header: undecodable, but the image EXISTS
    io.write_full(header_name("hdr-vm"), b"\x00not json\xff")
    with pytest.raises(RBDError) as ei:
        _load_meta(io, "hdr-vm")
    assert ei.value.errno == 5
    assert "undecodable" in str(ei.value)
    # missing image keeps its distinct errno
    with pytest.raises(RBDError) as ei:
        _load_meta(io, "no-such-vm")
    assert ei.value.errno == 2
