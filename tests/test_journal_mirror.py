"""Journal library + rbd-mirror-lite (ref: src/journal/ Journaler/
ObjectRecorder/JournalTrimmer; src/tools/rbd_mirror/ + librbd
journaling — closing VERDICT r2 'journal lib: no')."""
import numpy as np
import pytest

from ceph_tpu.journal import Journaler
from ceph_tpu.rbd import RBD, Image
from ceph_tpu.rbd.mirror import ImageMirror
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("primary", pg_num=8)
    r.pool_create("backup", pg_num=8)
    yield c
    c.shutdown()


def test_journal_append_replay_commit_trim(cluster):
    io = cluster.rados().open_ioctx("primary")
    j = Journaler(io, "t1", "master", object_size=256)
    j.create()
    j.register_client()
    for i in range(20):
        j.append("ev", {"n": i, "blob": b"x" * 50})
    got = []
    pos = j.replay(lambda tag, d: got.append((tag, d["n"])))
    assert [n for _t, n in got] == list(range(20))
    j.commit(pos)
    # a second client replays independently from its own position
    j2 = Journaler(io, "t1", "peer", object_size=256)
    j2.register_client()
    got2 = []
    pos2 = j2.replay(lambda tag, d: got2.append(d["n"]))
    assert got2 == list(range(20))
    j2.commit(pos2)
    # trim removes whole objects all clients passed
    removed = j.trim()
    assert removed > 0
    # new entries continue after the trim
    j.append("ev", {"n": 99, "blob": b""})
    more = []
    j.replay(lambda tag, d: more.append(d["n"]), from_pos=pos)
    assert more == [99]
    assert set(j.clients()) == {"master", "peer"}


def test_journal_torn_tail(cluster):
    from ceph_tpu.journal import data_obj
    io = cluster.rados().open_ioctx("primary")
    j = Journaler(io, "torn", "master")
    j.create()
    j.register_client()
    j.append("ok", {"v": 1})
    # simulate a crash mid-append: garbage after the valid frame
    io.append(data_obj("torn", 0), b"\x00\x01\x02torn!")
    got = []
    j.replay(lambda t, d: got.append(d["v"]))
    assert got == [1]


def test_rbd_mirror_replicates_image(cluster):
    r = cluster.rados()
    src = r.open_ioctx("primary")
    dst = r.open_ioctx("backup")
    RBD().create(src, "vm", size=1 << 20, order=16, journaling=True)
    img = Image(src, "vm")
    rng = np.random.default_rng(6)
    b1 = rng.integers(0, 256, 70_000, dtype=np.uint8).tobytes()
    img.write(0, b1)
    img.write(3 << 16, b"tail-block" * 100)
    m = ImageMirror(src, dst, "vm")
    applied = m.sync()
    assert applied >= 2
    rep = Image(dst, "vm")
    assert rep.read(0, 70_000) == b1
    assert rep.read(3 << 16, 1000) == (b"tail-block" * 100)[:1000]
    rep.close()
    # incremental: new writes + discard + snapshot flow on next sync
    img.write(100, b"UPDATED")
    img.discard(3 << 16, 1 << 16)
    img.snap_create("s1")
    assert m.sync() >= 3
    rep = Image(dst, "vm")
    assert rep.read(100, 7) == b"UPDATED"
    assert rep.read(3 << 16, 100) == b"\0" * 100
    assert [s["name"] for s in rep.snap_list()] == ["s1"]
    rep.close()
    # nothing new -> no-op sync
    assert m.sync() == 0
    img.snap_remove("s1")
    assert m.sync() == 1
    img.close()
