"""clay plugin: coupled-layer MSR regenerating code.

Faithful re-implementation of the reference clay plugin
(ref: src/erasure-code/clay/ErasureCodeClay.{h,cc}).  A Clay code wraps
a scalar MDS code (the `mds` sub-plugin, (k+nu)+m) whose codewords are
"coupled" across q^t sub-chunk planes via a pairwise (2,2) transform
(the `pft` sub-plugin): chunks carry sub-chunks, and repairing a single
lost chunk reads only q^(t-1) sub-chunk ranges from d helpers instead
of whole chunks — the MSR repair-bandwidth optimality that motivates
the code.

Structure mirrors the reference exactly:
- parse (:190-302): q = d-k+1, nu padding so q | (k+m+nu), t=(k+m+nu)/q,
  sub_chunk_no = q^t; mds profile k=k+nu, pft profile (2,2);
- encode = decode_layered with the parity chunks as erasures (:131);
- decode_layered (:648): per-plane intersection-score ordering,
  uncoupled-domain MDS decode, then pairwise recouple;
- repair (:400): single-lost-chunk path reading only the repair planes
  (get_repair_subchunks :364).

Buffers are numpy arrays; sub-chunk views are numpy slices, so the
"bufferlist substr_of" aliasing of the C++ (transform writes land in
the parent chunk) holds naturally.
"""
from __future__ import annotations

from typing import Iterable, Mapping

import numpy as np

from ..interface import (ErasureCode, ErasureCodeError, ErasureCodeProfile,
                         sanity_check_k_m, to_int)
from ..registry import ErasureCodePlugin


def pow_int(a: int, x: int) -> int:
    return a ** x


class ErasureCodeClay(ErasureCode):
    DEFAULT_K = "4"
    DEFAULT_M = "2"
    DEFAULT_W = "8"

    def __init__(self) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.d = 0
        self.w = 8
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 0
        self.mds = None           # scalar MDS over (k+nu, m)
        self.pft = None           # pairwise transform code (2, 2)
        self.U_buf: dict[int, np.ndarray] = {}

    # -- interface ----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, object_size: int) -> int:
        # ref: ErasureCodeClay.cc:90-96
        alignment_scalar = self.pft.get_chunk_size(1)
        alignment = self.sub_chunk_no * self.k * alignment_scalar
        padded = (object_size + alignment - 1) // alignment * alignment
        return padded // self.k

    # -- init ---------------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        from ..registry import ErasureCodePluginRegistry
        self.parse(profile)
        super().init(profile)
        registry = ErasureCodePluginRegistry.instance()
        self.mds = registry.factory(self.mds_profile["plugin"],
                                    self.mds_profile)
        self.pft = registry.factory(self.pft_profile["plugin"],
                                    self.pft_profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        """ref: ErasureCodeClay.cc:190-302."""
        super().parse(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        sanity_check_k_m(self.k, self.m)
        self.d = to_int("d", profile, str(self.k + self.m - 1))
        scalar_mds = profile.get("scalar_mds") or "jerasure"
        if scalar_mds not in ("jerasure", "isa", "shec"):
            raise ErasureCodeError(
                f"scalar_mds {scalar_mds} is not currently supported, "
                "use one of 'jerasure', 'isa', 'shec'")
        technique = profile.get("technique") or ""
        if not technique:
            technique = "reed_sol_van" if scalar_mds in ("jerasure", "isa") \
                else "single"
        allowed = {
            "jerasure": ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig",
                         "cauchy_good", "liber8tion"),
            "isa": ("reed_sol_van", "cauchy"),
            "shec": ("single", "multiple"),
        }[scalar_mds]
        if technique not in allowed:
            raise ErasureCodeError(
                f"technique {technique} is not currently supported with "
                f"scalar_mds {scalar_mds}, use one of {allowed}")
        if self.d < self.k or self.d > self.k + self.m - 1:
            raise ErasureCodeError(
                f"value of d {self.d} must be within "
                f"[{self.k},{self.k + self.m - 1}]")
        self.q = self.d - self.k + 1
        self.nu = (self.q - (self.k + self.m) % self.q) \
            if (self.k + self.m) % self.q else 0
        if self.k + self.m + self.nu > 254:
            raise ErasureCodeError("k+m+nu must be <= 254")
        self.mds_profile = {"plugin": scalar_mds, "technique": technique,
                            "k": str(self.k + self.nu), "m": str(self.m),
                            "w": "8"}
        self.pft_profile = {"plugin": scalar_mds, "technique": technique,
                            "k": "2", "m": "2", "w": "8"}
        if scalar_mds == "shec":
            self.mds_profile["c"] = "2"
            self.pft_profile["c"] = "2"
        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = pow_int(self.q, self.t)

    # -- plane helpers ------------------------------------------------------
    def get_plane_vector(self, z: int) -> list[int]:
        """Base-q digits of z (ref: ErasureCodeClay.cc:886-892)."""
        z_vec = [0] * self.t
        for i in range(self.t):
            z_vec[self.t - 1 - i] = z % self.q
            z = (z - z_vec[self.t - 1 - i]) // self.q
        return z_vec

    def get_max_iscore(self, erased_chunks: set) -> int:
        weight_vec = [0] * self.t
        iscore = 0
        for i in erased_chunks:
            if weight_vec[i // self.q] == 0:
                weight_vec[i // self.q] = 1
                iscore += 1
        return iscore

    def set_planes_sequential_decoding_order(self, erasures: set
                                             ) -> list[int]:
        order = [0] * self.sub_chunk_no
        for z in range(self.sub_chunk_no):
            z_vec = self.get_plane_vector(z)
            for i in erasures:
                if i % self.q == z_vec[i // self.q]:
                    order[z] += 1
        return order

    def _ensure_U(self, size: int) -> None:
        for i in range(self.q * self.t):
            if i not in self.U_buf or self.U_buf[i].size != size:
                self.U_buf[i] = np.zeros(size, dtype=np.uint8)

    # -- repair predicates ---------------------------------------------------
    def is_repair(self, want_to_read: set, available_chunks: set) -> bool:
        """ref: ErasureCodeClay.cc:304-324."""
        if set(want_to_read) <= set(available_chunks):
            return False
        if len(want_to_read) > 1:
            return False
        i = next(iter(want_to_read))
        lost_node_id = i if i < self.k else i + self.nu
        for x in range(self.q):
            node = (lost_node_id // self.q) * self.q + x
            node = node if node < self.k else node - self.nu
            if node != i and node not in available_chunks:
                return False
        if len(available_chunks) < self.d:
            return False
        return True

    def get_repair_subchunks(self, lost_node: int
                             ) -> list[tuple[int, int]]:
        """ref: ErasureCodeClay.cc:364-378."""
        y_lost = lost_node // self.q
        x_lost = lost_node % self.q
        seq_sc_count = pow_int(self.q, self.t - 1 - y_lost)
        num_seq = pow_int(self.q, y_lost)
        out = []
        index = x_lost * seq_sc_count
        for _ in range(num_seq):
            out.append((index, seq_sc_count))
            index += self.q * seq_sc_count
        return out

    def get_repair_sub_chunk_count(self, want_to_read: set) -> int:
        """ref: ErasureCodeClay.cc:380-396."""
        weight_vector = [0] * self.t
        for to_read in want_to_read:
            weight_vector[to_read // self.q] += 1
        cnt = 1
        for y in range(self.t):
            cnt *= self.q - weight_vector[y]
        return self.sub_chunk_no - cnt

    # -- minimum_to_decode ---------------------------------------------------
    def minimum_to_decode(self, want_to_read: set, available: set
                          ) -> dict[int, list[tuple[int, int]]]:
        """ref: ErasureCodeClay.cc:98-106.  Extended past the
        reference: when `want_to_read` spans multiple shards but only
        ONE of them is erased, the lost shard still repairs from
        sub-chunk planes — the wanted survivors are whole-chunk reads
        and the erased one keeps the d-helper repair plan, instead of
        silently falling through to a k-full-chunk decode."""
        want_to_read = set(want_to_read)
        available = set(available)
        if self.is_repair(want_to_read, available):
            return self.minimum_to_repair(want_to_read, available)
        erased = want_to_read - available
        if len(erased) == 1 and self.is_repair(erased, available):
            minimum = self.minimum_to_repair(erased, available)
            for c in want_to_read & available:
                minimum[c] = [(0, self.sub_chunk_no)]
            return minimum
        return super().minimum_to_decode(want_to_read, available)

    def repair_schedule(self, erasures: set, available: set):
        """Single-erasure regenerating plan: d helpers each shipping
        the q^(t-1)-of-q^t repair planes of minimum_to_repair."""
        erasures = set(erasures)
        available = set(available) - erasures
        if not self.is_repair(erasures, available):
            return None
        from ...ec.repairc import RepairPlan
        minimum = self.minimum_to_repair(erasures, available)
        return RepairPlan.make(erasures, minimum,
                               sub_chunk_no=self.sub_chunk_no)

    def minimum_to_repair(self, want_to_read: set, available_chunks: set
                          ) -> dict[int, list[tuple[int, int]]]:
        """ref: ErasureCodeClay.cc:326-362."""
        i = next(iter(want_to_read))
        lost_node_index = i if i < self.k else i + self.nu
        minimum: dict[int, list[tuple[int, int]]] = {}
        sub_chunk_ind = self.get_repair_subchunks(lost_node_index)
        if len(available_chunks) < self.d:
            raise ErasureCodeError("minimum_to_repair: not enough chunks")
        for j in range(self.q):
            if j != lost_node_index % self.q:
                rep = (lost_node_index // self.q) * self.q + j
                if rep < self.k:
                    minimum[rep] = list(sub_chunk_ind)
                elif rep >= self.k + self.nu:
                    minimum[rep - self.nu] = list(sub_chunk_ind)
        for chunk in sorted(available_chunks):
            if len(minimum) >= self.d:
                break
            if chunk not in minimum:
                minimum[chunk] = list(sub_chunk_ind)
        assert len(minimum) == self.d
        return minimum

    # -- encode / decode -----------------------------------------------------
    def encode_chunks(self, want_to_encode: Iterable[int],
                      encoded: dict[int, np.ndarray]) -> None:
        """ref: ErasureCodeClay.cc:131-158."""
        k, m, nu = self.k, self.m, self.nu
        chunk_size = len(encoded[0])
        chunks: dict[int, np.ndarray] = {}
        parity_chunks = set()
        for i in range(k + m):
            if i < k:
                chunks[i] = encoded[i]
            else:
                chunks[i + nu] = encoded[i]
                parity_chunks.add(i + nu)
        for i in range(k, k + nu):
            chunks[i] = np.zeros(chunk_size, dtype=np.uint8)
        self.decode_layered(set(parity_chunks), chunks)

    def decode(self, want_to_read: Iterable[int],
               chunks: Mapping[int, np.ndarray], chunk_size: int = 0
               ) -> dict[int, np.ndarray]:
        """Repair path for single-chunk loss with partial (repair-plane)
        reads (ref: ErasureCodeClay.cc:108-126)."""
        want = set(want_to_read)
        chunks = {i: np.asarray(c, dtype=np.uint8)
                  for i, c in chunks.items()}
        avail = set(chunks)
        first_len = len(next(iter(chunks.values()))) if chunks else 0
        if self.is_repair(want, avail) and chunk_size > first_len:
            return self.repair(want, chunks, chunk_size)
        erased = want - avail
        if (chunk_size and len(erased) == 1 and len(want) > 1
                and self.is_repair(erased, avail)
                and all(len(chunks[i]) == chunk_size
                        for i in want & avail)):
            out = self._decode_one_erased(erased, chunks, chunk_size)
            if out is not None:
                out.update({i: chunks[i] for i in want & avail})
                return {i: out[i] for i in want}
        return self._decode(want, chunks)

    def _decode_one_erased(self, erased: set,
                           chunks: Mapping[int, np.ndarray],
                           chunk_size: int):
        """Companion to the extended minimum_to_decode: rebuild the one
        erased chunk from its d helpers' repair planes.  Helpers read
        whole (because they were also wanted) are sliced down to their
        repair planes; helpers that shipped only planes pass through.
        None when buffers fit neither shape (caller falls back)."""
        lost = next(iter(erased))
        lost_node = lost if lost < self.k else lost + self.nu
        ssz = chunk_size // self.sub_chunk_no
        ext = [(o * ssz, c * ssz)
               for o, c in self.get_repair_subchunks(lost_node)]
        rb = sum(length for _, length in ext)
        helpers = {}
        for h in self.minimum_to_repair(erased, set(chunks)):
            buf = chunks[h]
            if len(buf) == chunk_size:
                helpers[h] = np.concatenate(
                    [buf[o:o + length] for o, length in ext])
            elif len(buf) == rb:
                helpers[h] = buf
            else:
                return None
        return self.repair(erased, helpers, chunk_size)

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        """ref: ErasureCodeClay.cc:160-188."""
        k, m, nu = self.k, self.m, self.nu
        erasures = set()
        coded_chunks: dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i not in chunks:
                erasures.add(i if i < k else i + nu)
            coded_chunks[i if i < k else i + nu] = decoded[i]
        chunk_size = len(coded_chunks[0])
        for i in range(k, k + nu):
            coded_chunks[i] = np.zeros(chunk_size, dtype=np.uint8)
        self.decode_layered(erasures, coded_chunks)

    # -- layered decode core -------------------------------------------------
    def decode_layered(self, erased_chunks: set,
                       chunks: dict[int, np.ndarray]) -> None:
        """ref: ErasureCodeClay.cc:648-711."""
        q, t, m = self.q, self.t, self.m
        num_erasures = len(erased_chunks)
        size = len(chunks[0])
        assert size % self.sub_chunk_no == 0
        sc_size = size // self.sub_chunk_no
        assert num_erasures > 0
        i = self.k + self.nu
        while num_erasures < m and i < q * t:
            if i not in erased_chunks:
                erased_chunks.add(i)
                num_erasures += 1
            i += 1
        assert num_erasures == m
        max_iscore = self.get_max_iscore(erased_chunks)
        self._ensure_U(size)
        order = self.set_planes_sequential_decoding_order(erased_chunks)
        for iscore in range(max_iscore + 1):
            for z in range(self.sub_chunk_no):
                if order[z] == iscore:
                    self.decode_erasures(erased_chunks, z, chunks, sc_size)
            for z in range(self.sub_chunk_no):
                if order[z] != iscore:
                    continue
                z_vec = self.get_plane_vector(z)
                for node_xy in sorted(erased_chunks):
                    x = node_xy % q
                    y = node_xy // q
                    node_sw = y * q + z_vec[y]
                    if z_vec[y] != x:
                        if node_sw not in erased_chunks:
                            self.recover_type1_erasure(
                                chunks, x, y, z, z_vec, sc_size)
                        elif z_vec[y] < x:
                            self.get_coupled_from_uncoupled(
                                chunks, x, y, z, z_vec, sc_size)
                    else:
                        chunks[node_xy][z * sc_size:(z + 1) * sc_size] = \
                            self.U_buf[node_xy][z * sc_size:(z + 1) * sc_size]

    def decode_erasures(self, erased_chunks: set, z: int,
                        chunks: dict[int, np.ndarray], sc_size: int) -> None:
        """ref: ErasureCodeClay.cc:713-739."""
        q, t = self.q, self.t
        z_vec = self.get_plane_vector(z)
        for x in range(q):
            for y in range(t):
                node_xy = q * y + x
                node_sw = q * y + z_vec[y]
                if node_xy in erased_chunks:
                    continue
                if z_vec[y] < x:
                    self.get_uncoupled_from_coupled(
                        chunks, x, y, z, z_vec, sc_size)
                elif z_vec[y] == x:
                    self.U_buf[node_xy][z * sc_size:(z + 1) * sc_size] = \
                        chunks[node_xy][z * sc_size:(z + 1) * sc_size]
                else:
                    if node_sw in erased_chunks:
                        self.get_uncoupled_from_coupled(
                            chunks, x, y, z, z_vec, sc_size)
        self.decode_uncoupled(erased_chunks, z, sc_size)

    def decode_uncoupled(self, erased_chunks: set, z: int,
                         sc_size: int) -> None:
        """MDS decode in the uncoupled domain
        (ref: ErasureCodeClay.cc:741-758)."""
        known = {}
        all_sub = {}
        for i in range(self.q * self.t):
            view = self.U_buf[i][z * sc_size:(z + 1) * sc_size]
            all_sub[i] = view
            if i not in erased_chunks:
                known[i] = view
        self.mds.decode_chunks(erased_chunks, known, all_sub)

    def recover_type1_erasure(self, chunks, x, y, z, z_vec,
                              sc_size) -> None:
        """ref: ErasureCodeClay.cc:773-807."""
        q, t = self.q, self.t
        node_xy = y * q + x
        node_sw = y * q + z_vec[y]
        z_sw = z + (x - z_vec[y]) * pow_int(q, t - 1 - y)
        i0, i1, i2, i3 = (0, 1, 2, 3) if z_vec[y] < x else (1, 0, 3, 2)
        scratch = np.zeros(sc_size, dtype=np.uint8)
        pft_sub = {
            i0: chunks[node_xy][z * sc_size:(z + 1) * sc_size],
            i1: chunks[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size],
            i2: self.U_buf[node_xy][z * sc_size:(z + 1) * sc_size],
            i3: scratch,
        }
        known = {i1: pft_sub[i1], i2: pft_sub[i2]}
        self.pft.decode_chunks({i0}, known, pft_sub)

    def get_coupled_from_uncoupled(self, chunks, x, y, z, z_vec,
                                   sc_size) -> None:
        """ref: ErasureCodeClay.cc:809-833."""
        q, t = self.q, self.t
        node_xy = y * q + x
        node_sw = y * q + z_vec[y]
        z_sw = z + (x - z_vec[y]) * pow_int(q, t - 1 - y)
        assert z_vec[y] < x
        uncoupled = {
            2: self.U_buf[node_xy][z * sc_size:(z + 1) * sc_size],
            3: self.U_buf[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size],
        }
        pft_sub = {
            0: chunks[node_xy][z * sc_size:(z + 1) * sc_size],
            1: chunks[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size],
            2: uncoupled[2],
            3: uncoupled[3],
        }
        self.pft.decode_chunks({0, 1}, uncoupled, pft_sub)

    def get_uncoupled_from_coupled(self, chunks, x, y, z, z_vec,
                                   sc_size) -> None:
        """ref: ErasureCodeClay.cc:835-865."""
        q, t = self.q, self.t
        node_xy = y * q + x
        node_sw = y * q + z_vec[y]
        z_sw = z + (x - z_vec[y]) * pow_int(q, t - 1 - y)
        i0, i1, i2, i3 = (0, 1, 2, 3) if z_vec[y] < x else (1, 0, 3, 2)
        coupled = {
            i0: chunks[node_xy][z * sc_size:(z + 1) * sc_size],
            i1: chunks[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size],
        }
        pft_sub = {
            0: coupled[0],
            1: coupled[1],
            i2: self.U_buf[node_xy][z * sc_size:(z + 1) * sc_size],
            i3: self.U_buf[node_sw][z_sw * sc_size:(z_sw + 1) * sc_size],
        }
        self.pft.decode_chunks({2, 3}, coupled, pft_sub)

    # -- single-chunk repair -------------------------------------------------
    def repair(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
               chunk_size: int) -> dict[int, np.ndarray]:
        """ref: ErasureCodeClay.cc:400-460."""
        assert len(want_to_read) == 1 and len(chunks) == self.d
        k, m, nu = self.k, self.m, self.nu
        # note: the reference passes the ORIGINAL chunk ids here (no nu
        # shift), ErasureCodeClay.cc:405
        repair_sub_chunk_no = self.get_repair_sub_chunk_count(
            set(want_to_read))
        repair_blocksize = len(next(iter(chunks.values())))
        assert repair_blocksize % repair_sub_chunk_no == 0
        sub_chunksize = repair_blocksize // repair_sub_chunk_no
        chunksize = self.sub_chunk_no * sub_chunksize
        assert chunksize == chunk_size

        recovered_data: dict[int, np.ndarray] = {}
        helper_data: dict[int, np.ndarray] = {}
        aloof_nodes: set = set()
        repaired: dict[int, np.ndarray] = {}
        repair_sub_chunks_ind: list[tuple[int, int]] = []
        lost = next(iter(want_to_read))
        for i in range(k + m):
            if i in chunks:
                helper_data[i if i < k else i + nu] = chunks[i]
            elif i != lost:
                aloof_nodes.add(i if i < k else i + nu)
            else:
                lost_node_id = i if i < k else i + nu
                repaired[i] = np.zeros(chunksize, dtype=np.uint8)
                recovered_data[lost_node_id] = repaired[i]
                repair_sub_chunks_ind = self.get_repair_subchunks(
                    lost_node_id)
        for i in range(k, k + nu):
            helper_data[i] = np.zeros(repair_blocksize, dtype=np.uint8)
        assert len(helper_data) + len(aloof_nodes) + len(recovered_data) \
            == self.q * self.t
        self.repair_one_lost_chunk(recovered_data, aloof_nodes,
                                   helper_data, repair_blocksize,
                                   repair_sub_chunks_ind)
        return repaired

    def repair_one_lost_chunk(self, recovered_data, aloof_nodes,
                              helper_data, repair_blocksize,
                              repair_sub_chunks_ind) -> None:
        """ref: ErasureCodeClay.cc:462-645."""
        q, t = self.q, self.t
        repair_subchunks = self.sub_chunk_no // q
        sub_chunksize = repair_blocksize // repair_subchunks

        ordered_planes: dict[int, list[int]] = {}
        repair_plane_to_ind: dict[int, int] = {}
        plane_ind = 0
        for index, count in repair_sub_chunks_ind:
            for j in range(index, index + count):
                z_vec = self.get_plane_vector(j)
                order = 0
                for node in recovered_data:
                    if node % q == z_vec[node // q]:
                        order += 1
                for node in aloof_nodes:
                    if node % q == z_vec[node // q]:
                        order += 1
                assert order > 0
                ordered_planes.setdefault(order, []).append(j)
                repair_plane_to_ind[j] = plane_ind
                plane_ind += 1
        assert plane_ind == repair_subchunks

        self._ensure_U(self.sub_chunk_no * sub_chunksize)
        temp_buf = np.zeros(sub_chunksize, dtype=np.uint8)

        assert len(recovered_data) == 1
        lost_chunk = next(iter(recovered_data))
        erasures = {lost_chunk - lost_chunk % q + i for i in range(q)}
        erasures |= aloof_nodes

        order = 1
        while order in ordered_planes:
            for z in sorted(ordered_planes[order]):
                z_vec = self.get_plane_vector(z)
                # fill U for all non-erased nodes at plane z
                for y in range(t):
                    for x in range(q):
                        node_xy = y * q + x
                        if node_xy in erasures:
                            continue
                        z_sw = z + (x - z_vec[y]) * pow_int(q, t - 1 - y)
                        node_sw = y * q + z_vec[y]
                        i0, i1, i2, i3 = (0, 1, 2, 3) if z_vec[y] <= x \
                            else (1, 0, 3, 2)
                        U_xy = self.U_buf[node_xy]
                        if node_sw in aloof_nodes:
                            known = {
                                i0: helper_data[node_xy][
                                    repair_plane_to_ind[z] * sub_chunksize:
                                    (repair_plane_to_ind[z] + 1)
                                    * sub_chunksize],
                                i3: self.U_buf[node_sw][
                                    z_sw * sub_chunksize:
                                    (z_sw + 1) * sub_chunksize],
                            }
                            pft_sub = {
                                i0: known[i0], i1: temp_buf,
                                i2: U_xy[z * sub_chunksize:
                                         (z + 1) * sub_chunksize],
                                i3: known[i3],
                            }
                            self.pft.decode_chunks({i2}, known, pft_sub)
                        elif z_vec[y] != x:
                            known = {
                                i0: helper_data[node_xy][
                                    repair_plane_to_ind[z] * sub_chunksize:
                                    (repair_plane_to_ind[z] + 1)
                                    * sub_chunksize],
                                i1: helper_data[node_sw][
                                    repair_plane_to_ind[z_sw]
                                    * sub_chunksize:
                                    (repair_plane_to_ind[z_sw] + 1)
                                    * sub_chunksize],
                            }
                            pft_sub = {
                                i0: known[i0], i1: known[i1],
                                i2: U_xy[z * sub_chunksize:
                                         (z + 1) * sub_chunksize],
                                i3: temp_buf[:sub_chunksize],
                            }
                            self.pft.decode_chunks({i2}, known, pft_sub)
                        else:
                            U_xy[z * sub_chunksize:(z + 1) * sub_chunksize] \
                                = helper_data[node_xy][
                                    repair_plane_to_ind[z] * sub_chunksize:
                                    (repair_plane_to_ind[z] + 1)
                                    * sub_chunksize]
                assert len(erasures) <= self.m
                self.decode_uncoupled(erasures, z, sub_chunksize)
                for i in sorted(erasures):
                    x = i % q
                    y = i // q
                    node_sw = y * q + z_vec[y]
                    z_sw = z + (x - z_vec[y]) * pow_int(q, t - 1 - y)
                    i0, i1, i2, i3 = (0, 1, 2, 3) if z_vec[y] <= x \
                        else (1, 0, 3, 2)
                    if i in aloof_nodes:
                        continue
                    if x == z_vec[y]:  # hole-dot pair (type 0)
                        recovered_data[i][
                            z * sub_chunksize:(z + 1) * sub_chunksize] = \
                            self.U_buf[i][z * sub_chunksize:
                                          (z + 1) * sub_chunksize]
                    else:
                        assert y == lost_chunk // q
                        assert node_sw == lost_chunk
                        known = {
                            i0: helper_data[i][
                                repair_plane_to_ind[z] * sub_chunksize:
                                (repair_plane_to_ind[z] + 1)
                                * sub_chunksize],
                            i2: self.U_buf[i][z * sub_chunksize:
                                              (z + 1) * sub_chunksize],
                        }
                        pft_sub = {
                            i0: known[i0],
                            i1: recovered_data[node_sw][
                                z_sw * sub_chunksize:
                                (z_sw + 1) * sub_chunksize],
                            i2: known[i2],
                            i3: temp_buf,
                        }
                        self.pft.decode_chunks({i1}, known, pft_sub)
            order += 1


PLUGIN = ErasureCodePlugin("clay", ErasureCodeClay)
