"""Versioned wire encoding — the denc/bufferlist analogue.

The reference pins every wire struct with
`ENCODE_START(version, compat, bl)` / `DECODE_START` (ref:
src/include/encoding.h:1 the macro family; src/include/denc.h:51) and
frames messages with a preamble + length-delimited segments + crc32c
epilogues (ref: src/msg/async/frames_v2.h:58-151).  This module is the
TPU framework's equivalent:

* a **TLV value codec** over a closed primitive domain (None/bool/int/
  float/str/bytes/list/tuple/set/dict/ndarray) — decoding can only ever
  construct these types, so network input is data, never code (the
  property `pickle.loads` lacked);
* a **struct registry**: dataclasses (or adapter-wrapped classes)
  register under a stable wire name with `(version, compat)`.  Structs
  encode as `name | u8 v | u8 compat | u32 len | fields...`; a decoder
  that only understands `v' < compat` must reject, while `v > known`
  decodes the known prefix and skips the tail via `len` — exactly the
  ENCODE_START evolution contract, so fields can be appended in later
  versions without flag days;
* **message framing**: magic + flags + length preamble, one payload
  segment, crc32c epilogue (frames_v2 reduced to one segment since we
  don't split front/middle/data).

`tests/fixtures/wire_corpus.json` pins encodings across rounds the way
ceph-object-corpus + ceph-dencoder pin the reference's
(ref: src/tools/ceph-dencoder, qa .../encode-decode-non-regression.sh).
"""
from __future__ import annotations

import dataclasses
import struct as _struct
from typing import Any, Callable

import numpy as _np

from ..common.crc32c import crc32c

# ---------------------------------------------------------------- tags

T_NONE = 0
T_TRUE = 1
T_FALSE = 2
T_INT = 3          # zigzag LEB128, arbitrary precision
T_FLOAT = 4        # IEEE754 double, big-endian
T_STR = 5          # LEB128 length + utf-8
T_BYTES = 6        # LEB128 length + raw
T_LIST = 7         # LEB128 count + values
T_TUPLE = 8
T_SET = 9
T_FROZENSET = 10
T_DICT = 11        # LEB128 count + (key, value) pairs
T_NDARRAY = 12     # dtype str, ndim, shape..., raw C-order bytes
T_STRUCT = 13      # name + ENCODE_START(v, compat, len) + field values

#: recursion guard — real payloads are shallow; a hostile frame must
#: not be able to blow the stack
MAX_DEPTH = 64

_U32 = _struct.Struct("!I")
_F64 = _struct.Struct("!d")


class WireError(ValueError):
    """Malformed, incompatible, or unregistered wire data."""


# ------------------------------------------------------------- varints

def _uvarint(n: int, out: bytearray) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _zigzag(n: int) -> int:
    # arbitrary-precision zigzag (bignums survive the wire)
    return (n << 1) if n >= 0 else ((-n << 1) - 1)


class _Reader:
    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf, pos: int = 0, end: int | None = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def take(self, n: int) -> memoryview:
        if n < 0 or self.pos + n > self.end:
            raise WireError("truncated wire data")
        v = memoryview(self.buf)[self.pos:self.pos + n]
        self.pos += n
        return v

    def u8(self) -> int:
        return self.take(1)[0]

    def uvarint(self) -> int:
        shift = n = 0
        while True:
            b = self.u8()
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7
            if shift > 80:          # bignum guard for lengths/counts
                raise WireError("varint too long")


# ------------------------------------------------------------ registry

@dataclasses.dataclass
class _StructInfo:
    name: str
    cls: type
    version: int
    compat: int
    to_fields: Callable[[Any], list]
    from_fields: Callable[[list], Any]
    #: ordered (field name, declared type or None) pairs when the
    #: registration exposes them (dataclass / fields=...); None for
    #: opaque to_fields/from_fields codecs.  The wire schema lockfile
    #: (scripts/gen_wire_schema.py) and cephck's wire-drift rule pin
    #: these the way ceph-object-corpus pins encodings.
    field_schema: tuple | None = None


_by_name: dict[str, _StructInfo] = {}
_by_cls: dict[type, _StructInfo] = {}


def register_struct(cls: type, name: str | None = None,
                    version: int = 1, compat: int = 1,
                    to_fields: Callable | None = None,
                    from_fields: Callable | None = None,
                    fields: tuple | None = None) -> type:
    """Register a wire struct.  Dataclasses get automatic positional
    field lists (append-only evolution: bump `version` when adding
    fields, keep `compat` at the oldest decoder that still works —
    ref: encoding.h ENCODE_START semantics).  Non-dataclass types can
    pass `fields=(attr, ...)`: values are read with getattr and
    restored with setattr onto a no-arg-constructed instance (missing
    trailing fields keep the constructor's defaults)."""
    name = name or cls.__name__
    field_schema: tuple | None = None
    if to_fields is None and fields is not None:
        field_schema = tuple((n, None, False) for n in fields)

        def to_fields(obj, _flds=fields):
            return [getattr(obj, n) for n in _flds]

        def from_fields(vals, _cls=cls, _flds=fields):
            obj = _cls()
            for n, v in zip(_flds, vals):
                setattr(obj, n, v)
            return obj

    if to_fields is None:
        if not dataclasses.is_dataclass(cls):
            raise TypeError(f"{cls} needs explicit to_fields/from_fields")
        dcf = [f for f in dataclasses.fields(cls) if f.init]
        flds = [f.name for f in dcf]
        # a field declared by ANY base keeps the base's wire position
        # even when a subclass redeclares it (dataclass field-order
        # rule) — mark those inherited so the static wire-drift check
        # knows not to expect them at their class-body position
        base_ann: set = set()
        for b in cls.__mro__[1:]:
            base_ann.update(vars(b).get("__annotations__", {}))
        field_schema = tuple(
            (f.name, f.type if isinstance(f.type, str)
             else getattr(f.type, "__name__", repr(f.type)),
             f.name in base_ann)
            for f in dcf)

        def to_fields(obj, _flds=flds):
            return [getattr(obj, n) for n in _flds]

        def from_fields(vals, _cls=cls, _flds=flds):
            return _cls(**dict(zip(_flds, vals)))

    info = _StructInfo(name, cls, version, compat, to_fields, from_fields,
                       field_schema)
    if name in _by_name and _by_name[name].cls is not cls:
        raise ValueError(f"wire name {name!r} already registered")
    _by_name[name] = info
    _by_cls[cls] = info
    return cls


def wire_struct(name: str | None = None, version: int = 1,
                compat: int = 1):
    """Decorator form of register_struct for dataclasses."""
    def deco(cls):
        return register_struct(cls, name, version, compat)
    return deco


def registered_types() -> dict[str, type]:
    return {n: i.cls for n, i in sorted(_by_name.items())}


def registered_schema() -> dict[str, dict]:
    """Wire schema of every registered struct — name, (version,
    compat), and the ordered field list where the registration exposes
    one.  scripts/gen_wire_schema.py serializes this to the committed
    lockfile; cephck's wire-drift rule and tests/test_wire_schema.py
    compare against it."""
    out: dict[str, dict] = {}
    for n, i in sorted(_by_name.items()):
        out[n] = {
            "version": i.version,
            "compat": i.compat,
            "fields": None if i.field_schema is None else
            [{"name": fn, "type": ft, "inherited": inh}
             for fn, ft, inh in i.field_schema],
        }
    return out


def ensure_registered() -> None:
    """Import every module that registers wire structs (idempotent).
    Decoders that touch PERSISTED data (LogDB WAL replay, dencoder)
    call this first so decoding never depends on what the caller
    happened to import — a BlueStore mount must be able to replay a
    WAL containing EVersion/PG/... structs in a bare process."""
    from ..crush import types as _ct          # noqa: F401
    from ..crush import wrapper as _cw        # noqa: F401
    from ..mon import fsmap as _fm            # noqa: F401
    from ..osd import osdmap as _om           # noqa: F401
    from ..osd import pg_types as _pt         # noqa: F401
    from ..osd import types as _ot            # noqa: F401
    from ..store import memstore as _ms       # noqa: F401
    from ..store import objectstore as _os    # noqa: F401
    from . import messages as _mm             # noqa: F401


# -------------------------------------------------------------- encode

def _encode_value(obj: Any, out: bytearray, depth: int) -> None:
    if depth > MAX_DEPTH:
        raise WireError("structure too deep")
    if obj is None:
        out.append(T_NONE)
    elif obj is True:
        out.append(T_TRUE)
    elif obj is False:
        out.append(T_FALSE)
    elif isinstance(obj, int) and not isinstance(obj, bool):
        out.append(T_INT)
        _uvarint(_zigzag(obj), out)
    elif isinstance(obj, float):
        out.append(T_FLOAT)
        out += _F64.pack(obj)
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(T_STR)
        _uvarint(len(b), out)
        out += b
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(T_BYTES)
        _uvarint(len(b), out)
        out += b
    elif isinstance(obj, _np.ndarray):
        if obj.dtype.hasobject:
            raise WireError("object-dtype ndarray is not wire-safe")
        arr = _np.ascontiguousarray(obj)
        dt = arr.dtype.str.encode()
        out.append(T_NDARRAY)
        _uvarint(len(dt), out)
        out += dt
        _uvarint(arr.ndim, out)
        for d in arr.shape:
            _uvarint(d, out)
        raw = arr.tobytes()
        _uvarint(len(raw), out)
        out += raw
    elif isinstance(obj, (_np.integer,)):
        out.append(T_INT)
        _uvarint(_zigzag(int(obj)), out)
    elif isinstance(obj, (_np.floating,)):
        out.append(T_FLOAT)
        out += _F64.pack(float(obj))
    elif type(obj) in (list, tuple, set, frozenset):
        out.append({list: T_LIST, tuple: T_TUPLE, set: T_SET,
                    frozenset: T_FROZENSET}[type(obj)])
        items = sorted(obj, key=repr) if isinstance(obj, (set, frozenset)) \
            else obj
        _uvarint(len(items), out)
        for v in items:
            _encode_value(v, out, depth + 1)
    elif type(obj) is dict:
        out.append(T_DICT)
        _uvarint(len(obj), out)
        for k, v in obj.items():
            _encode_value(k, out, depth + 1)
            _encode_value(v, out, depth + 1)
    else:
        info = _by_cls.get(type(obj))
        if info is None:
            raise WireError(
                f"type {type(obj).__module__}.{type(obj).__name__} is "
                "not wire-registered (register_struct/wire_struct)")
        _encode_struct(info, obj, out, depth)


def _encode_struct(info: _StructInfo, obj: Any, out: bytearray,
                   depth: int) -> None:
    nb = info.name.encode()
    out.append(T_STRUCT)
    _uvarint(len(nb), out)
    out += nb
    # ENCODE_START(v, compat, bl) (ref: encoding.h)
    out.append(info.version)
    out.append(info.compat)
    body = bytearray()
    fields = info.to_fields(obj)
    _uvarint(len(fields), body)
    for v in fields:
        _encode_value(v, body, depth + 1)
    out += _U32.pack(len(body))
    out += body


def encode(obj: Any) -> bytes:
    """Encode one value (any TLV primitive or registered struct)."""
    out = bytearray()
    _encode_value(obj, out, 0)
    return bytes(out)


# -------------------------------------------------------------- decode

def _decode_value(r: _Reader, depth: int) -> Any:
    if depth > MAX_DEPTH:
        raise WireError("structure too deep")
    tag = r.u8()
    if tag == T_NONE:
        return None
    if tag == T_TRUE:
        return True
    if tag == T_FALSE:
        return False
    if tag == T_INT:
        return _dec_int(r)
    if tag == T_FLOAT:
        return _F64.unpack(r.take(8))[0]
    if tag == T_STR:
        return bytes(r.take(r.uvarint())).decode()
    if tag == T_BYTES:
        return bytes(r.take(r.uvarint()))
    if tag == T_NDARRAY:
        dt = bytes(r.take(r.uvarint())).decode()
        ndim = r.uvarint()
        if ndim > 32:
            raise WireError("ndarray rank too large")
        shape = tuple(r.uvarint() for _ in range(ndim))
        raw = r.take(r.uvarint())
        try:
            dtype = _np.dtype(dt)
        except TypeError as ex:
            raise WireError(f"bad dtype {dt!r}") from ex
        if dtype.hasobject:
            raise WireError("object-dtype ndarray is not wire-safe")
        arr = _np.frombuffer(raw, dtype=dtype)
        try:
            return arr.reshape(shape).copy()
        except ValueError as ex:
            raise WireError(str(ex)) from ex
    if tag in (T_LIST, T_TUPLE, T_SET, T_FROZENSET):
        n = r.uvarint()
        vals = [_decode_value(r, depth + 1) for _ in range(n)]
        return {T_LIST: list, T_TUPLE: tuple, T_SET: set,
                T_FROZENSET: frozenset}[tag](vals)
    if tag == T_DICT:
        n = r.uvarint()
        out = {}
        for _ in range(n):
            k = _decode_value(r, depth + 1)
            out[k] = _decode_value(r, depth + 1)
        return out
    if tag == T_STRUCT:
        return _decode_struct(r, depth)
    raise WireError(f"unknown wire tag {tag}")


def _dec_int(r: _Reader) -> int:
    # arbitrary-precision LEB128 zigzag (mirror of _svarint/_zigzag)
    shift = n = 0
    while True:
        b = r.u8()
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 4096:
            raise WireError("int too long")
    return (n >> 1) if not n & 1 else -((n + 1) >> 1)


def _decode_struct(r: _Reader, depth: int) -> Any:
    name = bytes(r.take(r.uvarint())).decode()
    v = r.u8()
    compat = r.u8()
    (length,) = _U32.unpack(r.take(4))
    body = _Reader(r.buf, r.pos, r.pos + length)
    if body.end > r.end:
        raise WireError("struct overruns frame")
    r.pos += length
    info = _by_name.get(name)
    if info is None:
        raise WireError(f"unknown wire struct {name!r}")
    # DECODE_START compat contract (ref: encoding.h): a struct whose
    # compat is newer than the version we implement cannot be decoded
    if compat > info.version:
        raise WireError(
            f"{name} wire v{v} requires decoder >= v{compat}, "
            f"we implement v{info.version}")
    n = body.uvarint()
    vals = [_decode_value(body, depth + 1) for _ in range(n)]
    # v > ours: trailing fields already skipped via `length`;
    # v < ours: missing fields fall back to dataclass defaults
    try:
        return info.from_fields(vals)
    except TypeError as ex:
        raise WireError(f"{name}: {ex}") from ex


def decode(data) -> Any:
    r = _Reader(data)
    val = _decode_value(r, 0)
    if r.pos != r.end:
        raise WireError(f"{r.end - r.pos} trailing bytes")
    return val


# ----------------------------------------------------- message framing

#: frame magic (the banner/preamble marker; ref: frames_v2.h preamble)
MAGIC = b"CTv2"
FLAG_NONE = 0

_PREAMBLE = _struct.Struct("!4sBI")     # magic, flags, payload len


def encode_message(msg: Any) -> bytes:
    """Frame one message: preamble + struct payload + crc32c epilogue
    (ref: frames_v2.h:58-151, reduced to a single segment)."""
    info = _by_cls.get(type(msg))
    if info is None:
        raise WireError(f"message type {type(msg).__name__} is not "
                        "wire-registered")
    payload = bytearray()
    _encode_struct(info, msg, payload, 0)
    crc = crc32c(0, bytes(payload))
    return _PREAMBLE.pack(MAGIC, FLAG_NONE, len(payload)) + \
        bytes(payload) + _U32.pack(crc)


def decode_message(frame) -> Any:
    r = _Reader(frame)
    magic, _flags, n = _PREAMBLE.unpack(r.take(_PREAMBLE.size))
    if magic != MAGIC:
        raise WireError("bad frame magic")
    payload = r.take(n)
    (crc,) = _U32.unpack(r.take(4))
    if r.pos != r.end:
        raise WireError("trailing bytes after frame")
    if crc32c(0, bytes(payload)) != crc:
        raise WireError("frame crc mismatch")
    body = _Reader(payload)
    if body.u8() != T_STRUCT:
        raise WireError("frame payload is not a struct")
    msg = _decode_struct(body, 0)
    if body.pos != body.end:
        raise WireError("trailing bytes in payload")
    return msg
