"""mgr progress module + cluster-wide perf aggregation
(VERDICT r3 #10; ref: src/pybind/mgr/progress/module.py,
src/mgr/DaemonServer.cc counter aggregation)."""
import time
import urllib.request

import numpy as np

from ceph_tpu.testing import MiniCluster


def test_progress_tracks_backfill_to_completion():
    """A real remap opens a recovery/backfill event whose progress
    climbs to 1.0 and retires into history."""
    c = MiniCluster(n_osd=4, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("prog", pg_num=16)
        io = r.open_ioctx("prog")
        rng = np.random.default_rng(9)
        objs = {f"p{i}": rng.integers(0, 256, 2048,
                                      dtype=np.uint8).tobytes()
                for i in range(48)}
        for k, v in objs.items():
            io.write_full(k, v)
        mgr = c.start_mgr()
        deadline = time.monotonic() + 20
        while mgr.osdmap.epoch == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        prog = mgr.start_progress()
        # force a mass remap: stats report recovering/backfilling PGs
        e0 = r.objecter.osdmap.epoch
        r.mon_command({"prefix": "osd out", "ids": [0]})
        r.objecter.wait_for_map(e0 + 1)
        saw_event = False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            c.tick()
            mgr.progress_tick()
            if prog.ls():
                saw_event = True
            if saw_event and not prog.ls() and \
                    all(d.pgs_recovering() == 0
                        for d in c.osds.values()):
                break
            time.sleep(0.1)
        assert saw_event, "no progress event for the remap"
        assert not prog.ls(), "events never completed"
        done = prog.history()
        assert done and done[-1]["progress"] == 1.0
        assert any("recovering" in e["message"] or
                   "backfilling" in e["message"] for e in done)
    finally:
        c.shutdown()


def test_progress_external_events():
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        mgr = c.start_mgr()
        prog = mgr.start_progress()
        prog.update("upgrade", "upgrading osds", 0.25)
        prog.update("upgrade", "upgrading osds", 0.75)
        assert prog.ls()[0]["progress"] == 0.75
        prog.complete("upgrade")
        assert not prog.ls()
        assert prog.history()[-1]["progress"] == 1.0
    finally:
        c.shutdown()


def test_prometheus_exports_aggregates_and_progress():
    """Per-daemon counters aggregate into ceph_cluster_* sums, and
    progress events appear as gauges."""
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("pm", pg_num=8)
        io = r.open_ioctx("pm")
        for i in range(10):
            io.write_full(f"m{i}", b"x" * 512)
        # stats must reach the mon before the scrape
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            c.tick()
            rc, _, perf = c.mon.handle_command(
                {"prefix": "osd perf dump"})
            if rc == 0 and perf and any(
                    ctr.get("op_w", 0) for ctr in perf.values()):
                break
            time.sleep(0.1)
        mgr = c.start_mgr()
        deadline = time.monotonic() + 20
        while mgr.osdmap.epoch == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        prog = mgr.start_progress()
        prog.update("demo", "demo event", 0.5)
        exp = mgr.start_prometheus()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/metrics",
                    timeout=30) as resp:
                body = resp.read().decode()
        finally:
            exp.shutdown()
        assert "ceph_daemon_op_w{" in body
        assert "ceph_cluster_op_w " in body
        # the cluster sum equals the per-daemon sum
        per, total = 0.0, None
        for ln in body.splitlines():
            if ln.startswith("ceph_daemon_op_w{"):
                per += float(ln.rsplit(" ", 1)[1])
            elif ln.startswith("ceph_cluster_op_w "):
                total = float(ln.rsplit(" ", 1)[1])
        assert total == per and total > 0
        assert 'ceph_progress_event{id="demo"' in body
    finally:
        c.shutdown()
