"""crushtool equivalent: compile/decompile text crushmaps, test maps.

CLI port of src/tools/crushtool.cc:
  crushtool -c map.txt -o map.json        # compile text -> map
  crushtool -d map.json [-o map.txt]      # decompile map -> text
  crushtool -i map.json --test [--min-x N --max-x N --num-rep N
      --rule N --show-utilization --show-statistics --show-mappings
      --show-bad-mappings]
  crushtool -i map.json --tree
  crushtool --build --num-osds N -o map.json LAYER ALG SIZE ...

The compiled map is stored as JSON (this framework's codec; the
reference uses its binary encoding).  --test distribution runs ride the
batched vmapped CRUSH engine (ceph_tpu.crush.tester).
"""
from __future__ import annotations

import argparse
import json
import sys

from ..crush.codec import wrapper_from_json, wrapper_to_json
from ..crush.compiler import CompileError, compile_crushmap, decompile
from ..crush.tester import CrushTester
from ..crush.wrapper import CrushWrapper


def save(w: CrushWrapper, path: str) -> None:
    with open(path, "w") as f:
        json.dump(wrapper_to_json(w), f)


def load(path: str) -> CrushWrapper:
    with open(path) as f:
        return wrapper_from_json(json.load(f))


# ------------------------------------------------------------------- tree
def tree_text(w: CrushWrapper) -> str:
    lines = ["ID\tWEIGHT\tTYPE NAME"]

    def walk(item: int, depth: int) -> None:
        b = w.crush.bucket(item)
        indent = "\t" * 0 + " " * (depth * 4)
        if b is None:
            name = w.name_map.get(item, f"osd.{item}")
            lines.append(f"{item}\t\t{indent}{name}")
            return
        tname = w.type_map.get(b.type, str(b.type))
        name = w.name_map.get(item, "")
        lines.append(f"{item}\t{b.weight / 0x10000:g}\t{indent}"
                     f"{tname} {name}")
        for child in b.items:
            walk(child, depth + 1)

    children = {c for b in w.crush.buckets if b is not None
                for c in b.items}
    roots = [b.id for b in w.crush.buckets
             if b is not None and b.id not in children]
    for r in sorted(roots, reverse=True):
        walk(r, 0)
    return "\n".join(lines) + "\n"


def build_map(num_osds: int, layers: list[tuple[str, str, int]]
              ) -> CrushWrapper:
    """--build: bottom-up tree, SIZE children per bucket (0 = all)
    (ref: crushtool.cc --build / CrushWrapper::build_hierarchy)."""
    w = CrushWrapper()
    w.type_map = {0: "osd"}
    for dev in range(num_osds):
        w.name_map[dev] = f"osd.{dev}"
    w.crush.max_devices = num_osds
    prev: list[int] = list(range(num_osds))
    for depth, (tname, alg, size) in enumerate(layers, start=1):
        w.type_map[depth] = tname
        from ..crush.compiler import ALG_IDS
        if alg not in ALG_IDS:
            raise CompileError(f"unknown alg {alg!r}")
        cur: list[int] = []
        n = size or len(prev)
        for base in range(0, len(prev), n):
            group = prev[base:base + n]
            name = f"{tname}{len(cur)}" if size else tname
            bid = w.add_bucket(name, tname, alg=ALG_IDS[alg])
            b = w.crush.bucket(bid)
            for it in group:
                cw = 0x10000 if it >= 0 else w.crush.bucket(it).weight
                b.items.append(it)
                b.item_weights.append(cw)
                b.weight += cw
            cur.append(bid)
        prev = cur
    return w


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="crushtool")
    ap.add_argument("-c", "--compile", metavar="SRC", dest="compile_src")
    ap.add_argument("-d", "--decompile", metavar="MAP",
                    dest="decompile_src")
    ap.add_argument("-i", "--infn", metavar="MAP")
    ap.add_argument("-o", "--outfn", metavar="OUT")
    ap.add_argument("--test", action="store_true")
    ap.add_argument("--tree", action="store_true")
    ap.add_argument("--build", action="store_true")
    ap.add_argument("--num-osds", type=int, default=0)
    ap.add_argument("--min-x", type=int, default=0)
    ap.add_argument("--max-x", type=int, default=1023)
    ap.add_argument("--num-rep", type=int, default=0)
    ap.add_argument("--rule", type=int, default=-1)
    ap.add_argument("--show-utilization", action="store_true")
    ap.add_argument("--show-statistics", action="store_true")
    ap.add_argument("--show-mappings", action="store_true")
    ap.add_argument("--show-bad-mappings", action="store_true")
    ap.add_argument("layers", nargs="*",
                    help="--build: TYPE ALG SIZE triples")
    args = ap.parse_args(argv)

    try:
        if args.compile_src:
            with open(args.compile_src) as f:
                w = compile_crushmap(f.read())
            out = args.outfn or args.compile_src + ".compiled"
            save(w, out)
            print(f"crushtool successfully built or modified map.  "
                  f"output to {out}", file=sys.stderr)
            return 0
        if args.decompile_src:
            w = load(args.decompile_src)
            text = decompile(w)
            if args.outfn:
                with open(args.outfn, "w") as f:
                    f.write(text)
            else:
                sys.stdout.write(text)
            return 0
        if args.build:
            if args.num_osds <= 0 or len(args.layers) % 3:
                print("--build requires --num-osds and TYPE ALG SIZE "
                      "triples", file=sys.stderr)
                return 1
            triples = [(args.layers[i], args.layers[i + 1],
                        int(args.layers[i + 2]))
                       for i in range(0, len(args.layers), 3)]
            w = build_map(args.num_osds, triples)
            if args.outfn:
                save(w, args.outfn)
                print(f"crushtool successfully built or modified map.  "
                      f"output to {args.outfn}", file=sys.stderr)
            else:
                sys.stdout.write(decompile(w))
            return 0
        if args.infn:
            w = load(args.infn)
            if args.tree:
                sys.stdout.write(tree_text(w))
            if args.test:
                t = CrushTester(w, min_x=args.min_x, max_x=args.max_x,
                                min_rep=args.num_rep,
                                max_rep=args.num_rep, rule=args.rule)
                sys.stdout.write(t.test(
                    show_utilization=args.show_utilization,
                    show_statistics=args.show_statistics,
                    show_mappings=args.show_mappings,
                    show_bad_mappings=args.show_bad_mappings))
            return 0
    except (CompileError, FileNotFoundError, json.JSONDecodeError,
            KeyError) as ex:
        print(f"crushtool: {ex!r}", file=sys.stderr)
        return 1
    ap.print_usage()
    return 1


if __name__ == "__main__":
    sys.exit(main())
