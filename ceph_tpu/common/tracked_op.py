"""OpTracker: in-flight op tracking with per-stage timestamps.

(ref: src/common/TrackedOp.{h,cc} — TrackedOp::mark_event history,
OpTracker::dump_ops_in_flight / dump_historic_ops served through the
admin socket; the slow-op age warning mirrors
osd_op_complaint_time.)
"""
from __future__ import annotations

import threading

from .lockdep import make_lock
import time
from collections import deque


class TrackedOp:
    """(ref: TrackedOp.h:214)."""

    __slots__ = ("desc", "start", "events", "done_at")

    def __init__(self, desc: str, now: float):
        self.desc = desc
        self.start = now
        self.events: list[tuple[float, str]] = [(now, "initiated")]
        self.done_at: float | None = None

    def mark_event(self, name: str, now: float | None = None) -> None:
        self.events.append((time.monotonic() if now is None else now,
                            name))

    def dump(self, now: float) -> dict:
        end = self.done_at if self.done_at is not None else now
        return {"description": self.desc,
                "age": round(now - self.start, 6),
                "duration": round(end - self.start, 6),
                "events": [{"time": round(t - self.start, 6),
                            "event": e} for t, e in self.events]}


class OpTracker:
    """(ref: TrackedOp.h:64 OpTracker)."""

    def __init__(self, history_size: int = 20,
                 complaint_time: float = 30.0):
        self._lock = make_lock("optracker")
        self._inflight: dict[object, TrackedOp] = {}
        self._historic: deque[TrackedOp] = deque(maxlen=history_size)
        self.complaint_time = complaint_time

    def start(self, key, desc: str) -> TrackedOp:
        op = TrackedOp(desc, time.monotonic())
        with self._lock:
            self._inflight[key] = op
        return op

    def mark(self, key, event: str) -> None:
        with self._lock:
            op = self._inflight.get(key)
        if op is not None:
            op.mark_event(event)

    def finish(self, key, event: str = "done") -> None:
        with self._lock:
            op = self._inflight.pop(key, None)
            if op is None:
                return
            now = time.monotonic()
            op.events.append((now, event))
            op.done_at = now
            self._historic.append(op)

    # -- dumps (ref: OpTracker::dump_ops_in_flight :282) ----------------
    def dump_in_flight(self) -> dict:
        now = time.monotonic()
        with self._lock:
            ops = [op.dump(now) for op in self._inflight.values()]
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic(self) -> dict:
        now = time.monotonic()
        with self._lock:
            ops = [op.dump(now) for op in self._historic]
        return {"num_ops": len(ops), "ops": ops}

    def slow_ops(self) -> list[dict]:
        """Ops older than the complaint threshold
        (ref: OpTracker::check_ops_in_flight)."""
        now = time.monotonic()
        with self._lock:
            return [op.dump(now) for op in self._inflight.values()
                    if now - op.start > self.complaint_time]
