"""JSON codec for CrushMap / CrushWrapper — shared by crushtool and
osdmaptool so their map files stay interchangeable (the reference's
analogue is the single binary encode/decode in crush/CrushWrapper.cc)."""
from __future__ import annotations

from .types import (ChooseArg, CrushBucket, CrushMap, CrushRule,
                    CrushRuleMask, CrushRuleStep)

TUNABLE_FIELDS = ("choose_local_tries", "choose_local_fallback_tries",
                  "choose_total_tries", "chooseleaf_descend_once",
                  "chooseleaf_vary_r", "chooseleaf_stable",
                  "straw_calc_version")


def crush_to_json(c: CrushMap) -> dict:
    return {
        "tunables": {f: getattr(c, f) for f in TUNABLE_FIELDS},
        "max_devices": c.max_devices,
        "buckets": [None if b is None else {
            "id": b.id, "type": b.type, "alg": b.alg, "hash": b.hash,
            "weight": b.weight, "items": b.items,
            "item_weights": b.item_weights,
        } for b in c.buckets],
        "rules": [None if r is None else {
            "steps": [[s.op, s.arg1, s.arg2] for s in r.steps],
            "mask": [r.mask.ruleset, r.mask.type, r.mask.min_size,
                     r.mask.max_size],
        } for r in c.rules],
        "choose_args": {
            str(name): {str(bid): {"ids": a.ids,
                                   "weight_set": a.weight_set}
                        for bid, a in args.items()}
            for name, args in c.choose_args.items()},
    }


def crush_from_json(data: dict) -> CrushMap:
    c = CrushMap()
    for f in TUNABLE_FIELDS:
        setattr(c, f, data["tunables"][f])
    c.max_devices = data["max_devices"]
    for bd in data["buckets"]:
        c.buckets.append(None if bd is None else CrushBucket(
            id=bd["id"], type=bd["type"], alg=bd["alg"], hash=bd["hash"],
            weight=bd["weight"], items=list(bd["items"]),
            item_weights=list(bd["item_weights"])))
    for rd in data["rules"]:
        c.rules.append(None if rd is None else CrushRule(
            steps=[CrushRuleStep(*s) for s in rd["steps"]],
            mask=CrushRuleMask(*rd["mask"])))
    for name, args in data.get("choose_args", {}).items():
        try:
            key = int(name)
        except ValueError:
            key = name
        c.choose_args[key] = {
            int(bid): ChooseArg(ids=a.get("ids"),
                                weight_set=a.get("weight_set"))
            for bid, a in args.items()}
    return c


def wrapper_to_json(w) -> dict:
    data = crush_to_json(w.crush)
    data.update({
        "type_map": {str(k): v for k, v in w.type_map.items()},
        "name_map": {str(k): v for k, v in w.name_map.items()},
        "rule_name_map": {str(k): v for k, v in w.rule_name_map.items()},
        "class_map": {str(k): v for k, v in w.class_map.items()},
        "class_name": {str(k): v for k, v in w.class_name.items()},
    })
    return data


def wrapper_from_json(data: dict):
    from .wrapper import CrushWrapper
    w = CrushWrapper()
    w.crush = crush_from_json(data)
    w.type_map = {int(k): v for k, v in data["type_map"].items()}
    w.name_map = {int(k): v for k, v in data["name_map"].items()}
    w.rule_name_map = {int(k): v
                       for k, v in data["rule_name_map"].items()}
    w.class_map = {int(k): v for k, v in data.get("class_map",
                                                  {}).items()}
    w.class_name = {int(k): v for k, v in data.get("class_name",
                                                   {}).items()}
    return w
