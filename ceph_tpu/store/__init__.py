"""Object storage engine layer (ref: src/os/).

`ObjectStore` is the abstract transactional API (ObjectStore.h:66);
`MemStore` is the in-memory implementation used by the OSD shards and
tests (model: src/os/memstore/MemStore.cc).
"""
from .objectstore import ObjectStore, Transaction, ObjectId, StoreError
from .memstore import MemStore

__all__ = ["ObjectStore", "Transaction", "ObjectId", "StoreError",
           "MemStore"]
