"""green: pacing through the shared capped-exponential Backoff
(common/backoff.py) — jittered, capped, clock-injectable."""
from ceph_tpu.common.backoff import Backoff


def mount(rados, pool):
    b = Backoff(base_s=0.05, cap_s=1.0)
    while True:
        try:
            out = rados.pool_lookup(pool)
            b.reset()
            return out
        except LookupError:
            b.sleep()
