"""cls lock: cooperative object locks (ref: src/cls/lock/cls_lock.cc;
types src/cls/lock/cls_lock_types.h).

Lock state lives in a `lock.<name>` xattr as JSON:
{"type": "exclusive"|"shared", "lockers": {"client/cookie": {...}}} —
the reference stores the same map in an object attr keyed
`lock.<name>` (cls_lock.cc lock_obj / ATTR_PREFIX).
"""
from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, cls_method

LOCK_EXCLUSIVE = "exclusive"
LOCK_SHARED = "shared"

_ATTR_PREFIX = "lock."


def _key(client: str, cookie: str) -> str:
    return f"{client}/{cookie}"


def _load(ctx, name: str) -> dict:
    try:
        return json.loads(ctx.getxattr(_ATTR_PREFIX + name))
    except ClsError:
        return {"type": "", "lockers": {}}


def _store(ctx, name: str, st: dict) -> None:
    ctx.setxattr(_ATTR_PREFIX + name, json.dumps(st).encode())


@cls_method("lock", "lock", CLS_METHOD_RD | CLS_METHOD_WR)
def lock(ctx, ind):
    """(ref: cls_lock.cc lock_op/lock_obj).  ind: {name, type, cookie,
    client, desc?}.  Exclusive excludes everyone else; shared excludes
    exclusive.  Re-lock by the same (client, cookie) renews."""
    name, typ = ind["name"], ind.get("type", LOCK_EXCLUSIVE)
    if typ not in (LOCK_EXCLUSIVE, LOCK_SHARED):
        raise ClsError("EINVAL", f"lock type {typ}")
    st = _load(ctx, name)
    me = _key(ind["client"], ind.get("cookie", ""))
    others = [k for k in st["lockers"] if k != me]
    if others and (typ == LOCK_EXCLUSIVE or
                   st["type"] == LOCK_EXCLUSIVE):
        raise ClsError("EBUSY", f"lock {name} held")
    if not ctx.exists():
        ctx.create()
    st["type"] = typ
    st["lockers"][me] = {"desc": ind.get("desc", ""),
                         "client": ind["client"],
                         "cookie": ind.get("cookie", "")}
    _store(ctx, name, st)
    return None


@cls_method("lock", "unlock", CLS_METHOD_RD | CLS_METHOD_WR)
def unlock(ctx, ind):
    """(ref: cls_lock.cc unlock_op)."""
    name = ind["name"]
    st = _load(ctx, name)
    me = _key(ind["client"], ind.get("cookie", ""))
    if me not in st["lockers"]:
        raise ClsError("ENOENT", f"not locker of {name}")
    del st["lockers"][me]
    if not st["lockers"]:
        st["type"] = ""
    _store(ctx, name, st)
    return None


@cls_method("lock", "break_lock", CLS_METHOD_RD | CLS_METHOD_WR)
def break_lock(ctx, ind):
    """Forcibly evict another client's locker
    (ref: cls_lock.cc break_lock)."""
    name = ind["name"]
    st = _load(ctx, name)
    victim = _key(ind["locker"], ind.get("cookie", ""))
    if victim not in st["lockers"]:
        raise ClsError("ENOENT", f"{victim} does not hold {name}")
    del st["lockers"][victim]
    if not st["lockers"]:
        st["type"] = ""
    _store(ctx, name, st)
    return None


@cls_method("lock", "get_info", CLS_METHOD_RD)
def get_info(ctx, ind):
    """(ref: cls_lock.cc get_info)."""
    st = _load(ctx, ind["name"])
    return {"type": st["type"] or None,
            "lockers": list(st["lockers"].values())}


@cls_method("lock", "list_locks", CLS_METHOD_RD)
def list_locks(ctx, ind):
    """All lock names on the object (ref: cls_lock.cc list_locks)."""
    return sorted(k[len(_ATTR_PREFIX):] for k in ctx.getxattrs()
                  if k.startswith(_ATTR_PREFIX))
