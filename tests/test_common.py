"""Foundation layer tests: options schema/config, perf counters, dout.

Models the reference's config/perf unit tests
(ref: src/test/common/test_config.cc, src/test/perf_counters.cc).
"""
import json

import pytest

from ceph_tpu.common.options import (Config, Option, OptionLevel,
                                     OptionType, OPTIONS, _parse_size)
from ceph_tpu.common.perf_counters import (PerfCounters,
                                           PerfCountersCollection)
from ceph_tpu.common.log import dout, set_subsys_level


def test_option_parse_types():
    assert OPTIONS["osd_pool_default_size"].parse("5") == 5
    assert OPTIONS["mon_osd_down_out_interval"].parse("30") == 30.0
    assert OPTIONS["objectstore_debug_inject_read_err"].parse("yes") is True
    assert OPTIONS["objectstore_debug_inject_read_err"].parse("0") is False
    assert OPTIONS["memstore_device_bytes"].parse("4K") == 4096
    assert _parse_size("2M") == 2 << 20
    assert _parse_size("1.5k") == 1536


def test_option_validation():
    with pytest.raises(ValueError):
        OPTIONS["osd_pool_default_size"].parse("-1")   # uint
    with pytest.raises(ValueError):
        OPTIONS["ms_type"].parse("carrier-pigeon")     # enum
    with pytest.raises(ValueError):
        OPTIONS["osd_debug_inject_dispatch_delay_probability"].parse("1.5")


def test_config_get_set_defaults():
    cfg = Config()
    assert cfg.get("osd_pool_default_size") == 3
    cfg.set("osd_pool_default_size", "5")
    assert cfg["osd_pool_default_size"] == 5
    diff = cfg.diff()
    # env layer: tier-1's conftest exports CEPH_TPU_LOCKDEP=1, which
    # every fresh Config legitimately reports as changed-from-default
    diff.pop("lockdep", None)
    assert diff == {"osd_pool_default_size": 5}
    with pytest.raises(KeyError):
        cfg.set("nonexistent_option", 1)


def test_config_observers_fire_on_change():
    cfg = Config()
    seen = []
    cfg.observe("upmap_max_deviation", lambda k, v: seen.append((k, v)))
    cfg.set("upmap_max_deviation", 7)
    cfg.set("upmap_max_deviation", 7)   # unchanged -> no second event
    cfg.set("upmap_max_deviation", 2)
    assert seen == [("upmap_max_deviation", 7), ("upmap_max_deviation", 2)]


def test_config_env_layer(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_OSD_POOL_DEFAULT_PG_NUM", "128")
    cfg = Config()
    assert cfg.get("osd_pool_default_pg_num") == 128


def test_config_file_layer(tmp_path):
    p = tmp_path / "conf.json"
    p.write_text(json.dumps({"log_level": 10, "ms_type": "ici"}))
    cfg = Config()
    cfg.load_file(str(p))
    assert cfg.get("log_level") == 10
    assert cfg.get("ms_type") == "ici"


def test_config_dump_levels():
    cfg = Config()
    basic = cfg.dump(OptionLevel.BASIC)
    assert "osd_pool_default_size" in basic
    assert "mon_min_osdmap_epochs" not in basic
    assert set(cfg.dump()) == set(OPTIONS)


def test_perf_counter_kinds():
    pc = PerfCounters("osd.0")
    pc.add_u64_counter("op_w", "writes")
    pc.add_u64("numpg", "pg count")
    pc.add_time_avg("op_w_lat", "write latency")
    pc.add_histogram("op_size")
    pc.inc("op_w")
    pc.inc("op_w", 2)
    pc.set("numpg", 17)
    pc.tinc("op_w_lat", 0.5)
    pc.tinc("op_w_lat", 1.5)
    pc.hinc("op_size", 3000)
    d = pc.dump()
    assert d["op_w"] == 3
    assert d["numpg"] == 17
    assert d["op_w_lat"] == {"avgcount": 2, "sum": 2.0, "avg": 1.0}
    assert sum(d["op_size"]) == 1


def test_perf_time_block_and_reset():
    pc = PerfCounters("bench")
    pc.add_time_avg("encode_lat")
    with pc.time_block("encode_lat"):
        pass
    assert pc.get("encode_lat")["avgcount"] == 1
    pc.reset()
    assert pc.get("encode_lat")["avgcount"] == 0


def test_perf_collection_dump_json():
    coll = PerfCountersCollection()
    a = coll.create("osd.1")
    a.add_u64_counter("op_r")
    a.inc("op_r", 9)
    assert coll.create("osd.1") is a           # idempotent create
    parsed = json.loads(coll.perf_dump_json())
    assert parsed["osd.1"]["op_r"] == 9
    coll.remove("osd.1")
    assert coll.perf_dump() == {}


def test_dout_gating(capsys):
    set_subsys_level("osd", 1)
    sink = dout("osd", 20)
    assert not sink            # gated off -> no-op sink
    sink.write("should not appear")
    set_subsys_level("osd", 20)
    assert dout("osd", 20)
    dout("osd", 20).write("deep debug visible")
    err = capsys.readouterr().err
    assert "deep debug visible" in err
    assert "should not appear" not in err
    set_subsys_level("osd", 1)


def test_lockdep_detects_order_cycle():
    """(ref: src/common/lockdep.cc:154 — a new edge closing a cycle in
    the follows-graph raises on the FIRST interleaving that could
    deadlock, no actual deadlock required)."""
    import threading

    import pytest

    from ceph_tpu.common import lockdep
    from ceph_tpu.common.lockdep import (DebugLock, LockOrderError,
                                         make_lock)
    from ceph_tpu.common.options import global_config

    lockdep.reset()
    a, b = DebugLock("A"), DebugLock("B")
    with a:
        with b:               # records A -> B
            pass
    err = []

    def reversed_order():
        try:
            with b:
                with a:       # A -> B -> A: cycle
                    pass
        except LockOrderError as ex:
            err.append(ex)

    t = threading.Thread(target=reversed_order)
    t.start()
    t.join()
    assert err and "cycle" in str(err[0])
    # reentrancy is not a cycle
    lockdep.reset()
    r = DebugLock("R")
    with r:
        with r:
            pass
    # consistent ordering never raises
    x, y, z = DebugLock("X"), DebugLock("Y"), DebugLock("Z")
    for _ in range(3):
        with x, y, z:
            pass
    # factory is config-gated: plain RLock with the option OFF,
    # DebugLock with it ON (tier-1 runs with lockdep ON via conftest,
    # so force both states explicitly and restore)
    import _thread
    g = global_config()
    prev = g["lockdep"]
    try:
        g.set("lockdep", False)
        assert isinstance(make_lock("n"), _thread.RLock)
        g.set("lockdep", True)
        assert isinstance(make_lock("n"), DebugLock)
    finally:
        g.set("lockdep", prev)
    lockdep.reset()


def test_lockdep_on_under_tier1():
    """tests/conftest.py exports CEPH_TPU_LOCKDEP=1 before any
    ceph_tpu import, so EVERY tier-1 run is a lock-order-sanitizer
    run: make_lock hands out DebugLocks tree-wide."""
    import os

    from ceph_tpu.common.lockdep import DebugLock, make_lock
    from ceph_tpu.common.options import global_config

    assert os.environ.get("CEPH_TPU_LOCKDEP") == "1"
    assert global_config()["lockdep"] is True
    assert isinstance(make_lock("tier1.probe"), DebugLock)
