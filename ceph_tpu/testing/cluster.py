"""MiniCluster: vstart-style single-process cluster harness.

One mon + N OSD daemons + client handles over a LocalNetwork — the
tier-2 cluster fixture the reference builds with vstart.sh /
qa/tasks/ceph.py: spin a cluster up, create pools, do IO through
librados, kill/revive daemons, and let the mon's failure handling and
the client's resend engine react.
"""
from __future__ import annotations

import time

from ..client.rados import Rados
from ..mon.monitor import Monitor, build_initial
from ..msg.messenger import LocalNetwork
from ..osd.daemon import OSDDaemon


class MiniCluster:
    def __init__(self, n_osd: int = 6, osds_per_host: int = 1,
                 threaded: bool = True, n_mon: int = 1,
                 auth: str = "none", fabric=None,
                 mon_crash_dirs: dict[int, str] | None = None,
                 fault_seed: int = 0):
        import copy
        self.network = LocalNetwork(fault_seed=fault_seed)
        # fault-plane delays release against the same clock the mons
        # tick on, so a sim-time schedule holds messages sim-time long
        self.network.faults.clock = self._clock
        self.threaded = threaded
        #: shared ICIFabric — OSDs become device-mesh co-resident and
        #: EC writes ride the psum fan-out (ceph_tpu.dist.fabric)
        self.fabric = fabric
        self._sim_now: float | None = None
        from ..common.perf_counters import PerfCountersCollection
        self.perf_collection = PerfCountersCollection()
        ranks = list(range(n_mon))
        # cephx: one cluster keyring; daemons get it whole, clients
        # get per-entity secrets minted on demand (ref: ceph-authtool
        # provisioning + AuthMonitor key server)
        self.keyring = None
        if auth == "cephx":
            from ..auth import KeyRing
            self.keyring = KeyRing.generate(
                [f"mon.{r}" for r in ranks]
                + [f"osd.{o}" for o in range(n_osd)])
        self.mon_names = [f"mon.{r}" for r in ranks]
        self.osds: dict[int, OSDDaemon] = {}
        self._stores: dict[int, object] = {}
        #: per-osd crash-spool dirs, sticky across kill/revive
        self._crash_dirs: dict[int, str] = {}
        self.mgr = None
        self.clients: list[Rados] = []
        # MDS fleet (ref: vstart's mds spawning): rank -> daemon (or
        # the MDSStandby wrapper that promoted into it), plus the
        # waiting standby pool
        self.mdss: dict[int, object] = {}
        self.standbys: dict[str, object] = {}
        self._standby_seq = 0
        m, w = build_initial(n_osd, osds_per_host=osds_per_host)
        #: per-rank mon crash-spool dirs (tests of the post-election
        #: spool drain); also honored by revive_mon
        self._mon_crash_dirs = dict(mon_crash_dirs or {})
        self.mons: dict[int, Monitor] = {}
        for r in ranks:
            self.mons[r] = Monitor(
                self.network, rank=r,
                initial_map=copy.deepcopy(m),
                initial_wrapper=copy.deepcopy(w),
                threaded=threaded, clock=self._clock,
                mon_ranks=ranks if n_mon > 1 else None,
                keyring=self.keyring,
                crash_dir=self._mon_crash_dirs.get(r))
            self.mons[r].init()
        self.mon = self.mons[0]      # rank 0 wins elections when alive
        if not threaded and n_mon > 1:
            self.pump()              # settle the election
        for osd in range(n_osd):
            self.start_osd(osd)

    # ------------------------------------------------------------ mons
    def leader(self) -> Monitor | None:
        for mn in self.mons.values():
            if mn.is_leader:
                return mn
        return None

    def kill_mon(self, rank: int) -> None:
        mn = self.mons.pop(rank, None)
        if mn is not None:
            if not hasattr(self, "_mon_stores"):
                self._mon_stores = {}
            self._mon_stores[rank] = mn.store
            mn.shutdown()
        if self.mon is mn and self.mons:
            self.mon = self.mons[min(self.mons)]

    def revive_mon(self, rank: int) -> Monitor:
        """Restart a killed mon from its surviving store."""
        store = getattr(self, "_mon_stores", {}).get(rank)
        mn = Monitor(self.network, rank=rank, store=store,
                     threaded=self.threaded, clock=self._clock,
                     mon_ranks=[int(n.split(".")[1])
                                for n in self.mon_names],
                     crash_dir=self._mon_crash_dirs.get(rank))
        mn.init()
        self.mons[rank] = mn
        if not self.threaded:
            self.pump()
        return mn

    # ------------------------------------------------------------ osds
    def start_osd(self, osd: int,
                  crash_dir: str | None = None) -> OSDDaemon:
        store = self._stores.get(osd)
        if crash_dir is not None:
            self._crash_dirs[osd] = crash_dir
        d = OSDDaemon(self.network, osd, store=store,
                      threaded=self.threaded,
                      perf_collection=self.perf_collection,
                      mon=self.mon_names, keyring=self.keyring,
                      fabric=self.fabric,
                      crash_dir=self._crash_dirs.get(osd))
        self._stores[osd] = d.store
        d.init()
        self.osds[osd] = d
        return d

    def kill_osd(self, osd: int) -> None:
        """Hard-kill: the daemon vanishes from the wire; its store
        survives for a later restart (qa thrasher kill_osd model)."""
        d = self.osds.pop(osd, None)
        if d is not None:
            d.shutdown()

    def revive_osd(self, osd: int) -> OSDDaemon:
        return self.start_osd(osd)

    def crash_osd(self, osd: int, now: float | None = None) -> None:
        """Inject a fault into the OSD's next tick: it captures a
        crash report (backtrace + metadata), posts it to the mon's
        crash table, and leaves the cluster like an aborted process
        (store kept for revive_osd)."""
        self.osds[osd].inject_crash_tick = True
        self.tick(now)

    # ------------------------------------------------------------- mds
    def start_mds(self, rank: int = 0, **kw):
        """Spawn a beaconing rank daemon (threaded mode only)."""
        from ..fs import MDSDaemon
        d = MDSDaemon(self.network, self.rados(), rank=rank,
                      mon=self.mon_names, keyring=self.keyring, **kw)
        d.init()
        self.mdss[rank] = d
        return d

    def start_mds_standby(self, name: str | None = None,
                          standby_replay_rank: int | None = None):
        """Add a standby to the promotion pool."""
        from ..fs import MDSStandby
        if name is None:
            self._standby_seq += 1
            name = f"sb{self._standby_seq}"
        s = MDSStandby(self.network, self.rados(), name=name,
                       mon=self.mon_names, keyring=self.keyring,
                       standby_replay_rank=standby_replay_rank)
        s.init()
        self.standbys[name] = s
        return s

    def kill_mds(self, rank: int) -> None:
        """Hard-kill a rank daemon: beacons stop, the endpoint
        vanishes, the journal tail is left unflushed for the
        successor's replay (qa mds thrasher kill model)."""
        d = self.mdss.pop(rank, None)
        if d is not None:
            d.kill()
        # a standby that promoted INTO this rank is now dead too
        for name, s in list(self.standbys.items()):
            if getattr(s, "rank", None) == rank:
                del self.standbys[name]

    def adopt_promoted(self) -> None:
        """Move promoted standbys into the rank table so kill_mds /
        fs status style helpers see them."""
        for name, s in list(self.standbys.items()):
            if getattr(s, "active", None) is not None:
                self.mdss[s.rank] = s
                del self.standbys[name]

    def fsmap(self):
        ldr = self.leader() or self.mon
        return ldr.mdsmon.fsmap

    def wait_mds_active(self, rank: int = 0,
                        timeout: float = 30.0) -> None:
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            m = self.fsmap()
            info = m.ranks.get(rank)
            if info is not None and info.state == "active":
                self.adopt_promoted()
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"mds.{rank} never went active (fsmap e{self.fsmap().epoch}"
            f" ranks={ {r: i.state for r, i in self.fsmap().ranks.items()} })")

    # ------------------------------------------------------------- mgr
    def start_mgr(self, **kw):
        from ..mgr import MgrDaemon
        if self.mgr is not None:
            self.mgr.shutdown()
        self.mgr = MgrDaemon(self.network, threaded=self.threaded,
                             mon=self.mon_names, **kw)
        self.mgr.init()
        if not self.threaded:
            self.pump()
        return self.mgr

    # ---------------------------------------------------------- client
    def rados(self, timeout: float = 30.0,
              auth_secret: str | None = None) -> Rados:
        if self.keyring is not None and auth_secret is None:
            # mint this client's key into the shared keyring
            from ..auth import generate_key
            import itertools as _it
            if not hasattr(self, "_client_keys"):
                self._client_keys = _it.count(1)
            name = f"client.mc{next(self._client_keys)}"
            auth_secret = generate_key()
            self.keyring.keys[name] = auth_secret
            r = Rados(self.network, name=name, op_timeout=timeout,
                      threaded=self.threaded, mon=self.mon_names,
                      auth_secret=auth_secret)
            self.clients.append(r)
            if self.threaded:
                r.connect(timeout)
            else:
                raise NotImplementedError(
                    "cephx MiniCluster requires threaded mode")
            return r
        r = Rados(self.network, op_timeout=timeout,
                  threaded=self.threaded, mon=self.mon_names)
        self.clients.append(r)   # before connect: pump() must see it
        if self.threaded:
            r.connect(timeout)
        else:
            r.objecter.pump_hook = self.pump
            r.objecter.start()
            self.pump()
            if r.objecter.osdmap.epoch < 1:
                raise TimeoutError("no osdmap after pump")
            r._connected = True
        return r

    # ------------------------------------------------------------ sync
    def pump(self, rounds: int = 30) -> None:
        """Non-threaded mode: pump every endpoint until quiescent."""
        for _ in range(rounds):
            # release fault-held (delayed/reordered) messages whose
            # deadline passed; counts as movement so we keep pumping
            moved = self.network.faults.flush()
            moved += sum(mn.ms.poll() for mn in self.mons.values())
            for d in self.osds.values():
                moved += d.ms.poll()
            for c in self.clients:
                moved += c.objecter.ms.poll()
            if self.mgr is not None:
                moved += self.mgr.ms.poll()
            if not moved:
                break

    def _clock(self) -> float:
        """Mon clock: simulated when ticks carry `now`, else real —
        keeps the mon's failure/auto-out timers in the same time domain
        as the OSD heartbeats."""
        return self._sim_now if self._sim_now is not None \
            else time.monotonic()

    def tick(self, now: float | None = None) -> None:
        """One heartbeat round on every live OSD + a mon tick; pumps
        in non-threaded mode so the exchange completes.  An OSD whose
        tick raises has already crash-captured (osd.daemon
        heartbeat_tick) — the harness reaps it like an aborted
        process: off the wire, store kept for a revive."""
        if now is not None:
            self._sim_now = now
        for osd, d in list(self.osds.items()):
            try:
                d.heartbeat_tick(now)
            except Exception as ex:
                from ..common.log import dout
                dout("cluster", 0).write(
                    "osd.%d crashed in tick (%s: %s) — reaped",
                    osd, type(ex).__name__, ex)
                del self.osds[osd]
                d.shutdown()
        if not self.threaded:
            self.pump()
        for mn in self.mons.values():
            mn.tick(now)
        if not self.threaded:
            self.pump()

    # ------------------------------------------------------------- rgw
    def rgw_multisite(self, zones=("z1", "z2"), zonegroup: str = "zg1",
                      realm: str = "gold", index_shards: int = 4,
                      sync_interval: float = 0.05, **kw) -> list:
        """Spin one RGW gateway per zone (first zone = metadata
        master), each over its own `rgw-<zone>` pool, commit the
        realm/zonegroup/zone period into EVERY zone's pool (the
        `realm pull` bootstrap), and start the sync agents.  Returns
        the gateways in zone order (ref: the two-cluster multisite
        topology of qa/tasks/rgw-multisite; collapsed onto one RADOS
        cluster with per-zone pools)."""
        from ..rgw import RGWGateway
        gws = []
        for z in zones:
            gws.append(RGWGateway(
                self.rados(), pool=f"rgw-{z}", zone=z,
                index_shards=index_shards,
                sync_interval=sync_interval, **kw))
        for gw in gws:
            adm = gw.multisite.admin
            adm.realm_create(realm)
            adm.zonegroup_create(zonegroup)
            for i, z in enumerate(zones):
                adm.zone_create(
                    z, zonegroup,
                    endpoint=f"http://127.0.0.1:{gws[i].port}",
                    master=(i == 0))
            adm.period_commit()
            gw.multisite.refresh(force=True)
        self.rgws = getattr(self, "rgws", [])
        self.rgws.extend(gws)
        for i, z in enumerate(zones):
            # HTTP fault coverage: peer pulls to this zone's endpoint
            # resolve to the entity "rgw.<zone>" in partition rules
            self.network.faults.bind_alias(
                f"http://127.0.0.1:{gws[i].port}", f"rgw.{z}")
            gws[i].faults = self.network.faults
        for gw in gws:
            gw.start()
        return gws

    def kill_rgw_zone(self, gw) -> None:
        """Stop a zone's gateway the unclean way a kill -9 looks to
        the rest of the site: the sync agent abandons its in-flight
        batch (markers for it never persist), the HTTP port closes,
        and NO final GC pass runs — exactly the state a restart must
        recover from via the durable sync markers."""
        gw.sync._stop.set()
        if gw.sync._thread is not None:
            gw.sync._thread.join(timeout=10.0)
        gw.pusher.stop()
        gw._gc_stop.set()
        gw.httpd.shutdown()
        gw.httpd.server_close()
        if gw in getattr(self, "rgws", []):
            self.rgws.remove(gw)

    def restart_rgw_zone(self, gw, **kw):
        """Bring a killed zone's gateway back on the SAME port (its
        endpoint is baked into every peer's period) and pool — the
        restarted sync agent resumes from the durable markers.  The
        old gateway's security config rides along by default: a
        secured zone restarted anonymous would have its signed pulls
        refused by every peer (and stop gating its own surface)."""
        from ..rgw import RGWGateway
        kw.setdefault("keyring", gw.keyring)
        kw.setdefault("system_key", gw.system_key)
        if gw.keystone is not None:
            kw.setdefault("keystone_url", gw.keystone.url)
        g2 = RGWGateway(
            self.rados(), pool=gw.pool, zone=gw.zone, port=gw.port,
            index_shards=gw.index_shards,
            sync_interval=gw.sync.interval, **kw)
        self.rgws = getattr(self, "rgws", [])
        self.rgws.append(g2)
        g2.faults = gw.faults
        g2.start()
        return g2

    def wait_all_up(self, timeout: float = 30.0) -> None:
        end = time.monotonic() + timeout
        want = set(self.osds)
        while time.monotonic() < end:
            if not self.threaded:
                self.pump()
            m = self.mon.osdmap
            if all(o < m.max_osd and m.is_up(o) for o in want):
                return
            time.sleep(0.01)
        raise TimeoutError("osds never came up")

    def shutdown(self) -> None:
        for gw in list(getattr(self, "rgws", [])):
            gw.shutdown()
        for s in list(self.standbys.values()):
            s.shutdown()
        for d in list(self.mdss.values()):
            d.shutdown()
        for c in self.clients:
            c.shutdown()
        if self.mgr is not None:
            self.mgr.shutdown()
        for d in list(self.osds.values()):
            d.shutdown()
        for mn in self.mons.values():
            mn.shutdown()
