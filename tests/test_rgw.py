"""rgw-lite: S3 REST gateway over RADOS (ref: src/rgw REST frontend,
bucket-index-on-omap layout)."""
import urllib.error
import urllib.request
from xml.etree import ElementTree as ET

import pytest

from ceph_tpu.rgw import RGWGateway
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def gw():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    g = RGWGateway(c.rados(), pool="rgw")
    g.start()
    yield g
    g.shutdown()
    c.shutdown()


def req(gw, method, path, data=None, headers=None):
    r = urllib.request.Request(f"http://127.0.0.1:{gw.port}{path}",
                               data=data, method=method,
                               headers=headers or {})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


def test_bucket_lifecycle(gw):
    assert req(gw, "PUT", "/b1")[0] == 200
    assert req(gw, "PUT", "/b2")[0] == 200
    status, _, body = req(gw, "GET", "/")
    names = [e.text for e in ET.fromstring(body).iter("Name")]
    assert {"b1", "b2"} <= set(names)
    assert req(gw, "HEAD", "/b1")[0] == 200
    assert req(gw, "DELETE", "/b2")[0] == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "HEAD", "/b2")
    assert ei.value.code == 404


def test_object_crud_and_etag(gw):
    req(gw, "PUT", "/crud")
    payload = b"hello s3 world" * 100
    status, hdrs, _ = req(gw, "PUT", "/crud/dir/obj.bin", payload)
    assert status == 200
    import hashlib
    assert hdrs["ETag"] == f'"{hashlib.md5(payload).hexdigest()}"'
    status, hdrs, body = req(gw, "GET", "/crud/dir/obj.bin")
    assert status == 200 and body == payload
    assert req(gw, "HEAD", "/crud/dir/obj.bin")[0] == 200
    # bucket with content refuses delete
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "DELETE", "/crud")
    assert ei.value.code == 409
    assert req(gw, "DELETE", "/crud/dir/obj.bin")[0] == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "GET", "/crud/dir/obj.bin")
    assert ei.value.code == 404


def test_list_objects_v2_pagination(gw):
    req(gw, "PUT", "/lst")
    for i in range(12):
        req(gw, "PUT", f"/lst/k{i:02d}", b"v")
    req(gw, "PUT", "/lst/other", b"v")
    status, _, body = req(gw, "GET", "/lst?list-type=2&prefix=k&"
                          "max-keys=5")
    root = ET.fromstring(body)
    keys = [e.text for e in root.iter("Key")]
    assert keys == [f"k{i:02d}" for i in range(5)]
    assert root.find("IsTruncated").text == "true"
    token = root.find("NextContinuationToken").text
    status, _, body = req(gw, "GET", f"/lst?list-type=2&prefix=k&"
                          f"continuation-token={token}&max-keys=50")
    root = ET.fromstring(body)
    keys2 = [e.text for e in root.iter("Key")]
    assert keys2 == [f"k{i:02d}" for i in range(5, 12)]
    assert root.find("IsTruncated").text == "false"


def test_multipart_upload(gw):
    req(gw, "PUT", "/mp")
    status, _, body = req(gw, "POST", "/mp/big.bin?uploads")
    upload_id = ET.fromstring(body).find("UploadId").text
    parts = [b"A" * 70_000, b"B" * 50_000, b"C" * 10]
    for i, p in enumerate(parts, start=1):
        st, hdrs, _ = req(gw, "PUT",
                          f"/mp/big.bin?partNumber={i}&"
                          f"uploadId={upload_id}", p)
        assert st == 200
    status, _, body = req(gw, "POST",
                          f"/mp/big.bin?uploadId={upload_id}",
                          b"<CompleteMultipartUpload>"
                          b"<Part><PartNumber>1</PartNumber></Part>"
                          b"<Part><PartNumber>2</PartNumber></Part>"
                          b"<Part><PartNumber>3</PartNumber></Part>"
                          b"</CompleteMultipartUpload>")
    assert status == 200
    etag = ET.fromstring(body).find("ETag").text
    assert etag.endswith("-3\"") or etag.endswith("-3")
    _, _, got = req(gw, "GET", "/mp/big.bin")
    assert got == b"".join(parts)
    # upload bookkeeping cleaned out of the listing
    _, _, body = req(gw, "GET", "/mp?list-type=2")
    keys = [e.text for e in ET.fromstring(body).iter("Key")]
    assert keys == ["big.bin"]


def test_multipart_abort(gw):
    req(gw, "PUT", "/ab")
    _, _, body = req(gw, "POST", "/ab/x?uploads")
    uid = ET.fromstring(body).find("UploadId").text
    req(gw, "PUT", f"/ab/x?partNumber=1&uploadId={uid}", b"zzz")
    assert req(gw, "DELETE", f"/ab/x?uploadId={uid}")[0] == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "POST", f"/ab/x?uploadId={uid}", b"")
    assert ei.value.code == 404
    _, _, body = req(gw, "GET", "/ab?list-type=2")
    assert [e.text for e in ET.fromstring(body).iter("Key")] == []
