"""EC automatic recovery on acting-set changes: remapped shards are
rebuilt from >=k survivors and pushed to their new holders
(ref: EC backfill; src/osd/ECBackend.cc:735 recover_object)."""
import numpy as np
import pytest

from ceph_tpu.osd.types import PG
from ceph_tpu.testing import MiniCluster, OSDThrasher


def make_cluster(n=7):
    c = MiniCluster(n_osd=n, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k2m2",
                   "profile": {"plugin": "tpu", "k": "2", "m": "2",
                               "crush-failure-domain": "host"}})
    r.pool_create("ec", pg_num=8, pool_type="erasure",
                  erasure_code_profile="k2m2")
    c.pump()
    return c, r


def wait_clean(c, rounds=30):
    for _ in range(rounds):
        c.pump()
        if all(d.pgs_recovering() == 0 for d in c.osds.values()):
            return
    raise TimeoutError("EC recovery never finished")


def test_ec_out_remap_rebuilds_shards():
    c, r = make_cluster()
    io = r.open_ioctx("ec")
    rng = np.random.default_rng(11)
    objs = {f"e{i}": rng.integers(0, 256, 3000 + i,
                                  dtype=np.uint8).tobytes()
            for i in range(8)}
    for oid, data in objs.items():
        io.write_full(oid, data)
    c.pump()
    # force remaps
    r.mon_command({"prefix": "osd out", "ids": [0, 1]})
    wait_clean(c)
    # every object still reads back through the new acting sets
    for oid, data in objs.items():
        assert io.read(oid) == data, oid
    # every acting shard holds its index's chunk
    pid = r.pool_lookup("ec")
    m = c.mon.osdmap
    for oid in objs:
        raw = m.object_locator_to_pg(oid, pid)
        pg = m.pools[pid].raw_pg_to_pg(raw)
        _, _, acting, _ = m.pg_to_up_acting_osds(raw)
        for s, osd in enumerate(acting):
            if osd < 0 or osd >= (1 << 30):
                continue
            assert c.osds[osd].pgs[pg].shard.store.exists(
                c.osds[osd].pgs[pg].shard.cid,
                __import__("ceph_tpu.store",
                           fromlist=["ObjectId"]).ObjectId(
                    oid, shard=s)), (oid, s, osd)
    # back in: remap again, still clean
    r.mon_command({"prefix": "osd in", "ids": [0, 1]})
    wait_clean(c)
    for oid, data in objs.items():
        assert io.read(oid) == data, oid


def test_ec_kill_then_remap_recovers_from_survivors():
    """Kill an OSD (its chunks gone from the wire), remap via out:
    rebuilt chunks land on the replacement holders and data survives."""
    c, r = make_cluster()
    io = r.open_ioctx("ec")
    payload = bytes(range(256)) * 40
    io.write_full("survivor", payload)
    c.pump()
    pid = r.pool_lookup("ec")
    m = c.mon.osdmap
    raw = m.object_locator_to_pg("survivor", pid)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    victim = next(o for o in acting if 0 <= o < (1 << 30))
    c.kill_osd(victim)
    r.mon_command({"prefix": "osd down", "ids": [victim]})
    r.mon_command({"prefix": "osd out", "ids": [victim]})
    wait_clean(c)
    assert io.read("survivor") == payload
    # revive with its stale store: peering re-runs; data still intact
    c.revive_osd(victim)
    r.mon_command({"prefix": "osd in", "ids": [victim]})
    c.pump()
    wait_clean(c)
    assert io.read("survivor") == payload


def test_ec_deleted_object_not_resurrected():
    """Delete while a shard holder is down: its stale chunks must lose
    to the tombstone when it returns (version-aware recovery)."""
    c, r = make_cluster()
    io = r.open_ioctx("ec")
    io.write_full("ghost", b"G" * 5000)
    c.pump()
    pid = r.pool_lookup("ec")
    m = c.mon.osdmap
    raw = m.object_locator_to_pg("ghost", pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    victim = next(o for o in acting
                  if 0 <= o < (1 << 30) and o != primary)
    c.kill_osd(victim)
    r.mon_command({"prefix": "osd down", "ids": [victim]})
    c.pump()
    io.remove("ghost")
    c.pump()
    # victim returns holding its pre-delete chunk
    c.revive_osd(victim)
    r.mon_command({"prefix": "osd in", "ids": [victim]})
    c.pump()
    wait_clean(c)
    from ceph_tpu.client import RadosError
    with pytest.raises(RadosError) as ei:
        io.read("ghost")
    assert ei.value.errno_name == "ENOENT"
    # the returning holder's store carries the tombstone, not data
    from ceph_tpu.osd.ec_backend import ec_store_inventory, pg_cid
    inv = ec_store_inventory(c.osds[victim].store, pg_cid(pg))
    assert all(whiteout for _, whiteout in inv.get("ghost", {}).values())
    # and a new object under the same name starts fresh
    io.write_full("ghost", b"reborn")
    assert io.read("ghost") == b"reborn"
    c.shutdown()


def test_ec_thrash_out_in_cycle():
    """Out/in thrash on an EC pool with async IO, heal, verify."""
    import time
    c, r = make_cluster(n=8)
    io = r.open_ioctx("ec")
    rng = np.random.default_rng(21)
    expected, futures = {}, {}
    t = OSDThrasher(c, seed=5, min_in=5, min_live=8)  # out/in only
    for i in range(8):
        for _ in range(2):
            oid = f"t{int(rng.integers(12))}"
            data = bytes([int(rng.integers(256))]) * \
                int(rng.integers(100, 600))
            futures[oid] = io.aio_write_full(oid, data)
            expected[oid] = data
        c.pump()
        if i % 2 == 0:
            t.out_osd()
        else:
            t.in_osd()
        c.pump()
    t.heal()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        c.pump()
        if all(f.done() for f in futures.values()):
            break
        time.sleep(0.02)
    assert all(f.done() for f in futures.values()), t.log
    wait_clean(c)
    for oid, data in sorted(expected.items()):
        assert io.read(oid) == data, (oid, t.log)
    c.shutdown()
