"""Prototype: straw2 ln lookup as one-hot int8 MXU matmuls vs the
64Ki-entry gather (PERF_NOTES round-3: the gather is ~520ms of a
627ms (64Ki x 500) draw pass; VERDICT r4 weak #1 names this attack).

Formulation: u = hi*256 + lo.  T = _LN16 reshaped (256, 256), split
into 6 int8 byte limbs, LIMB-MAJOR columns (col = j*256 + lo) so the
second selection reduces over the minor axis:
    A_hi = onehot(hi)  (N, 256) int8
    M    = A_hi @ L    (N, 6*256) int32      # MXU row-select
    sel  = sum(M.reshape(N,6,256) * onehot(lo)[:,None,:], -1)  # VPU
    ln   = sum_j sel[:, j] << 8j  (int64)
The intermediate M costs 6KB/element, so the full (64Ki x 500) draw
cannot run in one piece (201 GB) — lax.map over x-chunks bounds it.
This script measures gather vs chunked matmul at several chunk sizes
to pick the map_batch chunking.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

import sys
sys.path.insert(0, "/root/repo")
from ceph_tpu.crush.batch import _LN16, crush_ln16  # noqa: E402

N_X, N_I = 65536, 500           # the placement draw shape
REPS = 20


def build_limbs() -> np.ndarray:
    t = _LN16.reshape(256, 256)          # [hi, lo] int64
    limbs = np.zeros((6, 256, 256), dtype=np.int8)   # [j, hi, lo]
    for j in range(6):
        limbs[j] = ((t >> (8 * j)) & 0xFF).astype(np.int8)
    # (hi, j*256+lo): limb-major columns
    return np.transpose(limbs, (1, 0, 2)).reshape(256, 6 * 256)


_LIMBS = build_limbs()


def ln16_matmul(u):
    """u: (...,) int in [0, 65536) -> int64 crush_ln.  Intermediate:
    1536 int32 per element — caller bounds the batch."""
    hi = (u >> 8).astype(jnp.int32)
    lo = (u & 0xFF).astype(jnp.int32)
    iota = jnp.arange(256, dtype=jnp.int32)
    a_hi = (hi[..., None] == iota).astype(jnp.int8)
    m = jax.lax.dot_general(
        a_hi, jnp.asarray(_LIMBS),
        (((a_hi.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)        # (..., 6*256)
    m = (m & 0xFF).reshape(*u.shape, 6, 256)     # undo int8 wrap
    a_lo = (lo[..., None] == iota)
    sel = jnp.where(a_lo[..., None, :], m, 0).sum(axis=-1)  # (...,6)
    out = jnp.zeros(u.shape, dtype=jnp.int64)
    for j in range(6):
        out = out + (sel[..., j].astype(jnp.int64) << (8 * j))
    return out


def chunked(fn, u, c):
    """lax.map over x-chunks — the shape map_batch would use."""
    chunks = u.reshape(u.shape[0] // c, c, *u.shape[1:])
    return jax.lax.map(fn, chunks).reshape(u.shape)


def chain(fn, u0):
    """REPS unique-work scan chain (PERF_NOTES methodology)."""
    def body(c, i):
        u = (c ^ i) & 0xFFFF
        return c, fn(u).sum()
    _, sums = jax.lax.scan(body, u0, jnp.arange(REPS, dtype=u0.dtype))
    return sums.sum()


def bench(name, fn, u0):
    f = jax.jit(lambda u: chain(fn, u))
    r = f(u0); r.block_until_ready()            # compile
    t0 = time.perf_counter()
    r = f(u0); r.block_until_ready()
    dt = (time.perf_counter() - t0) / REPS
    n = u0.size
    print(f"{name:20s} {dt*1e3:8.2f} ms/pass "
          f"({n/dt/1e9:.2f} G-lookups/s)  checksum={int(r)}")
    return dt


def main():
    print("backend:", jax.default_backend())
    rng = np.random.default_rng(7)
    u_np = rng.integers(0, 65536, size=(N_X, N_I), dtype=np.int64)
    small = jnp.asarray(u_np[:8])
    want = np.asarray(crush_ln16(small))
    got = np.asarray(ln16_matmul(small))
    assert (want == got).all(), \
        f"MISMATCH {np.argwhere(want != got)[:4]}"
    print("bit-exact over", small.size, "lookups")
    u0 = jnp.asarray(u_np)
    t_g = bench("gather", crush_ln16, u0)
    for c in (64, 128, 256, 512):
        t_m = bench(f"matmul chunk={c}",
                    lambda u, c=c: chunked(ln16_matmul, u, c), u0)
        print(f"  -> speedup vs gather: {t_g / t_m:.2f}x")


if __name__ == "__main__":
    main()
