"""RED: a dispatch handler blocks — directly and through a helper
the project call graph resolves (the graft-entry dryrun deadlock
shape: dispatch waiting on something that needs dispatch to make
progress)."""
import time


class OSDStub:
    def ms_dispatch(self, msg):
        if msg == "flush":
            # BUG: sleeping ON the dispatch thread stalls every peer
            time.sleep(0.2)
            return True
        self._apply(msg)
        return True

    def _apply(self, msg):
        # BUG: cross-function — reachable from ms_dispatch, blocks in
        # a condition wait
        self._flushed.wait(5.0)
        self._log.append(msg)
