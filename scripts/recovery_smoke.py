#!/usr/bin/env python
"""Recovery-bandwidth smoke — the network-optimal repair half of the
ship gate (check_green.sh).

Boots a MiniCluster with a clay (regenerating-code) EC pool, writes
objects, takes one OSD out, and asserts:

1. recovery completes and every object reads back byte-identical;
2. the cluster-wide `recovery_bytes_read` counter is STRICTLY below
   k x the rebuilt bytes — the sub-chunk repair path
   (ECSubRead v2 `subchunks`, ref: ErasureCodeClay.cc:364
   get_repair_subchunks; arxiv 1412.3022) shipped less than the k
   whole chunks a full-chunk rebuild pulls, and below the
   k x chunk_bytes x objects ceiling;
3. SLOW_OPS stays clear — the repair reads must not wedge ops.

Run from the repo root: python scripts/recovery_smoke.py
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np                                   # noqa: E402

from ceph_tpu.testing import MiniCluster             # noqa: E402

K, M = 4, 2
N_OBJ = 6


def main() -> int:
    c = MiniCluster(n_osd=7, threaded=False)
    try:
        c.pump()
        c.wait_all_up()
        r = c.rados()
        r.mon_command({"prefix": "osd erasure-code-profile set",
                       "name": "clay_smoke",
                       "profile": {"plugin": "clay", "k": str(K),
                                   "m": str(M),
                                   "crush-failure-domain": "host"}})
        r.pool_create("clay_pool", pg_num=4, pool_type="erasure",
                      erasure_code_profile="clay_smoke")
        c.pump()
        io = r.open_ioctx("clay_pool")
        rng = np.random.default_rng(23)
        objs = {f"smoke{i}": rng.integers(0, 256, 8192 + 37 * i,
                                          dtype=np.uint8).tobytes()
                for i in range(N_OBJ)}
        for oid, data in objs.items():
            io.write_full(oid, data)
        c.pump()

        r.mon_command({"prefix": "osd out", "ids": [0]})
        for _ in range(60):
            c.pump()
            if all(d.pgs_recovering() == 0 for d in c.osds.values()):
                break
        else:
            print("FAIL: recovery never finished", file=sys.stderr)
            return 1

        for oid, data in objs.items():
            got = io.read(oid)
            if got != data:
                print(f"FAIL: {oid} corrupted after recovery",
                      file=sys.stderr)
                return 1

        read = sum(d.perf._c["recovery_bytes_read"].value
                   for d in c.osds.values())
        rebuilt = sum(d.perf._c["recovery_bytes_rebuilt"].value
                      for d in c.osds.values())
        if rebuilt <= 0:
            print("FAIL: nothing was rebuilt (no recovery ran?)",
                  file=sys.stderr)
            return 1
        if read >= K * rebuilt:
            print(f"FAIL: recovery read {read} B >= k x rebuilt "
                  f"({K} x {rebuilt} B) — sub-chunk repair did not "
                  "engage", file=sys.stderr)
            return 1
        # absolute ceiling: k whole chunk streams per recovered object
        pool_cs = next(iter(c.osds.values()))._ec_plugin(
            "clay_smoke").get_chunk_size(K * 4096)
        stream_bytes = sum(
            ((len(d) + K * pool_cs - 1) // (K * pool_cs)) * pool_cs
            for d in objs.values())
        ceiling = K * stream_bytes
        if read >= ceiling:
            print(f"FAIL: recovery read {read} B >= full-chunk "
                  f"ceiling {ceiling} B", file=sys.stderr)
            return 1

        rc, _, health = r.mon_command({"prefix": "health"})
        if rc == 0 and "SLOW_OPS" in (health or {}).get("checks", {}):
            print("FAIL: SLOW_OPS raised during recovery",
                  file=sys.stderr)
            return 1
        print(f"recovery_smoke: OK (read {read} B vs full-chunk "
              f">= {K * rebuilt} B for the same shards; "
              f"saving {1 - read / (K * rebuilt):.0%})")
        return 0
    finally:
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
