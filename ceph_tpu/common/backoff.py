"""Shared capped-exponential retry backoff with jitter.

One policy for every "peer unreachable, try again later" loop in the
tree (ref: the reference's ExponentialBackoff in common/, and
MonClient::schedule_tick's reopen interval doubling).  Extracted from
the RGW SyncAgent, which grew the canonical form first: delay =
min(cap, base * 2^(fails-1)), multiplied by a jitter factor in
[0.5, 1.5) so peers recovering together do not re-stampede in
lockstep.

Two usage shapes:

* Blocking loops call ``next_delay()`` (or ``sleep()``) per failure —
  the objecter's EAGAIN command retry, the MDS client's send retry.
* Deadline-driven loops (an agent tick, a mon tick on simulated time)
  call ``fail(now)`` to arm a next-try stamp and ``ready(now)`` to
  test it — the caller owns its clock, so simulated-time harnesses
  pace exactly like wall-clock daemons.

A success MUST call ``reset()``; a Backoff that is never reset climbs
to its cap and stays there, which is the correct behavior for a peer
that stays dead but would mis-pace the next incident.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional


def full_jitter(delay: float, rng: Optional[random.Random] = None) -> float:
    """Spread a delay over [0.5, 1.5) * delay (the SyncAgent's jitter
    shape; callers that need a seeded stream pass their own rng)."""
    r = rng.random() if rng is not None else random.random()
    return delay * (0.5 + r)


class Backoff:
    """Capped exponential backoff: one instance per retried peer/op.

    Not thread-safe by itself — every current user mutates it under
    its own daemon lock or from a single thread.
    """

    def __init__(self, base_s: float = 0.1, cap_s: float = 5.0,
                 jitter: bool = True,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic):
        if base_s <= 0 or cap_s < base_s:
            raise ValueError(f"bad backoff bounds ({base_s}, {cap_s})")
        self.base_s = base_s
        self.cap_s = cap_s
        self.jitter = jitter
        self._rng = rng
        self._clock = clock
        self._fails = 0
        self._next_ok = 0.0

    @property
    def failures(self) -> int:
        return self._fails

    def reset(self) -> None:
        """The operation succeeded: the next failure starts at base."""
        self._fails = 0
        self._next_ok = 0.0

    def next_delay(self) -> float:
        """Record a failure, return how long to wait before retrying."""
        self._fails += 1
        delay = min(self.cap_s, self.base_s * 2 ** (self._fails - 1))
        if self.jitter:
            delay = full_jitter(delay, self._rng)
        return delay

    def sleep(self) -> float:
        """Blocking-loop form: record a failure and sleep it out."""
        delay = self.next_delay()
        time.sleep(delay)
        return delay

    # -- deadline form (simulated-clock friendly) ----------------------
    def fail(self, now: float | None = None) -> float:
        """Record a failure and arm the next-try stamp; returns the
        delay so callers can log it."""
        delay = self.next_delay()
        self._next_ok = (self._clock() if now is None else now) + delay
        return delay

    def ready(self, now: float | None = None) -> bool:
        """True when enough time has passed to try again."""
        return (self._clock() if now is None else now) >= self._next_ok
