"""green: named exceptions only."""


def drain(q):
    try:
        return q.pop()
    except (IndexError, KeyError):
        return None
