"""errcheck: the runtime error-path coverage sanitizer
(ceph_tpu/common/errcheck.py — the dynamic twin of cephck's
error-contract rule family).

Covers the ISSUE-18 contract: fired-handler counting keyed by concrete
exception type, the never-fired report shape, instrumented modules
behaving EXACTLY like pristine ones, and zero footprint when the
option is off (subprocess probe)."""
import json
import subprocess
import sys
import textwrap

import pytest

from ceph_tpu.common import errcheck

PROBE_SRC = textwrap.dedent("""\
    def lookup(d, k):
        try:
            return d[k]
        except KeyError:
            return None

    def reraise(x):
        try:
            return 10 // x
        except ZeroDivisionError as ex:
            raise ValueError("zero divisor") from ex

    def cold(x):
        try:
            return x + 1
        except TypeError:
            return -1

    try:
        import _ec_no_such_module_
    except ImportError:
        HAVE_OPT = False
""")


def _mk_pkg(tmp_path, pkgname, src=PROBE_SRC):
    """A throwaway importable package holding the probe module."""
    d = tmp_path / pkgname
    d.mkdir()
    (d / "__init__.py").write_text("")
    (d / "mod.py").write_text(src)
    return d


@pytest.fixture
def probe(tmp_path, monkeypatch, request):
    """Import <unique pkg>.mod under the (already conftest-armed)
    hook, widened to the temp package; cleaned out of sys.modules."""
    pkg = f"ec_probe_{request.function.__name__}"
    _mk_pkg(tmp_path, pkg)
    monkeypatch.syspath_prepend(str(tmp_path))
    assert errcheck.enabled(), "conftest arms CEPH_TPU_ERRCHECK=1"
    errcheck.enable(prefixes=(pkg,))    # idempotent widen, not reinstall
    mod = __import__(f"{pkg}.mod", fromlist=["mod"])
    yield pkg, mod
    for name in [m for m in sys.modules if m.split(".")[0] == pkg]:
        del sys.modules[name]


def _probe_counts(pkg):
    return {(m, ln, exc): n for (m, ln, exc), n in
            errcheck.counters().items() if m.startswith(pkg)}


# ---------------------------------------------------- fired counting

def test_fired_handlers_counted_by_exception_type(probe):
    pkg, mod = probe
    assert mod.lookup({"a": 1}, "a") == 1       # no exception: no bump
    assert _probe_counts(pkg) == {
        (f"{pkg}.mod", 21, "ModuleNotFoundError"): 1}
        # ^ the CONCRETE type from exc_info, not the declared ImportError
    assert mod.lookup({}, "x") is None
    assert mod.lookup({}, "y") is None
    with pytest.raises(ValueError):
        mod.reraise(0)
    c = _probe_counts(pkg)
    assert c[(f"{pkg}.mod", 4, "KeyError")] == 2
    assert c[(f"{pkg}.mod", 10, "ZeroDivisionError")] == 1
    # the cold handler exists but never fired — no key at its line
    assert not any(ln == 16 for (_m, ln, _e) in c)


def test_module_level_handler_counts_during_import(probe):
    """Import-fallback handlers run while exec_module is still on the
    stack — the hook global must be seeded BEFORE the body runs."""
    pkg, mod = probe
    assert mod.HAVE_OPT is False
    assert _probe_counts(pkg)[
        (f"{pkg}.mod", 21, "ModuleNotFoundError")] == 1


# ------------------------------------------------- never-fired report

def test_coverage_report_shape_and_never_fired(probe, tmp_path):
    pkg, mod = probe
    mod.lookup({}, "x")
    rep = errcheck.coverage_report(str(tmp_path / pkg), package=pkg)
    assert rep["package"] == pkg
    assert rep["handlers_total"] == 4
    # KeyError handler + the import-time ImportError fallback fired
    assert rep["handlers_fired"] == 2
    assert rep["never_fired_count"] == 2
    assert rep["handlers_fired"] + rep["never_fired_count"] == \
        rep["handlers_total"]
    st = rep["modules"][f"{pkg}.mod"]
    assert st == {"handlers": 4, "fired": 2, "ratio": 0.5}
    cold = {(d["module"], d["line"], d["catches"])
            for d in rep["never_fired"]}
    assert cold == {(f"{pkg}.mod", 10, "ZeroDivisionError"),
                    (f"{pkg}.mod", 16, "TypeError")}


def test_census_counts_unimported_modules(tmp_path):
    """The denominator is static: a module nothing imported still
    contributes its handlers (that is the whole point — dead error
    paths hide in exactly the code no test pulls in)."""
    d = _mk_pkg(tmp_path, "ec_cold_pkg")
    (d / "never_imported.py").write_text(PROBE_SRC)
    census = errcheck.handler_census(str(d), package="ec_cold_pkg")
    mods = {m for m, _ln, _c in census}
    assert "ec_cold_pkg.never_imported" in mods
    assert len([1 for m, *_ in census
                if m == "ec_cold_pkg.never_imported"]) == 4


# -------------------------------------------- semantics are unchanged

def test_instrumentation_preserves_semantics(probe):
    pkg, mod = probe
    # values, exception chaining and tracebacks all pristine
    with pytest.raises(ValueError) as ei:
        mod.reraise(0)
    assert isinstance(ei.value.__cause__, ZeroDivisionError)
    assert ei.traceback[-1].lineno + 1 == 11   # the raise, untouched
    assert mod.cold(5) == 6
    # uncaught exceptions still propagate untouched
    with pytest.raises(TypeError):
        mod.lookup(None, "k")


def test_syntax_error_modules_fail_like_pristine(probe, tmp_path):
    pkg, _mod = probe
    (tmp_path / pkg / "broken.py").write_text("def f(:\n")
    with pytest.raises(SyntaxError):
        __import__(f"{pkg}.broken")


# ------------------------------------------- subprocess counter dumps

def test_dump_and_merge_dir_roundtrip(probe, tmp_path):
    pkg, mod = probe
    mod.lookup({}, "x")
    path = tmp_path / "dumps" / "errcheck-12345.json"
    errcheck.dump(str(path))
    raw = json.loads(path.read_text())
    assert raw[f"{pkg}.mod\x004\x00KeyError"] == 1
    merged = errcheck.merge_dir(str(tmp_path / "dumps"))
    # live counters + the dump of the same counters = doubled
    assert merged[(f"{pkg}.mod", 4, "KeyError")] == 2
    # junk files are skipped, not fatal
    (tmp_path / "dumps" / "errcheck-junk.json").write_text("{nope")
    merged2 = errcheck.merge_dir(str(tmp_path / "dumps"))
    assert merged2[(f"{pkg}.mod", 4, "KeyError")] == 2


# --------------------------------------------- zero-overhead when off

def test_off_means_no_hook_no_globals_no_counters(tmp_path):
    """With CEPH_TPU_ERRCHECK unset, importing errcheck and a real
    ceph_tpu module must leave the import machinery pristine: no
    finder on sys.meta_path, no __errcheck_hit__ in module dicts, no
    counters.  Run in a subprocess — this suite's own interpreter is
    deliberately armed by conftest."""
    code = textwrap.dedent("""\
        import sys
        from ceph_tpu.common import errcheck
        assert not errcheck.enable_if_configured()
        assert not errcheck.enabled()
        assert not any(type(f).__module__ == "ceph_tpu.common.errcheck"
                       for f in sys.meta_path), sys.meta_path
        from ceph_tpu.common import backoff
        assert errcheck.HIT_NAME not in vars(backoff)
        assert errcheck.counters() == {}
        print("PRISTINE")
    """)
    import os
    env = dict(os.environ)
    env.pop("CEPH_TPU_ERRCHECK", None)
    env.pop("CEPH_TPU_ERRCHECK_DIR", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "PRISTINE" in out.stdout
