"""Crushmap text compiler/decompiler — the crushtool `-c`/`-d` codec.

Reads and writes the reference's text crushmap grammar
(ref: src/crush/CrushCompiler.{h,cc}: decompile :108-417, parse_*
:418-1080; golden format examples: src/test/cli/crushtool/*.txt):

    # begin crush map
    tunable choose_total_tries 50
    device 0 osd.0 [class ssd]
    type 1 host
    <type> <name> { id -N  alg straw2  hash 0  item <name> weight F }
    rule <name> { id N  type replicated|erasure  min_size/max_size
                  step take <name> / choose|chooseleaf firstn|indep N
                  type <t> / set_* N / emit }
    # end crush map

Decompile is canonical: compile(decompile(w)) reproduces the same map,
and decompile(compile(text)) is a fixed point — the property the
reference pins with compile-decompile-recompile.t.
"""
from __future__ import annotations

from .types import (CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW,
                    CRUSH_BUCKET_STRAW2, CRUSH_BUCKET_TREE,
                    CRUSH_BUCKET_UNIFORM, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
                    CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
                    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
                    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
                    CRUSH_RULE_SET_CHOOSELEAF_STABLE,
                    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
                    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                    CRUSH_RULE_SET_CHOOSE_TRIES, CRUSH_RULE_TAKE,
                    CrushBucket, CrushRule, CrushRuleMask, CrushRuleStep)
from .wrapper import RULE_TYPE_ERASURE, RULE_TYPE_REPLICATED, CrushWrapper

ALG_NAMES = {CRUSH_BUCKET_UNIFORM: "uniform", CRUSH_BUCKET_LIST: "list",
             CRUSH_BUCKET_TREE: "tree", CRUSH_BUCKET_STRAW: "straw",
             CRUSH_BUCKET_STRAW2: "straw2"}
ALG_IDS = {v: k for k, v in ALG_NAMES.items()}

SET_STEPS = {
    CRUSH_RULE_SET_CHOOSE_TRIES: "set_choose_tries",
    CRUSH_RULE_SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries",
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES: "set_choose_local_tries",
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
        "set_choose_local_fallback_tries",
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R: "set_chooseleaf_vary_r",
    CRUSH_RULE_SET_CHOOSELEAF_STABLE: "set_chooseleaf_stable",
}
SET_STEP_IDS = {v: k for k, v in SET_STEPS.items()}

# legacy (argonaut) values: tunables are emitted only when they differ
# (ref: CrushCompiler.cc decompile :129-156)
LEGACY_TUNABLES = {"choose_local_tries": 2,
                   "choose_local_fallback_tries": 5,
                   "choose_total_tries": 19,
                   "chooseleaf_descend_once": 0,
                   "chooseleaf_vary_r": 0,
                   "chooseleaf_stable": 0,
                   "straw_calc_version": 0}


class CompileError(ValueError):
    pass


# ---------------------------------------------------------------- decompile
def _wf(w16: int) -> str:
    return f"{w16 / 0x10000:.3f}"


def decompile(w: CrushWrapper) -> str:
    """(ref: CrushCompiler.cc:338 decompile)."""
    c = w.crush
    out = ["# begin crush map"]
    for name, legacy in LEGACY_TUNABLES.items():
        val = getattr(c, name)
        if val != legacy:
            out.append(f"tunable {name} {val}")
    out += ["", "# devices"]
    for dev in range(c.max_devices):
        name = w.name_map.get(dev, f"device{dev}")
        cls = w.class_map.get(dev)
        suffix = f" class {w.class_name[cls]}" if cls is not None else ""
        out.append(f"device {dev} {name}{suffix}")
    out += ["", "# types"]
    for tid in sorted(w.type_map):
        out.append(f"type {tid} {w.type_map[tid]}")
    out += ["", "# buckets"]
    emitted: set[int] = set()

    def emit_bucket(bid: int) -> None:
        b = c.bucket(bid)
        if b is None or bid in emitted:
            return
        for child in b.items:
            if child < 0:
                emit_bucket(child)
        emitted.add(bid)
        tname = w.type_map.get(b.type, str(b.type))
        name = w.name_map.get(bid, f"bucket{-1 - bid}")
        out.append(f"{tname} {name} {{")
        out.append(f"\tid {bid}\t\t# do not change unnecessarily")
        out.append(f"\t# weight {_wf(b.weight)}")
        out.append(f"\talg {ALG_NAMES.get(b.alg, str(b.alg))}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for item, iw in zip(b.items, b.item_weights):
            iname = w.name_map.get(item, f"device{item}" if item >= 0
                                   else f"bucket{-1 - item}")
            out.append(f"\titem {iname} weight {_wf(iw)}")
        out.append("}")

    for b in c.buckets:
        if b is not None:
            emit_bucket(b.id)
    out += ["", "# rules"]
    for rid, rule in enumerate(c.rules):
        if rule is None:
            continue
        name = w.rule_name_map.get(rid, f"rule{rid}")
        out.append(f"rule {name} {{")
        out.append(f"\tid {rule.mask.ruleset}")
        rtype = "replicated" if rule.mask.type == RULE_TYPE_REPLICATED \
            else "erasure" if rule.mask.type == RULE_TYPE_ERASURE \
            else str(rule.mask.type)
        out.append(f"\ttype {rtype}")
        out.append(f"\tmin_size {rule.mask.min_size}")
        out.append(f"\tmax_size {rule.mask.max_size}")
        for s in rule.steps:
            if s.op == CRUSH_RULE_TAKE:
                tn = w.name_map.get(s.arg1, str(s.arg1))
                out.append(f"\tstep take {tn}")
            elif s.op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
                          CRUSH_RULE_CHOOSELEAF_FIRSTN,
                          CRUSH_RULE_CHOOSELEAF_INDEP):
                verb = "choose" if s.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                            CRUSH_RULE_CHOOSE_INDEP) \
                    else "chooseleaf"
                mode = "firstn" if s.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                            CRUSH_RULE_CHOOSELEAF_FIRSTN) \
                    else "indep"
                tname = w.type_map.get(s.arg2, str(s.arg2))
                out.append(f"\tstep {verb} {mode} {s.arg1} type {tname}")
            elif s.op in SET_STEPS:
                out.append(f"\tstep {SET_STEPS[s.op]} {s.arg1}")
            elif s.op == CRUSH_RULE_EMIT:
                out.append("\tstep emit")
            else:
                raise CompileError(f"cannot decompile step op {s.op}")
        out.append("}")
        out.append("")
    if out[-1] == "":
        out.pop()
    out += ["", "# end crush map"]
    return "\n".join(out) + "\n"


# ------------------------------------------------------------------ compile
def _tokens(text: str):
    """Strip comments, split into per-line token lists."""
    for line in text.splitlines():
        line = line.split("#", 1)[0]
        # brace on its own or trailing: tokenize with spaces
        line = line.replace("{", " { ").replace("}", " } ")
        toks = line.split()
        if toks:
            yield toks


def compile_crushmap(text: str) -> CrushWrapper:
    """(ref: CrushCompiler.cc:1090 compile; grammar CrushCompiler.h)."""
    w = CrushWrapper()
    w.type_map = {}
    lines = list(_tokens(text))
    i = 0
    # O(1) name lookups (get_item_id scans; a 10k-device map would be
    # quadratic through it)
    item_ids: dict[str, int] = {}

    def parse_bucket(head, body):
        tname, name = head[0], head[1]
        tid = w.get_type_id(tname)
        if tid < 0:
            raise CompileError(f"unknown bucket type {tname!r}")
        if name in item_ids:
            raise CompileError(f"duplicate name {name!r}")
        bid = None
        alg = CRUSH_BUCKET_STRAW2
        hash_ = 0
        items: list[tuple[int, int]] = []
        for toks in body:
            if toks[0] == "id":
                bid = int(toks[1])
                if bid >= 0:
                    raise CompileError("bucket id must be negative")
                if w.crush.bucket(bid) is not None:
                    raise CompileError(f"duplicate bucket id {bid}")
            elif toks[0] == "alg":
                if toks[1] not in ALG_IDS:
                    raise CompileError(f"unknown alg {toks[1]!r}")
                alg = ALG_IDS[toks[1]]
            elif toks[0] == "hash":
                hash_ = int(toks[1])
            elif toks[0] == "item":
                iname = toks[1]
                iid = item_ids.get(iname)
                if iid is None:
                    raise CompileError(f"item {iname!r} not defined")
                weight = 0x10000
                j = 2
                while j < len(toks):
                    if toks[j] == "weight":
                        weight = int(round(float(toks[j + 1]) * 0x10000))
                        j += 2
                    elif toks[j] == "pos":
                        j += 2  # positions implied by order
                    else:
                        raise CompileError(
                            f"bad item modifier {toks[j]!r}")
                items.append((iid, weight))
            else:
                raise CompileError(f"bad bucket line {' '.join(toks)!r}")
        b = CrushBucket(id=bid if bid is not None else 0, type=tid,
                        alg=alg, hash=hash_,
                        items=[it for it, _ in items],
                        item_weights=[iw for _, iw in items],
                        weight=sum(iw for _, iw in items))
        bid = w.crush.add_bucket(b)
        w.name_map[bid] = name
        item_ids[name] = bid

    def parse_rule(head, body):
        name = head[0]
        mask = CrushRuleMask()
        steps: list[CrushRuleStep] = []
        rid = None
        for toks in body:
            if toks[0] in ("id", "ruleset"):      # pre-luminous synonym
                rid = int(toks[1])
                mask.ruleset = rid
            elif toks[0] == "type":
                mask.type = {"replicated": RULE_TYPE_REPLICATED,
                             "erasure": RULE_TYPE_ERASURE}.get(
                    toks[1], int(toks[1]) if toks[1].isdigit() else None)
                if mask.type is None:
                    raise CompileError(f"bad rule type {toks[1]!r}")
            elif toks[0] == "min_size":
                mask.min_size = int(toks[1])
            elif toks[0] == "max_size":
                mask.max_size = int(toks[1])
            elif toks[0] == "step":
                verb = toks[1]
                if verb == "take":
                    item = item_ids.get(toks[2])
                    if item is None:
                        raise CompileError(
                            f"step take: unknown item {toks[2]!r}")
                    steps.append(CrushRuleStep(CRUSH_RULE_TAKE, item, 0))
                elif verb in ("choose", "chooseleaf"):
                    mode = toks[2]
                    num = int(toks[3])
                    if toks[4] != "type":
                        raise CompileError("expected 'type'")
                    tid = w.get_type_id(toks[5])
                    if tid < 0:
                        raise CompileError(
                            f"unknown type {toks[5]!r}")
                    op = {
                        ("choose", "firstn"): CRUSH_RULE_CHOOSE_FIRSTN,
                        ("choose", "indep"): CRUSH_RULE_CHOOSE_INDEP,
                        ("chooseleaf", "firstn"):
                            CRUSH_RULE_CHOOSELEAF_FIRSTN,
                        ("chooseleaf", "indep"):
                            CRUSH_RULE_CHOOSELEAF_INDEP,
                    }.get((verb, mode))
                    if op is None:
                        raise CompileError(f"bad mode {mode!r}")
                    steps.append(CrushRuleStep(op, num, tid))
                elif verb in SET_STEP_IDS:
                    steps.append(CrushRuleStep(SET_STEP_IDS[verb],
                                               int(toks[2]), 0))
                elif verb == "emit":
                    steps.append(CrushRuleStep(CRUSH_RULE_EMIT))
                else:
                    raise CompileError(f"unknown step {verb!r}")
            else:
                raise CompileError(f"bad rule line {' '.join(toks)!r}")
        rule = CrushRule(steps=steps, mask=mask)
        if rid is None:
            rid = len(w.crush.rules)
            mask.ruleset = rid
        while len(w.crush.rules) <= rid:
            w.crush.rules.append(None)
        if w.crush.rules[rid] is not None:
            raise CompileError(f"duplicate rule id {rid}")
        w.crush.rules[rid] = rule
        w.rule_name_map[rid] = name

    while i < len(lines):
        toks = lines[i]
        if toks[0] == "tunable":
            if toks[1] not in LEGACY_TUNABLES:
                raise CompileError(f"unknown tunable {toks[1]!r}")
            setattr(w.crush, toks[1], int(toks[2]))
            i += 1
        elif toks[0] == "device":
            dev = int(toks[1])
            name = toks[2]
            w.name_map[dev] = name
            item_ids[name] = dev
            w.crush.max_devices = max(w.crush.max_devices, dev + 1)
            if len(toks) >= 5 and toks[3] == "class":
                w.class_map[dev] = w.class_id_or_create(toks[4])
            i += 1
        elif toks[0] == "type":
            w.type_map[int(toks[1])] = toks[2]
            i += 1
        elif toks[0] == "rule" or (len(toks) >= 3 and toks[2] == "{") or \
                (len(toks) >= 2 and toks[-1] == "{"):
            # block: rule <name> { ... }  or  <type> <name> { ... }
            is_rule = toks[0] == "rule"
            head = toks[1:2] if is_rule else toks[0:2]
            body = []
            if toks[-1] != "{":
                raise CompileError(f"expected '{{' in {' '.join(toks)!r}")
            i += 1
            while i < len(lines) and lines[i] != ["}"]:
                body.append(lines[i])
                i += 1
            if i >= len(lines):
                raise CompileError("unterminated block")
            i += 1  # consume }
            if is_rule:
                parse_rule(head, body)
            else:
                parse_bucket(head, body)
        else:
            raise CompileError(f"cannot parse {' '.join(toks)!r}")
    if not w.type_map:
        raise CompileError("no types defined")
    return w
