"""Shared daemon observability surface: the op-tracker + tracer admin
commands every daemon type serves.

The reference registers dump_ops_in_flight / dump_historic_ops /
dump_historic_slow_ops / dump_blocked_ops on each daemon's admin
socket from the shared OpTracker (ref: TrackedOp.cc
OpTracker::register_commands style hookup in OSD.cc / MDSDaemon.cc /
rgw_main.cc); `dump_traces` serves the daemon's blkin span ring.  One
helper, so mon/mgr/mds/rgw get an identical surface to the OSD's.
"""
from __future__ import annotations

from .admin_socket import AdminSocket
from .tracing import Tracer
from .tracked_op import OpTracker


def register_obs_commands(asok: AdminSocket, tracker: OpTracker,
                          tracer: Tracer) -> None:
    asok.register("dump_ops_in_flight", "ops currently executing",
                  lambda c: (0, tracker.dump_in_flight()))
    asok.register("dump_historic_ops", "recently completed ops",
                  lambda c: (0, tracker.dump_historic()))
    asok.register("dump_historic_slow_ops",
                  "recently completed ops over the complaint age",
                  lambda c: (0, tracker.dump_historic_slow()))
    asok.register("dump_blocked_ops", "ops over the complaint age",
                  lambda c: (0, tracker.slow_ops()))
    asok.register("dump_traces", "finished blkin spans "
                  "(optionally trace_id=...)",
                  lambda c: (0, tracer.dump(c.get("trace_id"))))
