"""ConfigMonitor: centralized configuration replicated through the mon
quorum (ref: src/mon/ConfigMonitor.cc; ConfigMap sections
src/mon/ConfigMap.h).

`config set/rm` stage into a pending change list, commit through paxos
like any map mutation, and push to subscribed daemons as MConfig —
the reference's `ceph config set osd.3 debug_osd 10` flow
(ConfigMonitor::prepare_command -> encode_pending ->
check_all_subs/send_config).

Sections: "global", a daemon type ("osd", "mon", "client"), or a
specific entity ("osd.3") — most-specific wins at the daemon
(ConfigMap::generate_entity_map precedence).
"""
from __future__ import annotations


from ..msg import encoding as wire
from .paxos import Paxos, PaxosService
from .store import StoreTransaction

_ENOENT, _EINVAL = 2, 22


class ConfigMonitor(PaxosService):
    """(ref: src/mon/ConfigMonitor.h:13)."""

    def __init__(self, paxos: Paxos):
        super().__init__("config", paxos)
        #: committed state: section -> {option: value}
        self.config: dict[str, dict[str, str]] = {}
        #: staged deltas: list of (section, name, value|None)
        self.pending: list[tuple] = []

    # ------------------------------------------------------- paxos hooks
    def create_initial(self) -> None:
        self.pending = []
        self._bootstrap = True

    def encode_pending(self, tx: StoreTransaction) -> None:
        if getattr(self, "_bootstrap", False):
            self._bootstrap = False
            self.put_version(tx, "v_1", wire.encode({}))
            self.put_version(tx, "last_committed", 1)
            self.put_version(tx, "first_committed", 1)
            return
        if not self.pending:
            return
        new = {k: dict(v) for k, v in self.config.items()}
        for section, name, value in self.pending:
            if value is None:
                new.get(section, {}).pop(name, None)
                if not new.get(section):
                    new.pop(section, None)
            else:
                new.setdefault(section, {})[name] = str(value)
        e = self.get_last_committed() + 1
        self.put_version(tx, f"v_{e}", wire.encode(new))
        self.put_version(tx, "last_committed", e)

    def update_from_paxos(self) -> None:
        e = self.get_last_committed()
        if e:
            blob = self.get_version(f"v_{e}")
            if blob is not None:
                self.config = wire.decode(blob)

    def create_pending(self) -> None:
        self.pending = []

    def _is_pending_empty(self) -> bool:
        return not self.pending

    # -------------------------------------------------------- commands
    def preprocess_command(self, cmdmap: dict):
        """Read-only commands answered from committed state; None
        means a write that must stage (ref: ConfigMonitor.cc
        preprocess_command)."""
        prefix = cmdmap.get("prefix", "")
        if prefix == "config dump":
            return 0, "", {k: dict(v)
                           for k, v in sorted(self.config.items())}
        if prefix == "config get":
            who = cmdmap["who"]
            name = cmdmap.get("name") or cmdmap.get("key")
            merged = self.entity_config(who)
            if name:
                if name not in merged:
                    return -_ENOENT, f"{name} not set for {who}", None
                return 0, "", merged[name]
            return 0, "", merged
        if prefix in ("config set", "config rm"):
            if not cmdmap.get("who") or not (
                    cmdmap.get("name") or cmdmap.get("key")):
                return -_EINVAL, "usage: config set <who> <name> " \
                    "<value>", None
            return None                     # stage it
        return None if prefix.startswith("config") else NotImplemented

    def prepare_command(self, cmdmap: dict):
        """(ref: ConfigMonitor.cc prepare_command)."""
        prefix = cmdmap.get("prefix", "")
        who = cmdmap["who"]
        name = cmdmap.get("name") or cmdmap.get("key")
        if prefix == "config set":
            if "value" not in cmdmap:
                return -_EINVAL, "missing value", None
            self.pending.append((who, name, str(cmdmap["value"])))
            return 0, f"set {who}/{name}", None
        if prefix == "config rm":
            self.pending.append((who, name, None))
            return 0, f"removed {who}/{name}", None
        return -_EINVAL, f"unknown config command {prefix}", None

    # ----------------------------------------------------- entity view
    def entity_config(self, entity: str) -> dict[str, str]:
        """Merged options for one daemon, least- to most-specific:
        global < type < entity (ref: ConfigMap::generate_entity_map)."""
        out: dict[str, str] = {}
        etype = entity.split(".", 1)[0]
        for section in ("global", etype, entity):
            out.update(self.config.get(section, {}))
        return out
