"""MDS standby/failover: FSMap + MDSMonitor beacons, rank takeover
with journal replay, client reconnect + cap recovery, thrashing
(tentpole PR; ref: src/mon/MDSMonitor.cc, src/mds/FSMap.h, the
standby-replay daemon states, and qa/tasks/mds_thrash.py)."""
import threading
import time

import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.fs import CephFS
from ceph_tpu.fs.mds import CAP_EXCL
from ceph_tpu.msg.messages import MClientReply
from ceph_tpu.testing import MiniCluster
from ceph_tpu.testing.thrasher import MDSThrasher

FAST = {"mds_beacon_interval": 0.2, "mds_beacon_grace": 1.0}


@pytest.fixture(autouse=True)
def fast_beacons():
    g = global_config()
    saved = {k: g[k] for k in FAST}
    for k, v in FAST.items():
        g.set(k, v)
    yield
    for k, v in saved.items():
        g.set(k, v)


def drive_failover(c, th, rank, timeout_rounds=40):
    """Tick simulated time until the rank is active again."""
    th.wait_takeover(rank, timeout_rounds=timeout_rounds)


@pytest.fixture()
def cluster():
    c = MiniCluster(n_osd=3, threaded=True)
    c.wait_all_up()
    yield c
    c.shutdown()


# ----------------------------------------------------- fsmap / beacons

def test_fsmap_registration_and_status(cluster):
    c = cluster
    c.start_mds(0)
    c.start_mds_standby()
    c.wait_mds_active(0)
    m = c.fsmap()
    assert m.ranks[0].state == "active"
    assert m.ranks[0].gid
    # the standby registered in the pool
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not c.fsmap().standbys:
        time.sleep(0.05)
    assert c.fsmap().standbys
    # `fs status` through the mon command path
    r, outs, outb = c.mon.handle_command({"prefix": "fs status"})
    assert r == 0
    assert outb["ranks"]["0" if "0" in outb["ranks"] else 0][
        "state"] == "active"
    assert len(outb["standbys"]) == 1


def test_kill_active_rank_promotes_standby(cluster):
    """The acceptance scenario, single rank: kill the active MDS
    under data, standby promotes through replay to active, clients
    continue without error."""
    c = cluster
    c.start_mds(0)
    c.start_mds_standby()
    c.wait_mds_active(0)
    fs = CephFS(c.rados())
    fs.mkdirs("/d/deep")
    for i in range(12):
        fs.write_file(f"/d/deep/f{i}", f"payload-{i}".encode())
    old_gid = c.fsmap().ranks[0].gid
    th = MDSThrasher(c)
    th.kill_rank(0)
    drive_failover(c, th, 0)
    assert c.fsmap().ranks[0].gid != old_gid
    # namespace intact (journal tail replayed), new writes work
    for i in range(12):
        assert fs.read_file(f"/d/deep/f{i}") == f"payload-{i}".encode()
    fs.write_file("/d/after", b"post-takeover")
    assert fs.read_file("/d/after") == b"post-takeover"
    assert fs.wait_rank_active(0, timeout=10)


def test_inflight_op_replayed_exactly_once(cluster):
    """An op whose reply died with the MDS is replayed by the client
    and answered from the promoted rank's completed-request table —
    not re-executed (ref: Session::completed_requests)."""
    c = cluster
    c.start_mds(0)
    c.start_mds_standby()
    c.wait_mds_active(0)
    fs = CephFS(c.rados())
    fs.mkdirs("/base")
    # drop every MClientReply the active rank sends: the op lands in
    # the journal + completed table but the client never hears
    c.network.filter = lambda src, dst, msg: not (
        src == "mds.0" and isinstance(msg, MClientReply))
    result, errors = [], []

    def worker():
        try:
            result.append(fs._session.call(
                "mkdir", {"path": "/base/dropped"}, timeout=60.0))
        except Exception as ex:      # noqa: BLE001
            errors.append(ex)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    # wait until the (mute) MDS has applied the mkdir
    meta = c.rados().open_ioctx("cephfs_metadata")
    root_ino_obj = "dir.1"
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        vals, _ = meta.get_omap_vals(root_ino_obj)
        if "base" in vals:
            base = __import__("json").loads(vals["base"])
            sub, _ = meta.get_omap_vals(f"dir.{base['ino']:x}")
            if "dropped" in sub:
                break
        time.sleep(0.05)
    th = MDSThrasher(c)
    th.kill_rank(0)
    c.network.filter = None
    drive_failover(c, th, 0)
    t.join(timeout=60)
    assert not t.is_alive(), "replayed op never completed"
    assert not errors, errors
    assert result and result[0]["type"] == "d"
    # exactly one directory, visible through the new rank
    assert fs.listdir("/base") == ["dropped"]


def test_client_cap_recovery_after_reconnect(cluster):
    """Caps die with the old daemon's session state; the fsmap push
    triggers a client reconnect that re-acquires them through the new
    rank (ref: the MDS reconnect phase)."""
    c = cluster
    c.start_mds(0)
    c.start_mds_standby()
    c.wait_mds_active(0)
    fs = CephFS(c.rados())
    fh = fs.open("/capfile", "w")
    assert fh.caps & CAP_EXCL
    fh.write(0, b"A" * 2048)
    th = MDSThrasher(c)
    th.kill_rank(0)
    drive_failover(c, th, 0)
    # the reconnect runs off the fsmap push: wait for cap re-grant
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not fh.caps & CAP_EXCL:
        time.sleep(0.05)
    assert fh.caps & CAP_EXCL, "caps never recovered after failover"
    fh.write(2048, b"B" * 100)
    fh.close()
    assert fs.read_file("/capfile") == b"A" * 2048 + b"B" * 100


def test_multi_mds_rank_failover_under_pins(cluster):
    """Kill one rank of a multi-MDS cluster: only that rank fails
    over; the surviving rank and its pinned subtree never blink."""
    c = cluster
    c.start_mds(0)
    c.start_mds(1)
    c.start_mds_standby()
    c.wait_mds_active(0)
    c.wait_mds_active(1)
    fs = CephFS(c.rados())
    fs.mkdirs("/t0")
    fs.mkdirs("/t1")
    fs.set_pin("/t1", 1)
    fs.write_file("/t0/a", b"rank0")
    fs.write_file("/t1/a", b"rank1")
    gid0 = c.fsmap().ranks[0].gid
    th = MDSThrasher(c)
    th.kill_rank(1)
    drive_failover(c, th, 1)
    # rank 0 untouched, rank 1 took over and serves its subtree
    assert c.fsmap().ranks[0].gid == gid0
    assert fs.read_file("/t1/a") == b"rank1"
    fs.write_file("/t1/b", b"post-failover")
    assert fs.read_file("/t1/b") == b"post-failover"
    assert fs.read_file("/t0/a") == b"rank0"


def test_standby_replay_warm_takeover(cluster):
    """A standby-replay follower tails the target rank's journal
    while standing by, then takes over (ref: the standby-replay
    daemon state)."""
    g = global_config()
    g.set("mds_standby_replay", True)
    try:
        c = cluster
        c.start_mds(0)
        sb = c.start_mds_standby(standby_replay_rank=0)
        c.wait_mds_active(0)
        fs = CephFS(c.rados())
        fs.mkdirs("/warm")
        for i in range(10):
            fs.write_file(f"/warm/f{i}", b"x" * 32)
        # the follower observed journal entries while standby
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and sb.tailed == 0:
            time.sleep(0.1)
        assert sb.tailed > 0, "standby-replay never tailed the journal"
        th = MDSThrasher(c)
        th.kill_rank(0)
        drive_failover(c, th, 0)
        assert sb.active is not None and sb.rank == 0
        assert fs.read_file("/warm/f3") == b"x" * 32
    finally:
        g.set("mds_standby_replay", False)


def test_beacon_mute_marks_rank_failed_then_rejoin(cluster):
    """Beacon-lapse detection via muting (the heartbeat_inject_failure
    analogue): a muted-but-alive rank is marked failed; un-muting
    re-registers it (no standby in the pool, so no split brain)."""
    c = cluster
    d = c.start_mds(0)
    c.wait_mds_active(0)
    d.inject_beacon_mute = True
    th = MDSThrasher(c)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and \
            c.fsmap().ranks[0].state != "failed":
        th.tick_grace(1)
    assert c.fsmap().ranks[0].state == "failed"
    # un-mute: the daemon's next beacon reclaims the vacant rank
    d.inject_beacon_mute = False
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and \
            c.fsmap().ranks[0].state != "active":
        time.sleep(0.1)
    assert c.fsmap().ranks[0].state == "active"
    assert c.fsmap().ranks[0].gid == d.gid


def test_mds_thrasher_repeated_kill_revive_under_load(cluster):
    """The thrasher drives repeated kill/promote cycles over a live
    multi-MDS cluster with client metadata load between kills."""
    c = cluster
    c.start_mds(0)
    c.start_mds(1)
    c.start_mds_standby()
    c.wait_mds_active(0)
    c.wait_mds_active(1)
    fs = CephFS(c.rados())
    fs.mkdirs("/load0")
    fs.mkdirs("/load1")
    fs.set_pin("/load1", 1)
    th = MDSThrasher(c, seed=7)

    def between(i):
        for j in range(3):
            fs.write_file(f"/load0/r{i}-{j}", f"{i}:{j}".encode())
            fs.write_file(f"/load1/r{i}-{j}", f"{i}:{j}".encode())

    th.do_thrash(3, between=between)
    # every write from every round is durable and readable
    for i in range(3):
        for j in range(3):
            want = f"{i}:{j}".encode()
            assert fs.read_file(f"/load0/r{i}-{j}") == want
            assert fs.read_file(f"/load1/r{i}-{j}") == want
    assert th.log, th.log


# ------------------------------------------------------------ TCP E2E

def test_tcp_mds_kill_failover():
    """The same scenario over real sockets: mon + osds + mds +
    standby each on its own TCP endpoint, kill the active rank, the
    standby takes over and the client continues."""
    from ceph_tpu.client import Rados
    from ceph_tpu.fs import MDSDaemon, MDSStandby
    from ceph_tpu.mon.monitor import Monitor, build_initial
    from ceph_tpu.msg.tcp import TcpNet, pick_free_ports
    from ceph_tpu.osd.daemon import OSDDaemon

    names = ["mon.0", "osd.0", "osd.1", "mds.0", "mds.sb1", "mds.sb2",
             "client.950", "client.951", "client.952", "client.953"]
    ports = pick_free_ports(len(names))
    net = TcpNet({n: ("127.0.0.1", p) for n, p in zip(names, ports)})
    m, w = build_initial(2, osds_per_host=1)
    mon = Monitor(net, initial_map=m, initial_wrapper=w)
    mon.init()
    osds = [OSDDaemon(net, i) for i in range(2)]
    for d in osds:
        d.init()
    r_mds = Rados(net, name="client.951").connect(20.0)
    r_sb = Rados(net, name="client.952").connect(20.0)
    r_cl = Rados(net, name="client.950").connect(20.0)
    mds = MDSDaemon(net, r_mds, rank=0, mon="mon.0")
    mds.init()
    sb = MDSStandby(net, r_sb, name="sb1", mon="mon.0")
    sb.init()
    fs = CephFS(r_cl)
    try:
        # mon ticks on the real clock over TCP
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                mon.mdsmon.fsmap.rank_state(0) != "active":
            mon.tick()
            time.sleep(0.1)
        assert mon.mdsmon.fsmap.rank_state(0) == "active"
        fs.mkdirs("/tcp")
        fs.write_file("/tcp/f", b"over sockets")
        mds.kill()
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            mon.tick()
            time.sleep(0.1)
            info = mon.mdsmon.fsmap.ranks.get(0)
            if info is not None and info.state == "active" and \
                    info.gid == sb.gid:
                break
        assert mon.mdsmon.fsmap.ranks[0].gid == sb.gid
        assert fs.read_file("/tcp/f") == b"over sockets"
        fs.write_file("/tcp/g", b"post-kill")
        assert fs.read_file("/tcp/g") == b"post-kill"
        # second kill/revive cycle: a fresh standby joins, the
        # promoted daemon dies, the cycle repeats over the same wire
        r_sb2 = Rados(net, name="client.953").connect(20.0)
        sb2 = MDSStandby(net, r_sb2, name="sb2", mon="mon.0")
        sb2.init()
        sb.kill()
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            mon.tick()
            time.sleep(0.1)
            info = mon.mdsmon.fsmap.ranks.get(0)
            if info is not None and info.state == "active" and \
                    info.gid == sb2.gid:
                break
        assert mon.mdsmon.fsmap.ranks[0].gid == sb2.gid
        assert fs.read_file("/tcp/g") == b"post-kill"
        fs.write_file("/tcp/h", b"second cycle")
        assert fs.read_file("/tcp/h") == b"second cycle"
        sb2.kill()
        r_sb2.shutdown()
    finally:
        sb.kill()
        if not mds.stopped:
            mds.kill()
        for c in (r_cl, r_mds, r_sb):
            c.shutdown()
        for d in osds:
            d.shutdown()
        mon.shutdown()
