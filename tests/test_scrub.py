"""PG scrub: replica/shard consistency detection + repair
(ref: src/osd/scrubber/pg_scrubber.cc, PrimaryLogPG be_select_auth_
object / be_compare_scrubmaps, ECBackend be_deep_scrub)."""
import numpy as np
import pytest

from ceph_tpu.osd.types import PG
from ceph_tpu.store import ObjectId
from ceph_tpu.testing import MiniCluster


def locate(c, r, pool_name, oid):
    pid = r.pool_lookup(pool_name)
    m = c.mon.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    return pid, pg, acting, primary


@pytest.fixture()
def cluster():
    c = MiniCluster(n_osd=6, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    r.pool_create("p", pg_num=8)
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k2m2",
                   "profile": {"plugin": "tpu", "k": "2", "m": "2",
                               "crush-failure-domain": "host"}})
    r.pool_create("ec", pg_num=8, pool_type="erasure",
                  erasure_code_profile="k2m2")
    c.pump()
    yield c, r
    c.shutdown()


def corrupt_replicated(c, pg, osd, oid, payload=b"CORRUPT!"):
    """Flip bytes in one replica's stored object, bypassing the stack."""
    from ceph_tpu.osd.ec_backend import pg_cid
    store = c.osds[osd].store
    store.queue_transaction(
        __import__("ceph_tpu.store", fromlist=["Transaction"])
        .Transaction().write(pg_cid(pg), ObjectId(oid), 0, payload))


def test_clean_scrub_reports_nothing(cluster):
    c, r = cluster
    io = r.open_ioctx("p")
    io.write_full("good", b"g" * 2000)
    c.pump()
    pid, pg, acting, primary = locate(c, r, "p", "good")
    res = r.pg_scrub(pid, pg.ps)
    assert res == {"inconsistent": [], "repaired": 0,
                   "unrepairable": []}


def test_replicated_corruption_detected_and_repaired(cluster):
    c, r = cluster
    io = r.open_ioctx("p")
    payload = b"x" * 4096
    io.write_full("victim", payload)
    c.pump()
    pid, pg, acting, primary = locate(c, r, "p", "victim")
    replica = next(o for o in acting if o != primary)
    corrupt_replicated(c, pg, replica, "victim")
    # detect
    res = r.pg_scrub(pid, pg.ps)
    assert res["inconsistent"] == ["victim"]
    assert res["repaired"] == 0
    # replica really is corrupt
    bad = c.osds[replica].pgs[pg].shard.read("victim")
    assert bad[:8] == b"CORRUPT!"
    # repair from the authoritative (primary) copy
    res = r.pg_scrub(pid, pg.ps, repair=True)
    c.pump()
    assert res["inconsistent"] == ["victim"]
    assert res["repaired"] >= 1 and not res["unrepairable"]
    assert c.osds[replica].pgs[pg].shard.read("victim") == payload
    # next scrub is clean
    res = r.pg_scrub(pid, pg.ps)
    assert res["inconsistent"] == []


def test_replicated_missing_copy_detected(cluster):
    c, r = cluster
    io = r.open_ioctx("p")
    io.write_full("half", b"h" * 1024)
    c.pump()
    pid, pg, acting, primary = locate(c, r, "p", "half")
    replica = next(o for o in acting if o != primary)
    from ceph_tpu.osd.ec_backend import pg_cid
    from ceph_tpu.store import Transaction
    c.osds[replica].store.queue_transaction(
        Transaction().remove(pg_cid(pg), ObjectId("half")))
    res = r.pg_scrub(pid, pg.ps, repair=True)
    c.pump()
    assert res["inconsistent"] == ["half"]
    assert c.osds[replica].pgs[pg].shard.read("half") == b"h" * 1024


def test_ec_shard_corruption_detected_and_rebuilt(cluster):
    c, r = cluster
    io = r.open_ioctx("ec")
    rng = np.random.default_rng(4)
    payload = rng.integers(0, 256, 8192, dtype=np.uint8).tobytes()
    io.write_full("ecobj", payload)
    c.pump()
    pid, pg, acting, primary = locate(c, r, "ec", "ecobj")
    victims = [o for o in acting if 0 <= o < (1 << 30) and o != primary]
    assert victims
    victim = victims[0]
    shard_idx = acting.index(victim)
    from ceph_tpu.osd.ec_backend import pg_cid
    from ceph_tpu.store import Transaction
    c.osds[victim].store.queue_transaction(Transaction().write(
        pg_cid(pg), ObjectId("ecobj", shard=shard_idx), 0, b"\xff" * 16))
    # detect: the shard's crc no longer matches its HashInfo
    res = r.pg_scrub(pid, pg.ps)
    assert res["inconsistent"] == ["ecobj"]
    # repair: rebuild the shard through the recovery path
    res = r.pg_scrub(pid, pg.ps, repair=True)
    c.pump()
    assert res["repaired"] == 1 and not res["unrepairable"]
    res = r.pg_scrub(pid, pg.ps)
    assert res["inconsistent"] == []
    # data still reads back
    assert io.read("ecobj") == payload
