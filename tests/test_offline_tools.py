"""Offline surgery tools: objectstore-tool + monstore-tool
(VERDICT r3 #7; ref: src/tools/ceph_objectstore_tool.cc,
src/tools/ceph_monstore_tool.cc)."""
import json
import time

import pytest

from ceph_tpu.osd.types import PG
from ceph_tpu.store import BlueStore
from ceph_tpu.testing import MiniCluster
from ceph_tpu.tools import monstore_tool, objectstore_tool


def _mk_store(tmp_path, name):
    st = BlueStore(str(tmp_path / name))
    st.mkfs()
    st.mount()
    return st


def test_objectstore_tool_cli_roundtrip(tmp_path):
    """list/info/fsck/export/import/remove against a bare store."""
    from ceph_tpu.osd.replicated_backend import ReplicatedPGShard
    from ceph_tpu.osd.pg_types import EVersion, MODIFY, PGLogEntry
    st = _mk_store(tmp_path, "osd0")
    pg = PG(3, 0xb)
    shard = ReplicatedPGShard(pg, st)
    for i in range(5):
        e = PGLogEntry(MODIFY, f"obj{i}", EVersion(2, i + 1))
        shard.apply_mutations(f"obj{i}", [], EVersion(2, i + 1), [e])
        st_data = f"payload-{i}".encode() * 10
        shard.apply_write(f"obj{i}", 0, st_data, False,
                          EVersion(2, i + 1), [])
    st.umount()

    # CLI: list + info + fsck
    assert objectstore_tool.main(
        ["--data-path", str(tmp_path / "osd0"), "--op", "list"]) == 0
    assert objectstore_tool.main(
        ["--data-path", str(tmp_path / "osd0"), "--op", "fsck"]) == 0
    st = _mk_store(tmp_path, "osd0")
    info = objectstore_tool.pg_info(st, pg)
    assert info["objects"] == 5
    assert info["log_entries"] == 5

    # export -> import into a different store
    blob = objectstore_tool.export_pg(st, pg)
    st.umount()
    st2 = _mk_store(tmp_path, "osd1")
    got = objectstore_tool.import_pg(st2, blob)
    assert got == pg
    # double import refused without --force
    with pytest.raises(Exception):
        objectstore_tool.import_pg(st2, blob)
    objectstore_tool.import_pg(st2, blob, force=True)
    info2 = objectstore_tool.pg_info(st2, pg)
    assert info2["objects"] == info["objects"]
    assert info2["log_head"] == info["log_head"]
    from ceph_tpu.osd.replicated_backend import ReplicatedPGShard as R
    sh2 = R(pg, st2, create=False)
    assert sh2.read("obj3") == b"payload-3" * 10
    # remove
    assert objectstore_tool.remove_pg(st2, pg) == 6  # 5 objs + pgmeta
    st2.umount()


def test_objectstore_tool_snap_index_ops(tmp_path):
    """list-snaps + dump-snap-index expose the durable snaptrim state
    of a stopped OSD: clone tags/covers, the snap->clone index still
    awaiting trim, and the purged_snaps cursor."""
    from ceph_tpu.osd import mutations as mut
    from ceph_tpu.osd.pg_types import EVersion, MODIFY, PGLogEntry
    from ceph_tpu.osd.replicated_backend import ReplicatedPGShard
    st = _mk_store(tmp_path, "osd2")
    pg = PG(5, 0x1)
    shard = ReplicatedPGShard(pg, st)
    shard.apply_write("snappy", 0, b"v1" * 50, False,
                      EVersion(2, 1),
                      [PGLogEntry(MODIFY, "snappy", EVersion(2, 1))])
    # a COW write preserving the head as clone 7 covering snaps {6, 7}
    shard.apply_mutations(
        "snappy", [(mut.M_WRITEFULL, b"v2" * 50)],
        EVersion(2, 2), [PGLogEntry(MODIFY, "snappy", EVersion(2, 2))],
        clone_snap=7, clone_covers=[6, 7], snap_seq=7)
    shard.mark_purged(3)

    snaps = objectstore_tool.list_snaps(st, pg)
    assert len(snaps) == 1 and snaps[0]["oid"] == "snappy"
    assert snaps[0]["clones"]["7"]["covers"] == [6, 7]
    assert snaps[0]["clones"]["7"]["present"]

    idx = objectstore_tool.dump_snap_index(st, pg)
    assert {(e["snap"], e["clone"]) for e in idx["index"]} == \
        {(6, 7), (7, 7)}
    assert idx["purged_snaps"] == [[3, 3]]
    st.umount()
    # CLI legs
    assert objectstore_tool.main(
        ["--data-path", str(tmp_path / "osd2"), "--op", "list-snaps",
         "--pgid", "5.1"]) == 0
    assert objectstore_tool.main(
        ["--data-path", str(tmp_path / "osd2"),
         "--op", "dump-snap-index", "--pgid", "5.1"]) == 0


def test_pg_export_import_rescues_killed_osd(tmp_path):
    """The VERDICT criterion: export a PG from a killed OSD's store,
    import it into a fresh one, revive — the cluster peers from the
    imported history and every object reads back."""
    import numpy as np
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        # move every OSD onto disk-backed BlueStore
        for i in range(3):
            c.kill_osd(i)
            st = _mk_store(tmp_path, f"osd{i}")
            c._stores[i] = st
            c.revive_osd(i)
        c.wait_all_up()
        r = c.rados()
        r.pool_create("surgery", pg_num=2)
        io = r.open_ioctx("surgery")
        rng = np.random.default_rng(5)
        objs = {f"s{i}": rng.integers(0, 256, 1024,
                                      dtype=np.uint8).tobytes()
                for i in range(24)}
        for k, v in objs.items():
            io.write_full(k, v)
        victim = 1
        c.kill_osd(victim)
        r.mon_command({"prefix": "osd down", "ids": [victim]})
        # offline surgery: every PG the dead OSD held moves to a
        # brand-new store (the disk-swap flow)
        old = c._stores[victim]
        fresh = _mk_store(tmp_path, "osd-fresh")
        moved = 0
        for pgs in objectstore_tool.list_pgs(old):
            pool_s, ps_s = pgs.split(".")
            pg = PG(int(pool_s), int(ps_s, 16))
            blob = objectstore_tool.export_pg(old, pg)
            objectstore_tool.import_pg(fresh, blob)
            moved += 1
        assert moved >= 1
        old.umount()
        c._stores[victim] = fresh
        c.revive_osd(victim)
        deadline = time.monotonic() + 60
        ok = False
        while time.monotonic() < deadline and not ok:
            c.tick()
            if all(d.pgs_recovering() == 0 for d in c.osds.values()):
                try:
                    ok = all(io.read(k) == v for k, v in objs.items())
                except Exception:
                    ok = False
            time.sleep(0.1)
        assert ok, "cluster never returned to clean after import"
        # the revived OSD serves from the imported collections
        d = c.osds[victim]
        assert any(cid.startswith("pg_") for cid in
                   d.store.list_collections())
    finally:
        c.shutdown()


def test_monstore_tool_dump_and_rebuild(tmp_path):
    """dump / show-versions / get-osdmap / rebuild on a real durable
    mon store."""
    from ceph_tpu.kv import LogDB
    from ceph_tpu.mon.monitor import Monitor, build_initial
    from ceph_tpu.mon.store import MonitorStore
    from ceph_tpu.msg.messenger import LocalNetwork
    mon_dir = str(tmp_path / "mon0")
    net = LocalNetwork()
    m0, w = build_initial(3)
    mon = Monitor(net, initial_map=m0, initial_wrapper=w,
                  store=MonitorStore(LogDB(mon_dir)), threaded=False)
    mon.init()
    rc, outs, _ = mon.handle_command({"prefix": "osd pool create",
                                      "pool": "p1", "pg_num": 8})
    assert rc == 0, outs
    rc, _, _ = mon.handle_command({"prefix": "osd pool create",
                                   "pool": "p2", "pg_num": 4})
    assert rc == 0
    mon.shutdown()

    store = monstore_tool._load(mon_dir)
    lines = monstore_tool.dump(store)
    assert any("osdmap" in ln for ln in lines)
    vers = monstore_tool.show_versions(store)
    assert "paxos" in vers or "osdmap" in vers
    summary = monstore_tool.get_osdmap(store)
    assert summary["epoch"] >= 3
    assert len(summary["pools"]) == 2
    store.db.close()

    # rebuild into a fresh dir; a mon boots from it with same state
    out_dir = str(tmp_path / "mon0-rebuilt")
    n = monstore_tool.rebuild(mon_dir, out_dir)
    assert n > 0
    mon2 = Monitor(net, initial_map=build_initial(3)[0],
                   initial_wrapper=build_initial(3)[1],
                   store=MonitorStore(LogDB(out_dir)), threaded=False)
    mon2.init()
    assert len(mon2.osdmap.pools) == 2
    assert mon2.osdmap.epoch >= 3
    mon2.shutdown()


def test_monstore_cli(tmp_path):
    from ceph_tpu.kv import LogDB
    from ceph_tpu.mon.monitor import Monitor, build_initial
    from ceph_tpu.mon.store import MonitorStore
    from ceph_tpu.msg.messenger import LocalNetwork
    mon_dir = str(tmp_path / "monc")
    m0, w = build_initial(2)
    mon = Monitor(LocalNetwork(), initial_map=m0, initial_wrapper=w,
                  store=MonitorStore(LogDB(mon_dir)), threaded=False)
    mon.init()
    mon.handle_command({"prefix": "osd pool create", "pool": "x",
                        "pg_num": 4})
    mon.shutdown()
    assert monstore_tool.main([mon_dir, "dump"]) == 0
    assert monstore_tool.main([mon_dir, "show-versions"]) == 0
    assert monstore_tool.main([mon_dir, "get-osdmap"]) == 0
    out = str(tmp_path / "monc2")
    assert monstore_tool.main([mon_dir, "rebuild", "--out", out]) == 0
