"""Distributed device-mesh compute: EC coding as ICI collectives.

The TPU re-design of the reference's inter-OSD data fan-out
(ref: src/osd/ECBackend.cc:2037-2070 per-shard message fan-out over the
messenger; src/msg/Messenger.h): when chunk shards are device-resident
on a `jax.sharding.Mesh`, the k+m shard traffic becomes XLA collectives
riding ICI instead of host messages.
"""
from .fabric import ICIFabric
from .mesh_ec import MeshECCoder, make_mesh

__all__ = ["ICIFabric", "MeshECCoder", "make_mesh"]
