"""PGMap aggregation + health checks + status/df commands
(ref: src/mon/PGMap.cc, src/mon/health_check.h,
Monitor.cc get_cluster_status)."""
import pytest

from ceph_tpu.testing import MiniCluster


@pytest.fixture()
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("sp", pg_num=8)
    yield c, r
    c.shutdown()


def _tick(c, n=3):
    for _ in range(n):
        c.tick()


def test_status_df_pg_stat(cluster):
    c, r = cluster
    io = r.open_ioctx("sp")
    for i in range(10):
        io.write_full(f"o{i}", b"x" * 1000)
    _tick(c)
    rc, _, s = r.mon_command({"prefix": "status"})
    assert rc == 0
    assert s["health"]["status"] == "HEALTH_OK"
    assert s["osdmap"]["num_up_osds"] == 4
    assert s["pgmap"]["num_pgs"] == 8
    assert s["pgmap"]["num_objects"] == 10
    assert s["pgmap"]["bytes_data"] == 10_000
    assert s["pgmap"]["pgs_by_state"] == {"active+clean": 8}
    assert s["monmap"]["quorum"] == [0]

    rc, _, df = r.mon_command({"prefix": "df"})
    assert rc == 0 and df["total_kb"] > 0
    assert df["pools"]["sp"]["objects"] == 10
    assert df["pools"]["sp"]["bytes"] == 10_000

    rc, outs, st = r.mon_command({"prefix": "pg stat"})
    assert rc == 0 and "8 pgs" in outs and st["num_objects"] == 10

    rc, _, q = r.mon_command({"prefix": "quorum_status"})
    assert rc == 0 and q["leader"] == 0

    rc, _, dump = r.mon_command({"prefix": "pg dump"})
    assert rc == 0 and len(dump) == 8


def test_health_osd_down_and_degraded(cluster):
    c, r = cluster
    io = r.open_ioctx("sp")
    io.write_full("hobj", b"d" * 100)
    _tick(c)
    e0 = r.objecter.osdmap.epoch
    c.kill_osd(3)
    r.mon_command({"prefix": "osd down", "ids": [3]})
    r.objecter.wait_for_map(e0 + 1)
    _tick(c, 4)
    rc, outs, h = r.mon_command({"prefix": "health"})
    assert rc == 0 and h["status"] == "HEALTH_WARN"
    assert "OSD_DOWN" in h["checks"]
    assert "1 osds down" in h["checks"]["OSD_DOWN"]["summary"]
    # size-3 pools on 3 live osds (osds_per_host=1 -> one osd per
    # host bucket): some pg reports 'degraded' until backfill can
    # restore width — with 3 up osds CRUSH can still map, so allow
    # either, but the checks must be well-formed
    rc, _, hd = r.mon_command({"prefix": "health detail"})
    assert rc == 0
    for chk in hd["checks"].values():
        assert chk["severity"].startswith("HEALTH_")
        assert isinstance(chk["detail"], list)
    rc, _, s = r.mon_command({"prefix": "status"})
    assert s["health"]["status"] == "HEALTH_WARN"
    assert s["osdmap"]["num_up_osds"] == 3
    # revive for teardown cleanliness
    c.revive_osd(3)


def test_degraded_pg_states_reported():
    """With replication width 3 and only 2 osds, every pg reports
    degraded (ref: pg_state_string PG_STATE_DEGRADED)."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("thin", pg_num=8)   # default size 3 > 2 osds
        io = r.open_ioctx("thin")
        io.write_full("o", b"z")
        for _ in range(3):
            c.tick()
        rc, _, s = r.mon_command({"prefix": "status"})
        states = s["pgmap"]["pgs_by_state"]
        assert any("degraded" in k for k in states), states
        rc, _, h = r.mon_command({"prefix": "health"})
        assert h["status"] == "HEALTH_WARN"
        assert "PG_DEGRADED" in h["checks"]
    finally:
        c.shutdown()
