"""lrc / shec / clay plugin tests, modeled on the reference suites
(src/test/erasure-code/TestErasureCodeLrc.cc, TestErasureCodeShec*.cc,
TestErasureCodeClay.cc): profile generation, round-trips across erasure
patterns, minimum_to_decode behavior, and the clay sub-chunk repair path."""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import factory

DATA = bytes(range(256)) * 96


def roundtrip(ec, erased, data=DATA):
    n = ec.get_chunk_count()
    enc = ec.encode(set(range(n)), data)
    sub = {i: c for i, c in enc.items() if i not in erased}
    dec = ec.decode(set(erased), sub)
    for e in erased:
        assert np.array_equal(dec[e], enc[e]), f"chunk {e} mismatch"
    return enc


# ---------------------------------------------------------------------------
# lrc


def test_lrc_kml_generation():
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    # (k+m)/l = 2 local groups, each adding one local parity
    assert ec.get_chunk_count() == 8
    assert ec.get_data_chunk_count() == 4
    # generated params are not exposed back (ErasureCodeLrc.cc:539)
    assert "mapping" not in ec.get_profile()
    assert "layers" not in ec.get_profile()


def test_lrc_kml_validation():
    with pytest.raises(ErasureCodeError, match="must be set or none"):
        factory("lrc", {"k": "4", "m": "2"})
    with pytest.raises(ErasureCodeError, match="multiple of l"):
        factory("lrc", {"k": "4", "m": "2", "l": "4"})
    with pytest.raises(ErasureCodeError, match="cannot be set"):
        factory("lrc", {"k": "4", "m": "2", "l": "3", "mapping": "DD__"})


def test_lrc_local_recovery_reads_fewer():
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    n = ec.get_chunk_count()
    # single lost chunk: only its local group is needed
    mn = ec.minimum_to_decode({1}, set(range(n)) - {1})
    assert len(mn) < ec.get_data_chunk_count()


def test_lrc_roundtrips():
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    roundtrip(ec, {1})
    roundtrip(ec, {0, 4})
    roundtrip(ec, {3, 7})


def test_lrc_explicit_layers():
    import json
    layers = json.dumps([["DDc", ""]])
    ec = factory("lrc", {"mapping": "DD_", "layers": layers})
    assert ec.get_chunk_count() == 3
    assert ec.get_data_chunk_count() == 2
    roundtrip(ec, {2})
    roundtrip(ec, {0})


def test_lrc_decode_concat():
    ec = factory("lrc", {"k": "4", "m": "2", "l": "3"})
    enc = ec.encode(set(range(8)), DATA)
    out = ec.decode_concat({i: c for i, c in enc.items() if i != 1})
    assert out[:len(DATA)] == DATA


# ---------------------------------------------------------------------------
# shec


def test_shec_profile_validation():
    with pytest.raises(ErasureCodeError, match="must be chosen"):
        factory("shec", {"k": "4"})
    with pytest.raises(ErasureCodeError, match="c=4 must be <= m=3"):
        factory("shec", {"k": "6", "m": "3", "c": "4"})
    with pytest.raises(ErasureCodeError, match="not a valid coding"):
        factory("shec", {"technique": "bogus"})


def test_shec_defaults():
    ec = factory("shec", {})
    assert (ec.k, ec.m, ec.c) == (4, 3, 2)
    assert ec.get_chunk_count() == 7


@pytest.mark.parametrize("technique", ["single", "multiple"])
def test_shec_single_loss_reads_fewer_than_k(technique):
    ec = factory("shec", {"k": "6", "m": "4", "c": "2",
                          "technique": technique})
    n = ec.get_chunk_count()
    mn = ec.minimum_to_decode({2}, set(range(n)) - {2})
    assert len(mn) < 6  # the shingle property


@pytest.mark.parametrize("technique", ["single", "multiple"])
def test_shec_roundtrip_all_single_and_double(technique):
    ec = factory("shec", {"k": "4", "m": "3", "c": "2",
                          "technique": technique})
    n = ec.get_chunk_count()
    for e in range(n):
        roundtrip(ec, {e})
    # c=2: every double erasure is recoverable
    for pair in itertools.combinations(range(n), 2):
        roundtrip(ec, set(pair))


def test_shec_minimum_is_sufficient():
    # decoding from exactly the minimum chunk set must succeed
    ec = factory("shec", {"k": "6", "m": "4", "c": "2"})
    n = ec.get_chunk_count()
    enc = ec.encode(set(range(n)), DATA)
    for lost in range(n):
        mn = ec.minimum_to_decode({lost}, set(range(n)) - {lost})
        sub = {i: enc[i] for i in mn}
        dec = ec.decode({lost}, sub)
        assert np.array_equal(dec[lost], enc[lost])


# ---------------------------------------------------------------------------
# clay


@pytest.mark.parametrize("km", [(4, 2), (5, 3), (4, 3)])
def test_clay_roundtrip(km):
    k, m = km
    ec = factory("clay", {"k": str(k), "m": str(m)})
    n = ec.get_chunk_count()
    assert n == k + m
    assert ec.get_sub_chunk_count() == ec.q ** ec.t
    for e in range(n):
        roundtrip(ec, {e})
    # m erasures (the MDS property)
    for pat in itertools.combinations(range(n), m):
        roundtrip(ec, set(pat))


def test_clay_repair_subchunk_reads():
    ec = factory("clay", {"k": "4", "m": "2"})
    n = ec.get_chunk_count()
    data = DATA
    cs = ec.get_chunk_size(len(data))
    enc = ec.encode(set(range(n)), data)
    ssize = cs // ec.get_sub_chunk_count()
    for lost in range(n):
        avail = set(range(n)) - {lost}
        assert ec.is_repair({lost}, avail)
        mn = ec.minimum_to_decode({lost}, avail)
        assert len(mn) == ec.d
        # partial (repair-plane) reads only
        helper = {}
        total_read = 0
        for i, ranges in mn.items():
            parts = [enc[i][off * ssize:(off + cnt) * ssize]
                     for off, cnt in ranges]
            helper[i] = np.concatenate(parts)
            total_read += sum(cnt for _, cnt in ranges)
        # MSR bandwidth: less than reading k full chunks
        assert total_read * ssize < ec.k * cs
        dec = ec.decode({lost}, helper, chunk_size=cs)
        assert np.array_equal(dec[lost], enc[lost]), f"repair of {lost}"


def test_clay_two_losses_fall_back_to_decode():
    ec = factory("clay", {"k": "4", "m": "2"})
    assert not ec.is_repair({1, 2}, {0, 3, 4, 5})
    roundtrip(ec, {1, 2})


def test_clay_d_validation():
    with pytest.raises(ErasureCodeError, match="must be within"):
        factory("clay", {"k": "4", "m": "2", "d": "3"})
    # d < k+m-1 reduces q (more helpers variants)
    ec = factory("clay", {"k": "4", "m": "4", "d": "5"})
    assert ec.q == 2 and ec.d == 5
    roundtrip(ec, {0})


def test_clay_with_isa_scalar_mds():
    ec = factory("clay", {"k": "4", "m": "2", "scalar_mds": "isa"})
    roundtrip(ec, {0, 5})
