"""GF(2^8) byte matmul as a GF(2) bit-plane matmul on the TPU MXU.

The TPU-first formulation of the erasure-code hot loop (the GF(2^8)
matrix-vector products that ISA-L's `ec_encode_data` AVX2 assembly computes
per 32-byte lane, ref: src/erasure-code/isa/ErasureCodeIsa.cc:129):

GF(2^8) multiplication by a constant c is GF(2)-linear in the bits of the
operand, so an (r x k) byte matrix over GF(2^8) lifts to an (8r x 8k) 0/1
companion matrix B with B[8i+t, 8j+c] = bit t of (mat[i,j] * x^c).  A byte
block (k, N) unpacks to bit-planes (8k, N); then

    out_bits = (B @ bits) mod 2        # one int8 matmul on the MXU
    out[i,n] = sum_t out_bits[8i+t, n] << t

XOR-accumulation across k inputs becomes mod-2 integer accumulation inside
the matmul, which is exactly what the MXU is good at.  The contraction
length is 8k <= 256, so int32 (or even bf16) accumulation is exact.

Two paths:
* `gf_matmul_xla`: pure jnp — XLA fuses unpack/pack around a dot_general;
* `gf_matmul_pallas`: a fused Pallas kernel that keeps the 8x bit-plane
  expansion in VMEM only (never materialized in HBM), grid over N tiles.

Both produce bytes identical to the numpy oracle (ceph_tpu.ec.gf) and hence
to the reference plugins.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import gf


def expand_bits(data: jax.Array) -> jax.Array:
    """(..., k, N) uint8 -> (..., 8k, N) int8 bit-planes (bit c of byte j
    at row 8j+c)."""
    *lead, k, n = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (data[..., :, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(*lead, 8 * k, n).astype(jnp.int8)


def pack_bits(out_bits: jax.Array) -> jax.Array:
    """(..., 8r, N) {0,1} int32 -> (..., r, N) uint8."""
    *lead, r8, n = out_bits.shape
    r = r8 // 8
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8)).astype(jnp.int32)
    planes = out_bits.reshape(*lead, r, 8, n)
    return (planes * weights[None, :, None]).sum(axis=-2).astype(jnp.uint8)


@jax.jit
def gf_matmul_xla(bitmat: jax.Array, data: jax.Array) -> jax.Array:
    """(8r x 8k) companion bit-matrix times (..., k, N) bytes -> (..., r, N).

    Leading axes of `data` are batch (stripes)."""
    bits = expand_bits(data)
    acc = jnp.matmul(bitmat, bits, preferred_element_type=jnp.int32)
    return pack_bits(acc & 1)


@functools.lru_cache(maxsize=512)
def companion_bitmatrix(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    return gf.expand_to_bitmatrix(mat).astype(np.int8)


class GFMatmul:
    """Cached, device-resident GF matmul for a fixed byte matrix.

    The companion bit-matrix lives in HBM across calls (the analogue of the
    ISA-L encode-table cache, ref: ErasureCodeIsaTableCache.cc); jit caches
    the compiled kernel per data shape.
    """

    def __init__(self, mat: np.ndarray, use_pallas: bool | None = None):
        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        self.r, self.k = mat.shape
        self.bitmat = jnp.asarray(
            companion_bitmatrix(mat.tobytes(), self.r, self.k))
        if use_pallas is None:
            # config-selected backend; pallas only makes sense on TPU.
            # Measured: the XLA formulation beats the current Pallas
            # kernel (PERF_NOTES.md), so the schema default is "xla".
            from ...common.options import global_config
            use_pallas = (global_config()["ec_tpu_backend"] == "pallas"
                          and jax.default_backend() == "tpu")
        self.use_pallas = use_pallas

    def __call__(self, data) -> jax.Array:
        """data: (..., k, N) uint8 (device or host) -> (..., r, N) uint8."""
        data = jnp.asarray(data, dtype=jnp.uint8)
        if self.use_pallas:
            return gf_matmul_pallas(self.bitmat, data)
        return gf_matmul_xla(self.bitmat, data)


# ---------------------------------------------------------------------------
# Pallas fused kernel
# ---------------------------------------------------------------------------

def _gf_kernel(bitmat_ref, data_ref, out_ref):
    """One N-tile: unpack -> MXU matmul -> mod 2 -> pack, all in VMEM."""
    data = data_ref[...].astype(jnp.int32)    # (k, TN)
    k, tn = data.shape
    shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
    bits = ((data[:, None, :] >> shifts) & 1).astype(jnp.int8)
    bits = bits.reshape(8 * k, tn)
    acc = jax.lax.dot_general(
        bitmat_ref[...], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)     # (8r, TN)
    acc = acc & 1
    r8 = acc.shape[0]
    weights = (jnp.int32(1) << jax.lax.broadcasted_iota(
        jnp.int32, (1, 8, 1), 1))
    planes = acc.reshape(r8 // 8, 8, tn) * weights
    out_ref[...] = planes.sum(axis=1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("tile_n",))
def _gf_matmul_pallas_2d(bitmat: jax.Array, data: jax.Array,
                         tile_n: int) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k8 = bitmat.shape[1]
    r8 = bitmat.shape[0]
    k = k8 // 8
    r = r8 // 8
    n = data.shape[1]
    grid = (n // tile_n,)
    return pl.pallas_call(
        _gf_kernel,
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, k8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, tile_n), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
    )(bitmat, data)


def gf_matmul_pallas(bitmat: jax.Array, data: jax.Array) -> jax.Array:
    """Fused kernel entry; handles batching and ragged tails by splitting
    into an aligned body (Pallas) and a remainder (XLA path)."""
    *lead, k, n = data.shape
    if lead:
        flat = jnp.moveaxis(data, -2, 0).reshape(k, -1)  # (k, B*N) view
        out = gf_matmul_pallas(bitmat, flat)
        r = bitmat.shape[0] // 8
        return jnp.moveaxis(out.reshape(r, *lead, n), 0, -2)
    tile_n = 2048
    if n < tile_n:
        return gf_matmul_xla(bitmat, data)
    body_n = (n // tile_n) * tile_n
    body = _gf_matmul_pallas_2d(bitmat, data[:, :body_n], tile_n)
    if body_n == n:
        return body
    tail = gf_matmul_xla(bitmat, data[:, body_n:])
    return jnp.concatenate([body, tail], axis=1)
