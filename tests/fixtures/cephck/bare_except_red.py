"""red: bare except catches SystemExit/KeyboardInterrupt too."""


def drain(q):
    try:
        return q.pop()
    except:                         # noqa: E722
        return None
