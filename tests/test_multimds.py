"""Multi-MDS: ranks + subtree authority + migration (closing VERDICT
r3 missing #3; ref: src/mds/MDSRank, src/mds/Migrator.cc, the
ceph.dir.pin export pin, MDS request forwarding)."""
import threading
import time

import pytest

from ceph_tpu.fs import CephFS, MDSDaemon
from ceph_tpu.fs.client import CephFSError
from ceph_tpu.fs.mds import INO_RANK_SHIFT
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    mds0 = MDSDaemon(c.network, c.rados(), rank=0)
    mds0.init()
    mds1 = MDSDaemon(c.network, c.rados(), rank=1)
    mds1.init()
    yield c, mds0, mds1
    mds1.shutdown()
    mds0.shutdown()
    c.shutdown()


def _fs(c):
    return CephFS(c.rados())


def test_pinned_subtree_served_by_other_rank(cluster):
    c, mds0, mds1 = cluster
    fs = _fs(c)
    fs.mkdirs("/tenant-a")
    fs.mkdirs("/tenant-b")
    fs.set_pin("/tenant-b", 1)
    assert fs.get_pins().get("/tenant-b") == 1
    # ops under the pin transparently forward to rank 1 and work
    fs.write_file("/tenant-b/file", b"served by rank one")
    assert fs.read_file("/tenant-b/file") == b"served by rank one"
    # rank 1 (not rank 0) granted the caps for the pinned file
    ino = fs.stat("/tenant-b/file")["ino"]
    fh = fs.open("/tenant-b/file", "w")
    assert ino in mds1._caps or ino in mds1._opens
    assert ino not in mds0._caps
    fh.close()
    # the unpinned tree stays on rank 0
    fs.write_file("/tenant-a/file", b"served by rank zero")
    ino0 = fs.stat("/tenant-a/file")["ino"]
    fh0 = fs.open("/tenant-a/file", "r")
    assert ino0 in mds0._opens
    assert ino0 not in mds1._opens
    fh0.close()


def test_ino_spaces_disjoint(cluster):
    """Each rank allocates inos from its own range (the InoTable
    partition), so concurrent creates never collide."""
    c, _m0, _m1 = cluster
    fs = _fs(c)
    fs.mkdirs("/inos-r0")
    fs.mkdirs("/inos-r1")
    fs.set_pin("/inos-r1", 1)
    inos = set()
    for i in range(8):
        fs.write_file(f"/inos-r0/f{i}", b"x")
        fs.write_file(f"/inos-r1/f{i}", b"y")
        inos.add(fs.stat(f"/inos-r0/f{i}")["ino"])
        inos.add(fs.stat(f"/inos-r1/f{i}")["ino"])
    assert len(inos) == 16
    r1_inos = {fs.stat(f"/inos-r1/f{i}")["ino"] for i in range(8)}
    assert all(ino >> INO_RANK_SHIFT == 1 for ino in r1_inos)


def test_migration_revokes_live_handles(cluster):
    """Re-pinning a subtree migrates authority out from under open
    handles: their caps are revoked (flushing buffered state) and
    subsequent ops route to the new rank."""
    c, mds0, mds1 = cluster
    fs = _fs(c)
    fs.mkdirs("/moving")
    fh = fs.open("/moving/live", "w")
    fh.write(0, b"A" * 3000)          # size buffered under EXCL
    fs.set_pin("/moving", 1)
    # the revoke lands asynchronously: wait for the surrender
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and fh.caps:
        time.sleep(0.05)
    assert fh.caps == 0, "migration never revoked the handle"
    # the flushed size is visible through the NEW authority
    st = fs.stat("/moving/live")
    assert st["size"] == 3000
    fh.write(3000, b"B" * 100)        # cap-less write-through works
    fh.close()
    assert fs.read_file("/moving/live") == b"A" * 3000 + b"B" * 100
    ino = st["ino"]
    assert ino not in mds0._caps and ino not in mds0._opens
    # migrate BACK under concurrent readers
    fs.set_pin("/moving", 0)
    assert fs.read_file("/moving/live")[:4] == b"AAAA"


def test_cross_rank_rename_works_and_link_refused(cluster):
    """Round 5 removed the rename EXDEV (two-phase slave protocol);
    cross-rank HARDLINKS still refuse — remote-link refcounting is
    the documented remaining gap."""
    c, _m0, _m1 = cluster
    fs = _fs(c)
    fs.mkdirs("/xr-a")
    fs.mkdirs("/xr-b")
    fs.set_pin("/xr-b", 1)
    fs.write_file("/xr-a/f", b"data")
    fs.rename("/xr-a/f", "/xr-b/f")
    assert fs.read_file("/xr-b/f") == b"data"
    with pytest.raises(CephFSError) as ei:
        fs.link("/xr-b/f", "/xr-a/alias")
    assert ei.value.errno_name == "EXDEV"
    # same-rank renames still fine on both ranks
    fs.write_file("/xr-a/g0", b"ga")
    fs.rename("/xr-a/g0", "/xr-a/g")
    fs.write_file("/xr-b/h", b"hb")
    fs.rename("/xr-b/h", "/xr-b/h2")
    assert fs.read_file("/xr-b/h2") == b"hb"


def test_concurrent_clients_across_ranks(cluster):
    """Two ranks serve disjoint subtrees under concurrent writers
    with no lost updates."""
    c, _m0, _m1 = cluster
    fs = _fs(c)
    fs.mkdirs("/par-r0")
    fs.mkdirs("/par-r1")
    fs.set_pin("/par-r1", 1)
    errors: list = []

    def worker(base, idx):
        try:
            wfs = _fs(c)
            for i in range(10):
                wfs.write_file(f"{base}/w{idx}-{i}",
                               (f"{base}:{idx}:{i}").encode())
        except Exception as ex:       # noqa: BLE001
            errors.append(ex)

    threads = [threading.Thread(target=worker, args=(b, i),
                                daemon=True)
               for b in ("/par-r0", "/par-r1") for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    for b in ("/par-r0", "/par-r1"):
        for idx in range(2):
            for i in range(10):
                assert fs.read_file(f"{b}/w{idx}-{i}") == \
                    (f"{b}:{idx}:{i}").encode()


def test_rank_crash_replay_isolated():
    """Each rank journals independently: a crashed rank replays its
    own journal without touching the other's state."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        mds0 = MDSDaemon(c.network, c.rados(), rank=0)
        mds0.init()
        mds1 = MDSDaemon(c.network, c.rados(), rank=1)
        mds1.init()
        fs = _fs(c)
        fs.mkdirs("/keep")
        fs.mkdirs("/crashy")
        fs.set_pin("/crashy", 1)
        fs.write_file("/keep/a", b"rank0 data")
        fs.write_file("/crashy/b", b"rank1 data")
        # hard-stop rank 1 (no graceful flush), revive it
        mds1.ms.shutdown()
        mds1b = MDSDaemon(c.network, c.rados(), rank=1)
        mds1b.init()
        fs2 = _fs(c)
        assert fs2.read_file("/crashy/b") == b"rank1 data"
        assert fs2.read_file("/keep/a") == b"rank0 data"
        fs2.write_file("/crashy/c", b"post-replay")
        assert fs2.read_file("/crashy/c") == b"post-replay"
        mds1b.shutdown()
        mds0.shutdown()
    finally:
        c.shutdown()

def test_migration_preserves_open_intents(cluster):
    """After a migration, the new rank knows about surviving handles:
    a second client's open must NOT get EXCL over a live writer."""
    from ceph_tpu.fs.mds import CAP_EXCL
    c, _m0, mds1 = cluster
    fs_w, fs_r = _fs(c), _fs(c)
    fs_w.mkdirs("/intent")
    w = fs_w.open("/intent/f", "w")
    w.write(0, b"writer data")
    fs_w.set_pin("/intent", 1)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and w.caps:
        time.sleep(0.05)
    assert w.caps == 0
    ino = fs_w.stat("/intent/f")["ino"]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and ino not in mds1._opens:
        time.sleep(0.05)
    assert ino in mds1._opens, "open intent never re-registered"
    # second client's open sees the conflict: no EXCL granted
    r = fs_r.open("/intent/f", "r")
    assert not (r.caps & CAP_EXCL)
    w.write(100, b"still-writing")
    assert fs_r.read_file("/intent/f")[:11] == b"writer data"
    w.close()
    r.close()
    fs_w.set_pin("/intent", 0)


def test_release_routes_to_owning_rank(cluster):
    """close() of a handle on a pinned subtree clears the owning
    rank's cap/open state (a mis-routed release would wedge future
    EXCL grants)."""
    from ceph_tpu.fs.mds import CAP_EXCL
    c, _m0, mds1 = cluster
    fs = _fs(c)
    fs.mkdirs("/rel")
    fs.set_pin("/rel", 1)
    fh = fs.open("/rel/f", "w")
    ino = fh.ino
    assert ino in mds1._opens
    fh.write(0, b"x")
    fh.close()
    assert ino not in mds1._opens, "release never reached rank 1"
    # a fresh open still gets EXCL (no stale-intent downgrade)
    fh2 = fs.open("/rel/f", "w")
    assert fh2.caps & CAP_EXCL
    fh2.close()
    fs.set_pin("/rel", 0)


def test_force_repin_rescues_bad_pin(cluster):
    """Pinning to a nonexistent rank is repairable: set_pin(force=True)
    through any live rank overrides the table."""
    c, _m0, _m1 = cluster
    fs = _fs(c)
    fs.mkdirs("/bricked")
    fs.write_file("/bricked/f", b"data")
    fs.set_pin("/bricked", 7)           # rank 7 does not exist
    with pytest.raises((CephFSError, TimeoutError)):
        fs._session.call("lookup", {"path": "/bricked/f"},
                         timeout=2.0)
    # repair through rank 0 with force
    fs._session.call("set_pin", {"path": "/bricked", "rank": 0,
                                 "force": True})
    assert fs.read_file("/bricked/f") == b"data"


def test_cross_rank_rename_file(cluster):
    """The EXDEV is gone: a rename whose src and dst live on
    different ranks runs the two-phase slave protocol, preserves
    inode identity, and moves cap authority (VERDICT r4 #6; ref:
    Server::handle_client_rename:7310, Migrator.h:51)."""
    c, mds0, mds1 = cluster
    fs = _fs(c)
    fs.mkdirs("/xr-src")
    fs.mkdirs("/xr-dst")
    fs.set_pin("/xr-dst", 1)
    fs.write_file("/xr-src/mover", b"identity survives")
    ino = fs.stat("/xr-src/mover")["ino"]
    fs.rename("/xr-src/mover", "/xr-dst/mover")
    # gone from src, present at dst, same inode, data intact
    with pytest.raises(CephFSError, match="ENOENT"):
        fs.stat("/xr-src/mover")
    assert fs.stat("/xr-dst/mover")["ino"] == ino
    assert fs.read_file("/xr-dst/mover") == b"identity survives"
    # the new authority (rank 1) now grants the caps
    fh = fs.open("/xr-dst/mover", "w")
    assert ino in mds1._caps or ino in mds1._opens
    assert ino not in mds0._caps
    fh.close()


def test_cross_rank_rename_preserves_hardlinks(cluster):
    """A hardlinked inode renamed across ranks keeps its other link
    alive (the itable-backed record never moves pools)."""
    c, _mds0, _mds1 = cluster
    fs = _fs(c)
    fs.mkdirs("/xh-src")
    fs.mkdirs("/xh-dst")
    fs.set_pin("/xh-dst", 1)
    fs.write_file("/xh-src/orig", b"two names")
    fs.link("/xh-src/orig", "/xh-src/alias")
    ino = fs.stat("/xh-src/orig")["ino"]
    fs.rename("/xh-src/orig", "/xh-dst/orig")
    assert fs.stat("/xh-dst/orig")["ino"] == ino
    assert fs.stat("/xh-src/alias")["ino"] == ino
    # writing through the surviving src-side link is visible at dst
    fs.write_file("/xh-src/alias", b"updated via alias")
    assert fs.read_file("/xh-dst/orig") == b"updated via alias"


def test_cross_rank_rename_directory_under_io(cluster):
    """A directory moves into another rank's subtree while a client
    holds an open handle inside it; the handle's caps are revoked and
    subsequent IO through fresh opens works at the new authority."""
    c, _mds0, _mds1 = cluster
    fs = _fs(c)
    fs.mkdirs("/xd-src/deep")
    fs.mkdirs("/xd-dst")
    fs.set_pin("/xd-dst", 1)
    fs.write_file("/xd-src/deep/a", b"aaa")
    fs.write_file("/xd-src/deep/b", b"bbb")
    fh = fs.open("/xd-src/deep/a", "r+")
    fh.write(0, b"AAA")
    fs.rename("/xd-src/deep", "/xd-dst/deep")
    time.sleep(0.3)                   # revoke lands, cache flushed
    assert fs.read_file("/xd-dst/deep/a")[:3] == b"AAA"
    assert fs.read_file("/xd-dst/deep/b") == b"bbb"
    fh.close()


def test_balancer_migrates_hot_subtree(cluster):
    """A hot directory on an overloaded rank auto-migrates to the
    colder rank, observable in get_pins; explicit pins are never
    auto-migrated (VERDICT r4 #6; ref: src/mds/MDBalancer.cc)."""
    from ceph_tpu.common.options import global_config
    g = global_config()
    saved = {k: g[k] for k in ("mds_bal_interval", "mds_bal_min_load",
                               "mds_bal_ratio")}
    g.set("mds_bal_interval", 1.0)
    g.set("mds_bal_min_load", 10.0)
    g.set("mds_bal_ratio", 1.5)
    c, mds0, mds1 = cluster
    fs = _fs(c)
    try:
        fs.mkdirs("/hot")
        fs.mkdirs("/pinned-hot")
        fs.set_pin("/pinned-hot", 0)      # operator override
        # hammer both dirs through rank 0
        for i in range(40):
            fs.write_file("/hot/f", b"x" * 64)
            fs.read_file("/hot/f")
            fs.write_file("/pinned-hot/f", b"y" * 64)
        t = 10_000.0
        mds1.tick(t); mds0.tick(t)        # both publish loads
        t += 2.0
        mds1.tick(t); mds0.tick(t)        # rank 0 sees a cold peer
        for _ in range(6):
            t += 2.0
            mds0.tick(t); mds1.tick(t)
            if fs.get_pins().get("/hot") == 1:
                break
        pins = fs.get_pins()
        assert pins.get("/hot") == 1, pins
        assert pins.get("/pinned-hot") == 0, \
            "explicit pin was auto-migrated"
        # the subtree actually serves from rank 1 now
        fs.write_file("/hot/after", b"post-migration")
        ino = fs.stat("/hot/after")["ino"]
        fh = fs.open("/hot/after", "r")
        assert ino in mds1._opens
        assert ino not in mds0._opens
        fh.close()
    finally:
        for k, v in saved.items():
            g.set(k, v)
