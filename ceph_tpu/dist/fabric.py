"""ICIFabric: the device-mesh chunk fan-out inside the OSD data plane.

The framework's thesis made real: when an EC PG's acting OSDs are
**co-resident** on one device mesh, the primary does not host-encode
and ship chunk bytes through the messenger.  Instead:

* the primary stages the stripe-aligned logical segment onto the
  (stripe, shard) mesh and runs ONE `shard_map` step — partial GF(2)
  bit-plane matmuls per device, combined with a `psum` over the
  'shard' axis.  That collective IS the reference's per-shard write
  fan-out (ref: src/osd/ECBackend.cc:2037-2070 — per-shard ECSubWrite
  construction + MOSDECSubOpWrite sends), riding ICI instead of the
  AsyncMessenger;
* the host messenger still carries the *control plane*: ECSubWrite
  messages shrink to metadata (tid, version, log entries, attrs txn)
  plus a `fabric_key` naming the staged device buffers;
* each acting shard resolves its `fabric_key` against the shared
  fabric and pulls ONLY its chunk slice from the device it co-resides
  with (`fetch_chunk` gathers the per-shard slice, not the stripe
  batch), writes it into its object store, and accumulates its own
  HashInfo crc locally.

Non-resident acting sets (or plugins without a plain MXU matrix form —
clay sub-chunks, lrc layers, legacy mappings) fall back to the host
encode path transparently; the fabric is an accelerator, not a
correctness dependency.
"""
from __future__ import annotations

import threading

from ..common.lockdep import make_lock

import numpy as np

from .mesh_ec import MeshECCoder, make_mesh


def _identity_mapping(ec) -> bool:
    n = ec.get_chunk_count()
    return all(ec.chunk_index(i) == i for i in range(n))


class ICIFabric:
    """Shared device-mesh coding fabric for co-resident OSD shards.

    One instance per process/host; daemons register residency at boot
    the way the reference's OSDs learn their NUMA/network locality.
    """

    def __init__(self, n_devices: int | None = None):
        self.n_devices = n_devices
        self.resident: set[int] = set()
        self._lock = make_lock("dist.fabric")
        #: serializes mesh PROGRAM launches.  The fabric is driven by
        #: many daemon threads at once (the primary staging an encode,
        #: k+m shard OSDs each gathering their slice), and jax dispatch
        #: is async: without this lock two in-flight XLA programs can
        #: interleave their collective rendezvous across the shared
        #: device set and deadlock (observed live: two psum AllReduces
        #: stuck waiting for each other's participants).  One program
        #: in flight at a time, completed before release — the device
        #: contract for a process-shared mesh.
        self._dispatch = make_lock("dist.fabric.dispatch")
        self._coders: dict = {}       # (k, m, matrix bytes) -> coder
        self._meshes: dict = {}       # shard_ways-compat k -> mesh
        self._staged: dict = {}       # fabric_key -> staging record
        self.stats = {"staged": 0, "fetched": 0, "released": 0}

    # ------------------------------------------------------- residency
    def register_resident(self, osd_id: int) -> None:
        with self._lock:
            self.resident.add(osd_id)

    def covers(self, acting) -> bool:
        """All acting OSDs co-resident on this fabric's mesh."""
        return bool(acting) and all(
            a >= 0 and a in self.resident for a in acting)

    # -------------------------------------------------------- support
    def supports(self, ec) -> bool:
        """Plain MXU-matrix plugins with identity chunk mapping and no
        sub-chunks (the fabric step is one bit-plane matmul + psum)."""
        return (getattr(ec, "encode_matrix", None) is not None
                and ec.get_sub_chunk_count() == 1
                and _identity_mapping(ec))

    def _coder_for(self, ec) -> MeshECCoder:
        k = ec.get_data_chunk_count()
        m = ec.get_coding_chunk_count()
        mat = np.ascontiguousarray(ec.encode_matrix, dtype=np.uint8)
        key = (k, m, mat.tobytes())
        with self._lock:
            coder = self._coders.get(key)
            if coder is None:
                mesh = self._meshes.get(k)
                if mesh is None:
                    mesh = make_mesh(self.n_devices, k=k)
                    self._meshes[k] = mesh
                coder = MeshECCoder(k, m, mesh, encode_matrix=mat)
                self._coders[key] = coder
            return coder

    # --------------------------------------------------------- staging
    def stage_encode(self, key, ec, seg: bytes, chunk_size: int) -> int:
        """Run the mesh encode step for one write and stage the
        device-resident chunk arrays under `key`.

        Returns the per-shard chunk length.  `seg` must be
        stripe-aligned (primary guarantees it, as for the host path).
        """
        k = ec.get_data_chunk_count()
        m = ec.get_coding_chunk_count()
        width = k * chunk_size
        if not seg or len(seg) % width:
            raise ValueError("segment must be non-empty stripe-aligned")
        nstripes = len(seg) // width
        coder = self._coder_for(ec)
        arr = np.frombuffer(seg, dtype=np.uint8).reshape(
            nstripes, k, chunk_size)
        # pad the stripe batch to the mesh's stripe axis (zero stripes
        # encode to zero parity; fetch slices them back off)
        stripe_ways = coder.mesh.devices.shape[0]
        pad = -nstripes % stripe_ways
        if pad:
            arr = np.concatenate(
                [arr, np.zeros((pad, k, chunk_size), dtype=np.uint8)])
        import jax
        with self._dispatch:
            data_dev = coder.shard_data(arr)
            parity_dev = coder.encode(data_dev)     # psum fan-out step
            # complete before releasing the launch lock: a second
            # program (another write's encode, a shard's fetch slice)
            # must never rendezvous concurrently with this one
            jax.block_until_ready(parity_dev)
        with self._lock:
            self._staged[key] = {
                "data": data_dev, "parity": parity_dev,
                "k": k, "m": m, "cs": chunk_size, "S": nstripes}
            self.stats["staged"] += 1
        return nstripes * chunk_size

    def fetch_chunk(self, key, shard: int) -> bytes:
        """One shard's chunk stream (concatenated over stripes) from
        the staged device arrays — the per-shard gather a co-resident
        OSD does instead of receiving bytes in the sub-write."""
        with self._lock:
            rec = self._staged.get(key)
            self.stats["fetched"] += 1
        if rec is None:
            raise KeyError(f"no staged write {key!r}")
        k = rec["k"]
        # slicing a sharded array launches a device program; serialize
        # it with every other mesh launch (k+m shards fetch at once)
        with self._dispatch:
            if shard < k:
                sl = np.asarray(rec["data"][:, shard, :])
            else:
                sl = np.asarray(rec["parity"][:, shard - k, :])
        return np.ascontiguousarray(sl[:rec["S"]]).tobytes()

    def release(self, key) -> None:
        with self._lock:
            if self._staged.pop(key, None) is not None:
                self.stats["released"] += 1

    def staged_count(self) -> int:
        with self._lock:
            return len(self._staged)
