"""cephx-lite: keyring, handshake, ticket verification, cluster gate
(ref: src/auth/cephx/CephxProtocol.cc, src/auth/KeyRing.cc)."""
import time

import pytest

from ceph_tpu.auth import (SERVICE_ENTITY, CephxClient, CephxServer,
                           CephxVerifier, KeyRing, generate_key)
from ceph_tpu.msg.messenger import Message
from ceph_tpu.testing import MiniCluster


def test_keyring_roundtrip(tmp_path):
    kr = KeyRing.generate(["mon.0", "osd.0", "client.a"])
    path = str(tmp_path / "keyring.json")
    kr.save(path)
    kr2 = KeyRing.load(path)
    assert kr2.keys == kr.keys
    sub = kr.subset("osd.0")
    assert set(sub.keys) == {"osd.0", SERVICE_ENTITY}


def _stamp(msg, src, seq=1):
    msg.src, msg.seq = src, seq
    return msg


def test_handshake_and_signatures():
    from ceph_tpu.msg.messages import OSDOp
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr)
    client = CephxClient("client.x", kr.get("client.x"))
    rep = server.handle_request(client.build_request())
    assert rep.result == 0
    assert client.ingest_reply(rep)
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    msg = client.sign(_stamp(OSDOp(oid="o", op="write"), "client.x", 7))
    assert ver.verify(msg)
    # header tampering invalidates the signature
    msg.seq = 8
    assert not ver.verify(msg)
    # unsigned fails; auth handshake types are exempt
    assert not ver.verify(_stamp(OSDOp(oid="o"), "client.x"))
    from ceph_tpu.msg.messages import MAuthRequest
    assert ver.verify(_stamp(MAuthRequest(), "client.x"))


def test_replay_rejected():
    """A captured signed message replayed verbatim must not verify a
    second time (ref: cephx freshness; ADVICE r2 #2)."""
    from ceph_tpu.msg.messages import OSDOp
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr)
    client = CephxClient("client.x", kr.get("client.x"))
    assert client.ingest_reply(server.handle_request(
        client.build_request()))
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    msg = client.sign(_stamp(OSDOp(oid="victim", op="delete"),
                             "client.x", 3))
    assert ver.verify(msg)
    assert not ver.verify(msg)            # verbatim replay
    # later messages from the live session still verify
    assert ver.verify(client.sign(_stamp(OSDOp(oid="o2", op="write"),
                                         "client.x", 4)))
    # a second verifier (another daemon) has its own window
    ver2 = CephxVerifier(kr.get(SERVICE_ENTITY))
    assert ver2.verify(msg)
    assert not ver2.verify(msg)


def test_entity_class_gating():
    """Client-class tickets cannot send daemon-internal messages
    (ref: cephx caps; ADVICE r2 #2)."""
    from ceph_tpu.msg.messages import (MMonSubscribe, MOSDFailure,
                                       RepOpWrite)
    kr = KeyRing.generate(["client.x", "osd.1"])
    server = CephxServer(kr)
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    client = CephxClient("client.x", kr.get("client.x"))
    assert client.ingest_reply(server.handle_request(
        client.build_request()))
    assert not ver.verify(client.sign(_stamp(
        RepOpWrite(oid="o"), "client.x")))
    assert not ver.verify(client.sign(_stamp(
        MOSDFailure(target_osd=1), "client.x")))
    assert ver.verify(client.sign(_stamp(
        MMonSubscribe(), "client.x", 2)))
    # daemon-class (self-minted with the service secret) may send them
    osd = CephxClient.self_mint("osd.1", kr.get(SERVICE_ENTITY))
    assert ver.verify(osd.sign(_stamp(RepOpWrite(oid="o"), "osd.1")))


def test_ticket_renewal():
    """Client re-handshakes before expiry; self-minted daemons re-mint
    transparently (ref: MonClient::_check_auth_rotating; ADVICE r2 #1)."""
    from ceph_tpu.msg.messages import OSDOp
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr, ticket_ttl=30.0)   # inside RENEW_MARGIN
    client = CephxClient("client.x", kr.get("client.x"))
    assert client.ingest_reply(server.handle_request(
        client.build_request()))
    assert client.needs_renewal
    assert client.should_send_renewal()
    assert not client.should_send_renewal()     # throttled
    # the renewal handshake refreshes key + ticket + expiry
    server.ttl = 3600.0
    assert client.ingest_reply(server.handle_request(
        client.build_request()))
    assert not client.needs_renewal
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    assert ver.verify(client.sign(_stamp(OSDOp(oid="o"), "client.x")))
    # self-minted short-ttl daemon: sign() re-mints, messages keep
    # verifying instead of going dark at expiry
    osd = CephxClient.self_mint("osd.0", kr.get(SERVICE_ENTITY),
                                ttl=0.05)
    stale_ticket = dict(osd.ticket)
    time.sleep(0.1)                     # original ticket now expired
    fresh = osd.sign(_stamp(Message(), "osd.0"))
    assert fresh.auth["ticket"] != stale_ticket   # re-minted
    assert ver.verify(fresh)


def test_renew_hook_fires_from_sign():
    """Wire-handshake clients renew from sign() — every traffic
    pattern (data ops, mds sessions) triggers it, not just one
    caller's submit path."""
    import threading
    from ceph_tpu.msg.messages import OSDOp
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr, ticket_ttl=30.0)   # inside RENEW_MARGIN
    client = CephxClient("client.x", kr.get("client.x"))
    assert client.ingest_reply(server.handle_request(
        client.build_request()))
    fired = threading.Event()
    client.renew_hook = fired.set
    client.sign(_stamp(OSDOp(oid="o"), "client.x"))
    assert fired.wait(5.0)
    # throttled: a second sign inside the window does not re-fire
    fired.clear()
    client.sign(_stamp(OSDOp(oid="o2"), "client.x", 2))
    time.sleep(0.05)
    assert not fired.is_set()


def test_bad_credentials_rejected():
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr)
    # wrong secret
    bad = CephxClient("client.x", generate_key())
    assert server.handle_request(bad.build_request()).result == -13
    # unknown entity
    ghost = CephxClient("client.ghost", generate_key())
    assert server.handle_request(ghost.build_request()).result == -1
    # forged ticket (wrong service secret) never verifies
    forged = CephxClient.self_mint("client.x", generate_key())
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    assert not ver.verify(forged.sign(_stamp(Message(), "client.x")))


def test_expired_ticket_rejected():
    kr = KeyRing.generate(["client.x"])
    server = CephxServer(kr, ticket_ttl=-1.0)     # born expired
    client = CephxClient("client.x", kr.get("client.x"))
    rep = server.handle_request(client.build_request())
    assert client.ingest_reply(rep)
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    assert not ver.verify(client.sign(_stamp(Message(), "client.x")))


def test_cephx_cluster_io():
    """Full cluster with cephx on: authenticated IO works; a client
    with a wrong key is refused."""
    c = MiniCluster(n_osd=4, threaded=True, auth="cephx")
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("authp", pg_num=8)
        io = r.open_ioctx("authp")
        io.write_full("sec", b"signed payload")
        assert io.read("sec") == b"signed payload"
        io.set_xattr("sec", "k", b"v")
        assert io.get_xattr("sec", "k") == b"v"
        # wrong secret: the mon refuses the handshake
        from ceph_tpu.client import Rados
        bad = Rados(c.network, name="client.evil",
                    mon=c.mon_names, auth_secret=generate_key())
        with pytest.raises(PermissionError):
            bad.connect(timeout=10.0)
        bad.shutdown()
        # no credentials at all: subscriptions are dropped, no map
        anon = Rados(c.network, name="client.anon", mon=c.mon_names)
        with pytest.raises(TimeoutError):
            anon.connect(timeout=2.0)
        anon.shutdown()
    finally:
        c.shutdown()

def test_cephx_mds_gate():
    """Advisor r3 (medium): in an auth cluster the MDS must verify
    inbound traffic like mon/OSD do — and the client->mds MClientCaps
    release ack must be client-allowed or cap revocation wedges."""
    import time

    from ceph_tpu.fs import CephFS, MDSDaemon
    from ceph_tpu.fs.client import CephFSError
    from ceph_tpu.fs.mds import CAP_EXCL
    from ceph_tpu.msg.messages import MClientRequest
    from ceph_tpu.msg.messenger import Messenger
    c = MiniCluster(n_osd=2, threaded=True, auth="cephx")
    mds = None
    try:
        c.wait_all_up()
        mds = MDSDaemon(c.network, c.rados(), keyring=c.keyring)
        mds.init()
        assert mds.ms.auth_verifier is not None
        fs_w, fs_r = CephFS(c.rados()), CephFS(c.rados())
        fs_w.mkdirs("/sec")
        w = fs_w.open("/sec/f", "w")
        assert w.caps & CAP_EXCL
        w.write(0, b"X" * 2048)          # size buffered under EXCL
        # the reader's open forces a revoke; the writer's release ack
        # travels client->mds as a signed MClientCaps
        r = fs_r.open("/sec/f", "r")
        assert r.size == 2048            # proves the flush+ack landed
        assert not (w.caps & CAP_EXCL)
        w.close()
        r.close()
        # an unauthenticated endpoint gets silently dropped
        rogue = Messenger.create(c.network, "client.rogue",
                                 threaded=True)
        got = []

        class _Sink:
            def ms_dispatch(self, msg):
                got.append(msg)
                return True

        rogue.add_dispatcher(_Sink())
        rogue.start()
        rogue.connect("mds.0").send_message(
            MClientRequest(tid=1, op="mkdir",
                           args={"path": "/evil", "mode": 0o755}))
        time.sleep(0.5)
        assert not got, "unauthenticated mds request must get no reply"
        assert not CephFS(c.rados()).exists("/evil")
        rogue.shutdown()
    finally:
        if mds is not None:
            mds.shutdown()
        c.shutdown()

def test_client_ticket_bound_to_src():
    """A client-class ticket speaks only for its own entity: services
    authorize by msg.src, so a valid ticket stamped with another
    client's name must not verify (cap-release forgery)."""
    from ceph_tpu.msg.messages import MClientCaps
    kr = KeyRing.generate(["client.a", "client.victim"])
    server = CephxServer(kr)
    ver = CephxVerifier(kr.get(SERVICE_ENTITY))
    atk = CephxClient("client.a", kr.get("client.a"))
    assert atk.ingest_reply(server.handle_request(atk.build_request()))
    forged = atk.sign(_stamp(MClientCaps(op="ack", ino=7),
                             "client.victim"))
    assert not ver.verify(forged)
    legit = atk.sign(_stamp(MClientCaps(op="ack", ino=7),
                            "client.a", 2))
    assert ver.verify(legit)
    # daemon-class stays exempt: the MDS's embedded RADOS client
    # legitimately signs as its daemon identity from a client-named
    # messenger (and every service-secret holder could mint any
    # daemon ticket anyway)
    mdsc = CephxClient.self_mint("mds.0", kr.get(SERVICE_ENTITY))
    assert ver.verify(mdsc.sign(_stamp(Message(), "client.mds123")))
