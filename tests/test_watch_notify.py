"""watch/notify: object notification fan-out with acks, timeouts and
linger re-registration across primary moves
(ref: src/osd/Watch.cc, src/messages/MWatchNotify.h,
librados watch2/notify2)."""
import time

import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("wp", pg_num=8)
    yield c, r
    c.shutdown()


def test_watch_missing_object(cluster):
    _, r = cluster
    io = r.open_ioctx("wp")
    with pytest.raises(RadosError, match="ENOENT"):
        io.watch("ghost", lambda *a: None)


def test_notify_no_watchers(cluster):
    _, r = cluster
    io = r.open_ioctx("wp")
    io.write_full("lonely", b"x")
    replies, timeouts = io.notify("lonely", payload={"ping": 1})
    assert replies == {} and timeouts == []


def test_notify_roundtrip_two_clients(cluster):
    c, r = cluster
    io = r.open_ioctx("wp")
    io.write_full("obj", b"watched")
    # second, independent client watches
    r2 = c.rados()
    io2 = r2.open_ioctx("wp")
    got = []

    def cb(notify_id, notifier, payload):
        got.append((notifier, payload))
        return {"seen": payload["n"] + 1}

    cookie = io2.watch("obj", cb)
    try:
        replies, timeouts = io.notify("obj", payload={"n": 41})
        assert timeouts == []
        assert list(replies.values()) == [{"seen": 42}]
        assert got and got[0][1] == {"n": 41}
        assert got[0][0] == r.objecter.name     # notifier identity
        # watcher sees its own notify too
        replies2, _ = io2.notify("obj", payload={"n": 1})
        assert list(replies2.values()) == [{"seen": 2}]
    finally:
        io2.unwatch("obj", cookie)
        r2.shutdown()
    # after unwatch, notifies see nobody
    replies3, timeouts3 = io.notify("obj", payload={"n": 0})
    assert replies3 == {} and timeouts3 == []


def test_notify_timeout_on_dead_watcher(cluster):
    """A watcher whose endpoint vanished is reported in timeouts, and
    the notify completes promptly rather than hanging."""
    c, r = cluster
    io = r.open_ioctx("wp")
    io.write_full("tobj", b"x")
    r2 = c.rados()
    io2 = r2.open_ioctx("wp")
    cookie = io2.watch("tobj", lambda *a: None)
    # hard-kill the watcher client (no unwatch)
    r2.shutdown()
    t0 = time.monotonic()
    replies, timeouts = io.notify("tobj", payload=1, timeout=3.0)
    assert replies == {}
    assert len(timeouts) == 1 and cookie in timeouts[0]
    assert time.monotonic() - t0 < 5.0


def test_watch_survives_primary_move(cluster):
    """Marking the primary out moves the PG; the linger re-registers
    the watch on the new primary and notifies still arrive."""
    c, r = cluster
    io = r.open_ioctx("wp")
    io.write_full("mobj", b"x")
    r2 = c.rados()
    io2 = r2.open_ioctx("wp")
    got = []
    cookie = io2.watch("mobj", lambda nid, who, p: got.append(p) or "ok")
    try:
        pid = r.pool_lookup("wp")
        m = r.objecter.osdmap
        raw = m.object_locator_to_pg("mobj", pid)
        _, _, _, primary = m.pg_to_up_acting_osds(raw)
        e0 = m.epoch
        r.mon_command({"prefix": "osd out", "ids": [primary]})
        r.objecter.wait_for_map(e0 + 1)
        r2.objecter.wait_for_map(e0 + 1)
        _, _, _, primary2 = \
            r.objecter.osdmap.pg_to_up_acting_osds(raw)
        assert primary2 != primary
        # give the relinger a beat, then notify through the new primary
        deadline = time.monotonic() + 10
        replies = {}
        while time.monotonic() < deadline and not replies:
            replies, _ = io.notify("mobj", payload="moved",
                                   timeout=2.0)
        assert list(replies.values()) == ["ok"]
        assert "moved" in got
    finally:
        io2.unwatch("mobj", cookie)
        r2.shutdown()
        r.mon_command({"prefix": "osd in", "ids": [primary]})
