"""red: the persist_log bug class — omap mutations applied outside
the owning transaction (a private side-txn or a raw store call
breaks atomicity with the caller's update)."""


def persist_log(store, cid, entries):
    # mutating through something that is not the caller's Transaction
    store.omap_setkeys(cid, "pgmeta", {"log": b"..."})
    store.omap_rmkeys(cid, "pgmeta", ["cursor"])
