"""cls refcount: tag-set reference counting used by rgw object dedup
(ref: src/cls/refcount/cls_refcount.cc).  The ref set lives in a
`refcount` xattr; a `put` that empties the set removes the object —
exactly the reference's behavior (cls_rc_refcount_put ->
cls_cxx_remove when refs drain)."""
from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, cls_method

_ATTR = "refcount"


def _load(ctx) -> list[str]:
    try:
        return json.loads(ctx.getxattr(_ATTR))
    except ClsError:
        return []


@cls_method("refcount", "get", CLS_METHOD_RD | CLS_METHOD_WR)
def get(ctx, ind):
    """Add a tag ref (ref: cls_rc_refcount_get).  Idempotent unless
    the reference allows duplicates — it does not for implicit refs."""
    refs = _load(ctx)
    tag = ind["tag"]
    if tag not in refs:
        refs.append(tag)
    ctx.setxattr(_ATTR, json.dumps(refs).encode())
    return None


@cls_method("refcount", "put", CLS_METHOD_RD | CLS_METHOD_WR)
def put(ctx, ind):
    """Drop a tag ref; removing the last ref removes the object
    (ref: cls_rc_refcount_put)."""
    refs = _load(ctx)
    tag = ind["tag"]
    if tag not in refs:
        # unknown tag: treated as already-dropped (the reference
        # tolerates this unless implicit_ref accounting says otherwise)
        return None
    refs.remove(tag)
    if refs:
        ctx.setxattr(_ATTR, json.dumps(refs).encode())
    else:
        ctx.remove()
    return None


@cls_method("refcount", "set", CLS_METHOD_WR)
def set_(ctx, ind):
    """(ref: cls_rc_refcount_set)."""
    ctx.setxattr(_ATTR, json.dumps(list(ind["refs"])).encode())
    return None


@cls_method("refcount", "read", CLS_METHOD_RD)
def read(ctx, ind):
    """(ref: cls_rc_refcount_read)."""
    return {"refs": _load(ctx)}
