"""jerasure bitmatrix techniques: liberation / blaum_roth /
liber8tion (ref: src/erasure-code/jerasure/ErasureCodeJerasure.h:
152-252, schedule encode :266; VERDICT r2 #9 — ENOENT removed)."""
import hashlib
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import registry
from ceph_tpu.ec.bitmatrix import (bitmatrix_apply, bitmatrix_schedule,
                                   blaum_roth_bitmatrix, gf2_inv,
                                   gf2_matmul_device, is_mds,
                                   liber8tion_bitmatrix,
                                   liberation_bitmatrix)
from ceph_tpu.ec.interface import ErasureCodeError


def _ec(tech, k, w, packetsize=64):
    return registry.factory("jerasure", {
        "plugin": "jerasure", "technique": tech, "k": str(k),
        "w": str(w), "packetsize": str(packetsize)})


#: pinned chunk digests: the committed non-regression corpus for the
#: bitmatrix family (layouts must stay byte-stable across rounds)
PINNED = [
    ("liberation", 4, 5, "bd544d763a176669fbf3045c4747857d"),
    ("liberation", 7, 7, "63cf9777a613c8a2a11dfda7add3d648"),
    ("blaum_roth", 4, 4, "7e1d0662b047b6366bc42e7ebb944d14"),
    ("blaum_roth", 6, 6, "abccd484e2898b53d28a3d358376782e"),
    ("liber8tion", 5, 8, "0920c7e3e121dd44e1d0f5537c7d94f4"),
    ("liber8tion", 8, 8, "9e0d243fe4957d8167dea5629f781a72"),
]


@pytest.mark.parametrize("tech,k,w,digest", PINNED)
def test_pinned_chunk_fixtures(tech, k, w, digest):
    ec = _ec(tech, k, w)
    rng = np.random.default_rng(1234)
    obj = rng.integers(0, 256, 50_000, dtype=np.uint8).tobytes()
    enc = ec.encode(set(range(k + 2)), obj)
    got = hashlib.sha256(
        b"".join(enc[i].tobytes() for i in range(k + 2))).hexdigest()
    assert got[:32] == digest, (
        f"{tech} k={k} w={w} chunk layout drifted — a wire-compat "
        "break unless deliberate")


@pytest.mark.parametrize("tech,k,w", [
    ("liberation", 3, 5), ("liberation", 7, 7),
    ("blaum_roth", 5, 6), ("blaum_roth", 4, 10),
    ("liber8tion", 4, 8), ("liber8tion", 8, 8)])
def test_exhaustive_double_erasure(tech, k, w):
    ec = _ec(tech, k, w)
    rng = np.random.default_rng(7)
    obj = rng.integers(0, 256, 40_000, dtype=np.uint8).tobytes()
    enc = ec.encode(set(range(k + 2)), obj)
    for gone in itertools.combinations(range(k + 2), 2):
        avail = {i: enc[i] for i in range(k + 2) if i not in gone}
        dec = ec.decode(set(gone), avail)
        for g in gone:
            assert np.array_equal(dec[g], enc[g]), (gone, g)
    assert ec.decode_concat(
        {i: enc[i] for i in range(k)})[:len(obj)] == obj


def test_constructions_are_mds():
    for k, w in ((3, 5), (5, 7), (7, 7), (11, 11)):
        assert is_mds(k, w, liberation_bitmatrix(k, w))
    for k, w in ((4, 4), (6, 6), (10, 10)):
        assert is_mds(k, w, blaum_roth_bitmatrix(k, w))
    for k in (2, 5, 8):
        assert is_mds(k, 8, liber8tion_bitmatrix(k))


def test_liberation_minimal_density():
    """Plank's bound: the Q submatrix of a Liberation code carries
    exactly kw + k - 1 ones (minimum density)."""
    for k, w in ((4, 5), (7, 7), (5, 11)):
        g = liberation_bitmatrix(k, w)
        q = g[(k + 1) * w:]
        assert int(q.sum()) == k * w + k - 1


def test_invalid_w_rejected():
    with pytest.raises(ErasureCodeError, match="prime"):
        _ec("liberation", 3, 6)
    with pytest.raises(ErasureCodeError, match="prime"):
        _ec("blaum_roth", 3, 5)        # w+1 = 6 not prime
    with pytest.raises(ErasureCodeError, match="k <= w"):
        _ec("liberation", 8, 7)
    with pytest.raises(ErasureCodeError, match="k <= 8"):
        _ec("liber8tion", 9, 8)


def test_enoent_removed():
    """Round 2 raised ENOENT for this family; now every technique
    constructs (the registry lists them as loadable)."""
    for tech, k, w in (("liberation", 2, 3), ("blaum_roth", 2, 4),
                       ("liber8tion", 2, 8)):
        ec = _ec(tech, k, w)
        assert ec.get_chunk_count() == k + 2


def test_schedule_matches_apply():
    """The XOR schedule form computes the same coding packets as the
    matrix form (ref: jerasure_schedule_encode equivalence)."""
    g = liberation_bitmatrix(4, 5)
    coding = g[4 * 5:]
    rng = np.random.default_rng(3)
    packets = rng.integers(0, 256, (20, 128), dtype=np.uint8)
    want = bitmatrix_apply(coding, packets)
    got = np.zeros_like(want)
    for dst, src in bitmatrix_schedule(coding):
        got[dst] ^= packets[src]
    assert np.array_equal(got, want)


def test_device_form_matches_numpy():
    """The MXU bit-plane form (one int8 matmul mod 2) is byte-identical
    to the XOR-reduce form — the bitmatrix IS the companion matrix."""
    g = blaum_roth_bitmatrix(5, 6)
    coding = g[5 * 6:]
    rng = np.random.default_rng(9)
    packets = rng.integers(0, 256, (30, 256), dtype=np.uint8)
    want = bitmatrix_apply(coding, packets)
    got = np.asarray(gf2_matmul_device(coding, packets))
    assert np.array_equal(got, want)


def test_gf2_inv_roundtrip():
    rng = np.random.default_rng(11)
    for n in (4, 9, 16):
        while True:
            m = rng.integers(0, 2, (n, n)).astype(np.uint8)
            inv = gf2_inv(m)
            if inv is not None:
                break
        assert np.array_equal(
            (m.astype(np.uint8) @ inv) % 2, np.eye(n, dtype=np.uint8))
    assert gf2_inv(np.zeros((3, 3), dtype=np.uint8)) is None
