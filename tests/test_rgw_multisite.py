"""RGW multisite: realm/zonegroup/zone period model, sharded
datalog, async site-to-site replication + the keystone auth satellite
(ref: src/rgw/rgw_sync.cc, rgw_data_sync.cc, rgw_period.cc,
rgw_auth_keystone.cc; ISSUE 5)."""
import io as _io
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from xml.etree import ElementTree as ET

import pytest

from ceph_tpu.rgw import RGWGateway
from ceph_tpu.rgw.auth import KeystoneEngine, KeystoneError
from ceph_tpu.rgw.datalog import DataLog, is_dl_key, shard_obj
from ceph_tpu.rgw.multisite import (MultisiteAdmin, MultisiteError,
                                    sync_status_obj)
from ceph_tpu.testing import MiniCluster
from ceph_tpu.tools import rados_cli

VERS_ON = (b"<VersioningConfiguration>"
           b"<Status>Enabled</Status></VersioningConfiguration>")


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def ms(cluster):
    """The long-lived two-zone site: m1 master, m2 secondary.  Tests
    use per-test bucket names so they share it."""
    return cluster.rgw_multisite(zones=("m1", "m2"))


def req(gw, method, path, data=None, headers=None):
    r = urllib.request.Request(f"http://127.0.0.1:{gw.port}{path}",
                               data=data, method=method,
                               headers=headers or {})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


def _wait(cond, timeout=30.0, interval=0.05):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _get_bytes(gw, bucket, key, vid=""):
    path = f"/{bucket}/{key}"
    if vid:
        path += f"?versionId={vid}"
    try:
        return req(gw, "GET", path)[2]
    except urllib.error.HTTPError:
        return None


def _dl_entries(gw, bucket):
    """Every datalog entry of every shard, in (shard, seq) order."""
    dl = DataLog(gw.io)
    out = []
    for s in range(gw._nshards(bucket)):
        ents, _ = dl.list(bucket, s, 0, 10_000)
        out.extend(ents)
    return out


# ------------------------------------------------------- period model

def test_period_model_staging_commit_adopt(cluster):
    r = cluster.rados()
    r.pool_create("msadm", pg_num=8)
    adm = MultisiteAdmin(r.open_ioctx("msadm"))
    assert adm.period_get()["epoch"] == 0
    with pytest.raises(MultisiteError):
        adm.zonegroup_create("zg")      # realm first
    adm.realm_create("gold")
    adm.zonegroup_create("zg")
    with pytest.raises(MultisiteError):
        adm.zone_create("z1", "nope")
    adm.zone_create("z1", "zg", endpoint="http://a", master=True)
    adm.zone_create("z2", "zg", endpoint="http://b")
    # edits stage: the committed period is still empty
    assert adm.period_get()["epoch"] == 0
    assert adm.period_commit() == 1
    p = adm.period_get()
    assert p["realm"] == "gold"
    assert p["zonegroups"]["zg"]["zones"]["z1"]["master"]
    assert not p["zonegroups"]["zg"]["zones"]["z2"]["master"]
    # a no-op commit must not bump the epoch
    assert adm.period_commit() == 1
    # exactly one master: flipping z2 demotes z1
    adm.zone_modify("z2", "zg", master=True)
    assert adm.period_commit() == 2
    zones = adm.period_get()["zonegroups"]["zg"]["zones"]
    assert zones["z2"]["master"] and not zones["z1"]["master"]
    # adopt: newer period replaces, older is refused
    newer = dict(adm.period_get(), epoch=9)
    assert adm.period_adopt(newer)
    assert adm.period_get()["epoch"] == 9
    assert not adm.period_adopt(dict(newer, epoch=3))
    assert adm.period_get()["epoch"] == 9


def test_period_epoch_propagates_between_zones(ms):
    """A topology commit on the master radiates to the secondary via
    the sync agent's period probe (the `period pull` analogue)."""
    m1, m2 = ms
    adm = m1.multisite.admin
    zg = m1.multisite.my_zonegroup()[0]
    adm.zone_create("m3", zg, endpoint="")  # endpoint-less: no peer
    epoch = adm.period_commit()
    assert epoch > 1
    assert _wait(lambda: (m2.multisite.refresh(force=True) or
                          m2.multisite.epoch == epoch))
    assert "m3" in m2.multisite.period["zonegroups"][zg]["zones"]


# ----------------------------------------------------------- datalog

def test_datalog_rides_the_index_transaction(ms):
    m1, _ = ms
    req(m1, "PUT", "/dlb")
    for i in range(3):
        req(m1, "PUT", f"/dlb/k{i}", b"x%d" % i)
    ents = _dl_entries(m1, "dlb")
    puts = [e for e in ents if e["op"] == "put"]
    assert len(puts) == 3
    assert all(e["trace"] == ["m1"] for e in puts)
    # the record lives in the SAME omap object as the index entry it
    # describes (appended by cls in the same mutation batch — the
    # txn-atomicity contract)
    for e in puts:
        raw = m1.io.get_omap_vals(
            shard_obj("dlb", m1.shard_of("dlb", e["key"])))[0]
        assert e["key"] in raw
        assert any(is_dl_key(k) and json.loads(raw[k])["seq"] ==
                   e["seq"] for k in raw)
    # datalog keys never leak into listings or index dumps
    _, _, body = req(m1, "GET", "/dlb?list-type=2")
    keys = [el.text for el in ET.fromstring(body).iter("Key")]
    assert keys == ["k0", "k1", "k2"]
    assert not any(is_dl_key(k) for k in m1._index("dlb"))


def test_datalog_cursor_and_trim(ms):
    m1, _ = ms
    req(m1, "PUT", "/dlc")
    for i in range(6):
        req(m1, "PUT", "/dlc/same", b"v%d" % i)   # one shard
    s = m1.shard_of("dlc", "same")
    dl = DataLog(m1.io)
    ents, head = dl.list("dlc", s, 0, 100)
    assert head == 6 and [e["seq"] for e in ents] == list(range(1, 7))
    # cursor read: only entries past the marker
    ents, head = dl.list("dlc", s, 4, 100)
    assert [e["seq"] for e in ents] == [5, 6]
    # batch cap
    ents, _ = dl.list("dlc", s, 0, 2)
    assert [e["seq"] for e in ents] == [1, 2]
    # missing shard object reads as empty
    assert dl.list("nope", 0) == ([], 0)
    # trim drops entries but the head never regresses
    assert dl.trim("dlc", s, 4) == 4
    ents, head = dl.list("dlc", s, 0, 100)
    assert head == 6 and [e["seq"] for e in ents] == [5, 6]


# ------------------------------------------------- replication (E2E)

def test_e2e_convergence_plain_and_versioned(ms):
    """The acceptance E2E: plain writes, a versioned overwrite and
    deletes on the master converge byte-identical on the secondary."""
    m1, m2 = ms
    req(m1, "PUT", "/convp")
    req(m1, "PUT", "/convp/a", b"A-bytes")
    req(m1, "PUT", "/convp/b", b"B-bytes")
    req(m1, "DELETE", "/convp/b")
    req(m1, "PUT", "/convv")
    req(m1, "PUT", "/convv?versioning", VERS_ON)
    _, h1, _ = req(m1, "PUT", "/convv/v", b"V-one")
    _, h2, _ = req(m1, "PUT", "/convv/v", b"V-two")   # overwrite
    vid1, vid2 = h1["x-amz-version-id"], h2["x-amz-version-id"]
    _, hd, _ = req(m1, "DELETE", "/convv/v")          # delete marker
    dm_vid = hd["x-amz-version-id"]

    assert _wait(lambda: _get_bytes(m2, "convp", "a") == b"A-bytes")
    assert _wait(lambda: _get_bytes(m2, "convp", "b") is None)
    assert _wait(lambda: _get_bytes(m2, "convv", "v", vid2) == b"V-two")
    assert _get_bytes(m2, "convv", "v", vid1) == b"V-one"
    assert _get_bytes(m2, "convv", "v") is None       # dm is current
    # version stacks converge identically (vids, order, the marker)
    assert _wait(lambda: m2._index_entry("convv", "v") is not None)

    def stack(gw):
        return [(v["vid"], bool(v.get("dm")), v["mtime"], v["etag"])
                for v in gw._index_entry("convv", "v")["versions"]]
    assert _wait(lambda: stack(m2) == stack(m1))
    assert [v[0] for v in stack(m2)] == [dm_vid, vid2, vid1]
    # both agents report caught up, 0 behind shards
    assert _wait(lambda: m2.sync.caught_up() and m1.sync.caught_up())
    st = m2.sync.status()["sources"][0]
    assert st["behind_shards"] == 0 and st["lag_entries"] == 0
    # ... through the REST surface a remote `sync status` reads
    _, _, body = req(m2, "GET", "/admin/sync-status")
    rest = json.loads(body)
    assert rest["sources"][0]["caught_up"]


def test_inflight_multipart_does_not_wedge_sync(ms):
    """Multipart bookkeeping (.upload.<id>) shares the index omap but
    is not object state: the /admin/bucket dump a peer full-syncs
    from must carry objects only — the upload meta has no
    size/etag/mtime and used to crash the op synthesizer, aborting
    the whole peer round every tick (regression)."""
    m1, m2 = ms
    req(m1, "PUT", "/mpb")
    _, _, body = req(m1, "POST", "/mpb/big.bin?uploads")
    uid = ET.fromstring(body).find("UploadId").text
    req(m1, "PUT", f"/mpb/big.bin?partNumber=1&uploadId={uid}",
        b"P" * 1024)                    # upload stays in flight
    req(m1, "PUT", "/mpb/done", b"done-bytes")
    _, _, dump = req(m1, "GET", "/admin/bucket?name=mpb")
    keys = set(json.loads(dump))
    assert "done" in keys
    assert not [k for k in keys if k.startswith(".upload.")]
    # replication proceeds past the in-flight upload: converged,
    # caught up, nothing quarantined
    assert _wait(lambda: _get_bytes(m2, "mpb", "done") == b"done-bytes")
    assert _wait(lambda: m2.sync.caught_up())
    assert not [e for e in m2.sync.error_list()
                if e["bucket"] == "mpb"]


def test_delete_marker_removal_replicates(ms):
    """rmver of the delete marker restores the key on both zones."""
    m1, m2 = ms
    req(m1, "PUT", "/dmr")
    req(m1, "PUT", "/dmr?versioning", VERS_ON)
    req(m1, "PUT", "/dmr/k", b"alive")
    _, hd, _ = req(m1, "DELETE", "/dmr/k")
    dm_vid = hd["x-amz-version-id"]
    assert _wait(lambda: _get_bytes(m2, "dmr", "k") is None and
                 m2._index_entry("dmr", "k") is not None)
    req(m1, "DELETE", f"/dmr/k?versionId={dm_vid}")
    assert _get_bytes(m1, "dmr", "k") == b"alive"
    assert _wait(lambda: _get_bytes(m2, "dmr", "k") == b"alive")
    vids = [v["vid"] for v in m2._index_entry("dmr", "k")["versions"]]
    assert dm_vid not in vids


def test_overwrite_race_converges_deterministically(ms):
    """Conflicting same-key writes on both zones settle to ONE winner
    on both — newest (mtime, etag) wins, ties broken by etag so the
    zones cannot disagree."""
    m1, m2 = ms
    req(m1, "PUT", "/race")
    assert _wait(lambda: "race" in m2._buckets())
    req(m1, "PUT", "/race/k", b"AAAA")
    req(m2, "PUT", "/race/k", b"BBBB")

    def settled():
        if not (m1.sync.caught_up() and m2.sync.caught_up()):
            return False
        e1 = m1._index_entry("race", "k")
        e2 = m2._index_entry("race", "k")
        return (e1 and e2 and
                (e1["mtime"], e1["etag"]) == (e2["mtime"], e2["etag"]))
    assert _wait(settled)
    b1, b2 = _get_bytes(m1, "race", "k"), _get_bytes(m2, "race", "k")
    assert b1 == b2 and b1 in (b"AAAA", b"BBBB")
    # the survivor is the (mtime, etag)-max of the two writes
    e1 = m1._index_entry("race", "k")
    import hashlib
    etags = {hashlib.md5(b).hexdigest(): b
             for b in (b"AAAA", b"BBBB")}
    assert etags[e1["etag"]] == b1


def test_suspended_overwrite_replicates(ms):
    """Every suspended-mode overwrite reuses vid "null": the replica
    must not mistake the second overwrite for a replay of the first —
    vid-dedupe alone skipped it forever (regression)."""
    m1, m2 = ms
    vers_off = (b"<VersioningConfiguration>"
                b"<Status>Suspended</Status></VersioningConfiguration>")
    req(m1, "PUT", "/susp")
    req(m1, "PUT", "/susp?versioning", VERS_ON)
    req(m1, "PUT", "/susp?versioning", vers_off)
    req(m1, "PUT", "/susp/k", b"first")
    assert _wait(lambda: _get_bytes(m2, "susp", "k") == b"first")
    req(m1, "PUT", "/susp/k", b"second")
    assert _wait(lambda: _get_bytes(m2, "susp", "k") == b"second")

    def vids(gw):
        ent = gw._index_entry("susp", "k")
        return [v["vid"] for v in ent["versions"]] if ent else None
    assert vids(m1) == ["null"] and vids(m2) == ["null"]
    # the suspended DELETE replaces the null put with a null MARKER —
    # same vid again, and it too must replicate past the collision
    req(m1, "DELETE", "/susp/k")
    assert _wait(lambda: _get_bytes(m2, "susp", "k") is None and
                 m2._index_entry("susp", "k") is not None)
    assert m2._index_entry("susp", "k")["versions"][0]["dm"]


def test_delete_after_bumped_put_replicates(ms):
    """The del datalog record must stamp strictly after the entry it
    removed: a same-millisecond put leaves a future-bumped head mtime,
    and a wall-clock del stamp would lose the replica's newer-wins
    comparison — object deleted on the origin, kept on the replica
    forever (regression; amplified here by stamping the put 5s
    ahead)."""
    m1, m2 = ms
    req(m1, "PUT", "/dbump")
    future = time.strftime("%Y-%m-%dT%H:%M:%S",
                           time.gmtime(time.time() + 5)) + ".000Z"
    m1._now_str = lambda: future
    try:
        req(m1, "PUT", "/dbump/k", b"doomed")
    finally:
        del m1._now_str
    assert _wait(lambda: _get_bytes(m2, "dbump", "k") == b"doomed")
    req(m1, "DELETE", "/dbump/k")       # wall clock < the put's stamp
    assert _get_bytes(m1, "dbump", "k") is None
    assert _wait(lambda: _get_bytes(m2, "dbump", "k") is None)


def test_plain_put_replay_after_delete_does_not_resurrect(cluster):
    """A peer's plain-put record arriving AFTER the local delete of
    the same key must stay dead: the delete leaves a per-key tombstone
    whose stamp the late put loses to (regression: the delete removed
    the index entry outright, so the replayed put landed on an absent
    key and resurrected the object)."""
    gw = RGWGateway(cluster.rados(), pool="rgw-tomb")
    gw._create_bucket("tb")
    put = {"key": "k", "op": "put", "mode": "plain", "size": 3,
           "etag": "e1", "mtime": "2026-08-03T12:00:00.000Z",
           "trace": ["zx"]}
    assert gw.sync_apply("tb", put, b"v1!", "zx")
    shard = shard_obj("tb", gw.shard_of("tb", "k"))
    gw.io.exec(shard, "rgw", "obj_delete_plain", {"key": "k"})
    assert gw._index_entry("tb", "k") is None
    assert "k" not in gw._index("tb")   # tombstone hides from listings
    # a replay of the SAME put (another peer's re-log) must not land
    assert not gw.sync_apply("tb", put, b"v1!", "zy")
    assert gw._index_entry("tb", "k") is None
    # ... nor a different put still stamped before the delete
    older = dict(put, etag="e2", mtime="2026-08-03T12:00:00.500Z")
    assert not gw.sync_apply("tb", older, b"v2!", "zy")
    assert gw._index_entry("tb", "k") is None
    # deleting the dead key again is a clean no-op
    out = gw.io.exec(shard, "rgw", "obj_delete_plain", {"key": "k"})
    assert out["removed"] == []
    # a LOCAL put revives the key and stamps past the tombstone, so
    # replicas apply it over their own tombstones
    out = gw.io.exec(shard, "rgw", "obj_store",
                     {"key": "k", "mode": "plain", "size": 3,
                      "etag": "e3", "mtime": "2026-08-03T12:00:01.000Z",
                      "obj": ".kv3"})
    assert out["removed"] == []         # tombstone backs no object
    ent = gw._index_entry("tb", "k")
    assert ent["etag"] == "e3"
    raw = gw.io.get_omap_vals_by_keys(shard, ["k"])
    assert json.loads(raw["k"])["mtime"] > "2026-08-07"


def test_sync_del_on_absent_key_leaves_tombstone(cluster):
    """Third-zone ordering: a replicated delete can arrive BEFORE the
    put it chased.  It must leave a tombstone on the absent key so the
    late put still loses; a put strictly newer than the delete wins."""
    gw = RGWGateway(cluster.rados(), pool="rgw-tomb3")
    gw._create_bucket("tc")
    dele = {"key": "k", "op": "del",
            "mtime": "2026-08-03T12:00:01.000Z", "trace": ["zx"]}
    assert gw.sync_apply("tc", dele, None, "zx")
    assert gw._index_entry("tc", "k") is None
    assert not gw.sync_apply("tc", dele, None, "zy")     # replay
    late = {"key": "k", "op": "put", "mode": "plain", "size": 3,
            "etag": "eo", "mtime": "2026-08-03T12:00:00.900Z",
            "trace": ["zy"]}
    assert not gw.sync_apply("tc", late, b"old", "zy")
    assert gw._index_entry("tc", "k") is None
    # delete-wins-ties: an equal-stamp put was ordered before the
    # delete on the origin (datalog order), so it must lose here too
    tied = dict(late, etag="et", mtime=dele["mtime"])
    assert not gw.sync_apply("tc", tied, b"tie", "zy")
    assert gw._index_entry("tc", "k") is None
    fresh = dict(late, etag="ef", mtime="2026-08-03T12:00:01.100Z")
    assert gw.sync_apply("tc", fresh, b"new", "zy")
    assert gw._index_entry("tc", "k")["etag"] == "ef"


def test_cross_zone_delete_beats_racing_put(ms):
    """E2E resurrection window: m2 deletes a key while m1's racing
    (older-stamped) put is still in flight.  Both zones must converge
    on 'deleted' — the put record reaching m2 after its delete used to
    land on the absent key and resurrect the object on m2 only."""
    m1, m2 = ms
    req(m1, "PUT", "/tdrace")
    req(m1, "PUT", "/tdrace/k", b"v1")
    assert _wait(lambda: _get_bytes(m2, "tdrace", "k") == b"v1")
    # warm the m2->m1 pipeline on THIS bucket before the race: a
    # round-tripped delete proves m1's incremental cursor for m2's
    # tdrace log is live — otherwise the cursor gets initialized at
    # m2's CURRENT head mid-stall (full-sync floor) and would skip
    # straight past the del record the test depends on
    req(m1, "PUT", "/tdrace/warm", b"w")
    assert _wait(lambda: _get_bytes(m2, "tdrace", "warm") == b"w")
    req(m2, "DELETE", "/tdrace/warm")
    assert _wait(lambda: _get_bytes(m1, "tdrace", "warm") is None)
    # stall m1's OUTBOUND pulls: m2's delete stays unseen at m1 while
    # m1's racing put replicates to m2 (m1 still serves m2's pulls)
    real = m1.peer_request

    def stall(endpoint, method, path, *a, **k):
        if path == "/admin/log":
            raise urllib.error.URLError("stalled")
        return real(endpoint, method, path, *a, **k)
    m1.peer_request = stall
    try:
        req(m2, "DELETE", "/tdrace/k")  # wall-clock stamp, newest
        # m1's concurrent overwrite: forced-past stamp bumps to just
        # above v1 — strictly OLDER than m2's delete
        m1._now_str = lambda: "2000-01-01T00:00:00.000Z"
        try:
            req(m1, "PUT", "/tdrace/k", b"v2-racer")
        finally:
            del m1._now_str
        assert _get_bytes(m1, "tdrace", "k") == b"v2-racer"
        # m2 pulls the racing put and must refuse it: its tombstone
        # outranks the put's stamp
        assert _wait(lambda: m2.sync.caught_up())
        assert _get_bytes(m2, "tdrace", "k") is None
    finally:
        m1.peer_request = real
    # m1 hears the delete and drops its own racer: converged deleted
    assert _wait(lambda: _get_bytes(m1, "tdrace", "k") is None)
    assert _get_bytes(m2, "tdrace", "k") is None


def test_forwarded_master_refusal_passes_through(ms):
    """A forwarded metadata op the master answers-and-refuses must
    surface the master's real S3 error: 409 BucketNotEmpty is
    permanent, the old blanket 503 invited pointless retries
    (regression)."""
    m1, m2 = ms
    req(m1, "PUT", "/fwderr")
    assert _wait(lambda: "fwderr" in m2._buckets())
    xml = (b'<?xml version="1.0"?><Error><Code>BucketNotEmpty</Code>'
           b"<Message>fwderr</Message></Error>")
    real = m2.peer_request

    def refuse(endpoint, method, path, *a, **k):
        if method == "DELETE" and path == "/fwderr":
            raise urllib.error.HTTPError(endpoint + path, 409,
                                         "Conflict", {},
                                         _io.BytesIO(xml))
        return real(endpoint, method, path, *a, **k)
    m2.peer_request = refuse
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(m2, "DELETE", "/fwderr")
    finally:
        m2.peer_request = real
    assert ei.value.code == 409
    assert ET.fromstring(ei.value.read()).findtext("Code") == \
        "BucketNotEmpty"


def test_bucket_404_mid_round_skips_not_backoff(ms):
    """A bucket vanishing between the round's registry snapshot and
    its log fetch must skip THAT bucket only: the old peer-level
    PeerError backed off the whole (healthy) peer, stalling every
    other bucket's replication (regression)."""
    m1, m2 = ms
    req(m1, "PUT", "/gone")
    req(m1, "PUT", "/gone/k", b"g")
    req(m1, "PUT", "/alive")
    assert _wait(lambda: _get_bytes(m2, "gone", "k") == b"g")
    real = m2.peer_request

    def vanish(endpoint, method, path, *a, **k):
        body = a[0] if a else k.get("body")
        if path == "/admin/log" and body and b'"gone"' in body:
            raise urllib.error.HTTPError(endpoint + path, 404,
                                         "Not Found", {},
                                         _io.BytesIO(b"{}"))
        return real(endpoint, method, path, *a, **k)
    m2.peer_request = vanish
    try:
        req(m1, "PUT", "/alive/k", b"still-flowing")
        assert _wait(lambda:
                     _get_bytes(m2, "alive", "k") == b"still-flowing")
        # the peer stayed healthy through the 404s: no backoff state
        assert _wait(lambda: m2.sync.status()["sources"][0]["state"]
                     != "backoff" and m2.sync.caught_up())
    finally:
        m2.peer_request = real
    assert _wait(lambda: m2.sync.caught_up())


def test_versioned_same_mtime_insert_converges(cluster):
    """Concurrent same-mtime versioned puts from two zones must land
    in the SAME stack order on both sides (vid tie-break — mtime
    alone ordered them by arrival, and the two zones see opposite
    arrival orders)."""
    gw = RGWGateway(cluster.rados(), pool="rgw-tie")
    mt = "2026-08-03T12:00:00.000Z"
    a = {"key": "k", "op": "put", "mode": "enabled", "vid": "va",
         "size": 4, "etag": "ea", "mtime": mt, "trace": ["zx"]}
    b = dict(a, vid="vb", etag="eb")
    for bucket, order in (("cva", (a, b)), ("cvb", (b, a))):
        gw._create_bucket(bucket)
        for ent in order:
            assert gw.sync_apply(bucket, ent,
                                 b"dat-" + ent["vid"].encode(), "zx")
    sa = [v["vid"] for v in gw._index_entry("cva", "k")["versions"]]
    sb = [v["vid"] for v in gw._index_entry("cvb", "k")["versions"]]
    assert sa == sb == ["vb", "va"]
    # the ORIGIN's local insert bumps a same-millisecond write past
    # the head (strictly increasing per-key mtimes): sequential
    # writes keep read-your-writes, and replicas replaying the
    # origin's stamps by (mtime, vid) reproduce the same order
    gw._create_bucket("cvl")
    o1 = {"key": "k", "mode": "enabled", "vid": "va", "size": 4,
          "etag": "ea", "mtime": mt, "obj": ".x1"}
    o2 = dict(o1, vid="vb", etag="eb", obj=".x2")
    s = gw.shard_of("cvl", "k")
    for ent in (o2, o1):        # arrival order vb then va, same ms
        gw.io.exec(shard_obj("cvl", s), "rgw", "obj_store", ent)
    vers = gw._index_entry("cvl", "k")["versions"]
    assert [v["vid"] for v in vers] == ["va", "vb"]  # last write wins
    assert vers[0]["mtime"] > vers[1]["mtime"]       # bumped stamp


def test_master_bucket_delete_propagates(ms):
    """DELETE of an (empty) bucket on the master tombstones the
    registry: the secondary drops its copy, and the master's own sync
    round must NOT resurrect the bucket from the secondary's listing
    (it did, before tombstones — the client's 204 was silently
    undone)."""
    m1, m2 = ms
    req(m1, "PUT", "/bdel")
    assert _wait(lambda: "bdel" in m2._buckets())
    req(m1, "DELETE", "/bdel")
    assert "bdel" not in m1._buckets()
    assert _wait(lambda: "bdel" not in m2._buckets())
    time.sleep(0.3)             # several sync rounds
    assert "bdel" not in m1._buckets()
    assert "bdel" not in m2._buckets()
    # recreate under the same name: a fresh incarnation (new
    # "created" stamp) retires any stale cursors and full-syncs —
    # new writes must arrive on the secondary
    req(m1, "PUT", "/bdel")
    req(m1, "PUT", "/bdel/k2", b"second-life")
    assert _wait(lambda: _get_bytes(m2, "bdel", "k2") ==
                 b"second-life")


def test_datalog_auto_trim_and_lagging_peer_blocks(cluster):
    """Datalog auto-trim (ROADMAP multisite residual): a shard's .dl.
    records go once EVERY registered peer's durable cursor has passed
    them; a registered-but-lagging peer blocks the trim for exactly
    the records it still needs."""
    t1, t2 = cluster.rgw_multisite(zones=("t1", "t2"),
                                   sync_interval=0.02)
    req(t1, "PUT", "/tb")
    for i in range(6):
        req(t1, "PUT", f"/tb/k{i}", b"v%d" % i)
    assert _wait(lambda: t2.sync.caught_up())
    assert len(_dl_entries(t1, "tb")) == 6
    # the peer answers /admin/sync-markers with its DURABLE cursors;
    # durability trails the in-memory apply by up to one sync round
    # (caught_up flips before that round's _persist lands), so wait
    assert _wait(lambda: sum(
        int(m) for m in t2.sync.markers_for("t1")
        .get("tb", {"cursors": {}})["cursors"].values()) >= 6)

    # every record is behind t2's durable cursor: the trim takes all
    def _trim_converged():
        t1.sync.datalog_trim_round()
        return _dl_entries(t1, "tb") == []
    assert _wait(_trim_converged)
    assert t1.sync.datalog_trimmed >= 6

    # make t2 lag (agent stopped, zone still registered) and write on
    t2.sync.stop()
    for i in range(4):
        req(t1, "PUT", f"/tb/l{i}", b"w%d" % i)
    assert len(_dl_entries(t1, "tb")) == 4
    # the lagging peer's cursors sit below the new records: no trim
    assert t1.sync.datalog_trim_round() == 0
    assert len(_dl_entries(t1, "tb")) == 4
    # sequences never regress across a trim: the new records continue
    # past the trimmed range, so a resumed peer cannot re-read gaps
    assert min(e["seq"] for e in _dl_entries(t1, "tb")) > 0

    # incarnation guard: recreate the bucket while the peer (still
    # stopped) holds the OLD incarnation's cursors — its stale high
    # markers say nothing about the fresh datalog, so no trim
    for key in [f"k{i}" for i in range(6)] + [f"l{i}" for i in range(4)]:
        req(t1, "DELETE", f"/tb/{key}")
    req(t1, "DELETE", "/tb")
    req(t1, "PUT", "/tb")
    req(t1, "PUT", "/tb/fresh", b"new-life")
    entries = _dl_entries(t1, "tb")
    fresh = len(entries)
    assert fresh >= 1
    stale = t2.sync.markers_for("t1")["tb"]
    assert sum(int(m) for m in stale["cursors"].values()) >= 6
    # the fresh datalog restarted below the stale cursors: without
    # the incarnation check these records WOULD be trimmed
    assert min(e["seq"] for e in entries) <= max(
        int(m) for m in stale["cursors"].values())
    assert t1.sync.datalog_trim_round() == 0
    assert len(_dl_entries(t1, "tb")) == fresh


def test_registry_tombstones_pruned_after_peers_pass(ms):
    """Bounded tombstone growth (the PR 5 residual): a bucket-delete
    tombstone is pruned from BOTH zones' registries once every peer's
    sync has demonstrably passed the deletion — and never while a
    peer still holds a live pre-deletion copy (pruning then would let
    the next listing pull resurrect the bucket)."""
    import time as _t
    m1, m2 = ms
    req(m1, "PUT", "/btomb")
    assert _wait(lambda: "btomb" in m2._buckets())
    # hold m2's pull so the pre-prune state is observable: while m2
    # still lists the bucket LIVE, m1 must keep its tombstone
    # (pruning now would let m1's next listing pull resurrect the
    # bucket).  _sync_peer is stubbed (a backoff entry would be reset
    # by an in-flight round's success path), and a round-length is
    # waited out BEFORE the delete so an in-flight pull that started
    # pre-stub cannot have seen the tombstone.
    held = m2.sync._sync_peer
    m2.sync._sync_peer = lambda peer, views=None: 0
    try:
        _t.sleep(0.3)
        req(m1, "DELETE", "/btomb")
        assert "btomb" in m1._buckets_raw()
        assert "deleted" in m1._buckets_raw()["btomb"]
        _t.sleep(0.4)           # several m1 sync rounds
        assert "btomb" in m2._buckets(), \
            "hold failed: peer applied it"
        assert "btomb" in m1._buckets_raw(), \
            "tombstone pruned while the peer still held a live copy"
    finally:
        m2.sync._sync_peer = held
    # prune against a fabricated (fresh) live view is likewise a
    # no-op, and so is one whose fetch stamp PREDATES the deletion
    # (stale absence evidence must never prune)
    from ceph_tpu.cls.rgw import now_str
    live_view = {"m2": (now_str(),
                        {"btomb": {"created": "1970-01-01T00:00:00"}})}
    assert m1.prune_registry_tombstones(live_view) == 0
    stale_view = {"m2": ("1970-01-01T00:00:00.000Z", {})}
    assert m1.prune_registry_tombstones(stale_view) == 0
    # once both agents run rounds that reach every peer, the
    # tombstones drain from BOTH registries (count 0 = bounded)
    assert _wait(lambda: "btomb" not in m1._buckets_raw() and
                 "btomb" not in m2._buckets_raw())
    # and the bucket stays deleted — pruning must not resurrect
    time.sleep(0.3)
    assert "btomb" not in m1._buckets()
    assert "btomb" not in m2._buckets()
    # a recreate after the prune behaves like any fresh bucket
    req(m1, "PUT", "/btomb")
    req(m1, "PUT", "/btomb/k", b"reborn")
    assert _wait(lambda: _get_bytes(m2, "btomb", "k") == b"reborn")


def test_reserved_object_keys_rejected(ms):
    """Client objects must not collide with the index omap's
    bookkeeping namespaces — a PUT literally named `.dlmeta` would
    overwrite the shard's datalog head."""
    m1, _ = ms
    req(m1, "PUT", "/rsv")
    for key in (".dlmeta", ".dl.00000001", ".upload.deadbeef"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(m1, "PUT", f"/rsv/{key}", b"x")
        assert ei.value.code == 400


def test_secondary_config_ops_forward_and_survive(ms):
    """Bucket config PUT/DELETE on a secondary forwards to the master
    like bucket creation does — without the forward, the next sync
    round's master-copy adoption silently reverted the change the
    client got a 200 for."""
    m1, m2 = ms
    req(m2, "PUT", "/cfgf")
    assert _wait(lambda: "cfgf" in m1._buckets())
    req(m2, "PUT", "/cfgf?versioning", VERS_ON)
    assert m1._buckets()["cfgf"].get("versioning") == "Enabled"
    time.sleep(0.3)     # several sync rounds of master-copy adoption
    assert m2._buckets()["cfgf"].get("versioning") == "Enabled"
    # bucket DELETE forwards too: gone on both, never resurrected
    req(m2, "DELETE", "/cfgf")
    assert "cfgf" not in m1._buckets()
    time.sleep(0.3)
    assert "cfgf" not in m2._buckets()


def test_secondary_metadata_ops_forward_to_master(ms):
    """Bucket creation on the secondary lands on the master in the
    same request (forward_to_master), not a sync round later."""
    m1, m2 = ms
    req(m2, "PUT", "/fwd")
    assert "fwd" in m1._buckets()       # no sync wait: forwarded
    assert "fwd" in m2._buckets()


# ------------------------------------- kill / restart, notifications

class _Receiver:
    def __init__(self):
        self.events = []
        rec = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                rec.events.append(json.loads(body))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def keys(self):
        return [e["Records"][0]["s3"]["object"]["key"]
                for e in self.events]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_kill_mid_sync_restart_resumes_from_markers(cluster):
    """The acceptance thrash: kill the secondary gateway mid-sync,
    restart it, and the agent resumes from the durable markers — full
    convergence, no duplicate applies, no re-fired notifications, no
    second full sync."""
    k1, k2 = cluster.rgw_multisite(zones=("k1", "k2"),
                                   sync_interval=0.02)
    rec = _Receiver()
    try:
        # the SAME topic name on both zones points at the receiver: a
        # replica that wrongly re-fired would be caught red-handed
        for gw in (k1, k2):
            req(gw, "POST",
                f"/?Action=CreateTopic&Name=kt&push-endpoint="
                f"http%3A%2F%2F127.0.0.1%3A{rec.port}%2F")
        req(k1, "PUT", "/kb")
        req(k1, "PUT", "/kb?notification",
            b'<NotificationConfiguration><TopicConfiguration>'
            b'<Id>n</Id><Topic>arn:aws:sns:::kt</Topic>'
            b'<Event>s3:ObjectCreated:*</Event>'
            b'</TopicConfiguration></NotificationConfiguration>')
        n = 40
        payload = {f"o{i:02d}": b"payload-%02d" % i for i in range(n)}
        for k, v in payload.items():
            req(k1, "PUT", f"/kb/{k}", v)
        # let the secondary get partway, then kill it unclean
        _wait(lambda: len(k2._index("kb")) >= 5, timeout=20)
        cluster.kill_rgw_zone(k2)
        k2b = cluster.restart_rgw_zone(k2)
        assert _wait(lambda: len(k2b._index("kb")) == n, timeout=40)
        for k, v in payload.items():
            assert _get_bytes(k2b, "kb", k) == v
        assert _wait(lambda: k2b.sync.caught_up(), timeout=40)
        # resumed incrementally from the durable markers: the fresh
        # agent never re-ran full sync ...
        assert k2b.sync.full_syncs == 0
        # ... and never re-applied a write: one datalog record per
        # object across the kill/restart, no duplicates
        puts = [e for e in _dl_entries(k2b, "kb") if e["op"] == "put"]
        assert sorted(e["key"] for e in puts) == sorted(payload)
        # the origin fired one event per object; the replica fired
        # none (zone-trace guard) — give stragglers a grace window
        assert _wait(lambda: len(rec.events) >= n, timeout=20)
        time.sleep(0.5)
        assert sorted(rec.keys()) == sorted(payload)
        # the durable marker object really is the resume point
        vals, _ = k2b.io.get_omap_vals(sync_status_obj("k1"))
        assert any(k.startswith("m.kb.") for k in vals)
    finally:
        rec.close()


def test_recreate_while_replica_down_discards_stale_content(cluster):
    """Delete + recreate a bucket while the replica sleeps: the old
    incarnation's datalog died with its bucket, so its object deletes
    can never replicate — the revived replica must DISCARD its stale
    copy and rebuild from the new incarnation, not converge to
    old ∪ new (regression: cluster-wide-deleted objects were served
    and listed there forever while sync-status said caught up)."""
    r1, r2 = cluster.rgw_multisite(zones=("r1", "r2"),
                                   sync_interval=0.02)
    req(r1, "PUT", "/rb")
    req(r1, "PUT", "/rb/old1", b"old-1")
    req(r1, "PUT", "/rb/old2", b"old-2")
    assert _wait(lambda: _get_bytes(r2, "rb", "old1") == b"old-1" and
                 _get_bytes(r2, "rb", "old2") == b"old-2")
    assert _wait(lambda: r2.sync.caught_up())
    cluster.kill_rgw_zone(r2)
    req(r1, "DELETE", "/rb/old1")
    req(r1, "DELETE", "/rb/old2")
    req(r1, "DELETE", "/rb")
    req(r1, "PUT", "/rb")                      # new incarnation
    req(r1, "PUT", "/rb/new1", b"new-1")
    r2b = cluster.restart_rgw_zone(r2)
    assert _wait(lambda: _get_bytes(r2b, "rb", "new1") == b"new-1")
    assert _wait(lambda: _get_bytes(r2b, "rb", "old1") is None and
                 _get_bytes(r2b, "rb", "old2") is None)
    assert set(r2b._index("rb")) == {"new1"}
    assert _wait(lambda: r2b.sync.caught_up())
    # both registries agree on the new incarnation's generation
    assert r2b._buckets_raw()["rb"]["created"] == \
        r1._buckets_raw()["rb"]["created"]


def test_poisoned_entry_quarantined_and_retried(cluster):
    """A datalog entry that will not apply lands in the per-shard
    error list and is retried every round — the cursor keeps moving
    past it (the reference's error_repo, not thread death)."""
    p1, p2 = cluster.rgw_multisite(zones=("p1", "p2"),
                                   sync_interval=0.02)
    orig = p2.sync_apply
    poisoned = threading.Event()
    poisoned.set()

    def wrapper(bucket, ent, data, src, **kw):
        if poisoned.is_set() and ent["key"] == "poison":
            raise RuntimeError("injected apply failure")
        return orig(bucket, ent, data, src, **kw)
    p2.sync_apply = wrapper

    req(p1, "PUT", "/pz")
    req(p1, "PUT", "/pz/ok1", b"one")
    req(p1, "PUT", "/pz/poison", b"toxic")
    req(p1, "PUT", "/pz/ok2", b"two")
    # the healthy entries apply; the cursor moved past the poison
    assert _wait(lambda: _get_bytes(p2, "pz", "ok1") == b"one" and
                 _get_bytes(p2, "pz", "ok2") == b"two")
    assert _get_bytes(p2, "pz", "poison") is None
    assert _wait(lambda: len(p2.sync.error_list()) == 1)
    rec = p2.sync.error_list()[0]
    assert rec["entry"]["key"] == "poison" and rec["bucket"] == "pz"
    assert "injected apply failure" in rec["err"]
    # it is RETRIED, not parked: the retry counter climbs
    assert _wait(lambda: p2.sync.error_list()[0]["retries"] >= 2)
    st = [s for s in p2.sync.status()["sources"]
          if s["source"] == "p1"][0]
    assert st["errors"] == 1 and not st["caught_up"]
    # the error list is durable (a restart would retry it too)
    assert _wait(lambda: any(
        k.startswith("e.pz.") and json.loads(v)
        for k, v in p2.io.get_omap_vals(
            sync_status_obj("p1"))[0].items()))
    # lift the poison: the retry drains the list and converges
    poisoned.clear()
    assert _wait(lambda: _get_bytes(p2, "pz", "poison") == b"toxic")
    assert _wait(lambda: not p2.sync.error_list())
    assert _wait(lambda: p2.sync.caught_up())


# ------------------------------------------------------ CLI satellite

def test_rados_cli_rgw_verbs(cluster, ms):
    m1, m2 = ms
    out = _io.StringIO()
    rc = rados_cli.main(["rgw", "period", "get", "--pool", "rgw-m1"],
                        rados=cluster.rados(), out=out)
    assert rc == 0
    period = json.loads(out.getvalue())
    assert period["realm"] == "gold" and period["epoch"] >= 1
    out = _io.StringIO()
    rc = rados_cli.main(
        ["rgw", "sync-status", "--endpoint",
         f"http://127.0.0.1:{m2.port}"],
        rados=cluster.rados(), out=out)
    assert rc == 0
    txt = out.getvalue()
    assert "zone m2" in txt and "source m1:" in txt
    out = _io.StringIO()
    rc = rados_cli.main(
        ["rgw", "datalog", "status", "dlc", "--pool", "rgw-m1",
         "--shards", "4"],
        rados=cluster.rados(), out=out)
    assert rc == 0 and "head" in out.getvalue()
    # unknown verb shapes fail with usage, not a traceback
    assert rados_cli.main(["rgw", "realm", "frob"],
                          rados=cluster.rados(),
                          out=_io.StringIO()) == 1


# ------------------------------------------------- keystone satellite

class _KeystoneStub:
    """Stub keystone: GET /v3/auth/tokens validates X-Subject-Token
    against a token table (the test's 'external identity service')."""

    def __init__(self, tokens):
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                tok = self.headers.get("X-Subject-Token", "")
                if self.path != "/v3/auth/tokens" or \
                        tok not in stub.tokens:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(
                    {"token": stub.tokens[tok]}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.tokens = tokens
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def keystone():
    ks = _KeystoneStub({
        "tok-good": {"user": {"name": "alice"}},
        "tok-expired": {"user": {"name": "bob"},
                        "expires_at": time.time() - 5},
        "tok-iso": {"user": {"name": "carol"},
                    "expires_at": "2099-01-01T00:00:00Z"}})
    yield ks
    ks.close()


def test_amz_date_parses_utc_under_dst_tz():
    """x-amz-date is UTC: parsing it through mktime applied the
    host's DST offset, skewing every signed request — including all
    peer sync traffic between secured zones — by 3600s for half the
    year (regression)."""
    import calendar
    import os
    from ceph_tpu.rgw.auth import _parse_amz_date
    old = os.environ.get("TZ")
    os.environ["TZ"] = "America/New_York"     # observes DST in July
    time.tzset()
    try:
        assert _parse_amz_date("20260715T120000Z") == \
            calendar.timegm((2026, 7, 15, 12, 0, 0, 0, 0, 0))
    finally:
        if old is None:
            os.environ.pop("TZ", None)
        else:
            os.environ["TZ"] = old
        time.tzset()


def test_keystone_engine_validation(keystone):
    eng = KeystoneEngine(keystone.url)
    assert eng.validate("tok-good") == "alice"
    assert eng.validate("tok-iso") == "carol"
    with pytest.raises(KeystoneError) as ei:
        eng.validate("tok-unknown")
    assert ei.value.status == 401
    with pytest.raises(KeystoneError) as ei:
        eng.validate("")
    assert ei.value.status == 401
    # expired token is EACCES (403), not merely invalid
    with pytest.raises(KeystoneError) as ei:
        eng.validate("tok-expired")
    assert ei.value.status == 403 and ei.value.code == "AccessDenied"
    # keystone down -> 503, never a free pass
    keystone.close()
    with pytest.raises(KeystoneError) as ei:
        eng.validate("tok-never-seen")
    assert ei.value.status == 503


def test_keystone_cache_still_enforces_expiry(keystone):
    """A cached acceptance must not outlive the token: expiry is
    checked on every use, cache hit or not."""
    keystone.tokens["tok-brief"] = {"user": {"name": "dave"},
                                    "expires_at": time.time() + 0.6}
    eng = KeystoneEngine(keystone.url)
    assert eng.validate("tok-brief") == "dave"   # cached now
    time.sleep(0.8)
    with pytest.raises(KeystoneError) as ei:
        eng.validate("tok-brief")                # cache hit, expired
    assert ei.value.status == 403


def test_keystone_gateway_config_gated(cluster, keystone):
    g = RGWGateway(cluster.rados(), pool="ksgw",
                   keystone_url=keystone.url)
    g.start()
    try:
        st, _, _ = req(g, "PUT", "/ksb",
                       headers={"X-Auth-Token": "tok-good"})
        assert st == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(g, "PUT", "/ksb2",
                headers={"X-Auth-Token": "tok-expired"})
        assert ei.value.code == 403
        assert b"AccessDenied" in ei.value.read()
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(g, "PUT", "/ksb2",
                headers={"X-Auth-Token": "tok-bogus"})
        assert ei.value.code == 401
        # keystone as the ONLY engine: a token-less request fails
        # closed instead of falling back to anonymous
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(g, "PUT", "/ksb3")
        assert ei.value.code == 401
    finally:
        g.shutdown()
    # config-gated: a gateway WITHOUT keystone_url ignores the header
    g2 = RGWGateway(cluster.rados(), pool="ksgw2")
    g2.start()
    try:
        st, _, _ = req(g2, "PUT", "/anon",
                       headers={"X-Auth-Token": "tok-bogus"})
        assert st == 200
    finally:
        g2.shutdown()


def test_keystone_only_multisite_replicates(cluster, keystone, capsys):
    """Two keystone-secured zones (no keyring): sync traffic signs
    SigV4 as the system user and carries no token, so the auth gate
    must verify that signature instead of failing it closed as
    token-less — or a keystone-secured zone never receives a byte of
    sync traffic (regression).  Also drives `rados rgw sync-status`
    both unsigned (refused, not 'unreachable') and signed."""
    from ceph_tpu.rgw.auth import sign_request
    k1, k2 = cluster.rgw_multisite(
        zones=("ks1", "ks2"), zonegroup="kszg", realm="ksr",
        keystone_url=keystone.url, system_key=("sys-ak", "sys-sk"))
    tok = {"X-Auth-Token": "tok-good"}

    def get(gw, path):
        try:
            return req(gw, "GET", path, headers=dict(tok))[2]
        except urllib.error.HTTPError:
            return None
    try:
        st, _, _ = req(k1, "PUT", "/ksms", headers=dict(tok))
        assert st == 200
        req(k1, "PUT", "/ksms/k", b"ks-bytes", headers=dict(tok))
        assert _wait(lambda: get(k2, "/ksms/k") == b"ks-bytes")
        assert _wait(lambda: k2.sync.caught_up() and
                     k1.sync.caught_up())
        # wrong system secret is refused, not silently accepted
        bad = sign_request("GET", "/", {"host": f"127.0.0.1:{k1.port}"},
                           b"", "sys-ak", "wrong-sk")
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(k1, "GET", "/", headers=bad)
        assert ei.value.code == 403
        # the CLI against the secured admin surface: unsigned is a
        # REFUSAL (the old message claimed the gateway was down)...
        ep = f"http://127.0.0.1:{k2.port}"
        assert rados_cli.main(
            ["rgw", "sync-status", "--endpoint", ep],
            rados=cluster.rados(), out=_io.StringIO()) == 1
        assert "gateway refused" in capsys.readouterr().err
        # ...and signing with the system key reads the live status
        buf = _io.StringIO()
        assert rados_cli.main(
            ["rgw", "sync-status", "--endpoint", ep,
             "--access", "sys-ak", "--secret", "sys-sk"],
            rados=cluster.rados(), out=buf) == 0
        assert "ks1" in buf.getvalue()
        # kill + restart: the revived gateway keeps its security
        # config — an anonymous restart would have every signed pull
        # refused by its peers and replication would never resume
        cluster.kill_rgw_zone(k2)
        k2 = cluster.restart_rgw_zone(k2)
        assert k2.system_key == ("sys-ak", "sys-sk")
        assert k2.keystone is not None
        req(k1, "PUT", "/ksms/k2", b"after-restart", headers=dict(tok))
        assert _wait(lambda: get(k2, "/ksms/k2") == b"after-restart")
        assert _wait(lambda: k2.sync.caught_up())
    finally:
        for g in (k1, k2):
            g.shutdown()
            if g in cluster.rgws:
                cluster.rgws.remove(g)


def test_forwarded_create_adopts_master_stamp(ms):
    """Bucket creation forwarded from a secondary must adopt the
    master's created stamp: independently-stamped registries would
    make the incarnation guard (sync_reset_bucket) treat the SAME
    bucket as two generations and discard fresh local content
    (regression)."""
    m1, m2 = ms
    req(m2, "PUT", "/fwdstamp")             # forwarded to master m1
    assert _wait(lambda: "fwdstamp" in m1._buckets() and
                 "fwdstamp" in m2._buckets())
    assert m1._buckets_raw()["fwdstamp"]["created"] == \
        m2._buckets_raw()["fwdstamp"]["created"]
    # a server-side copy whose SOURCE is a bookkeeping key is a
    # clean 404, not a handler crash
    req(m1, "PUT", "/fwdstamp/ok", b"ok")
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(m1, "PUT", "/fwdstamp/copy", headers={
            "x-amz-copy-source": "/fwdstamp/.dlmeta"})
    assert ei.value.code == 404


def test_reserved_key_reads_are_clean_404(ms):
    """GET/HEAD of a bookkeeping key must be a clean NoSuchKey: the
    index record behind `.dlmeta` has no etag/size, so serving it
    crashed the handler (HEAD) or 500'd (GET) instead of 404ing
    (regression; the write side already rejects 400)."""
    m1, _ = ms
    req(m1, "PUT", "/resk")
    req(m1, "PUT", "/resk/x", b"x")     # seeds .dlmeta on a shard
    for key in (".dlmeta", ".dl.0000000000000001", ".upload.dead"):
        for method in ("GET", "HEAD"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                req(m1, method, f"/resk/{key}")
            assert ei.value.code == 404, (method, key)
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(m1, "PUT", "/resk/.dlmeta", b"z")
    assert ei.value.code == 400


def test_synth_retry_applies_real_source_state(ms):
    """A quarantined synthesizer failure retries against the key's
    CURRENT state at the source: the old fabricated plain-put stub
    (no mtime/etag) either applied corrupt metadata or silently
    drained without syncing (regression)."""
    from ceph_tpu.client.rados import RadosError
    from ceph_tpu.rgw.gateway import _index_obj
    m1, m2 = ms
    req(m1, "PUT", "/synthb")
    req(m1, "PUT", "/synthb/k", b"real-bytes")
    assert _wait(lambda: _get_bytes(m2, "synthb", "k") == b"real-bytes")
    assert _wait(lambda: m2.sync.caught_up())
    ep = f"http://127.0.0.1:{m1.port}"
    # a key that vanished at the source drains (0 applied, no crash)
    ghost = {"key": "ghost", "op": "synth", "vid": None, "trace": []}
    assert m2.sync._apply("m1", ep, "synthb", ghost) == 0
    # surgically lose m2's index entry (offline-surgery style), then
    # retry the synth record: the REAL state comes back, with the
    # origin's metadata — not empty-string mtime/etag
    for s in range(m2._nshards("synthb")):
        try:
            m2.io.remove_omap_keys(_index_obj("synthb", s), ["k"])
        except RadosError:
            pass
    assert _get_bytes(m2, "synthb", "k") is None
    ent = {"key": "k", "op": "synth", "vid": None, "trace": []}
    assert m2.sync._apply("m1", ep, "synthb", ent) == 1
    restored = m2._index_entry("synthb", "k")
    assert restored["etag"] and restored["mtime"]
    assert restored["etag"] == m1._index_entry("synthb", "k")["etag"]
    assert _get_bytes(m2, "synthb", "k") == b"real-bytes"
    # an already-synced key is an idempotent skip on retry
    assert m2.sync._apply("m1", ep, "synthb", ent) == 0


def test_datalog_head_probe_returns_no_entries(ms):
    """max=0 is the head-probe contract (DataLog.head, the pre-dump
    head capture in full sync): it must ship ZERO entries, not one —
    the limit check ran after the append (regression)."""
    m1, _ = ms
    req(m1, "PUT", "/dlh")
    req(m1, "PUT", "/dlh/k", b"x")
    dl = DataLog(m1.io)
    heads = 0
    for s in range(m1._nshards("dlh")):
        ents, head = dl.list("dlh", s, 0, 0)
        assert ents == []
        heads += head
    assert heads >= 1           # the put IS in some shard's log


def test_zero_peer_datalog_trims_by_age_respecting_fullsync(cluster):
    """Zero-peer residual (ROADMAP): a zone with NO registered peers
    has no cursors to trim behind, so its datalog ages out instead —
    bounded per round, and never past an in-flight full-sync floor
    (a peer that just pulled the bucket index dump starts its
    incremental cursors at the dump-time heads)."""
    solo, = cluster.rgw_multisite(zones=("solo",), zonegroup="zgsolo",
                                  realm="lone", sync_interval=0.5)
    assert solo.multisite.peers() == []
    req(solo, "PUT", "/ab")
    for i in range(6):
        req(solo, "PUT", f"/ab/k{i}", b"v%d" % i)
    first = _dl_entries(solo, "ab")
    assert len(first) == 6
    # entries are younger than the age bar: nothing trims
    assert solo.sync.datalog_trim_round() == 0
    assert len(_dl_entries(solo, "ab")) == 6

    # an in-flight full sync (the bucket index dump) floors the trim:
    # records past the dump-time heads must survive any aging
    assert json.loads(req(solo, "GET", "/admin/bucket?name=ab")[2])
    floors = solo.fullsync_floor("ab")
    assert floors and sum(floors.values()) >= 6
    for i in range(3):
        req(solo, "PUT", f"/ab/post{i}", b"p%d" % i)
    time.sleep(0.15)
    solo.sync.NOPEER_MAX_AGE_S = 0.1        # everything now "old"
    assert _wait(lambda: (solo.sync.datalog_trim_round() or True) and
                 len(_dl_entries(solo, "ab")) == 3)
    # exactly the pre-dump records went; the post-dump ones survived
    left = {e["key"] for e in _dl_entries(solo, "ab")}
    assert left == {f"post{i}" for i in range(3)}

    # grace expiry releases the floor: the rest ages out too, still
    # bounded per shard per round
    solo.FULLSYNC_GRACE_S = 0.0
    assert solo.fullsync_floor("ab") is None
    solo.sync.NOPEER_TRIM_MAX = 1
    assert _wait(lambda: (solo.sync.datalog_trim_round() or True) and
                 _dl_entries(solo, "ab") == [])
    assert solo.sync.datalog_trimmed >= 9
