"""ceph_erasure_code_benchmark-compatible CLI.

Flags and output format follow the reference harness
(ref: src/test/erasure-code/ceph_erasure_code_benchmark.cc:40-139 options,
:151-181 encode loop, :246-312 decode loop): prints "seconds\tKiB" and, on
decode, byte-verifies the reconstructed chunks against the originals
(ref: :220-231).

Example:
    python -m ceph_tpu.tools.ec_bench --plugin tpu --workload encode \
        --size $((1024*1024)) --iterations 64 --parameter k=8 --parameter m=4
"""
from __future__ import annotations

import argparse
import itertools
import sys
import time

import numpy as np

from ceph_tpu.ec import registry


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="ec_bench")
    p.add_argument("--plugin", "-P", default="jerasure")
    p.add_argument("--workload", "-w", default="encode",
                   choices=["encode", "decode"])
    p.add_argument("--size", "-s", type=int, default=1 << 20,
                   help="total size in bytes per iteration")
    p.add_argument("--iterations", "-i", type=int, default=1)
    p.add_argument("--erasures", "-e", type=int, default=1)
    p.add_argument("--erasures-generation", "-S", default="random",
                   choices=["random", "exhaustive"])
    p.add_argument("--erased", type=int, action="append", default=None,
                   help="explicit chunk index to erase (repeatable)")
    p.add_argument("--parameter", "-p", action="append", default=[],
                   help="k=v plugin profile parameter (repeatable)")
    p.add_argument("--verbose", "-v", action="store_true")
    return p.parse_args(argv)


def _choose_erasures(n: int, count: int, mode: str, explicit, rng):
    if explicit:
        yield tuple(explicit)
        return
    if mode == "exhaustive":
        yield from itertools.combinations(range(n), count)
    else:
        while True:
            yield tuple(sorted(rng.choice(n, size=count, replace=False)))


def run(args) -> float:
    profile = {}
    for kv in args.parameter:
        key, _, val = kv.partition("=")
        profile[key] = val
    ec = registry.factory(args.plugin, profile)
    k, n = ec.get_data_chunk_count(), ec.get_chunk_count()
    rng = np.random.default_rng(795)
    data = rng.integers(0, 256, args.size, dtype=np.uint8).tobytes()
    want_all = set(range(n))

    if args.workload == "encode":
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            ec.encode(want_all, data)
        elapsed = time.perf_counter() - t0
    else:
        encoded = ec.encode(want_all, data)
        gen = _choose_erasures(n, args.erasures, args.erasures_generation,
                               args.erased, rng)
        elapsed = 0.0
        done = 0
        for erasures in gen:
            if done >= args.iterations:
                break
            avail = {i: c for i, c in encoded.items() if i not in erasures}
            t0 = time.perf_counter()
            decoded = ec.decode(want_all, avail)
            elapsed += time.perf_counter() - t0
            # correctness gate (ref: ceph_erasure_code_benchmark.cc:220-231)
            for i in range(n):
                if not np.array_equal(decoded[i], encoded[i]):
                    raise SystemExit(f"chunk {i} differs after decode "
                                     f"(erasures={erasures})")
            done += 1

    kib = args.size / 1024 * args.iterations
    print(f"{elapsed:f}\t{kib:.0f}")
    return elapsed


def main(argv=None):
    run(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
