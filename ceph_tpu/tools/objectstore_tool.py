"""ceph-objectstore-tool: offline surgery on a stopped OSD's store
(ref: src/tools/ceph_objectstore_tool.cc; VERDICT r3 #7).

Operates directly on the data directory of a DOWN OSD (BlueStore
layout: `block` + `kv/`):

    --op list                     list PG collections (or objects
                                  with --pgid)
    --op info    --pgid P         object count + durable log bounds
    --op fsck                     BlueStore checksum/reference fsck
    --op export  --pgid P --file F   serialize the whole PG: objects
                                  (data, attrs, omap, snap clones) +
                                  the pgmeta omap (durable pg_log)
    --op import  --file F         restore an exported PG into this
                                  (possibly different) OSD's store —
                                  the disk-swap / PG-rescue flow the
                                  reference tool exists for
    --op remove  --pgid P         delete a PG collection outright
    --op list-snaps --pgid P      per-object snapshot state: clone
                                  tags, covered snapids, presence
    --op dump-snap-index --pgid P the durable snaptrim state: the
                                  snap->clone index awaiting trim (the
                                  crash-resume cursor) + purged_snaps

The export blob uses the typed wire codec, so it round-trips the
exact ObjectIds (snap clones included) and the pg_log omap that
peering reads — an imported PG peers from real history instead of
backfilling."""
from __future__ import annotations

import argparse
import sys

from ..msg import encoding as wire
from ..osd.types import PG
from ..store import BlueStore, ObjectId, StoreError, Transaction

EXPORT_VERSION = 1


def _open_store(path: str) -> BlueStore:
    st = BlueStore(path)
    st.mount()
    return st


def _parse_pgid(s: str) -> PG:
    pool, ps = s.split(".", 1)
    return PG(int(pool), int(ps, 16))


def _pg_cid(pg: PG) -> str:
    from ..osd.ec_backend import pg_cid
    return pg_cid(pg)


def list_pgs(store) -> list[str]:
    out = []
    for cid in store.list_collections():
        if cid.startswith("pg_"):
            out.append(cid[3:])
    return sorted(out)


def list_objects(store, pg: PG) -> list[str]:
    cid = _pg_cid(pg)
    if not store.collection_exists(cid):
        raise StoreError("ENOENT", f"pg {pg}")
    return [repr(o) for o in sorted(store.collection_list(cid),
                                    key=lambda o: (o.name, o.snap))]


def pg_info(store, pg: PG) -> dict:
    from ..osd.replicated_backend import ReplicatedPGShard
    cid = _pg_cid(pg)
    if not store.collection_exists(cid):
        raise StoreError("ENOENT", f"pg {pg}")
    shard = ReplicatedPGShard(pg, store, create=False)
    head, tail = shard.log_info()
    objs = [o for o in store.collection_list(cid)
            if o.name != "pgmeta"]
    return {"pgid": str(pg), "objects": len(objs),
            "log_head": str(head), "log_tail": str(tail),
            "log_entries": len(shard.pg_log.log)}


def export_pg(store, pg: PG) -> bytes:
    """Serialize a whole PG — every object (head + snap clones) with
    data/attrs/omap, plus pgmeta's omap (the durable pg_log)."""
    cid = _pg_cid(pg)
    if not store.collection_exists(cid):
        raise StoreError("ENOENT", f"pg {pg}")
    objects = []
    for oid in sorted(store.collection_list(cid),
                      key=lambda o: (o.name, o.snap)):
        objects.append({
            "oid": oid,
            "data": bytes(store.read(cid, oid, 0, 0)),
            "attrs": dict(store.getattrs(cid, oid)),
            "omap": dict(store.omap_get(cid, oid)),
        })
    return wire.encode({"version": EXPORT_VERSION, "pgid": pg,
                        "objects": objects})


def import_pg(store, blob: bytes, force: bool = False) -> PG:
    """Restore an exported PG.  Refuses to clobber an existing
    collection unless forced (ref: the tool's same guard)."""
    rec = wire.decode(blob)
    if not isinstance(rec, dict) or \
            rec.get("version") != EXPORT_VERSION:
        raise StoreError("EINVAL", "not a PG export blob")
    pg = rec["pgid"]
    cid = _pg_cid(pg)
    if store.collection_exists(cid):
        if not force:
            raise StoreError("EEXIST", f"pg {pg} already present "
                                       "(--force to overwrite)")
        txn = Transaction()
        for oid in store.collection_list(cid):
            txn.remove(cid, oid)
        txn.remove_collection(cid)
        store.queue_transaction(txn)
    txn = Transaction()
    txn.create_collection(cid)
    for ent in rec["objects"]:
        oid = ent["oid"]
        txn.touch(cid, oid)
        if ent["data"]:
            txn.write(cid, oid, 0, ent["data"])
        if ent["attrs"]:
            txn.setattrs(cid, oid, ent["attrs"])
        if ent["omap"]:
            txn.omap_setkeys(cid, oid, ent["omap"])
    store.queue_transaction(txn)
    return pg


def list_snaps(store, pg: PG) -> list[dict]:
    """Per-object snapshot state: head snap_seq, clone tags + the
    snapids each clone covers, and whether the clone object actually
    exists — the offline view of the SnapSet scrub compares."""
    from ..osd.ec_backend import OI_ATTR
    cid = _pg_cid(pg)
    if not store.collection_exists(cid):
        raise StoreError("ENOENT", f"pg {pg}")
    out = []
    for oid in sorted(store.collection_list(cid),
                      key=lambda o: (o.name, o.snap)):
        if oid.name == "pgmeta" or oid.snap != -2:
            continue
        try:
            oi = dict(store.getattr(cid, oid, OI_ATTR))
        except StoreError:
            continue
        clones = {int(t): list(c)
                  for t, c in oi.get("clones", {}).items()}
        if not clones and not oi.get("snap_seq"):
            continue
        out.append({
            "oid": oid.name,
            "snap_seq": oi.get("snap_seq", 0),
            "whiteout": bool(oi.get("whiteout")),
            "clones": {
                str(t): {"covers": c,
                         "present": store.exists(
                             cid, ObjectId(oid.name, snap=t))}
                for t, c in sorted(clones.items())},
        })
    return out


def dump_snap_index(store, pg: PG) -> dict:
    """The durable snaptrim state: the snap->clone index entries still
    awaiting trim (the resume cursor) + the purged_snaps interval set
    — what a promoted primary would act on."""
    from ..osd.snap_mapper import SnapMapper
    cid = _pg_cid(pg)
    if not store.collection_exists(cid):
        raise StoreError("ENOENT", f"pg {pg}")
    sm = SnapMapper(store, cid)
    return {"pgid": str(pg), "index": sm.dump(),
            "purged_snaps": sm.purged_snaps().to_list()}


def remove_pg(store, pg: PG) -> int:
    cid = _pg_cid(pg)
    if not store.collection_exists(cid):
        raise StoreError("ENOENT", f"pg {pg}")
    objs = store.collection_list(cid)
    txn = Transaction()
    for oid in objs:
        txn.remove(cid, oid)
    txn.remove_collection(cid)
    store.queue_transaction(txn)
    return len(objs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph-tpu-objectstore-tool")
    ap.add_argument("--data-path", required=True,
                    help="the STOPPED OSD's store directory")
    ap.add_argument("--op", required=True,
                    choices=["list", "info", "fsck", "export",
                             "import", "remove", "list-snaps",
                             "dump-snap-index"])
    ap.add_argument("--pgid", default="",
                    help="pg id as <pool>.<ps-hex>")
    ap.add_argument("--file", default="", help="export/import blob")
    ap.add_argument("--force", action="store_true")
    ap.add_argument("--repair", action="store_true",
                    help="(fsck) placeholder — errors are reported; "
                         "repair rides scrub in a live cluster")
    a = ap.parse_args(argv)
    store = _open_store(a.data_path)
    try:
        if a.op == "list":
            if a.pgid:
                for line in list_objects(store, _parse_pgid(a.pgid)):
                    print(line)
            else:
                for p in list_pgs(store):
                    print(p)
        elif a.op == "info":
            import json
            print(json.dumps(pg_info(store, _parse_pgid(a.pgid))))
        elif a.op == "list-snaps":
            import json
            for ent in list_snaps(store, _parse_pgid(a.pgid)):
                print(json.dumps(ent))
        elif a.op == "dump-snap-index":
            import json
            print(json.dumps(dump_snap_index(store,
                                             _parse_pgid(a.pgid))))
        elif a.op == "fsck":
            errors = store.fsck()
            for e in errors:
                print(e)
            print(f"fsck: {len(errors)} error(s)")
            return 1 if errors else 0
        elif a.op == "export":
            blob = export_pg(store, _parse_pgid(a.pgid))
            with open(a.file, "wb") as f:
                f.write(blob)
            print(f"exported {a.pgid}: {len(blob)} bytes")
        elif a.op == "import":
            with open(a.file, "rb") as f:
                pg = import_pg(store, f.read(), force=a.force)
            print(f"imported {pg}")
        elif a.op == "remove":
            n = remove_pg(store, _parse_pgid(a.pgid))
            print(f"removed {a.pgid}: {n} object(s)")
        return 0
    except StoreError as ex:
        print(f"error: {ex}", file=sys.stderr)
        return 1
    finally:
        store.umount()


if __name__ == "__main__":
    sys.exit(main())
