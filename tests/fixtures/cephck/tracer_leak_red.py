"""red: traced values stored past the trace boundary."""
import jax
import jax.numpy as jnp

_DEBUG_TAPS = []


class Coder:
    @jax.jit
    def encode(self, v):
        out = jnp.matmul(v, v)
        self.last = out             # leaks the tracer on self
        return out


@jax.jit
def encode(v):
    out = v * 2
    _DEBUG_TAPS.append(out)         # leaks into module state
    return out
