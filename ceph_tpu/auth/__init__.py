"""cephx-lite: shared-secret authentication with session tickets.

The reference's cephx (ref: src/auth/cephx/CephxProtocol.{h,cc}) in
reduced form, keeping the protocol shape:

* a **KeyRing** holds per-entity secrets; the mon holds everyone's
  (ref: src/auth/KeyRing.cc, mon AuthMonitor's key server);
* a client proves identity with an HMAC over a fresh nonce + server
  challenge (ref: CephxAuthorizer's challenge round-trip), and both
  sides DERIVE the session key from (entity secret, nonce, challenge)
  — it never crosses the wire, mirroring how cephx wraps the session
  key under the entity secret;
* the mon answers with a **ticket**: the session key + entity +
  expiry, sealed under the *service secret* every daemon shares
  (ref: service ticket encrypted with the service's rotating key) —
  daemons can open it; clients cannot forge it;
* afterwards every message carries `auth = (ticket, sig)` where sig
  is an HMAC under the session key over the message header AND
  payload fields, the msgr-v2 message-signing analogue
  (ref: CEPHX_REQUIRE_SIGNATURES / ProtocolV2 auth signatures): a
  captured ticket cannot be replayed onto a forged op.

Sealing is authenticate-only (HMAC tag, no confidentiality): the
threat model this layer exists to test is impersonation and
unauthorized cluster access, not wire snooping; swap `_seal/_open`
for AES-GCM to get the rest.

Modes (ref: auth_cluster_required option): "none" (default) or
"cephx".
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import json
import os
import time

from ..common.log import dout
from ..common.lockdep import make_lock
from ..msg.messages import MAuthReply, MAuthRequest

SERVICE_ENTITY = "service"           # the shared service-secret slot

#: daemon-class entity prefixes (everything else is a client).  The
#: class rides inside the sealed ticket, so a client cannot upgrade
#: itself (ref: cephx caps — "allow *" for daemons vs client caps).
DAEMON_PREFIXES = frozenset({"osd", "mon", "mds", "mgr", SERVICE_ENTITY})

#: message types a *client*-class ticket may send to daemons
#: (ref: the effect of default client caps: client ops + mon
#: subscriptions/commands + mds requests + cap-release acks, which
#: travel client->mds as MClientCaps; daemon-internal traffic like
#: RepOpWrite/ECSubWrite/MMap/MOSDFailure is daemon-only)
CLIENT_ALLOWED = frozenset({
    "OSDOp", "MMonSubscribe", "MMonCommand", "MClientRequest",
    "MClientCaps"})

#: replay-window size: how far behind the highest-seen signing seq a
#: message may arrive before it is considered stale (tolerates
#: multi-connection reordering; ref: cephx challenge freshness)
REPLAY_WINDOW = 1024

#: renew this long before ticket expiry (ref: MonClient's
#: _check_auth_rotating renews before ttl runs out)
RENEW_MARGIN = 60.0


def entity_class(entity: str) -> str:
    return ("daemon" if entity.split(".", 1)[0] in DAEMON_PREFIXES
            else "client")


def generate_key() -> str:
    return os.urandom(16).hex()


def _mac(secret: str, blob: bytes) -> str:
    return _hmac.new(secret.encode(), blob,
                     hashlib.sha256).hexdigest()


class KeyRing:
    """entity -> secret (ref: src/auth/KeyRing.h).  JSON file format:
    {"osd.0": "<hex>", ...}."""

    def __init__(self, keys: dict[str, str] | None = None):
        self.keys: dict[str, str] = dict(keys or {})

    @classmethod
    def generate(cls, entities) -> "KeyRing":
        kr = cls({SERVICE_ENTITY: generate_key()})
        for e in entities:
            kr.keys[e] = generate_key()
        return kr

    @classmethod
    def load(cls, path: str) -> "KeyRing":
        with open(path) as f:
            return cls(json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.keys, f, indent=1)

    def get(self, entity: str) -> str | None:
        return self.keys.get(entity)

    def subset(self, *entities: str) -> "KeyRing":
        """A daemon's keyring: its own key + the service secret."""
        return KeyRing({e: self.keys[e] for e in
                        (*entities, SERVICE_ENTITY) if e in self.keys})


def attach_cephx(ms, entity: str, keyring: "KeyRing",
                 verifier: bool = True) -> None:
    """Wire a messenger for cephx: self-minted signer (daemons hold
    the service secret — the reference's rotating service keys) plus,
    for daemon endpoints, an inbound verifier.  `verifier=False` is
    for a daemon's embedded *client* messenger (e.g. the MDS's RADOS
    client), which signs as the daemon but must not gate inbound
    replies.  One place for the gate so mon/OSD/MDS cannot drift, and
    a keyring missing the service secret fails loud here instead of
    deep inside _mac."""
    svc = keyring.get(SERVICE_ENTITY)
    if svc is None:
        raise ValueError(
            f"cephx for {entity}: keyring has no service secret")
    ms.auth_signer = CephxClient.self_mint(entity, svc)
    if verifier:
        ms.auth_verifier = CephxVerifier(svc)


def _derive_session_key(secret: str, nonce: str, challenge: str) -> str:
    return _mac(secret, f"session|{nonce}|{challenge}".encode())


def _seal(secret: str, payload: dict) -> dict:
    blob = json.dumps(payload, sort_keys=True)
    return {"blob": blob, "tag": _mac(secret, blob.encode())}


def _open(secret: str, sealed: dict) -> dict | None:
    if not isinstance(sealed, dict) or "blob" not in sealed:
        return None
    if not _hmac.compare_digest(
            _mac(secret, sealed["blob"].encode()),
            sealed.get("tag", "")):
        return None
    return json.loads(sealed["blob"])


def _canon(msg) -> bytes:
    """Byte-stable digest input covering header AND payload: a
    captured ticket must not be reattachable to a forged op (the TCP
    transport is reachable by unauthenticated processes).  Uses the
    typed wire codec — deterministic for our payload domain, and dict
    insertion order survives the decode, so receiver-side
    re-canonicalization matches what was signed."""
    import dataclasses

    from ..msg import encoding as wire
    fields = tuple((f.name, getattr(msg, f.name))
                   for f in dataclasses.fields(msg)
                   if f.name != "auth")
    return wire.encode((msg.type_name, fields))


class CephxServer:
    """Mon-side authenticator (ref: CephxServiceHandler +
    AuthMonitor's key server)."""

    def __init__(self, keyring: KeyRing,
                 ticket_ttl: float = 3600.0):
        self.keyring = keyring
        self.ttl = ticket_ttl

    def handle_request(self, msg: MAuthRequest) -> MAuthReply:
        secret = self.keyring.get(msg.entity)
        challenge = os.urandom(8).hex()
        if secret is None:
            return MAuthReply(result=-1, errstr="unknown entity")
        want = _mac(secret, f"auth|{msg.entity}|{msg.nonce}".encode())
        if not _hmac.compare_digest(want, msg.sig):
            dout("auth", 1).write("cephx: bad signature from %s",
                                  msg.entity)
            return MAuthReply(result=-13, errstr="bad signature")
        # fresh challenge binds the session key to this exchange
        session_key = _derive_session_key(secret, msg.nonce, challenge)
        expires = time.time() + self.ttl
        ticket = _seal(self.keyring.get(SERVICE_ENTITY), {
            "entity": msg.entity, "session_key": session_key,
            "cls": entity_class(msg.entity), "expires": expires})
        return MAuthReply(result=0, challenge=challenge,
                          ticket=ticket, expires=expires)


class CephxClient:
    """Per-daemon/client signer (ref: CephxClientHandler)."""

    def __init__(self, entity: str, secret: str):
        import itertools
        self.entity = entity
        self.secret = secret
        self.nonce = os.urandom(8).hex()
        self.session_key: str | None = None
        self.ticket: dict | None = None
        self.expires: float = 0.0
        #: guards the (session_key, ticket) pair: renewal replies land
        #: while other threads sign, and a MAC under the new key paired
        #: with the old ticket would be dropped by every verifier
        self._lock = make_lock(f"auth.cephx.{entity}")
        #: monotonic signing sequence — receivers use it for replay
        #: freshness (itertools.count is atomic under the GIL)
        self._seq = itertools.count(1)
        #: self_mint daemons keep the service secret to re-mint locally
        self._mint_secret: str | None = None
        self._mint_ttl: float = 0.0
        self._renew_sent: float = 0.0
        #: wire-handshake renewal: the channel owner (Objecter) sets
        #: this to a callable that re-sends the MAuthRequest; sign()
        #: fires it (throttled, off-thread) so EVERY traffic pattern —
        #: data ops, mds sessions, mon commands — renews, not just
        #: Objecter.operate()
        self.renew_hook = None

    def build_request(self) -> MAuthRequest:
        self.nonce = os.urandom(8).hex()
        return MAuthRequest(
            entity=self.entity, nonce=self.nonce,
            sig=_mac(self.secret,
                     f"auth|{self.entity}|{self.nonce}".encode()))

    def ingest_reply(self, msg: MAuthReply) -> bool:
        if msg.result != 0:
            return False
        key = _derive_session_key(self.secret, self.nonce,
                                  msg.challenge)
        with self._lock:          # atomic (key, ticket, expiry) swap
            self.session_key = key
            self.ticket = msg.ticket
            self.expires = msg.expires
        return True

    @property
    def authenticated(self) -> bool:
        return self.session_key is not None

    @property
    def needs_renewal(self) -> bool:
        """True inside the renewal margin.  Callers owning a wire
        channel re-run the MAuthRequest handshake; self-minted daemons
        renew transparently in sign()."""
        return (self.session_key is not None and self.expires > 0 and
                time.time() > self.expires - RENEW_MARGIN)

    def should_send_renewal(self, throttle: float = 5.0) -> bool:
        """Rate-limited renewal trigger for wire-handshake clients."""
        if self._mint_secret is not None or not self.needs_renewal:
            return False
        with self._lock:
            now = time.time()
            if now - self._renew_sent < throttle:
                return False
            self._renew_sent = now
        return True

    @classmethod
    def self_mint(cls, entity: str,
                  service_secret: str,
                  ttl: float = 365 * 86400.0) -> "CephxClient":
        """Daemon-side shortcut: an entity that HOLDS the service
        secret (mon/osd/mds — the reference distributes rotating
        service keys to daemons) mints its own ticket locally instead
        of doing the wire handshake."""
        c = cls(entity, service_secret)
        c._mint_secret = service_secret
        c._mint_ttl = ttl
        c._remint()
        return c

    def _remint(self) -> None:
        key = generate_key()
        expires = time.time() + self._mint_ttl
        ticket = _seal(self._mint_secret, {
            "entity": self.entity, "session_key": key,
            "cls": entity_class(self.entity), "expires": expires})
        with self._lock:
            self.session_key = key
            self.expires = expires
            self.ticket = ticket

    def sign(self, msg):
        """Attach (ticket, seq, sig) to an outgoing message copy.  The
        seq is covered by the MAC, so a captured message cannot be
        replayed past the verifier's freshness window."""
        if self.session_key is None:
            return msg
        if self._mint_secret is not None and self.needs_renewal:
            self._remint()       # local renewal: we hold the secret
        elif self.renew_hook is not None and self.should_send_renewal():
            # off-thread: sign() runs under transport locks, and the
            # hook re-enters the messenger to send the MAuthRequest
            import threading
            threading.Thread(target=self.renew_hook,
                             daemon=True).start()
        seq = next(self._seq)
        with self._lock:          # key+ticket must be the same session
            key, ticket = self.session_key, self.ticket
        msg.auth = {"ticket": ticket, "seq": seq,
                    "sig": _mac(key, _canon(msg) + b"|seq=%d" % seq)}
        return msg


class CephxVerifier:
    """Service-side message gate (ref: the require-signatures check in
    Protocol/ms_verify_authorizer)."""

    #: always-allowed types: the auth handshake itself, plus replies
    #: going TO clients (verified by them only if they hold keys)
    EXEMPT = {"MAuthRequest", "MAuthReply"}

    def __init__(self, service_secret: str):
        self.service_secret = service_secret
        self._lock = make_lock("auth.cephx_verifier")
        #: (entity, ticket_tag) -> (max_seq, seen-set) replay state;
        #: keyed per session so a restarted entity gets a fresh window
        self._sessions: "dict[tuple, tuple[int, set]]" = {}

    def verify(self, msg) -> bool:
        if msg.type_name in self.EXEMPT:
            return True
        auth = getattr(msg, "auth", None)
        if not auth:
            return False
        ticket = _open(self.service_secret, auth.get("ticket"))
        if ticket is None or ticket["expires"] < time.time():
            return False
        # entity-class gate: a client-class ticket cannot send
        # daemon-internal traffic (RepOpWrite/ECSubWrite/MMap/
        # MOSDFailure/paxos...) even with a valid signature
        if ticket.get("cls", "client") == "client":
            if msg.type_name not in CLIENT_ALLOWED:
                dout("auth", 1).write(
                    "cephx: client-class %s may not send %s",
                    ticket.get("entity"), msg.type_name)
                return False
            # identity binding: a client ticket speaks only for its own
            # entity — services authorize state changes (cap releases,
            # ops) by msg.src, and src is MAC-covered, so without this
            # check any authenticated client could stamp another
            # client's name and e.g. forge its MClientCaps release.
            # Daemon-class is exempt: every service-secret holder can
            # mint any daemon ticket anyway (and the MDS's embedded
            # RADOS client legitimately signs as its daemon identity).
            if ticket.get("entity") != getattr(msg, "src", None):
                dout("auth", 1).write(
                    "cephx: ticket for %s on message from %s",
                    ticket.get("entity"), getattr(msg, "src", None))
                return False
        seq = auth.get("seq", 0)
        want = _mac(ticket["session_key"],
                    _canon(msg) + b"|seq=%d" % seq)
        if not _hmac.compare_digest(want, auth.get("sig", "")):
            return False
        return self._check_fresh(ticket, auth.get("ticket"), seq)

    def _check_fresh(self, ticket: dict, sealed: dict, seq: int) -> bool:
        """Per-(entity, session) replay window: each signing seq is
        accepted once; anything at or below max_seen - REPLAY_WINDOW is
        stale.  Tolerates reordering inside the window."""
        key = (ticket.get("entity"), (sealed or {}).get("tag"))
        with self._lock:
            entry = self._sessions.pop(key, None)  # re-insert = LRU
            max_seq, seen = entry if entry is not None else (0, set())
            floor = max(0, max(max_seq, seq) - REPLAY_WINDOW)
            if seq <= floor or seq in seen:
                self._sessions[key] = (max_seq, seen)
                dout("auth", 1).write("cephx: replayed/stale seq %d "
                                      "from %s", seq, key[0])
                return False
            seen.add(seq)
            if len(seen) > 2 * REPLAY_WINDOW:   # prune below the floor
                seen = {s for s in seen if s > floor}
            if len(self._sessions) >= 4096:
                # evict least-recently-used sessions (dict order is
                # re-insertion order, so the front IS the LRU end);
                # active daemon sessions stay hot and keep their
                # replay windows — only dead/stale peers age out
                for k in list(self._sessions)[:256]:
                    del self._sessions[k]
            self._sessions[key] = (max(max_seq, seq), seen)
        return True
