"""Journal library: an append-only replicated log over RADOS objects.

The src/journal/ analogue (ref: Journaler/JournalMetadata/
ObjectRecorder — the generic journal librbd journaling and rbd-mirror
are built on): a journal is a header object carrying the registered
clients and their commit positions, plus a chain of numbered data
objects holding crc-framed entries.

* `append(tag, data)` frames an entry (crc32c + typed-codec payload)
  onto the active data object, rolling to the next object at
  `object_size` (ref: ObjectRecorder append + overflow);
* readers `replay(handler, from_pos)` from any position — a torn tail
  (crash mid-append) fails its crc and cleanly ends the stream
  (ref: JournalPlayer fetch/replay);
* every consumer registers a client and advances its commit position
  (header omap, ref: JournalMetadata::committed);
* `trim()` removes whole data objects all clients have passed
  (ref: JournalTrimmer).
"""
from __future__ import annotations

import struct
from typing import Callable

from ..client import RadosError
from ..common.crc32c import crc32c
from ..msg import encoding as wire

_FRAME = struct.Struct("!II")        # length, crc32c


def header_obj(journal_id: str) -> str:
    return f"journal.{journal_id}"


def data_obj(journal_id: str, objno: int) -> str:
    return f"journal_data.{journal_id}.{objno:08x}"


class JournalError(Exception):
    pass


class Journaler:
    """One client's handle on a journal (ref: src/journal/Journaler.h)."""

    def __init__(self, ioctx, journal_id: str, client_id: str,
                 object_size: int = 1 << 22):
        self.io = ioctx
        self.jid = journal_id
        self.client_id = client_id
        self.object_size = object_size
        self._hdr = header_obj(journal_id)

    # -- lifecycle ------------------------------------------------------
    def create(self) -> None:
        """Create the journal (idempotent)."""
        try:
            self.io.create(self._hdr)
            self.io.set_omap(self._hdr, {
                "active": b"0", "first": b"0"})
        except RadosError:
            pass

    def exists(self) -> bool:
        try:
            self.io.stat(self._hdr)
            return True
        except RadosError:
            return False

    def remove(self) -> None:
        first, active = self._range()
        for objno in range(first, active + 1):
            try:
                self.io.remove(data_obj(self.jid, objno))
            except RadosError:
                pass
        try:
            self.io.remove(self._hdr)
        except RadosError:
            pass

    # -- clients (ref: JournalMetadata register/unregister_client) ------
    def register_client(self) -> None:
        key = f"client.{self.client_id}"
        vals = self.io.get_omap_vals_by_keys(self._hdr, [key])
        if key not in vals:
            self.io.set_omap(self._hdr, {
                key: wire.encode({"pos": (0, 0)})})

    def unregister_client(self) -> None:
        try:
            self.io.remove_omap_keys(self._hdr,
                                     [f"client.{self.client_id}"])
        except RadosError:
            pass

    def clients(self) -> dict[str, dict]:
        vals, _ = self.io.get_omap_vals(self._hdr)
        return {k[len("client."):]: wire.decode(v)
                for k, v in vals.items() if k.startswith("client.")}

    # -- positions ------------------------------------------------------
    def _range(self) -> tuple[int, int]:
        vals, _ = self.io.get_omap_vals(self._hdr)
        if "active" not in vals:
            raise JournalError(f"no journal {self.jid!r}")
        return int(vals.get("first", b"0")), int(vals["active"])

    def commit_position(self) -> tuple[int, int]:
        key = f"client.{self.client_id}"
        vals = self.io.get_omap_vals_by_keys(self._hdr, [key])
        if key not in vals:
            raise JournalError(f"client {self.client_id!r} not "
                               "registered")
        return tuple(wire.decode(vals[key])["pos"])

    def commit(self, pos: tuple[int, int]) -> None:
        """Advance this client's committed position."""
        self.io.set_omap(self._hdr, {
            f"client.{self.client_id}": wire.encode({"pos": tuple(pos)})})

    # -- append (ref: ObjectRecorder) -----------------------------------
    def append(self, tag: str, data) -> tuple[int, int]:
        """Frame + append one entry; returns the position AFTER it."""
        _first, active = self._range()
        body = wire.encode({"tag": tag, "data": data})
        frame = _FRAME.pack(len(body), crc32c(0, body)) + body
        try:
            size = self.io.stat(data_obj(self.jid, active))["size"]
        except RadosError:
            size = 0
        if size >= self.object_size:
            active += 1
            self.io.set_omap(self._hdr,
                             {"active": str(active).encode()})
            size = 0
        self.io.append(data_obj(self.jid, active), frame)
        return (active, size + len(frame))

    # -- replay (ref: JournalPlayer) ------------------------------------
    def replay(self, handler: Callable[[str, object], None],
               from_pos: tuple[int, int] | None = None
               ) -> tuple[int, int]:
        """Feed entries after `from_pos` (default: this client's commit
        position) to `handler(tag, data)`; returns the new position.
        A torn tail ends the stream cleanly."""
        pos = tuple(from_pos) if from_pos is not None \
            else self.commit_position()
        first, active = self._range()
        objno, off = pos
        if objno < first:
            # the position's object was trimmed away: resume at the
            # start of the first surviving object — carrying the old
            # byte offset into a different object would land mid-frame
            # and read as a permanently torn tail
            objno, off = first, 0
        while objno <= active:
            try:
                raw = self.io.read(data_obj(self.jid, objno))
            except RadosError:
                raw = b""
            while off + _FRAME.size <= len(raw):
                n, crc = _FRAME.unpack_from(raw, off)
                body = raw[off + _FRAME.size: off + _FRAME.size + n]
                if len(body) < n or crc32c(0, body) != crc:
                    return (objno, off)     # torn tail
                ent = wire.decode(body)
                handler(ent["tag"], ent["data"])
                off += _FRAME.size + n
            if objno == active:
                break
            objno += 1
            off = 0
        return (objno, off)

    # -- trim (ref: JournalTrimmer) -------------------------------------
    def trim(self) -> int:
        """Remove whole data objects every client has committed past;
        returns how many were removed."""
        first, active = self._range()
        clients = self.clients()
        if not clients:
            return 0
        min_obj = min(c["pos"][0] for c in clients.values())
        removed = 0
        for objno in range(first, min(min_obj, active)):
            try:
                self.io.remove(data_obj(self.jid, objno))
            except RadosError:
                pass
            removed += 1
        if removed:
            self.io.set_omap(self._hdr, {
                "first": str(first + removed).encode()})
        return removed
