"""Transport layer — the Messenger analogue (ref: src/msg/).

`Messenger.create(ms_type)` (ref: src/msg/Messenger.cc:21) returns a
transport backend:

* `local` — in-process entity registry with per-endpoint dispatch
  queues (threaded or deterministically pumped).  The analogue of the
  reference's AsyncMessenger+posix stack for the simulated cluster and
  of its loopback test messenger (src/test/direct_messenger/).
* `ici` — NOT a host message path: bulk chunk fan-out between
  co-located "OSD" shards rides XLA collectives inside jitted steps
  (see ceph_tpu.dist); control metadata still flows over `local`.

Wire framing, epoll loops and ProtocolV2 have no TPU-native purpose —
the abstraction boundary (entity addressing, typed messages,
dispatchers, delivery policies, fault injection) is what survives.
"""
from .messenger import (Connection, Dispatcher, EntityName, Message,
                        Messenger, LocalNetwork)

__all__ = ["Connection", "Dispatcher", "EntityName", "Message",
           "Messenger", "LocalNetwork"]
