"""`rados` CLI: pool/object utility + IO benchmark.

The analogue of the reference's rados tool (ref: src/tools/rados/
rados.cc — usage :168; obj_bencher engine src/common/obj_bencher.cc:
`rados bench` aio pipeline with fixed concurrency, bandwidth/latency
summary :471-560).

Connects to a running cluster via --monmap (the TCP daemon world of
tools/daemon_main.py), or tests inject an in-process `Rados`.

    rados --monmap mm.json lspools
    rados --monmap mm.json mkpool data 64
    rados --monmap mm.json put data obj ./file
    rados --monmap mm.json bench data 10 write -b 65536 -t 16
"""
from __future__ import annotations

import argparse
import os
import sys
import time



def _net_from_monmap(mm_path: str, keyring_path: str = ""):
    """TcpNet honoring the monmap's ms_secure_mode (needs a keyring
    with the service secret when secure)."""
    import json
    from ..msg.tcp import TcpNet
    with open(mm_path) as f:
        mm = json.load(f)
    addrs = {k: tuple(v) for k, v in mm["addrs"].items()}
    secret = None
    if mm.get("ms_secure_mode"):
        if not keyring_path:
            raise SystemExit("secure cluster: pass --keyring")
        from ..auth import SERVICE_ENTITY, KeyRing
        secret = KeyRing.load(keyring_path).get(SERVICE_ENTITY)
        if secret is None:
            raise SystemExit("keyring has no service secret")
    return TcpNet(addrs, secure_secret=secret,
                  compress=mm.get("ms_compress"))

def _connect(args):
    from ..client import Rados
    # ad-hoc client: not in the monmap — daemons answer over the
    # connections we open (learned-connection replies)
    name = f"client.{os.getpid() % 50000 + 10000}"
    net = _net_from_monmap(args.monmap, getattr(args, "keyring", ""))
    return Rados(net, name=name,
                 op_timeout=args.timeout).connect(args.timeout)


# ------------------------------------------------------------ commands

def cmd_lspools(r, a, out):
    for p in r.list_pools():
        print(p, file=out)


def cmd_mkpool(r, a, out):
    r.pool_create(a.pool, pg_num=a.pg_num)
    print(f"successfully created pool {a.pool}", file=out)


def cmd_rmpool(r, a, out):
    rc, outs, _ = r.mon_command(
        {"prefix": "osd pool delete", "pool": a.pool,
         "yes_i_really_really_mean_it": True})
    if rc < 0:
        print(f"error: {outs}", file=sys.stderr)
        return
    print(f"successfully deleted pool {a.pool}", file=out)


def cmd_ls(r, a, out):
    io = r.open_ioctx(a.pool)
    for o in io.list_objects():
        print(o, file=out)


def cmd_put(r, a, out):
    data = sys.stdin.buffer.read() if a.infile == "-" else \
        open(a.infile, "rb").read()
    r.open_ioctx(a.pool).write_full(a.obj, data)


def cmd_get(r, a, out):
    data = r.open_ioctx(a.pool).read(a.obj)
    if a.outfile == "-":
        out.write(data.decode(errors="replace"))
    else:
        with open(a.outfile, "wb") as f:
            f.write(data)


def cmd_rm(r, a, out):
    r.open_ioctx(a.pool).remove(a.obj)


def cmd_stat(r, a, out):
    st = r.open_ioctx(a.pool).stat(a.obj)
    print(f"{a.pool}/{a.obj} size {st['size']}", file=out)


def cmd_setxattr(r, a, out):
    r.open_ioctx(a.pool).set_xattr(a.obj, a.name, a.value.encode())


def cmd_getxattr(r, a, out):
    v = r.open_ioctx(a.pool).get_xattr(a.obj, a.name)
    print(v.decode(errors="replace"), file=out)


def cmd_listxattr(r, a, out):
    for k in sorted(r.open_ioctx(a.pool).get_xattrs(a.obj)):
        print(k, file=out)


def cmd_setomapval(r, a, out):
    r.open_ioctx(a.pool).set_omap(a.obj, {a.key: a.value.encode()})


def cmd_listomapvals(r, a, out):
    vals, _ = r.open_ioctx(a.pool).get_omap_vals(a.obj)
    for k in sorted(vals):
        print(f"{k}\n value ({len(vals[k])} bytes) :", file=out)
        print(vals[k].decode(errors="replace"), file=out)


# ------------------------------------------- observability (mgr/mon)
# (ref: src/ceph.in routing `ceph crash|telemetry|insights ...` to the
#  mon, which serves crash from its table and proxies the mgr-module
#  verbs to the active mgr)

def _mon_verb(r, cmd: dict, out) -> int:
    import json
    rc, outs, outb = r.mon_command(cmd)
    if rc < 0:
        print(f"error: {outs}", file=sys.stderr)
        return 1
    if outb is not None:
        print(json.dumps(outb, indent=1, sort_keys=True), file=out)
    elif outs:
        print(outs, file=out)
    return 0


def cmd_crash(r, a, out):
    cmd = {"prefix": f"crash {a.verb}"}
    if a.verb in ("info", "archive"):
        if not a.arg:
            print(f"error: crash {a.verb} wants a crash id",
                  file=sys.stderr)
            return 1
        cmd["id"] = a.arg
    elif a.verb == "prune":
        # an omitted keep-days must NOT default to 0 — that means
        # "drop every archived report"
        try:
            cmd["keep"] = float(a.arg)
        except (TypeError, ValueError):
            print("error: crash prune wants <keep-days> (a number)",
                  file=sys.stderr)
            return 1
    return _mon_verb(r, cmd, out)


def cmd_telemetry(r, a, out):
    cmd = {"prefix": f"telemetry {a.verb}"}
    if a.verb == "channel":
        if not a.name:
            print("error: telemetry channel wants <name> [on|off]",
                  file=sys.stderr)
            return 1
        cmd["name"] = a.name
        cmd["enabled"] = a.state != "off"
    return _mon_verb(r, cmd, out)


def cmd_insights(r, a, out):
    return _mon_verb(r, {"prefix": "insights"}, out)


def cmd_trace(r, a, out):
    """Assemble one cross-daemon trace: query every daemon's
    `dump_traces` ring (admin sockets under --asok-dir) by trace_id
    and print ONE indented span tree with per-span durations (the
    blkin/zipkin-UI job as a CLI verb)."""
    import glob

    from ..common.admin_socket import admin_command
    from ..common.tracing import format_tree, span_tree

    if not a.asok_dir:
        print("error: trace wants --asok-dir <dir of *.asok>",
              file=sys.stderr)
        return 1
    spans, asked = [], 0
    for p in sorted(glob.glob(os.path.join(a.asok_dir, "*.asok"))):
        try:
            rc, got = admin_command(
                p, {"prefix": "dump_traces", "trace_id": a.trace_id})
        except OSError as e:
            print(f"warning: {p}: {e}", file=sys.stderr)
            continue
        asked += 1
        if rc == 0 and isinstance(got, list):
            spans.extend(got)
    if not asked:
        print(f"error: no *.asok under {a.asok_dir}", file=sys.stderr)
        return 1
    if not spans:
        print(f"no spans found for trace {a.trace_id} "
              f"({asked} daemons asked)", file=out)
        return 1
    print(f"trace {a.trace_id}: {len(spans)} spans from {asked} "
          f"daemons, {len(span_tree(spans))} root(s)", file=out)
    for line in format_tree(spans):
        print(line, file=out)
    return 0


# ------------------------------------------------- rgw multisite admin
# (ref: src/rgw/rgw_admin.cc realm/zonegroup/zone/period/datalog verbs
#  + `radosgw-admin sync status`)

def cmd_rgw(r, a, out):
    import json
    import urllib.error
    import urllib.request
    from ..rgw.multisite import (MultisiteAdmin, MultisiteError,
                                 render_sync_status)
    from ..rgw.datalog import DataLog

    def usage(msg):
        print(f"error: {msg}", file=sys.stderr)
        return 1

    if a.verb == "sync-status":
        # live agent state lives in the gateway process, not RADOS:
        # ask its /admin REST surface (ref: radosgw-admin asking the
        # running gateway over the admin socket/REST)
        if not a.endpoint:
            return usage("rgw sync-status wants --endpoint http://gw")
        url = a.endpoint.rstrip("/") + "/admin/sync-status"
        hdrs = {}
        if a.access and a.secret:
            # secured gateways gate /admin to the system user: sign
            # like the sync agents do (gateway.peer_request)
            from urllib.parse import urlparse as _up
            from ..rgw.auth import sign_request
            u = _up(url)
            hdrs = sign_request("GET", u.path, {"host": u.netloc},
                                b"", a.access, a.secret)
        try:
            with urllib.request.urlopen(
                    urllib.request.Request(url, headers=hdrs),
                    timeout=a.timeout) as resp:
                st = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # the gateway answered and REFUSED — saying "unreachable"
            # would send the operator chasing a network problem
            hint = " (secured gateway: pass the system user's " \
                   "--access/--secret)" if e.code == 403 else ""
            return usage(f"gateway refused: HTTP {e.code}"
                         f" {e.reason}{hint}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            # a down gateway is an operator-readable error, not a
            # traceback
            return usage(f"gateway unreachable: {e}")
        for line in render_sync_status(st):
            print(line, file=out)
        return 0
    io = r.open_ioctx(a.pool)
    adm = MultisiteAdmin(io)
    args = a.args
    try:
        if a.verb == "realm":
            if args[:1] != ["create"] or len(args) != 2:
                return usage("rgw realm create <name>")
            adm.realm_create(args[1])
        elif a.verb == "zonegroup":
            if args[:1] != ["create"] or len(args) != 2:
                return usage("rgw zonegroup create <name>")
            adm.zonegroup_create(args[1])
        elif a.verb == "zone":
            if len(args) != 2 or args[0] not in ("create", "modify"):
                return usage("rgw zone create|modify <name> "
                             "--zonegroup <zg> [--endpoint url] "
                             "[--master]")
            if args[0] == "create":
                adm.zone_create(args[1], a.zonegroup,
                                endpoint=a.endpoint or "",
                                master=a.master)
            else:
                adm.zone_modify(args[1], a.zonegroup,
                                endpoint=a.endpoint or None,
                                master=True if a.master else None)
        elif a.verb == "period":
            if args[:1] == ["get"]:
                print(json.dumps(adm.period_get(), indent=1,
                                 sort_keys=True), file=out)
            elif args[:1] == ["commit"]:
                print(f"period epoch {adm.period_commit()}", file=out)
            else:
                return usage("rgw period get|commit")
        elif a.verb == "datalog":
            dl = DataLog(io)
            if args[:1] == ["status"] and len(args) == 2:
                for s, head in sorted(
                        dl.heads(args[1], a.shards).items()):
                    print(f"shard {s}: head {head}", file=out)
            elif args[:1] == ["trim"] and len(args) == 4:
                n = dl.trim(args[1], int(args[2]), int(args[3]))
                print(f"trimmed {n} entries", file=out)
            else:
                return usage("rgw datalog status <bucket> | "
                             "trim <bucket> <shard> <upto>")
        else:
            return usage(f"unknown rgw verb {a.verb}")
    except MultisiteError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_serve(r, a, out):
    """Paged artifact store verbs (ceph_tpu.serve): put a checkpoint
    shard, stream it back through a readahead policy, stat the
    manifest, or inspect individual pages."""
    import hashlib
    import json
    from ..serve import ArtifactStore

    def usage(msg):
        print(f"error: {msg}", file=sys.stderr)
        return 1

    io = r.open_ioctx(a.pool)
    st = ArtifactStore(io, page_size=a.page_size)
    if a.verb == "put":
        if len(a.args) != 1:
            return usage("serve put <pool> <name> <infile> "
                         "[--shard s] [--page-size n]")
        data = sys.stdin.buffer.read() if a.args[0] == "-" else \
            open(a.args[0], "rb").read()
        m = st.put(a.name, shards={a.shard: data})
        si = m.shards[a.shard]
        print(f"published {a.name} epoch {m.epoch}: shard "
              f"{a.shard} {si.size} B in {si.n_pages} pages "
              f"({len(si.vlens)} ragged)", file=out)
    elif a.verb == "get":
        if len(a.args) > 1:
            return usage("serve get <pool> <name> [outfile] "
                         "[--shard s] [--policy p]")
        h = st.open(a.name, policy=a.policy)
        data = h.read_shard(a.shard)
        h.close()
        outfile = a.args[0] if a.args else "-"
        if outfile == "-":
            out.write(data.decode(errors="replace"))
        else:
            with open(outfile, "wb") as f:
                f.write(data)
    elif a.verb == "stat":
        print(json.dumps(st.stat(a.name), indent=1, sort_keys=True),
              file=out)
    elif a.verb == "pages":
        if len(a.args) != 2:
            return usage("serve pages <pool> <name> <shard> "
                         "<id,id,...>")
        shard = a.args[0]
        try:
            ids = [int(x) for x in a.args[1].split(",") if x]
        except ValueError:
            return usage(f"bad page-id list {a.args[1]!r}")
        m = st.manifest(a.name)
        if shard not in m.shards:
            return usage(f"no shard {shard!r} in {a.name}")
        blobs = st.fetch_pages(a.name, shard, ids, manifest=m)
        for pid, blob in zip(ids, blobs):
            digest = hashlib.sha256(blob).hexdigest()[:16]
            print(f"page {pid}: {len(blob)} B sha256 {digest}",
                  file=out)
    return 0


# ---------------------------------------------------------------- bench
# (ref: src/common/obj_bencher.cc ObjBencher::write_bench /
#  seq_read_bench: fixed-depth aio pipeline, per-op latency tracking,
#  bandwidth summary)

def _bench(r, a, out):
    io = r.open_ioctx(a.pool)
    size, depth, secs = a.block_size, a.concurrency, a.seconds
    prefix = f"benchmark_data_{os.getpid()}_"
    payload = os.urandom(size)
    lat: list[float] = []
    t0 = time.monotonic()
    n_done = 0

    if a.mode == "write":
        submit = lambda i: io.aio_write_full(prefix + str(i), payload)
    else:
        # seq read over whatever a prior write bench left behind
        objs = sorted(o for o in io.list_objects()
                      if o.startswith("benchmark_data_"))
        if not objs:
            print("no benchmark objects; run write first", file=out)
            return 1
        submit = lambda i: io.aio_read(objs[i % len(objs)])

    in_flight: list[tuple[float, object]] = []
    i = 0
    deadline = t0 + secs
    while True:
        now = time.monotonic()
        if now < deadline:
            while len(in_flight) < depth:
                in_flight.append((time.monotonic(), submit(i)))
                i += 1
        elif not in_flight:
            break
        start, fut = in_flight[0]
        fut.wait(max(1.0, a.timeout))
        in_flight.pop(0)
        lat.append(time.monotonic() - start)
        n_done += 1
        if fut.result < 0:
            print(f"op failed: {fut.errno_name}", file=out)
            return 1
    elapsed = time.monotonic() - t0
    mb = n_done * size / 1e6
    print(f"Total time run:         {elapsed:.4f}", file=out)
    print(f"Total {a.mode}s made:      {n_done}", file=out)
    print(f"{a.mode} size:             {size}", file=out)
    print(f"Bandwidth (MB/sec):     {mb / elapsed:.3f}", file=out)
    print(f"Average IOPS:           {n_done / elapsed:.0f}", file=out)
    print(f"Average Latency(s):     {sum(lat) / len(lat):.6f}",
          file=out)
    print(f"Max latency(s):         {max(lat):.6f}", file=out)
    print(f"Min latency(s):         {min(lat):.6f}", file=out)
    if a.mode == "write" and not a.no_cleanup:
        from ..client import RadosError
        for j in range(i):
            try:
                io.remove(prefix + str(j))
            except RadosError:
                pass            # best-effort cleanup of bench objects
    return 0


def main(argv=None, rados=None, out=None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(
        prog="rados", description="object store utility")
    ap.add_argument("--monmap", help="monmap JSON (TCP cluster)")
    ap.add_argument("--keyring", default="",
                    help="keyring JSON (secure-mode clusters)")
    ap.add_argument("--timeout", type=float, default=30.0)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("lspools")
    p = sub.add_parser("mkpool")
    p.add_argument("pool")
    p.add_argument("pg_num", type=int, nargs="?", default=32)
    p = sub.add_parser("rmpool")
    p.add_argument("pool")
    p = sub.add_parser("ls")
    p.add_argument("pool")
    p = sub.add_parser("put")
    p.add_argument("pool"), p.add_argument("obj")
    p.add_argument("infile")
    p = sub.add_parser("get")
    p.add_argument("pool"), p.add_argument("obj")
    p.add_argument("outfile", nargs="?", default="-")
    p = sub.add_parser("rm")
    p.add_argument("pool"), p.add_argument("obj")
    p = sub.add_parser("stat")
    p.add_argument("pool"), p.add_argument("obj")
    p = sub.add_parser("setxattr")
    p.add_argument("pool"), p.add_argument("obj")
    p.add_argument("name"), p.add_argument("value")
    p = sub.add_parser("getxattr")
    p.add_argument("pool"), p.add_argument("obj"), p.add_argument("name")
    p = sub.add_parser("listxattr")
    p.add_argument("pool"), p.add_argument("obj")
    p = sub.add_parser("setomapval")
    p.add_argument("pool"), p.add_argument("obj")
    p.add_argument("key"), p.add_argument("value")
    p = sub.add_parser("listomapvals")
    p.add_argument("pool"), p.add_argument("obj")
    p = sub.add_parser("crash")
    p.add_argument("verb", choices=["ls", "ls-new", "stat", "info",
                                    "archive", "archive-all", "prune"])
    p.add_argument("arg", nargs="?",
                   help="crash id (info/archive) or keep-days (prune)")
    p = sub.add_parser("telemetry")
    p.add_argument("verb", nargs="?", default="show",
                   choices=["show", "status", "on", "off", "channel"])
    p.add_argument("name", nargs="?", help="channel name")
    p.add_argument("state", nargs="?", default="on",
                   choices=["on", "off"])
    p = sub.add_parser("insights")
    p = sub.add_parser("trace")
    p.add_argument("trace_id", help="trace id to assemble")
    p.add_argument("--asok-dir", default="",
                   help="directory of daemon admin sockets (*.asok) "
                        "to query dump_traces on")
    p = sub.add_parser("rgw")
    p.add_argument("verb", choices=["realm", "zonegroup", "zone",
                                    "period", "datalog",
                                    "sync-status"])
    p.add_argument("args", nargs="*")
    p.add_argument("--pool", default="rgw",
                   help="the zone's rgw pool (period + datalog live "
                        "there)")
    p.add_argument("--zonegroup", default="",
                   help="zonegroup for zone create/modify")
    p.add_argument("--endpoint", default="",
                   help="zone endpoint URL (zone create/modify) or "
                        "gateway URL (sync-status)")
    p.add_argument("--master", action="store_true",
                   help="make the zone the zonegroup's metadata "
                        "master")
    p.add_argument("--shards", type=int, default=8,
                   help="index shards to report (datalog status)")
    p.add_argument("--access", default="",
                   help="system-user access key: secured gateways "
                        "gate /admin to the multisite system user "
                        "(sync-status)")
    p.add_argument("--secret", default="",
                   help="system-user secret key (sync-status)")
    p = sub.add_parser("serve")
    p.add_argument("verb", choices=["put", "get", "stat", "pages"])
    p.add_argument("pool")
    p.add_argument("name", help="artifact name")
    p.add_argument("args", nargs="*")
    p.add_argument("--shard", default="shard0",
                   help="shard name (put/get)")
    p.add_argument("--page-size", type=int, default=1 << 16,
                   help="page size for put (readers take it from "
                        "the manifest)")
    p.add_argument("--policy", default="checkpoint",
                   choices=["checkpoint", "kvcache"],
                   help="readahead policy for get")
    p = sub.add_parser("bench")
    p.add_argument("pool")
    p.add_argument("seconds", type=float)
    p.add_argument("mode", choices=["write", "seq"])
    p.add_argument("-b", "--block-size", type=int, default=4 << 20)
    p.add_argument("-t", "--concurrency", type=int, default=16)
    p.add_argument("--no-cleanup", action="store_true")
    a = ap.parse_args(argv)

    if a.cmd == "trace":
        # pure admin-socket verb: needs no cluster connection
        return cmd_trace(None, a, out) or 0
    own = rados is None
    if own:
        if not a.monmap:
            ap.error("--monmap required (or pass rados=)")
        rados = _connect(a)
    try:
        from ..client import RadosError
        try:
            if a.cmd == "bench":
                return _bench(rados, a, out) or 0
            rc = {"lspools": cmd_lspools, "mkpool": cmd_mkpool,
                  "rmpool": cmd_rmpool, "ls": cmd_ls, "put": cmd_put,
                  "get": cmd_get, "rm": cmd_rm, "stat": cmd_stat,
                  "setxattr": cmd_setxattr, "getxattr": cmd_getxattr,
                  "listxattr": cmd_listxattr,
                  "setomapval": cmd_setomapval,
                  "listomapvals": cmd_listomapvals,
                  "crash": cmd_crash, "telemetry": cmd_telemetry,
                  "insights": cmd_insights,
                  "rgw": cmd_rgw,
                  "serve": cmd_serve}[a.cmd](rados, a, out)
            return rc or 0
        except RadosError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    finally:
        if own:
            rados.shutdown()


if __name__ == "__main__":
    raise SystemExit(main())
