"""MgrDaemon: the balancer loop as a wire citizen.

The mgr shape (ref: src/mgr/Mgr.cc + the balancer module's serve loop,
src/pybind/mgr/balancer/module.py:340 serve -> optimize -> execute):
subscribe to osdmaps, periodically run the upmap optimizer against the
current map, and submit the resulting pg-upmap-items commands to the
mon, which commits them and publishes the new epoch back.

The optimizer itself is ceph_tpu.osd.balancer (calc_pg_upmaps over the
batched vmapped mapping tables) — the mgr is the scheduling/command
glue around it.
"""
from __future__ import annotations

import itertools
import threading

from ..common.lockdep import make_lock

from ..common.log import dout
from ..common.options import global_config
from ..msg.messages import (MMap, MMonCommand, MMonCommandAck,
                            MMonSubscribe)
from ..msg.mon_client import MonHunter
from ..msg.messenger import Dispatcher, LocalNetwork, Message, Messenger
from ..osd.balancer import Balancer
from ..osd.osdmap import OSDMap


class MgrDaemon(Dispatcher, MonHunter):
    def __init__(self, network: LocalNetwork, rank: int = 0,
                 mon="mon.0", threaded: bool = False,
                 max_deviation: int = 1, max_iterations: int = 100):
        self.name = f"mgr.{rank}"
        self._init_mons(mon)
        self.osdmap = OSDMap()
        self.active = True
        self.balancer = Balancer(max_deviation=max_deviation,
                                 max_iterations=max_iterations)
        self.last_optimize: dict = {}
        self._tid = itertools.count(1)
        self._pending: set[int] = set()       # unacked command tids
        self._sync_cmds: dict = {}            # tid -> (Event, slot)
        self.prometheus = None
        #: restful admin API (ref: pybind/mgr/restful); start_restful
        self.restful = None
        self.failed_commands = 0
        #: pg_autoscaler module (ref: pybind/mgr/pg_autoscaler);
        #: enable with start_pg_autoscaler(), driven by autoscale_tick
        self.pg_autoscaler = None
        #: progress module (ref: pybind/mgr/progress); enable with
        #: start_progress(), driven by progress_tick
        self.progress = None
        #: devicehealth module (ref: pybind/mgr/devicehealth); enable
        #: with start_devicehealth(), driven by devicehealth_tick
        self.devicehealth = None
        self._lock = make_lock(f"mgr.{self.name}")
        self.ms = Messenger.create(network, self.name, threaded=threaded)
        self.ms.add_dispatcher(self)

    def _hunt_greeting(self) -> list:
        return [MMonSubscribe(what="osdmap",
                              start=self.osdmap.epoch + 1)]

    def ms_handle_reset(self, peer: str) -> None:
        self._maybe_hunt(peer)

    # ------------------------------------------------------------ setup
    def init(self) -> None:
        self.ms.start()
        self.ms.connect(self.mon).send_message(
            MMonSubscribe(what="osdmap", start=1))

    def shutdown(self) -> None:
        if self.prometheus is not None:
            self.prometheus.shutdown()
        if getattr(self, "restful", None) is not None:
            self.restful.shutdown()
        self.ms.shutdown()

    # -------------------------------------------------------- dispatch
    def ms_dispatch(self, msg: Message) -> bool:
        if isinstance(msg, MMap):
            with self._lock:
                self.osdmap = self.osdmap.ingest(msg.full_map,
                                                 msg.incrementals)
            return True
        if isinstance(msg, MMonCommandAck):
            with self._lock:
                self._pending.discard(msg.tid)
                entry = self._sync_cmds.pop(msg.tid, None)
                if msg.result != 0 and entry is None:
                    self.failed_commands += 1
                    dout("mgr", 0).write(
                        "%s: mon command failed (%d): %s", self.name,
                        msg.result, msg.outs)
            if entry is not None:
                ev, slot = entry
                slot.update(r=msg.result, outs=msg.outs,
                            outb=msg.outb)
                ev.set()
            return True
        return False

    def mon_command(self, cmd: dict,
                    timeout: float = 30.0) -> tuple[int, str, object]:
        """Synchronous round-trip (the prometheus module's command
        channel)."""
        tid = next(self._tid)
        ev, slot = threading.Event(), {}
        with self._lock:
            self._sync_cmds[tid] = (ev, slot)
        self.ms.connect(self.mon).send_message(
            MMonCommand(tid=tid, cmd=cmd))
        if not ev.wait(timeout):
            with self._lock:
                self._sync_cmds.pop(tid, None)
            raise TimeoutError(f"mon command {cmd.get('prefix')!r}")
        return slot["r"], slot["outs"], slot["outb"]

    def start_pg_autoscaler(self, **kw):
        from .pg_autoscaler import PGAutoscaler
        self.pg_autoscaler = PGAutoscaler(self, **kw)
        return self.pg_autoscaler

    def autoscale_tick(self, pool_bytes: dict | None = None) -> int:
        """One pg_autoscaler round (scheduled alongside the balancer
        tick the way the reference's module serve loops both run)."""
        if self.pg_autoscaler is None:
            return 0
        with self._lock:
            return self.pg_autoscaler.tick(pool_bytes)

    def start_progress(self):
        """Track long-running operations (ref: pybind/mgr/progress)."""
        from .progress import ProgressModule
        self.progress = ProgressModule(self)
        return self.progress

    def start_devicehealth(self):
        """Device media-error health (ref: pybind/mgr/devicehealth)."""
        from .devicehealth import DeviceHealth
        self.devicehealth = DeviceHealth(self)
        return self.devicehealth

    def devicehealth_tick(self) -> None:
        if getattr(self, "devicehealth", None) is not None:
            self.devicehealth.tick()

    def progress_tick(self) -> int:
        if self.progress is None:
            return 0
        return self.progress.tick()

    def start_prometheus(self, port: int = 0):
        """Serve /metrics (ref: pybind/mgr/prometheus).  Exports
        progress events too when the progress module is running."""
        from .prometheus import PrometheusExporter
        # late-bound: progress may start before OR after the exporter
        self.prometheus = PrometheusExporter(
            self.mon_command, port=port,
            progress_ls=lambda: (self.progress.ls()
                                 if self.progress is not None else []),
            device_ls=lambda: (self.devicehealth.ls()
                               if self.devicehealth is not None
                               else []))
        self.prometheus.start()
        return self.prometheus

    def start_restful(self, port: int = 0):
        """Serve the JSON admin API (ref: pybind/mgr/restful)."""
        from .restful import RestfulServer
        self.restful = RestfulServer(self, port=port)
        self.restful.start()
        return self.restful

    # ------------------------------------------------------- balancing
    def tick(self) -> int:
        """One balancer round: optimize the current map and submit the
        upmap commands (ref: balancer module.py execute :1450 —
        pg-upmap-items mon commands per plan item).  Returns the number
        of commands submitted."""
        with self._lock:
            if not self.active or self.osdmap.epoch == 0 or \
                    not self.osdmap.pools:
                return 0
            inc = self.balancer.optimize(self.osdmap)
            rm = [str(pg) for pg in sorted(inc.old_pg_upmap_items)]
            set_ = [(str(pg), items) for pg, items in
                    sorted(inc.new_pg_upmap_items.items())]
            sent = len(rm) + len(set_)
            if sent:
                # one batched command = one map epoch for the whole
                # plan (an epoch per item would fan N incrementals to
                # every subscriber)
                self._command({"prefix": "osd upmap-batch",
                               "rm": rm, "set": set_})
            self.last_optimize = {
                "epoch": self.osdmap.epoch,
                "commands": sent,
            }
            if sent:
                dout("mgr", 1).write("%s: submitted %d upmap changes "
                                     "at e%d", self.name, sent,
                                     self.osdmap.epoch)
            return sent

    def _command(self, cmd: dict) -> None:
        tid = next(self._tid)
        self._pending.add(tid)
        self.ms.connect(self.mon).send_message(
            MMonCommand(tid=tid, cmd=cmd))

    def status(self) -> dict:
        """(ref: `ceph balancer status`)."""
        with self._lock:
            score = self.balancer.score(self.osdmap) \
                if self.osdmap.pools else {}
            return {"active": self.active,
                    "mode": "upmap",
                    "epoch": self.osdmap.epoch,
                    "last_optimize": dict(self.last_optimize),
                    "score": {k: score.get(k)
                              for k in ("stddev", "max_deviation")}}
