"""CephFS capabilities + hardlinks + crash replay under concurrency
(ref: src/mds/Locker.cc cap issue/revoke; CDentry remote linkage for
hardlinks; MDLog replay — VERDICT r2 #8)."""
import pytest

from ceph_tpu.fs import CephFS, MDSDaemon
from ceph_tpu.fs.client import CephFSError
from ceph_tpu.fs.mds import CAP_CACHE, CAP_EXCL
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def fscluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    mds = MDSDaemon(c.network, c.rados())
    mds.init()
    yield c, mds
    mds.shutdown()
    c.shutdown()


def _fs(c):
    return CephFS(c.rados())


def test_single_client_gets_excl(fscluster):
    c, _mds = fscluster
    fs = _fs(c)
    fs.mkdirs("/caps")
    fh = fs.open("/caps/solo", "w")
    assert fh.caps & CAP_EXCL and fh.caps & CAP_CACHE
    fh.write(0, b"solo")
    fh.close()


def test_reader_revokes_writer_excl(fscluster):
    """A second client's read-open revokes the writer's EXCL; the
    writer's buffered size is flushed first, so the reader sees it."""
    c, _mds = fscluster
    fs_w, fs_r = _fs(c), _fs(c)
    fs_w.mkdirs("/caps")
    w = fs_w.open("/caps/shared", "w")
    assert w.caps & CAP_EXCL
    w.write(0, b"E" * 5000)      # size buffered under EXCL, not flushed
    r = fs_r.open("/caps/shared", "r")
    # the open interlock flushed the writer's dirty size
    assert r.size == 5000
    assert r.read(0) == b"E" * 5000
    assert not (w.caps & CAP_EXCL)       # revoked
    w.close()
    r.close()


def test_concurrent_writers_no_lost_update(fscluster):
    """The round-2 failure mode: two writers appending — without caps
    the second writer's cached size 0 overwrote the first's bytes.
    With revoke-on-conflict + grow-only flushes both extents land."""
    c, _mds = fscluster
    fs_a, fs_b = _fs(c), _fs(c)
    fs_a.mkdirs("/caps")
    a = fs_a.open("/caps/both", "w")
    a.write(0, b"A" * 1000)              # buffered under EXCL
    b = fs_b.open("/caps/both", "a")     # conflict: revokes a's EXCL
    assert b.size == 1000                # saw a's flushed size
    b.append(b"B" * 1000)
    # a appends again: cap-less now, re-fetches authoritative size
    a.append(b"C" * 1000)
    final = _fs(c).read_file("/caps/both")
    assert final == b"A" * 1000 + b"B" * 1000 + b"C" * 1000
    a.close()
    b.close()


def test_cache_invalidated_on_revoke(fscluster):
    c, _mds = fscluster
    fs_1, fs_2 = _fs(c), _fs(c)
    fs_1.mkdirs("/caps")
    fs_1.write_file("/caps/cached", b"v1-data")
    h1 = fs_1.open("/caps/cached", "r")
    assert h1.caps & CAP_CACHE
    assert h1.read(0) == b"v1-data"
    # cached (ObjectCacher when enabled, legacy rcache otherwise)
    assert (h1._oc is not None and h1._oc.cached_bytes() > 0) or \
        h1._rcache
    # another client writes: h1's CACHE is revoked, cache dropped
    h2 = fs_2.open("/caps/cached", "r+")
    h2.write(0, b"v2-DATA")
    h2.fsync()
    import time
    deadline = time.monotonic() + 5
    while h1.caps and time.monotonic() < deadline:
        time.sleep(0.02)
    assert h1.caps == 0 and not h1._rcache
    assert h1._oc is None or h1._oc.cached_bytes() == 0
    assert h1.read(0) == b"v2-DATA"
    h1.close()
    h2.close()


def test_hardlink_shares_data_until_last_unlink(fscluster):
    c, _mds = fscluster
    fs = _fs(c)
    fs.mkdirs("/hl")
    fs.write_file("/hl/one", b"linked-bytes")
    fs.link("/hl/one", "/hl/two")
    assert fs.read_file("/hl/two") == b"linked-bytes"
    st1, st2 = fs.stat("/hl/one"), fs.stat("/hl/two")
    assert st1["ino"] == st2["ino"]
    assert st1.get("nlink") == 2
    # writes through either name are visible through the other
    fh = fs.open("/hl/two", "r+")
    fh.write(0, b"LINKED")
    fh.close()
    assert fs.read_file("/hl/one")[:6] == b"LINKED"
    # unlinking one name keeps the data alive
    fs.unlink("/hl/one")
    assert not fs.exists("/hl/one")
    assert fs.read_file("/hl/two")[:6] == b"LINKED"
    # last unlink purges
    ino = st1["ino"]
    fs.unlink("/hl/two")
    io = fs.rados.open_ioctx("cephfs_data")
    assert not [o for o in io.list_objects()
                if o.startswith(f"{ino:x}.")]
    # a second link then rename keeps resolution intact
    fs.write_file("/hl/base", b"renamed-link")
    fs.link("/hl/base", "/hl/alias")
    fs.rename("/hl/alias", "/hl/alias2")
    assert fs.read_file("/hl/alias2") == b"renamed-link"
    with pytest.raises(CephFSError, match="EEXIST"):
        fs.link("/hl/base", "/hl/alias2")


def test_crash_replay_window_with_concurrent_clients():
    """Hard-stop the MDS inside the applied_seq window (journaled,
    dirfrags not checkpointed) with TWO clients mid-flight; the
    restarted rank replays and both clients' namespaces converge
    (ref: MDLog::replay; VERDICT r2 #8 crash inside the lazy window)."""
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        mds = MDSDaemon(c.network, c.rados())
        mds.init()
        fs_a, fs_b = CephFS(c.rados()), CephFS(c.rados())
        fs_a.mkdirs("/w")
        fs_a.write_file("/w/a", b"from-a")
        fs_b.write_file("/w/b", b"from-b")
        fs_b.link("/w/b", "/w/b2")       # itable op inside the window
        # hard stop: no shutdown flush — applied_seq lags the journal
        mds.ms.shutdown()
        mds2 = MDSDaemon(c.network, c.rados())
        mds2.init()
        fs2 = CephFS(c.rados())
        assert sorted(fs2.listdir("/w")) == ["a", "b", "b2"]
        assert fs2.read_file("/w/a") == b"from-a"
        assert fs2.read_file("/w/b2") == b"from-b"
        assert fs2.stat("/w/b")["nlink"] == 2
        # both clients keep working against the new rank
        fs_a2, fs_b2 = CephFS(c.rados()), CephFS(c.rados())
        ha = fs_a2.open("/w/a", "a")
        ha.append(b"+more")
        ha.close()
        assert fs_b2.read_file("/w/a") == b"from-a+more"
        mds2.shutdown()
    finally:
        c.shutdown()
