"""PGLog: per-shard write-ahead log + divergence merge.

The log-based consistency core of recovery (ref: src/osd/PGLog.{h,cc}):
an ordered entry list with an object index, a missing set derived from
it, `merge_log` to adopt an authoritative log, and the five-case
divergent-entry resolution of `_merge_object_divergent_entries`
(PGLog.h:864-1087).  The TestPGLog corner cases are the spec
(src/test/osd/TestPGLog.cc); tests/test_pg_log.py ports them.
"""
from __future__ import annotations

from typing import Iterable, Optional

from ..common.log import dout
from .pg_types import EVersion, PGLogEntry, PGMissing, ZERO_VERSION


class LogEntryHandler:
    """Side-effect hooks for divergence resolution
    (ref: PGLog.h LogEntryHandler: remove/rollback/trim)."""

    def remove(self, soid: str) -> None:
        pass

    def rollback(self, entry: PGLogEntry) -> None:
        pass

    def trim(self, entry: PGLogEntry) -> None:
        pass


class IndexedLog:
    """Entry list + per-object last-entry index
    (ref: PGLog.h IndexedLog)."""

    def __init__(self, entries: Iterable[PGLogEntry] = (),
                 head: EVersion = ZERO_VERSION,
                 tail: EVersion = ZERO_VERSION,
                 can_rollback_to: EVersion = ZERO_VERSION):
        self.entries: list[PGLogEntry] = list(entries)
        self.head = head if head != ZERO_VERSION or not self.entries \
            else self.entries[-1].version
        self.tail = tail
        self.can_rollback_to = can_rollback_to
        self.objects: dict[str, PGLogEntry] = {}
        self.index()

    def index(self) -> None:
        self.objects = {}
        for e in self.entries:
            if not e.is_error():
                self.objects[e.soid] = e

    def add(self, e: PGLogEntry) -> None:
        assert e.version > self.head, (e.version, self.head)
        self.entries.append(e)
        self.head = e.version
        if not e.is_error():
            self.objects[e.soid] = e

    def trim_to(self, v: EVersion) -> list[PGLogEntry]:
        """Drop entries with version <= v (ref: PGLog.cc trim)."""
        kept, dropped = [], []
        for e in self.entries:
            (dropped if e.version <= v else kept).append(e)
        self.entries = kept
        if v > self.tail:
            self.tail = v
        self.index()
        return dropped

    def entries_for(self, soid: str) -> list[PGLogEntry]:
        return [e for e in self.entries if e.soid == soid]

    def __len__(self) -> int:
        return len(self.entries)


class PGLog:
    """The merge/rewind engine around an IndexedLog + PGMissing."""

    def __init__(self, log: Optional[IndexedLog] = None,
                 missing: Optional[PGMissing] = None):
        self.log = log if log is not None else IndexedLog()
        self.missing = missing if missing is not None else PGMissing()

    # -- local append (the write path) ---------------------------------
    def append(self, e: PGLogEntry) -> None:
        self.log.add(e)

    # -- divergence core (ref: PGLog.h:864) ----------------------------
    @staticmethod
    def _merge_object_divergent_entries(
            log: IndexedLog, soid: str,
            orig_entries: list[PGLogEntry],
            original_can_rollback_to: EVersion,
            missing: PGMissing,
            rollbacker: Optional[LogEntryHandler] = None) -> None:
        # strip ERROR entries (they are never authoritative)
        entries = [e for e in orig_entries if not e.is_error()]
        if not entries:
            return
        prior_version = entries[0].prior_version
        first_divergent_update = entries[0].version
        last_divergent_update = entries[-1].version
        object_not_in_store = (not missing.is_missing(soid)
                               and entries[-1].is_delete())

        objiter = log.objects.get(soid)
        if objiter is not None and objiter.version >= first_divergent_update:
            # Case 1: a more recent entry in the authoritative log
            # already covers this object — the merge of that entry
            # handled missing; just forget any stale 'have'
            assert objiter.version > last_divergent_update
            missing.revise_have(soid, ZERO_VERSION)
            if rollbacker:
                if not object_not_in_store:
                    rollbacker.remove(soid)
                for e in entries:
                    rollbacker.trim(e)
            return

        if prior_version == ZERO_VERSION or entries[0].is_clone():
            # Case 2: the divergent entries created the object —
            # it should not exist
            if missing.is_missing(soid):
                missing.rm(soid)
            if rollbacker:
                if not object_not_in_store:
                    rollbacker.remove(soid)
                for e in entries:
                    rollbacker.trim(e)
            return

        if missing.is_missing(soid):
            # Case 3: already missing — adjust need to prior_version
            item = missing.items[soid]
            if item.have == prior_version:
                missing.rm(soid)
            else:
                missing.revise_need(soid, prior_version)
            if rollbacker:
                for e in entries:
                    rollbacker.trim(e)
            return

        # distinguish 4 (rollbackable) from 5
        can_rollback = all(
            e.can_rollback() and e.version > original_can_rollback_to
            for e in entries)
        if can_rollback:
            # Case 4: undo in reverse order
            if rollbacker:
                for e in reversed(entries):
                    rollbacker.rollback(e)
            return
        # Case 5: cannot roll back — remove and mark missing at
        # prior_version
        if rollbacker:
            if not object_not_in_store:
                rollbacker.remove(soid)
            for e in entries:
                rollbacker.trim(e)
        missing.add(soid, prior_version, ZERO_VERSION, False)

    @classmethod
    def _merge_divergent_entries(
            cls, log: IndexedLog, entries: list[PGLogEntry],
            original_can_rollback_to: EVersion,
            missing: PGMissing,
            rollbacker: Optional[LogEntryHandler] = None) -> None:
        by_object: dict[str, list[PGLogEntry]] = {}
        for e in entries:
            by_object.setdefault(e.soid, []).append(e)
        for soid, lst in by_object.items():
            cls._merge_object_divergent_entries(
                log, soid, lst, original_can_rollback_to, missing,
                rollbacker)

    # -- rewind (ref: PGLog.cc rewind_divergent_log) -------------------
    def rewind_divergent_log(
            self, newhead: EVersion,
            rollbacker: Optional[LogEntryHandler] = None) -> None:
        assert newhead >= self.log.tail
        divergent = [e for e in self.log.entries if e.version > newhead]
        self.log.entries = [e for e in self.log.entries
                            if e.version <= newhead]
        self.log.head = newhead
        original_crt = self.log.can_rollback_to
        if self.log.can_rollback_to > newhead:
            self.log.can_rollback_to = newhead
        self.log.index()
        self._merge_divergent_entries(
            self.log, divergent, original_crt, self.missing, rollbacker)

    # -- merge (ref: PGLog.cc:358 merge_log) ---------------------------
    def merge_log(self, olog: IndexedLog,
                  rollbacker: Optional[LogEntryHandler] = None) -> bool:
        """Adopt the authoritative log `olog`.  Returns True if our log
        changed.  Requires overlap: log.head >= olog.tail and
        olog.head >= log.tail (else backfill, not log recovery)."""
        if not (self.log.head >= olog.tail
                and olog.head >= self.log.tail):
            raise ValueError(
                f"no log overlap: ours [{self.log.tail},{self.log.head}]"
                f" theirs [{olog.tail},{olog.head}] (needs backfill)")
        changed = False
        orig_tail = self.log.tail

        # extend tail backwards — pure history, missing unaffected
        if olog.tail < self.log.tail:
            older = [e for e in olog.entries if e.version <= self.log.tail]
            self.log.entries = older + self.log.entries
            self.log.tail = olog.tail
            self.log.index()
            changed = True

        if olog.head < self.log.head:
            # authoritative log is shorter: everything past its head
            # is divergent
            self.rewind_divergent_log(olog.head, rollbacker)
            changed = True
        elif olog.head > self.log.head:
            # find the cut point: the last entry the two logs share
            # (ref: PGLog.cc "merge_log cut point (usually last
            # shared)").  Entries of ours past it are divergent even
            # though olog.head is ahead of ours.
            lower_bound = max(olog.tail, orig_tail)
            for e in olog.entries:
                if e.version <= self.log.head:
                    lower_bound = max(lower_bound, e.version)
            original_crt = self.log.can_rollback_to
            divergent = [e for e in self.log.entries
                         if e.version > lower_bound]
            self.log.entries = [e for e in self.log.entries
                                if e.version <= lower_bound]
            self.log.head = lower_bound
            self.log.index()
            # adopt the authoritative entries first (so Case 1 of the
            # divergent merge sees them), updating missing
            new_entries = [e for e in olog.entries
                           if e.version > lower_bound]
            for e in new_entries:
                self.log.add(e)
                self.missing.add_next_event(e)
                if rollbacker and e.is_delete():
                    rollbacker.remove(e.soid)
            self._merge_divergent_entries(
                self.log, divergent, original_crt, self.missing,
                rollbacker)
            self.log.head = olog.head
            # cannot roll back into freshly adopted entries
            self.log.can_rollback_to = self.log.head
            dout("pg", 10).write(
                "merge_log: cut %s, +%d new, %d divergent",
                lower_bound, len(new_entries), len(divergent))
            changed = True
        return changed

    # -- recovery bookkeeping ------------------------------------------
    def recover_got(self, soid: str, version: EVersion) -> None:
        self.missing.got(soid, version)
