"""ObjectStore: the transactional storage API the OSD data path sits on.

Abstract surface modeled on the reference's `ObjectStore` class
(ref: src/os/ObjectStore.h:66): collections order transactions; a
`Transaction` is an ordered op list applied atomically by
`queue_transaction`; reads (`read`/`stat`/`getattr`/`omap_get`) are
synchronous.  Op coverage follows Transaction's builder surface
(ObjectStore.h:998-1306: touch/write/zero/truncate/remove/setattr(s)/
rmattr(s)/clone/clone_range/create_collection/remove_collection/
collection_move_rename/omap_*).

The TPU build keeps this layer host-side and native-friendly: chunk
payloads are bytes/numpy buffers handed straight to/from the device
arrays of the EC path, never copied through an intermediate
"bufferlist" abstraction.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping


@dataclass(frozen=True, order=True)
class ObjectId:
    """ghobject_t-lite: object name + shard id for EC per-shard clones
    (ref: src/common/hobject.h ghobject_t; shard_id marks which EC
    shard's chunk stream this object holds)."""
    name: str
    snap: int = -2            # CEPH_NOSNAP analogue: head object
    shard: int = -1           # NO_SHARD analogue

    def __str__(self) -> str:
        s = self.name
        if self.snap != -2:
            s += f"@{self.snap}"
        if self.shard != -1:
            s += f"(s{self.shard})"
        return s


class StoreError(Exception):
    def __init__(self, errno_name: str, msg: str = ""):
        self.errno_name = errno_name
        super().__init__(f"{errno_name}: {msg}" if msg else errno_name)


# Transaction op codes (ref: ObjectStore.h Transaction::Op enum)
OP_TOUCH = "touch"
OP_WRITE = "write"
OP_ZERO = "zero"
OP_TRUNCATE = "truncate"
OP_REMOVE = "remove"
OP_SETATTRS = "setattrs"
OP_RMATTR = "rmattr"
OP_RMATTRS = "rmattrs"
OP_CLONE = "clone"
OP_CLONE_RANGE = "clone_range"
OP_MKCOLL = "create_collection"
OP_RMCOLL = "remove_collection"
OP_COLL_MOVE_RENAME = "collection_move_rename"
OP_OMAP_CLEAR = "omap_clear"
OP_OMAP_SETKEYS = "omap_setkeys"
OP_OMAP_RMKEYS = "omap_rmkeys"


@dataclass
class Transaction:
    """Ordered op list applied atomically (ref: ObjectStore.h:850
    "Transactions are apply sequentially; a collection orders them")."""
    ops: list[tuple] = field(default_factory=list)

    # -- builder surface ------------------------------------------------
    def touch(self, cid: str, oid: ObjectId) -> "Transaction":
        self.ops.append((OP_TOUCH, cid, oid))
        return self

    def write(self, cid: str, oid: ObjectId, off: int,
              data: bytes) -> "Transaction":
        self.ops.append((OP_WRITE, cid, oid, off, bytes(data)))
        return self

    def zero(self, cid: str, oid: ObjectId, off: int,
             length: int) -> "Transaction":
        self.ops.append((OP_ZERO, cid, oid, off, length))
        return self

    def truncate(self, cid: str, oid: ObjectId, size: int) -> "Transaction":
        self.ops.append((OP_TRUNCATE, cid, oid, size))
        return self

    def remove(self, cid: str, oid: ObjectId) -> "Transaction":
        self.ops.append((OP_REMOVE, cid, oid))
        return self

    def setattr(self, cid: str, oid: ObjectId, name: str,
                value) -> "Transaction":
        return self.setattrs(cid, oid, {name: value})

    def setattrs(self, cid: str, oid: ObjectId,
                 attrs: Mapping[str, Any]) -> "Transaction":
        self.ops.append((OP_SETATTRS, cid, oid, dict(attrs)))
        return self

    def rmattr(self, cid: str, oid: ObjectId, name: str) -> "Transaction":
        self.ops.append((OP_RMATTR, cid, oid, name))
        return self

    def rmattrs(self, cid: str, oid: ObjectId) -> "Transaction":
        self.ops.append((OP_RMATTRS, cid, oid))
        return self

    def clone(self, cid: str, oid: ObjectId,
              noid: ObjectId) -> "Transaction":
        self.ops.append((OP_CLONE, cid, oid, noid))
        return self

    def clone_range(self, cid: str, oid: ObjectId, noid: ObjectId,
                    srcoff: int, length: int, dstoff: int) -> "Transaction":
        self.ops.append(
            (OP_CLONE_RANGE, cid, oid, noid, srcoff, length, dstoff))
        return self

    def create_collection(self, cid: str, bits: int = 0) -> "Transaction":
        self.ops.append((OP_MKCOLL, cid, bits))
        return self

    def remove_collection(self, cid: str) -> "Transaction":
        self.ops.append((OP_RMCOLL, cid))
        return self

    def collection_move_rename(self, oldcid: str, oldoid: ObjectId,
                               cid: str, oid: ObjectId) -> "Transaction":
        self.ops.append((OP_COLL_MOVE_RENAME, oldcid, oldoid, cid, oid))
        return self

    def omap_clear(self, cid: str, oid: ObjectId) -> "Transaction":
        self.ops.append((OP_OMAP_CLEAR, cid, oid))
        return self

    def omap_setkeys(self, cid: str, oid: ObjectId,
                     keys: Mapping[str, bytes]) -> "Transaction":
        self.ops.append((OP_OMAP_SETKEYS, cid, oid, dict(keys)))
        return self

    def omap_rmkeys(self, cid: str, oid: ObjectId,
                    keys: Iterable[str]) -> "Transaction":
        self.ops.append((OP_OMAP_RMKEYS, cid, oid, list(keys)))
        return self

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        return self

    def empty(self) -> bool:
        return not self.ops

    def __len__(self) -> int:
        return len(self.ops)


class ObjectStore(abc.ABC):
    """Abstract store (ref: ObjectStore.h:66).  Writes go through
    transactions; reads are direct."""

    @abc.abstractmethod
    def mount(self) -> None: ...

    @abc.abstractmethod
    def umount(self) -> None: ...

    @abc.abstractmethod
    def mkfs(self) -> None: ...

    @abc.abstractmethod
    def queue_transaction(self, txn: Transaction) -> None:
        """Apply atomically; raises StoreError and leaves no partial
        effects on failure."""

    # -- read side ------------------------------------------------------
    @abc.abstractmethod
    def read(self, cid: str, oid: ObjectId, off: int = 0,
             length: int = 0) -> bytes:
        """length=0 means to the end of the object."""

    @abc.abstractmethod
    def stat(self, cid: str, oid: ObjectId) -> dict: ...

    @abc.abstractmethod
    def exists(self, cid: str, oid: ObjectId) -> bool: ...

    @abc.abstractmethod
    def getattr(self, cid: str, oid: ObjectId, name: str): ...

    @abc.abstractmethod
    def getattrs(self, cid: str, oid: ObjectId) -> dict: ...

    @abc.abstractmethod
    def omap_get(self, cid: str, oid: ObjectId) -> dict[str, bytes]: ...

    @abc.abstractmethod
    def list_collections(self) -> list[str]: ...

    @abc.abstractmethod
    def collection_exists(self, cid: str) -> bool: ...

    @abc.abstractmethod
    def collection_list(self, cid: str) -> list[ObjectId]: ...

    @abc.abstractmethod
    def statfs(self) -> dict: ...


# wire registration: transactions ride ECSubWrite frames between
# shards (ref: ObjectStore::Transaction::encode, MOSDECSubOpWrite)
from ..msg.encoding import register_struct as _reg  # noqa: E402

_reg(ObjectId, version=1, compat=1)
_reg(Transaction, version=1, compat=1)
