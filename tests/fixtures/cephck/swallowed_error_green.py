"""green: every handler leaves a trace, records the failure, or
re-raises what it can't own."""
from ceph_tpu.common.log import dout


def apply_entry(store, entry):
    try:
        store.apply(entry)
    except Exception as ex:
        dout("osd", 1).write("apply failed: %s", ex)
        raise


def drain(store, entries):
    bad = []
    for e in entries:
        try:
            store.apply(e)
        except KeyError:
            bad.append(e)         # recorded: the supervisor checks
    return bad
