"""cephck — project-specific static analysis (the lint gate).

The reference gates merges on exactly this class of tooling: lockdep
(src/common/lockdep.cc) catches lock-order cycles, ceph-dencoder +
ceph-object-corpus pin wire encodings, and a battery of tree-specific
checks (src/script/) runs before anything ships.  cephck is this
repo's analogue: an AST-based rule engine whose rules encode *bugs we
actually shipped* (a pgmeta omap mutation outside its owning
transaction, a wire encode that silently diverged from its registered
version) plus the JAX-specific hazards that invalidate perf claims
(timing a dispatch instead of a compute, unhashable jit static args).

Run it from the repo root::

    python -m ceph_tpu.analysis ceph_tpu/ tests/ scripts/ bench.py

Exit 0 means no unsuppressed findings.  Suppressions live in
``.cephck-baseline.json`` at the repo root and every entry MUST carry
a one-line ``reason`` — a baseline without justification is just a
blindfold.  See README "Static analysis & sanitizers".
"""
from .engine import Engine, Finding, load_baseline, main  # noqa: F401
from .rules import ALL_RULES  # noqa: F401
