#!/usr/bin/env python
"""Chaos smoke — the fault-injection half of the ship gate
(check_green.sh).

Boots a fixed-seed MiniCluster and drives the regression schedule for
the chaos-surfaced elector bugs (ISSUE 17) through ChaosRunner:

  t=20   partition the mon minority (mon.2) from the majority
  t=60   heal — mon.2 must be readmitted to the quorum
  t=80   kill osd.3 (flap down)
  t=120  revive osd.3
  t=140  2% seeded Ping loss on every osd<->osd heartbeat link
  t=200  heal

all under live client IO.  run() raises InvariantViolation unless,
at the end: quorum re-forms with a leader, every PG returns to
active+clean, every acked write reads back byte-identical, SLOW_OPS
and health warnings clear, and the crash table is empty.

Determinism gate: the schedule runs TWICE against fresh clusters and
the per-link fault-log digest (sha256 over every decided fault) plus
the per-kind fault counts must match byte-for-byte — a failing chaos
run must replay exactly from its seed or it cannot be debugged.

Writes CHAOS_r01.json with per-phase client-IO p50/p99 latencies,
fault counts, and the replay digest.

Run from the repo root: python scripts/chaos_smoke.py
"""
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from ceph_tpu.testing import ChaosRunner, MiniCluster   # noqa: E402

FAULT_SEED = 7          # FaultPlane per-link RNG seed
RUNNER_SEED = 1         # ChaosRunner IO/placement seed
N_OSD = 5
N_MON = 3

SCHEDULE = [
    {"at": 20.0, "action": "partition", "a": ["mon.2"],
     "b": ["mon.0", "mon.1"], "label": "mon-minority"},
    {"at": 60.0, "action": "heal", "target": "mon-minority"},
    {"at": 80.0, "action": "kill_osd", "osd": 3},
    {"at": 120.0, "action": "revive_osd", "osd": 3},
    {"at": 140.0, "action": "drop", "src": "osd.*", "dst": "osd.*",
     "p": 0.02, "types": ["Ping"], "label": "ping-loss"},
    {"at": 200.0, "action": "heal", "target": "ping-loss"},
]


def run_once() -> dict:
    c = MiniCluster(n_osd=N_OSD, threaded=False, n_mon=N_MON,
                    fault_seed=FAULT_SEED)
    try:
        c.pump()
        c.wait_all_up()
        return ChaosRunner(c, SCHEDULE, rados=c.rados(),
                           seed=RUNNER_SEED).run()
    finally:
        c.shutdown()


def main() -> int:
    rep1 = run_once()
    if not (rep1["acked"] == rep1["ops_total"] > 0):
        print(f"chaos smoke: FAIL — {rep1['acked']}/{rep1['ops_total']}"
              " writes acked", file=sys.stderr)
        return 1
    if rep1["fault_counts"].get("partition", 0) <= 0:
        print("chaos smoke: FAIL — the partition never bit",
              file=sys.stderr)
        return 1

    rep2 = run_once()
    if rep2["fault_digest"] != rep1["fault_digest"] or \
            rep2["fault_counts"] != rep1["fault_counts"]:
        print("chaos smoke: FAIL — replay diverged from seed "
              f"{FAULT_SEED}:\n  run1 {rep1['fault_digest']} "
              f"{rep1['fault_counts']}\n  run2 {rep2['fault_digest']} "
              f"{rep2['fault_counts']}", file=sys.stderr)
        return 1

    out = {
        "smoke": "chaos",
        "fault_seed": FAULT_SEED,
        "runner_seed": RUNNER_SEED,
        "n_osd": N_OSD,
        "n_mon": N_MON,
        "schedule": SCHEDULE,
        "fault_digest": rep1["fault_digest"],
        "fault_counts": rep1["fault_counts"],
        "ops_total": rep1["ops_total"],
        "acked": rep1["acked"],
        "phases": rep1["phases"],
    }
    path = pathlib.Path(__file__).resolve().parent.parent / \
        "CHAOS_r01.json"
    path.write_text(json.dumps(out, indent=1, sort_keys=True) + "\n")
    worst = max((p["p99_ms"] for p in rep1["phases"]), default=0.0)
    print(f"chaos smoke: OK — {rep1['acked']}/{rep1['ops_total']} "
          f"writes acked+verified, faults {rep1['fault_counts']}, "
          f"digest replayed, worst phase p99 {worst:.1f} ms "
          f"-> {path.name}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
