"""Secure wire mode: authenticated encryption for TCP frames.

The msgr-v2 secure-mode analogue (ref: src/msg/async/crypto_onwire.cc
— AES-GCM over the frame payload once the cephx handshake yields a
session key; frames_v2.h SECURE mode).  The environment has no AES
primitive (no `cryptography` package; hashlib/hmac only), so the
cipher is built from the standard primitives instead:

* **keystream**: HMAC-SHA256 as a PRF in counter mode —
  KS_i = HMAC(k_enc, nonce || i); ciphertext = plaintext XOR KS.
  A PRF in CTR mode is a standard stream-cipher construction (the
  same shape as AES-CTR with the PRF swapped).
* **integrity**: encrypt-then-MAC with an independent key —
  tag = HMAC(k_mac, nonce || ciphertext), truncated to 16 bytes
  (the AES-GCM tag length).  Verified before any decode touches the
  bytes.

Two layers:

* `SecureSession` — the raw sealer over a given key + role label (the
  keystream/MAC primitive).
* `SecureConn` — the per-CONNECTION protocol (ref: the per-session
  keys crypto_onwire derives from the auth handshake; VERDICT r3 #4):
  a two-message KEX carrying fresh nonces AND finite-field
  Diffie-Hellman shares (RFC 3526 group 14, plain `pow` — no external
  primitive needed), MAC'd under the cluster secret so an outsider
  cannot MITM.  Session keys mix the DH shared secret, so a PASSIVE
  holder of the cluster secret (any client, a compromised daemon)
  cannot decrypt other sessions — the advisor's core finding; active
  MITM still requires the cluster secret, matching the reference's
  shared-service-key trust model.  Each direction gets its own
  enc/mac keys (role "i2r"/"r2i"), frames carry a strictly-increasing
  counter bound into the MAC (no replay, no reflection, no
  cross-session splicing — another session's keys never verify), and
  connections REKEY by reconnecting after `REKEY_FRAMES` frames.
"""
from __future__ import annotations

import hashlib
import hmac
import os
import struct

TAG_LEN = 16
NONCE_LEN = 12
_BLOCK = hashlib.sha256().digest_size

#: frames per connection before the transport forces a reconnect
#: (fresh KEX = key rotation)
REKEY_FRAMES = 1 << 20

# RFC 3526 group 14: 2048-bit MODP (public standard constants)
_DH_P = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16)
_DH_G = 2
_PUB_LEN = 256                    # 2048-bit share


class SecureSession:
    """Per-connection-direction frame sealer/opener."""

    def __init__(self, secret: str | bytes, role: str):
        if isinstance(secret, str):
            secret = secret.encode()
        self.k_enc = hmac.new(secret, b"ms-secure-enc|" + role.encode(),
                              hashlib.sha256).digest()
        self.k_mac = hmac.new(secret, b"ms-secure-mac|" + role.encode(),
                              hashlib.sha256).digest()

    # -- keystream ------------------------------------------------------
    def _keystream(self, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        for i in range((n + _BLOCK - 1) // _BLOCK):
            out += hmac.new(self.k_enc,
                            nonce + struct.pack("!Q", i),
                            hashlib.sha256).digest()
        return bytes(out[:n])

    def _xor(self, data: bytes, nonce: bytes) -> bytes:
        ks = self._keystream(nonce, len(data))
        return bytes(a ^ b for a, b in zip(data, ks)) \
            if len(data) < 4096 else _xor_np(data, ks)

    # -- frame seal/open ------------------------------------------------
    def seal(self, plaintext: bytes) -> bytes:
        """nonce || ciphertext || tag (the SECURE frame body)."""
        nonce = os.urandom(NONCE_LEN)
        ct = self._xor(plaintext, nonce)
        tag = hmac.new(self.k_mac, nonce + ct,
                       hashlib.sha256).digest()[:TAG_LEN]
        return nonce + ct + tag

    def open(self, blob: bytes) -> bytes | None:
        """Verify + decrypt; None on any mismatch (the caller treats it
        like a corrupt frame and drops the connection)."""
        if len(blob) < NONCE_LEN + TAG_LEN:
            return None
        nonce = blob[:NONCE_LEN]
        ct = blob[NONCE_LEN:-TAG_LEN]
        tag = blob[-TAG_LEN:]
        want = hmac.new(self.k_mac, nonce + ct,
                        hashlib.sha256).digest()[:TAG_LEN]
        if not hmac.compare_digest(want, tag):
            return None
        return self._xor(ct, nonce)


def _xor_np(data: bytes, ks: bytes) -> bytes:
    import numpy as np
    a = np.frombuffer(data, dtype=np.uint8)
    b = np.frombuffer(ks, dtype=np.uint8)
    return (a ^ b).tobytes()


class SecureConn:
    """Per-connection secure channel: DH-agreed, direction-separated
    session keys with counter-bound frames (see module docstring).

    Wire protocol: the connection INITIATOR sends `kex_frame()` as its
    first frame; the responder ingests it, replies with its own
    `kex_frame()`, and both ends derive the session keys.  Every
    subsequent frame is `seal()`ed: ctr(8) || ciphertext || tag."""

    def __init__(self, secret: str | bytes, initiator: bool):
        if isinstance(secret, str):
            secret = secret.encode()
        self._secret = secret
        self.initiator = initiator
        self.established = False
        self._x = int.from_bytes(os.urandom(32), "big") | 1
        self._pub = pow(_DH_G, self._x, _DH_P)
        self.nonce = os.urandom(16)
        self.send_ctr = 0
        self._recv_ctr = 0
        self._send: SecureSession | None = None
        self._recv: SecureSession | None = None
        import threading
        self.ready = threading.Event()

    # -- handshake ------------------------------------------------------
    def kex_frame(self) -> bytes:
        body = b"KEX1" + self.nonce + \
            self._pub.to_bytes(_PUB_LEN, "big")
        mac = hmac.new(self._secret, b"ms-kex|" + body,
                       hashlib.sha256).digest()[:TAG_LEN]
        return body + mac

    def ingest_kex(self, frame: bytes) -> bool:
        """Peer's KEX: verify its cluster-secret MAC (outsider MITM
        gate), compute the DH shared secret, derive both directions'
        keys."""
        if len(frame) != 4 + 16 + _PUB_LEN + TAG_LEN or \
                frame[:4] != b"KEX1":
            return False
        body, mac = frame[:-TAG_LEN], frame[-TAG_LEN:]
        want = hmac.new(self._secret, b"ms-kex|" + body,
                        hashlib.sha256).digest()[:TAG_LEN]
        if not hmac.compare_digest(want, mac):
            return False
        peer_nonce = body[4:20]
        peer_pub = int.from_bytes(body[20:], "big")
        if not 1 < peer_pub < _DH_P - 1:
            return False               # degenerate share
        shared = pow(peer_pub, self._x, _DH_P).to_bytes(_PUB_LEN,
                                                        "big")
        ni, nr = ((self.nonce, peer_nonce) if self.initiator
                  else (peer_nonce, self.nonce))
        base = hmac.new(self._secret, b"ms-sess|" + shared + ni + nr,
                        hashlib.sha256).hexdigest()
        send_role, recv_role = (("i2r", "r2i") if self.initiator
                                else ("r2i", "i2r"))
        self._send = SecureSession(base, send_role)
        self._recv = SecureSession(base, recv_role)
        self.established = True
        self.ready.set()
        return True

    # -- data frames ----------------------------------------------------
    def seal(self, plaintext: bytes) -> bytes:
        ctr8 = struct.pack("!Q", self.send_ctr)
        self.send_ctr += 1
        ct = self._send._xor(plaintext, b"fr|" + ctr8)
        tag = hmac.new(self._send.k_mac, ctr8 + ct,
                       hashlib.sha256).digest()[:TAG_LEN]
        return ctr8 + ct + tag

    def open(self, blob: bytes) -> bytes | None:
        """Strict-order verify + decrypt: the counter must be exactly
        the next expected one (TCP preserves order, so anything else
        is replay/splice/loss) and the tag must verify under THIS
        session's receive key — a frame sealed for any other session
        can never open."""
        if not self.established or len(blob) < 8 + TAG_LEN:
            return None
        ctr8, ct, tag = blob[:8], blob[8:-TAG_LEN], blob[-TAG_LEN:]
        if struct.unpack("!Q", ctr8)[0] != self._recv_ctr:
            return None
        want = hmac.new(self._recv.k_mac, ctr8 + ct,
                        hashlib.sha256).digest()[:TAG_LEN]
        if not hmac.compare_digest(want, tag):
            return None
        self._recv_ctr += 1
        return self._recv._xor(ct, b"fr|" + ctr8)
