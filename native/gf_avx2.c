/* Minimal ISA-L-style GF(2^8) erasure encode for the CPU baseline.
 *
 * The measured floor for bench.py's vs_baseline: the same split-nibble
 * PSHUFB scheme ISA-L's ec_encode_data AVX2 assembly uses
 * (ref: src/erasure-code/isa/ ec_encode_data -> gf_vect_mad_avx2: two
 * 16-entry table lookups per 32-byte lane, xor-accumulated across k
 * inputs).  Written from the public algorithm, not the ISA-L sources.
 *
 * Build: cc -O3 -mavx2 -shared -fPIC -o libgfavx2.so gf_avx2.c
 */
#include <immintrin.h>
#include <stdint.h>
#include <string.h>

/* GF(2^8) multiply, AES polynomial 0x11d (same field as jerasure/ISA-L). */
static uint8_t gf_mul_slow(uint8_t a, uint8_t b)
{
    uint16_t p = 0, aa = a;
    while (b) {
        if (b & 1)
            p ^= aa;
        aa <<= 1;
        if (aa & 0x100)
            aa ^= 0x11d;
        b >>= 1;
    }
    return (uint8_t)p;
}

/* Per-coefficient nibble tables: lo[x] = c*x, hi[x] = c*(x<<4). */
static void build_tables(uint8_t c, uint8_t lo[16], uint8_t hi[16])
{
    for (int x = 0; x < 16; x++) {
        lo[x] = gf_mul_slow(c, (uint8_t)x);
        hi[x] = gf_mul_slow(c, (uint8_t)(x << 4));
    }
}

/* out[m][len] ^= mat[m][k] * data[k][len], 32 bytes per AVX2 step.
 * mat is row-major (m x k); data/out are arrays of row pointers. */
void gf_encode_avx2(int k, int m, long len, const uint8_t *mat,
                    const uint8_t **data, uint8_t **out)
{
    const __m256i mask0f = _mm256_set1_epi8(0x0f);
    for (int i = 0; i < m; i++)
        memset(out[i], 0, (size_t)len);
    for (int j = 0; j < k; j++) {
        for (int i = 0; i < m; i++) {
            uint8_t lo[16], hi[16];
            build_tables(mat[i * k + j], lo, hi);
            const __m256i tlo = _mm256_broadcastsi128_si256(
                _mm_loadu_si128((const __m128i *)lo));
            const __m256i thi = _mm256_broadcastsi128_si256(
                _mm_loadu_si128((const __m128i *)hi));
            const uint8_t *src = data[j];
            uint8_t *dst = out[i];
            long n = 0;
            for (; n + 32 <= len; n += 32) {
                __m256i v = _mm256_loadu_si256((const __m256i *)(src + n));
                __m256i l = _mm256_and_si256(v, mask0f);
                __m256i h = _mm256_and_si256(
                    _mm256_srli_epi16(v, 4), mask0f);
                __m256i prod = _mm256_xor_si256(
                    _mm256_shuffle_epi8(tlo, l),
                    _mm256_shuffle_epi8(thi, h));
                __m256i acc = _mm256_loadu_si256((__m256i *)(dst + n));
                _mm256_storeu_si256((__m256i *)(dst + n),
                                    _mm256_xor_si256(acc, prod));
            }
            for (; n < len; n++)
                dst[n] ^= gf_mul_slow(mat[i * k + j], src[n]);
        }
    }
}
