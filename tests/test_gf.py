"""GF(2^8) core sanity: field axioms, inversion, matrix generators."""
import numpy as np
import pytest

from ceph_tpu.ec import gf


def test_field_basics():
    MUL = gf.mul_table()
    # identity, zero
    assert np.array_equal(MUL[1], np.arange(256))
    assert np.all(MUL[0] == 0)
    # commutative
    assert np.array_equal(MUL, MUL.T)
    # known value in 0x11d field: 2*128 = 0x1d ^ ... 0x80<<1 = 0x100 -> ^0x11d = 0x1d
    assert gf.gf_mul(2, 0x80) == 0x1D
    # every nonzero element has an inverse
    inv = gf.inv_table()
    a = np.arange(1, 256)
    assert np.all(MUL[a, inv[a]] == 1)


def test_associativity_sample():
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = rng.integers(0, 256, 3)
        assert gf.gf_mul(gf.gf_mul(a, b), c) == gf.gf_mul(a, gf.gf_mul(b, c))
        # distributive over xor
        assert gf.gf_mul(a, b ^ c) == gf.gf_mul(a, b) ^ gf.gf_mul(a, c)


def test_invert_matrix_roundtrip():
    rng = np.random.default_rng(1)
    for n in (2, 4, 8, 13):
        for _ in range(5):
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            inv = gf.gf_invert_matrix(m)
            if inv is None:
                continue
            assert np.array_equal(gf.gf_matmul(m, inv), np.eye(n, dtype=np.uint8))


def test_invert_singular():
    m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
    assert gf.gf_invert_matrix(m) is None


@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 4), (10, 4)])
def test_isa_rs_matrix_mds(k, m):
    a = gf.isa_rs_matrix(k, m)
    assert np.array_equal(a[:k], np.eye(k, dtype=np.uint8))
    assert np.all(a[k] == 1)  # first coding row all ones (XOR fast path)
    # ISA-L only guarantees MDS for limited m with vandermonde; check small cases
    if m <= 2:
        _assert_mds(a, k, m)


@pytest.mark.parametrize("k,m", [(2, 2), (4, 2), (8, 4), (10, 4)])
def test_isa_cauchy_mds(k, m):
    _assert_mds(gf.isa_cauchy_matrix(k, m), k, m)


@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 4), (10, 4)])
def test_jerasure_vandermonde_systematic_mds(k, m):
    c = gf.jerasure_vandermonde_coding_matrix(k, m)
    assert c.shape == (m, k)
    full = np.vstack([np.eye(k, dtype=np.uint8), c])
    _assert_mds(full, k, m)


@pytest.mark.parametrize("k,m", [(4, 2), (8, 3), (10, 4)])
def test_cauchy_orig_good_mds(k, m):
    for mat in (gf.cauchy_original_coding_matrix(k, m),
                gf.cauchy_good_coding_matrix(k, m)):
        full = np.vstack([np.eye(k, dtype=np.uint8), mat])
        _assert_mds(full, k, m)
    good = gf.cauchy_good_coding_matrix(k, m)
    assert np.all(good[0] == 1)


def test_r6_matrix():
    mat = gf.jerasure_r6_coding_matrix(6)
    assert np.all(mat[0] == 1)
    assert list(mat[1]) == [1, 2, 4, 8, 16, 32]


def test_bitmatrix_equivalence():
    rng = np.random.default_rng(2)
    k, m, n = 5, 3, 64
    mat = rng.integers(0, 256, (m, k)).astype(np.uint8)
    data = rng.integers(0, 256, (k, n)).astype(np.uint8)
    want = gf.gf_matmul_bytes(mat, data)
    B = gf.expand_to_bitmatrix(mat)
    bits = ((data[:, None, :] >> np.arange(8)[None, :, None]) & 1).reshape(8 * k, n)
    out_bits = (B.astype(np.int32) @ bits.astype(np.int32)) & 1
    got = (out_bits.reshape(m, 8, n) * (1 << np.arange(8))[None, :, None]).sum(1)
    assert np.array_equal(got.astype(np.uint8), want)


def _assert_mds(full, k, m):
    """Every k-row subset of the (k+m) x k matrix must be invertible."""
    import itertools
    for rows in itertools.combinations(range(k + m), k):
        sub = full[list(rows)]
        assert gf.gf_invert_matrix(sub) is not None, rows
