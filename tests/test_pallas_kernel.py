"""Planar Pallas kernel parity (interpret mode, runs on CPU): the fused
grouped kernel must match the numpy GF oracle bit-for-bit across group/
tile/ragged-shape selections (ref kernel design: PERF_NOTES.md;
behavior parity target: src/erasure-code/isa ec_encode_data)."""
import numpy as np
import pytest

from ceph_tpu.ec import gf
from ceph_tpu.ec.kernels.bitmatmul import (companion_bitmatrix,
                                           gf_matmul_pallas,
                                           gf_matmul_xla,
                                           grouped_planar_bitmatrix,
                                           pack_matrix)


@pytest.mark.parametrize("s,k,m,n", [
    (8, 8, 4, 16384),   # g=4, tile 8192
    (7, 8, 4, 8192),    # odd batch -> g=1
    (2, 4, 2, 2048),    # g=2, min tile
    (1, 8, 4, 4096),    # single stripe
    (6, 3, 2, 2112),    # ragged tail (2048 body + 64 xla tail)
    (4, 8, 4, 1024),    # below min tile -> pure xla fallback
    (4, 2, 1, 6144),    # tiny code, multiple tiles
])
def test_pallas_parity_vs_oracle(s, k, m, n):
    rng = np.random.default_rng(k * 1000 + n)
    mat = gf.isa_rs_matrix(k, m)[k:]
    data = rng.integers(0, 256, (s, k, n), dtype=np.uint8)
    out = np.asarray(gf_matmul_pallas(mat, data, interpret=True))
    want = np.stack([gf.gf_matmul_bytes(mat, data[i]) for i in range(s)])
    assert np.array_equal(out, want)


def test_grouped_planar_matrix_structure():
    """The permuted block-diagonal matrix recomputes the interleaved
    one: B_planar[:, c*gk + j] == B_blockdiag[:, 8j + c]."""
    mat = np.ascontiguousarray(gf.isa_rs_matrix(8, 4)[8:])
    b1 = companion_bitmatrix(mat.tobytes(), 4, 8)
    bp = grouped_planar_bitmatrix(mat.tobytes(), 4, 8, 4)
    gk = 4 * 8
    assert bp.shape == (128, 256)
    # reconstruct the interleaved block-diag and compare per block
    for g in range(4):
        for j in range(8):
            for c in range(8):
                col_planar = c * gk + (g * 8 + j)
                np.testing.assert_array_equal(
                    bp[32 * g:32 * (g + 1), col_planar],
                    b1[:, 8 * j + c])


def test_pack_matrix_int8_wraparound():
    p = pack_matrix(4)
    assert p.shape == (4, 32)
    assert p[0, 7] == -128  # 1<<7 wraps; mod-256 exact after uint8 cast
    bits = np.ones((32, 4), dtype=np.int8)
    packed = (p.astype(np.int32) @ bits.astype(np.int32)).astype(np.uint8)
    assert (packed == 0xFF).all()


def test_pallas_matches_xla_path():
    """Both public paths agree (the plugin picks by backend/config)."""
    import jax.numpy as jnp
    mat = gf.isa_rs_matrix(6, 3)[6:]
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (4, 6, 4096), dtype=np.uint8)
    b = jnp.asarray(companion_bitmatrix(
        np.ascontiguousarray(mat).tobytes(), 3, 6))
    out_x = np.asarray(gf_matmul_xla(b, data))
    out_p = np.asarray(gf_matmul_pallas(mat, data, interpret=True))
    assert np.array_equal(out_x, out_p)
