"""Admin socket + OpTracker (ref: src/common/admin_socket.cc,
src/common/TrackedOp.h)."""
import time

import pytest

from ceph_tpu.common.admin_socket import AdminSocket, admin_command
from ceph_tpu.common.tracked_op import OpTracker
from ceph_tpu.testing import MiniCluster


def test_admin_socket_roundtrip(tmp_path):
    sock = str(tmp_path / "a.asok")
    a = AdminSocket(sock)
    a.register("echo", "echo back", lambda c: (0, c.get("x", "?")))
    a.register("fail", "always fails", lambda c: (-5, "EIO"))
    a.start()
    try:
        rc, out = admin_command(sock, {"prefix": "echo", "x": 42})
        assert rc == 0 and out == 42
        rc, out = admin_command(sock, "fail")
        assert rc == -5
        rc, out = admin_command(sock, "nope")
        assert rc == -22 and "unknown" in out
        rc, out = admin_command(sock, "help")
        assert rc == 0 and "echo" in out
    finally:
        a.shutdown()


def test_op_tracker():
    t = OpTracker(history_size=3, complaint_time=0.05)
    t.start("k1", "op one")
    t.mark("k1", "queued")
    assert t.dump_in_flight()["num_ops"] == 1
    time.sleep(0.08)
    assert len(t.slow_ops()) == 1
    t.finish("k1")
    assert t.dump_in_flight()["num_ops"] == 0
    h = t.dump_historic()
    assert h["num_ops"] == 1
    assert [e["event"] for e in h["ops"][0]["events"]] == \
        ["initiated", "queued", "done"]
    # history ring bounded
    for i in range(5):
        t.start(i, f"op{i}")
        t.finish(i)
    assert t.dump_historic()["num_ops"] == 3


def test_osd_admin_socket_end_to_end(tmp_path):
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("ap", pg_num=8)
        io = r.open_ioctx("ap")
        sock = str(tmp_path / "osd0.asok")
        c.osds[0].start_admin_socket(sock)
        for i in range(6):
            io.write_full(f"o{i}", b"data")
        rc, perf = admin_command(sock, "perf dump")
        assert rc == 0 and perf["op"] > 0
        rc, st = admin_command(sock, "status")
        assert rc == 0 and st["whoami"] == 0 and st["num_pgs"] > 0
        rc, hist = admin_command(sock, "dump_historic_ops")
        assert rc == 0 and hist["num_ops"] > 0
        ev = [e["event"] for e in hist["ops"][-1]["events"]]
        assert ev[0] == "initiated" and "dispatched" in ev
        rc, infl = admin_command(sock, "dump_ops_in_flight")
        assert rc == 0 and isinstance(infl["ops"], list)
        rc, cfg = admin_command(sock, "config show")
        assert rc == 0 and "osd_heartbeat_interval" in cfg
        rc, _ = admin_command(sock, {"prefix": "config set",
                                     "var": "log_level", "val": "2"})
        assert rc == 0
        rc, v = admin_command(sock, {"prefix": "config get",
                                     "var": "log_level"})
        assert rc == 0 and v == 2
    finally:
        c.shutdown()


def test_mon_admin_socket(tmp_path):
    c = MiniCluster(n_osd=2, threaded=True)
    try:
        c.wait_all_up()
        sock = str(tmp_path / "mon.asok")
        c.mon.start_admin_socket(sock)
        rc, s = admin_command(sock, "status")
        assert rc == 0 and s["osdmap"]["num_up_osds"] == 2
        rc, q = admin_command(sock, "quorum_status")
        assert rc == 0 and q["leader"] == 0
        rc, h = admin_command(sock, "health")
        assert rc == 0
    finally:
        c.shutdown()
