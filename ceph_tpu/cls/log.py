"""cls log: omap-backed time-indexed log object class
(ref: src/cls/log/cls_log.cc).

The reference's rgw metadata/data logs and the mon's timecheck
history all ride this class: entries land in an object's omap keyed
``1_<sec>.<usec>_<counter>`` so lexicographic omap order IS time
order; ``add`` appends (a per-call counter disambiguates same-stamp
entries exactly like cls_log.cc's ``index_time_prefix`` + unique
suffix), ``list`` pages forward from a time bound or an opaque
marker, ``trim`` drops a time range or everything up to a marker.
The max_entries page cap mirrors MAX_TRIM_ENTRIES/list bounds so one
call can neither return nor delete an unbounded batch.
"""
from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, cls_method

#: omap key prefix for log entries (ref: cls_log.cc LOG_INDEX_PREFIX
#: "1_")
_PREFIX = "1_"
#: header key carrying the allocation counter (kept out of the entry
#: namespace — "0" sorts before every "1_" key)
_HEADER = "0_header"

#: page/trim bound per call (ref: cls_log.cc MAX_TRIM_ENTRIES; list
#: clamps to 1000 in the reference's callers)
MAX_ENTRIES = 1000


def _key(ts: float, counter: int) -> str:
    """Zero-padded so lexicographic omap order is (time, counter)
    order (ref: cls_log.cc get_index_time_prefix's %010ld.%06ld)."""
    sec = int(ts)
    usec = int(round((ts - sec) * 1_000_000))
    if usec >= 1_000_000:
        # a stamp within 0.5us below a whole second rounds UP: carry
        # into sec, or the 7-digit usec field would sort BEFORE every
        # 6-digit one and break the time-order invariant
        sec += 1
        usec = 0
    return f"{_PREFIX}{sec:010d}.{usec:06d}_{counter:010d}"


def _load_header(ctx) -> dict:
    try:
        raw = ctx.omap_get_header()
    except ClsError:
        raw = b""
    if not raw:
        return {"counter": 0}
    return json.loads(raw)


def _entries(ctx) -> dict:
    try:
        omap = ctx.omap_get()
    except ClsError:
        return {}
    return {k: v for k, v in omap.items() if k.startswith(_PREFIX)}


@cls_method("log", "add", CLS_METHOD_RD | CLS_METHOD_WR)
def add(ctx, ind):
    """Append entries (ref: cls_log.cc cls_log_add).  ``entries`` is
    a list of {timestamp, section, name, data}; each gets a unique
    monotonic key even when timestamps collide."""
    entries = ind.get("entries")
    if entries is None and "entry" in ind:
        entries = [ind["entry"]]
    if not isinstance(entries, list) or not entries:
        raise ClsError("EINVAL", "log add needs 'entries'")
    hdr = _load_header(ctx)
    kv: dict[str, bytes] = {}
    for e in entries:
        try:
            ts = float(e["timestamp"])
        except (KeyError, TypeError, ValueError):
            raise ClsError("EINVAL", "entry needs a numeric timestamp")
        hdr["counter"] += 1
        rec = {"timestamp": ts,
               "section": str(e.get("section", "")),
               "name": str(e.get("name", "")),
               "data": str(e.get("data", ""))}
        kv[_key(ts, hdr["counter"])] = json.dumps(rec).encode()
    if not ctx.exists():
        ctx.create()
    ctx.omap_set(kv)
    ctx.omap_set_header(json.dumps(hdr).encode())
    return None


@cls_method("log", "list", CLS_METHOD_RD)
def list_(ctx, ind):
    """Page entries in time order (ref: cls_log.cc cls_log_list).

    ``from_time``/``to_time`` bound the window (to_time exclusive,
    like the reference's to_index upper bound); ``marker`` resumes a
    paged listing after that opaque key; ``max_entries`` caps the
    page.  Returns {entries, marker, truncated}: ``marker`` is the
    resume cursor when ``truncated`` is set."""
    maxe = min(int(ind.get("max_entries", MAX_ENTRIES)), MAX_ENTRIES)
    if maxe <= 0:
        raise ClsError("EINVAL", "max_entries must be positive")
    lo = _key(float(ind["from_time"]), 0) \
        if "from_time" in ind else _PREFIX
    hi = _key(float(ind["to_time"]), 0) \
        if "to_time" in ind else None
    marker = str(ind.get("marker", ""))
    if marker:
        lo = None           # marker supersedes the time lower bound
    out = []
    truncated = False
    last = ""
    entries = _entries(ctx)
    for k in sorted(entries):
        if marker and k <= marker:
            continue
        if lo is not None and k < lo:
            continue
        if hi is not None and k >= hi:
            break
        if len(out) == maxe:
            truncated = True
            break
        rec = json.loads(entries[k])
        rec["id"] = k
        out.append(rec)
        last = k
    return {"entries": out, "marker": last if truncated else "",
            "truncated": truncated}


@cls_method("log", "trim", CLS_METHOD_RD | CLS_METHOD_WR)
def trim(ctx, ind):
    """Drop entries by time range or up to a marker (ref: cls_log.cc
    cls_log_trim).  At most MAX_ENTRIES go per call — the caller
    repeats until it stops returning trimmed > 0, exactly how the
    reference re-enters until -ENODATA."""
    to_marker = str(ind.get("to_marker", ""))
    from_time = float(ind.get("from_time", 0.0))
    has_window = "to_time" in ind or to_marker
    if not has_window:
        raise ClsError("EINVAL", "log trim needs to_time or to_marker")
    hi = _key(float(ind["to_time"]), 0) if "to_time" in ind else None
    lo = _key(from_time, 0)
    doomed = []
    for k in sorted(_entries(ctx)):
        if k < lo:
            continue
        if to_marker:
            if k > to_marker:
                break
        elif hi is not None and k >= hi:
            break
        doomed.append(k)
        if len(doomed) == MAX_ENTRIES:
            break
    if doomed:
        ctx.omap_rmkeys(doomed)
    return {"trimmed": len(doomed)}


@cls_method("log", "info", CLS_METHOD_RD)
def info(ctx, ind):
    """Head summary (ref: cls_log.cc cls_log_info): the allocation
    counter plus first/last entry keys — the cheap "how far along is
    this log" probe trim loops use."""
    hdr = _load_header(ctx)
    keys = sorted(_entries(ctx))
    return {"counter": hdr.get("counter", 0),
            "entries": len(keys),
            "first": keys[0] if keys else "",
            "last": keys[-1] if keys else ""}
