"""EC thrash suite: a wide k=8,m=4 pool through repeated kills/
out-in/pg growth under IO, with shard read-error injection
(ref: qa/tasks/ceph_manager.py OSDThrasher over EC pools +
qa/standalone/erasure-code/test-erasure-code.sh; the EIO leg models
objectstore_debug_inject_read_err applied to EC chunk reads, so
recovery-from-EIO is exercised end to end)."""
import random
import time

import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.osd.types import PG
from ceph_tpu.testing import MiniCluster, OSDThrasher

K, M = 8, 4


def make_ec_cluster(n_osd=14, pg_num=4, pool="ecp"):
    c = MiniCluster(n_osd=n_osd, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k8m4t",
                   "profile": {"plugin": "tpu", "k": str(K),
                               "m": str(M),
                               "crush-failure-domain": "osd"}})
    r.pool_create(pool, pg_num=pg_num, pool_type="erasure",
                  erasure_code_profile="k8m4t")
    c.pump()
    return c, r


@pytest.fixture()
def eio_flag():
    cfg = global_config()
    old = cfg["objectstore_debug_inject_read_err"]
    cfg.set("objectstore_debug_inject_read_err", True)
    yield
    cfg.set("objectstore_debug_inject_read_err", old)


def drain(c, io, futures, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        c.pump()
        if all(f.done() for f in futures.values()):
            break
        time.sleep(0.02)
    return [o for o, f in futures.items() if not f.done()]


def test_ec_shard_eio_read_reconstructs(eio_flag):
    """A chunk read failing with EIO on one shard must not fail the
    client read: the primary retries the remaining shards and
    decodes (ref: ECBackend get_remaining_shards retry)."""
    c, r = make_ec_cluster(n_osd=13, pg_num=2)
    try:
        io = r.open_ioctx("ecp")
        payload = bytes(random.Random(1).randrange(256)
                        for _ in range(1 << 14))
        io.write_full("eobj", payload)
        c.pump()
        pid = r.pool_lookup("ecp")
        m = c.mon.osdmap
        raw = m.object_locator_to_pg("eobj", pid)
        pg = m.pools[pid].raw_pg_to_pg(raw)
        _, _, acting, primary = m.pg_to_up_acting_osds(raw)
        # hit a DATA shard on a non-primary OSD so the decode path
        # (not the local fast path) must tolerate the error
        victim_shard = next(s for s in range(K)
                            if acting[s] != primary and acting[s] >= 0)
        victim = acting[victim_shard]
        st = c.osds[victim].pgs[pg]
        st.shard.inject_read_err("eobj")
        assert io.read("eobj") == payload
        # injection really fires: the victim's own chunk read errors
        from ceph_tpu.store import StoreError, ObjectId
        from ceph_tpu.osd.ec_backend import pg_cid
        with pytest.raises(StoreError):
            c.osds[victim].store.read(
                pg_cid(pg), ObjectId("eobj", shard=victim_shard))
        st.shard.clear_read_err("eobj")
        assert io.read("eobj") == payload
    finally:
        c.shutdown()


def test_ec_thrash_kills_eio_and_io_survives(eio_flag):
    """The full loop over an EC pool: random kill/revive/out/in plus
    shard-EIO injection with async IO interleaved, then heal and
    verify every object byte-for-byte."""
    c, r = make_ec_cluster(n_osd=14, pg_num=4)
    try:
        io = r.open_ioctx("ecp")
        rng = random.Random(42)
        expected: dict[str, bytes] = {}
        futures: dict[str, object] = {}

        def do_io(i):
            for _ in range(2):
                oid = f"e{rng.randrange(10)}"
                data = bytes([rng.randrange(256)]) * \
                    rng.randrange(256, 4096)
                futures[oid] = io.aio_write_full(oid, data)
                expected[oid] = data
            c.pump()

        # >= K+M must stay in/alive so CRUSH keeps full-width
        # mappings while still letting the thrasher take 2 down
        t = OSDThrasher(c, seed=7, min_in=12, min_live=12,
                        ec_pools=["ecp"], rados=r)
        do_io(-1)
        t.do_thrash(8, between=do_io)
        # at least one EIO injection must have occurred in the mix;
        # force one if the dice never rolled it
        if not t.injected and not any("eio" in l for l in t.log):
            t.inject_shard_eio()
            do_io(99)
        t.heal()
        undone = drain(c, io, futures)
        assert not undone, (undone, t.log)
        failed = {o: f.errno_name for o, f in futures.items()
                  if f.result < 0}
        assert not failed, (failed, t.log)
        for oid, data in sorted(expected.items()):
            assert io.read(oid) == data, (oid, t.log)
        assert all(c.mon.osdmap.is_up(o) and c.mon.osdmap.is_in(o)
                   for o in range(14)), t.log
    finally:
        c.shutdown()


def test_ec_pg_growth_under_io():
    """pg_num/pgp_num growth on a live k=8,m=4 pool: collections
    split, placements reseed, and every object stays readable."""
    c, r = make_ec_cluster(n_osd=13, pg_num=4, pool="egrow")
    try:
        io = r.open_ioctx("egrow")
        rng = random.Random(9)
        expected = {}
        for i in range(12):
            data = bytes([rng.randrange(256)]) * rng.randrange(512, 3000)
            io.write_full(f"g{i}", data)
            expected[f"g{i}"] = data
        c.pump()
        rc, outs, _ = r.mon_command({"prefix": "osd pool set",
                                     "pool": "egrow", "var": "pg_num",
                                     "val": "8"})
        assert rc == 0, outs
        rc, outs, _ = r.mon_command({"prefix": "osd pool set",
                                     "pool": "egrow",
                                     "var": "pgp_num", "val": "8"})
        assert rc == 0, outs
        c.pump()
        now = 30_000.0
        for _ in range(4):
            now += 11
            c.tick(now)
            c.pump()
        # writes keep landing post-split
        for i in range(12, 16):
            data = bytes([rng.randrange(256)]) * 1024
            io.write_full(f"g{i}", data)
            expected[f"g{i}"] = data
        c.pump()
        pid = r.pool_lookup("egrow")
        assert c.mon.osdmap.pools[pid].pg_num == 8
        for oid, data in sorted(expected.items()):
            assert io.read(oid) == data, oid
    finally:
        c.shutdown()
