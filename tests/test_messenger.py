"""Transport layer tests (behavioral model: the reference's messenger
unit tests src/test/msgr/test_msgr.cc basic deliver/reset cases, scaled
to the local backend)."""
import threading
import time

import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.msg import Dispatcher, LocalNetwork, Messenger
from ceph_tpu.msg.messages import Ping, PingReply


class Collector(Dispatcher):
    def __init__(self):
        self.msgs = []
        self.resets = []
        self.event = threading.Event()

    def ms_dispatch(self, msg):
        self.msgs.append(msg)
        self.event.set()
        return True

    def ms_handle_reset(self, peer):
        self.resets.append(peer)


def _wait(pred, timeout=5.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.005)
    return False


def test_threaded_send_receive():
    net = LocalNetwork()
    a = Messenger.create(net, "osd.0", "local")
    b = Messenger.create(net, "osd.1", "local")
    ca, cb = Collector(), Collector()
    a.add_dispatcher(ca)
    b.add_dispatcher(cb)
    a.start()
    b.start()
    try:
        assert a.connect("osd.1").send_message(Ping(epoch=3))
        assert _wait(lambda: len(cb.msgs) == 1)
        msg = cb.msgs[0]
        assert isinstance(msg, Ping) and msg.epoch == 3
        assert msg.src == "osd.0" and msg.seq > 0
        # reply using msg.src
        assert b.connect(msg.src).send_message(PingReply(epoch=3))
        assert _wait(lambda: len(ca.msgs) == 1)
        assert isinstance(ca.msgs[0], PingReply)
    finally:
        a.shutdown()
        b.shutdown()


def test_polled_mode_deterministic():
    net = LocalNetwork()
    a = Messenger.create(net, "a", "local", threaded=False)
    b = Messenger.create(net, "b", "local", threaded=False)
    cb = Collector()
    b.add_dispatcher(cb)
    for i in range(5):
        a.connect("b").send_message(Ping(epoch=i))
    assert cb.msgs == []                 # nothing delivered yet
    assert b.poll(2) == 2                # bounded pump
    assert [m.epoch for m in cb.msgs] == [0, 1]
    assert b.poll() == 3
    assert [m.epoch for m in cb.msgs] == [0, 1, 2, 3, 4]  # FIFO order


def test_send_to_unknown_peer_resets():
    net = LocalNetwork()
    a = Messenger.create(net, "a", "local", threaded=False)
    ca = Collector()
    a.add_dispatcher(ca)
    assert not a.connect("ghost").send_message(Ping())
    assert ca.resets == ["ghost"]


def test_duplicate_bind_rejected():
    net = LocalNetwork()
    Messenger.create(net, "osd.0", "local")
    with pytest.raises(ValueError):
        Messenger.create(net, "osd.0", "local")


def test_inject_socket_failures_drops():
    """ms_inject_socket_failures=N is a compat shim over the
    FaultPlane: a seeded 1/N drop probability per message, not the
    old every-Nth modulus — assert consistency + determinism rather
    than an exact count."""
    cfg = global_config()

    def run():
        net = LocalNetwork()
        a = Messenger.create(net, "a", "local", threaded=False)
        b = Messenger.create(net, "b", "local", threaded=False)
        cb = Collector()
        b.add_dispatcher(cb)
        sent = [a.connect("b").send_message(Ping(epoch=i))
                for i in range(60)]
        b.poll()
        return sent, net, cb

    try:
        cfg.set("ms_inject_socket_failures", 3)   # p = 1/3 per message
        sent, net, cb = run()
        dropped = sent.count(False)
        assert 0 < dropped < 60            # some but not all
        assert dropped == len(net.dropped) == net.drops_total
        assert dropped + len(cb.msgs) == 60
        # drops signal resets both ways (legacy shim semantics)
        assert len(net.dropped) == len(
            [p for p in cb.resets if p == "a"]) > 0
        # same seed -> byte-identical drop pattern on a fresh network
        sent2, _, _ = run()
        assert sent2 == sent
    finally:
        cfg.set("ms_inject_socket_failures", 0)


def test_network_filter_hook():
    net = LocalNetwork()
    a = Messenger.create(net, "a", "local", threaded=False)
    b = Messenger.create(net, "b", "local", threaded=False)
    cb = Collector()
    b.add_dispatcher(cb)
    net.filter = lambda src, dst, msg: not (
        isinstance(msg, Ping) and msg.epoch == 1)
    for i in range(3):
        a.connect("b").send_message(Ping(epoch=i))
    b.poll()
    assert [m.epoch for m in cb.msgs] == [0, 2]


def test_shutdown_unregisters():
    net = LocalNetwork()
    a = Messenger.create(net, "a", "local", threaded=False)
    b = Messenger.create(net, "b", "local")
    b.start()
    b.shutdown()
    assert not a.connect("b").send_message(Ping())
    # name is reusable after shutdown
    Messenger.create(net, "b", "local")
