"""Multi-mon quorum: election, replicated paxos commits, peon command
forwarding, leader failover, catch-up (ref: src/mon/Elector.cc,
src/mon/Paxos.cc begin/accept/commit, Monitor::forward_request_leader)."""
import pytest

from ceph_tpu.msg.messages import MMonCommand, MMonCommandAck
from ceph_tpu.msg.messenger import Dispatcher, Messenger
from ceph_tpu.testing import MiniCluster


def make_cluster(n_mon=3, n_osd=4):
    c = MiniCluster(n_osd=n_osd, threaded=False, n_mon=n_mon)
    c.pump()
    c.wait_all_up()
    return c


def stores_converged(c):
    versions = {r: mn.paxos.last_committed for r, mn in c.mons.items()}
    epochs = {r: mn.osdmap.epoch for r, mn in c.mons.items()}
    assert len(set(versions.values())) == 1, versions
    assert len(set(epochs.values())) == 1, epochs


class CmdClient(Dispatcher):
    def __init__(self, net, name, mon):
        self.ms = Messenger.create(net, name, threaded=False)
        self.ms.add_dispatcher(self)
        self.ms.start()
        self.mon = mon
        self.acks = []

    def ms_dispatch(self, msg):
        if isinstance(msg, MMonCommandAck):
            self.acks.append(msg)
            return True
        return False

    def send(self, tid, cmd):
        self.ms.connect(self.mon).send_message(
            MMonCommand(tid=tid, cmd=cmd))

    def pump_with(self, c, rounds=10):
        for _ in range(rounds):
            c.pump()
            if not self.ms.poll():
                break


def test_election_lowest_rank_wins():
    c = make_cluster()
    leaders = [r for r, mn in c.mons.items() if mn.is_leader]
    assert leaders == [0]
    for r, mn in c.mons.items():
        assert mn.leader_rank == 0
    # the winning quorum is a majority that contains the leader (late
    # ackers need not be in it)
    q = c.mons[0].elector.quorum
    assert 0 in q and len(q) >= 2
    c.shutdown()


def test_commit_replicates_to_all_mons():
    c = make_cluster()
    r = c.rados()
    r.pool_create("p", pg_num=8)
    c.pump()
    stores_converged(c)
    for mn in c.mons.values():
        assert "p" in mn.osdmap.pool_names.values()
    c.shutdown()


def test_peon_forwards_write_commands():
    c = make_cluster()
    cl = CmdClient(c.network, "client.77", "mon.2")   # a peon
    cl.send(5, {"prefix": "osd pool create", "pool": "via-peon",
                "pg_num": 8})
    cl.pump_with(c)
    assert cl.acks and cl.acks[0].tid == 5 and cl.acks[0].result == 0
    stores_converged(c)
    assert "via-peon" in c.mons[0].osdmap.pool_names.values()
    # reads answered by the peon locally
    cl.send(6, {"prefix": "osd stat"})
    cl.pump_with(c)
    assert cl.acks[1].result == 0
    c.shutdown()


def test_leader_failover_and_continuity():
    c = make_cluster()
    r = c.rados()
    r.pool_create("before", pg_num=8)
    c.pump()
    # kill the leader; peons re-elect after the lease goes stale
    c.kill_mon(0)
    now = 50_000.0
    c.tick(now)
    c.tick(now + 20.0)          # > LEASE_TIMEOUT
    c.pump()
    leaders = [rk for rk, mn in c.mons.items() if mn.is_leader]
    assert leaders == [1]
    assert c.mons[2].leader_rank == 1
    # cluster still mutable through the new leader (client hunts mons)
    io_client = c.rados()
    io_client.pool_create("after", pg_num=8)
    c.pump()
    for mn in c.mons.values():
        assert "after" in mn.osdmap.pool_names.values()
        assert "before" in mn.osdmap.pool_names.values()
    # IO still flows
    io = io_client.open_ioctx("after")
    io.write_full("obj", b"post-failover")
    assert io.read("obj") == b"post-failover"
    c.shutdown()


def test_peon_death_keeps_majority_working():
    c = make_cluster()
    r = c.rados()
    c.kill_mon(2)
    r.pool_create("still-works", pg_num=8)
    c.pump()
    assert "still-works" in c.mons[0].osdmap.pool_names.values()
    assert "still-works" in c.mons[1].osdmap.pool_names.values()
    c.shutdown()


def test_revived_mon_catches_up():
    c = make_cluster()
    r = c.rados()
    c.kill_mon(2)
    r.pool_create("while-away", pg_num=8)
    c.pump()
    mn2 = c.revive_mon(2)
    c.pump()
    # leases carry last_committed; the revived peon syncs
    now = 90_000.0
    c.tick(now)
    c.tick(now + 6.0)
    c.pump()
    assert mn2.paxos.last_committed == \
        c.mons[0].paxos.last_committed
    assert "while-away" in mn2.osdmap.pool_names.values()
    stores_converged(c)
    c.shutdown()


def test_full_store_sync_beyond_trim_window():
    """A mon lagging past the paxos trim window gets a full store
    snapshot instead of an unfillable gap."""
    c = make_cluster()
    r = c.rados()
    c.kill_mon(2)
    r.pool_create("a", pg_num=8)
    r.pool_create("b", pg_num=8)
    r.pool_create("c", pg_num=8)
    c.pump()
    lead = c.mons[0]
    lead.paxos.keep_versions = 1
    lead.paxos._maybe_trim()
    # the revived mon's last_committed is 1 (bootstrap): a gap it
    # cannot fill incrementally
    assert lead.paxos.first_committed > 2
    mn2 = c.revive_mon(2)
    c.pump()
    now = 120_000.0
    c.tick(now)
    c.pump()
    assert mn2.paxos.last_committed == lead.paxos.last_committed
    assert "a" in mn2.osdmap.pool_names.values()
    assert "b" in mn2.osdmap.pool_names.values()
    c.shutdown()


def test_revived_stale_leader_does_not_fork_history():
    """mon.0 (lowest rank) revives behind the others and wins the
    election; the collect phase (lease acks + peer pushes) must bring
    it up to date BEFORE it proposes, so no version is forked."""
    c = make_cluster()
    r = c.rados()
    c.kill_mon(0)
    now = 200_000.0
    c.tick(now)
    c.tick(now + 20.0)
    c.pump()
    assert [rk for rk, mn in c.mons.items() if mn.is_leader] == [1]
    r2 = c.rados()
    r2.pool_create("while-0-dead", pg_num=8)
    c.pump()
    v_ahead = c.mons[1].paxos.last_committed
    # revive the stale rank-0: it wins the next election
    mn0 = c.revive_mon(0)
    c.pump()
    c.tick(now + 40.0)
    c.pump()
    assert mn0.is_leader
    # collect phase must have caught it up, not forked
    assert mn0.paxos.last_committed >= v_ahead
    assert "while-0-dead" in mn0.osdmap.pool_names.values()
    # new commits extend everyone identically
    r3 = c.rados()
    r3.pool_create("after-revive", pg_num=8)
    c.pump()
    stores_converged(c)
    for mn in c.mons.values():
        assert "while-0-dead" in mn.osdmap.pool_names.values()
        assert "after-revive" in mn.osdmap.pool_names.values()
    c.shutdown()


def test_sync_handle_command_raises_in_quorum():
    c = make_cluster()
    with pytest.raises(RuntimeError):
        c.mons[0].handle_command({"prefix": "osd pool create",
                                  "pool": "x", "pg_num": 8})
    # reads still fine synchronously anywhere
    r, outs, outb = c.mons[2].handle_command({"prefix": "osd stat"})
    assert r == 0
    c.shutdown()
