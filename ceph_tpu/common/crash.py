"""Daemon crash capture: unhandled exception -> crash metadata ->
cluster crash table (VERDICT r5 partial "mgr dashboard-class modules";
ref: src/pybind/mgr/crash/module.py ingest + the ceph-crash spool
agent src/ceph-crash.in).

Every daemon installs a CrashReporter: when an unhandled exception
escapes a tick, a dispatch thread, or the process itself, the
reporter serializes it into a crash-metadata dict (crash_id =
timestamp+entity, backtrace, entity name/type, version, process args)
and posts it to the cluster's crash table (`crash post` through the
mon — the mgr crash module's ingest analogue).  When the cluster is
unreachable the report is SPOOLED to a crash dir
(`<crash_dir>/<crash_id>/meta.json`, the reference's
/var/lib/ceph/crash layout) and drained on the daemon's next boot;
the crash table dedups by crash_id, so spool+post double delivery
still lands exactly one report.
"""
from __future__ import annotations

import itertools
import json
import os
import re
import sys
import time
import traceback

from .log import dout

#: crash metadata format version (bump when adding fields)
CRASH_META_VERSION = 1

#: filename-safe crash_id (ISO stamps carry ':' and '.')
_SAFE = re.compile(r"[^A-Za-z0-9_.-]")

#: `File "/long/host/path/mod.py"` -> `File "mod.py"` (telemetry's
#: no-raw-paths anonymization contract)
_TB_PATH = re.compile(r'File "([^"]*[/\\])([^"/\\]+)"')

#: directory prefix of any absolute path — the traceback's final line
#: is the exception MESSAGE, and OSError et al. embed the offending
#: path there ("[Errno 2] ...: '/var/lib/.../store'")
_ANY_PATH = re.compile(r"(?:[A-Za-z]:)?(?:[\\/][\w.+~-]+)+[\\/]")


def utc_iso(stamp: float) -> str:
    """ISO-8601 UTC with microseconds (the reference crash module's
    timestamp format)."""
    frac = int(round((stamp - int(stamp)) * 1e6))
    if frac >= 1_000_000:           # float rounding at a second edge
        frac -= 1_000_000
        stamp += 1.0
    return time.strftime("%Y-%m-%dT%H:%M:%S",
                         time.gmtime(stamp)) + f".{frac:06d}Z"


def crash_meta(entity: str, exc: BaseException,
               stamp: float | None = None,
               argv: list[str] | None = None) -> dict:
    """Serialize an exception into the crash-metadata dict the crash
    table stores (ref: the JSON meta ceph daemons dump via
    generate_crash_dump and mgr/crash validates on `crash post`)."""
    stamp = time.time() if stamp is None else stamp
    iso = utc_iso(stamp)
    tb = traceback.format_exception(type(exc), exc, exc.__traceback__)
    try:
        from importlib.metadata import version as _v
        version = _v("ceph-tpu")
    except Exception:           # uninstalled tree: version best-effort
        version = "0.3.0-dev"
    return {
        "crash_id": f"{iso}_{entity}",
        "timestamp": iso,
        "stamp": stamp,
        "entity_name": entity,
        "entity_type": entity.split(".", 1)[0],
        "backtrace": [ln.rstrip("\n") for ln in tb],
        "exc_type": type(exc).__name__,
        "exc_msg": str(exc),
        "version": version,
        "process_args": list(sys.argv if argv is None else argv),
        "meta_version": CRASH_META_VERSION,
        "archived": None,
    }


def sanitize_backtrace(lines: list[str]) -> list[str]:
    """Strip directory components from backtrace frames AND from any
    path embedded in the exception-message line — telemetry ships
    stacks but never raw filesystem paths (the anonymization
    contract; ref: the reference telemetry module's crash sanitizer)."""
    return [_ANY_PATH.sub("", _TB_PATH.sub(r'File "\2"', ln))
            for ln in lines]


class CrashReporter:
    """Per-daemon capture + spool + post agent.

    `post` is a best-effort callable(meta) that ships the report to
    the cluster (a mon command send); it may raise or silently fail —
    the spool (when a crash_dir is configured) is the durable copy
    until `mark_delivered` removes it on the cluster's ack.
    """

    #: identical-signature captures inside this window are dropped —
    #: a persistently failing tick in a survive-loop daemon must not
    #: storm the crash table with one report per second
    REPEAT_WINDOW = 60.0

    def __init__(self, entity: str, crash_dir: str | None = None,
                 post=None, clock=time.time):
        self.entity = entity
        self.crash_dir = crash_dir or None
        self.post = post
        self.clock = clock
        #: crash_ids captured by this process (tests/ops introspection)
        self.captured: list[str] = []
        self._last_sig: tuple | None = None
        self._last_stamp = 0.0
        # wire posts awaiting the cluster's ack: tid -> crash_id
        self._tids: dict[int, str] = {}
        self._tid_gen = itertools.count(1)

    # ------------------------------------------------------ ack tracking
    # (shared by every daemon that posts over the command channel: the
    #  sender allocates a tid per post, feeds the MMonCommandAck back
    #  through on_ack, and the matching spool copy is retired)
    def alloc_tid(self, crash_id: str) -> int:
        """Tid for one wire post; pair with on_ack(tid, result)."""
        tid = next(self._tid_gen)
        self._tids[tid] = crash_id
        return tid

    def forget_tid(self, tid: int) -> None:
        """The post was never sent: no ack is coming."""
        self._tids.pop(tid, None)

    def on_ack(self, tid: int, result: int) -> bool:
        """Route a command ack: True iff the tid was one of our posts.
        A zero result retires the spool copy; any other result leaves
        it for the next drain."""
        cid = self._tids.pop(tid, None)
        if cid is not None and result == 0:
            self.mark_delivered(cid)
        return cid is not None

    # ---------------------------------------------------------- capture
    def capture(self, exc: BaseException) -> dict:
        """Serialize, spool, and post one crash.  Never raises — this
        runs on already-failing paths."""
        sig = (type(exc).__name__, str(exc))
        now = self.clock()
        if sig == self._last_sig and \
                0 <= now - self._last_stamp < self.REPEAT_WINDOW:
            return {}
        self._last_sig, self._last_stamp = sig, now
        try:
            meta = crash_meta(self.entity, exc, stamp=self.clock())
        except Exception as ex:
            dout("crash", 0).write("%s: crash meta build failed: %s",
                                   self.entity, ex)
            return {}
        self.captured.append(meta["crash_id"])
        dout("crash", 0).write("%s: crashed — %s: %s (crash_id %s)",
                               self.entity, meta["exc_type"],
                               meta["exc_msg"], meta["crash_id"])
        self.spool(meta)                 # durable first
        if self.post is not None:
            try:
                self.post(meta)
            except Exception as ex:      # cluster unreachable: spooled
                dout("crash", 1).write(
                    "%s: crash post failed (%s); report spooled",
                    self.entity, ex)
        return meta

    # ------------------------------------------------------------ spool
    def _spool_path(self, crash_id: str) -> str:
        return os.path.join(self.crash_dir, _SAFE.sub("_", crash_id),
                            "meta.json")

    def spool(self, meta: dict) -> None:
        if self.crash_dir is None or not meta:
            return
        try:
            path = self._spool_path(meta["crash_id"])
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f, indent=1, sort_keys=True)
            os.replace(tmp, path)        # crash-safe: whole file or none
        except OSError as ex:
            dout("crash", 0).write("%s: crash spool failed: %s",
                                   self.entity, ex)

    def spooled(self) -> list[dict]:
        """Reports awaiting delivery (drained on boot, oldest first)."""
        if self.crash_dir is None or not os.path.isdir(self.crash_dir):
            return []
        out = []
        for d in sorted(os.listdir(self.crash_dir)):
            path = os.path.join(self.crash_dir, d, "meta.json")
            try:
                with open(path) as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue                 # half-written spool: skip
        return out

    def drain(self) -> int:
        """Re-post every spooled report (next-boot delivery; the crash
        table dedups so this is safe to repeat).  Spool files stay
        until the cluster acks via mark_delivered."""
        n = 0
        if self.post is None:
            return n
        for meta in self.spooled():
            try:
                self.post(meta)
                n += 1
            except Exception as ex:
                dout("crash", 1).write("%s: spool drain post failed: %s",
                                       self.entity, ex)
        return n

    def mark_delivered(self, crash_id: str) -> None:
        """The cluster acked this report: drop the spool copy."""
        if self.crash_dir is None:
            return
        path = self._spool_path(crash_id)
        try:
            os.remove(path)
            os.rmdir(os.path.dirname(path))
        except OSError:
            pass                         # never spooled / already gone

    # ------------------------------------------------------ process hook
    def install_excepthook(self) -> None:
        """Capture exceptions that escape the whole process/threads
        (daemon_main's last line of defense), then chain to the
        previous hooks."""
        import threading
        prev_sys = sys.excepthook
        prev_thread = threading.excepthook

        def _hook(exc_type, exc, tb):
            if exc is not None and not isinstance(exc, KeyboardInterrupt):
                self.capture(exc)
            prev_sys(exc_type, exc, tb)

        def _thread_hook(args):
            if args.exc_value is not None and \
                    not isinstance(args.exc_value, KeyboardInterrupt):
                self.capture(args.exc_value)
            prev_thread(args)

        sys.excepthook = _hook
        threading.excepthook = _thread_hook
