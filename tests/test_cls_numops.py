"""cls numops: atomic omap counter arithmetic
(ref: src/cls/numops/cls_numops.cc; see ceph_tpu/cls/numops.py)."""
import pytest

from ceph_tpu.client import RadosError
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=3, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("meta", pg_num=8)
    yield c, r
    c.shutdown()


@pytest.fixture()
def io(cluster):
    _, r = cluster
    return r.open_ioctx("meta")


def test_add_creates_counter_and_accumulates(io):
    oid = "n-acc"
    out = io.exec(oid, "numops", "add", {"key": "hits", "value": 3})
    assert out == {"key": "hits", "value": 3}
    out = io.exec(oid, "numops", "add", {"key": "hits", "value": 4})
    assert out["value"] == 7
    # stored representation is a clean decimal string other omap
    # readers can parse
    assert dict(io.get_omap_vals(oid)[0])["hits"] == b"7"


def test_sub_mul_div_roundtrip(io):
    oid = "n-ops"
    io.exec(oid, "numops", "add", {"key": "k", "value": 10})
    assert io.exec(oid, "numops", "sub",
                   {"key": "k", "value": 4})["value"] == 6
    assert io.exec(oid, "numops", "mul",
                   {"key": "k", "value": 3})["value"] == 18
    assert io.exec(oid, "numops", "div",
                   {"key": "k", "value": 4})["value"] == 4.5
    assert dict(io.get_omap_vals(oid)[0])["k"] == b"4.5"
    # back to integral: the trailing .0 is dropped in storage
    assert io.exec(oid, "numops", "mul",
                   {"key": "k", "value": 2})["value"] == 9
    assert dict(io.get_omap_vals(oid)[0])["k"] == b"9"


def test_keys_are_independent(io):
    oid = "n-multi"
    io.exec(oid, "numops", "add", {"key": "a", "value": 1})
    io.exec(oid, "numops", "add", {"key": "b", "value": 2})
    io.exec(oid, "numops", "add", {"key": "a", "value": 1})
    omap = dict(io.get_omap_vals(oid)[0])
    assert omap["a"] == b"2" and omap["b"] == b"2"


def test_missing_key_counts_as_zero(io):
    oid = "n-zero"
    assert io.exec(oid, "numops", "sub",
                   {"key": "fresh", "value": 5})["value"] == -5
    assert io.exec(oid, "numops", "mul",
                   {"key": "fresh2", "value": 5})["value"] == 0


def test_non_numeric_input_is_einval(io):
    oid = "n-badin"
    for bad in ("three", None, [1], True):
        with pytest.raises(RadosError, match="EINVAL"):
            io.exec(oid, "numops", "add", {"key": "k", "value": bad})
    with pytest.raises(RadosError, match="EINVAL"):
        io.exec(oid, "numops", "add", {"value": 1})     # no key
    with pytest.raises(RadosError, match="EINVAL"):
        io.exec(oid, "numops", "add", {"key": "k"})     # no value
    # failed calls must not have created the object
    with pytest.raises(RadosError, match="ENOENT"):
        io.stat(oid)


def test_non_numeric_stored_value_is_einval_not_clobbered(io):
    """A key someone else uses for non-counter data must not be
    silently overwritten — the reference rejects unparseable stored
    values instead of treating them as zero."""
    oid = "n-badstore"
    io.set_omap(oid, {"blob": b"not a number"})
    with pytest.raises(RadosError, match="EINVAL"):
        io.exec(oid, "numops", "add", {"key": "blob", "value": 1})
    assert dict(io.get_omap_vals(oid)[0])["blob"] == b"not a number"


def test_div_by_zero_is_einval_and_atomic(io):
    oid = "n-div0"
    io.exec(oid, "numops", "add", {"key": "k", "value": 9})
    with pytest.raises(RadosError, match="EINVAL"):
        io.exec(oid, "numops", "div", {"key": "k", "value": 0})
    # the failed method's queued mutations never commit
    assert dict(io.get_omap_vals(oid)[0])["k"] == b"9"


def test_concurrent_adds_all_land(io):
    """The point of the class: racing increments are serialized
    inside the OSD, so none is lost to read-modify-write races."""
    import concurrent.futures
    oid = "n-race"

    def bump(_):
        return io.exec(oid, "numops", "add", {"key": "c", "value": 1})

    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(bump, range(32)))
    assert dict(io.get_omap_vals(oid)[0])["c"] == b"32"
