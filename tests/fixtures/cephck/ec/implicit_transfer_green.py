"""green: stage once, explicitly, at the batch boundary."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def gf_mul(a, b):
    return jnp.matmul(a, b, preferred_element_type=jnp.int32)


_TABLE = jnp.asarray(np.zeros((8, 8), dtype=np.int8))


def encode(data):
    table = jnp.asarray(np.zeros((8, 8), dtype=np.int8))
    return gf_mul(table, data)


def encode_shared(data):
    return gf_mul(_TABLE, data)


def encode_rebound(data, device_tables):
    # `table` starts host-side but is REBOUND by the loop target to a
    # device array before reaching the op — provenance must not stick
    table = np.zeros((8, 8), dtype=np.int8)
    out = gf_mul(jnp.asarray(table), data)
    for table in device_tables:
        out = gf_mul(table, data)
    return out
