"""mgr insights module: a time-windowed cluster snapshot
(ref: src/pybind/mgr/insights/module.py — health-check history,
recent crashes, osdmap epoch deltas, and cluster-log severity counts
over a sliding window, the support-bundle feed).

Per tick the module samples health / osdmap epoch / cluster-log
counts into bounded history rings; `insights` reports the window's
deltas from those rings only, so the mon-proxied command handler
(mgr dispatch thread) never issues a synchronous mon command.
"""
from __future__ import annotations

import time
from collections import deque

from ..common.crash import utc_iso
from ..common.options import global_config

_EINVAL = 22

#: ring bound — independent of the time window so a fast ticker can't
#: grow memory without bound
MAX_SAMPLES = 512


class InsightsModule:
    """(ref: insights/module.py Module)."""

    def __init__(self, mgr, window: float | None = None):
        self.mgr = mgr
        #: report window in seconds (mgr_insights_window)
        self.window = (window if window is not None
                       else global_config()["mgr_insights_window"])
        #: (stamp, status, sorted check names)
        self._health: deque = deque(maxlen=MAX_SAMPLES)
        #: (stamp, osdmap epoch)
        self._epochs: deque = deque(maxlen=MAX_SAMPLES)
        #: (stamp, {level: count}) — cumulative cluster-log counters
        self._log_counts: deque = deque(maxlen=MAX_SAMPLES)

    # ------------------------------------------------------------ tick
    def tick(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        rc, _, health = self.mgr.mon_command({"prefix": "health"})
        if rc == 0 and isinstance(health, dict):
            self._health.append(
                (now, health.get("status", "?"),
                 sorted(health.get("checks", {}))))
        self._epochs.append((now, self.mgr.osdmap.epoch))
        rc, _, counts = self.mgr.mon_command({"prefix": "log counts"})
        if rc == 0 and isinstance(counts, dict):
            self._log_counts.append((now, dict(counts)))

    def prune_health(self, before: float) -> int:
        """Drop health history older than `before` (ref: `insights
        prune-health <hours>`)."""
        kept = [s for s in self._health if s[0] >= before]
        dropped = len(self._health) - len(kept)
        self._health = deque(kept, maxlen=MAX_SAMPLES)
        return dropped

    # ---------------------------------------------------------- report
    def report(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        lo = now - self.window
        health = [s for s in self._health if lo <= s[0] <= now]
        epochs = [s for s in self._epochs if lo <= s[0] <= now]
        logs = [s for s in self._log_counts if lo <= s[0] <= now]
        transitions = sum(1 for a, b in zip(health, health[1:])
                          if a[1] != b[1] or a[2] != b[2])
        crashes = []
        if self.mgr.crash is not None:
            crashes = [{
                "entity_name": c.get("entity_name", "?"),
                "timestamp": c.get("timestamp", ""),
                "exc_type": c.get("exc_type", ""),
            } for c in self.mgr.crash.last_crashes
                if not c.get("archived")
                and lo <= c.get("stamp", 0.0) <= now]
        log_delta: dict[str, int] = {}
        if logs:
            first, last = logs[0][1], logs[-1][1]
            for level in ("warn", "error"):
                log_delta[level] = max(
                    0, last.get(level, 0) - first.get(level, 0))
        return {
            "window_seconds": self.window,
            "report_timestamp": utc_iso(now),
            "health": {
                "current": health[-1][1] if health else "unknown",
                "current_checks": list(health[-1][2]) if health else [],
                "samples": len(health),
                "transitions": transitions,
                "history": [{"timestamp": utc_iso(s[0]),
                             "status": s[1], "checks": list(s[2])}
                            for s in health],
            },
            "osdmap": {
                "first_epoch": epochs[0][1] if epochs else 0,
                "last_epoch": epochs[-1][1] if epochs else 0,
                "epoch_delta": (epochs[-1][1] - epochs[0][1])
                if epochs else 0,
            },
            "cluster_log": log_delta,
            "crashes": crashes,
        }

    # -------------------------------------------------------- commands
    def handle_command(self, cmd: dict) -> tuple[int, str, object]:
        pfx = str(cmd.get("prefix", ""))
        if pfx == "insights":
            return 0, "", self.report()
        if pfx == "insights prune-health":
            hours = float(cmd.get("hours", 0))
            if hours < 0:
                return -_EINVAL, "hours must be >= 0", None
            n = self.prune_health(time.time() - hours * 3600.0)
            return 0, f"pruned {n} health history entries", None
        return -_EINVAL, f"unknown insights command {pfx!r}", None
