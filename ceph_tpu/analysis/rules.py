"""cephck rules — each one encodes a bug class this repo has shipped
(or a hazard the reference gates on).  A rule is deliberately small:
``id``, a ``doc`` a finder can read, and ``check(ctx)`` yielding
findings over one parsed file.  Every rule has at least one red and
one green fixture under tests/fixtures/cephck/ and a test asserting
both (tests/test_cephck.py) — a rule that can't demonstrate its bug
is deleted, not kept.
"""
from __future__ import annotations

import ast
import json
import re
from typing import Iterator

from .engine import FileContext, Finding, dotted
from .project import ModuleInfo

# --------------------------------------------------------------- No. 1


class RawLockRule:
    id = "raw-lock"
    doc = """
Raw threading.Lock/RLock/Condition construction outside
common/lockdep.py.

Locks must come from ceph_tpu.common.lockdep.make_lock(name): under
the `lockdep` option (ON for every tier-1 run via tests/conftest.py)
make_lock returns an order-checked DebugLock, so the lock-order cycle
detector (ref: src/common/lockdep.cc) sees every acquisition.  A raw
threading primitive is invisible to it — a deadlock through that lock
is only found by the unlucky interleaving that actually hangs.

Fix: `from ceph_tpu.common.lockdep import make_lock` and construct
`make_lock("<subsystem>.<role>")` (name it uniquely enough that a
reported cycle identifies the site).  Note make_lock is reentrant
(RLock semantics) — do not rely on self-blocking.
"""
    FACTORIES = {"Lock", "RLock", "Condition"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.rel.endswith("common/lockdep.py"):
            return
        from_imports = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "threading":
                for a in node.names:
                    if a.name in self.FACTORIES:
                        from_imports.add(a.asname or a.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            hit = name.startswith("threading.") and \
                name.split(".", 1)[1] in self.FACTORIES or \
                name in from_imports
            if hit:
                yield ctx.finding(
                    self.id, node,
                    f"raw {name}() — use "
                    f"common.lockdep.make_lock(name) so the lock-order "
                    f"sanitizer sees this lock")


# --------------------------------------------------------------- No. 2

def _versions_literal(tree: ast.Module) -> dict[str, tuple[int, int]]:
    """Module-level ``_VERSIONS = {"Name": (v, compat), ...}``."""
    out: dict[str, tuple[int, int]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                any(isinstance(t, ast.Name) and t.id == "_VERSIONS"
                    for t in node.targets) and \
                isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(v, ast.Tuple) and len(v.elts) == 2 and \
                        all(isinstance(e, ast.Constant) for e in v.elts):
                    out[str(k.value)] = (v.elts[0].value, v.elts[1].value)
    return out


def _message_classes(tree: ast.Module) -> list[ast.ClassDef]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and any(
                dotted(b).split(".")[-1] == "Message"
                for b in node.bases):
            out.append(node)
    return out


def _is_dataclass(node: ast.ClassDef) -> bool:
    for d in node.decorator_list:
        if dotted(d).split(".")[-1] == "dataclass":
            return True
    return False


def _norm_type(s: str | None) -> str:
    return re.sub(r"\s+", "", s or "")


class WireSchemaRule:
    id = "wire-drift"
    doc = """
Wire struct drifted from the committed schema lockfile
(tests/fixtures/wire_schema.json).

The encode contract is ENCODE_START's (ref: src/include/encoding.h):
field lists are APPEND-ONLY.  Reordering, removing, renaming, or
retyping a field changes the positional encoding silently — an old
decoder reads the wrong field into the wrong slot, which is exactly
the PR 1 mon fork (an encode diverged from its registered version).
Appending a field is legal ONLY with a `version` bump in _VERSIONS
(or the wire_struct/register_struct call).  `compat > version` is a
contradiction — no decoder could ever accept the struct — and is
rejected here before it can reject every peer at runtime.

Fix: restore the committed field prefix; append new fields at the
end and bump the version.  For an INTENTIONAL evolution, bump the
version and regenerate the lockfile:
`python scripts/gen_wire_schema.py` (then commit the diff).
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        classes = [c for c in _message_classes(ctx.tree)
                   if _is_dataclass(c)]
        if not classes:
            return
        schema_path = ctx.options["wire_schema"]
        try:
            lock = json.loads(schema_path.read_text())
        except FileNotFoundError:
            yield ctx.finding(
                self.id, ctx.tree,
                f"wire schema lockfile missing ({schema_path}) — "
                f"run: python scripts/gen_wire_schema.py", symbol="")
            return
        except json.JSONDecodeError as ex:
            yield ctx.finding(
                self.id, ctx.tree,
                f"wire schema lockfile unreadable: {ex}", symbol="")
            return
        versions = _versions_literal(ctx.tree)
        structs = lock.get("structs", {})
        for cls in classes:
            v, compat = versions.get(cls.name, (1, 1))
            if compat > v:
                yield ctx.finding(
                    self.id, cls,
                    f"{cls.name}: compat {compat} > version {v} — no "
                    f"decoder could ever accept this struct",
                    symbol=cls.name)
                continue
            fields = [(n.target.id, _norm_type(ast.unparse(n.annotation)))
                      for n in cls.body
                      if isinstance(n, ast.AnnAssign) and
                      isinstance(n.target, ast.Name)]
            pinned = structs.get(cls.name)
            if pinned is not None:
                # a redeclared base field (e.g. MClientCaps.seq) keeps
                # the BASE's wire position, not its class-body one —
                # compare declared-only fields on both sides
                inherited = {f["name"] for f in pinned["fields"] or ()
                             if f.get("inherited")}
                fields = [f for f in fields if f[0] not in inherited]
            if pinned is None:
                yield ctx.finding(
                    self.id, cls,
                    f"{cls.name}: not in the wire schema lockfile — "
                    f"regenerate it (python scripts/gen_wire_schema.py) "
                    f"to pin the new struct", symbol=cls.name)
                continue
            # inherited (Message-base) fields encode first but are not
            # declared in the class body the AST sees — the runtime
            # check (tests/test_wire_schema.py) pins those
            want = [(f["name"], _norm_type(f.get("type")))
                    for f in pinned["fields"] or ()
                    if not f.get("inherited")]
            bad = None
            for i, (wn, wt) in enumerate(want):
                if i >= len(fields):
                    bad = (f"field {wn!r} removed (committed at "
                           f"position {i}) — wire field lists are "
                           f"append-only")
                    break
                gn, gt = fields[i]
                if gn != wn:
                    bad = (f"field {i} is {gn!r} but the lockfile pins "
                           f"{wn!r} — reorder/rename breaks positional "
                           f"decode")
                    break
                if wt and gt and gt != wt:
                    bad = (f"field {gn!r} retyped {wt!r} -> {gt!r} — "
                           f"old decoders read the old type")
                    break
            if bad:
                yield ctx.finding(self.id, cls, f"{cls.name}: {bad}",
                                  symbol=cls.name)
                continue
            if len(fields) > len(want) and v <= int(pinned["version"]):
                extra = [n for n, _t in fields[len(want):]]
                yield ctx.finding(
                    self.id, cls,
                    f"{cls.name}: field(s) {extra} appended without a "
                    f"version bump (still v{v}) — old decoders can't "
                    f"tell the tail is there; bump _VERSIONS and "
                    f"regenerate the lockfile", symbol=cls.name)


# --------------------------------------------------------------- No. 3


class UnregisteredMessageRule:
    id = "unregistered-message"
    doc = """
Message subclass that _register_all() will never wire-register.

msg/messages.py registers every module-level *dataclass* Message
subclass automatically.  A Message subclass that is not a dataclass
compiles, type-checks, and then raises WireError("not
wire-registered") the first time it crosses a TCP messenger — or
worse, never does in tests (the in-process transport skips
serialization) and only fails in a real deployment.

Fix: decorate the class with @dataclass (fields become the wire
field list), or register it explicitly via register_struct with
to_fields/from_fields.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in _message_classes(ctx.tree):
            if not _is_dataclass(cls):
                yield ctx.finding(
                    self.id, cls,
                    f"{cls.name}(Message) is not a dataclass — "
                    f"_register_all() skips it, so it is NOT "
                    f"wire-registered and dies with WireError on the "
                    f"first real (TCP) send", symbol=cls.name)


# --------------------------------------------------------------- No. 4

#: Transaction mutators that touch object omaps — the pgmeta bug class
OMAP_MUTATORS = {"omap_setkeys", "omap_rmkeys", "omap_clear"}

#: receiver names that clearly ARE a transaction
_TXNISH = re.compile(r"^(txn?\d*|tx\d*|transaction|.*_txn)$")


class TxnAtomicityRule:
    id = "txn-atomicity"
    doc = """
omap mutation in osd/ outside a Transaction context.

PR 2's persist_log bug: an omap mutation issued outside the owning
store Transaction wiped non-log pgmeta keys (the snap index and
purged_snaps cursor) on every peering merge — state that must move
atomically with the data didn't.  In osd/ code, omap_setkeys /
omap_rmkeys / omap_clear must be invoked on a Transaction (named
txn/t/tx/*_txn, or constructed from Transaction() in the same
function) that the caller applies as ONE unit with the rest of the
update.

Fix: thread the owning Transaction into the helper and append the
omap ops to IT; never apply a private side-transaction for state
that must be atomic with the caller's.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "osd" not in ctx.rel.split("/"):
            return
        # names bound from Transaction() per enclosing function
        txn_bound: dict[ast.AST, set[str]] = {}
        parents = ctx.parents()

        def scope_of(node: ast.AST) -> ast.AST:
            cur = parents.get(node)
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Module)):
                cur = parents.get(cur)
            return cur or ctx.tree

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    dotted(node.value.func).split(".")[-1] == "Transaction":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        txn_bound.setdefault(scope_of(node),
                                             set()).add(t.id)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in OMAP_MUTATORS):
                continue
            recv = node.func.value
            # chained builder calls: txn.touch(...).omap_setkeys(...)
            while isinstance(recv, ast.Call) and \
                    isinstance(recv.func, ast.Attribute):
                recv = recv.func.value
            name = dotted(recv).split(".")[-1]
            if _TXNISH.match(name):
                continue
            if isinstance(recv, ast.Call) and \
                    dotted(recv.func).split(".")[-1] == "Transaction":
                continue
            if name in txn_bound.get(scope_of(node), ()):
                continue
            yield ctx.finding(
                self.id, node,
                f".{node.func.attr}() on {dotted(recv) or '<expr>'!r} — "
                f"omap state in osd/ must mutate through the owning "
                f"Transaction (persist_log bug class: non-atomic pgmeta "
                f"updates)")


# --------------------------------------------------------------- No. 5

_LOGGISH = re.compile(
    r"(dout|derr|print|log|warn|error|exception|fail|append|traceback|"
    r"put_nowait|set_exception)", re.I)


class SilentThreadRule:
    id = "silent-thread"
    doc = """
threading.Thread target that can swallow its own death.

A daemon thread whose body catches Exception (or everything) and
neither logs nor re-raises dies silently: the heartbeat keeps
beating, the queue keeps growing, and the first observable symptom
is a wedged cluster minutes later.  (Python threads don't propagate
exceptions to their parent — the except handler is the ONLY place
the failure can surface.)

Fix: in the handler, log through dout/derr (common.log) or collect
the error somewhere a supervisor checks — or narrow the except to
the exceptions the loop genuinely expects.
"""
    BROAD = {None, "Exception", "BaseException"}

    def _resolve(self, ctx: FileContext,
                 target: ast.AST) -> ast.FunctionDef | None:
        if isinstance(target, ast.Name):
            want, in_class = target.id, False
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            want, in_class = target.attr, True
        else:
            return None
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == want:
                parent = ctx.parents().get(node)
                if in_class == isinstance(parent, ast.ClassDef):
                    return node
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        seen: set[ast.AST] = set()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    dotted(node.func).split(".")[-1] == "Thread"):
                continue
            target = next((kw.value for kw in node.keywords
                           if kw.arg == "target"), None)
            if target is None:
                continue
            fn = self._resolve(ctx, target)
            if fn is None or fn in seen:
                continue
            seen.add(fn)
            for h in ast.walk(fn):
                if not isinstance(h, ast.ExceptHandler):
                    continue
                tname = None if h.type is None \
                    else dotted(h.type).split(".")[-1]
                if tname not in self.BROAD:
                    continue
                ok = any(isinstance(n, ast.Raise)
                         for n in ast.walk(h)) or any(
                    isinstance(n, ast.Call) and
                    _LOGGISH.search(dotted(n.func))
                    for n in ast.walk(h))
                if not ok:
                    yield ctx.finding(
                        self.id, h,
                        f"thread target {fn.name}() swallows "
                        f"{'everything' if tname is None else tname} "
                        f"without logging or re-raising — the thread "
                        f"dies silently", symbol=fn.name)


# --------------------------------------------------------------- No. 6

#: calls that are legitimate inside a timed region without a sync
_TIMING_EXEMPT = re.compile(
    r"(perf_counter|monotonic|time|sleep|ns)$")


class JaxTimingRule:
    id = "jax-timing"
    doc = """
time.perf_counter() pair whose timed region can return before the
device work does.

JAX dispatch is asynchronous: a call that produces a jax.Array
returns as soon as the work is ENQUEUED.  Stopping the clock without
jax.block_until_ready() therefore measures dispatch, not compute —
the exact failure mode called out for the EC hot paths in
"Accelerating XOR-based Erasure Coding..." (arxiv 2108.02692), where
mis-timed async dispatch invalidates the perf claim.  float()/
np.asarray() conversions do force a sync of the converted value, but
only that value — and they smuggle a device->host copy into the
timed region; block_until_ready is the only honest stop-the-clock.

The rule fires in jax-importing files when a perf_counter region
contains a call but no block_until_ready before the closing
perf_counter read.

Fix: `jax.block_until_ready(result)` (or result.block_until_ready())
as the LAST statement inside the timed region.  Host-only timed
regions (pure numpy/ctypes) in jax-importing files are false
positives: suppress them in .cephck-baseline.json with a reason.
"""

    def _is_perf_start(self, stmt: ast.stmt) -> str | None:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Call) and \
                dotted(stmt.value.func).endswith("perf_counter"):
            return stmt.targets[0].id
        return None

    def _has_perf_call(self, stmt: ast.stmt) -> bool:
        return any(isinstance(n, ast.Call) and
                   dotted(n.func).endswith("perf_counter")
                   for n in ast.walk(stmt))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.imports_jax():
            return
        for block in ast.walk(ctx.tree):
            for body in (getattr(block, "body", None),
                         getattr(block, "orelse", None),
                         getattr(block, "finalbody", None)):
                if not isinstance(body, list):
                    continue
                yield from self._check_block(ctx, body)

    def _check_block(self, ctx: FileContext,
                     body: list[ast.stmt]) -> Iterator[Finding]:
        i = 0
        while i < len(body):
            var = self._is_perf_start(body[i])
            if var is None:
                i += 1
                continue
            start_line = body[i].lineno
            j = i + 1
            while j < len(body) and not self._has_perf_call(body[j]):
                j += 1
            region = body[i + 1:j]
            i = j
            if not region:
                continue
            synced = any(isinstance(n, ast.Call) and
                         dotted(n.func).endswith("block_until_ready")
                         for stmt in region for n in ast.walk(stmt))
            if synced:
                continue
            offender = next(
                (n for stmt in region for n in ast.walk(stmt)
                 if isinstance(n, ast.Call) and
                 not _TIMING_EXEMPT.search(dotted(n.func) or "x")),
                None)
            if offender is not None:
                yield ctx.finding(
                    self.id, offender,
                    f"timed region (clock started at line "
                    f"{start_line}) calls "
                    f"{dotted(offender.func) or '<dynamic>'}() with no "
                    f"block_until_ready before the clock stops — this "
                    f"times the DISPATCH, not the compute")


# --------------------------------------------------------------- No. 7

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp, ast.GeneratorExp)


def _jit_statics(call: ast.Call) -> tuple[set[int], set[str]] | None:
    """(static positions, static names) if `call` is jax.jit/jit with
    static args declared, else None."""
    if dotted(call.func).split(".")[-1] != "jit":
        return None
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, int):
                    nums.add(v.value)
        elif kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    names.add(v.value)
    if not nums and not names:
        return None
    return nums, names


class JitStaticRule:
    id = "jit-static"
    doc = """
Unhashable Python container passed as a jax.jit static argument.

static_argnums/static_argnames values are jit CACHE KEYS: jax hashes
them to find the compiled executable.  A list/dict/set there raises
"Non-hashable static arguments" at the first call — or, when the
call site is only reached on a rare path (error handling, failover),
at 3am.  Tuples are hashable but a FRESH tuple of varying contents
recompiles on every distinct value, silently turning the jit cache
into a compile-per-call.

Fix: pass tuples (stable contents) for static args, or move the
container into the traced arguments.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # jitted symbols declared in this module, with their statics
        registry: dict[str, tuple[set[int], set[str]]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                st = _jit_statics(node.value)
                if st:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            registry[t.id] = st
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for d in node.decorator_list:
                    if isinstance(d, ast.Call):
                        inner = next(
                            (a for a in d.args
                             if isinstance(a, (ast.Name, ast.Attribute))
                             and dotted(a).split(".")[-1] == "jit"),
                            None)
                        if dotted(d.func).split(".")[-1] == "partial" \
                                and inner is not None:
                            st = _jit_statics(d)
                            if st:
                                registry[node.name] = st

        def flag_call(call: ast.Call, nums: set[int],
                      names: set[str]) -> Iterator[Finding]:
            for pos, a in enumerate(call.args):
                if pos in nums and isinstance(a, _UNHASHABLE):
                    yield ctx.finding(
                        self.id, a,
                        f"unhashable {type(a).__name__.lower()} passed "
                        f"as static arg {pos} of a jitted function — "
                        f"static args are jit cache keys and must hash")
            for kw in call.keywords:
                if kw.arg in names and isinstance(kw.value, _UNHASHABLE):
                    yield ctx.finding(
                        self.id, kw.value,
                        f"unhashable {type(kw.value).__name__.lower()} "
                        f"passed as static arg {kw.arg!r} of a jitted "
                        f"function — static args are jit cache keys "
                        f"and must hash")

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and \
                    node.func.id in registry:
                yield from flag_call(node, *registry[node.func.id])
            elif isinstance(node.func, ast.Call):
                st = _jit_statics(node.func)
                if st:
                    yield from flag_call(node, *st)


# --------------------------------------------------------------- No. 8


class BareExceptRule:
    id = "bare-except"
    doc = """
Bare `except:` clause.

Bare except catches SystemExit, KeyboardInterrupt, and MemoryError —
a daemon loop with one becomes unkillable and hides OOM.  The
reference's C++ has no equivalent hazard; in this Python tree it is
banned outright.

Fix: catch Exception (plus logging — see silent-thread) or the
specific exceptions the call can raise; re-raise what you can't
handle.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield ctx.finding(
                    self.id, node,
                    "bare `except:` also catches SystemExit/"
                    "KeyboardInterrupt — name the exceptions (at "
                    "minimum `except Exception`)")


# ===================================================================
# The device-contract family (cephck v2): cross-module rules that
# police the host<->device boundary on the TPU hot path.  They lean on
# ctx.project (analysis/project.py) — canonical import expansion
# ("np.asarray" == "numpy.asarray"), the project-wide jit registry,
# and the call graph — instead of per-file guessing.

#: files on the per-stripe/per-batch hot path: everything under ec/
#: and crush/, plus the two OSD EC files the backend dispatches from
_HOT_BASENAMES = {"ec_backend.py", "ecutil.py"}


def _hot_path(rel: str) -> bool:
    parts = rel.split("/")
    return "ec" in parts or "crush" in parts or \
        parts[-1] in _HOT_BASENAMES


def _loop_body_nodes(loop: ast.AST) -> Iterator[ast.AST]:
    """Every node executed PER ITERATION of a loop: walks body/orelse
    (plus the While test), skipping nested def/class bodies (those run
    when called, not per iteration) — but not nested loops' bodies,
    which do."""
    stack: list[ast.stmt] = list(loop.body) + list(
        getattr(loop, "orelse", []) or [])
    if isinstance(loop, ast.While):
        yield from ast.walk(loop.test)
    while stack:
        stmt = stack.pop()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield stmt
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                stack.append(child)
            else:
                yield from ast.walk(child)


#: canonical names whose CALL forces a device->host sync (or a
#: device round-trip) of the converted value
_SYNC_NP = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}
_SYNC_DEFINITE = {"jax.device_get"}


def _sync_call(node: ast.Call, mod: ModuleInfo | None) -> str | None:
    """Spelled-out sync name when `node` is a host-sync call."""
    name = dotted(node.func)
    if not name:
        return None
    canon = mod.expand(name) if mod else name
    if canon in _SYNC_DEFINITE or canon in _SYNC_NP:
        return canon
    last = name.split(".")[-1]
    if last == "item" and "." in name and not node.args:
        return f"{name}()"
    if last == "block_until_ready":
        return name
    return None


def _definite_sync(node: ast.Call, mod: ModuleInfo | None) -> str | None:
    """Like _sync_call but only the unambiguous device syncs — used
    for the cross-module (callee) check, where numpy conversions are
    too often host-native to flag at a distance."""
    s = _sync_call(node, mod)
    if s is None or (mod.expand(dotted(node.func)) if mod
                     else dotted(node.func)) in _SYNC_NP:
        return None
    return s


class HostSyncHotPathRule:
    id = "host-sync-hot-path"
    doc = """
Host sync (.item()/float()/np.asarray()/block_until_ready/
jax.device_get) reachable inside a per-stripe or per-batch loop on
the EC/CRUSH hot path (ec/, crush/, osd/ec_backend.py,
osd/ecutil.py).

JAX dispatch is asynchronous; the batched EC path exists so the
host<->device boundary is crossed ONCE per batch.  A sync inside the
per-stripe loop turns the pipeline back into
dispatch-wait-dispatch-wait: every iteration pays the full device
round-trip latency, and on a multi-chip mesh every chip idles behind
it.  This is the exact hazard class PR 9 removed from the decode path
(staging-free decode) — the rule keeps it from growing back.  The
check is cross-module: a loop that calls a helper (resolved through
the project call graph) which syncs inside is flagged at the
callsite.

Fix: hoist the conversion out of the loop — batch the stripes into
one array, dispatch once, convert once.  Where the sync is
load-bearing (a host-native fallback path that never sees device
arrays, a bench timer floor), waive the site inline with
`# cephck: ignore[host-sync-hot-path]` and a reason comment, or add
a baseline entry with the reason.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _hot_path(ctx.rel):
            return
        base = ctx.rel.split("/")[-1]
        if base not in _HOT_BASENAMES and not ctx.imports_jax():
            return      # host-native module (pure-numpy plugin, the
            # scalar CRUSH oracle): nothing to sync
        mod = ctx.module()
        project = ctx.project
        flagged: set[ast.AST] = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for node in _loop_body_nodes(loop):
                if not isinstance(node, ast.Call) or node in flagged:
                    continue
                sync = _sync_call(node, mod)
                if sync is not None:
                    flagged.add(node)
                    yield ctx.finding(
                        self.id, node,
                        f"{sync} inside a loop (started line "
                        f"{loop.lineno}) — per-iteration host sync "
                        f"serializes the device pipeline; batch the "
                        f"loop and sync once")
                    continue
                if isinstance(node.func, ast.Name) and \
                        node.func.id == "float" and len(node.args) == 1 \
                        and not isinstance(node.args[0], ast.Constant) \
                        and ctx.imports_jax():
                    flagged.add(node)
                    yield ctx.finding(
                        self.id, node,
                        f"float(...) inside a loop (started line "
                        f"{loop.lineno}) — float() of a jax value "
                        f"forces a device->host sync per iteration")
                    continue
                # cross-module: the loop calls a project function that
                # definitely syncs inside (call-graph reachable)
                if project is None or mod is None:
                    continue
                target = project.resolve(mod, dotted(node.func),
                                         ctx.qualname(node))
                if target is None:
                    continue
                hit = self._callee_sync(project, *target)
                if hit is not None:
                    flagged.add(node)
                    tmod, tqual, sync = hit
                    yield ctx.finding(
                        self.id, node,
                        f"call to {tqual}() ({tmod}) inside a loop "
                        f"(started line {loop.lineno}) — the callee "
                        f"host-syncs via {sync}, so every iteration "
                        f"pays a device round-trip")

    def _callee_sync(self, project, owner: ModuleInfo, qual: str,
                     depth: int = 2):
        """(modname, qual, syncname) when `qual` (or anything it
        reaches within `depth` hops) contains a definite sync."""
        targets = [(owner.name, qual)]
        targets += list(project.reachable(owner, qual, max_depth=depth))
        for modname, q in targets:
            m = project.modules.get(modname)
            fn = m.functions.get(q) if m else None
            if fn is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    s = _definite_sync(node, m)
                    if s is not None:
                        return modname, q, s
        return None


_PER_CALL_VARYING = re.compile(
    r"(^|\.)(id|hash|perf_counter|perf_counter_ns|monotonic|time|"
    r"time_ns|random|randint|randbytes|uuid4|tobytes|tolist)$")


class JitRetraceChurnRule:
    id = "jit-retrace-churn"
    doc = """
jax.jit callsite whose compiled-function cache cannot hit: a fresh
jit wrapper per call, a jit wrapper built inside a loop, or a static
argument derived from a per-call value (time, id(), random,
.tobytes()/.tolist() of data).

jit caches compiled executables PER WRAPPER OBJECT, keyed by argument
shapes/dtypes and static values.  `jax.jit(f)(x)` inside a function
builds a new wrapper — and a new, empty cache — on every call, so
every call recompiles (~100ms-10s each) no matter how stable the
shapes are.  A static arg fed from time/random/id/object-contents
never repeats, so each call misses the cache the same way.  Either
form silently turns the hot path into compile-per-call — the
cache-miss churn class the Ragged-Paged-Attention literature calls
out as the first-order TPU serving hazard.

Fix: build the jit wrapper ONCE (module level, or memoized like
crush/batch.py's _RULE_JIT keyed by static config) and call the
cached wrapper; keep per-call values out of static args (pass them
as traced arguments, or hoist them into the cache key on purpose).
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        mod = ctx.module()
        if mod is None:
            return
        parents = ctx.parents()

        def enclosing(node, kinds):
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, kinds):
                    return cur
                cur = parents.get(cur)
            return None

        flagged: set[ast.AST] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            # (a)/(b): a Call that BUILDS a jit wrapper
            if mod._jit_of_call(node) is not None:
                loop = enclosing(node, (ast.For, ast.AsyncFor,
                                        ast.While))
                caller = enclosing(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                # a decorator position is fine (wrapper built once at
                # def time) — skip jit calls that decorate a def
                parent = parents.get(node)
                is_decorator = isinstance(
                    parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node in parent.decorator_list
                immediately_called = isinstance(parent, ast.Call) and \
                    parent.func is node
                if is_decorator:
                    continue
                if loop is not None and node not in flagged:
                    flagged.add(node)
                    yield ctx.finding(
                        self.id, node,
                        f"jit wrapper built inside a loop (line "
                        f"{loop.lineno}) — each iteration gets a "
                        f"fresh, empty compile cache; build the "
                        f"wrapper once outside the loop")
                    continue
                if immediately_called and caller is not None and \
                        node not in flagged:
                    flagged.add(node)
                    yield ctx.finding(
                        self.id, node,
                        f"jax.jit(...)(...) built and called in one "
                        f"expression inside {caller.name}() — a new "
                        f"wrapper (and empty cache) per call, i.e. "
                        f"compile-per-call; hoist the jit wrapper out")
                    continue
            # (c): per-call-varying value in a static arg slot
            st = None
            if ctx.project is not None:
                st = ctx.project.jit_statics_of(mod, dotted(node.func),
                                                ctx.qualname(node))
            if not st:
                continue
            nums, names = st
            slots = [(f"static arg {i}", a) for i, a in
                     enumerate(node.args) if i in nums]
            slots += [(f"static arg {kw.arg!r}", kw.value)
                      for kw in node.keywords if kw.arg in names]
            for label, expr in slots:
                bad = next(
                    (n for n in ast.walk(expr)
                     if isinstance(n, ast.Call) and
                     _PER_CALL_VARYING.search(dotted(n.func) or "")),
                    None)
                if bad is not None:
                    yield ctx.finding(
                        self.id, bad,
                        f"{label} of jitted {dotted(node.func)}() is "
                        f"derived from {dotted(bad.func)}() — a "
                        f"per-call value as a jit cache key misses "
                        f"the cache (recompile) on every call")


#: container mutators a traced function could leak a tracer through
_LEAK_MUTATORS = {"append", "extend", "add", "insert", "update",
                  "setdefault", "put", "put_nowait"}


class TracerLeakRule:
    id = "tracer-leak"
    doc = """
Traced (jit-wrapped) function stores a value somewhere that outlives
the traced call: on self, on a global, or into a module-level
container.

Inside jax.jit, every intermediate is a TRACER — a symbolic stand-in
valid only while tracing runs.  Storing one on self/globals/a shared
container smuggles it past the trace boundary; the next use raises
jax's "leaked tracer" UnexpectedTracerError at best, or (for cached
shapes) silently captures a stale constant from trace time.  Either
way the bug surfaces far from the store, usually on the second call
with a new shape.

Fix: return the value from the traced function and store it OUTSIDE
the jit boundary; for debug taps use jax.debug.callback (or
io_callback), which marshals concrete values out safely.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        mod = ctx.module()
        if mod is None:
            return
        traced: list = []
        for qual, st in mod.jitted.items():
            fn = mod.functions.get(qual)
            if fn is not None:
                traced.append((qual, fn))
        # `g = jax.jit(f)` also traces module-local f
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    mod._jit_of_call(node) is not None:
                for a in node.args:
                    target = dotted(a)
                    if isinstance(a, (ast.Name, ast.Attribute)) and \
                            target in mod.functions:
                        traced.append((target, mod.functions[target]))
        seen: set[ast.AST] = set()
        for qual, fn in traced:
            if fn in seen:
                continue
            seen.add(fn)
            globals_declared: set[str] = {
                name for node in ast.walk(fn)
                if isinstance(node, ast.Global) for name in node.names}
            for node in ast.walk(fn):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    base = t.value if isinstance(t, ast.Subscript) \
                        else t
                    if isinstance(base, ast.Attribute) and \
                            isinstance(base.value, ast.Name) and \
                            base.value.id == "self":
                        yield ctx.finding(
                            self.id, node,
                            f"traced function {qual}() stores to "
                            f"self.{base.attr} — a tracer written to "
                            f"an attribute outlives the trace "
                            f"(leaked-tracer class)", symbol=qual)
                    elif isinstance(base, ast.Name) and \
                            base.id in globals_declared:
                        yield ctx.finding(
                            self.id, node,
                            f"traced function {qual}() assigns "
                            f"global {base.id!r} — a tracer stored in "
                            f"module state outlives the trace",
                            symbol=qual)
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _LEAK_MUTATORS:
                    recv = node.func.value
                    leaky = (isinstance(recv, ast.Attribute) and
                             isinstance(recv.value, ast.Name) and
                             recv.value.id == "self") or \
                        (isinstance(recv, ast.Name) and
                         recv.id in mod.module_names)
                    if leaky:
                        yield ctx.finding(
                            self.id, node,
                            f"traced function {qual}() calls "
                            f".{node.func.attr}() on "
                            f"{dotted(recv)!r} — mutating state that "
                            f"outlives the trace leaks the tracer",
                            symbol=qual)


#: numpy constructors that pin a value to HOST memory
_NP_CTORS = {
    "numpy." + n for n in (
        "zeros", "ones", "empty", "full", "arange", "frombuffer",
        "array", "asarray", "ascontiguousarray", "stack",
        "concatenate", "eye", "vstack", "hstack", "copy", "tile")}

#: the EXPLICIT transfer spellings — these are the fix, never flagged
_EXPLICIT_TRANSFER = {"jax.numpy.asarray", "jax.numpy.array",
                      "jax.device_put"}


def _nonassign_bindings(node: ast.AST) -> Iterator[str]:
    """Names bound by non-Assign constructs: for/with-as targets,
    aug/ann-assign, walrus, comprehension loop vars."""
    targets: list[ast.AST] = []
    if isinstance(node, (ast.For, ast.AsyncFor, ast.comprehension)):
        targets.append(node.target)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                           ast.NamedExpr)):
        targets.append(node.target)
    elif isinstance(node, (ast.With, ast.AsyncWith)):
        targets += [i.optional_vars for i in node.items
                    if i.optional_vars is not None]
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                yield sub.id


class ImplicitTransferRule:
    id = "implicit-transfer"
    doc = """
Host (numpy) array fed straight into device compute on a kernel-path
function — an implicit host->device transfer per call.

Passing a numpy array directly to a jnp op or a jit-wrapped function
works, but XLA silently copies it host->device on EVERY call; under
jax.transfer_guard('disallow') (armed by the jaxguard sanitizer on
the EC/placement entry points) the same call is an error.  The rule
uses the project call graph to recognize jit-wrapped callees defined
in OTHER modules (e.g. a kernels/bitmatmul.py wrapper called from a
plugin), not just local jnp spellings.

Fix: stage once, explicitly — `jnp.asarray(x)` / `jax.device_put(x)`
at the batch boundary — and keep the device array across calls; or,
for genuinely host-side math, stay in numpy end to end.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _hot_path(ctx.rel) or not ctx.imports_jax():
            return
        mod = ctx.module()
        if mod is None:
            return
        for qual, fn in mod.functions.items():
            np_locals: dict[str, str] = {}
            rebound: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if not isinstance(t, ast.Name):
                            # tuple/attr/subscript unpack: every name
                            # inside loses its numpy provenance
                            for sub in ast.walk(t):
                                if isinstance(sub, ast.Name):
                                    rebound.add(sub.id)
                            continue
                        if isinstance(node.value, ast.Call):
                            canon = mod.expand(dotted(node.value.func))
                            if canon in _NP_CTORS:
                                np_locals[t.id] = canon
                                continue
                        rebound.add(t.id)
                else:
                    # any OTHER binding construct (for/with-as targets,
                    # aug/ann-assign, walrus, comprehensions) rebinds
                    # the name to an unknown value
                    for name in _nonassign_bindings(node):
                        rebound.add(name)
            for name in rebound:        # conservatively drop names
                np_locals.pop(name, None)   # ever bound to non-numpy
            if not np_locals:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                target = dotted(node.func)
                canon = mod.expand(target)
                jitted = ctx.project is not None and \
                    ctx.project.jit_statics_of(mod, target,
                                               qual) is not None
                device_op = canon.startswith(("jax.numpy.",
                                              "jax.lax.")) and \
                    canon not in _EXPLICIT_TRANSFER
                if not (jitted or device_op):
                    continue
                args = list(node.args) + [kw.value
                                          for kw in node.keywords]
                for a in args:
                    if isinstance(a, ast.Name) and a.id in np_locals:
                        kind = "jit-wrapped function" if jitted \
                            else "device op"
                        yield ctx.finding(
                            self.id, node,
                            f"host array {a.id!r} "
                            f"({np_locals[a.id]}) passed into "
                            f"{kind} {target}() — implicit "
                            f"host->device transfer per call; stage "
                            f"it once with jnp.asarray/device_put",
                            symbol=ctx.qualname(node))
                        break


# ===================================================================
# The concurrency family (racecheck's static half): guarded-by
# inference and blocking-in-dispatch, both leaning on ctx.project.


def _self_attr(node: ast.AST) -> str | None:
    """'x' when `node` is ``self.x`` (one level only)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


_LOCK_FACTORIES = {"make_lock", "Lock", "RLock", "Condition"}


def _lock_fields(cls: ast.ClassDef) -> set[str]:
    """Attribute names assigned from make_lock()/threading locks
    anywhere in the class body: the candidate guards."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                dotted(node.value.func).split(".")[-1] in _LOCK_FACTORIES:
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.add(attr)
    return out


def _acquires(fn: ast.AST, lock: str) -> bool:
    """Does `fn` take ``self.<lock>`` anywhere — ``with self.L:`` or
    an explicit ``self.L.acquire()``?  Method-level granularity on
    purpose: cephck flags the METHOD that touches guarded state
    without ever taking the guard (the persist_log shape), not
    statement-level windows."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if _self_attr(expr) == lock:
                    return True
                if isinstance(expr, ast.Call) and \
                        _self_attr(expr.func) == lock:
                    return True
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("acquire", "acquire_lock"):
            if _self_attr(node.func.value) == lock:
                return True
    return False


#: methods whose accesses never count: constructors and teardown run
#: before publish / after quiesce (the init-before-publish phase the
#: runtime sanitizer's EXCLUSIVE state models)
_GB_EXEMPT_METHODS = {"__init__", "__post_init__", "__del__", "init",
                      "start", "shutdown", "close", "stop", "__exit__"}

#: minimum accessing methods / guarded fraction before the inference
#: trusts itself: below this the "majority" is noise, not a contract
_GB_MIN_GUARDED_METHODS = 2
_GB_MIN_ACCESSES = 5
_GB_MIN_FRACTION = 0.75


class GuardedByRule:
    id = "guarded-by"
    doc = """
Attribute access outside the lock that guards it everywhere else in
the class.

For each class owning a make_lock() field, the rule infers which lock
guards each ``self._x``: if >= 75% of the accesses (outside
__init__/shutdown) happen in methods that take ``self._lock``, that
lock IS the attribute's guard — and the minority accesses in methods
that never take it are exactly the persist_log bug shape (PR 2: one
unlocked writer clobbering pgmeta under a peering merge), caught at
parse time instead of by the unlucky interleaving.  A method reached
ONLY from acquiring methods (a private helper called under the lock)
counts as guarded through the project call graph.

Fix: take the inferred lock around the flagged access (or hoist the
access into a locked caller).  If the access is genuinely safe — an
init-phase path, a hand-off the runtime sanitizer documents with
transfer_ownership(), a read of a monotonic flag — waive it inline
with `# cephck: ignore[guarded-by]` and a reason comment, or add a
baseline entry with the reason.  The runtime twin of this rule is
common/racecheck.py: annotate both the same way.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        mod = ctx.module()
        project = ctx.project
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_fields(cls)
            if not locks:
                continue
            methods = [n for n in cls.body if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef))]
            if not methods:
                continue
            # attr -> [(method, access node, is_store)]
            accesses: dict[str, list] = {}
            acquired: dict[str, set[str]] = {
                L: {m.name for m in methods if _acquires(m, L)}
                for L in locks}
            for m in methods:
                if m.name in _GB_EXEMPT_METHODS:
                    continue
                for node in ast.walk(m):
                    attr = _self_attr(node)
                    if attr is None or attr in locks or \
                            not attr.startswith("_") or \
                            attr.startswith("__"):
                        continue
                    accesses.setdefault(attr, []).append(
                        (m, node, isinstance(node.ctx, ast.Store)))
            for attr, accs in accesses.items():
                yield from self._check_attr(ctx, mod, project, cls,
                                            attr, accs, acquired)

    def _covered(self, project, mod, cls: ast.ClassDef,
                 guarded: set[str], method: str) -> bool:
        """True when `method` is reached ONLY from guarded methods of
        the same class (a locked caller's private helper).  A public
        or caller-less method is its own entry point: not covered."""
        if project is None or mod is None or \
                not method.startswith("_"):
            return False
        project.finalize()
        me = (mod.name, f"{cls.name}.{method}")
        callers = project.callers.get(me)
        if not callers:
            return False
        seen = {method}
        work = list(callers)
        while work:
            src_mod, src_qual = work.pop()
            if src_mod != mod.name or \
                    not src_qual.startswith(f"{cls.name}."):
                return False            # reached from outside the class
            name = src_qual.split(".", 1)[1]
            if name in guarded or name in seen:
                continue
            if not name.startswith("_"):
                return False
            seen.add(name)
            nxt = project.callers.get((src_mod, src_qual))
            if not nxt:
                return False
            work.extend(nxt)
        return True

    def _check_attr(self, ctx, mod, project, cls, attr, accs,
                    acquired) -> Iterator[Finding]:
        if len(accs) < _GB_MIN_ACCESSES:
            return
        best = None
        for lock, fns in acquired.items():
            under = sum(1 for m, _n, _w in accs if m.name in fns)
            if best is None or under > best[1]:
                best = (lock, under, fns)
        lock, under, fns = best
        if under < len(accs) * _GB_MIN_FRACTION or under == len(accs):
            return
        if len({m.name for m, _n, _w in accs
                if m.name in fns}) < _GB_MIN_GUARDED_METHODS:
            return
        flagged: set[int] = set()
        for m, node, is_store in accs:
            if m.name in fns or node.lineno in flagged:
                continue
            if self._covered(project, mod, cls, fns, m.name):
                continue
            flagged.add(node.lineno)
            kind = "write to" if is_store else "read of"
            yield ctx.finding(
                self.id, node,
                f"{kind} self.{attr} in {cls.name}.{m.name}() without "
                f"self.{lock} — {under}/{len(accs)} accesses take "
                f"that lock, so it is the inferred guard "
                f"(persist_log bug class: one unlocked accessor "
                f"corrupts state every locked site protects)",
                symbol=f"{cls.name}.{m.name}")


# -------------------------------------------------- blocking-in-dispatch

#: function names that ARE a message-dispatch context: the messenger
#: dispatch/reader threads call these per message, so anything that
#: blocks inside stalls every peer behind the queue.  The top-of-loop
#: waits (_dispatch_loop's queue.get, _read_loop's recv) are the
#: wait-for-work by design and are NOT entries.
_DISPATCH_ENTRIES = {"ms_dispatch", "_deliver", "_deliver_verified"}

#: canonical call names that block the calling thread outright
_BLOCKING_CANON = {"time.sleep", "socket.create_connection",
                   "select.select"}

#: attribute-call patterns that block: last segment -> receiver test
_THREADISH = re.compile(r"(thread|worker|proc)", re.I)
_QUEUEISH = re.compile(r"(queue|_q)$|^q$", re.I)
_SOCKISH = re.compile(r"(sock|conn|listener)$|^s$", re.I)


def _blocking_call(node: ast.Call, mod: ModuleInfo | None) -> str | None:
    """Human-readable description when `node` blocks its thread."""
    name = dotted(node.func)
    if not name:
        return None
    canon = mod.expand(name) if mod else name
    if canon in _BLOCKING_CANON:
        return canon
    last = name.split(".")[-1]
    recv = name.rsplit(".", 2)[-2] if "." in name else ""
    if last == "sleep" and (canon.startswith("time.") or recv == "time"):
        return f"{name}()"
    if last == "join" and _THREADISH.search(recv):
        return f"{name}() (thread join)"
    if last in ("wait", "wait_for"):
        # Event/Condition wait — any receiver: there is no non-blocking
        # spelling of .wait()
        return f"{name}() (condition/event wait)"
    if last == "get" and _QUEUEISH.search(recv) and not node.args \
            and not any(kw.arg == "block" for kw in node.keywords):
        # a positional arg IS `block` (q.get(False)), and an explicit
        # block= keyword means the caller chose — only the bare
        # blocking default is flagged
        return f"{name}() (blocking queue get)"
    if last in ("recv", "recv_into", "accept") and \
            _SOCKISH.search(recv or "x"):
        return f"{name}() (socket wait)"
    if last == "block_until_ready":
        return f"{name}() (device sync)"
    if last in ("recv_frame", "_recv_exact"):
        return f"{name}() (socket wait)"
    return None


class BlockingInDispatchRule:
    id = "blocking-in-dispatch"
    doc = """
Blocking call reachable from a messenger dispatch entry point
(ms_dispatch / the deliver path in ceph_tpu/msg/).

The dispatch thread is shared: every message from every peer funnels
through it.  A handler that sleeps, joins a thread, waits on a
condition, or blocks in a socket/queue/device wait stalls the WHOLE
daemon's inbound traffic for the duration — and when the thing it
waits for needs another message on the same thread to make progress,
it deadlocks outright (the ICIFabric concurrent mesh-launch hang:
dispatch blocked in block_until_ready while the reply it needed sat
behind it in the queue).  The check is cross-module: the project call
graph is walked from each dispatch entry (depth-bounded), so a
handler that calls a helper that sleeps two modules away is flagged
at the handler.

Fix: move the blocking work off the dispatch thread (queue it to a
worker, complete it from the tick), or make the wait event-driven.
For a BOUNDED wait that is the design (e.g. a capped handshake wait
with a timeout argument), waive the site inline with
`# cephck: ignore[blocking-in-dispatch]` and a reason comment, or
add a baseline entry with the reason.
"""
    MAX_DEPTH = 4

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        mod = ctx.module()
        if mod is None:
            return
        project = ctx.project
        for qual, fn in mod.functions.items():
            short = qual.split(".")[-1]
            if short not in _DISPATCH_ENTRIES:
                continue
            # local blocking calls: flagged at the call itself
            reported: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    b = _blocking_call(node, mod)
                    if b is not None and b not in reported:
                        reported.add(b)
                        yield ctx.finding(
                            self.id, node,
                            f"{b} inside dispatch entry {qual}() — "
                            f"the dispatch thread serves every peer; "
                            f"a blocked handler stalls the daemon's "
                            f"whole inbound queue", symbol=qual)
            if project is None:
                continue
            # cross-module: anything reachable from the entry that
            # contains a blocking call, flagged at the entry
            for tmod_name, tqual in project.reachable(
                    mod, qual, max_depth=self.MAX_DEPTH):
                tmod = project.modules.get(tmod_name)
                tfn = tmod.functions.get(tqual) if tmod else None
                if tfn is None:
                    continue
                for node in ast.walk(tfn):
                    if not isinstance(node, ast.Call):
                        continue
                    b = _blocking_call(node, tmod)
                    key = f"{tmod_name}.{tqual}:{b}"
                    if b is not None and key not in reported:
                        reported.add(key)
                        yield ctx.finding(
                            self.id, fn,
                            f"dispatch entry {qual}() reaches "
                            f"{tqual}() ({tmod_name}) which blocks "
                            f"in {b} — the dispatch thread serves "
                            f"every peer; a blocked handler stalls "
                            f"the daemon's whole inbound queue",
                            symbol=qual)


# ===================================================================
# The error-contract family (errcheck's static half): how failures
# propagate — or vanish — between `except`, the return value, and the
# reply a client is waiting on.  Runtime twin: common/errcheck.py
# (the fired-handler coverage sanitizer; ERRCOV_rNN.json says which of
# these handlers fault injection has actually reached).

#: the broad spellings; bare `except:` is bare-except's, not ours
_BROAD_EXC = {"Exception", "BaseException"}


def _error_scope(rel: str) -> bool:
    """Daemon/library code only: tests and scripts sleep-poll and
    clean up best-effort BY DESIGN, so the error-contract rules skip
    them.  The fixture corpus stays in scope so the rules can
    demonstrate themselves."""
    parts = rel.split("/")
    if "fixtures" in parts:
        return True
    return parts[0] not in ("tests", "scripts", "bench.py")


def _broad_handler(node: ast.AST) -> str | None:
    """'Exception'/'BaseException' when `node` is a handler catching
    (at least) everything an op can raise."""
    if isinstance(node, ast.ExceptHandler) and node.type is not None:
        t = dotted(node.type).split(".")[-1]
        if t in _BROAD_EXC:
            return t
    return None


class SwallowedErrorRule:
    id = "swallowed-error"
    doc = """
Broad except handler whose body is only pass/continue/break — the
failure vanishes without a trace.

`except Exception: pass` is how DataLog.list turned an injected EIO
into "caught up" and how an undecodable sync marker wedged a sync
tick forever: the caller branches on a result that no longer says
anything, and the first observable symptom is minutes away from the
fault.  This tree has crash capture (common/crash.py), a structured
logger (common/log.py dout/derr), and a quarantine pattern for
poison input — a handler that uses NONE of them is hiding a failure,
not handling it.

Fix: narrow the except to the exceptions this call genuinely expects,
or keep the broad catch but leave a trace (dout/derr), record the
error somewhere a caller/supervisor checks, or re-raise what you
can't own.  For a true don't-care (best-effort cleanup on teardown),
waive inline with `# cephck: ignore[swallowed-error]` and a reason
comment, or add a baseline entry with the reason.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _error_scope(ctx.rel):
            return
        for node in ast.walk(ctx.tree):
            t = _broad_handler(node)
            if t is None:
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue, ast.Break))
                   for s in node.body):
                yield ctx.finding(
                    self.id, node,
                    f"except {t} swallows every failure without "
                    f"logging, recording, or re-raising — narrow the "
                    f"except or leave a trace (DataLog "
                    f"EIO-became-'caught up' class)")


def _success_shaped(expr: ast.expr | None) -> str | None:
    """Spelled-out value when `expr` is a success-shaped constant —
    the shapes a healthy read path also returns, so the caller cannot
    tell failure from empty.  Booleans are excluded: False IS an
    error encoding for predicate paths."""
    if expr is None:
        return "None"
    if isinstance(expr, ast.Constant):
        v = expr.value
        if v is None:
            return "None"
        if isinstance(v, bool):
            return None
        if v == 0 or v == "" or v == b"":
            return repr(v)
        return None
    if isinstance(expr, ast.List) and not expr.elts:
        return "[]"
    if isinstance(expr, ast.Tuple) and not expr.elts:
        return "()"
    if isinstance(expr, ast.Dict) and not expr.keys:
        return "{}"
    if isinstance(expr, ast.Call) and not expr.args and \
            not expr.keywords and \
            dotted(expr.func) in ("list", "dict", "set", "tuple"):
        return f"{dotted(expr.func)}()"
    return None


def _enoent_raise(handler: ast.ExceptHandler) -> ast.Raise | None:
    """A raise inside `handler` that maps the caught exception to an
    ENOENT-shaped error (errno 2 / "ENOENT" literal)."""
    for node in ast.walk(handler):
        if not (isinstance(node, ast.Raise) and
                isinstance(node.exc, ast.Call)):
            continue
        args = node.exc.args
        if not args:
            continue
        first = args[0]
        enoentish = (isinstance(first, ast.Constant) and
                     first.value == 2 and
                     not isinstance(first.value, bool)) or any(
            isinstance(a, ast.Constant) and a.value == "ENOENT"
            for a in args)
        if enoentish:
            return node
    return None


class ErrnoConflationRule:
    id = "errno-conflation"
    doc = """
Broad except handler that maps EVERY failure of a read/apply path to
one success-shaped or ENOENT-shaped result.

Three shapes of the same bug: (a) `except Exception: return []` — an
injected EIO now reads as "no data" (the DataLog.list class, fixed in
PR 5 by re-raising non-ENOENT); (b) `except Exception: x = 0` — a
transient stat failure silently resets a cursor/size to its initial
value; (c) `except Exception: raise XError(2, ...)` — decode errors,
EIO, and genuine not-found all become "does not exist", so the caller
deletes/recreates state that still exists.  In every shape the errno
dataflow from the fault to the caller is severed at the handler.

Fix: catch the one exception that legitimately means empty/not-found
(KeyError, the ENOENT RadosError) and let everything else propagate —
or map exceptions to DISTINCT errnos so the caller can branch.  A
handler that LOGS before collapsing (dout/derr) is observable and is
exempt from shapes (a)/(b) — the bug class is silence.  Where the
collapse is the documented contract, waive inline with
`# cephck: ignore[errno-conflation]` and a reason comment, or add a
baseline entry with the reason.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _error_scope(ctx.rel):
            return
        parents = ctx.parents()

        def enclosing_fn(node: ast.AST):
            cur = parents.get(node)
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cur = parents.get(cur)
            return cur

        for node in ast.walk(ctx.tree):
            t = _broad_handler(node)
            if t is None:
                continue
            # (c) everything -> ENOENT
            rz = _enoent_raise(node)
            if rz is not None:
                yield ctx.finding(
                    self.id, rz,
                    f"except {t} re-raised as an ENOENT-shaped error — "
                    f"EIO/decode failures become 'does not exist'; "
                    f"narrow the except or map distinct errnos")
                continue
            # (a)/(b) fire only on SILENT collapse — a handler that
            # logs first is observable
            if len(node.body) != 1:
                continue
            only = node.body[0]
            # (a) everything -> success-shaped return
            if isinstance(only, ast.Return):
                shape = _success_shaped(only.value)
                fn = enclosing_fn(node)
                if shape is None or fn is None:
                    continue
                real_return = any(
                    isinstance(r, ast.Return) and r is not only and
                    r.value is not None and
                    _success_shaped(r.value) is None
                    for r in ast.walk(fn))
                if real_return:
                    yield ctx.finding(
                        self.id, only,
                        f"except {t}: return {shape} — every failure "
                        f"of {fn.name}() now reads as a successful "
                        f"empty result (DataLog EIO class); re-raise "
                        f"what isn't the expected miss")
            # (b) everything -> success-shaped assignment
            elif isinstance(only, ast.Assign) and \
                    len(only.targets) == 1 and \
                    isinstance(only.targets[0], ast.Name):
                shape = _success_shaped(only.value)
                if shape is not None:
                    yield ctx.finding(
                        self.id, only,
                        f"except {t}: {only.targets[0].id} = {shape} — "
                        f"any failure (including EIO) silently resets "
                        f"the value to its success-shaped default; "
                        f"narrow the except or propagate")


# ------------------------------------------------- reply-on-all-paths

#: command handlers that must RETURN a (r, outs, outb) result (or
#: raise) on every path — the caller unpacks the tuple
_RETURN_CONV = {"handle_command", "_handle_module_command"}

#: HTTP-op methods (RGW/Swift `_*_op` convention): every path must
#: send a reply, delegate, or raise
_OP_METHOD = re.compile(r"^_[a-z0-9_]+_op$")

#: call names that ARE the reply
_REPLYISH = {"_respond", "respond", "send_reply", "send_error",
             "reply_cb"}

_RESOLVED, _OPEN = "resolved", "open"


def _reply_call(node: ast.Call) -> bool:
    last = (dotted(node.func) or "").split(".")[-1]
    return last in _REPLYISH or bool(_OP_METHOD.match(last))


class _PathScan:
    """Conservative all-paths walk over a handler body.  Tracks, per
    path, whether a reply has been sent; collects findings at returns
    that end a path unanswered.  `block` returns (_RESOLVED if no
    path can fall out the bottom, else _OPEN, replied-after)."""

    def __init__(self, conv: str):
        self.conv = conv                    # "return" | "respond"
        self.findings: list[tuple[ast.AST, str]] = []

    def block(self, stmts, replied: bool):
        for st in stmts:
            status, replied = self.stmt(st, replied)
            if status is _RESOLVED:
                return _RESOLVED, replied
        return _OPEN, replied

    def _branches(self, replied, *blocks, fallthrough: bool):
        """If/Match combinator: every branch resolved (and no silent
        fallthrough) resolves the statement; else the open paths'
        replied states AND together."""
        outs = []
        for b in blocks:
            s, r = self.block(b, replied)
            if s is _OPEN:
                outs.append(r)
        if fallthrough:
            outs.append(replied)
        if not outs:
            return _RESOLVED, replied
        return _OPEN, all(outs)

    def stmt(self, st: ast.stmt, replied: bool):
        if isinstance(st, ast.Return):
            if self.conv == "return":
                if st.value is None:
                    self.findings.append((
                        st, "bare `return` — the caller unpacks a "
                            "(r, outs, outb) result and gets None "
                            "(30s-client-hang class)"))
            else:
                ok = replied or isinstance(st.value, ast.Call)
                if not ok:
                    self.findings.append((
                        st, "returns without sending a reply on this "
                            "path — the client waits out its full "
                            "timeout"))
            return _RESOLVED, replied
        if isinstance(st, ast.Raise):
            return _RESOLVED, replied
        if isinstance(st, ast.If):
            return self._branches(
                replied, st.body, *((st.orelse,) if st.orelse else ()),
                fallthrough=not st.orelse)
        if isinstance(st, ast.Match):
            wild = any(isinstance(c.pattern, ast.MatchAs) and
                       c.pattern.pattern is None for c in st.cases)
            return self._branches(
                replied, *(c.body for c in st.cases),
                fallthrough=not wild)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            return self.block(st.body, replied)
        if isinstance(st, ast.Try):
            sb, rb = self.block(list(st.body) + list(st.orelse),
                                replied)
            outs = [] if sb is _RESOLVED else [rb]
            for h in st.handlers:
                sh, rh = self.block(h.body, replied)
                if sh is _OPEN:
                    outs.append(rh)
            entry = all(outs) if outs else True
            sf, rf = self.block(st.finalbody, entry)
            if sf is _RESOLVED or not outs:
                return _RESOLVED, rf
            return _OPEN, rf
        if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            # findings inside still count; the loop itself only
            # guarantees resolution when it can never exit
            self.block(st.body, replied)
            self.block(st.orelse, replied)
            infinite = isinstance(st, ast.While) and \
                isinstance(st.test, ast.Constant) and \
                bool(st.test.value) and not any(
                    isinstance(n, ast.Break)
                    for n in _loop_body_nodes(st))
            return (_RESOLVED if infinite else _OPEN), replied
        # simple statement: a reply call anywhere in it answers the
        # client for the rest of this path
        if any(isinstance(n, ast.Call) and _reply_call(n)
               for n in ast.walk(st)):
            replied = True
        return _OPEN, replied


def _class_has_respond(cls: ast.ClassDef) -> bool:
    return any(isinstance(n, ast.Call) and
               _self_attr(n.func) == "_respond"
               for n in ast.walk(cls))


class ReplyOnAllPathsRule:
    id = "reply-on-all-paths"
    doc = """
Dispatch/command handler with an execution path that never answers.

The PR 4 bug class: a mgr module command path that neither returned a
result nor raised left the client waiting out its FULL 30s timeout —
the failure mode is silence, which no log line ever explains.  Two
conventions are checked: (1) command handlers (handle_command /
_handle_module_command) must `return` a (r, outs, outb) result or
raise on every CFG path — a bare `return` or falling off the end
hands the caller None; (2) RGW/Swift HTTP op methods (`_*_op` in a
class that replies via self._respond) must send a reply
(_respond/send_error/...), delegate (`return self._other_op(...)`),
or raise on every path — an early `return` before any reply leaves
the HTTP client hanging.

Fix: make the missing branch answer — return an explicit
(-errno, explanation, None), call self._respond with the right
status, or raise the typed error the wrapper maps to a reply.  For a
path that genuinely must not reply (a reply already owned by a
callee the rule can't see), waive inline with
`# cephck: ignore[reply-on-all-paths]` and a reason comment, or add
a baseline entry with the reason.
"""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _error_scope(ctx.rel):
            return
        parents = ctx.parents()
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name in _RETURN_CONV:
                conv = "return"
            elif _OP_METHOD.match(fn.name) and isinstance(
                    parents.get(fn), ast.ClassDef) and \
                    _class_has_respond(parents[fn]):
                conv = "respond"
            else:
                continue
            scan = _PathScan(conv)
            status, replied = scan.block(fn.body, False)
            for node, msg in scan.findings:
                yield ctx.finding(self.id, node,
                                  f"{fn.name}(): {msg}",
                                  symbol=ctx.qualname(fn))
            if status is _OPEN:
                if conv == "return":
                    yield ctx.finding(
                        self.id, fn,
                        f"{fn.name}() can fall off the end without "
                        f"returning a (r, outs, outb) result — the "
                        f"caller unpacks None (30s-client-hang "
                        f"class)", symbol=ctx.qualname(fn))
                elif not replied:
                    yield ctx.finding(
                        self.id, fn,
                        f"{fn.name}() has a path that falls off the "
                        f"end without sending a reply — the HTTP "
                        f"client waits out its full timeout",
                        symbol=ctx.qualname(fn))


class BareRetryRule:
    id = "bare-retry"
    doc = """
Retry loop pacing itself with raw time.sleep / hand-rolled delay
math instead of common/backoff.Backoff.

PR 17 unified retry pacing for a reason: fixed-delay retries
synchronize (every client re-hits the dead mon on the same beat),
hand-rolled `delay *= 2` forgets the cap or the jitter, and none of
it is clock-injectable for tests.  Backoff(base_s, cap_s) gives
capped exponential full-jitter pacing (AWS-architecture shape), a
fail()/ready() non-blocking form for tick loops, and deterministic
tests via rng/clock injection.

The rule fires on (a) a time.sleep inside an except handler inside a
loop — the classic catch-sleep-retry shape — and (b) a loop that
sleeps on a delay variable it multiplies/exponentiates itself.
Fixed-interval tick/poll pacing (sleep in the loop body, no handler
involvement) is not a retry and is not flagged; sleeps driven by a
Backoff (.next_delay()/.sleep()) are the fix, never flagged.

Fix: hoist a Backoff(base_s=..., cap_s=...) out of the loop, call
.sleep() where the raw sleep was, and .reset() on success.
"""

    def _is_time_sleep(self, node: ast.Call, mod) -> bool:
        name = dotted(node.func)
        if not name:
            return False
        canon = mod.expand(name) if mod else name
        if canon != "time.sleep" and name != "time.sleep" and \
                not (mod is None and name == "sleep"):
            return False
        # a Backoff-derived delay is the sanctioned spelling
        return not any(
            isinstance(n, ast.Call) and
            dotted(n.func).split(".")[-1] == "next_delay"
            for a in node.args for n in ast.walk(a))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _error_scope(ctx.rel) or \
                ctx.rel.endswith("common/backoff.py"):
            return
        mod = ctx.module()
        parents = ctx.parents()

        def inside(node: ast.AST, kinds) -> ast.AST | None:
            cur = parents.get(node)
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Module)):
                if isinstance(cur, kinds):
                    return cur
                cur = parents.get(cur)
            return None

        flagged: set[ast.AST] = set()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    self._is_time_sleep(node, mod)):
                continue
            loop = inside(node, (ast.For, ast.AsyncFor, ast.While))
            if loop is None:
                continue
            # (a) sleep inside an except handler inside the loop
            handler = inside(node, ast.ExceptHandler)
            if handler is not None and node not in flagged:
                flagged.add(node)
                yield ctx.finding(
                    self.id, node,
                    f"catch-sleep-retry loop paced by raw "
                    f"time.sleep — use common.backoff.Backoff "
                    f"(capped exponential, jittered, "
                    f"clock-injectable) and .reset() on success")
                continue
            # (b) sleep(delay) where the loop multiplies delay itself
            arg = node.args[0] if node.args else None
            if not isinstance(arg, ast.Name):
                continue
            grows = any(
                (isinstance(n, ast.AugAssign) and
                 isinstance(n.target, ast.Name) and
                 n.target.id == arg.id and
                 isinstance(n.op, (ast.Mult, ast.Pow))) or
                (isinstance(n, ast.Assign) and
                 any(isinstance(t, ast.Name) and t.id == arg.id
                     for t in n.targets) and
                 any(isinstance(b, ast.BinOp) and
                     isinstance(b.op, (ast.Mult, ast.Pow))
                     for b in ast.walk(n.value)))
                for n in _loop_body_nodes(loop)
                if isinstance(n, (ast.AugAssign, ast.Assign)))
            if grows and node not in flagged:
                flagged.add(node)
                yield ctx.finding(
                    self.id, node,
                    f"hand-rolled exponential delay ({arg.id!r} "
                    f"multiplied in-loop) — common.backoff.Backoff "
                    f"already does capped full-jitter pacing; "
                    f"hand-rolled math forgets the cap or the jitter")


ALL_RULES = [RawLockRule, WireSchemaRule, UnregisteredMessageRule,
             TxnAtomicityRule, SilentThreadRule, JaxTimingRule,
             JitStaticRule, BareExceptRule, HostSyncHotPathRule,
             JitRetraceChurnRule, TracerLeakRule, ImplicitTransferRule,
             GuardedByRule, BlockingInDispatchRule,
             SwallowedErrorRule, ErrnoConflationRule,
             ReplyOnAllPathsRule, BareRetryRule]
