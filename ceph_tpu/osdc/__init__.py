"""Client-side object compute: striping (ref: src/osdc/)."""
from .striper import ObjectExtent, StripeLayout, Striper

__all__ = ["Striper", "StripeLayout", "ObjectExtent"]
