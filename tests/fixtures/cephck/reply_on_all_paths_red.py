"""RED: handlers with a path that never answers (the PR 4 mgr
EIO-hang class: the failure mode is silence and the client waits out
its full timeout)."""


class Handler:
    def _respond(self, h, status, body=b""):
        h.send(status, body)

    def _bucket_op(self, h, method, bucket, q):
        if method == "PUT":
            self._respond(h, 200)
            return
        if method == "DELETE":
            self._delete(bucket)
            return                # BUG: no reply on the DELETE path
        self._respond(h, 405)

    def handle_command(self, cmdmap):
        if cmdmap.get("prefix") == "status":
            return 0, "", self._status()
        if cmdmap.get("prefix") == "flush":
            self._flush()
            return                # BUG: caller unpacks (r, outs, outb)
