"""RepairPlan: one erasure signature's read/rebuild schedule.

A plan is the *what* of a repair — which shards are lost, which
helpers serve bytes and which sub-chunk ranges of each — normalized
into a hashable value whose string signature keys the compiled-program
cache.  Extents are in SUB-CHUNK units (the plugin's native repair
granularity, ref: ErasureCodeClay.cc:364 get_repair_subchunks); the
OSD scales them to bytes against the pool's chunk size, so one plan
(and one compiled program) serves every object and chunk size of the
profile.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Mapping


def _norm_extents(extents: Iterable[tuple[int, int]]
                  ) -> tuple[tuple[int, int], ...]:
    out = tuple((int(o), int(c)) for o, c in extents)
    if not out or any(c <= 0 or o < 0 for o, c in out):
        raise ValueError(f"bad repair extents {out!r}")
    return out


@dataclass(frozen=True)
class RepairPlan:
    """Read/rebuild schedule for one erasure signature.

    lost:     shards to rebuild, sorted.
    helpers:  ((shard, ((sub_off, count), ...)), ...) sorted by shard —
              each helper ships exactly those sub-chunk ranges of its
              chunk, per stripe.
    sub_chunk_no: the code's sub-chunk granularity (1 for MDS/LRC
              full-chunk helpers, q^t for clay).
    """
    lost: tuple[int, ...]
    helpers: tuple[tuple[int, tuple[tuple[int, int], ...]], ...]
    sub_chunk_no: int

    @classmethod
    def make(cls, lost: Iterable[int],
             helpers: Mapping[int, Iterable[tuple[int, int]]],
             sub_chunk_no: int) -> "RepairPlan":
        lost_t = tuple(sorted(set(int(i) for i in lost)))
        help_t = tuple(sorted(
            (int(h), _norm_extents(ext)) for h, ext in helpers.items()))
        if not lost_t or not help_t:
            raise ValueError("repair plan needs lost shards and helpers")
        if set(lost_t) & {h for h, _ in help_t}:
            raise ValueError("a lost shard cannot be its own helper")
        return cls(lost_t, help_t, int(sub_chunk_no))

    # ------------------------------------------------------------ shape
    def helper_ids(self) -> list[int]:
        return [h for h, _ in self.helpers]

    def planes_of(self, shard: int) -> int:
        """Sub-chunk planes this helper contributes per stripe."""
        for h, ext in self.helpers:
            if h == shard:
                return sum(c for _, c in ext)
        raise KeyError(shard)

    def total_planes(self) -> int:
        """Gathered input planes per stripe (the matmul contraction)."""
        return sum(sum(c for _, c in ext) for _, ext in self.helpers)

    def output_planes(self) -> int:
        """Rebuilt planes per stripe: every lost shard comes back
        whole (all sub-chunks)."""
        return len(self.lost) * self.sub_chunk_no

    def read_fraction(self, k: int) -> float:
        """Helper bytes read / the k-full-chunk baseline (the l/k or
        clay d/(k*q) saving the recovery_bytes gates assert)."""
        return self.total_planes() / (k * self.sub_chunk_no)

    # ------------------------------------------------------- byte space
    def byte_extents(self, chunk_size: int) -> dict[int,
                                                    list[tuple[int, int]]]:
        """Per-helper byte extents WITHIN ONE CHUNK of `chunk_size`."""
        if chunk_size % self.sub_chunk_no:
            raise ValueError("chunk size not sub-chunk aligned")
        ssz = chunk_size // self.sub_chunk_no
        return {h: [(o * ssz, c * ssz) for o, c in ext]
                for h, ext in self.helpers}

    # -------------------------------------------------------- signature
    def signature(self) -> str:
        """Cache key, same spirit as matrix_code.erasure_signature's
        "+r..-e.." strings, extended with each helper's extents."""
        lost = "".join(f"-{e}" for e in self.lost)
        helps = "".join(
            f"+{h}@" + ",".join(f"{o}:{c}" for o, c in ext)
            for h, ext in self.helpers)
        return f"{lost}{helps}/{self.sub_chunk_no}"
