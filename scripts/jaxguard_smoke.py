#!/usr/bin/env python
"""jaxguard smoke — the device-contract half of the ship gate.

One batched EC encode/decode pair (the staged path through
osd/ecutil plus the PR 9 staging-free decode_batch_full path) run
TWICE with identical shapes, asserting:

* **exactly-once compilation per signature**: every jit callsite's
  compile count equals its distinct-signature count after round 1,
  and round 2 adds ZERO compiles (pure cache hits) — the
  jit-retrace-churn class cannot ship through this gate;
* **zero unintended transfers**: the dispatches run inside
  jax.transfer_guard('disallow') (armed because CEPH_TPU_JAXGUARD=1),
  so any implicit host<->device copy would have raised;
* **zero recompiles** anywhere (the RecompileError bound of 0 held).

Exit 0 = green.  Wired into scripts/check_green.sh before the suite.
"""
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["CEPH_TPU_JAXGUARD"] = "1"

from ceph_tpu.common import jaxguard  # noqa: E402

jaxguard.enable_if_configured()

import numpy as np  # noqa: E402

from ceph_tpu.ec.registry import ErasureCodePluginRegistry  # noqa: E402
from ceph_tpu.osd import ecutil  # noqa: E402

K, M = 4, 2
STRIPES = 8


def total_compiles(st):
    return sum(v["compiles"] for v in st.values())


def one_pair(ec, sinfo, data):
    """One batched encode + staged decode + staging-free full decode."""
    shards = ecutil.encode(sinfo, ec, data)
    have = {i: shards[i] for i in range(K + M) if i not in (1, K)}
    got = ecutil.decode(sinfo, ec, have, want=[1, K])
    assert got[1] == shards[1] and got[K] == shards[K], \
        "decode mismatch"
    # staging-free decode: (S, k+m, N) arrival layout, erased slots
    # carrying garbage the zero-column matrix must ignore
    cs = sinfo.chunk_size
    arrival = np.zeros((STRIPES, K + M, cs), dtype=np.uint8)
    for i in range(K + M):
        if i in (1, K):
            arrival[:, i, :] = 0xAB     # garbage in the erased slots
        else:
            arrival[:, i, :] = np.frombuffer(
                shards[i], dtype=np.uint8).reshape(STRIPES, cs)
    rec = np.asarray(ec.decode_batch_full([1, K], arrival))
    assert rec[:, 0, :].tobytes() == shards[1], "full decode mismatch"
    assert rec[:, 1, :].tobytes() == shards[K], "full decode mismatch"


def main() -> int:
    if not jaxguard.enabled():
        print("jaxguard smoke: FAIL (sanitizer did not arm)")
        return 1
    ec = ErasureCodePluginRegistry.instance().factory(
        "tpu", {"k": str(K), "m": str(M)})
    cs = ec.get_chunk_size(K * 4096)
    sinfo = ecutil.StripeInfo(K, K * cs)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, STRIPES * K * cs,
                        dtype=np.uint8).tobytes()

    one_pair(ec, sinfo, data)           # round 1: compiles
    st1 = jaxguard.stats()
    for key, s in st1.items():
        if s["recompiles"]:
            print(f"jaxguard smoke: FAIL recompiles at {key}: {s}")
            return 1
        if s["compiles"] != s["signatures"]:
            print(f"jaxguard smoke: FAIL compiles != signatures "
                  f"at {key}: {s}")
            return 1

    one_pair(ec, sinfo, data)           # round 2: pure cache hits
    st2 = jaxguard.stats()
    if total_compiles(st2) != total_compiles(st1):
        grew = {k: (st1.get(k, {}).get("compiles", 0), v["compiles"])
                for k, v in st2.items()
                if v["compiles"] != st1.get(k, {}).get("compiles", 0)}
        print(f"jaxguard smoke: FAIL round 2 recompiled: {grew}")
        return 1
    for key, s in st2.items():
        if s["recompiles"]:
            print(f"jaxguard smoke: FAIL recompiles at {key}: {s}")
            return 1

    sites = sum(1 for v in st2.values() if v["calls"])
    print(f"jaxguard smoke: OK ({sites} jit callsites, "
          f"{total_compiles(st2)} compiles, all exactly-once per "
          f"signature; transfer guard clean on encode/decode/"
          f"decode_batch_full)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
