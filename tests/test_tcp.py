"""TCP messenger backend: socket transport + the full stack over real
sockets, incl. one-process-per-daemon (ref: src/msg/async/
AsyncMessenger.cc model; src/ceph_mon.cc / src/ceph_osd.cc)."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ceph_tpu.client import Rados
from ceph_tpu.msg.messages import Ping, PingReply
from ceph_tpu.msg.messenger import Dispatcher, Messenger
from ceph_tpu.msg.tcp import TcpNet, pick_free_ports


class Collector(Dispatcher):
    def __init__(self):
        self.got = []
        self.resets = []

    def ms_dispatch(self, msg):
        self.got.append(msg)
        return True

    def ms_handle_reset(self, peer):
        self.resets.append(peer)


def wait_for(pred, timeout=10.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(0.01)
    return False


def make_net(names):
    ports = pick_free_ports(len(names))
    return TcpNet({n: ("127.0.0.1", p) for n, p in zip(names, ports)})


# ------------------------------------------------------------- transport
def test_tcp_send_receive_roundtrip():
    net = make_net(["a", "b"])
    ma, mb = Messenger.create(net, "a"), Messenger.create(net, "b")
    ca, cb = Collector(), Collector()
    ma.add_dispatcher(ca)
    mb.add_dispatcher(cb)
    ma.start()
    mb.start()
    try:
        assert ma.connect("b").send_message(Ping(epoch=7, stamp=1.5))
        assert wait_for(lambda: cb.got)
        msg = cb.got[0]
        assert isinstance(msg, Ping) and msg.epoch == 7
        assert msg.src == "a" and msg.seq == 1
        # reply path reuses the addressing
        assert mb.connect("a").send_message(PingReply(stamp=msg.stamp))
        assert wait_for(lambda: ca.got)
        assert isinstance(ca.got[0], PingReply)
    finally:
        ma.shutdown()
        mb.shutdown()


def test_tcp_numpy_payloads_and_ordering():
    from ceph_tpu.msg.messages import PGPush
    net = make_net(["x", "y"])
    mx, my = Messenger.create(net, "x"), Messenger.create(net, "y")
    cy = Collector()
    my.add_dispatcher(cy)
    mx.start()
    my.start()
    try:
        blobs = [np.random.default_rng(i).integers(
            0, 256, 10_000, dtype=np.uint8).tobytes() for i in range(20)]
        for i, b in enumerate(blobs):
            assert mx.connect("y").send_message(
                PGPush(oid=f"o{i}", data=b))
        assert wait_for(lambda: len(cy.got) == 20)
        # FIFO per peer, payloads intact
        assert [m.oid for m in cy.got] == [f"o{i}" for i in range(20)]
        assert all(m.data == b for m, b in zip(cy.got, blobs))
    finally:
        mx.shutdown()
        my.shutdown()


def test_tcp_dead_peer_resets():
    net = make_net(["p", "q"])
    mp = Messenger.create(net, "p")
    cp = Collector()
    mp.add_dispatcher(cp)
    mp.start()
    try:
        assert not mp.connect("q").send_message(Ping())   # never bound
        assert cp.resets == ["q"]
        assert not mp.connect("nobody").send_message(Ping())
    finally:
        mp.shutdown()


# --------------------------------------------- full stack over sockets
def test_cluster_over_tcp_in_process():
    """mon + 3 osds + client, each on its own socket (one process)."""
    from ceph_tpu.mon.monitor import Monitor, build_initial
    from ceph_tpu.osd.daemon import OSDDaemon
    names = ["mon.0", "osd.0", "osd.1", "osd.2", "client.900"]
    net = make_net(names)
    m, w = build_initial(3, osds_per_host=1)
    mon = Monitor(net, initial_map=m, initial_wrapper=w)
    mon.init()
    osds = [OSDDaemon(net, i) for i in range(3)]
    for d in osds:
        d.init()
    r = Rados(net, name="client.900").connect(10.0)
    try:
        assert wait_for(lambda: all(
            d.osdmap.epoch >= 1 for d in osds))
        r.pool_create("p", pg_num=8)
        io = r.open_ioctx("p")
        payload = os.urandom(50_000)
        io.write_full("sock-obj", payload)
        assert io.read("sock-obj") == payload
        assert io.stat("sock-obj")["size"] == len(payload)
        assert "sock-obj" in io.list_objects()
    finally:
        r.shutdown()
        for d in osds:
            d.shutdown()
        mon.shutdown()


@pytest.mark.slow
def test_cluster_multiprocess(tmp_path):
    """The real thing: mon + 2 osds as separate OS processes, client in
    this one — IO over localhost sockets."""
    names = ["mon.0", "osd.0", "osd.1", "client.901"]
    ports = pick_free_ports(len(names))
    addrs = {n: ["127.0.0.1", p] for n, p in zip(names, ports)}
    monmap = {"addrs": addrs, "mon_ranks": [0], "n_osd": 2,
              "osds_per_host": 1}
    mpath = tmp_path / "monmap.json"
    mpath.write_text(json.dumps(monmap))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.getcwd())
    procs = []
    try:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.tools.daemon_main", "mon",
             "--rank", "0", "--monmap", str(mpath)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        time.sleep(1.0)
        for i in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ceph_tpu.tools.daemon_main",
                 "osd", "--id", str(i), "--monmap", str(mpath)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
        net = TcpNet({k: tuple(v) for k, v in addrs.items()})
        r = Rados(net, name="client.901", op_timeout=60.0).connect(60.0)
        try:
            # wait until both subprocess OSDs are up in the map
            assert wait_for(lambda: sum(
                1 for o in range(2)
                if r.objecter.osdmap.is_up(o)) == 2, timeout=60.0), \
                "subprocess osds never came up"
            r.pool_create("mp", pg_num=8)
            io = r.open_ioctx("mp")
            io.write_full("cross-process", b"hello from another pid")
            assert io.read("cross-process") == \
                b"hello from another pid"
        finally:
            r.shutdown()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
