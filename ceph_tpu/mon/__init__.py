"""Monitor: the cluster's map authority.

Single-process mon-lite: a versioned store (MonitorStore), a
degenerate-quorum Paxos commit pipeline (Paxos/PaxosService), the
OSDMonitor command engine (pool/EC-profile/osd state/upmap commands),
and the Monitor daemon speaking MMonCommand/MMonSubscribe/MOSDBoot/
MOSDFailure over the messenger (ref: src/mon/).
"""
from .store import MonitorStore, StoreTransaction
from .paxos import Paxos, PaxosService
from .osd_monitor import OSDMonitor
from .monitor import Monitor

__all__ = ["MonitorStore", "StoreTransaction", "Paxos", "PaxosService",
           "OSDMonitor", "Monitor"]
