"""GF(2^8) byte matmul as a GF(2) bit-plane matmul on the TPU MXU.

The TPU-first formulation of the erasure-code hot loop (the GF(2^8)
matrix-vector products that ISA-L's `ec_encode_data` AVX2 assembly computes
per 32-byte lane, ref: src/erasure-code/isa/ErasureCodeIsa.cc:129):

GF(2^8) multiplication by a constant c is GF(2)-linear in the bits of the
operand, so an (r x k) byte matrix over GF(2^8) lifts to an (8r x 8k) 0/1
companion matrix B with B[8i+t, 8j+c] = bit t of (mat[i,j] * x^c).  A byte
block (k, N) unpacks to bit-planes (8k, N); then

    out_bits = (B @ bits) mod 2        # one int8 matmul on the MXU
    out[i,n] = sum_t out_bits[8i+t, n] << t

XOR-accumulation across k inputs becomes mod-2 integer accumulation inside
the matmul, which is exactly what the MXU is good at.  The contraction
length is 8k <= 256, so int32 (or even bf16) accumulation is exact.

Two paths:
* `gf_matmul_xla`: pure jnp — XLA fuses unpack/pack around a dot_general;
* `gf_matmul_pallas`: a fused Pallas kernel that keeps the 8x bit-plane
  expansion in VMEM only (never materialized in HBM), grid over N tiles.

Both produce bytes identical to the numpy oracle (ceph_tpu.ec.gf) and hence
to the reference plugins.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import gf


def expand_bits(data: jax.Array) -> jax.Array:
    """(..., k, N) uint8 -> (..., 8k, N) int8 bit-planes (bit c of byte j
    at row 8j+c)."""
    *lead, k, n = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (data[..., :, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(*lead, 8 * k, n).astype(jnp.int8)


def pack_bits(out_bits: jax.Array) -> jax.Array:
    """(..., 8r, N) {0,1} int32 -> (..., r, N) uint8."""
    *lead, r8, n = out_bits.shape
    r = r8 // 8
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8)).astype(jnp.int32)
    planes = out_bits.reshape(*lead, r, 8, n)
    return (planes * weights[None, :, None]).sum(axis=-2).astype(jnp.uint8)


@jax.jit
def gf_matmul_xla(bitmat: jax.Array, data: jax.Array) -> jax.Array:
    """(8r x 8k) companion bit-matrix times (..., k, N) bytes -> (..., r, N).

    Leading axes of `data` are batch (stripes)."""
    bits = expand_bits(data)
    acc = jnp.matmul(bitmat, bits, preferred_element_type=jnp.int32)
    return pack_bits(acc & 1)


@functools.lru_cache(maxsize=512)
def companion_bitmatrix(mat_bytes: bytes, r: int, k: int) -> np.ndarray:
    mat = np.frombuffer(mat_bytes, dtype=np.uint8).reshape(r, k)
    return gf.expand_to_bitmatrix(mat).astype(np.int8)


class GFMatmul:
    """Cached, device-resident GF matmul for a fixed byte matrix.

    The companion bit-matrix lives in HBM across calls (the analogue of the
    ISA-L encode-table cache, ref: ErasureCodeIsaTableCache.cc); jit caches
    the compiled kernel per data shape.
    """

    def __init__(self, mat: np.ndarray, use_pallas: bool | None = None):
        self.mat = np.ascontiguousarray(mat, dtype=np.uint8)
        self.r, self.k = self.mat.shape
        self.bitmat = jnp.asarray(
            companion_bitmatrix(self.mat.tobytes(), self.r, self.k))
        if use_pallas is None:
            # config-selected backend; pallas only lowers on TPU.
            # Measured on v5e (PERF_NOTES.md): the fused planar kernel
            # beats the XLA formulation ~1.5x, so it is the default.
            from ...common.options import global_config
            use_pallas = (global_config()["ec_tpu_backend"] == "pallas"
                          and jax.default_backend() == "tpu")
        self.use_pallas = use_pallas

    def __call__(self, data) -> jax.Array:
        """data: (..., k, N) uint8 (device or host) -> (..., r, N) uint8."""
        data = jnp.asarray(data, dtype=jnp.uint8)
        if self.use_pallas:
            return gf_matmul_pallas(self.mat, data)
        return gf_matmul_xla(self.bitmat, data)


# ---------------------------------------------------------------------------
# Grouped (block-diagonal) formulation: full MXU tiles
# ---------------------------------------------------------------------------
# A single (8m x 8k) companion matmul uses a sliver of the 128x128 MXU
# tile (k=8,m=4: 32 of 128 rows, 64 of 128 contraction lanes).  Stacking
# g stripes' bit-planes into one column vector and the weights into a
# block-diagonal (8mg x 8kg) matrix turns g tiny matmuls into one dense-
# tile matmul: for g=4, (128 x 256) @ (256 x N) — full rows, double-pass
# contraction.  The reshape (S, k, N) -> (S/g, gk, N) is free (no data
# movement); only the weight matrix grows (by g, with zeros the MXU
# processes at full rate).

@functools.lru_cache(maxsize=512)
def grouped_bitmatrix(mat_bytes: bytes, r: int, k: int,
                      group: int) -> np.ndarray:
    """Block-diagonal stack of `group` copies of the companion matrix:
    (8r*g, 8k*g) int8."""
    b = companion_bitmatrix(mat_bytes, r, k)
    g = group
    out = np.zeros((8 * r * g, 8 * k * g), dtype=np.int8)
    for i in range(g):
        out[8 * r * i:8 * r * (i + 1), 8 * k * i:8 * k * (i + 1)] = b
    return out


@functools.partial(jax.jit, static_argnames=("group",))
def gf_matmul_xla_grouped(bitmat_g: jax.Array, data: jax.Array,
                          group: int) -> jax.Array:
    """data (S, k, N) with S % group == 0; bitmat_g the grouped
    block-diagonal companion -> (S, r, N)."""
    s, k, n = data.shape
    d = data.reshape(s // group, group * k, n)
    bits = expand_bits(d)
    acc = jnp.matmul(bitmat_g, bits, preferred_element_type=jnp.int32)
    out = pack_bits(acc & 1)                    # (S/g, g*r, N)
    return out.reshape(s, -1, n)


# ---------------------------------------------------------------------------
# Pallas fused kernel (plane-major, pack-by-matmul)
# ---------------------------------------------------------------------------
# Design notes (measured on v5e, see PERF_NOTES.md):
# * The bit-plane expansion must never touch HBM: fused in VMEM per grid
#   cell.
# * Plane-major bit layout — all bit-0 planes, then all bit-1 planes —
#   lowers to 8 flat shift/mask passes with no sublane interleave; the
#   companion matrix's columns are permuted to match (free, host side).
# * The byte re-pack is itself a (gr x 8gr) matmul against a weight
#   matrix with P[i, 8i+t] = 1<<t: elementwise epilogues over the
#   8x-inflated mod-2 accumulator dominated the kernel before this.
# * Mosaic constraints: MXU accumulator must be int32; int8/int16
#   shifts and uint8 iota don't lower (and the int8 compare-mask
#   variant lowers but runs slower than int32 shifts).

@functools.lru_cache(maxsize=512)
def _planar_perm(gk: int) -> np.ndarray:
    """Column permutation taking byte-major bit rows (bit c of byte j at
    8j+c) to plane-major (at c*gk+j)."""
    return np.array([8 * j + c for c in range(8) for j in range(gk)],
                    dtype=np.int64)


@functools.lru_cache(maxsize=512)
def grouped_planar_bitmatrix(mat_bytes: bytes, r: int, k: int,
                             group: int) -> np.ndarray:
    """Block-diagonal companion stack with plane-major columns:
    (8rg, 8kg) int8, ready for the fused kernel."""
    bg = grouped_bitmatrix(mat_bytes, r, k, group)
    return np.ascontiguousarray(bg[:, _planar_perm(group * k)])


@functools.lru_cache(maxsize=64)
def pack_matrix(rows: int) -> np.ndarray:
    """(rows, 8*rows) int8 with P[i, 8i+t] = 1<<t — packs mod-2 bit rows
    back into bytes as a matmul.  1<<7 wraps to -128 in int8; the int32
    accumulation truncated to uint8 is still exact mod 256."""
    p = np.zeros((rows, 8 * rows), dtype=np.int8)
    for i in range(rows):
        for t in range(8):
            p[i, 8 * i + t] = np.int8(np.uint8(1 << t).view(np.int8))
    return p


def _gf_kernel_planar(bitmat_ref, pack_ref, data_ref, out_ref):
    """One (stripe-group, N-tile) cell: plane-major unpack -> dense-tile
    MXU matmul -> &1 -> MXU pack-matmul; bit-planes only in VMEM."""
    data = data_ref[0].astype(jnp.int32)           # (gk, TN)
    planes = [((data >> c) & 1) for c in range(8)]
    bits = jnp.concatenate(planes, axis=0).astype(jnp.int8)  # (8gk, TN)
    acc = jax.lax.dot_general(
        bitmat_ref[...], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)          # (8gr, TN)
    acc1 = (acc & 1).astype(jnp.int8)
    packed = jax.lax.dot_general(
        pack_ref[...], acc1, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)          # (gr, TN)
    out_ref[0] = packed.astype(jnp.uint8)


@functools.partial(jax.jit,
                   static_argnames=("group", "tile_n", "interpret"))
def gf_matmul_pallas_grouped(bitmat_gp: jax.Array, data: jax.Array,
                             group: int, tile_n: int,
                             interpret: bool = False) -> jax.Array:
    """Fused grouped kernel: grid (stripe-groups, N-tiles); the grid
    walks the stripe axis directly (no batch flatten/transpose).

    bitmat_gp: grouped_planar_bitmatrix; data (S, k, N) uint8 with
    S % group == 0 and N % tile_n == 0."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, k, n = data.shape
    gr8, gk8 = bitmat_gp.shape
    gk, gr = gk8 // 8, gr8 // 8
    d = data.reshape(s // group, gk, n)
    pmat = jnp.asarray(pack_matrix(gr))
    out = pl.pallas_call(
        _gf_kernel_planar,
        out_shape=jax.ShapeDtypeStruct((s // group, gr, n), jnp.uint8),
        grid=(s // group, n // tile_n),
        in_specs=[
            pl.BlockSpec((gr8, gk8), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((gr, gr8), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, gk, tile_n), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, gr, tile_n), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(bitmat_gp, pmat, d)
    return out.reshape(s, -1, n)


PALLAS_MIN_TILE = 2048
PALLAS_TILE = 8192


# ---------------------------------------------------------------------------
# Full-width decode: device-resident survivor selection
# ---------------------------------------------------------------------------
# A degraded read holds the (S, n, N) chunk array in ARRIVAL layout —
# all n = k+m slots, erased slots carrying whatever garbage happens to
# sit there.  The staged formulation gathers k survivor rows into a
# dense (S, k, N) array on the HOST (np.stack + moveaxis), which
# BENCH_r05 showed costs more than the decode matmul itself
# (decode_incl_stage 35.4 GB/s vs kernel 76.7 GB/s).  The zero-column
# (nerrs x n) decode matrix (matrix_code.make_decode_matrix_full)
# makes the gather unnecessary: the selection IS the matrix.  But the
# naive full-width matmul unpacks 8n bit-planes instead of 8k — the
# round-3 measurement (PERF_NOTES) lost to staged decode (37 GB/s)
# exactly because the int32 unpack is the wall.
#
# The resolution here: the survivor selection derives STATICALLY from
# the matrix's nonzero columns (validated against the caller's
# validity mask), so
# * the Pallas kernel reads the full-width block but slices out only
#   the survivor rows in VMEM (static sublane slices, coalesced into
#   runs) before the bit-plane unpack — compute is IDENTICAL to the
#   staged path (8k planes, same grouped matmul), the gather costs a
#   VMEM copy, and no host staging exists at all;
# * the XLA fallback gathers survivor rows on DEVICE (one take) and
#   runs the same dense matmul — still no host stack/moveaxis.
# The extra n/k x HBM read of the full block is paid only by the
# Pallas path and is invisible while the kernel stays unpack/MXU-bound
# (PERF_NOTES round 2: far from the 819 GB/s HBM roof).

def _survivor_runs(idx: list[int]) -> list[tuple[int, int]]:
    """Sorted row indexes -> maximal contiguous [start, stop) runs, so
    the in-kernel gather is a handful of sublane slices, not k
    single-row copies."""
    runs: list[tuple[int, int]] = []
    for i in idx:
        if runs and runs[-1][1] == i:
            runs[-1] = (runs[-1][0], i + 1)
        else:
            runs.append((i, i + 1))
    return runs


def selection_from_matrix(mat_full: np.ndarray,
                          valid: np.ndarray | None = None) -> list[int]:
    """Survivor columns of a full-width decode matrix: the nonzero
    columns, checked against `valid` (length-n bool mask of slots
    whose content is real).  A nonzero column over an INVALID slot
    would fold garbage into the output — that is a caller bug, not a
    degraded mode, so it raises."""
    nz = [int(j) for j in np.flatnonzero(mat_full.any(axis=0))]
    if valid is not None:
        valid = np.asarray(valid, dtype=bool)
        bad = [j for j in nz if not valid[j]]
        if bad:
            raise ValueError(
                f"decode matrix has nonzero columns {bad} over slots "
                "the validity mask marks erased")
    return nz


def _gf_kernel_planar_select(runs, n, bitmat_ref, pack_ref, data_ref,
                             out_ref):
    """Full-width cell: static survivor slices out of the (g*n, TN)
    arrival block, then the identical plane-major unpack -> grouped
    matmul -> pack-matmul of the staged kernel.  `runs` are
    per-stripe-relative [start, stop) row runs; g stripes sit at
    offsets j*n."""
    full = data_ref[0]                              # (g*n, TN)
    g = full.shape[0] // n
    parts = [full[j * n + a:j * n + b, :]
             for j in range(g) for (a, b) in runs]
    data = (parts[0] if len(parts) == 1
            else jnp.concatenate(parts, axis=0)).astype(jnp.int32)
    planes = [((data >> c) & 1) for c in range(8)]
    bits = jnp.concatenate(planes, axis=0).astype(jnp.int8)  # (8gk, TN)
    acc = jax.lax.dot_general(
        bitmat_ref[...], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)           # (8gr, TN)
    acc1 = (acc & 1).astype(jnp.int8)
    packed = jax.lax.dot_general(
        pack_ref[...], acc1, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)           # (gr, TN)
    out_ref[0] = packed.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=(
    "sel", "n", "group", "tile_n", "interpret"))
def gf_decode_pallas_grouped_full(bitmat_gp: jax.Array, data: jax.Array,
                                  sel: tuple, n: int, group: int,
                                  tile_n: int,
                                  interpret: bool = False) -> jax.Array:
    """Fused full-width decode: data (S, n, N) in arrival layout with
    S % group == 0, N % tile_n == 0; `sel` the static survivor column
    tuple; bitmat_gp the grouped planar companion of the DENSE
    (r x len(sel)) matrix."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, n_, nbytes = data.shape
    gr8, gk8 = bitmat_gp.shape
    gr = gr8 // 8
    d = data.reshape(s // group, group * n, nbytes)
    pmat = jnp.asarray(pack_matrix(gr))
    runs = tuple(_survivor_runs(list(sel)))
    kern = functools.partial(_gf_kernel_planar_select, runs, n)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((s // group, gr, nbytes),
                                       jnp.uint8),
        grid=(s // group, nbytes // tile_n),
        in_specs=[
            pl.BlockSpec((gr8, gk8), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((gr, gr8), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, group * n, tile_n), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, gr, tile_n), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(bitmat_gp, pmat, d)
    return out.reshape(s, -1, nbytes)


@functools.partial(jax.jit, static_argnames=("sel",))
def gf_decode_xla_full(bitmat: jax.Array, data: jax.Array,
                       sel: tuple) -> jax.Array:
    """XLA full-width decode: DEVICE-resident survivor gather (one
    take along the chunk axis — no host stack/moveaxis) then the dense
    8k-contraction matmul."""
    survivors = jnp.take(data, jnp.asarray(sel, dtype=jnp.int32),
                         axis=-2)
    return gf_matmul_xla(bitmat, survivors)


class GFDecodeFull:
    """Cached device-resident decode for one full-width matrix.

    Holds the dense companion of mat_full restricted to its survivor
    columns (HBM-resident across calls, the ISA-L table-cache
    analogue) plus the static selection; __call__ consumes (..., n, N)
    arrival-layout chunk arrays with NO host-side staging."""

    def __init__(self, mat_full: np.ndarray,
                 valid: np.ndarray | None = None,
                 use_pallas: bool | None = None):
        self.mat_full = np.ascontiguousarray(mat_full, dtype=np.uint8)
        self.r, self.n = self.mat_full.shape
        self.sel = tuple(selection_from_matrix(self.mat_full, valid))
        if not self.sel:
            raise ValueError("decode matrix has no nonzero columns")
        self.mat = np.ascontiguousarray(self.mat_full[:, list(self.sel)])
        self.bitmat = jnp.asarray(
            companion_bitmatrix(self.mat.tobytes(), self.r,
                                len(self.sel)))
        #: group -> device-resident grouped planar companion; built on
        #: first use so repeat calls (the cached-signature hot path)
        #: never re-upload the weight matrix
        self._bgp: dict[int, jax.Array] = {}
        if use_pallas is None:
            from ...common.options import global_config
            use_pallas = (global_config()["ec_tpu_backend"] == "pallas"
                          and jax.default_backend() == "tpu")
        self.use_pallas = use_pallas

    def __call__(self, data, interpret: bool = False) -> jax.Array:
        data = jnp.asarray(data, dtype=jnp.uint8)
        *lead, n, nbytes = data.shape
        if n != self.n:
            raise ValueError(f"expected {self.n} chunk slots, got {n}")
        s = int(np.prod(lead)) if lead else 1
        d = data.reshape(s, n, nbytes)
        if not self.use_pallas and not interpret:
            out = gf_decode_xla_full(self.bitmat, d, self.sel)
            return out.reshape(*lead, self.r, nbytes) if lead else out[0]
        group = 4 if s % 4 == 0 else 2 if s % 2 == 0 else 1
        tile = PALLAS_TILE if nbytes % PALLAS_TILE == 0 else (
            PALLAS_MIN_TILE if nbytes % PALLAS_MIN_TILE == 0 else 0)
        body_n = nbytes if tile else \
            (nbytes // PALLAS_MIN_TILE) * PALLAS_MIN_TILE
        if body_n == 0:
            out = gf_decode_xla_full(self.bitmat, d, self.sel)
            return out.reshape(*lead, self.r, nbytes) if lead else out[0]
        bgp = self._bgp.get(group)
        if bgp is None:
            bgp = self._bgp[group] = jnp.asarray(grouped_planar_bitmatrix(
                self.mat.tobytes(), self.r, len(self.sel), group))
        if tile:
            out = gf_decode_pallas_grouped_full(
                bgp, d, sel=self.sel, n=n, group=group, tile_n=tile,
                interpret=interpret)
        else:
            body = gf_decode_pallas_grouped_full(
                bgp, d[:, :, :body_n], sel=self.sel, n=n, group=group,
                tile_n=PALLAS_MIN_TILE, interpret=interpret)
            tail = gf_decode_xla_full(self.bitmat, d[:, :, body_n:],
                                      self.sel)
            out = jnp.concatenate([body, tail], axis=2)
        return out.reshape(*lead, self.r, nbytes) if lead else out[0]


def gf_matmul_pallas(mat: np.ndarray, data: jax.Array,
                     interpret: bool = False) -> jax.Array:
    """Fused-kernel entry on the BYTE matrix `mat` (r, k): picks the
    stripe group (4/2/1 dividing the batch) and N tiling, sends ragged
    tails through the XLA path.  data (..., k, N) -> (..., r, N)."""
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    r, k = mat.shape
    *lead, k_, n = data.shape
    s = int(np.prod(lead)) if lead else 1
    d = data.reshape(s, k, n)
    group = 4 if s % 4 == 0 else 2 if s % 2 == 0 else 1
    tile = PALLAS_TILE if n % PALLAS_TILE == 0 else (
        PALLAS_MIN_TILE if n % PALLAS_MIN_TILE == 0 else 0)
    body_n = n if tile else (n // PALLAS_MIN_TILE) * PALLAS_MIN_TILE
    if body_n == 0:
        B = jnp.asarray(companion_bitmatrix(mat.tobytes(), r, k))
        return gf_matmul_xla(B, data)
    bgp = jnp.asarray(grouped_planar_bitmatrix(mat.tobytes(), r, k, group))
    if tile:
        out = gf_matmul_pallas_grouped(bgp, d, group=group, tile_n=tile,
                                       interpret=interpret)
    else:
        body = gf_matmul_pallas_grouped(
            bgp, d[:, :, :body_n], group=group, tile_n=PALLAS_MIN_TILE,
            interpret=interpret)
        B = jnp.asarray(companion_bitmatrix(mat.tobytes(), r, k))
        tail = gf_matmul_xla(B, d[:, :, body_n:])
        out = jnp.concatenate([body, tail], axis=2)
    return out.reshape(*lead, r, n) if lead else out[0]
