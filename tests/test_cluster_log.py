"""Cluster log: LogClient -> mon LogMonitor through paxos (VERDICT r4
#4; ref: src/common/LogClient.cc, src/mon/LogMonitor.cc).

Acceptance: osd failure, scrub inconsistency, and repair outcome all
appear in `log last`, surviving mon failover."""
import time

import pytest

from ceph_tpu.common.options import global_config
from ceph_tpu.store import ObjectId, Transaction
from ceph_tpu.testing import MiniCluster


def locate(c, r, pool_name, oid):
    pid = r.pool_lookup(pool_name)
    m = c.mon.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    return pid, pg, acting, primary


def log_last(r, n=50, level="debug"):
    rc, outs, out = r.mon_command({"prefix": "log last", "num": n,
                                   "level": level})
    assert rc == 0, outs
    return out


def test_operator_log_and_log_last():
    c = MiniCluster(n_osd=3, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    try:
        rc, outs, _ = r.mon_command({"prefix": "log",
                                     "logtext": "hello cluster"})
        assert rc == 0, outs
        c.pump()
        entries = log_last(r)
        assert any(e["text"] == "hello cluster" for e in entries)
        # level filter drops info entries
        assert not any(e["text"] == "hello cluster"
                       for e in log_last(r, level="error"))
        # counts surface for prometheus
        rc, _, counts = r.mon_command({"prefix": "log counts"})
        assert rc == 0 and counts.get("info", 0) >= 1
    finally:
        c.shutdown()


def test_daemon_clog_flush_and_ack():
    """An OSD's clog entry reaches `log last` via the tick flush and
    the ack trims the client buffer (resends dedup by seq)."""
    c = MiniCluster(n_osd=3, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    try:
        d = c.osds[0]
        d.clog.warn("something odd happened")
        assert d.clog.pending() == 1
        for i in range(6):
            c.tick(100.0 + i)
        entries = log_last(r)
        assert any(e["text"] == "something odd happened" and
                   e["name"] == "osd.0" and e["level"] == "warn"
                   for e in entries)
        assert d.clog.pending() == 0, "ack never trimmed the buffer"
        # duplicate-flush storm must not duplicate the entry
        d.clog.flush()
        c.pump()
        n = sum(1 for e in log_last(r)
                if e["text"] == "something odd happened")
        assert n == 1
    finally:
        c.shutdown()


def test_osd_failure_scrub_and_repair_in_log():
    """The acceptance triple: a failed OSD, a scrub inconsistency,
    and its repair outcome all land in the cluster log with no
    operator log commands."""
    from ceph_tpu.osd.ec_backend import pg_cid
    g = global_config()
    saved = {k: g[k] for k in ("osd_scrub_min_interval",
                               "osd_deep_scrub_interval")}
    g.set("osd_scrub_min_interval", 30.0)
    g.set("osd_deep_scrub_interval", 60.0)
    c = MiniCluster(n_osd=4, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    try:
        r.pool_create("p", pg_num=4)
        io = r.open_ioctx("p")
        payload = b"log-me" * 700
        io.write_full("victim", payload)
        c.pump()
        _pid, pg, acting, primary = locate(c, r, "p", "victim")
        replica = next(o for o in acting if o != primary)
        c.osds[replica].store.queue_transaction(
            Transaction().write(pg_cid(pg), ObjectId("victim"), 0,
                                b"ROTROTRO"))
        # kill an uninvolved osd so the failure report line appears
        dead = next(o for o in range(4)
                    if o not in acting and o != primary)
        c.kill_osd(dead)
        t = 1000.0
        for i in range(50):
            t += 5.0
            c.tick(t)
            if c.mon.osdmap.is_down(dead) and \
                    c.osds[replica].pgs[pg].shard.read("victim") == \
                    payload:
                break
        # let the repair's clog line flush + commit
        for i in range(6):
            t += 5.0
            c.tick(t)
        texts = [e["text"] for e in log_last(r, n=100)]
        assert any(f"osd.{dead} marked down" in t_ for t_ in texts), \
            texts
        assert any("inconsistent" in t_ and str(pg) in t_
                   for t_ in texts), texts
        assert any("repaired and re-verified" in t_
                   for t_ in texts), texts
    finally:
        for k, v in saved.items():
            g.set(k, v)
        c.shutdown()


def test_log_survives_mon_failover():
    """Entries committed through paxos answer identically from the
    surviving quorum after the leader dies."""
    c = MiniCluster(n_osd=3, n_mon=3, threaded=False)
    c.pump()
    c.wait_all_up()
    r = c.rados()
    try:
        rc, outs, _ = r.mon_command({"prefix": "log",
                                     "logtext": "before failover"})
        assert rc == 0, outs
        c.pump()
        assert any(e["text"] == "before failover"
                   for e in log_last(r))
        leader = next(m for m in c.mons.values() if m.is_leader)
        c.kill_mon(leader.rank)
        t = 2000.0
        for i in range(10):
            t += 3.0
            c.tick(t)
        # the first command after the kill may time out while the
        # client hunts to a live mon — that's the reconnect, not the
        # log; retry a few times
        deadline = time.monotonic() + 90
        entries = None
        while time.monotonic() < deadline:
            t += 3.0
            c.tick(t)
            try:
                rc, _, out = r.mon_command({"prefix": "log last",
                                            "num": 50,
                                            "level": "debug"})
                if rc == 0 and any(e["text"] == "before failover"
                                   for e in out):
                    entries = out
                    break
            except Exception:
                pass
        assert entries is not None, \
            "log last never answered after failover"
    finally:
        c.shutdown()
