"""cephfs-lite: a POSIX-ish file namespace on RADOS.

Multi-rank metadata servers + libcephfs-like client
(ref: src/mds + src/client: dentry-omap directory objects in a
metadata pool, a per-rank write-ahead journal over ceph_tpu.journal,
striped file data objects `{ino}.{objno}` in a data pool, caps,
subtree pinning/balancing, snapshots — and standby/failover: the mon's
MDSMonitor promotes MDSStandby daemons through replay -> resolve ->
active when a rank's beacon lapses)."""
from .client import CephFS, FileHandle
from .mds import MDSDaemon, MDSStandby

__all__ = ["MDSDaemon", "MDSStandby", "CephFS", "FileHandle"]
