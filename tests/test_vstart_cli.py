"""vstart CLI shell: the ceph-command tour as a smoke test
(ref: src/vstart.sh + src/ceph.in usage model)."""
import io

from ceph_tpu.tools.vstart import VstartShell


def test_vstart_shell_tour(tmp_path):
    src = tmp_path / "payload"
    src.write_bytes(b"cli payload " * 10)
    out = io.StringIO()
    sh = VstartShell(n_osd=4, osds_per_host=1, out=out)
    try:
        for line in [
            "osd stat",
            "osd pool create p 8",
            f"put p obj {src}",
            f"get p obj {tmp_path / 'back'}",
            "ls p",
            "stat p obj",
            "pg map 0.1",
            "pg scrub 0.1",
            "balance",
            f"serve put p art {src}",
            f"serve get p art {tmp_path / 'art_back'}",
            "serve stat p art",
            "serve pages p art shard0 0",
            "osd down 1",
            "osd in 1",
            "status",
            "perf dump",
        ]:
            assert sh.run_line(line)
        assert not sh.run_line("quit")
        text = out.getvalue()
        assert "4 osds: 4 up" in text
        assert "pool 'p' created" in text
        assert (tmp_path / "back").read_bytes() == src.read_bytes()
        assert "obj" in text
        assert '"inconsistent": []' in text
        assert "marked down osd.1" in text
        assert '"op"' in text            # perf dump
        assert "published art epoch 1" in text
        assert (tmp_path / "art_back").read_bytes() == \
            src.read_bytes()
        assert '"ragged_pages": 1' in text      # serve stat
        assert "page 0: 120 B sha256 " in text  # serve pages
        # errors report, not raise, and the shell keeps running
        assert sh.run_line("bogus command here")
        assert "Error:" in out.getvalue()
    finally:
        sh.close()
