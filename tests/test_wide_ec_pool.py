"""Wide EC pools (k+m > 10): the legacy CRUSH rule-mask ceiling
(max_size=10) silently unmapped every PG of a k=8,m=4 pool — find_rule
returned -1, mappings came back empty, and client IO hung to timeout
(found by the multichip E2E hardening; ref: ErasureCode.cc create_rule
passes get_chunk_count() as the rule's max_size)."""
import time

import numpy as np
import pytest

from ceph_tpu.osd.types import PG
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=16, threaded=True)
    c.wait_all_up()
    yield c
    c.shutdown()


def test_k8m4_pool_maps_and_serves_io(cluster):
    r = cluster.rados()
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k8m4",
                   "profile": {"plugin": "tpu", "k": "8", "m": "4",
                               "crush-failure-domain": "host"}})
    r.pool_create("wide", pg_num=8, pool_type="erasure",
                  erasure_code_profile="k8m4")
    pool_id = r.pool_lookup("wide")
    om = r.objecter.osdmap
    pool = om.pools[pool_id]
    assert pool.size == 12
    ruleno = om.crush.find_rule(pool.crush_rule, pool.type, pool.size)
    assert ruleno >= 0, "rule mask must admit size=k+m"
    for ps in range(8):
        up, _, acting, primary = om.pg_to_up_acting_osds(PG(pool_id, ps))
        assert len([o for o in acting if o >= 0]) >= 9, \
            f"pg {ps} under-mapped: {acting}"
        assert primary >= 0
    io = r.open_ioctx("wide")
    payload = np.random.default_rng(3).integers(
        0, 256, 1 << 20, dtype=np.uint8).tobytes()
    io.write_full("big", payload)
    assert io.read("big") == payload


def test_write_racing_pool_creation_retries_to_success(cluster):
    """A write fired IMMEDIATELY after pool creation lands during
    peering; the pre-active gate must ESTALE it back to the client's
    rescan-retry (not drop it into an unacked fan-out) so it
    eventually commits."""
    r = cluster.rados()
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k8m4b",
                   "profile": {"plugin": "tpu", "k": "8", "m": "4",
                               "crush-failure-domain": "host"}})
    r.pool_create("wide2", pg_num=8, pool_type="erasure",
                  erasure_code_profile="k8m4b")
    io = r.open_ioctx("wide2")    # no settling sleep on purpose
    t0 = time.time()
    io.write_full("early", b"e" * 300_000)
    assert io.read("early") == b"e" * 300_000
    assert time.time() - t0 < 30
