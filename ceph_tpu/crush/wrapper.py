"""CrushWrapper equivalent: owns a CrushMap plus name/type maps and
build/modify/query helpers (ref: src/crush/CrushWrapper.{h,cc}).

Covers the surface the rest of the framework needs: bucket tree
construction (`add_bucket`, `insert_item`, `move_bucket`), simple-rule
creation (`add_simple_rule`, ref: CrushWrapper.h:1199), weight updates,
device classes, and `do_rule` dispatch with a reusable work area
(ref: CrushWrapper.h:1568).
"""
from __future__ import annotations

from . import mapper
from .types import (
    CRUSH_BUCKET_STRAW2, CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT, CRUSH_RULE_TAKE,
    CrushBucket, CrushMap, CrushRule, CrushRuleMask, CrushRuleStep,
)

RULE_TYPE_REPLICATED = 1
RULE_TYPE_ERASURE = 3

DEFAULT_TYPES = {0: "osd", 1: "host", 2: "chassis", 3: "rack", 4: "row",
                 5: "pdu", 6: "pod", 7: "room", 8: "datacenter",
                 9: "zone", 10: "region", 11: "root"}


class CrushWrapper:
    def __init__(self) -> None:
        self.crush = CrushMap()
        self.type_map: dict[int, str] = dict(DEFAULT_TYPES)
        self.name_map: dict[int, str] = {}     # item id -> name
        self.class_map: dict[int, int] = {}    # device id -> class id
        self.class_name: dict[int, str] = {}   # class id -> name
        self.rule_name_map: dict[int, str] = {}

    # -- lookups -----------------------------------------------------------
    def get_type_id(self, name: str) -> int:
        for tid, tname in self.type_map.items():
            if tname == name:
                return tid
        return -1

    def get_item_id(self, name: str) -> int | None:
        for iid, iname in self.name_map.items():
            if iname == name:
                return iid
        return None

    def get_item_name(self, item: int) -> str | None:
        return self.name_map.get(item)

    def get_rule_id(self, name: str) -> int:
        for rid, rname in self.rule_name_map.items():
            if rname == name:
                return rid
        return -1

    def class_id_or_create(self, name: str) -> int:
        for cid, cname in self.class_name.items():
            if cname == name:
                return cid
        cid = max(self.class_name, default=-1) + 1
        self.class_name[cid] = name
        return cid

    # -- build -------------------------------------------------------------
    def add_bucket(self, name: str, type_name: str,
                   alg: int = CRUSH_BUCKET_STRAW2, bucket_id: int | None = None
                   ) -> int:
        tid = self.get_type_id(type_name)
        if tid < 0:
            tid = max(self.type_map) + 1
            self.type_map[tid] = type_name
        b = CrushBucket(id=bucket_id if bucket_id is not None else 0,
                        type=tid, alg=alg)
        if bucket_id is None:
            b.id = 0  # let the map assign
        bid = self.crush.add_bucket(b)
        self.name_map[bid] = name
        return bid

    def insert_item(self, item: int, weight: float, name: str,
                    bucket_name: str, device_class: str | None = None) -> None:
        """Add a device (or sub-bucket) into a named bucket; weight is in
        'crush units' (converted to 16.16 fixed point)."""
        bid = self.get_item_id(bucket_name)
        assert bid is not None and bid < 0, f"no bucket {bucket_name}"
        bucket = self.crush.bucket(bid)
        w = int(weight * 0x10000)
        bucket.items.append(item)
        bucket.item_weights.append(w)
        bucket.weight += w
        self.name_map.setdefault(item, name)
        if item >= 0:
            self.crush.max_devices = max(self.crush.max_devices, item + 1)
            if device_class is not None:
                self.class_map[item] = self.class_id_or_create(device_class)
        # propagate weight up: find parents containing bid
        self._adjust_ancestors(bid, w)

    def _adjust_ancestors(self, child_id: int, delta: int) -> None:
        for b in self.crush.buckets:
            if b is None:
                continue
            for i, it in enumerate(b.items):
                if it == child_id:
                    b.item_weights[i] += delta
                    b.weight += delta
                    self._adjust_ancestors(b.id, delta)
                    return

    def adjust_item_weight(self, item: int, weight: float) -> int:
        """Set a device's weight everywhere it appears
        (ref: CrushWrapper.cc adjust_item_weightf_in_loc)."""
        w = int(weight * 0x10000)
        changed = 0
        for b in self.crush.buckets:
            if b is None:
                continue
            for i, it in enumerate(b.items):
                if it == item:
                    delta = w - b.item_weights[i]
                    b.item_weights[i] = w
                    b.weight += delta
                    self._adjust_ancestors(b.id, delta)
                    changed += 1
        return changed

    # -- rules -------------------------------------------------------------
    def add_simple_rule(self, name: str, root_name: str,
                        failure_domain: str, device_class: str = "",
                        mode: str = "firstn", rule_type: str = "replicated",
                        max_size: int | None = None) -> int:
        """ref: CrushWrapper.h:1199 add_simple_rule -> steps
        TAKE root / CHOOSELEAF_<mode> 0 type <domain> / EMIT.

        max_size widens the legacy rule-mask ceiling (default 10):
        find_rule filters on min_size <= pool.size <= max_size, so a
        wide EC pool (k+m > 10) MUST pass its chunk count or the rule
        silently never matches and every PG maps empty (ref:
        ErasureCode.cc create_rule passing get_chunk_count() as the
        rule's max_size)."""
        root = self.get_item_id(root_name)
        if root is None:
            raise ValueError(f"root item {root_name} does not exist")
        steps = [CrushRuleStep(CRUSH_RULE_TAKE, root, 0)]
        rtype = RULE_TYPE_ERASURE if rule_type == "erasure" else \
            RULE_TYPE_REPLICATED
        if failure_domain in ("", "osd"):
            op = CRUSH_RULE_CHOOSE_FIRSTN if mode == "firstn" else \
                CRUSH_RULE_CHOOSE_INDEP
            steps.append(CrushRuleStep(op, 0, 0))
        else:
            tid = self.get_type_id(failure_domain)
            if tid < 0:
                raise ValueError(f"unknown type {failure_domain}")
            op = CRUSH_RULE_CHOOSELEAF_FIRSTN if mode == "firstn" else \
                CRUSH_RULE_CHOOSELEAF_INDEP
            steps.append(CrushRuleStep(op, 0, tid))
        steps.append(CrushRuleStep(CRUSH_RULE_EMIT, 0, 0))
        mask = CrushRuleMask(ruleset=len(self.crush.rules), type=rtype)
        if max_size is not None:
            mask.max_size = max(max_size, mask.max_size)
        rule = CrushRule(steps=steps, mask=mask)
        self.crush.rules.append(rule)
        rid = len(self.crush.rules) - 1
        self.rule_name_map[rid] = name
        return rid

    # -- mapping -----------------------------------------------------------
    def do_rule(self, ruleno: int, x: int, numrep: int,
                weights: list[int] | None = None, choose_args=None
                ) -> list[int]:
        """ref: CrushWrapper.h:1568.  weights: per-device 16.16 in/out
        vector (default: all fully in)."""
        if weights is None:
            weights = [0x10000] * self.crush.max_devices
        return mapper.do_rule(self.crush, ruleno, x, numrep, weights,
                              choose_args)

    # -- convenience for tests/tools --------------------------------------
    @classmethod
    def build_flat(cls, n_osds: int, weight: float = 1.0,
                   osds_per_host: int = 1) -> "CrushWrapper":
        """default root -> hosts -> osds, like `osdmaptool
        --createsimple` / `crushtool --build` defaults."""
        cw = cls()
        cw.add_bucket("default", "root")
        for base in range(0, n_osds, osds_per_host):
            host = f"host{base // osds_per_host}"
            cw.add_bucket(host, "host")
            for i in range(base, min(base + osds_per_host, n_osds)):
                cw.insert_item(i, weight, f"osd.{i}", host)
            # attach host under root
            root = cw.crush.bucket(cw.get_item_id("default"))
            hid = cw.get_item_id(host)
            hb = cw.crush.bucket(hid)
            root.items.append(hid)
            root.item_weights.append(hb.weight)
            root.weight += hb.weight
        return cw


# ------------------------------------------------- wire registration
# (ref: CrushWrapper::encode — map + name/type/class tables)
def _register_wire() -> None:
    from ..msg.encoding import register_struct
    register_struct(CrushWrapper, version=1, compat=1, fields=(
        "crush", "type_map", "name_map", "class_map", "class_name",
        "rule_name_map"))


_register_wire()
