"""Swift frontend over the S3 bucket namespace
(ref: src/rgw/rgw_rest_swift.cc, rgw_swift_auth.cc TempAuth;
VERDICT r4 missing #4)."""
import base64
import json
import urllib.error
import urllib.request

import pytest

from ceph_tpu.auth import KeyRing
from ceph_tpu.rgw import RGWGateway
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def gw(cluster):
    g = RGWGateway(cluster.rados(), pool="swift")
    g.start()
    yield g
    g.shutdown()


def req(gw, method, path, data=None, headers=None):
    r = urllib.request.Request(f"http://127.0.0.1:{gw.port}{path}",
                               data=data, method=method,
                               headers=headers or {})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


def test_container_crud_and_listing(gw):
    assert req(gw, "PUT", "/swift/v1/c1")[0] == 201
    assert req(gw, "PUT", "/swift/v1/c1")[0] == 202   # idempotent
    st, hdrs, _ = req(gw, "HEAD", "/swift/v1/c1")
    assert st == 204 and hdrs["X-Container-Object-Count"] == "0"
    # account listing sees it (text + json)
    st, _, body = req(gw, "GET", "/swift/v1")
    assert b"c1\n" in body
    st, _, body = req(gw, "GET", "/swift/v1?format=json")
    names = [r["name"] for r in json.loads(body)]
    assert "c1" in names
    assert req(gw, "DELETE", "/swift/v1/c1")[0] == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "HEAD", "/swift/v1/c1")
    assert ei.value.code == 404


def test_object_crud_headers_and_listing(gw):
    req(gw, "PUT", "/swift/v1/c2")
    st, hdrs, _ = req(gw, "PUT", "/swift/v1/c2/a/b.txt", b"hello")
    assert st == 201
    assert '"' not in hdrs["ETag"]          # Swift: unquoted md5
    st, hdrs, body = req(gw, "GET", "/swift/v1/c2/a/b.txt")
    assert body == b"hello"
    assert hdrs["ETag"] == "5d41402abc4b2a76b9719d911017c592"
    st, hdrs, body = req(gw, "HEAD", "/swift/v1/c2/a/b.txt")
    assert st == 200 and hdrs["Content-Length"] == "5"
    assert body == b""
    req(gw, "PUT", "/swift/v1/c2/a/c.txt", b"xx")
    req(gw, "PUT", "/swift/v1/c2/z.txt", b"yy")
    # prefix + json listing
    st, _, body = req(gw, "GET", "/swift/v1/c2?prefix=a/&format=json")
    rows = json.loads(body)
    assert [r["name"] for r in rows] == ["a/b.txt", "a/c.txt"]
    assert rows[0]["bytes"] == 5 and rows[0]["hash"]
    # container stats
    _, hdrs, _ = req(gw, "HEAD", "/swift/v1/c2")
    assert hdrs["X-Container-Object-Count"] == "3"
    assert hdrs["X-Container-Bytes-Used"] == "9"
    # delete via swift; non-empty container refuses deletion first
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "DELETE", "/swift/v1/c2")
    assert ei.value.code == 409
    assert req(gw, "DELETE", "/swift/v1/c2/a/b.txt")[0] == 204
    with pytest.raises(urllib.error.HTTPError):
        req(gw, "GET", "/swift/v1/c2/a/b.txt")


def test_copy_from(gw):
    req(gw, "PUT", "/swift/v1/c3")
    req(gw, "PUT", "/swift/v1/c3/src", b"payload")
    st, _, _ = req(gw, "PUT", "/swift/v1/c3/dst", b"",
                   {"X-Copy-From": "/c3/src"})
    assert st == 201
    assert req(gw, "GET", "/swift/v1/c3/dst")[2] == b"payload"


def test_s3_and_swift_share_namespace(gw):
    """A bucket made over S3 is a Swift container and vice versa —
    the reference's single-namespace contract."""
    req(gw, "PUT", "/xproto")                       # S3 create
    req(gw, "PUT", "/xproto/via-s3", b"one")        # S3 PUT
    st, _, body = req(gw, "GET", "/swift/v1/xproto?format=json")
    assert [r["name"] for r in json.loads(body)] == ["via-s3"]
    assert req(gw, "GET", "/swift/v1/xproto/via-s3")[2] == b"one"
    req(gw, "PUT", "/swift/v1/xproto/via-swift", b"two")
    st, _, body = req(gw, "GET", "/xproto")         # S3 listing
    assert b"via-swift" in body
    assert req(gw, "GET", "/xproto/via-swift")[2] == b"two"


@pytest.fixture(scope="module")
def auth_gw(cluster):
    kr = KeyRing.generate(["client.swift"])
    g = RGWGateway(cluster.rados(), pool="swiftauth", keyring=kr)
    g.start()
    yield g, kr
    g.shutdown()


def test_tempauth_token_flow(auth_gw):
    gw, kr = auth_gw
    secret = kr.get("client.swift")
    key = secret if isinstance(secret, str) \
        else base64.b64encode(secret).decode()
    # wrong key -> 401
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "GET", "/auth/v1.0",
            headers={"X-Auth-User": "client.swift",
                     "X-Auth-Key": "bogus"})
    assert ei.value.code == 401
    st, hdrs, _ = req(gw, "GET", "/auth/v1.0",
                      headers={"X-Auth-User": "client.swift",
                               "X-Auth-Key": key})
    assert st == 204
    token = hdrs["X-Auth-Token"]
    assert hdrs["X-Storage-Url"].endswith("/swift/v1")
    # no token -> 401; with token -> works
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(gw, "PUT", "/swift/v1/ac")
    assert ei.value.code == 401
    tk = {"X-Auth-Token": token}
    assert req(gw, "PUT", "/swift/v1/ac", headers=tk)[0] == 201
    assert req(gw, "PUT", "/swift/v1/ac/o", b"d",
               headers=tk)[0] == 201
    assert req(gw, "GET", "/swift/v1/ac/o",
               headers=tk)[2] == b"d"


def test_token_valid_across_gateways(auth_gw, cluster):
    """Tokens live in RADOS, so a token issued by one gateway
    authenticates against another on the same pool."""
    gw, kr = auth_gw
    secret = kr.get("client.swift")
    key = secret if isinstance(secret, str) \
        else base64.b64encode(secret).decode()
    _, hdrs, _ = req(gw, "GET", "/auth/v1.0",
                     headers={"X-Auth-User": "client.swift",
                              "X-Auth-Key": key})
    token = hdrs["X-Auth-Token"]
    g2 = RGWGateway(cluster.rados(), pool="swiftauth", keyring=kr)
    g2.start()
    try:
        st, _, _ = req(g2, "PUT", "/swift/v1/xgw",
                       headers={"X-Auth-Token": token})
        assert st == 201
    finally:
        g2.shutdown()


def test_reserved_key_namespace_guarded(gw):
    """The index bookkeeping namespaces are not objects through Swift
    either: a PUT named .dlmeta on a zone member would wedge the
    shard's datalog head, and reads crash on the record's missing
    fields (regression: the guard lived only in the S3 router)."""
    req(gw, "PUT", "/swift/v1/resv")
    for key in (".dlmeta", ".dl.0000000000000001", ".upload.x"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(gw, "PUT", f"/swift/v1/resv/{key}", b"z")
        assert ei.value.code == 400, key
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(gw, "GET", f"/swift/v1/resv/{key}")
        assert ei.value.code == 404, key
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(gw, "DELETE", f"/swift/v1/resv/{key}")
        assert ei.value.code == 400, key
