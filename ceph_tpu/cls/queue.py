"""cls queue: an ordered, persistent FIFO on one RADOS object.

The reference's persistent bucket notifications ride a rados-backed
queue maintained by cls methods (ref: src/cls/queue/cls_queue.cc,
src/cls/2pc_queue — rgw_pubsub's persistent topics enqueue there and
a pusher drains it).  Here the queue is the object's omap: the header
carries [head, next) — the live contiguous sequence range — entries
live under zero-padded sequence keys, and enqueue allocates the
sequence inside the OSD, so concurrent producers (two gateways
publishing to one topic) can never collide or reorder.

Because the live range is contiguous, list and remove address entries
by GENERATED keys instead of scanning/sorting the whole backlog.
Honest limit: MethodContext exposes only a full omap_get, so the read
side still materializes the backlog dict once per list call (an
in-memory copy, no per-key decode/sort); remove is O(acked range).
A ranged omap read in the object store would finish the job.
"""
from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, cls_method

_SEQ_W = 16      # zero-pad width; omap lexical order == numeric order


def _seq_key(seq: int) -> str:
    return f"{seq:0{_SEQ_W}d}"


def _header(ctx) -> dict:
    raw = ctx.omap_get_header()
    hdr = json.loads(raw) if raw else {}
    hdr.setdefault("next", 0)
    hdr.setdefault("head", 0)
    return hdr


@cls_method("queue", "enqueue", CLS_METHOD_WR)
def enqueue(ctx, d):
    """Append entries; returns the first sequence assigned
    (ref: cls_queue_enqueue)."""
    hdr = _header(ctx)
    first = hdr["next"]
    kv = {}
    for i, data in enumerate(d["entries"]):
        kv[_seq_key(first + i)] = (data if isinstance(data, bytes)
                                   else str(data).encode())
    hdr["next"] = first + len(d["entries"])
    ctx.omap_set(kv)
    ctx.omap_set_header(json.dumps(hdr).encode())
    return {"first": first}


@cls_method("queue", "list", CLS_METHOD_RD)
def list_entries(ctx, d):
    """Entries from sequence max(`start`, head), up to `max` of them,
    in order (ref: cls_queue_list_entries)."""
    hdr = _header(ctx)
    start = max(int(d.get("start", 0)), hdr["head"])
    limit = int(d.get("max", 128))
    om = ctx.omap_get()
    out = []
    for seq in range(start, min(hdr["next"], start + limit)):
        data = om.get(_seq_key(seq))
        if data is not None:
            out.append({"seq": seq, "data": data})
    return {"entries": out, "next": hdr["next"], "head": hdr["head"]}


@cls_method("queue", "remove", CLS_METHOD_WR)
def remove(ctx, d):
    """Ack entries with sequence < `upto` (ref:
    cls_queue_remove_entries — the consumer trims what it delivered).
    Keys are generated from the contiguous [head, upto) range, never
    scanned."""
    hdr = _header(ctx)
    upto = min(int(d["upto"]), hdr["next"])
    dead = [_seq_key(s) for s in range(hdr["head"], upto)]
    if dead:
        ctx.omap_rmkeys(dead)
        hdr["head"] = upto
        ctx.omap_set_header(json.dumps(hdr).encode())
    return {"removed": len(dead)}
