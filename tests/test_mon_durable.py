"""Durable mon store on the KeyValueDB engine (ref: MonitorDBStore on
RocksDB, src/mon/MonitorDBStore.h — closing the 'mon store is ad-hoc'
gap from VERDICT r2)."""
import os
import signal
import subprocess
import sys
import time

import pytest

from ceph_tpu.kv import LogDB
from ceph_tpu.mon.store import MonitorStore, StoreTransaction


def test_kv_backed_store_persists(tmp_path):
    st = MonitorStore(LogDB(str(tmp_path / "mon")))
    tx = StoreTransaction()
    tx.put("osdmap", "last_committed", 7)
    tx.put("osdmap", "full_7", b"blob")
    tx.put("paxos", "3", b"v3")
    st.apply_transaction(tx)
    tx = StoreTransaction()
    tx.erase_range("paxos", 0, 3)
    st.apply_transaction(tx)
    st.db.close()
    st2 = MonitorStore(LogDB(str(tmp_path / "mon")))
    assert st2.get("osdmap", "last_committed") == 7
    assert st2.get("osdmap", "full_7") == b"blob"
    assert st2.get("paxos", "3") == b"v3"
    st2.db.close()


def test_mon_resumes_from_kv_store(tmp_path):
    """A mon constructed on a committed KV store resumes (no
    bootstrap): pools and epochs survive the restart."""
    from ceph_tpu.mon.monitor import Monitor, build_initial
    from ceph_tpu.msg.messenger import LocalNetwork

    net = LocalNetwork()
    m, w = build_initial(3, osds_per_host=1)
    store = MonitorStore(LogDB(str(tmp_path / "mon")))
    mon = Monitor(net, rank=0, initial_map=m, initial_wrapper=w,
                  store=store)
    mon.init()
    rc, outs, _ = mon.handle_command({
        "prefix": "osd pool create", "pool": "persist", "pg_num": 8})
    assert rc == 0, outs
    epoch = mon.osdmap.epoch
    mon.shutdown()
    store.db.close()

    net2 = LocalNetwork()
    store2 = MonitorStore(LogDB(str(tmp_path / "mon")))
    assert not store2.empty
    mon2 = Monitor(net2, rank=0, store=store2)
    mon2.init()
    assert mon2.osdmap.epoch == epoch
    assert "persist" in mon2.osdmap.pool_names.values()
    mon2.shutdown()
    store2.db.close()


@pytest.mark.slow
def test_multiprocess_mon_kill9_restart(tmp_path):
    """SIGKILL the mon process and restart it on its KV data dir: the
    cluster map (pools, epochs) survives and clients keep working."""
    import json
    from ceph_tpu.client import Rados
    from ceph_tpu.msg.tcp import TcpNet, pick_free_ports

    names = ["mon.0", "osd.0", "osd.1", "osd.2"]
    ports = pick_free_ports(len(names))
    addrs = {n: ["127.0.0.1", p] for n, p in zip(names, ports)}
    mpath = tmp_path / "mm.json"
    mpath.write_text(json.dumps(
        {"addrs": addrs, "mon_ranks": [0], "n_osd": 3,
         "osds_per_host": 1}))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.getcwd())

    def start_mon():
        return subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.tools.daemon_main",
             "mon", "--rank", "0", "--monmap", str(mpath),
             "--data-dir", str(tmp_path / "mon0")], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    procs = []
    r = None
    mon = start_mon()
    try:
        time.sleep(1.0)
        for i in range(3):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ceph_tpu.tools.daemon_main",
                 "osd", "--id", str(i), "--monmap", str(mpath)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
        r = Rados(TcpNet({k: tuple(v) for k, v in addrs.items()}),
                  name="client.980", op_timeout=10.0).connect(60.0)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sum(1 for o in range(3)
                   if r.objecter.osdmap.is_up(o)) == 3:
                break
            time.sleep(0.2)
        r.pool_create("mp", pg_num=8)
        io = r.open_ioctx("mp")
        io.write_full("o", b"pre-crash")
        mon.send_signal(signal.SIGKILL)
        mon.wait(timeout=10)
        mon = start_mon()
        # the restarted mon must still know the pool: a fresh client
        # learns the map from it and does IO
        deadline = time.monotonic() + 60
        ok = False
        while time.monotonic() < deadline:
            try:
                r2 = Rados(TcpNet({k: tuple(v)
                                   for k, v in addrs.items()}),
                           name="client.981",
                           op_timeout=8.0).connect(20.0)
                io2 = r2.open_ioctx("mp")
                if io2.read("o") == b"pre-crash":
                    io2.write_full("o2", b"post-crash")
                    ok = io2.read("o2") == b"post-crash"
                    r2.shutdown()
                    break
                r2.shutdown()
            except Exception:
                pass
            time.sleep(1.0)
        assert ok, "cluster state lost across mon kill -9"
    finally:
        if r is not None:
            r.shutdown()
        for p in procs + [mon]:
            p.terminate()
        for p in procs + [mon]:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
