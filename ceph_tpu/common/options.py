"""Typed configuration schema + live config with observers.

Models the reference's single typed option schema and its layered
apply/observe machinery (ref: src/common/options.cc — `Option(name,
type, level)` entries with defaults/min-max/enum/see_also/flags;
src/common/config.cc — md_config_t value application with registered
observers for runtime-updatable options).

The TPU build keeps the same shape — one declarative schema, values
resolved default < file < env < override — but the schema holds only
the options this framework actually consumes (the reference carries
1,501; a copy of that list would be dead weight, not parity).
"""
from __future__ import annotations

import enum
import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable


class OptionType(enum.Enum):
    UINT = "uint"
    INT = "int"
    STR = "str"
    FLOAT = "float"
    BOOL = "bool"
    SIZE = "size"       # accepts 4K/1M/2G suffixes
    SECS = "secs"


class OptionLevel(enum.Enum):
    BASIC = "basic"
    ADVANCED = "advanced"
    DEV = "dev"


_SIZE_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def _parse_size(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    for suf, mult in _SIZE_SUFFIX.items():
        for full in (suf + "i", suf):
            if s.endswith(full):
                return int(float(s[:-len(full)]) * mult)
    return int(float(s))


@dataclass
class Option:
    """One schema entry (ref: options.cc Option chain builders)."""
    name: str
    type: OptionType
    level: OptionLevel = OptionLevel.ADVANCED
    default: Any = None
    description: str = ""
    min: Any = None
    max: Any = None
    enum_values: tuple = ()
    see_also: tuple = ()
    runtime: bool = False   # may be changed on a live daemon

    def parse(self, value):
        t = self.type
        if t is OptionType.BOOL:
            if isinstance(value, bool):
                out = value
            else:
                s = str(value).strip().lower()
                if s in ("true", "yes", "on", "1"):
                    out = True
                elif s in ("false", "no", "off", "0"):
                    out = False
                else:
                    raise ValueError(f"{self.name}: bad bool {value!r}")
        elif t in (OptionType.UINT, OptionType.INT):
            out = int(value)
            if t is OptionType.UINT and out < 0:
                raise ValueError(f"{self.name}: negative uint {value!r}")
        elif t in (OptionType.FLOAT, OptionType.SECS):
            out = float(value)
        elif t is OptionType.SIZE:
            out = _parse_size(value)
        else:
            out = str(value)
        if self.min is not None and out < self.min:
            raise ValueError(f"{self.name}: {out} < min {self.min}")
        if self.max is not None and out > self.max:
            raise ValueError(f"{self.name}: {out} > max {self.max}")
        if self.enum_values and out not in self.enum_values:
            raise ValueError(
                f"{self.name}: {out!r} not in {self.enum_values}")
        return out


def _o(name, type_, default, level=OptionLevel.ADVANCED, desc="",
       min=None, max=None, enum=(), see_also=(), runtime=False):
    return Option(name=name, type=type_, level=level, default=default,
                  description=desc, min=min, max=max, enum_values=enum,
                  see_also=see_also, runtime=runtime)


T, L = OptionType, OptionLevel

# The live schema.  Names keep the reference's osd_/mon_/ms_ prefixes so
# operators recognize them; values are consumed by the TPU framework's
# own subsystems.
OPTIONS: dict[str, Option] = {opt.name: opt for opt in [
    # messenger / transport (ref: options.cc ms_* family)
    _o("ms_type", T.STR, "local", L.BASIC,
       "transport backend", enum=("local", "ici", "grpc")),
    _o("ms_inject_socket_failures", T.UINT, 0, L.DEV,
       "inject a transport failure every N messages (0=off)",
       runtime=True),
    _o("ms_dispatch_threads", T.UINT, 1, desc="dispatcher threads"),
    # osd daemon (ref: options.cc osd_* family)
    _o("osd_pool_default_size", T.UINT, 3, L.BASIC,
       "replica count for new replicated pools", runtime=True),
    _o("osd_pool_default_pg_num", T.UINT, 32, L.BASIC,
       "pg count for new pools"),
    _o("osd_heartbeat_interval", T.SECS, 6.0, desc="peer ping period",
       min=0.001, runtime=True),
    _o("osd_heartbeat_grace", T.SECS, 20.0,
       desc="missed-ping window before reporting a peer down",
       runtime=True),
    _o("osd_max_markdown_count", T.UINT, 5, L.DEV),
    _o("osd_recovery_max_active", T.UINT, 3, runtime=True,
       desc="concurrent recovery ops per OSD shard"),
    # mClock op-class QoS (ref: options.cc osd_mclock_scheduler_*)
    _o("osd_mclock_client_wgt", T.FLOAT, 10.0, L.ADVANCED,
       desc="client op-class weight", runtime=True),
    _o("osd_mclock_recovery_res", T.FLOAT, 20.0, L.ADVANCED,
       desc="recovery reservation, ops/s", runtime=True),
    _o("osd_mclock_recovery_wgt", T.FLOAT, 1.0, L.ADVANCED,
       desc="recovery op-class weight", runtime=True),
    _o("osd_mclock_recovery_lim", T.FLOAT, 200.0, L.ADVANCED,
       desc="recovery limit, ops/s (0 = unlimited)", runtime=True),
    _o("osd_mclock_scrub_wgt", T.FLOAT, 1.0, L.ADVANCED,
       desc="scrub op-class weight", runtime=True),
    _o("osd_mclock_scrub_lim", T.FLOAT, 100.0, L.ADVANCED,
       desc="scrub limit, ops/s (0 = unlimited)", runtime=True),
    # automatic scrub scheduling (ref: options.cc:3351
    # osd_scrub_min_interval / osd_deep_scrub_interval / osd_max_scrubs)
    _o("osd_scrub_auto", T.BOOL, True, L.ADVANCED, runtime=True,
       desc="schedule scrubs automatically from the heartbeat tick"),
    _o("osd_scrub_min_interval", T.FLOAT, 24 * 3600.0, L.ADVANCED,
       runtime=True,
       desc="seconds between shallow scrubs of a clean PG"),
    _o("osd_deep_scrub_interval", T.FLOAT, 7 * 24 * 3600.0,
       L.ADVANCED, runtime=True,
       desc="seconds between deep scrubs of a clean PG"),
    _o("osd_max_scrubs", T.UINT, 1, L.ADVANCED, runtime=True,
       desc="concurrent scrubs an OSD will drive or serve"),
    _o("osd_scrub_auto_repair", T.BOOL, True, L.ADVANCED,
       runtime=True,
       desc="repair inconsistencies found by scheduled deep scrubs "
            "(diverges from the reference default=false: BlueStore "
            "at-rest checksums make auto-repair the useful default "
            "here; the repair is re-verified in-round either way)"),
    # MDS beacons / failover (ref: options.cc mds_beacon_interval,
    # mds_beacon_grace, mds_standby_replay)
    _o("mds_beacon_interval", T.SECS, 4.0, L.ADVANCED, runtime=True,
       desc="seconds between MDS beacons to the monitor"),
    _o("mds_beacon_grace", T.SECS, 15.0, L.ADVANCED, runtime=True,
       desc="beacon silence before the monitor marks a rank failed "
            "and promotes a standby"),
    _o("mds_standby_replay", T.BOOL, False, L.ADVANCED,
       desc="standby daemons warm-tail their target rank's journal "
            "so takeover replay starts from a warm cursor"),
    # MDS balancer (ref: options.cc mds_bal_* family)
    _o("mds_bal_interval", T.FLOAT, 5.0, L.ADVANCED, runtime=True,
       desc="seconds between MDS balancer passes"),
    _o("mds_bal_min_load", T.FLOAT, 20.0, L.ADVANCED, runtime=True,
       desc="minimum decayed op load before a rank exports"),
    _o("mds_bal_ratio", T.FLOAT, 1.5, L.ADVANCED, runtime=True,
       desc="load multiple over the coldest rank that triggers an "
            "export"),
    _o("mds_bal_split_size", T.UINT, 10000, L.ADVANCED, runtime=True,
       desc="dentries per directory fragment before it splits "
            "(ref: options.cc mds_bal_split_size)"),
    _o("mds_bal_merge_size", T.UINT, 50, L.ADVANCED, runtime=True,
       desc="total dentries under which a fragmented directory "
            "merges back (ref: options.cc mds_bal_merge_size)"),
    _o("mon_target_pg_per_osd", T.UINT, 100, L.ADVANCED,
       desc="pg_autoscaler target PG replicas per OSD", runtime=True),
    _o("osd_ec_batch_stripes", T.UINT, 64, L.ADVANCED,
       desc="stripes batched per TPU encode dispatch"),
    # monitor (ref: options.cc mon_* family)
    _o("mon_osd_down_out_interval", T.SECS, 600.0, L.BASIC,
       desc="seconds a down OSD stays in before auto-out",
       runtime=True),
    _o("mon_osd_min_up_ratio", T.FLOAT, 0.3, L.ADVANCED,
       desc="refuse to mark OSDs down below this up fraction"),
    _o("mon_osd_report_timeout", T.SECS, 900.0),
    _o("mon_osd_min_down_reporters", T.UINT, 2, L.ADVANCED,
       desc="distinct failure reporters required to mark an OSD down",
       runtime=True),
    _o("mon_min_osdmap_epochs", T.UINT, 500, L.DEV),
    _o("osd_mon_report_interval", T.SECS, 5.0, L.ADVANCED,
       desc="seconds between pg-stat reports to the mon",
       runtime=True),
    _o("mon_osd_stale_report_grace", T.SECS, 60.0, L.ADVANCED,
       desc="flag osds whose last pg-stat report is older than this"),
    _o("mon_mgr_health_grace", T.SECS, 60.0, L.ADVANCED, runtime=True,
       desc="expire mgr-module health checks (RECENT_CRASH, "
            "DEVICE_HEALTH...) not re-reported within this window — a "
            "dead mgr's last report must not warn forever (0 = never "
            "expire)"),
    # mgr observability modules (ref: options mgr/crash
    # warn_recent_interval; mgr/insights health history)
    _o("mgr_crash_warn_recent_interval", T.SECS, 14 * 24 * 3600.0,
       L.ADVANCED, runtime=True,
       desc="unarchived crashes newer than this raise RECENT_CRASH "
            "(ref: mgr/crash warn_recent_interval)"),
    _o("mgr_insights_window", T.SECS, 3600.0, L.ADVANCED, runtime=True,
       desc="time window the insights report summarizes (health "
            "history, osdmap churn, cluster-log counts)"),
    _o("osd_debug_inject_crash_tick", T.BOOL, False, L.DEV,
       runtime=True,
       desc="inject an unhandled exception into the OSD's next "
            "heartbeat tick (crash-capture exerciser)"),
    # balancer (ref: OSDMap.cc calc_pg_upmaps knobs)
    _o("upmap_max_deviation", T.UINT, 5, L.BASIC, runtime=True,
       desc="target max PG-count deviation per OSD"),
    _o("upmap_max_optimizations", T.UINT, 10, runtime=True),
    # EC / bench
    _o("ec_tpu_backend", T.STR, "pallas", L.ADVANCED,
       enum=("xla", "pallas"), desc="bit-matmul kernel backend"),
    _o("ec_profile_default_k", T.UINT, 2, L.DEV),
    _o("ec_profile_default_m", T.UINT, 1, L.DEV),
    # object store
    _o("memstore_device_bytes", T.SIZE, 1 << 30, L.ADVANCED,
       desc="capacity reported by MemStore statfs"),
    _o("bluestore_device_bytes", T.SIZE, 0, L.ADVANCED,
       desc="provisioned capacity reported by BlueStore statfs; 0 = "
            "grow with the block file (never report used > total)"),
    # peering / recovery / backfill (ref: options.cc osd_min_pg_log_
    # entries, osd_max_pg_log_entries, osd_max_backfills,
    # osd_backfill_scan_max)
    _o("osd_min_pg_log_entries", T.UINT, 250, L.ADVANCED, runtime=True,
       desc="entries kept after a pg log trim"),
    _o("osd_max_pg_log_entries", T.UINT, 500, L.ADVANCED, runtime=True,
       desc="log length that triggers a trim"),
    _o("osd_max_backfills", T.UINT, 1, L.ADVANCED, runtime=True,
       desc="concurrent backfills an OSD serves (local or remote)"),
    _o("osd_backfill_scan_max", T.UINT, 512, L.ADVANCED, runtime=True,
       desc="objects per ranged backfill scan chunk"),
    # snaptrim (ref: options.cc osd_max_trimming_pgs,
    # osd_pg_max_concurrent_snap_trims, osd_snap_trim_sleep)
    _o("osd_max_trimming_pgs", T.UINT, 2, L.ADVANCED, runtime=True,
       desc="PGs an OSD will snap-trim concurrently; PGs past the "
            "cap report snaptrim_wait until a slot frees"),
    _o("osd_pg_max_concurrent_snap_trims", T.UINT, 2, L.ADVANCED,
       runtime=True,
       desc="clone trims in flight per trimming PG"),
    _o("osd_snap_trim_sleep", T.SECS, 0.0, L.ADVANCED, runtime=True,
       desc="seconds between clone trims (throttles trim against "
            "client IO; 0 = unthrottled)"),
    # client-side object cache (ref: options.cc client_oc*, rbd_cache*)
    _o("client_oc", T.BOOL, True, L.ADVANCED,
       desc="cephfs write-back object cache under CAP_EXCL/CAP_CACHE"),
    _o("client_oc_size", T.SIZE, 32 << 20, L.ADVANCED),
    _o("client_oc_max_dirty", T.SIZE, 8 << 20, L.ADVANCED),
    _o("rbd_cache", T.BOOL, True, L.ADVANCED,
       desc="librbd write-back object cache (flushed on lock "
            "release, snap create, close)"),
    _o("rbd_cache_size", T.SIZE, 32 << 20, L.ADVANCED),
    _o("rbd_cache_max_dirty", T.SIZE, 8 << 20, L.ADVANCED),
    # fault injection (ref: options.cc:774 heartbeat_inject_failure,
    # :3565 osd_debug_inject_dispatch_delay)
    _o("heartbeat_inject_failure", T.SECS, 0.0, L.DEV, runtime=True),
    _o("lockdep", T.BOOL, False, L.DEV,
       desc="lock-order cycle detection on instrumented locks; read "
            "at lock construction, so set it before daemons start "
            "(ref: src/common/lockdep.cc)"),
    _o("racecheck", T.BOOL, False, L.DEV,
       desc="Eraser-style lockset data-race sanitizer on classes "
            "marked shared_state()/RaceTracked: attribute accesses "
            "intersect per-(object, attr) candidate locksets against "
            "the thread's held DebugLocks and raise RaceError when "
            "the intersection empties; requires `lockdep` (the held "
            "set comes from it) and is read when "
            "racecheck.enable_if_configured() runs "
            "(see common/racecheck.py)",
       see_also=("lockdep",)),
    _o("jaxguard", T.BOOL, False, L.DEV,
       desc="device-contract sanitizer: count jit compilations per "
            "callsite (fail on same-signature recompiles) and arm "
            "jax.transfer_guard around the EC/placement dispatch; "
            "read when jaxguard.enable_if_configured() runs, so set "
            "it before jit wrappers are built (see common/jaxguard.py)"),
    _o("errcheck", T.BOOL, False, L.DEV,
       desc="error-path coverage sanitizer: an import hook recompiles "
            "instrumented packages with a counter bump at the top of "
            "every except handler, so coverage_report() can list the "
            "handlers no test or chaos run has ever entered; read "
            "when errcheck.enable_if_configured() runs — arm it "
            "before the modules you want counted import (see "
            "common/errcheck.py)"),
    _o("osd_debug_inject_dispatch_delay_probability", T.FLOAT, 0.0,
       L.DEV, min=0.0, max=1.0, runtime=True),
    _o("objectstore_debug_inject_read_err", T.BOOL, False, L.DEV,
       runtime=True,
       desc="make MemStore reads of marked objects fail with EIO"),
    # op tracking / slow-op health (ref: options.cc
    # osd_op_complaint_time, osd_op_history_size)
    _o("osd_op_complaint_time", T.SECS, 30.0, L.ADVANCED, runtime=True,
       desc="in-flight op age that counts as slow: feeds each "
            "daemon's dump_blocked_ops and the cluster SLOW_OPS "
            "health warning"),
    _o("osd_op_history_size", T.UINT, 20, L.ADVANCED,
       desc="completed ops kept for dump_historic_ops (and the slow "
            "subset for dump_historic_slow_ops)"),
    # telemetry upload (ref: the telemetry module's endpoint url)
    _o("mgr_telemetry_url", T.STR, "", L.ADVANCED, runtime=True,
       desc="sink the compiled telemetry report posts to on each "
            "mgr tick: file://<path> appends JSON lines, "
            "http(s)://... POSTs; empty = compile only, never send"),
    # logging
    _o("blkin_trace_all", T.BOOL, False, L.DEV, runtime=True,
       desc="trace every client op with distributed spans"),
    _o("log_level", T.UINT, 1, L.BASIC, runtime=True,
       desc="global default debug level", max=30),
]}


class Config:
    """Resolved configuration with observer support
    (ref: src/common/config.cc md_config_t::set_val + observers)."""

    def __init__(self, schema: dict[str, Option] | None = None,
                 values: dict[str, Any] | None = None):
        self.schema = dict(schema or OPTIONS)
        self._values: dict[str, Any] = {}
        self._observers: dict[str, list[Callable[[str, Any], None]]] = {}
        # env source: CEPH_TPU_<NAME>=value (ref env layer of config.cc)
        for name in self.schema:
            env = os.environ.get("CEPH_TPU_" + name.upper())
            if env is not None:
                self._values[name] = self.schema[name].parse(env)
        for k, v in (values or {}).items():
            self.set(k, v)

    def get(self, name: str):
        opt = self.schema[name]
        return self._values.get(name, opt.default)

    def __getitem__(self, name: str):
        return self.get(name)

    def set(self, name: str, value) -> None:
        opt = self.schema.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        parsed = opt.parse(value)
        old = self.get(name)
        self._values[name] = parsed
        if parsed != old:
            for cb in self._observers.get(name, []):
                cb(name, parsed)

    def observe(self, name: str, cb: Callable[[str, Any], None]) -> None:
        if name not in self.schema:
            raise KeyError(f"unknown option {name!r}")
        self._observers.setdefault(name, []).append(cb)

    def load_file(self, path: str) -> None:
        """JSON config file — the ceph.conf layer."""
        with open(path) as f:
            for k, v in json.load(f).items():
                self.set(k, v)

    def dump(self, level: OptionLevel | None = None) -> dict:
        """`config show` equivalent."""
        out = {}
        for name, opt in sorted(self.schema.items()):
            if level is not None and opt.level != level:
                continue
            out[name] = self.get(name)
        return out

    def diff(self) -> dict:
        """`config diff` — only values changed from schema defaults."""
        return {k: v for k, v in sorted(self._values.items())
                if v != self.schema[k].default}


_global_config: Config | None = None


def global_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config()
        from .log import set_default_level
        _global_config.observe(
            "log_level", lambda k, v: set_default_level(int(v)))
        set_default_level(int(_global_config["log_level"]))
    return _global_config
