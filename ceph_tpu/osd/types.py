"""OSD-layer core types: placement groups and pools.

Python rendering of the reference's osd_types (ref: src/osd/osd_types.h,
osd_types.cc) limited to the placement math the framework needs:
pg_t, pg_pool_t with pg/pgp masks, the stable-mod seed folding
(src/include/rados.h:86), the object-name string hashes
(src/common/ceph_hash.cc), and pps seed derivation
(pg_pool_t::raw_pg_to_pps, src/osd/osd_types.cc:1650).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..crush.hashes import hash32_2

# pool types (osd_types.h pg_pool_t::TYPE_*)
POOL_TYPE_REPLICATED = 1
POOL_TYPE_ERASURE = 3

# pg_pool_t flags (osd_types.h)
FLAG_HASHPSPOOL = 1 << 0

# object hash algorithms (src/include/rados.h CEPH_STR_HASH_*)
CEPH_STR_HASH_LINUX = 1
CEPH_STR_HASH_RJENKINS = 2

_U32 = 0xFFFFFFFF


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo for non-power-of-2 pg counts (rados.h:86-92)."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def cbits(v: int) -> int:
    """Number of significant bits (intarith.h cbits)."""
    return v.bit_length()


def _mix32(a: int, b: int, c: int) -> tuple[int, int, int]:
    # rjenkins mix on plain ints (ceph_hash.cc mix macro)
    a = (a - b - c) & _U32; a ^= c >> 13
    b = (b - c - a) & _U32; b ^= (a << 8) & _U32
    c = (c - a - b) & _U32; c ^= b >> 13
    a = (a - b - c) & _U32; a ^= c >> 12
    b = (b - c - a) & _U32; b ^= (a << 16) & _U32
    c = (c - a - b) & _U32; c ^= b >> 5
    a = (a - b - c) & _U32; a ^= c >> 3
    b = (b - c - a) & _U32; b ^= (a << 10) & _U32
    c = (c - a - b) & _U32; c ^= b >> 15
    return a, b, c


def ceph_str_hash_rjenkins(data: bytes) -> int:
    """Robert Jenkins string hash (ceph_hash.cc:22-78)."""
    length = len(data)
    a = 0x9E3779B9
    b = a
    c = 0
    k = 0
    ln = length
    while ln >= 12:
        a = (a + (data[k] | data[k + 1] << 8 | data[k + 2] << 16 |
                  data[k + 3] << 24)) & _U32
        b = (b + (data[k + 4] | data[k + 5] << 8 | data[k + 6] << 16 |
                  data[k + 7] << 24)) & _U32
        c = (c + (data[k + 8] | data[k + 9] << 8 | data[k + 10] << 16 |
                  data[k + 11] << 24)) & _U32
        a, b, c = _mix32(a, b, c)
        k += 12
        ln -= 12
    c = (c + length) & _U32
    # the last 11 bytes; all cases fall through
    if ln >= 11:
        c = (c + (data[k + 10] << 24)) & _U32
    if ln >= 10:
        c = (c + (data[k + 9] << 16)) & _U32
    if ln >= 9:
        c = (c + (data[k + 8] << 8)) & _U32
    if ln >= 8:
        b = (b + (data[k + 7] << 24)) & _U32
    if ln >= 7:
        b = (b + (data[k + 6] << 16)) & _U32
    if ln >= 6:
        b = (b + (data[k + 5] << 8)) & _U32
    if ln >= 5:
        b = (b + data[k + 4]) & _U32
    if ln >= 4:
        a = (a + (data[k + 3] << 24)) & _U32
    if ln >= 3:
        a = (a + (data[k + 2] << 16)) & _U32
    if ln >= 2:
        a = (a + (data[k + 1] << 8)) & _U32
    if ln >= 1:
        a = (a + data[k]) & _U32
    _, _, c = _mix32(a, b, c)
    return c


def ceph_str_hash_linux(data: bytes) -> int:
    """Linux dcache hash (ceph_hash.cc:82-92)."""
    h = 0
    for ch in data:
        h = ((h + (ch << 4) + (ch >> 4)) * 11) & _U32
    return h


def ceph_str_hash(hash_type: int, data: bytes) -> int:
    if hash_type == CEPH_STR_HASH_RJENKINS:
        return ceph_str_hash_rjenkins(data)
    if hash_type == CEPH_STR_HASH_LINUX:
        return ceph_str_hash_linux(data)
    raise ValueError(f"unknown str hash {hash_type}")


@dataclass(frozen=True, order=True)
class PG:
    """pg_t: (pool id, placement seed) (osd_types.h struct pg_t);
    ordered like the reference's operator< (pool, then seed)."""
    pool: int
    ps: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.ps:x}"


@dataclass
class PGPool:
    """pg_pool_t (osd_types.h:1261): the placement-relevant subset."""
    type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    crush_rule: int = 0
    object_hash: int = CEPH_STR_HASH_RJENKINS
    pg_num: int = 64
    pgp_num: int = 64
    flags: int = FLAG_HASHPSPOOL
    erasure_code_profile: str = ""
    # pool snapshots (ref: pg_pool_t::snap_seq/snaps/removed_snaps,
    # osd_types.h:1331-1340): snap_seq is the newest snapid; snaps
    # maps live snapid -> name; removed_snaps keeps deleted ids out of
    # every future SnapContext (a lagging client must not resurrect a
    # deleted snapshot through the snapc union)
    snap_seq: int = 0
    snaps: dict = field(default_factory=dict)
    removed_snaps: list = field(default_factory=list)  # JSON-safe ids
    # derived
    pg_num_mask: int = field(default=0, repr=False)
    pgp_num_mask: int = field(default=0, repr=False)

    def __post_init__(self) -> None:
        self.calc_pg_masks()

    def calc_pg_masks(self) -> None:
        """osd_types.cc:1468-1472."""
        self.pg_num_mask = (1 << cbits(self.pg_num - 1)) - 1
        self.pgp_num_mask = (1 << cbits(self.pgp_num - 1)) - 1

    def can_shift_osds(self) -> bool:
        """Replicated pools compact holes; EC pools are positional
        (osd_types.h:1581-1590)."""
        return self.type == POOL_TYPE_REPLICATED

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE

    def is_replicated(self) -> bool:
        return self.type == POOL_TYPE_REPLICATED

    def hash_key(self, key: str, nspace: str = "") -> int:
        """osd_types.cc:1618-1629 (ns + 0x1f separator + key)."""
        if not nspace:
            return ceph_str_hash(self.object_hash, key.encode())
        buf = nspace.encode() + b"\x1f" + key.encode()
        return ceph_str_hash(self.object_hash, buf)

    def raw_pg_to_pg(self, pg: PG) -> PG:
        """Fold full-precision ps into [0, pg_num)
        (osd_types.cc:1639-1643)."""
        return PG(pg.pool, ceph_stable_mod(pg.ps, self.pg_num,
                                           self.pg_num_mask))

    def raw_pg_to_pps(self, pg: PG) -> int:
        """Placement seed: mix pool id so pools don't overlap
        (osd_types.cc:1650-1666)."""
        if self.flags & FLAG_HASHPSPOOL:
            return int(hash32_2(
                ceph_stable_mod(pg.ps, self.pgp_num, self.pgp_num_mask),
                pg.pool))
        return ceph_stable_mod(pg.ps, self.pgp_num,
                               self.pgp_num_mask) + pg.pool

    def raw_pg_to_pps_batch(self, pss: np.ndarray, pool_id: int) -> np.ndarray:
        """Vectorized raw_pg_to_pps over many placement seeds."""
        pss = np.asarray(pss, dtype=np.int64)
        masked = pss & self.pgp_num_mask
        folded = np.where(masked < self.pgp_num, masked,
                          pss & (self.pgp_num_mask >> 1))
        if self.flags & FLAG_HASHPSPOOL:
            return hash32_2(folded, np.full_like(folded, pool_id)) \
                .astype(np.int64)
        return folded + pool_id


# wire registration (ref: pg_t / pg_pool_t encode in osd_types.cc)
from ..msg.encoding import register_struct as _reg  # noqa: E402

_reg(PG, version=1, compat=1)
_reg(PGPool, version=1, compat=1)
