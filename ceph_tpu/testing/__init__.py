"""Test/QA harnesses (the qa/ tier analogues)."""
from .cluster import MiniCluster

__all__ = ["MiniCluster"]
