"""FaultPlane: deterministic link-level network fault injection.

The chaos layer under the messenger (ref: the reference's
ms_inject_socket_failures / ms_inject_delay_* options in
src/common/options.cc, and the qa netem/iptables partition helpers in
qa/tasks/ceph_manager.py) collapsed into one seeded, per-link rule
table:

* **drop** — per-message drop probability, so burst loss is
  expressible (the old global 1-in-N modulus could never drop two
  consecutive messages);
* **partition** — black-hole a direction entirely.  Rules are
  directional, so A->B blocked while B->A flows (the asymmetric case
  that breaks naive quorum logic) is one rule, not a special mode;
* **delay / jitter** — hold delivery for a fixed + uniformly-jittered
  interval in the plane's clock domain (simulated time under a
  MiniCluster tick harness, wall-clock otherwise);
* **reorder** — buffer a window of N messages per link and release
  them shuffled;
* **dup** — deliver a message twice (same seq: receivers must
  tolerate the replay like a TCP retransmit).

Effect precedence per message: partition > drop > reorder > delay >
dup.

Determinism: every random draw comes from a per-link stream seeded
from (master seed, src, dst), and every decision is folded into a
per-link hash chain.  ``digest()`` combines the chains sorted by link
name, so the digest is reproducible from the seed whenever each
link's own message sequence is reproducible — concurrent traffic on
*other* links cannot perturb it.  A failing schedule therefore
replays byte-identically from its seed in a pump-mode harness.

The rule table is shared between injector threads (tests, the
ChaosRunner) and every routing thread, so it is racecheck-
instrumented: all access holds ``self._lock``.
"""
from __future__ import annotations

import hashlib
import itertools
import random
import time
from collections import Counter, deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..common.lockdep import make_lock
from ..common.racecheck import shared_state

#: reorder buffers older than this (in the plane's clock domain) are
#: released even if the window never filled — a partial window must
#: not strand messages forever
REORDER_LATCH_S = 0.25

#: fault-log ring size (debugging aid; the digest is unbounded-exact)
LOG_RING = 4096


def _pat_match(pat: str, name: str) -> bool:
    """Entity pattern: exact name, "osd.*" prefix wildcard, or "*"."""
    if pat == "*" or pat == name:
        return True
    if pat.endswith("*"):
        return name.startswith(pat[:-1])
    return False


@dataclass
class LinkRule:
    """One directional fault rule (src pattern -> dst pattern)."""
    src: str
    dst: str
    drop: float = 0.0          # drop probability in [0, 1]
    partition: bool = False    # black-hole this direction
    delay: float = 0.0         # fixed delivery delay (seconds)
    jitter: float = 0.0        # extra uniform delay in [0, jitter)
    dup: float = 0.0           # duplication probability
    reorder: int = 0           # window size (0 = off)
    #: drops signal a socket reset to both sides (the legacy
    #: ms_inject_socket_failures behavior); partitions default to
    #: silence — detection must come from timeouts, like real netsplits
    reset: bool = False
    #: restrict to these Message type_names ("" tuple = all traffic)
    types: tuple = ()
    rule_id: int = 0

    def matches(self, src: str, dst: str, type_name: str) -> bool:
        if self.types and type_name not in self.types:
            return False
        return _pat_match(self.src, src) and _pat_match(self.dst, dst)


class Effects:
    """The decided fate of one message."""
    __slots__ = ("verdict", "dropped", "reset", "delay", "dup",
                 "reorder_key")

    def __init__(self, verdict: str, dropped: bool = False,
                 reset: bool = False, delay: float = 0.0,
                 dup: bool = False, reorder_key=None):
        self.verdict = verdict
        self.dropped = dropped
        self.reset = reset
        self.delay = delay
        self.dup = dup
        self.reorder_key = reorder_key


@shared_state(only=("_rules",), mutating=("_rules",))
class FaultPlane:
    """Seeded per-link fault rule table + held-message buffers."""

    def __init__(self, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.seed = seed
        self.clock = clock
        self._lock = make_lock("msg.faultplane")
        self._rules: dict[int, LinkRule] = {}
        self._ids = itertools.count(1)
        self._hold_seq = itertools.count(1)
        #: delayed messages: [release_time, seq, src, dst, msg]
        self._held: list[list] = []
        #: reorder buffers: (rule_id, src, dst) ->
        #: {"deadline": t, "msgs": [(src, dst, msg), ...]}
        self._reorder: dict[tuple, dict] = {}
        #: per-link RNG streams + decision indexes + digest chains
        self._rngs: dict[tuple[str, str], random.Random] = {}
        self._chain: dict[tuple[str, str], "hashlib._Hash"] = {}
        self._chain_idx: dict[tuple[str, str], int] = {}
        self.counts: Counter = Counter()
        self.log: deque = deque(maxlen=LOG_RING)
        #: endpoint-string -> entity aliases for non-messenger
        #: transports (RGW peer HTTP) consulting the same rule table
        self._aliases: dict[str, str] = {}
        #: default delivery callback for flush() callers that have
        #: none (set by LocalNetwork.attach_faults)
        self.deliver_cb: Optional[Callable] = None

    # ------------------------------------------------------- rule admin
    def add_rule(self, src: str, dst: str, **kw) -> int:
        """Install one directional rule; returns its id."""
        rid = next(self._ids)
        rule = LinkRule(src=src, dst=dst, rule_id=rid, **kw)
        if not 0.0 <= rule.drop <= 1.0 or not 0.0 <= rule.dup <= 1.0:
            raise ValueError(f"probability out of [0,1]: {rule}")
        with self._lock:
            self._rules[rid] = rule
        return rid

    def remove_rule(self, rid: int) -> None:
        with self._lock:
            self._rules.pop(rid, None)
            # orphaned reorder buffers release on the next flush
            for key, buf in self._reorder.items():
                if key[0] == rid:
                    buf["deadline"] = 0.0

    def heal(self, ids=None) -> None:
        """Remove the given rules (default: all) and mark every held
        buffer for release on the next flush."""
        with self._lock:
            if ids is None:
                self._rules.clear()
            else:
                for rid in ids:
                    self._rules.pop(rid, None)
            for h in self._held:
                h[0] = 0.0
            for buf in self._reorder.values():
                buf["deadline"] = 0.0
        self.flush()

    def clear(self) -> None:
        self.heal()

    def partition(self, a, b, symmetric: bool = True, **kw) -> list[int]:
        """Block a->b (and b->a when symmetric) for every pattern
        pair; returns the installed rule ids for a targeted heal."""
        a = [a] if isinstance(a, str) else list(a)
        b = [b] if isinstance(b, str) else list(b)
        ids = []
        for s in a:
            for d in b:
                ids.append(self.add_rule(s, d, partition=True, **kw))
                if symmetric:
                    ids.append(self.add_rule(d, s, partition=True, **kw))
        return ids

    def isolate(self, entity: str, **kw) -> list[int]:
        """Cut an entity off from everyone, both directions."""
        return self.partition([entity], ["*"], **kw)

    def rules(self) -> list[LinkRule]:
        with self._lock:
            return [self._rules[k] for k in sorted(self._rules)]

    # ------------------------------------------------------ determinism
    def _link_rng(self, src: str, dst: str) -> random.Random:
        rng = self._rngs.get((src, dst))
        if rng is None:
            # seeding from a string is stable across processes
            # (random.seed version 2), unlike hash() which is salted
            rng = random.Random(f"{self.seed}|{src}|{dst}")
            self._rngs[(src, dst)] = rng
        return rng

    def _record(self, src: str, dst: str, verdict: str,
                type_name: str, extra: str = "") -> None:
        link = (src, dst)
        h = self._chain.get(link)
        if h is None:
            h = self._chain[link] = hashlib.sha256()
        i = self._chain_idx.get(link, 0)
        self._chain_idx[link] = i + 1
        h.update(f"{i}|{verdict}|{type_name}|{extra}\n".encode())
        self.counts[verdict] += 1
        self.log.append((src, dst, verdict, type_name, extra))

    def digest(self) -> str:
        """Order-insensitive across links, exact within each link:
        the reproducibility fingerprint of this run's fault sequence."""
        with self._lock:
            agg = hashlib.sha256()
            for (s, d), h in sorted(self._chain.items()):
                agg.update(f"{s}>{d}:{h.hexdigest()}\n".encode())
            return agg.hexdigest()

    # --------------------------------------------------------- deciding
    def decide(self, src: str, dst: str, type_name: str) -> Effects:
        """Roll this message's fate.  Pure decision — the caller
        applies the effects (LocalNetwork via intercept(), the TCP
        messenger inline)."""
        with self._lock:
            matched = [self._rules[k] for k in sorted(self._rules)
                       if self._rules[k].matches(src, dst, type_name)]
            if not matched:
                return Effects("deliver")
            rng = self._link_rng(src, dst)
            for r in matched:
                if r.partition:
                    self._record(src, dst, "partition", type_name)
                    return Effects("partition", dropped=True,
                                   reset=r.reset)
            for r in matched:
                if r.drop > 0.0 and rng.random() < r.drop:
                    self._record(src, dst, "drop", type_name)
                    return Effects("drop", dropped=True, reset=r.reset)
            for r in matched:
                if r.reorder > 0:
                    self._record(src, dst, "reorder", type_name)
                    return Effects("reorder",
                                   reorder_key=(r.rule_id, src, dst))
            delay = 0.0
            for r in matched:
                if r.delay > 0.0 or r.jitter > 0.0:
                    delay += r.delay
                    if r.jitter > 0.0:
                        delay += rng.random() * r.jitter
            if delay > 0.0:
                self._record(src, dst, "delay", type_name,
                             f"{delay:.6f}")
                return Effects("delay", delay=delay)
            for r in matched:
                if r.dup > 0.0 and rng.random() < r.dup:
                    self._record(src, dst, "dup", type_name)
                    return Effects("dup", dup=True)
            self._record(src, dst, "pass", type_name)
            return Effects("deliver")

    # ------------------------------------------------------ intercepting
    def intercept(self, src: str, dst: str, msg,
                  deliver: Callable[[str, str, object], None]) -> Effects:
        """Full-service path for queue transports: flush due held
        traffic, decide this message's fate, and apply it through
        `deliver(src, dst, msg)`.  Returns the Effects so the caller
        can do its drop bookkeeping (ring, counters, resets)."""
        self.flush(deliver)
        eff = self.decide(src, dst, msg.type_name)
        if eff.dropped:
            return eff
        if eff.reorder_key is not None:
            release = self._reorder_put(eff.reorder_key, src, dst, msg)
            for s, d, m in release:
                deliver(s, d, m)
            return eff
        if eff.delay > 0.0:
            with self._lock:
                self._held.append([self.clock() + eff.delay,
                                   next(self._hold_seq), src, dst, msg])
            return eff
        deliver(src, dst, msg)
        if eff.dup:
            deliver(src, dst, msg)
        return eff

    def _reorder_put(self, key, src, dst, msg) -> list[tuple]:
        """Buffer into the rule's window; a full window releases
        shuffled (the shuffle order rides the digest)."""
        with self._lock:
            buf = self._reorder.get(key)
            if buf is None:
                buf = self._reorder[key] = {
                    "deadline": self.clock() + REORDER_LATCH_S,
                    "msgs": []}
            buf["msgs"].append((src, dst, msg))
            rule = self._rules.get(key[0])
            window = rule.reorder if rule is not None else 1
            if len(buf["msgs"]) < window:
                return []
            del self._reorder[key]
            rng = self._link_rng(key[1], key[2])
            order = list(range(len(buf["msgs"])))
            rng.shuffle(order)
            self._record(key[1], key[2], "shuffle", "-",
                         ",".join(map(str, order)))
            return [buf["msgs"][i] for i in order]

    def flush(self, deliver: Callable | None = None,
              force: bool = False) -> int:
        """Release held traffic whose time has come (or all of it,
        with force=True); returns the number of messages released."""
        deliver = deliver or self.deliver_cb
        now = self.clock()
        out: list[tuple] = []
        with self._lock:
            due, keep = [], []
            for h in self._held:
                (due if force or h[0] <= now else keep).append(h)
            if due:
                self._held = keep
                due.sort(key=lambda h: (h[0], h[1]))
                out.extend((h[2], h[3], h[4]) for h in due)
            for key in list(self._reorder):
                buf = self._reorder[key]
                if force or buf["deadline"] <= now:
                    del self._reorder[key]
                    rng = self._link_rng(key[1], key[2])
                    order = list(range(len(buf["msgs"])))
                    rng.shuffle(order)
                    self._record(key[1], key[2], "shuffle", "-",
                                 ",".join(map(str, order)))
                    out.extend(buf["msgs"][i] for i in order)
        if deliver is not None:
            for s, d, m in out:
                deliver(s, d, m)
        return len(out)

    def pending(self) -> int:
        """Messages currently held for delay/reorder."""
        with self._lock:
            return len(self._held) + sum(
                len(b["msgs"]) for b in self._reorder.values())

    # --------------------------------------- non-messenger transports
    def bind_alias(self, key: str, entity: str) -> None:
        """Map an endpoint string (an RGW peer URL) to an entity name
        so HTTP-side checks hit the same rule table."""
        with self._lock:
            self._aliases[key] = entity

    def check_http(self, src: str, endpoint: str) -> None:
        """Send-side gate for HTTP transports: raises ConnectionError
        when the (aliased) link is partitioned or the drop roll says
        lose it.  Delay/reorder do not apply to request/response
        transports."""
        with self._lock:
            dst = self._aliases.get(endpoint, endpoint)
        eff = self.decide(src, dst, "http")
        if eff.dropped:
            raise ConnectionError(
                f"faultplane: {src} -> {dst} {eff.verdict}")
