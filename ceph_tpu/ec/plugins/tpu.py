"""The `tpu` erasure-code plugin — the north-star component.

A JAX/Pallas GF(2^8) Reed-Solomon/Cauchy code behind the exact
ErasureCodeInterface boundary (ref: src/erasure-code/ErasureCodeInterface.h).
The GF matmul hot loop runs on the TPU MXU as a bit-plane GF(2) matmul
(see ceph_tpu.ec.kernels.bitmatmul); matrices, chunk sizes and padding follow
the isa/jerasure plugins so chunks are byte-identical to the CPU reference.

Techniques (profile `technique=`):
  reed_sol_van  - ISA-L gf_gen_rs_matrix (default; parity with isa plugin)
  cauchy        - ISA-L gf_gen_cauchy1_matrix
  jerasure_reed_sol_van, reed_sol_r6_op, cauchy_orig, cauchy_good
                - jerasure-compatible matrices (parity with jerasure plugin)

Beyond the interface, the plugin exposes a batched device-resident path
(`encode_batch`/`decode_batch`) used by the benchmark and the EC backend:
many stripes are encoded per dispatch so the host<->device boundary stays
off the hot path.
"""
from __future__ import annotations

import numpy as np

from .. import gf
from ..interface import ErasureCodeProfile, ErasureCodeError, to_int, \
    sanity_check_k_m
from ..matrix_code import MatrixErasureCode, make_decode_matrix, \
    erasure_signature
from ..registry import ErasureCodePlugin

EC_TPU_DEFAULT_ALIGNMENT = 32  # match isa (EC_ISA_ADDRESS_ALIGNMENT)


def _matrices(technique: str, k: int, m: int) -> np.ndarray:
    eye = np.eye(k, dtype=np.uint8)
    if technique == "reed_sol_van":
        return gf.isa_rs_matrix(k, m)
    if technique == "cauchy":
        return gf.isa_cauchy_matrix(k, m)
    if technique == "jerasure_reed_sol_van":
        return np.vstack([eye, gf.jerasure_vandermonde_coding_matrix(k, m)])
    if technique == "reed_sol_r6_op":
        if m != 2:
            raise ErasureCodeError("reed_sol_r6_op requires m=2")
        return np.vstack([eye, gf.jerasure_r6_coding_matrix(k)])
    if technique == "cauchy_orig":
        return np.vstack([eye, gf.cauchy_original_coding_matrix(k, m)])
    if technique == "cauchy_good":
        return np.vstack([eye, gf.cauchy_good_coding_matrix(k, m)])
    raise ErasureCodeError(f"ENOENT: tpu technique={technique!r} not supported")


class ErasureCodeTpu(MatrixErasureCode):
    DEFAULT_K = "8"
    DEFAULT_M = "4"

    #: decode-kernel LRU capacity in matrix-WIDTH units (byte columns):
    #: a dense (nerrs x k) entry costs k, a full-width (nerrs x n)
    #: entry costs n, so the bound tracks HBM footprint across mixed
    #: signatures (ref: ErasureCodeIsaTableCache.cc
    #: decoding_tables_lru_length, which bounds dense entries only)
    DECODE_LRU_WIDTH = 2516 * 8

    def __init__(self) -> None:
        super().__init__()
        self.technique = "reed_sol_van"
        self.alignment = EC_TPU_DEFAULT_ALIGNMENT
        self._encode_mm = None          # GFMatmul for coding rows
        from ..matrix_code import DecodeTableCache
        #: signature -> GFMatmul/GFDecodeFull, cost-weighted LRU so
        #: HBM-resident decode kernels can't grow unbounded across
        #: erasure patterns (full-width entries charge n, dense k)
        self._decode_mm = DecodeTableCache(self.DECODE_LRU_WIDTH)

    def init(self, profile: ErasureCodeProfile) -> None:
        profile.setdefault("plugin", "tpu")
        self.technique = profile.setdefault("technique", "reed_sol_van")
        self.parse(profile)
        self.prepare()
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        self.alignment = to_int("tpu-alignment", profile,
                                str(EC_TPU_DEFAULT_ALIGNMENT))
        sanity_check_k_m(self.k, self.m)

    def get_chunk_size(self, object_size: int) -> int:
        # identical to the isa plugin (ErasureCodeIsa.cc:66-79) by default
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % self.alignment
        if modulo:
            chunk_size += self.alignment - modulo
        return chunk_size

    def prepare(self) -> None:
        from ..kernels.bitmatmul import GFMatmul
        self._prepare(_matrices(self.technique, self.k, self.m))
        self._encode_mm = GFMatmul(self.encode_matrix[self.k:])

    # -- matmul backend on device -----------------------------------------
    def matmul(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        from ..kernels.bitmatmul import GFMatmul
        if self._encode_mm is not None and mat is not None and \
                mat.shape == self._encode_mm_shape and \
                np.array_equal(mat, self.encode_matrix[self.k:]):
            mm = self._encode_mm
        else:
            mm = GFMatmul(mat)
        return np.asarray(mm(data))

    @property
    def _encode_mm_shape(self):
        return (self.m, self.k)

    # -- batched device API (the perf path) -------------------------------
    def encode_batch(self, data):
        """(..., k, N) uint8 (host or device) -> (..., m, N) parity, on device.

        One dispatch encodes every stripe in the batch; keep inputs as jax
        arrays to avoid transfers between calls.
        """
        return self._encode_mm(data)

    def decode_batch(self, decode_index: list[int], erasures: list[int], data):
        """Reconstruct `erasures` from survivor chunks.

        data: (..., k, N) survivor chunks ordered by decode_index.
        Returns (..., len(erasures), N) on device.  The decode companion
        matrix is cached per erasure signature (ISA-L table-cache analogue).
        """
        from ..kernels.bitmatmul import GFMatmul
        sig = erasure_signature(decode_index, erasures)
        mm = self._decode_mm.get(sig)
        if mm is None:
            dmat = make_decode_matrix(self.encode_matrix, self.k,
                                      list(decode_index), list(erasures))
            mm = GFMatmul(dmat)
            self._decode_mm.put(sig, mm, cost=self.k)
        return mm(data)

    def decode_batch_full(self, erasures: list[int], data,
                          valid=None):
        """Reconstruct `erasures` straight from the FULL chunk array —
        device-resident survivor selection, the staging-free decode
        path.

        data: (..., k+m, N) in ARRIVAL layout (every chunk slot
        present; erased slots carry garbage).  `valid` optionally
        narrows which slots hold real survivor data (length-n bool
        mask; default: everything outside `erasures`).  The decode
        matrix is the zero-column (nerrs x n) form — the selection IS
        the matrix — and the kernel slices the survivor rows on
        DEVICE, so no host-side stack/moveaxis exists and only 8k
        bit-planes unpack (see bitmatmul.GFDecodeFull).  Returns
        (..., len(erasures), N) on device.  Kernels cached per erasure
        signature in HBM, cost-weighted in the LRU (full-width entries
        are (k+m)/k x a dense entry)."""
        from ..kernels.bitmatmul import GFDecodeFull
        from ..matrix_code import make_decode_matrix_full
        n = self.k + self.m
        erased = sorted(int(e) for e in erasures)
        if valid is None:
            valid = np.ones(n, dtype=bool)
            valid[erased] = False
        else:
            valid = np.asarray(valid, dtype=bool)
        sig = "full" + "".join(f"-{e}" for e in erased) + \
            "+v" + "".join("1" if v else "0" for v in valid)
        mm = self._decode_mm.get(sig)
        if mm is None:
            decode_index = [i for i in range(n)
                            if valid[i] and i not in set(erased)][:self.k]
            if len(decode_index) < self.k:
                raise ErasureCodeError(
                    "EIO: fewer than k valid chunks available")
            dmat = make_decode_matrix_full(self.encode_matrix, self.k,
                                           n, decode_index, erased)
            mm = GFDecodeFull(dmat, valid)
            self._decode_mm.put(sig, mm, cost=n)
        # staging-free contract (PR 9): the kernel slices survivors on
        # device — nothing inside this dispatch may touch the host
        from ...common import jaxguard
        with jaxguard.guard_transfers():
            return mm(data)

    def decode_batches_full(self, erasures: list[int], batches,
                            valid=None):
        """Pipelined staging-free decode over a stream of host-resident
        full-width batches: batch i+1's H2D transfer (async
        jax.device_put) is issued BEFORE batch i's result is consumed,
        so the transfer of the next dispatch double-buffers against the
        previous dispatch's kernel.  Yields device arrays in order."""
        import jax
        it = iter(batches)
        try:
            nxt = jax.device_put(next(it))
        except StopIteration:
            return
        while True:
            cur = nxt
            out = self.decode_batch_full(erasures, cur, valid)
            try:
                # next batch's H2D starts while `out`'s kernel runs
                nxt = jax.device_put(next(it))
            except StopIteration:
                yield out
                return
            yield out


PLUGIN = ErasureCodePlugin("tpu", ErasureCodeTpu)
