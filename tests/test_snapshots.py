"""Pool snapshots: COW clones, snap reads, rollback, recovery of
clones (ref: pg_pool_t snap_seq/snaps; PrimaryLogPG::make_writeable /
_rollback_to; OSDMonitor 'osd pool mksnap')."""
import pytest

from ceph_tpu.client import RadosError, WriteOp
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    r = c.rados()
    r.pool_create("sp", pg_num=8)
    r.mon_command({"prefix": "osd erasure-code-profile set",
                   "name": "k2m1",
                   "profile": {"plugin": "tpu", "k": "2", "m": "1",
                               "crush-failure-domain": "osd"}})
    r.pool_create("esp", pg_num=8, pool_type="erasure",
                  erasure_code_profile="k2m1")
    yield c, r
    c.shutdown()


@pytest.fixture()
def io(cluster):
    _, r = cluster
    return r.open_ioctx("sp")


def test_mksnap_rmsnap_commands(io):
    io.snap_create("alpha")
    snaps = io.list_pool_snaps()
    assert "alpha" in snaps.values()
    with pytest.raises(RadosError):
        io.snap_create("alpha")          # EEXIST
    io.snap_remove("alpha")
    assert "alpha" not in io.list_pool_snaps().values()
    with pytest.raises(RadosError):
        io.snap_remove("alpha")          # ENOENT


def test_ec_pool_refuses_snaps(cluster):
    _, r = cluster
    e = r.open_ioctx("esp")
    with pytest.raises(RadosError):
        e.snap_create("nope")


def test_cow_and_snap_reads(io):
    oid = "cowobj"
    io.write_full(oid, b"version-one")
    io.snap_create("s1")
    s1 = io.snap_lookup("s1")
    io.write_full(oid, b"version-two is longer")
    io.snap_create("s2")
    s2 = io.snap_lookup("s2")
    io.write_full(oid, b"v3")
    # head and both snapshots readable independently
    assert io.read(oid) == b"v3"
    assert io.read(oid, snapid=s1) == b"version-one"
    assert io.read(oid, snapid=s2) == b"version-two is longer"
    ls = io.list_snaps(oid)
    assert ls["head_exists"]
    assert sorted(int(t) for t in ls["clones"]) == [s1, s2]


def test_snap_of_unmodified_object_reads_head(io):
    oid = "lazy"
    io.write_full(oid, b"unchanged")
    io.snap_create("s-l")
    sid = io.snap_lookup("s-l")
    # no write since the snap: served from head, no clone exists
    assert io.read(oid, snapid=sid) == b"unchanged"
    assert io.list_snaps(oid)["clones"] == {}


def test_object_created_after_snap_absent_at_snap(io):
    io.snap_create("s-pre")
    sid = io.snap_lookup("s-pre")
    io.write_full("newborn", b"late")
    io.write_full("newborn", b"later")   # forces a clone decision
    with pytest.raises(RadosError, match="ENOENT"):
        io.read("newborn", snapid=sid)


def test_delete_preserves_snapshots(io):
    oid = "ghost"
    io.write_full(oid, b"will be deleted")
    io.snap_create("s-g")
    sid = io.snap_lookup("s-g")
    io.remove(oid)
    with pytest.raises(RadosError, match="ENOENT"):
        io.read(oid)
    assert io.read(oid, snapid=sid) == b"will be deleted"


def test_rollback(io):
    oid = "rb"
    io.operate(oid, WriteOp().write_full(b"good state")
               .set_xattr("tag", b"good").set_omap({"k": b"good"}))
    io.snap_create("s-rb")
    io.operate(oid, WriteOp().write_full(b"bad state!")
               .set_xattr("tag", b"bad").set_omap({"k": b"bad"}))
    io.snap_rollback(oid, "s-rb")
    assert io.read(oid) == b"good state"
    assert io.get_xattr(oid, "tag") == b"good"
    assert io.get_omap_vals(oid)[0] == {"k": b"good"}
    # rollback of a post-snap object removes it
    io.snap_create("s-rb2")
    io.write_full("rb-new", b"x")
    io.write_full("rb-new", b"y")
    io.snap_rollback("rb-new", "s-rb2")
    with pytest.raises(RadosError, match="ENOENT"):
        io.read("rb-new")


def test_removed_snap_never_resurrects(io):
    """A lagging client's snapc must not re-create clones for a
    deleted snapshot (pool removed_snaps filtering,
    ref: pg_pool_t::removed_snaps)."""
    oid = "zombie"
    io.write_full(oid, b"content")
    io.snap_create("doomed")
    sid = io.snap_lookup("doomed")
    io.snap_remove("doomed")
    # lagging client: sends the stale snapc by hand
    io.set_write_snapc(sid, [sid])
    try:
        io.write_full(oid, b"after removal")
    finally:
        io.write_snapc = None
    assert io.list_snaps(oid)["clones"] == {}
    with pytest.raises(RadosError, match="ENOENT"):
        io.read(oid, snapid=sid)


def test_write_cows_with_lagging_osd_map():
    """The client's SnapContext rides with the write: even when the
    primary's map hasn't caught up with a fresh snapshot, the COW
    still happens (ref: MOSDOp's snapc)."""
    c = MiniCluster(n_osd=3, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("lp", pg_num=8)
        io = r.open_ioctx("lp")
        from ceph_tpu.msg.messages import MMap
        oid = "lagobj"
        io.write_full(oid, b"pre-snap state")
        # freeze map delivery to OSDs, then take the snap (the client
        # sees it; the OSDs don't)
        c.network.filter = lambda src, dst, msg: not (
            dst.startswith("osd.") and isinstance(msg, MMap))
        try:
            io.snap_create("s-lag")
            sid = io.snap_lookup("s-lag")
            io.write_full(oid, b"post-snap state")
        finally:
            c.network.filter = None
        assert io.read(oid, snapid=sid) == b"pre-snap state"
        assert io.read(oid) == b"post-snap state"
    finally:
        c.shutdown()


def test_clones_survive_recovery(cluster, io):
    """A newcomer receiving recovery pushes gets the clones too, and
    snap reads keep working after the old holder is gone."""
    c, r = cluster
    oid = "snapdur"
    io.write_full(oid, b"snapshotted data")
    io.snap_create("s-dur")
    sid = io.snap_lookup("s-dur")
    io.write_full(oid, b"newer data")
    pid = r.pool_lookup("sp")
    m = r.objecter.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    victim = next(o for o in acting if o != primary)
    e0 = m.epoch
    r.mon_command({"prefix": "osd out", "ids": [victim]})
    r.objecter.wait_for_map(e0 + 1)
    import time
    deadline = time.monotonic() + 20
    moved = False
    while time.monotonic() < deadline and not moved:
        m2 = r.objecter.osdmap
        _, _, acting2, _ = m2.pg_to_up_acting_osds(raw)
        newcomer = [o for o in acting2 if o not in acting and o >= 0]
        if newcomer:
            pg = m2.pools[pid].raw_pg_to_pg(raw)
            st = c.osds[newcomer[0]].pgs.get(pg)
            if st is not None and st.shard is not None and \
                    st.shard.clone_tags(oid):
                moved = True
        time.sleep(0.1)
    assert moved, "newcomer never received the clones"
    assert io.read(oid, snapid=sid) == b"snapshotted data"
    assert io.read(oid) == b"newer data"
    r.mon_command({"prefix": "osd in", "ids": [victim]})


def test_scrub_detects_clone_divergence(cluster, io):
    c, r = cluster
    oid = "scrubsnap"
    io.write_full(oid, b"snap me")
    io.snap_create("s-sc")
    io.write_full(oid, b"head now")
    pid = r.pool_lookup("sp")
    m = r.objecter.osdmap
    raw = m.object_locator_to_pg(oid, pid)
    pg = m.pools[pid].raw_pg_to_pg(raw)
    _, _, acting, primary = m.pg_to_up_acting_osds(raw)
    victim = next(o for o in acting if o != primary)
    # corrupt the replica's clone
    sid = io.snap_lookup("s-sc")
    from ceph_tpu.osd.ec_backend import pg_cid
    from ceph_tpu.store import ObjectId, Transaction
    c.osds[victim].store.queue_transaction(Transaction().write(
        pg_cid(pg), ObjectId(oid, snap=sid), 0, b"EVIL"))
    res = r.pg_scrub(pid, pg.ps)
    assert oid in res["inconsistent"]
    res2 = r.pg_scrub(pid, pg.ps, repair=True)
    assert res2["repaired"] >= 1
    res3 = r.pg_scrub(pid, pg.ps)
    assert res3["inconsistent"] == []
    assert io.read(oid, snapid=sid) == b"snap me"
