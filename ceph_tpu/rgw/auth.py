"""AWS Signature Version 4 verification against the cephx keyring.

The reference authenticates S3 requests by recomputing the SigV4
signature from the stored secret key (ref: src/rgw/rgw_auth_s3.cc
AWSv4ComplMulti / rgw_auth_s3.h; algorithm per the public AWS SigV4
spec).  Here S3 access keys ARE cephx entities: access_key_id is the
entity name (e.g. "client.s3user"), the secret key is its keyring
secret — one credential store for the whole cluster, the way radosgw
users live in the cluster's auth database.
"""
from __future__ import annotations

import hashlib
import hmac
import time as _time
from urllib.parse import urlparse

ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED = "UNSIGNED-PAYLOAD"
#: accepted clock skew for x-amz-date (AWS uses 15 minutes); bounds
#: how long a captured signed request stays replayable
MAX_SKEW = 15 * 60.0


class SigV4Error(Exception):
    def __init__(self, code: str, msg: str = ""):
        self.code = code
        super().__init__(msg or code)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str,
                service: str = "s3") -> bytes:
    """AWS4 key derivation chain."""
    k = _hmac(f"AWS4{secret}".encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def _parse_amz_date(s: str) -> float:
    """X-Amz-Date/x-amz-date -> epoch seconds; SigV4Error on junk."""
    try:
        return _time.mktime(_time.strptime(s, "%Y%m%dT%H%M%SZ")) \
            - _time.timezone
    except ValueError:
        raise SigV4Error("AccessDenied", "malformed amz date")


def canonical_query(query: str) -> str:
    """Sort the wire query pairs.  The wire form is already
    percent-encoded by the client (and that exact form was signed), so
    pairs are sorted as-received — re-quoting would double-encode and
    break spec-compliant clients."""
    if not query:
        return ""
    pairs = []
    for part in query.split("&"):
        if not part:
            continue
        if "=" not in part:
            part += "="
        pairs.append(tuple(part.split("=", 1)))
    return "&".join(f"{k}={v}" for k, v in sorted(pairs))


def parse_auth_header(value: str) -> dict:
    """'AWS4-HMAC-SHA256 Credential=..., SignedHeaders=..., Signature=...'"""
    if not value.startswith(ALGORITHM):
        raise SigV4Error("InvalidArgument", "unsupported auth scheme")
    out = {}
    for field in value[len(ALGORITHM):].split(","):
        field = field.strip()
        if "=" not in field:
            continue
        k, v = field.split("=", 1)
        out[k] = v
    for need in ("Credential", "SignedHeaders", "Signature"):
        if need not in out:
            raise SigV4Error("InvalidArgument", f"missing {need}")
    cred = out["Credential"].split("/")
    if len(cred) != 5 or cred[4] != "aws4_request":
        raise SigV4Error("InvalidArgument", "malformed credential")
    return {"access_key": cred[0], "date": cred[1], "region": cred[2],
            "service": cred[3],
            "signed_headers": out["SignedHeaders"].split(";"),
            "signature": out["Signature"]}


def verify(method: str, path: str, headers, body: bytes,
           lookup_secret) -> str:
    """Verify a SigV4-signed request; returns the authenticated entity
    or raises SigV4Error (ref: rgw_auth_s3.cc the same recompute-and-
    compare flow)."""
    auth_header = headers.get("Authorization")
    if not auth_header:
        raise SigV4Error("AccessDenied", "anonymous access disabled")
    a = parse_auth_header(auth_header)
    secret = lookup_secret(a["access_key"])
    if secret is None:
        raise SigV4Error("InvalidAccessKeyId", a["access_key"])
    # freshness: x-amz-date within the skew window and matching the
    # credential scope date — without this, one captured request is a
    # permanent bearer token (AWS enforces the same 15-minute window)
    amz_date_hdr = headers.get("x-amz-date", "")
    if not amz_date_hdr or amz_date_hdr[:8] != a["date"]:
        raise SigV4Error("AccessDenied", "x-amz-date/scope mismatch")
    when = _parse_amz_date(amz_date_hdr)
    if abs(_time.time() - when) > MAX_SKEW:
        raise SigV4Error("RequestTimeTooSkewed", amz_date_hdr)
    u = urlparse(path)
    canon_headers = ""
    for name in a["signed_headers"]:
        v = headers.get(name, "")
        canon_headers += f"{name}:{' '.join(v.split())}\n"
    payload_hash = headers.get("x-amz-content-sha256",
                               hashlib.sha256(body).hexdigest())
    if payload_hash == UNSIGNED:
        payload_part = UNSIGNED
    else:
        payload_part = hashlib.sha256(body).hexdigest()
        if payload_hash != payload_part:
            raise SigV4Error("XAmzContentSHA256Mismatch")
    canonical = "\n".join([
        method,
        u.path or "/",       # wire path is already percent-encoded;
        canonical_query(u.query),   # re-quoting would double-encode
        canon_headers,
        ";".join(a["signed_headers"]),
        payload_part,
    ])
    amz_date = headers.get("x-amz-date", "")
    scope = f"{a['date']}/{a['region']}/{a['service']}/aws4_request"
    sts = "\n".join([
        ALGORITHM, amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])
    key = signing_key(secret, a["date"], a["region"], a["service"])
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, a["signature"]):
        raise SigV4Error("SignatureDoesNotMatch")
    return a["access_key"]


def verify_presigned(method: str, path: str, headers,
                     lookup_secret) -> str:
    """Query-string SigV4 (presigned URL) verification (ref:
    src/rgw/rgw_auth_s3.h's query-string path; the AWS
    `X-Amz-Signature` scheme): the signature, credential scope and
    expiry all ride the query, the payload is UNSIGNED-PAYLOAD, and
    only the listed headers (normally just `host`) are signed."""
    u = urlparse(path)
    q: dict[str, str] = {}
    for part in u.query.split("&"):
        if "=" in part:
            k, v = part.split("=", 1)
            q[k] = v
    from urllib.parse import unquote
    if unquote(q.get("X-Amz-Algorithm", "")) != ALGORITHM:
        raise SigV4Error("InvalidArgument", "unsupported algorithm")
    cred = unquote(q.get("X-Amz-Credential", "")).split("/")
    if len(cred) != 5 or cred[4] != "aws4_request":
        raise SigV4Error("InvalidArgument", "malformed credential")
    access_key, date, region, service = cred[:4]
    secret = lookup_secret(access_key)
    if secret is None:
        raise SigV4Error("InvalidAccessKeyId", access_key)
    amz_date = unquote(q.get("X-Amz-Date", ""))
    if amz_date[:8] != date:
        raise SigV4Error("AccessDenied", "date/scope mismatch")
    when = _parse_amz_date(amz_date)
    try:
        expires = min(int(q.get("X-Amz-Expires", "300")), 7 * 86400)
    except ValueError:
        raise SigV4Error("AccessDenied", "malformed X-Amz-Expires")
    now = _time.time()
    if now > when + expires:
        raise SigV4Error("AccessDenied", "request has expired")
    if when > now + MAX_SKEW:
        raise SigV4Error("RequestTimeTooSkewed", amz_date)
    signed = unquote(q.get("X-Amz-SignedHeaders", "host")).split(";")
    canon_headers = ""
    for name in signed:
        v = headers.get(name, "")
        canon_headers += f"{name}:{' '.join(str(v).split())}\n"
    # canonical query: every pair as received EXCEPT the signature
    cq = canonical_query("&".join(
        part for part in u.query.split("&")
        if not part.startswith("X-Amz-Signature=")))
    canonical = "\n".join([method, u.path or "/", cq, canon_headers,
                           ";".join(signed), UNSIGNED])
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])
    key = signing_key(secret, date, region, service)
    want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, q.get("X-Amz-Signature", "")):
        raise SigV4Error("SignatureDoesNotMatch")
    return access_key


def presign(method: str, path: str, host: str, access_key: str,
            secret: str, expires: int = 300, region: str = "default",
            amz_date: str | None = None) -> str:
    """Generate a presigned URL path+query (the boto3
    generate_presigned_url analogue for tests and in-tree clients)."""
    from urllib.parse import quote
    amz_date = amz_date or _time.strftime("%Y%m%dT%H%M%SZ",
                                          _time.gmtime())
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    params = {
        "X-Amz-Algorithm": ALGORITHM,
        "X-Amz-Credential": quote(f"{access_key}/{scope}", safe=""),
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    }
    pairs = sorted(params.items())
    cq = "&".join(f"{k}={v}" for k, v in pairs)
    canonical = "\n".join([method, path, cq, f"host:{host}\n", "host",
                           UNSIGNED])
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])
    key = signing_key(secret, date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    return f"{path}?{cq}&X-Amz-Signature={sig}"


def sign_request(method: str, path: str, headers: dict, body: bytes,
                 access_key: str, secret: str, region: str = "default",
                 amz_date: str | None = None) -> dict:
    """Client-side signer (tests + any in-tree S3 client): returns the
    headers to add (Authorization, x-amz-date, x-amz-content-sha256)."""
    import time as _time
    amz_date = amz_date or _time.strftime("%Y%m%dT%H%M%SZ",
                                          _time.gmtime())
    date = amz_date[:8]
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {k.lower(): v for k, v in headers.items()}
    headers.setdefault("x-amz-date", amz_date)
    headers["x-amz-content-sha256"] = payload_hash
    signed = sorted(set(headers) | {"x-amz-date",
                                    "x-amz-content-sha256"})
    u = urlparse(path)
    canon_headers = "".join(
        f"{n}:{' '.join(str(headers.get(n, '')).split())}\n"
        for n in signed)
    canonical = "\n".join([
        method, u.path or "/",     # caller passes the wire-encoded
        canonical_query(u.query),  # path; sign exactly what is sent
        canon_headers, ";".join(signed),
        payload_hash])
    scope = f"{date}/{region}/s3/aws4_request"
    sts = "\n".join([ALGORITHM, amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])
    key = signing_key(secret, date, region)
    sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    out = dict(headers)
    out["Authorization"] = (
        f"{ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return out
