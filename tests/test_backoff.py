"""common/backoff: the shared capped-exponential retry policy
(extracted from the RGW SyncAgent; now also paces MonClient hunting,
mon elections, objecter/MDS-client retries)."""
import random

import pytest

from ceph_tpu.common.backoff import Backoff, full_jitter


def test_delay_doubles_to_cap():
    b = Backoff(base_s=0.1, cap_s=1.0, jitter=False)
    assert [round(b.next_delay(), 3) for _ in range(6)] == \
        [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]
    assert b.failures == 6


def test_reset_restarts_at_base():
    b = Backoff(base_s=0.1, cap_s=5.0, jitter=False)
    for _ in range(4):
        b.next_delay()
    b.reset()
    assert b.failures == 0
    assert b.next_delay() == pytest.approx(0.1)


def test_jitter_spreads_over_half_to_threehalves():
    rng = random.Random(7)
    b = Backoff(base_s=1.0, cap_s=1.0, jitter=True, rng=rng)
    draws = [b.next_delay() for _ in range(200)]
    assert all(0.5 <= d < 1.5 for d in draws)
    assert max(draws) - min(draws) > 0.5      # actually spread out


def test_full_jitter_seeded_stream_is_deterministic():
    a = [full_jitter(2.0, random.Random(3)) for _ in range(3)]
    b = [full_jitter(2.0, random.Random(3)) for _ in range(3)]
    assert a == b
    assert all(1.0 <= x < 3.0 for x in a)


def test_deadline_form_on_a_fake_clock():
    t = [100.0]
    b = Backoff(base_s=1.0, cap_s=8.0, jitter=False,
                clock=lambda: t[0])
    assert b.ready()                 # never failed: go
    assert b.fail() == 1.0
    assert not b.ready()
    t[0] += 0.5
    assert not b.ready()
    t[0] += 0.6
    assert b.ready()
    # explicit-now form (simulated-time mon ticks)
    assert b.fail(now=200.0) == 2.0
    assert not b.ready(now=201.0)
    assert b.ready(now=202.0)
    b.reset()
    assert b.ready(now=0.0)          # reset rearms immediately


def test_bad_bounds_rejected():
    with pytest.raises(ValueError):
        Backoff(base_s=0.0, cap_s=1.0)
    with pytest.raises(ValueError):
        Backoff(base_s=2.0, cap_s=1.0)


def test_sync_agent_uses_shared_backoff():
    """The policy's birthplace now consumes the shared class (the
    extraction satellite): per-source Backoff instances, cap/base from
    the agent's own knobs."""
    from ceph_tpu.rgw.multisite import SyncAgent
    assert SyncAgent.BACKOFF_BASE_S == pytest.approx(0.1)
    assert SyncAgent.BACKOFF_CAP_S == pytest.approx(5.0)
    import inspect
    src = inspect.getsource(SyncAgent.tick)
    assert "Backoff(" in src and "bo.fail(" in src
