"""mClock op-class QoS + pg_autoscaler + PG splitting
(ref: src/osd/mClockOpClassQueue.h + dmclock;
src/pybind/mgr/pg_autoscaler/; OSD split handling — VERDICT r2 #10)."""
import time

import numpy as np
import pytest

from ceph_tpu.osd.op_queue import MClockQueue
from ceph_tpu.testing import MiniCluster


# --------------------------------------------------------- queue unit

class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_limit_caps_class_rate():
    clk = FakeClock()
    q = MClockQueue(clock=clk)
    q.set_class("recovery", reservation=0, weight=1, limit=10,
                burst=5)
    for i in range(50):
        q.enqueue("recovery", i)
    # burst drains immediately, then the limit gates
    got = []
    while (item := q.dequeue()) is not None:
        got.append(item)
    assert len(got) == 5                 # burst capacity
    assert q.dequeue() is None
    clk.t += 0.5                         # 0.5s -> 5 tokens (cap=burst)
    more = []
    while (item := q.dequeue()) is not None:
        more.append(item)
    assert len(more) == 5
    # long-run rate == limit when drained continuously
    total = 0
    for _ in range(10):
        clk.t += 0.1                     # 1 token per step
        while q.dequeue() is not None:
            total += 1
    assert total == 10                   # 10 ops over 1s at lim=10
    assert q.stats()["recovery"]["deferred"] > 0


def test_reservation_guarantees_minimum():
    """A reserved class makes its minimum rate even when a heavier
    competitor is backlogged."""
    clk = FakeClock()
    q = MClockQueue(clock=clk)
    q.set_class("heavy", weight=100, limit=0)
    q.set_class("reserved", reservation=10, weight=0.001, limit=0,
                burst=1000)
    for i in range(1000):
        q.enqueue("heavy", ("h", i))
    for i in range(100):
        q.enqueue("reserved", ("r", i))
    clk.t += 2.0                       # 2s of reservation accrual
    got = [q.dequeue() for _ in range(40)]
    reserved = [g for g in got if g and g[0] == "r"]
    # >= 10/s * 2s = 20 reserved items must have run
    assert len(reserved) >= 20


def test_weight_splits_excess():
    clk = FakeClock()
    q = MClockQueue(clock=clk)
    q.set_class("a", weight=3)
    q.set_class("b", weight=1)
    for i in range(400):
        q.enqueue("a", ("a", i))
        q.enqueue("b", ("b", i))
    got = [q.dequeue() for _ in range(200)]
    a = sum(1 for g in got if g[0] == "a")
    b = sum(1 for g in got if g[0] == "b")
    assert a / max(b, 1) > 2.0           # ~3:1 split


def test_account_consumes_share():
    """Inline (client) ops advance the class tags so queued classes
    see the real load."""
    clk = FakeClock()
    q = MClockQueue(clock=clk)
    q.set_class("client", weight=10)
    q.set_class("recovery", weight=1)
    for _ in range(30):
        q.account("client")
    q.enqueue("recovery", "r0")
    assert q.dequeue() == "r0"           # idle excess still flows


# --------------------------------------- recovery storm, bounded impact

def test_recovery_storm_client_latency_bounded():
    """Kill + revive an OSD under many objects: recovery floods are
    paced by the mClock queue while client IO keeps completing."""
    from ceph_tpu.common.options import global_config
    g = global_config()
    old = (g["osd_mclock_recovery_lim"],)
    g.set("osd_mclock_recovery_lim", 40.0)   # tight pacing, burst 10
    c = MiniCluster(n_osd=4, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        # few PGs -> each PG's _finish_recovery enqueues a dense burst
        # of pushes (deterministically larger than the token bucket)
        r.pool_create("q", pg_num=4)
        io = r.open_ioctx("q")
        rng = np.random.default_rng(2)
        for i in range(200):
            io.write_full(f"s{i}", rng.integers(
                0, 256, 4000, dtype=np.uint8).tobytes())
        c.kill_osd(3)
        r.mon_command({"prefix": "osd down", "ids": [3]})
        r.mon_command({"prefix": "osd out", "ids": [3]})
        for i in range(200, 240):        # writes while it is out
            io.write_full(f"s{i}", b"x" * 2000)
        c.revive_osd(3)                  # storm: osd.3 must backfill
        r.mon_command({"prefix": "osd in", "ids": [3]})
        # client IO during the storm: every op bounded + correct
        lat = []
        for i in range(30):
            t0 = time.monotonic()
            io.write_full(f"live{i}", b"y" * 1000)
            assert io.read(f"live{i}") == b"y" * 1000
            lat.append(time.monotonic() - t0)
        assert max(lat) < 10.0, f"client latency spiked: {max(lat)}"
        # recovery completes (ticks drain the paced queue)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            c.tick()
            if all(d.pgs_recovering() == 0 and len(d.op_queue) == 0
                   for d in c.osds.values()):
                break
            time.sleep(0.2)
        for i in range(240):
            assert io.read(f"s{i}") is not None
        # pacing engaged at some point: pushes were deferred (counter
        # is cumulative, so this is safe to read after completion)
        deferred = sum(
            d.op_queue.stats()["recovery"]["deferred"]
            for d in c.osds.values())
        assert deferred > 0, \
            "recovery pacing never engaged during the storm"
    finally:
        g.set("osd_mclock_recovery_lim", old[0])
        c.shutdown()


# ------------------------------------------- pg_autoscaler + splitting

def test_pg_split_preserves_objects():
    """Growing pg_num re-homes objects into child PGs (OSD-side
    collection split) with no reads lost."""
    c = MiniCluster(n_osd=4, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("sp", pg_num=4)
        io = r.open_ioctx("sp")
        rng = np.random.default_rng(4)
        objs = {f"o{i}": rng.integers(0, 256, 2000 + i,
                                      dtype=np.uint8).tobytes()
                for i in range(60)}
        for k, v in objs.items():
            io.write_full(k, v)
        rc, outs, _ = r.mon_command({"prefix": "osd pool set",
                                     "pool": "sp", "var": "pg_num",
                                     "val": "16"})
        assert rc == 0, outs
        # pgp_num beyond pg_num stays invalid
        rc2, outs2, _ = r.mon_command({"prefix": "osd pool set",
                                       "pool": "sp", "var": "pgp_num",
                                       "val": "32"})
        assert rc2 < 0
        # wait for the map + split + re-peering to settle
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            c.tick()
            if all(d.osdmap.pools.get(0) is not None and
                   d.pgs_recovering() == 0
                   for d in c.osds.values()):
                try:
                    if all(io.read(k) == v for k, v in objs.items()):
                        break
                except Exception:
                    pass
            time.sleep(0.2)
        for k, v in objs.items():
            assert io.read(k) == v, f"{k} lost across the split"
        # pgp_num growth (placement reseed) is now a supported
        # operation: the peering statechart's prior-interval queries +
        # backfill chase the relocated data (VERDICT r3 #1)
        rc3, outs3, _ = r.mon_command({"prefix": "osd pool set",
                                       "pool": "sp", "var": "pgp_num",
                                       "val": "16"})
        assert rc3 == 0, outs3
        deadline = time.monotonic() + 90
        settled = False
        while time.monotonic() < deadline and not settled:
            c.tick()
            if all(d.osdmap.pools.get(0) is not None and
                   d.osdmap.pools[0].pgp_num == 16 and
                   d.pgs_recovering() == 0
                   for d in c.osds.values()):
                try:
                    settled = all(io.read(k) == v
                                  for k, v in objs.items())
                except Exception:
                    settled = False
            time.sleep(0.2)
        for k, v in objs.items():
            assert io.read(k) == v, f"{k} lost across the reseed"
    finally:
        c.shutdown()


def test_pg_autoscaler_grows_undersized_pool():
    c = MiniCluster(n_osd=6, threaded=True)
    try:
        c.wait_all_up()
        r = c.rados()
        r.pool_create("tiny", pg_num=4)   # far below target
        io = r.open_ioctx("tiny")
        io.write_full("seed", b"z" * 1000)
        mgr = c.start_mgr()
        deadline = time.monotonic() + 30
        while mgr.osdmap.epoch == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        auto = mgr.start_pg_autoscaler()
        sent = mgr.autoscale_tick()
        assert sent >= 1
        plan = auto.status()
        tiny = next(p for p in plan if p["pool_name"] == "tiny")
        assert tiny["would_adjust"] and tiny["target"] > 4
        # the mon applied it and data survives the split
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            c.tick()
            pool = c.mon.osdmap.pools.get(
                r.pool_lookup("tiny"))
            if pool is not None and pool.pg_num == tiny["target"]:
                break
            time.sleep(0.2)
        pool = c.mon.osdmap.pools[r.pool_lookup("tiny")]
        assert pool.pg_num == tiny["target"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            c.tick()
            try:
                if io.read("seed") == b"z" * 1000:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        assert io.read("seed") == b"z" * 1000
        # steady state: a second tick makes no further change
        mgr.autoscale_tick()
        t2 = next(p for p in auto.status()
                  if p["pool_name"] == "tiny")
        assert not t2["would_adjust"]
    finally:
        c.shutdown()
