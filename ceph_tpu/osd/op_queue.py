"""mClock op-class queue: QoS between client / recovery / scrub work.

dmclock-lite (ref: src/osd/mClockOpClassQueue.h + the dmclock
submodule's algorithm; Gulati et al.'s mClock): each class has a
(reservation, weight, limit) triple in ops/sec, each enqueued item
gets three virtual tags, and dequeue runs the two-phase scheduler:

1. **reservation phase** — any head item whose R tag <= now runs
   (guaranteed minimum rate per class, regardless of the others);
2. **weight phase** — among classes whose L tag <= now (limit not
   exceeded), the smallest proportional P tag runs (excess capacity
   split by weight);
3. otherwise nothing is eligible: the caller retries when the clock
   reaches `next_eligible()`.

The OSD keeps executing client ops inline (their latency is the whole
point); it *accounts* them here so recovery/scrub tags compete against
real client load, and routes recovery/scrub work items through the
queue so storms are paced instead of flooding the cluster
(ref: osd_mclock_scheduler_* option family).
"""
from __future__ import annotations

import threading

from ..common.lockdep import make_lock
import time
from collections import deque
from typing import Callable


class _Class:
    __slots__ = ("name", "res", "wgt", "lim", "burst", "tokens",
                 "refilled", "r", "p", "q", "deferred")

    def __init__(self, name: str, res: float, wgt: float, lim: float,
                 burst: float, now: float):
        self.name = name
        self.res = res          # reservation, ops/s (0 = none)
        self.wgt = wgt          # proportional weight
        self.lim = lim          # limit, ops/s (0 = unlimited)
        self.burst = burst      # token-bucket capacity (ops)
        self.tokens = burst
        self.refilled = now
        self.r = 0.0            # last reservation tag
        self.p = 0.0            # last proportional tag
        self.q: deque = deque()
        self.deferred = 0       # times the head had to wait

    def refill(self, now: float) -> None:
        if self.lim <= 0:
            return
        self.tokens = min(self.burst,
                          self.tokens + (now - self.refilled) * self.lim)
        self.refilled = now

    def limited(self, now: float) -> bool:
        """Over limit right now?  The token bucket allows bursts up to
        `burst` ops, then caps at `lim` ops/s — a small recovery flows
        immediately, a storm is paced (tag-spaced limits would stall
        short bursts for no benefit)."""
        if self.lim <= 0:
            return False
        self.refill(now)
        return self.tokens < 1.0


class MClockQueue:
    """(ref: dmclock ClientQueue tag math, reduced)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._classes: dict[str, _Class] = {}
        self._lock = make_lock("osd.mclock")

    def set_class(self, name: str, reservation: float = 0.0,
                  weight: float = 1.0, limit: float = 0.0,
                  burst: float = 64.0) -> None:
        with self._lock:
            c = self._classes.get(name)
            if c is None:
                self._classes[name] = _Class(name, reservation, weight,
                                             limit, burst, self.clock())
            else:
                c.res, c.wgt, c.lim = reservation, weight, limit
                c.burst = burst

    def enqueue(self, name: str, item) -> None:
        now = self.clock()
        with self._lock:
            c = self._classes[name]
            c.q.append(self._tagged(c, now, item))

    def _tagged(self, c: _Class, now: float, item):
        r = max(now, c.r + 1.0 / c.res) if c.res > 0 else float("inf")
        p = max(now, c.p + 1.0 / c.wgt)
        # tags advance at enqueue (the dmclock convention) so a burst
        # of enqueues spaces itself even before any dequeue
        c.r = r if c.res > 0 else c.r
        c.p = p
        return (r, p, item)

    def account(self, name: str) -> None:
        """An op of this class executed OUTSIDE the queue (inline
        client ops): advance its tags + consume a token so queued
        classes' shares are computed against the real total load."""
        now = self.clock()
        with self._lock:
            c = self._classes[name]
            if c.res > 0:
                c.r = max(now, c.r + 1.0 / c.res)
            c.p = max(now, c.p + 1.0 / c.wgt)
            if c.lim > 0:
                c.refill(now)
                c.tokens = max(0.0, c.tokens - 1.0)

    def dequeue(self):
        """Next eligible item or None (two-phase mClock pick)."""
        now = self.clock()
        with self._lock:
            best = None            # (tag, class) reservation phase
            for c in self._classes.values():
                if not c.q or c.limited(now):
                    continue
                r = c.q[0][0]
                if r <= now and (best is None or r < best[0]):
                    best = (r, c)
            if best is None:       # weight phase, limit-gated
                for c in self._classes.values():
                    if not c.q or c.limited(now):
                        continue
                    p = c.q[0][1]
                    if best is None or p < best[0]:
                        best = (p, c)
            if best is not None:
                c = best[1]
                _r, _p, item = c.q.popleft()
                if c.lim > 0:
                    c.tokens = max(0.0, c.tokens - 1.0)
                return item
            for c in self._classes.values():
                if c.q:
                    c.deferred += 1
            return None

    def next_eligible(self) -> float | None:
        """Earliest time any queued head becomes eligible."""
        now = self.clock()
        with self._lock:
            best = None
            for c in self._classes.values():
                if not c.q:
                    continue
                t = now
                if c.lim > 0:
                    c.refill(now)
                    if c.tokens < 1.0:
                        t = now + (1.0 - c.tokens) / c.lim
                if best is None or t < best:
                    best = t
            return best

    def __len__(self) -> int:
        with self._lock:
            return sum(len(c.q) for c in self._classes.values())

    def backlog(self, name: str) -> int:
        with self._lock:
            return len(self._classes[name].q)

    def stats(self) -> dict:
        with self._lock:
            return {n: {"queued": len(c.q), "deferred": c.deferred,
                        "reservation": c.res, "weight": c.wgt,
                        "limit": c.lim}
                    for n, c in self._classes.items()}
