"""Object classes: server-side procedures executed inside the OSD.

The reference loads `libcls_*.so` plugins via ClassHandler
(ref: src/osd/ClassHandler.cc; plugin API src/objclass/objclass.h) and
executes their methods inside the op context with direct access to the
target object (cls_cxx_read/write/getxattr/map_*).  Clients invoke them
with CEPH_OSD_OP_CALL (`IoCtx::exec`).

Here a class is a Python module registering named methods on the
singleton registry; a method runs on the PG primary with a
`MethodContext` exposing synchronous reads of the local object and a
mutation collector — queued mutations commit atomically WITH the
method's success through the normal backend pipeline, mirroring how the
reference folds cls writes into the op's ObjectStore transaction.

Built-in classes mirror the reference's most-used plugins:
`lock` (src/cls/lock), `refcount` (src/cls/refcount),
`version` (src/cls/version), `log` (src/cls/log),
`numops` (src/cls/numops — atomic omap counter arithmetic).

Exec is limited to replicated pools (the data reads a method may issue
are synchronous primary-local reads; EC pools would need a
reconstructing read — the reference's cls users, rbd/rgw metadata,
likewise live on replicated pools).
"""
from __future__ import annotations

from typing import Callable

from ..store import StoreError

# method flags (ref: objclass.h CLS_METHOD_RD/WR/PROMOTE)
CLS_METHOD_RD = 1
CLS_METHOD_WR = 2


class ClsError(Exception):
    """Method failure carrying an errno name (maps to the negative rc
    the reference's cls methods return)."""

    def __init__(self, errno_name: str, msg: str = ""):
        self.errno_name = errno_name
        super().__init__(f"{errno_name}: {msg}" if msg else errno_name)


class MethodContext:
    """Per-call handle onto the target object (ref: objclass.h
    cls_method_context_t + the cls_cxx_* accessors).

    Reads are served synchronously from the primary's local shard;
    writes queue mutations that the daemon commits atomically after
    the method returns successfully.
    """

    def __init__(self, shard, oid: str):
        self._shard = shard
        self.oid = oid
        self.mutations: list[tuple] = []

    # -- reads (cls_cxx_read/stat/getxattr/map_get_*) -------------------
    def exists(self) -> bool:
        return self._shard.exists(self.oid)

    def stat(self) -> dict:
        if not self.exists():
            raise ClsError("ENOENT", self.oid)
        return {"size": self._shard.object_size(self.oid)}

    def read(self, off: int = 0, length: int = 0) -> bytes:
        try:
            return self._shard.read(self.oid, off, length)
        except StoreError as e:
            raise ClsError(e.errno_name) from e

    def getxattr(self, name: str) -> bytes:
        try:
            return self._shard.getxattr(self.oid, name)
        except StoreError as e:
            raise ClsError(e.errno_name) from e

    def getxattrs(self) -> dict:
        try:
            return self._shard.getxattrs(self.oid)
        except StoreError as e:
            raise ClsError(e.errno_name) from e

    def omap_get(self) -> dict:
        try:
            return self._shard.omap_get(self.oid)
        except StoreError as e:
            raise ClsError(e.errno_name) from e

    def omap_get_header(self) -> bytes:
        try:
            return self._shard.omap_get_header(self.oid)
        except StoreError as e:
            raise ClsError(e.errno_name) from e

    # -- queued writes (cls_cxx_write/setxattr/map_set_*) ---------------
    def create(self, exclusive: bool = False) -> None:
        if exclusive and self.exists():
            raise ClsError("EEXIST", self.oid)
        self.mutations.append(("create",))

    def write(self, off: int, data: bytes) -> None:
        self.mutations.append(("write", off, bytes(data)))

    def write_full(self, data: bytes) -> None:
        self.mutations.append(("writefull", bytes(data)))

    def truncate(self, size: int) -> None:
        self.mutations.append(("truncate", int(size)))

    def remove(self) -> None:
        self.mutations.append(("delete",))

    def setxattr(self, name: str, value: bytes) -> None:
        self.mutations.append(("setxattrs", {name: bytes(value)}))

    def rmxattr(self, name: str) -> None:
        self.mutations.append(("rmxattr", name))

    def omap_set(self, kv: dict) -> None:
        self.mutations.append(("omap_setkeys",
                               {k: bytes(v) for k, v in kv.items()}))

    def omap_rmkeys(self, keys) -> None:
        self.mutations.append(("omap_rmkeys", list(keys)))

    def omap_clear(self) -> None:
        self.mutations.append(("omap_clear",))

    def omap_set_header(self, data: bytes) -> None:
        self.mutations.append(("omap_setheader", bytes(data)))


class ClassHandler:
    """Singleton method registry (ref: src/osd/ClassHandler.cc —
    open_class/dlopen replaced by lazy import of built-in modules)."""

    _BUILTIN = ("lock", "refcount", "version", "rgw", "queue", "log",
                "numops")

    def __init__(self):
        self._methods: dict[tuple[str, str], tuple[int, Callable]] = {}
        self._loaded: set[str] = set()

    def register(self, cls: str, method: str, flags: int,
                 fn: Callable) -> None:
        self._methods[(cls, method)] = (flags, fn)

    def _load(self, cls: str) -> None:
        if cls in self._loaded:
            return
        if cls in self._BUILTIN:
            import importlib
            importlib.import_module(f".{cls}", __package__)
        self._loaded.add(cls)

    def resolve(self, cls: str, method: str) -> tuple[int, Callable]:
        """-> (flags, fn); raises ClsError(EOPNOTSUPP) like the
        reference's -EOPNOTSUPP for an unknown class/method
        (PrimaryLogPG CEPH_OSD_OP_CALL)."""
        self._load(cls)
        entry = self._methods.get((cls, method))
        if entry is None:
            raise ClsError("EOPNOTSUPP", f"{cls}.{method}")
        return entry


class_handler = ClassHandler()


def cls_method(cls: str, method: str, flags: int = CLS_METHOD_RD):
    """Decorator used by class modules to register a method
    (ref: cls_register_cxx_method)."""
    def wrap(fn):
        class_handler.register(cls, method, flags, fn)
        return fn
    return wrap
