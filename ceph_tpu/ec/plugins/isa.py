"""isa-compatible CPU plugin (numpy backend).

Mirrors the ISA-L plugin semantics (ref: src/erasure-code/isa/ErasureCodeIsa.cc):

* technique reed_sol_van -> gf_gen_rs_matrix (identity + gen^j rows,
  ref: :385), technique cauchy -> gf_gen_cauchy1_matrix (1/(i^j), ref: :387);
* chunk size = ceil(object_size/k) rounded up to 32 bytes
  (EC_ISA_ADDRESS_ALIGNMENT, ref: :66-79, xor_op.h:28);
* m=1 encode/decode is a pure XOR (region_xor, ref: :126,:196);
* single-erasure decode of a data chunk or the first coding chunk under
  Vandermonde is a pure XOR of the k survivors (ref: :204-216);
* Vandermonde k/m are clamped to known-MDS ranges (ref: :330-360 parse);
* decode tables cached per erasure signature (MatrixErasureCode handles it,
  mirroring ErasureCodeIsaTableCache).
"""
from __future__ import annotations

import numpy as np

from .. import gf
from ..interface import ErasureCodeProfile, ErasureCodeError, to_int, \
    sanity_check_k_m
from ..matrix_code import MatrixErasureCode
from ..registry import ErasureCodePlugin

EC_ISA_ADDRESS_ALIGNMENT = 32  # ref: src/erasure-code/isa/xor_op.h:28


class ErasureCodeIsa(MatrixErasureCode):
    DEFAULT_K = "7"
    DEFAULT_M = "3"

    def __init__(self) -> None:
        super().__init__()
        self.technique = "reed_sol_van"

    def init(self, profile: ErasureCodeProfile) -> None:
        profile.setdefault("plugin", "isa")
        self.technique = profile.setdefault("technique", "reed_sol_van")
        if self.technique not in ("reed_sol_van", "cauchy"):
            raise ErasureCodeError(
                f"ENOENT: isa technique={self.technique!r} not supported")
        self.parse(profile)
        self.prepare()
        super().init(profile)

    def parse(self, profile: ErasureCodeProfile) -> None:
        super().parse(profile)
        self.k = to_int("k", profile, self.DEFAULT_K)
        self.m = to_int("m", profile, self.DEFAULT_M)
        sanity_check_k_m(self.k, self.m)
        if self.technique == "reed_sol_van":
            # verified-MDS clamps (ref: ErasureCodeIsa.cc:330-360)
            if self.k > 32:
                self.k = 32
            if self.m > 4:
                self.m = 4
            if self.m == 4 and self.k > 21:
                self.k = 21

    def get_chunk_size(self, object_size: int) -> int:
        # ref: ErasureCodeIsa.cc:66-79
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % EC_ISA_ADDRESS_ALIGNMENT
        if modulo:
            chunk_size += EC_ISA_ADDRESS_ALIGNMENT - modulo
        return chunk_size

    def prepare(self) -> None:
        if self.technique == "cauchy":
            full = gf.isa_cauchy_matrix(self.k, self.m)
        else:
            full = gf.isa_rs_matrix(self.k, self.m)
        self._prepare(full)

    # -- fast paths (byte-identical to the generic matmul, but cheaper) ----
    def encode_chunks(self, want_to_encode, encoded) -> None:
        if self.m == 1:
            data = np.stack([encoded[self.chunk_index(i)] for i in range(self.k)])
            encoded[self.chunk_index(self.k)][...] = \
                np.bitwise_xor.reduce(data, axis=0)
            return
        super().encode_chunks(want_to_encode, encoded)

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        k, m = self.k, self.m
        erasures = [i for i in range(k + m) if i not in chunks]
        xor_ok = (m == 1) or (
            self.technique == "reed_sol_van"
            and len(erasures) == 1 and erasures[0] < k + 1)
        if xor_ok and len(erasures) == 1:
            # survivors = first k available in index order (ref: :173-192)
            decode_index = [i for i in range(k + m) if i in chunks][:k]
            if len(decode_index) == k:
                survivors = np.stack([decoded[i] for i in decode_index])
                decoded[erasures[0]][...] = np.bitwise_xor.reduce(survivors, axis=0)
                return
        super().decode_chunks(want_to_read, chunks, decoded)


PLUGIN = ErasureCodePlugin("isa", ErasureCodeIsa)
