"""STS: roles, AssumeRole temp credentials, SigV4 with session tokens
(ref: src/rgw/rgw_sts.cc, rgw_rest_sts.cc; VERDICT r4 missing #4)."""
import time
import urllib.error
import urllib.request

import pytest

from ceph_tpu.auth import KeyRing
from ceph_tpu.rgw import RGWGateway
from ceph_tpu.rgw.auth import sign_request
from ceph_tpu.rgw.sts import STSEngine, STSError
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    yield c
    c.shutdown()


# ---------------------------------------------------------------- engine

@pytest.fixture()
def engine(cluster):
    r = cluster.rados()
    try:
        r.pool_lookup("stseng")
    except Exception:
        r.pool_create("stseng", pg_num=8)
    return STSEngine(r.open_ioctx("stseng"))


def test_role_crud_and_trust(engine):
    engine.create_role("reader", ["client.alice"])
    assert engine.get_role("reader")["trust"] == ["client.alice"]
    assert "reader" in engine.list_roles()
    creds = engine.assume_role("client.alice", "reader")
    assert creds["access_key_id"].startswith("STS")
    assert creds["expiration"] > time.time()
    # untrusted principal is refused
    with pytest.raises(STSError) as ei:
        engine.assume_role("client.mallory", "reader")
    assert ei.value.code == "AccessDenied"
    # unknown role
    with pytest.raises(STSError):
        engine.assume_role("client.alice", "nope")
    engine.delete_role("reader")
    assert engine.get_role("reader") is None


def test_temp_cred_validation(engine):
    engine.create_role("any", ["*"], max_duration=7200)
    creds = engine.assume_role("client.bob", "any", duration_s=60)
    akid = creds["access_key_id"]
    assert engine.resolve_secret(akid, creds["session_token"]) == \
        creds["secret_access_key"]
    with pytest.raises(STSError) as ei:
        engine.resolve_secret(akid, "wrong-token")
    assert ei.value.code == "InvalidToken"
    with pytest.raises(STSError):
        engine.resolve_secret("STSDEADBEEF", creds["session_token"])
    assert "assumed-role/any/client.bob" in engine.identity_of(akid)
    # duration beyond the role cap is refused
    with pytest.raises(STSError):
        engine.assume_role("client.bob", "any", duration_s=8000)


def test_expiry_reaps(engine):
    engine.create_role("gone", ["*"])
    creds = engine.assume_role("client.c", "gone", duration_s=1)
    akid = creds["access_key_id"]
    time.sleep(1.2)
    with pytest.raises(STSError) as ei:
        engine.resolve_secret(akid, creds["session_token"])
    assert ei.value.code in ("ExpiredToken", "InvalidClientTokenId")
    # mint-time sweep drops the stale row
    engine.assume_role("client.c", "gone")
    import json
    vals, _ = engine.io.get_omap_vals(".rgw.sts.creds")
    assert akid not in vals


# --------------------------------------------------------- gateway flow

@pytest.fixture(scope="module")
def auth_gw(cluster):
    kr = KeyRing.generate(["client.ops", "client.outsider"])
    g = RGWGateway(cluster.rados(), pool="stsgw", keyring=kr)
    g.start()
    yield g, kr
    g.shutdown()


def req(gw, method, path, data=None, headers=None):
    r = urllib.request.Request(f"http://127.0.0.1:{gw.port}{path}",
                               data=data, method=method,
                               headers=headers or {})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.status, dict(resp.headers), resp.read()


def _signed(gw, kr, method, path, data=b"", entity="client.ops",
            secret=None, extra=None):
    host = f"127.0.0.1:{gw.port}"
    hdrs = dict(extra or {})
    hdrs.update(sign_request(
        method, path, dict({"host": host}, **(extra or {})), data,
        entity, secret if secret is not None else kr.get(entity)))
    return req(gw, method, path, data, hdrs)


def test_assume_role_and_use_temp_creds(auth_gw):
    gw, kr = auth_gw
    gw.sts.create_role("writer", ["client.ops"])
    # AssumeRole is an authenticated Action
    st, _, body = _signed(
        gw, kr, "POST",
        "/?Action=AssumeRole&RoleArn=arn%3Aaws%3Aiam%3A%3A%3Arole"
        "%2Fwriter&DurationSeconds=600")
    assert st == 200
    import re
    akid = re.search(rb"<AccessKeyId>([^<]+)", body).group(1).decode()
    secret = re.search(rb"<SecretAccessKey>([^<]+)",
                       body).group(1).decode()
    token = re.search(rb"<SessionToken>([^<]+)", body).group(1).decode()
    assert akid.startswith("STS")
    # the temp credentials sign real S3 requests (token header required)
    tok = {"x-amz-security-token": token}
    assert _signed(gw, kr, "PUT", "/stsb", entity=akid,
                   secret=secret, extra=tok)[0] == 200
    assert _signed(gw, kr, "PUT", "/stsb/obj", b"payload",
                   entity=akid, secret=secret, extra=tok)[0] == 200
    st, _, body = _signed(gw, kr, "GET", "/stsb/obj", entity=akid,
                          secret=secret, extra=tok)
    assert st == 200 and body == b"payload"
    # missing/wrong token -> 403
    with pytest.raises(urllib.error.HTTPError) as ei:
        _signed(gw, kr, "GET", "/stsb/obj", entity=akid,
                secret=secret)
    assert ei.value.code == 403
    with pytest.raises(urllib.error.HTTPError) as ei:
        _signed(gw, kr, "GET", "/stsb/obj", entity=akid,
                secret=secret,
                extra={"x-amz-security-token": "forged"})
    assert ei.value.code == 403


def test_untrusted_caller_cannot_assume(auth_gw):
    gw, kr = auth_gw
    gw.sts.create_role("locked", ["client.someoneelse"])
    with pytest.raises(urllib.error.HTTPError) as ei:
        _signed(gw, kr, "POST",
                "/?Action=AssumeRole&RoleArn=arn%3Aaws%3Aiam%3A%3A%3A"
                "role%2Flocked", entity="client.outsider")
    assert ei.value.code == 403
