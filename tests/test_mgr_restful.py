"""mgr restful module: JSON admin API over the mon-command plumbing
(ref: src/pybind/mgr/restful/module.py; VERDICT r4 missing #7)."""
import json
import urllib.error
import urllib.request

import pytest

from ceph_tpu.mgr.restful import RestfulServer
from ceph_tpu.testing import MiniCluster


@pytest.fixture(scope="module")
def setup():
    c = MiniCluster(n_osd=4, threaded=True)
    c.wait_all_up()
    mgr = c.start_mgr()
    srv = RestfulServer(mgr)
    srv.start()
    yield c, mgr, srv
    srv.shutdown()
    c.shutdown()


def req(srv, method, path, payload=None, key=None):
    headers = {}
    if key:
        headers["Authorization"] = f"Bearer {key}"
    data = json.dumps(payload).encode() if payload is not None else None
    r = urllib.request.Request(f"http://127.0.0.1:{srv.port}{path}",
                               data=data, method=method,
                               headers=headers)
    with urllib.request.urlopen(r, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_status_health_df(setup):
    _c, _mgr, srv = setup
    st, idx = req(srv, "GET", "/")
    assert "/status" in idx["endpoints"]
    st, status = req(srv, "GET", "/status")
    assert st == 200 and "health" in status
    st, health = req(srv, "GET", "/health")
    assert st == 200
    st, df = req(srv, "GET", "/df")
    assert st == 200


def test_dashboard_json_and_html(setup):
    """The read-only /dashboard status view (the dashboard-module
    analogue over restful): one JSON document with health, usage, pg
    states, sync lag, crashes and slow ops — and the same data as a
    server-rendered HTML page via ?format=html."""
    _c, _mgr, srv = setup
    for _ in range(2):
        _c.tick()           # land at least one pg-stat report
    st, idx = req(srv, "GET", "/")
    assert "/dashboard" in idx["endpoints"]
    st, dash = req(srv, "GET", "/dashboard")
    assert st == 200
    for k in ("health", "osdmap", "pg_states", "usage", "sync",
              "recent_crashes", "slow_ops"):
        assert k in dash, k
    assert dash["health"]["status"].startswith("HEALTH_")
    assert dash["osdmap"]["num_up_osds"] == 4
    assert dash["usage"]["total_kb"] > 0
    assert isinstance(dash["sync"], list)
    # HTML rendering serves text/html and carries the same status
    r = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/dashboard?format=html")
    with urllib.request.urlopen(r, timeout=30) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/html")
        body = resp.read().decode()
    assert "<!DOCTYPE html>" in body
    assert dash["health"]["status"] in body
    assert "pg states" in body


def test_osd_listing_and_command(setup):
    _c, _mgr, srv = setup
    st, osds = req(srv, "GET", "/osd")
    assert st == 200 and len(osds) == 4
    assert all(o["up"] == 1 for o in osds)
    st, one = req(srv, "GET", "/osd/2")
    assert one["osd"] == 2
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(srv, "GET", "/osd/99")
    assert ei.value.code == 404
    # mark out then back in through the API
    st, _ = req(srv, "POST", "/osd/1/command", {"command": "out"})
    assert st == 200
    st, one = req(srv, "GET", "/osd/1")
    assert one["in"] == 0
    req(srv, "POST", "/osd/1/command", {"command": "in"})
    st, one = req(srv, "GET", "/osd/1")
    assert one["in"] == 1
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(srv, "POST", "/osd/1/command", {"command": "explode"})
    assert ei.value.code == 400


def test_pool_lifecycle(setup):
    _c, _mgr, srv = setup
    st, _ = req(srv, "POST", "/pool",
                {"name": "viarest", "pg_num": 8})
    assert st == 200
    st, pools = req(srv, "GET", "/pool")
    names = [p["pool_name"] for p in pools]
    assert "viarest" in names
    st, one = req(srv, "GET", "/pool/viarest")
    assert one["pg_num"] == 8
    st, _ = req(srv, "DELETE", "/pool/viarest")
    assert st == 200
    st, pools = req(srv, "GET", "/pool")
    assert "viarest" not in [p["pool_name"] for p in pools]


def test_api_key_auth(setup):
    _c, mgr, srv = setup
    key = srv.create_key("admin")
    try:
        # keyed server refuses anonymous...
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(srv, "GET", "/status")
        assert ei.value.code == 401
        with pytest.raises(urllib.error.HTTPError):
            req(srv, "GET", "/status", key="wrong")
        # ...and serves the bearer
        st, _ = req(srv, "GET", "/status", key=key)
        assert st == 200
    finally:
        srv.delete_key(key)
    st, _ = req(srv, "GET", "/status")   # open again
    assert st == 200


def test_bad_osd_id_is_400_not_500(setup):
    """ADVICE r5 low: a non-integer osd id is a client error, not a
    500 from the handler's blanket except."""
    _c, _mgr, srv = setup
    with pytest.raises(urllib.error.HTTPError) as ei:
        req(srv, "GET", "/osd/abc")
    assert ei.value.code == 400
    assert json.loads(ei.value.read())["error"] == "bad osd id"
