"""MDS: the metadata server rank.

Reference shapes kept (ref: src/mds/MDSRank.cc dispatch;
src/mds/CDir.cc dirfrag storage — directories are RADOS objects whose
omap maps dentry name -> inode; src/mds/MDLog.cc + src/osdc/
Journaler.cc — every metadata mutation is journaled to a RADOS object
before the dirfrag update, and replayed on startup):

* `dir.<ino:x>` objects in the metadata pool hold one directory each:
  omap dentry name -> JSON inode record (primary dentries embed the
  inode, like CDentry::linkage).
* the write-ahead log is a per-rank `ceph_tpu.journal` Journaler
  (`journal.mds.<rank>` + framed data objects): each op appends one
  entry (seq, op, omap deltas) BEFORE the dirfrag omap update;
  `mds.meta` tracks `applied_seq` (advanced lazily every few ops, so
  a crash leaves a replay window) and the inode allocator, and the
  rank's journal commit position trims consumed objects.  On boot —
  or standby takeover — the MDS replays entries past applied_seq;
  all deltas are idempotent upserts/deletes, so replay converges
  (ref: MDLog::replay over src/osdc/Journaler.cc).
* high availability (this round, ref: MDSMonitor + FSMap): daemons
  beacon to the mon cluster; a rank whose beacon lapses past
  `mds_beacon_grace` is marked failed and a registered `MDSStandby`
  is promoted through replay -> resolve -> active.  Mutating ops
  record their reply in a per-rank completed-request table
  (`mds.completed.<rank>`) keyed by the client's reqid, so a client
  replaying an unreplied op after failover gets the original answer
  instead of a re-execution (ref: Session::completed_requests).
* File data never touches the MDS: clients stripe `{ino:x}.{objno:08x}`
  objects into the data pool themselves (ref: file_layout_t +
  Striper), and report size growth via setattr like cap flushes.

Single rank, one dispatch at a time.  Round 3 adds the Locker-lite
concurrency model (ref: src/mds/Locker.cc + client caps,
src/messages/MClientCaps.h):

* clients OPEN files and are granted **capabilities**: CAP_CACHE (may
  cache reads) and CAP_EXCL (may buffer writes and own the size);
* a conflicting open triggers **revoke-on-conflict**: the MDS sends
  MClientCaps revokes to the holders and answers the opener EAGAIN;
  holders flush dirty size/caches, ack, and the retried open gets a
  grant consistent with the surviving sharers (two writers -> nobody
  caches, the reference's LOCK_MIX outcome);
* caps are leases, not journaled — they die with the session like the
  reference's session reconnect rebuild.

Hardlinks use the reference's primary/remote dentry split
(ref: CDentry::linkage_t): the first link migrates the embedded inode
into the `mds.itable` omap (ino -> record, the anchor-table analogue)
and both dentries become remote references carrying just the ino;
nlink reaches 0 -> the itable entry dies and the client purges data.
"""
from __future__ import annotations

import itertools
import json
import os
import threading

from ..common.backoff import Backoff
from ..common.lockdep import make_lock
import time
import zlib

from ..client import RadosError, WriteOp
from ..common.log import dout
from ..journal import Journaler
from ..msg.messages import (MClientCaps, MClientReply, MClientRequest,
                            MFSMap, MMDSBeacon, MMonCommandAck)
from ..msg.messenger import Dispatcher, Message, Messenger

ROOT_INO = 1
META_OBJ = "mds.meta"
ITABLE_OBJ = "mds.itable"
#: realm table (ref: src/mds/SnapServer.cc's snap table): omap key =
#: realm dir ino -> {name: {"id": snapid, "stamp": t}}
SNAPTABLE_OBJ = "mds.snaptable"
#: subtree authority table (ref: the subtree map MDSRank/Migrator
#: maintain + the ceph.dir.pin export pin): omap key = normalized
#: directory path -> owning rank; longest prefix wins, "/" -> 0
SUBTREE_OBJ = "mds.subtrees"
#: balancer-made subtree assignments (ref: MDBalancer's export
#: decisions): same shape as SUBTREE_OBJ; explicit pins override on
#: path conflicts and are never auto-migrated
AUTO_SUBTREE_OBJ = "mds.auto_subtrees"
#: per-rank load publication for the balancer (ref: mds_load_t
#: exchanged via MHeartbeat in src/mds/MDBalancer.cc)
LOAD_OBJ = "mds.load"
#: in-flight cross-rank rename intents (ref: the slave-request
#: journaling Server::handle_client_rename does for multi-rank
#: renames): omap key = intent id -> json{src, dst, dent, dst_rank}
XRENAME_OBJ = "mds.xrename"
#: per-rank inode-number spaces (ref: each rank's InoTable range):
#: ino = (rank << INO_RANK_SHIFT) | n, so allocations never collide
INO_RANK_SHIFT = 48
#: applied_seq persists every N ops: the gap is the replay window
APPLY_EVERY = 8
#: per-rank completed-request table (ref: the per-session
#: completed_requests the reference journals so a reconnecting client
#: can safely replay an unreplied op): omap key = client entity ->
#: json {reqid: reply}, capped per client
COMPLETED_RETAIN = 16

#: ops that mutate the namespace — replay of these consults the
#: completed table (read ops are naturally replay-safe)
_MUTATING_OPS = frozenset({"mkdir", "create", "setattr", "unlink",
                           "rmdir", "rename", "link", "mksnap",
                           "rmsnap", "set_pin"})

_GID_SEQ = itertools.count(1)


def _alloc_gid() -> int:
    """Cluster-unique daemon gid (the mds_gid_t analogue): pid-scoped
    so multi-process (TCP) daemons never collide."""
    return (os.getpid() << 20) | next(_GID_SEQ)


def journal_id(rank: int) -> str:
    """The rank's metadata WAL journal id (ceph_tpu.journal naming:
    header `journal.mds.<rank>`, data `journal_data.mds.<rank>.*`)."""
    return f"mds.{rank}"


def completed_obj(rank: int) -> str:
    return f"mds.completed.{rank}"

# capability bits (reduced from src/include/ceph_fs.h CEPH_CAP_*)
CAP_CACHE = 1          # may cache reads
CAP_EXCL = 2           # may buffer writes; cached size is authoritative

_ERRNO = {"ENOENT": -2, "EEXIST": -17, "ENOTDIR": -20, "EISDIR": -21,
          "EROFS": -30, "EXDEV": -18,
          "EINVAL": -22, "ENOTEMPTY": -39, "EAGAIN": -11,
          "EMLINK": -31}


class MDSForward(Exception):
    """Request belongs to another rank's subtree (ref: the
    MDS_OP forward the reference sends when it is not auth)."""

    def __init__(self, rank: int):
        self.rank = rank
        super().__init__(f"forward to mds.{rank}")


class _CrossRankRename(Exception):
    """A rename whose source we own but whose destination another
    rank owns: handled off the dispatch thread through the two-phase
    slave protocol (ref: Server::handle_client_rename:7310 +
    Migrator.h:51 slave requests)."""

    def __init__(self, dst_rank: int):
        self.dst_rank = dst_rank


def snap_dir_obj(snapid: int, ino: int) -> str:
    """Snapped dirfrag: the realm's namespace as captured at mksnap
    (ref: the snapped CDentry versions a SnapRealm preserves)."""
    return f"mds.snapdir.{snapid}.{ino:x}"


def dir_obj(ino: int) -> str:
    return f"dir.{ino:x}"


def dir_frag_obj(ino: int, frag: int) -> str:
    """One fragment of a directory (ref: src/mds/CDir.cc dirfrags —
    a dir's dentries hash across 2^bits RADOS objects once it grows
    past mds_bal_split_size).  Fragment 0 IS the base object: it
    always exists and its omap HEADER records the current bits, so
    every rank resolves the layout from shared state."""
    return dir_obj(ino) if frag == 0 else f"{dir_obj(ino)}.f{frag}"


def name_frag(name: str, bits: int) -> int:
    """dentry -> fragment placement (ref: CDir::pick_dirfrag via
    ceph_str_hash; any stable hash works, split points are ours)."""
    if bits <= 0:
        return 0
    return zlib.crc32(name.encode()) & ((1 << bits) - 1)


class MDSError(Exception):
    def __init__(self, errno_name: str, msg: str = ""):
        self.errno_name = errno_name
        super().__init__(f"{errno_name}: {msg}" if msg else errno_name)


class MDSDaemon(Dispatcher):
    """mds.<rank> (ref: src/mds/MDSDaemon.cc + MDSRank).  Multiple
    ranks serve one filesystem: each rank is authoritative for the
    subtrees pinned to it (SUBTREE_OBJ, default everything -> rank 0),
    forwards requests outside its subtrees, journals to its own
    per-rank journal, and allocates inos from its own range.
    `set_pin` migrates a subtree's authority (the Migrator's export,
    collapsed: metadata already lives in shared RADOS omaps, so only
    serving authority and cap ownership move)."""

    def __init__(self, network, rados, rank: int = 0,
                 metadata_pool: str = "cephfs_metadata",
                 data_pool: str = "cephfs_data",
                 threaded: bool = True, keyring=None,
                 mon=None, gid: int | None = None,
                 crash_dir: str | None = None):
        self.name = f"mds.{rank}"
        self.rank = rank
        self.rados = rados
        # beacon/failover plumbing (ref: MDSDaemon beacon_sender):
        # with `mon` set the daemon announces itself and walks
        # resolve -> active; without it the legacy standalone behavior
        # is unchanged (no beacons, no fsmap)
        self.mons = [mon] if isinstance(mon, str) else list(mon or [])
        self.gid = gid if gid is not None else _alloc_gid()
        self._mds_state = "resolve"
        self._beacon_seq = itertools.count(1)
        self._beacon_stop = threading.Event()
        #: test/fault hook: True = stop sending beacons (a "hung" MDS,
        #: the inject_heartbeat_mute analogue on the OSD)
        self.inject_beacon_mute = False
        self.fsmap_epoch = 0
        self.stopped = False
        for pool in (metadata_pool, data_pool):
            try:
                rados.pool_lookup(pool)
            except RadosError:
                try:
                    rados.pool_create(pool, pg_num=32)
                except RadosError:
                    # raced another booting rank to the create: wait
                    # for the winner's pool to reach our map
                    end = time.monotonic() + 30
                    wait = Backoff(base_s=0.2, cap_s=2.0)
                    while True:
                        try:
                            rados.pool_lookup(pool)
                            break
                        except RadosError:
                            if time.monotonic() >= end:
                                raise
                            wait.sleep()
        self.meta = rados.open_ioctx(metadata_pool)
        self.data_pool = data_pool
        # per-rank WAL over the generic journal library (ref:
        # src/osdc/Journaler.cc — the MDS log IS a Journaler client);
        # the rank itself is the committing client, standby-replay
        # followers tail without registering
        self.jr = Journaler(self.meta, journal_id(rank),
                            client_id=f"rank{rank}")
        self._jpos = (0, 0)
        self._k_applied = "applied_seq" if rank == 0 \
            else f"applied_seq.{rank}"
        self._k_next_ino = "next_ino" if rank == 0 \
            else f"next_ino.{rank}"
        # completed-request table: client -> {reqid: reply} (rebuilt
        # from the omap on boot so a replayed op after failover never
        # re-executes; ref: Session::completed_requests)
        self._completed: dict[str, dict[str, object]] = {}
        self._ino_base = rank << INO_RANK_SHIFT
        self._lock = make_lock(f"mds.{rank}")
        self._seq = 0
        self._next_ino = self._ino_base + ROOT_INO + 1
        self._ops_since_apply = 0
        # capability leases (volatile; ref: Locker + session caps):
        # ino -> {client: capbits}; open intents: ino -> {client: wants_write}
        self._caps: dict[int, dict[str, int]] = {}
        self._opens: dict[int, dict[str, bool]] = {}
        self._chain: list[int] = [ROOT_INO]   # last-resolve dir chain
        self._subtree_cache: dict | None = None
        self._subtree_cache_at = 0.0
        self._pending_revokes: list[tuple[str, MClientCaps]] = []
        self._revoking: dict[tuple[int, str], float] = {}
        # internal thread-liveness watchdog (ref: MDSRank's hbmap
        # reset in dispatch): the dispatch worker arms on the first
        # client request and a wedged dispatch surfaces via asok
        # status instead of silent beacon loss
        from ..common.heartbeat_map import HeartbeatMap
        self.hbmap = HeartbeatMap()
        self._hb_handle = self.hbmap.add_worker(
            f"mds.{rank}.dispatch", grace=60.0, arm=False)
        # MDS-to-MDS slave calls (cross-rank rename): tid -> (event,
        # reply slot); replies ride MClientReply like client traffic
        self._peer_tids = itertools.count(1)
        self._peer_pending: dict[int, tuple] = {}
        # balancer heat: top-level dir -> decayed op count
        # (ref: MDBalancer's per-subtree load)
        self._heat: dict[str, float] = {}
        self._ops_handled = 0
        self._last_bal = 0.0
        self._mkfs_or_replay()
        # subtree-table invalidation channel: set_pin on any rank
        # notifies every MDS to drop its cached pin table
        try:
            self.meta.create(SUBTREE_OBJ)
        except RadosError:
            pass
        self._subtree_watch = None
        try:
            self._subtree_watch = self.meta.watch(SUBTREE_OBJ,
                                                  self._subtree_notify)
        except RadosError:
            pass          # TTL refresh covers a failed watch
        self.ms = Messenger.create(network, self.name,
                                   threaded=threaded)
        if keyring is not None:
            # like the OSD: the MDS holds the service secret, mints its
            # ticket locally, and gates inbound client traffic — an
            # auth-enabled cluster must not leave the metadata server
            # as the one unauthenticated daemon (advisor r3 medium)
            from ..auth import attach_cephx
            attach_cephx(self.ms, self.name, keyring)
        self.ms.add_dispatcher(self)
        # crash capture: dispatch-thread exceptions serialize into the
        # mon crash table, spooled to crash_dir until the mon's ack
        # (the table dedups crash_id, so spool+post lands once)
        from ..common.crash import CrashReporter
        self.crash_reporter = CrashReporter(
            self.name, crash_dir=crash_dir,
            post=self._post_crash_meta)
        self.ms.crash_hook = self.crash_reporter.capture
        #: crash-post targets; defaults to the beacon mons but is
        #: settable independently — a standalone MDS (no beacons, no
        #: fsmap) still reports crashes to the cluster
        self.crash_mons = list(self.mons)
        # op tracking + span ring (ref: MDSDaemon's op_tracker +
        # OpRequest tracing): every client request is tracked, aged
        # ones ride the beacon as the SLOW_OPS feed, and traced
        # requests root a span whose journal/objecter legs nest under
        # the ambient scope
        from ..common.options import global_config
        from ..common.tracked_op import OpTracker
        from ..common.tracing import Tracer
        self.op_tracker = OpTracker(
            history_size=global_config()["osd_op_history_size"])
        self.tracer = Tracer(self.name)
        self.asok = None

    def start_admin_socket(self, path: str) -> None:
        """`ceph daemon mds.N <cmd>` endpoint (ref:
        MDSDaemon::asok_command)."""
        from ..common.admin_socket import AdminSocket
        from ..common.obs import register_obs_commands
        a = AdminSocket(path)
        register_obs_commands(a, self.op_tracker, self.tracer)
        a.register("status", "daemon status",
                   lambda c: (0, {"whoami": self.rank,
                                  "state": self._mds_state,
                                  "gid": self.gid,
                                  "hbmap_unhealthy":
                                      self.hbmap.get_unhealthy_workers()}))
        a.start()
        self.asok = a

    def _post_crash_meta(self, meta: dict) -> None:
        from ..msg.messages import MMonCommand
        tid = self.crash_reporter.alloc_tid(meta["crash_id"])
        for m in self.crash_mons:
            if self.ms.connect(m).send_message(MMonCommand(
                    tid=tid,
                    cmd={"prefix": "crash post", "meta": meta})):
                return
        self.crash_reporter.forget_tid(tid)   # nothing sent: no ack

    def init(self) -> None:
        self.ms.start()
        # resolve phase off-thread: finish coordinator-crashed
        # cross-rank renames (the slave call needs the messenger
        # live), then go active and keep beaconing
        threading.Thread(target=self._startup_and_beacon,
                         daemon=True).start()

    def _startup_and_beacon(self) -> None:
        """resolve -> active walk + the periodic beacon loop
        (ref: MDSRank::resolve_done/active_start + Beacon::_send)."""
        from ..common.options import global_config
        self._send_beacon()                      # announce "resolve"
        try:
            self._recover_xrenames()
        except Exception as ex:      # noqa: BLE001 — must reach active
            dout("mds", 0).write("%s: resolve recovery failed: %r",
                                 self.name, ex)
        self._mds_state = "active"
        self._send_beacon()
        while self.mons and not self._beacon_stop.wait(
                global_config()["mds_beacon_interval"]):
            self._send_beacon()

    def _send_beacon(self) -> None:
        if not self.mons or self.inject_beacon_mute or self.stopped:
            return
        msg = MMDSBeacon(gid=self.gid, name=self.name, rank=self.rank,
                         state=self._mds_state,
                         seq=next(self._beacon_seq),
                         # SLOW_OPS feed: aged in-flight client
                         # requests; count 0 clears the mon's entry
                         slow_ops=self.op_tracker.slow_summary())
        for m in self.mons:
            if self.ms.connect(m).send_message(msg):
                return

    def _handle_fsmap(self, msg: MFSMap) -> None:
        """Beacon reply / subscription push: stand down when another
        gid holds our rank (the split-brain fence — a muted-but-alive
        daemon must not keep serving after its replacement took over;
        ref: MDSDaemon::handle_mds_map respawning on removal)."""
        if msg.epoch < self.fsmap_epoch:
            return          # stale push must not stand us down
        self.fsmap_epoch = msg.epoch
        m = msg.fsmap
        info = m.ranks.get(self.rank) if m is not None else None
        if info is not None and info.gid and info.gid != self.gid \
                and info.state != "failed" and not self.stopped:
            dout("mds", 0).write(
                "%s: fsmap e%d says gid %d holds our rank (we are "
                "gid %d) — standing down", self.name, msg.epoch,
                info.gid, self.gid)
            # kill() joins the dispatch thread: must run off it
            threading.Thread(target=self.kill, daemon=True).start()

    def kill(self) -> None:
        """Hard stop for tests/standdown: no flush, no journal commit
        — the next holder of the rank replays (the SIGKILL model the
        thrasher uses)."""
        self.stopped = True
        self._beacon_stop.set()
        if self.asok is not None:
            self.asok.shutdown()
            self.asok = None
        if self._subtree_watch is not None:
            try:
                self.meta.unwatch(SUBTREE_OBJ, self._subtree_watch)
            except Exception as ex:
                dout("mds", 10).write(
                    "kill: unwatch failed (already dead): %s", ex)
            self._subtree_watch = None
        self.ms.shutdown()

    def shutdown(self) -> None:
        self.stopped = True
        self._beacon_stop.set()
        if self.asok is not None:
            self.asok.shutdown()
            self.asok = None
        with self._lock:
            self._persist_applied()
        if self._subtree_watch is not None:
            try:
                self.meta.unwatch(SUBTREE_OBJ, self._subtree_watch)
            except Exception as ex:   # noqa: BLE001
                dout("mds", 10).write(
                    "%s: subtree unwatch on shutdown failed: %s",
                    self.name, ex)
            self._subtree_watch = None
        self.ms.shutdown()

    # ------------------------------------------------------ journal/WAL
    def _mkfs_or_replay(self) -> None:
        """(ref: MDSRank boot: journal replay before going active).
        The WAL rides the generic journal library: the rank is a
        registered journal client whose commit position IS the
        applied checkpoint — a takeover (standby promotion after a
        kill) replays the dead holder's tail from that position, with
        idempotent deltas making double-apply safe."""
        self.jr.create()
        self.jr.register_client()
        try:
            meta = self.meta.get_omap_vals(META_OBJ)[0]
        except RadosError:
            # fresh fs: root dir + meta + itable
            # (exclusive create arbitrates racing first-boot ranks:
            # the loser re-reads the winner's state)
            try:
                self.meta.create(META_OBJ, exclusive=True)
            except RadosError:
                meta = self.meta.get_omap_vals(META_OBJ)[0]
            else:
                for obj in (dir_obj(ROOT_INO), ITABLE_OBJ):
                    try:
                        self.meta.create(obj)
                    except RadosError:
                        pass
                self.meta.set_omap(META_OBJ, {
                    self._k_applied: b"0",
                    self._k_next_ino:
                        str(self._ino_base + ROOT_INO + 1).encode()})
                self._load_completed()
                return
        applied = int(meta.get(self._k_applied, b"0"))
        self._seq = applied          # stay monotonic across journal trims
        self._next_ino = max(
            self._ino_base + ROOT_INO + 1,
            int(meta.get(self._k_next_ino,
                         str(self._ino_base + ROOT_INO + 1).encode())))
        replayed = [0]

        def handler(_tag, ent):
            self._seq = max(self._seq, ent["seq"])
            self._next_ino = max(self._next_ino,
                                 ent.get("next_ino", 0))
            if ent["seq"] <= applied:
                return
            self._apply_deltas(ent["deltas"])
            replayed[0] += 1

        self._jpos = self.jr.replay(handler)
        if replayed[0]:
            dout("mds", 1).write("%s: replayed %d journal entries",
                                 self.name, replayed[0])
        self._load_completed()
        self._persist_applied()

    def _journal(self, op: str, deltas: list) -> None:
        """Append-then-apply: the WAL entry lands before the dirfrag
        mutation (ref: Journaler::append_entry + flush)."""
        self._seq += 1
        self._jpos = self.jr.append(op, {
            "seq": self._seq, "op": op, "next_ino": self._next_ino,
            "deltas": deltas})
        self._apply_deltas(deltas)
        self._ops_since_apply += 1
        if self._ops_since_apply >= APPLY_EVERY:
            self._persist_applied()

    def _apply_deltas(self, deltas: list) -> None:
        """Idempotent omap upserts/deletes on dirfrag objects."""
        for d in deltas:
            kind, obj = d[0], d[1]
            if kind == "set":
                self.meta.operate(obj, WriteOp().set_omap(
                    {k: v.encode() for k, v in d[2].items()}))
            elif kind == "rm":
                try:
                    self.meta.remove_omap_keys(obj, d[2])
                except RadosError:
                    pass
            elif kind == "rmobj":
                try:
                    self.meta.remove(obj)
                except RadosError:
                    pass
            elif kind == "mkobj":
                try:
                    self.meta.create(obj)
                except RadosError:
                    pass               # replay idempotency (EEXIST)
            elif kind == "sethdr":
                self.meta.set_omap_header(obj, d[2].encode())

    def _persist_applied(self) -> None:
        self.meta.set_omap(META_OBJ, {
            self._k_applied: str(self._seq).encode(),
            self._k_next_ino: str(self._next_ino).encode()})
        self._ops_since_apply = 0
        # Checkpoint + trim (ref: MDLog::trim via the Journaler's
        # commit position): everything <= applied_seq is fully
        # applied, so the commit cursor advances and whole data
        # objects behind every client's cursor are reclaimed.
        # Ordering matters — applied_seq persists first; a crash in
        # between just replays already-applied idempotent deltas.
        try:
            self.jr.commit(self._jpos)
            self.jr.trim()
        except RadosError:
            pass          # journal may be mid-create on first boot

    # -------------------------------------------- completed requests
    def _load_completed(self) -> None:
        """Rebuild the replay dedup table on boot (a promoted standby
        must answer a dead rank's unreplied ops from it)."""
        try:
            vals, _ = self.meta.get_omap_vals(completed_obj(self.rank))
        except RadosError:
            self._completed = {}
            return
        self._completed = {c: json.loads(v) for c, v in vals.items()}

    def _completed_get(self, client: str, reqid: str):
        ent = self._completed.get(client)
        if ent is None or reqid not in ent:
            return None
        return (ent[reqid],)          # 1-tuple: a None reply is a hit

    def _completed_put(self, client: str, reqid: str, out) -> None:
        """Record the reply BEFORE it goes on the wire: a client that
        never saw it can replay the op and get the same answer
        (ref: the journaled completed_requests table).  Eviction is
        insertion-ordered — comparing reqid sequence numbers across
        session nonces would evict a live session's fresh entries
        before a dead session's stale ones."""
        ent = self._completed.setdefault(client, {})
        ent[reqid] = out
        while len(ent) > COMPLETED_RETAIN:
            del ent[next(iter(ent))]
        obj = completed_obj(self.rank)
        try:
            self.meta.operate(obj, WriteOp().set_omap(
                {client: json.dumps(ent).encode()}))
        except RadosError:
            try:
                self.meta.create(obj)
                self.meta.set_omap(obj, {client:
                                         json.dumps(ent).encode()})
            except RadosError:
                pass      # volatile fallback: in-memory table serves

    def _replay_tolerate(self, op: str, args: dict, err: MDSError):
        """A replayed mutating op that re-executed into the tiny
        journal-applied-but-completed-unrecorded window: map the
        already-done outcome to success instead of surfacing EEXIST/
        ENOENT to a client that is just retrying its own op.  Only
        reachable for DELIVERED ops whose result was never recorded
        (genuine errors of executed ops replay from the completed
        table; never-delivered retries don't carry the replay flag)."""
        if err.errno_name == "EEXIST":
            if op == "mksnap":
                # answer in the mksnap reply shape: the existing
                # snap's id, not the directory dentry
                _p, _n, dent = self._resolve(args["path"])
                if dent is not None:
                    snaps = self._snaps_of(dent["ino"])
                    name = args.get("name", "")
                    if name in snaps:
                        return {"id": snaps[name]["id"],
                                "name": name}
            elif op in ("mkdir", "link"):
                _p, _n, dent = self._resolve(
                    args.get("path") or args.get("dst") or "/")
                if dent is not None:
                    return self._record_of(dent)
        if err.errno_name == "ENOENT":
            if op in ("unlink", "rmdir", "rmsnap"):
                return {"purge": False} if op == "unlink" else None
            if op == "rename":
                _p, _n, ddent = self._resolve(args["dst"])
                if ddent is not None:
                    return ddent      # already moved
        raise err

    # ------------------------------------------------------- name space
    def _frag_bits(self, ino: int) -> int:
        """Current fragmentation of a directory, from the base
        object's omap header.  Deliberately UNCACHED: the bits are
        shared cluster state (another rank's authority may split a dir
        we later walk for a snapshot), and a stale-cached layout would
        silently drop the suffixed fragments' dentries."""
        try:
            hdr = self.meta.get_omap_header(dir_obj(ino))
        except RadosError:
            return 0
        if not hdr:
            return 0
        try:
            return int(json.loads(hdr).get("bits", 0))
        except (ValueError, AttributeError):
            return 0

    def _dent_obj(self, ino: int, name: str) -> str:
        """The fragment object holding (or due to hold) this dentry."""
        return dir_frag_obj(ino, name_frag(name, self._frag_bits(ino)))

    def _dir_rmobj_deltas(self, ino: int) -> list:
        """rmobj deltas covering EVERY fragment of a directory."""
        bits = self._frag_bits(ino)
        return [("rmobj", dir_frag_obj(ino, f))
                for f in range(1 << bits)]

    def _lookup_dentry(self, ino: int, name: str) -> dict | None:
        """Single-dentry lookup reading only its fragment — the
        resolve fast path (a fragmented dir's full listing would read
        every fragment)."""
        obj = self._dent_obj(ino, name)
        try:
            vals = self.meta.get_omap_vals_by_keys(obj, [name])
        except RadosError:
            if obj == dir_obj(ino):
                raise MDSError("ENOENT", f"dir ino {ino:x}")
            return None       # absent fragment object = no dentry
        return json.loads(vals[name]) if name in vals else None

    def _readdir(self, ino: int) -> dict[str, dict]:
        bits = self._frag_bits(ino)
        out: dict[str, dict] = {}
        for f in range(1 << bits):
            try:
                vals, _ = self.meta.get_omap_vals(dir_frag_obj(ino, f))
            except RadosError:
                if f == 0:
                    raise MDSError("ENOENT", f"dir ino {ino:x}")
                continue      # empty fragment was never materialized
            for k, v in vals.items():
                out[k] = json.loads(v)
        return out

    # ------------------------------------------------- dir fragmentation
    def _refrag(self, ino: int, new_bits: int) -> None:
        """Rewrite a directory into 2^new_bits fragments as ONE
        journaled entry (ref: CDir::split/merge + the EFragment event
        MDLog records — crash mid-refrag replays the whole layout
        change).  Deviation from the reference: fragments stay uniform
        (one global bits per dir) instead of an arbitrary frag tree —
        a split rewrites the whole directory, which is bounded by
        split_size * fragments."""
        old_bits = self._frag_bits(ino)
        if new_bits == old_bits:
            return
        ents = self._readdir(ino)
        buckets: dict[int, dict[str, str]] = {}
        for nm, rec in ents.items():
            buckets.setdefault(name_frag(nm, new_bits),
                               {})[nm] = json.dumps(rec)
        deltas: list = []
        for f in range(1, 1 << old_bits):
            deltas.append(("rmobj", dir_frag_obj(ino, f)))
        gone = [nm for nm in ents if name_frag(nm, new_bits) != 0]
        if gone:
            deltas.append(("rm", dir_obj(ino), gone))
        for f, kv in sorted(buckets.items()):
            if f:
                deltas.append(("mkobj", dir_frag_obj(ino, f)))
            deltas.append(("set", dir_frag_obj(ino, f), kv))
        deltas.append(("sethdr", dir_obj(ino),
                       json.dumps({"bits": new_bits})))
        self._journal("refrag", deltas)
        dout("mds", 4).write("%s: dir %x refrag %d -> %d bits "
                             "(%d dentries)", self.name, ino,
                             old_bits, new_bits, len(ents))

    def _maybe_refrag(self, ino: int, name: str | None = None,
                      removed: bool = False) -> None:
        """Split/merge check after a dentry change (ref:
        MDBalancer::maybe_fragment).  Split looks only at the TOUCHED
        fragment (per-frag threshold, like mds_bal_split_size); merge
        pre-gates on that fragment before paying a full count."""
        from ..common.options import global_config
        cfg = global_config()
        bits = self._frag_bits(ino)
        frag_obj = self._dent_obj(ino, name) if name else dir_obj(ino)
        try:
            vals, _ = self.meta.get_omap_vals(frag_obj)
            n = len(vals)
        except RadosError:
            n = 0
        if not removed:
            if n > int(cfg["mds_bal_split_size"]) and bits < 12:
                self._refrag(ino, bits + 1)
            return
        if bits == 0:
            return
        merge = int(cfg["mds_bal_merge_size"])
        if n * (1 << bits) < merge and \
                len(self._readdir(ino)) < merge:
            self._refrag(ino, 0)

    def _readdir_at(self, ino: int, snapid: int | None) -> dict:
        """Directory listing now, or as captured at `snapid` (the
        snapped dirfrag written by mksnap)."""
        if snapid is None:
            return self._readdir(ino)
        try:
            vals, _ = self.meta.get_omap_vals(snap_dir_obj(snapid,
                                                           ino))
        except RadosError:
            return {}        # dir did not exist at the snap
        return {k: json.loads(v) for k, v in vals.items()}

    def _resolve(self, path: str) -> tuple[int, str, dict | None]:
        """path -> (parent ino, final name, dentry|None)
        (ref: MDCache::path_traverse).  Understands `.snap/<name>`
        components (ref: SnapRealm's snapdir traversal): past one, the
        walk continues through the snapped dirfrags and the final
        dentry carries "snapid".  Side effect: self._chain holds the
        traversed directory-ino chain (root..parent) for snap-context
        resolution — handle_op serializes under the daemon lock."""
        parts = [p for p in path.strip("/").split("/") if p]
        self._chain = [ROOT_INO]
        if not parts:
            return 0, "", {"ino": ROOT_INO, "type": "d"}
        ino = ROOT_INO
        snapid = None
        i = 0
        while i < len(parts):
            comp = parts[i]
            is_last = i == len(parts) - 1
            if comp == ".snap":
                if snapid is not None:
                    raise MDSError("EINVAL", ".snap inside .snap")
                if is_last:
                    # the snapdir pseudo-directory itself
                    return ino, ".snap", {"ino": ino,
                                          "type": "snapdir"}
                name = parts[i + 1]
                snaps = self._snaps_of(ino)
                if name not in snaps:
                    raise MDSError("ENOENT", f".snap/{name}")
                snapid = snaps[name]["id"]
                if i + 1 == len(parts) - 1:
                    # the snap root: the realm dir at that snap
                    return ino, name, {"ino": ino, "type": "d",
                                       "snapid": snapid}
                i += 2
                continue
            if snapid is None:
                # live namespace: read only the dentry's fragment
                d = self._lookup_dentry(ino, comp)
            else:
                d = self._readdir_at(ino, snapid).get(comp)
            if is_last:
                if d is not None and snapid is not None:
                    d = dict(d)
                    d["snapid"] = snapid
                return ino, comp, d
            if d is None:
                raise MDSError("ENOENT", "/".join(parts[:i + 1]))
            if d["type"] != "d":
                raise MDSError("ENOTDIR", comp)
            ino = d["ino"]
            self._chain.append(ino)
            i += 1
        raise MDSError("EINVAL", path)     # unreachable

    # ------------------------------------------------------- snaprealms
    def _snaps_of(self, ino: int) -> dict[str, dict]:
        """Realm snaps of a directory ino (ref: SnapRealm::srnode)."""
        try:
            vals = self.meta.get_omap_vals_by_keys(SNAPTABLE_OBJ,
                                                   [str(ino)])
        except RadosError:
            return {}
        raw = vals.get(str(ino))
        return json.loads(raw) if raw is not None else {}

    def _snapc_for_chain(self, chain: list[int]) -> dict | None:
        """The snap context a file under this directory chain writes
        with (ref: SnapRealm::get_snap_context — the union of every
        ancestor realm's snapids; self-managed, so it exists only in
        the client's snapc, the librbd model)."""
        ids: set[int] = set()
        for ino in chain:
            for ent in self._snaps_of(ino).values():
                ids.add(ent["id"])
        if not ids:
            return None
        return {"seq": max(ids), "snaps": sorted(ids, reverse=True)}

    def _walk_realm(self, realm: int) -> list[tuple[int, dict, list]]:
        """Subtree walk from the realm dir: [(dir ino, entries with
        remote dentries materialized, chain-below-realm)].  Remote
        (hardlink) dentries are resolved NOW so the snapped dirfrag
        freezes the inode state at snap time."""
        out = []
        stack = [(realm, [realm])]
        while stack:
            ino, chain = stack.pop()
            ents = {}
            for name, d in self._readdir(ino).items():
                if "remote" in d:
                    rec = self._iget(d["remote"])
                    ents[name] = dict(rec) if rec is not None else d
                else:
                    ents[name] = d
                if d.get("type") == "d":
                    stack.append((d["ino"], chain + [d["ino"]]))
            out.append((ino, ents, chain))
        return out

    def _alloc_snapid(self) -> int:
        """Allocate a self-managed snapid on the data pool (ref:
        SnapServer's table; riding the pool's self-managed allocator
        keeps removed-snap bookkeeping on the OSD path)."""
        return self.rados.open_ioctx(self.data_pool) \
            .selfmanaged_snap_create()

    def _op_mksnap(self, a):
        """Create a realm snapshot (ref: Server::handle_client_mksnap
        + SnapRealm COW).  EAGAIN while EXCL holders under the realm
        still buffer sizes — the client retries after the revokes
        flush them, so the snapped dirfrags capture true sizes."""
        _p, _n, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent.get("snapid") is not None or dent["type"] != "d":
            raise MDSError("EINVAL", a["path"])
        name = a.get("name", "")
        if not name or "/" in name or name == ".snap":
            raise MDSError("EINVAL", f"snap name {name!r}")
        realm = dent["ino"]
        realm_chain = self._chain + [realm]
        snaps = self._snaps_of(realm)
        if name in snaps:
            raise MDSError("EEXIST", name)
        walk = self._walk_realm(realm)
        # flush gate: any EXCL holder's buffered size would be frozen
        # stale into the snap
        excl = []
        for _ino, ents, _chain in walk:
            for d in ents.values():
                if d.get("type") != "f":
                    continue
                holders = [c for c, b in
                           self._caps.get(d["ino"], {}).items()
                           if b & CAP_EXCL]
                if holders:
                    excl.append((d["ino"], holders))
        if excl:
            for ino, holders in excl:
                self._queue_revoke(ino, holders)
            raise MDSError("EAGAIN", "flushing EXCL holders")
        snapid = self._alloc_snapid()
        snaps = dict(snaps)
        snaps[name] = {"id": snapid, "stamp": time.time(),
                       "dirs": [ino for ino, _e, _c in walk]}
        deltas = [("set", SNAPTABLE_OBJ, {str(realm):
                                          json.dumps(snaps)})]
        for ino, ents, _chain in walk:
            obj = snap_dir_obj(snapid, ino)
            deltas.append(("mkobj", obj))
            if ents:
                deltas.append(("set", obj,
                               {k: json.dumps(v)
                                for k, v in ents.items()}))
        self._journal("mksnap", deltas)
        # push the widened snap context to every open handle under the
        # realm (ref: the SnapRealm update broadcast): without it their
        # next write carries the old snapc and the OSD never COWs for
        # this snap
        prefix = realm_chain[:-1]
        for ino, ents, chain in walk:
            snapc = None          # one computation per directory
            for d in ents.values():
                if d.get("type") != "f" or \
                        d["ino"] not in self._opens:
                    continue
                if snapc is None:
                    snapc = self._snapc_for_chain(prefix + chain)
                for client in self._opens[d["ino"]]:
                    # every _op_* runs under handle_op's self._lock;
                    # the getattr dispatch in _route hides that from
                    # the call graph: cephck: ignore[guarded-by]
                    self._pending_revokes.append((client, MClientCaps(
                        op="snapc", ino=d["ino"], snapc=snapc)))
        return {"id": snapid, "name": name}

    def _op_rmsnap(self, a):
        """(ref: Server::handle_client_rmsnap; the snapid joins the
        pool's removed set so OSD snap contexts stop carrying it)."""
        _p, _n, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent.get("snapid") is not None or dent["type"] != "d":
            raise MDSError("EINVAL", a["path"])
        realm = dent["ino"]
        snaps = dict(self._snaps_of(realm))
        ent = snaps.pop(a.get("name", ""), None)
        if ent is None:
            raise MDSError("ENOENT", a.get("name", ""))
        deltas = [("set", SNAPTABLE_OBJ,
                   {str(realm): json.dumps(snaps)})]
        for ino in ent.get("dirs", []):
            deltas.append(("rmobj", snap_dir_obj(ent["id"], ino)))
        self._journal("rmsnap", deltas)
        try:
            self.rados.open_ioctx(self.data_pool) \
                .selfmanaged_snap_remove(ent["id"])
        except RadosError:
            pass      # snapid leak on failure: ids are never reused
        return None

    def _op_lssnap(self, a):
        _p, _n, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent["type"] not in ("d", "snapdir"):
            raise MDSError("ENOTDIR", a["path"])
        return self._snaps_of(dent["ino"])

    def _alloc_ino(self) -> int:
        ino = self._next_ino
        self._next_ino += 1
        return ino

    # ------------------------------------------- hardlinks / itable
    def _iget(self, ino: int) -> dict | None:
        """itable record for a multiply-linked inode."""
        try:
            vals = self.meta.get_omap_vals_by_keys(ITABLE_OBJ,
                                                   [str(ino)])
        except RadosError:
            return None
        raw = vals.get(str(ino))
        return json.loads(raw) if raw is not None else None

    def _record_of(self, dent: dict) -> dict:
        """Resolve a dentry to its inode record — remote dentries
        (ref: CDentry remote linkage) indirect through the itable."""
        if dent is not None and "remote" in dent:
            rec = self._iget(dent["remote"])
            if rec is None:
                raise MDSError("ENOENT", f"ino {dent['remote']:x}")
            return rec
        return dent

    def _update_record(self, parent: int, name: str, dent: dict,
                       rec: dict, op: str) -> None:
        """Persist an updated inode record where it lives: the itable
        for remote dentries, the primary dentry otherwise."""
        if "remote" in dent:
            self._journal(op, [("set", ITABLE_OBJ,
                                {str(dent["remote"]): json.dumps(rec)})])
        else:
            self._journal(op, [("set", self._dent_obj(parent, name),
                                {name: json.dumps(rec)})])

    # --------------------------------------------------- capabilities
    #: unacked revoke grace before caps are force-dropped (the session
    #: timeout analogue, ref: mds_session_autoclose)
    REVOKE_GRACE = 5.0

    def _queue_revoke(self, ino: int, clients) -> None:
        now = time.monotonic()
        for c in clients:
            key = (ino, c)
            started = self._revoking.setdefault(key, now)
            if now - started > self.REVOKE_GRACE:
                # client never acked (dead/hung): force-drop its caps
                # and session so the opener can make progress
                self._caps.get(ino, {}).pop(c, None)
                self._opens.get(ino, {}).pop(c, None)
                self._revoking.pop(key, None)
                continue
            # callers (handle_op's _op_* dispatch, the tick's session
            # reaper) all hold self._lock; the getattr dispatch hides
            # that from the call graph: cephck: ignore[guarded-by]
            self._pending_revokes.append((c, MClientCaps(
                op="revoke", ino=ino,
                caps=self._caps.get(ino, {}).get(c, 0))))

    def _grant_caps(self, ino: int, client: str,
                    wants_write: bool) -> int:
        """Revoke-on-conflict grant (ref: Locker file lock states,
        collapsed): raises EAGAIN after queueing revokes."""
        other_caps = {c: b for c, b in self._caps.get(ino, {}).items()
                      if c != client and b}
        others = {c: w for c, w in self._opens.get(ino, {}).items()
                  if c != client}
        if wants_write:
            if other_caps:
                self._queue_revoke(ino, other_caps)
                raise MDSError("EAGAIN", "caps being revoked")
            caps = (CAP_EXCL | CAP_CACHE) if not others else 0
        else:
            excl = [c for c, b in other_caps.items() if b & CAP_EXCL]
            if excl:
                self._queue_revoke(ino, excl)
                raise MDSError("EAGAIN", "caps being revoked")
            caps = CAP_CACHE if not any(others.values()) else 0
        self._opens.setdefault(ino, {})[client] = wants_write
        if caps:
            self._caps.setdefault(ino, {})[client] = caps
        else:
            self._caps.get(ino, {}).pop(client, None)
        return caps

    def handle_caps(self, msg: MClientCaps) -> None:
        """Client returned caps (ack after flushing dirty state)."""
        with self._lock:
            if msg.op == "ack":
                m = self._caps.get(msg.ino)
                if m is not None:
                    m.pop(msg.src, None)
                self._revoking.pop((msg.ino, msg.src), None)

    # --------------------------------------------- subtree authority
    @staticmethod
    def _norm(path: str) -> str:
        return "/" + "/".join(p for p in path.strip("/").split("/")
                              if p)

    #: staleness bound when the invalidation notify was missed
    _SUBTREE_TTL = 2.0

    def _subtrees(self) -> dict[str, int]:
        """The pin table, cached in memory (the reference keeps the
        subtree map resident) and invalidated by set_pin's notify on
        SUBTREE_OBJ — a per-op omap read would sit on every metadata
        op's hot path."""
        now = time.monotonic()
        cached = self._subtree_cache
        if cached is not None and \
                now - self._subtree_cache_at < self._SUBTREE_TTL:
            return cached
        try:
            vals, _ = self.meta.get_omap_vals(AUTO_SUBTREE_OBJ)
            table = {k: int(v) for k, v in vals.items()}
        except RadosError:
            table = {}
        try:
            vals, _ = self.meta.get_omap_vals(SUBTREE_OBJ)
            # explicit pins overwrite balancer assignments on the
            # same path (pins are the operator's override)
            table.update({k: int(v) for k, v in vals.items()})
        except RadosError:
            pass
        self._subtree_cache = table
        self._subtree_cache_at = now
        return table

    def _explicit_pins(self) -> dict[str, int]:
        try:
            vals, _ = self.meta.get_omap_vals(SUBTREE_OBJ)
            return {k: int(v) for k, v in vals.items()}
        except RadosError:
            return {}

    def _subtree_notify(self, notify_id=None, notifier=None,
                        payload=None):
        """Watch callback: a peer's set_pin changed the table."""
        self._subtree_cache = None
        return {"rank": self.rank}

    def _authority(self, path: str) -> int:
        """Owning rank by longest-prefix match (ref: the subtree map;
        everything defaults to rank 0)."""
        path = self._norm(path)
        best, rank = "", 0
        for prefix, r in self._subtrees().items():
            if (path == prefix or
                    path.startswith(prefix.rstrip("/") + "/")) and \
                    len(prefix) > len(best):
                best, rank = prefix, r
        return rank

    #: ops served by whichever rank receives them (no path to route)
    _LOCAL_OPS = frozenset({"statfs"})

    def _route(self, op: str, a: dict) -> None:
        """Forward requests outside our subtrees (ref: the reference
        MDS forwarding non-auth requests via the mdsmap)."""
        if op in self._LOCAL_OPS:
            return
        if op == "set_pin" and a.get("force"):
            # admin repair hatch: a subtree pinned to a dead or
            # nonexistent rank is otherwise unreachable — any live
            # rank may override the table
            return
        path = a.get("path") or a.get("src")
        if path is None:
            return
        auth = self._authority(path)
        dst = a.get("dst")
        if dst is not None and self._authority(dst) != auth:
            if op == "rename":
                # the SOURCE authority coordinates a two-phase
                # cross-rank rename (ref: Server::handle_client_rename
                # with remote witnesses); anyone else forwards there
                if auth != self.rank:
                    raise MDSForward(auth)
                raise _CrossRankRename(self._authority(dst))
            # cross-rank hardlink would additionally need remote-link
            # refcounting through the slave machinery
            raise MDSError("EXDEV", "paths belong to different ranks")
        if auth != self.rank:
            raise MDSForward(auth)

    def _op_reopen(self, a):
        """Re-register an open intent after a cap surrender (the
        client's half of a subtree migration: the NEW authority must
        know the handle exists or it would grant a later opener
        conflicting EXCL over live write-through traffic)."""
        _parent, _name, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        rec = self._record_of(dent)
        self._opens.setdefault(rec["ino"], {})[a["__client"]] = \
            bool(a.get("wants_write"))
        return None

    def _op_reconnect(self, a):
        """Session reconnect after an MDS failover (ref: the client
        reconnect phase of MDSRank rejoin — clients re-state their
        open files and the new rank re-issues caps).  Best-effort:
        conflicting caps come back as 0 and the handle runs
        write-through until the conflict clears."""
        _parent, _name, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        rec = self._record_of(dent)
        if rec["type"] != "f":
            raise MDSError("EISDIR", a["path"])
        ino = rec["ino"]
        wants_write = bool(a.get("wants_write"))
        try:
            caps = self._grant_caps(ino, a["__client"], wants_write)
        except MDSError:
            # revoke in flight: register the intent cap-less
            self._opens.setdefault(ino, {})[a["__client"]] = \
                wants_write
            caps = 0
        return {"caps": caps, "rec": rec}

    def _op_set_pin(self, a):
        """Migrate a subtree's authority (ref: Migrator export +
        `setfattr ceph.dir.pin`): journal the new pin, then evict our
        caps/open state under it — clients re-acquire through the new
        rank on their next forwarded op."""
        _p, _n, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent["type"] != "d" or dent.get("snapid") is not None:
            raise MDSError("ENOTDIR", a["path"])
        target = int(a["rank"])
        if target < 0:
            raise MDSError("EINVAL", f"rank {target}")
        path = self._norm(a["path"])
        self._do_pin(dent, path, target, SUBTREE_OBJ)
        return {"path": path, "rank": target}

    def _do_pin(self, dent: dict, path: str, target: int,
                table_obj: str) -> None:
        """Migrate a subtree's authority into `table_obj` (explicit
        pin table or the balancer's): journal the entry, persist, and
        evict our caps/open state under it — clients re-acquire
        through the new rank on their next forwarded op."""
        self._journal("set_pin", [
            ("mkobj", table_obj),
            ("set", table_obj, {path: str(target)})])
        # clean handoff: nothing of ours left unflushed for the new
        # authority to miss
        self._persist_applied()
        self._subtree_cache = None
        try:
            # synchronous invalidation: peers drop their cached table
            # before this reply releases the client to the new rank
            self.meta.notify(SUBTREE_OBJ, {"op": "repin"})
        except RadosError:
            pass
        if target != self.rank:
            self._evict_moved(dent)

    def _op_get_pins(self, a):
        return self._subtrees()

    # --------------------------------------------- load balancer
    def tick(self, now: float | None = None) -> None:
        """Periodic MDBalancer pass (ref: src/mds/MDBalancer.cc —
        ranks exchange loads, the overloaded one exports a hot
        subtree).  Loads ride a shared RADOS object instead of
        MHeartbeat; an export is an entry in the balancer's own
        subtree table, so explicit pins stay the operator's override
        and are never auto-migrated."""
        from ..common.options import global_config
        self.hbmap.reset_timeout(self._hb_handle)
        now = time.monotonic() if now is None else now
        cfg = global_config()
        interval = cfg["mds_bal_interval"]
        with self._lock:
            if now - self._last_bal < interval:
                return
            self._last_bal = now
            my_load = sum(self._heat.values())
            # half-life decay so load reflects the recent window
            for k in list(self._heat):
                self._heat[k] *= 0.5
                if self._heat[k] < 0.01:
                    del self._heat[k]
        try:
            self.meta.create(LOAD_OBJ)
        except RadosError:
            pass
        # stamps shared through RADOS need a SHARED clock: monotonic
        # bases are per-host, so freshness math across ranks on
        # different hosts would be garbage (ref: mds_load_t rides
        # wall-clock utime_t)
        wall = time.time()
        try:
            self.meta.operate(LOAD_OBJ, WriteOp().set_omap({
                str(self.rank): json.dumps(
                    {"load": my_load, "stamp": wall}).encode()}))
            vals, _ = self.meta.get_omap_vals(LOAD_OBJ)
        except RadosError:
            return
        loads: dict[int, float] = {}
        for r, blob in vals.items():
            try:
                rec = json.loads(blob)
                if wall - float(rec["stamp"]) <= 3 * interval:
                    loads[int(r)] = float(rec["load"])
            except (ValueError, KeyError):
                continue
        if len(loads) < 2:
            return                      # no live peer to export to
        coldest = min((r for r in loads if r != self.rank),
                      key=lambda r: loads[r])
        if my_load < cfg["mds_bal_min_load"] or \
                my_load < cfg["mds_bal_ratio"] * (loads[coldest] + 1):
            return
        with self._lock:
            pins = self._explicit_pins()
            best = None
            for d, h in sorted(self._heat.items(),
                               key=lambda kv: -kv[1]):
                if d in pins or self._authority(d) != self.rank:
                    continue
                # exporting our ONLY load would just ping-pong;
                # keep at least something resident
                if h >= my_load * 0.9 and len(self._heat) == 1 and \
                        loads[coldest] <= 0.0 and my_load < \
                        2 * cfg["mds_bal_min_load"]:
                    continue
                _p, _n, dent = self._resolve(d)
                if dent is None or dent.get("type") != "d":
                    continue
                best = (d, dent)
                break
            if best is None:
                return
            path, dent = best
            dout("mds", 1).write(
                "%s: balancer exporting %s (heat %.1f, load %.1f) "
                "-> mds.%d (load %.1f)", self.name, path,
                self._heat.get(path, 0.0), my_load, coldest,
                loads[coldest])
            self._do_pin(dent, path, coldest, AUTO_SUBTREE_OBJ)
            self._heat.pop(path, None)
            revokes, self._pending_revokes = self._pending_revokes, []
        # tick runs outside the dispatch loop: send the evictions
        # ourselves (dispatch would otherwise drain them on the next op)
        for client, cap_msg in revokes:
            self.ms.connect(client).send_message(cap_msg)

    # ------------------------------------------------------- operations
    #: ops allowed to traverse `.snap` paths — everything else on a
    #: snapshot path is EROFS (ref: the snapdir is read-only)
    _SNAP_RO_OPS = frozenset({"lookup", "open", "readdir", "statfs",
                              "lssnap", "release"})

    def handle_op(self, op: str, args: dict):
        """Returns the reply payload; raises MDSError/MDSForward.
        (ref: Server::dispatch_client_request op switch)."""
        with self._lock:
            self._route(op, args)
            # balancer heat: ops we actually serve, attributed to the
            # path's top-level subtree (ref: MDBalancer hit_dir)
            _p = args.get("path") or args.get("src")
            if _p and not str(args.get("__client", "")
                              ).startswith("mds."):
                parts = self._norm(_p).strip("/").split("/")
                if parts and parts[0]:
                    top = "/" + parts[0]
                    self._heat[top] = self._heat.get(top, 0.0) + 1.0
            if op not in self._SNAP_RO_OPS and any(
                    ".snap" in str(args.get(k, "")).split("/")
                    for k in ("path", "src", "dst")):
                raise MDSError("EROFS", "snapshots are read-only")
            client = args.get("__client")
            reqid = args.get("__reqid")
            if reqid and client and op in _MUTATING_OPS:
                hit = self._completed_get(client, reqid)
                if hit is not None:
                    # the op already ran on this rank (or the rank we
                    # replaced): answer from the table — success OR
                    # error — never re-execute (ref:
                    # completed_requests dedup)
                    stored = hit[0]
                    if isinstance(stored, dict) and \
                            "__mds_errno" in stored:
                        raise MDSError(stored["__mds_errno"],
                                       "(replayed)")
                    return stored
                try:
                    out = getattr(self, f"_op_{op}")(args)
                except MDSError as e:
                    if e.errno_name == "EAGAIN":
                        raise      # transient: client retries fresh
                    if args.get("__replay"):
                        out = self._replay_tolerate(op, args, e)
                    else:
                        # record the failure too: a replay after a
                        # lost error reply must re-fail identically,
                        # not be tolerance-mapped to success
                        self._completed_put(
                            client, reqid,
                            {"__mds_errno": e.errno_name})
                        raise
                self._completed_put(client, reqid, out)
                return out
            return getattr(self, f"_op_{op}")(args)

    def _with_snapc(self, rec: dict) -> dict:
        """Attach the write snap context for the just-resolved path's
        realm chain (consumed by the client's data ioctx)."""
        snapc = self._snapc_for_chain(self._chain)
        if snapc is None:
            return rec
        rec = dict(rec)
        rec["snapc"] = snapc
        return rec

    def _op_mkdir(self, a):
        parent, name, dent = self._resolve(a["path"])
        if not name:
            raise MDSError("EEXIST", "/")
        if dent is not None:
            raise MDSError("EEXIST", a["path"])
        ino = self._alloc_ino()
        rec = {"ino": ino, "type": "d",
               "mtime": time.time()}
        self._journal("mkdir", [
            ("mkobj", dir_obj(ino)),
            ("set", self._dent_obj(parent, name), {name: json.dumps(rec)})])
        self._maybe_refrag(parent, name)
        return rec

    def _op_create(self, a):
        parent, name, dent = self._resolve(a["path"])
        if not name:
            raise MDSError("EISDIR", "/")
        if dent is not None:
            if dent["type"] == "d":
                raise MDSError("EISDIR", a["path"])
            rec = self._record_of(dent)
            if not a.get("truncate"):
                # open-existing ('r+'/'a')
                return self._with_snapc(rec)
            # O_TRUNC semantics (ref: Server::handle_client_openc +
            # inode truncate): size -> 0; the client purges the old
            # data objects, mirroring how unlink purges client-side
            old_size = rec.get("size", 0)
            rec = dict(rec)
            rec["size"] = 0
            rec["mtime"] = time.time()
            self._update_record(parent, name, dent, rec, "truncate")
            out = self._with_snapc(dict(rec))
            out["purge_size"] = old_size
            return out
        ino = self._alloc_ino()
        rec = {"ino": ino, "type": "f", "size": 0,
               "mtime": time.time(),
               "layout": a.get("layout") or
               {"stripe_unit": 1 << 16, "stripe_count": 4,
                "object_size": 1 << 18},
               "pool": self.data_pool}
        self._journal("create", [
            ("set", self._dent_obj(parent, name), {name: json.dumps(rec)})])
        self._maybe_refrag(parent, name)
        return self._with_snapc(rec)

    def _op_lookup(self, a):
        _parent, _name, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent.get("snapid") is not None:
            return dent        # frozen snap record, size at snap time
        return self._with_snapc(self._record_of(dent))

    def _op_open(self, a):
        """Open with a capability request (ref: Server::handle_client_
        open -> Locker issue).  EAGAIN while conflicting caps are being
        revoked; the client retries.  Snapshot paths open read-only
        with no caps — the record itself is frozen."""
        _parent, _name, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent.get("snapid") is not None:
            if a.get("wants_write"):
                raise MDSError("EROFS", a["path"])
            if dent["type"] != "f":
                raise MDSError("EISDIR", a["path"])
            return {"rec": dent, "caps": 0}
        rec = self._record_of(dent)
        if rec["type"] != "f":
            raise MDSError("EISDIR", a["path"])
        caps = self._grant_caps(rec["ino"], a["__client"],
                                bool(a.get("wants_write")))
        return {"rec": self._with_snapc(rec), "caps": caps}

    def _op_release(self, a):
        """Close: drop the session's caps + open intent
        (ref: Locker::remove_client_cap)."""
        ino = a["ino"]
        for table in (self._caps, self._opens):
            ent = table.get(ino)
            if ent is not None:
                ent.pop(a["__client"], None)
                if not ent:
                    del table[ino]
        return None

    def _op_link(self, a):
        """Hardlink (ref: Server::handle_client_link): the first link
        migrates the embedded inode to the itable; both dentries become
        remote references."""
        sp, sname, sdent = self._resolve(a["src"])
        if sdent is None:
            raise MDSError("ENOENT", a["src"])
        if self._record_of(sdent)["type"] == "d":
            raise MDSError("EISDIR", a["src"])
        dp, dname, ddent = self._resolve(a["dst"])
        if not dname:
            raise MDSError("EINVAL", a["dst"])
        if ddent is not None:
            raise MDSError("EEXIST", a["dst"])
        if "remote" in sdent:
            rec = self._iget(sdent["remote"])
            rec["nlink"] = rec.get("nlink", 1) + 1
            self._journal("link", [
                ("set", ITABLE_OBJ, {str(rec["ino"]): json.dumps(rec)}),
                ("set", self._dent_obj(dp, dname),
                 {dname: json.dumps({"type": "f",
                                     "remote": rec["ino"]})})])
            return rec
        rec = dict(sdent)
        rec["nlink"] = 2
        remote = {"type": "f", "remote": rec["ino"]}
        self._journal("link", [
            ("set", ITABLE_OBJ, {str(rec["ino"]): json.dumps(rec)}),
            ("set", self._dent_obj(sp, sname), {sname: json.dumps(remote)}),
            ("set", self._dent_obj(dp, dname), {dname: json.dumps(remote)})])
        self._maybe_refrag(dp, dname)
        return rec

    def _op_readdir(self, a):
        _parent, _name, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent["type"] == "snapdir":
            # `ls dir/.snap`: the realm's snapshots as directories
            return {n: {"ino": dent["ino"], "type": "d",
                        "snapid": s["id"]}
                    for n, s in self._snaps_of(dent["ino"]).items()}
        if dent["type"] != "d":
            raise MDSError("ENOTDIR", a["path"])
        return self._readdir_at(dent["ino"], dent.get("snapid"))

    def _op_unlink(self, a):
        parent, name, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent["type"] == "d":
            raise MDSError("EISDIR", a["path"])
        if "remote" in dent:
            # hardlink: drop the reference; purge only at nlink 0
            rec = self._iget(dent["remote"])
            if rec is None:
                self._journal("unlink", [("rm", self._dent_obj(parent, name),
                                          [name])])
                raise MDSError("ENOENT", a["path"])
            rec["nlink"] = rec.get("nlink", 1) - 1
            if rec["nlink"] <= 0:
                self._journal("unlink", [
                    ("rm", self._dent_obj(parent, name), [name]),
                    ("rm", ITABLE_OBJ, [str(rec["ino"])])])
                out = self._with_snapc(dict(rec))
                out["purge"] = True
                return out
            self._journal("unlink", [
                ("rm", self._dent_obj(parent, name), [name]),
                ("set", ITABLE_OBJ, {str(rec["ino"]): json.dumps(rec)})])
            out = self._with_snapc(dict(rec))
            out["purge"] = False
            return out
        # the purge travels with the realm's snapc: under a snapped
        # realm the OSD-side delete COWs the head into a clone first,
        # so `.snap` reads keep serving the file's frozen state
        out = self._with_snapc(dict(dent))
        out["purge"] = True
        self._journal("unlink", [("rm", self._dent_obj(parent, name), [name])])
        self._maybe_refrag(parent, name, removed=True)
        return out                       # client purges the data objs

    def _op_rmdir(self, a):
        parent, name, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        if dent["type"] != "d":
            raise MDSError("ENOTDIR", a["path"])
        if self._readdir(dent["ino"]):
            raise MDSError("ENOTEMPTY", a["path"])
        self._journal("rmdir", [
            ("rm", self._dent_obj(parent, name), [name])]
            + self._dir_rmobj_deltas(dent["ino"]))
        self._maybe_refrag(parent, name, removed=True)
        return None

    def _op_rename(self, a):
        """(ref: Server::handle_client_rename, single-rank so no
        subtree migration)."""
        src = "/" + "/".join(p for p in a["src"].split("/") if p)
        dst = "/" + "/".join(p for p in a["dst"].split("/") if p)
        sp, sname, sdent = self._resolve(a["src"])
        if sdent is None:
            raise MDSError("ENOENT", a["src"])
        if src == dst:
            return sdent                 # POSIX: rename to self is a no-op
        if dst.startswith(src + "/"):
            # a directory cannot move into its own subtree
            # (ref: the rename cycle check in Server::handle_client_rename)
            raise MDSError("EINVAL", f"{dst} is inside {src}")
        dp, dname, ddent = self._resolve(a["dst"])
        if not dname:
            raise MDSError("EINVAL", a["dst"])
        if ddent is not None:
            if ddent["type"] == "d":
                if self._readdir(ddent["ino"]):
                    raise MDSError("ENOTEMPTY", a["dst"])
            elif sdent["type"] == "d":
                raise MDSError("ENOTDIR", a["dst"])
        deltas = [("set", self._dent_obj(dp, dname),
                   {dname: json.dumps(sdent)}),
                  ("rm", self._dent_obj(sp, sname), [sname])]
        if ddent is not None and ddent["type"] == "d":
            deltas.extend(self._dir_rmobj_deltas(ddent["ino"]))
        self._journal("rename", deltas)
        self._maybe_refrag(dp, dname)
        self._maybe_refrag(sp, sname, removed=True)
        return sdent

    # ---------------------------------------- cross-rank rename (slave)
    def _peer_call(self, rank: int, op: str, args: dict,
                   timeout: float = 15.0):
        """Synchronous MDS-to-MDS request (the slave-request channel,
        ref: Migrator.h:51 / MMDSSlaveRequest).  MUST run off the
        dispatch thread — the reply rides it."""
        tid = next(self._peer_tids)
        ev, slot = threading.Event(), []
        self._peer_pending[tid] = (ev, slot)
        req = MClientRequest(tid=tid, op=op, args=args)
        if not self.ms.connect(f"mds.{rank}").send_message(req):
            self._peer_pending.pop(tid, None)
            raise MDSError("EAGAIN", f"mds.{rank} unreachable")
        if not ev.wait(timeout):
            self._peer_pending.pop(tid, None)
            raise MDSError("EAGAIN", f"mds.{rank} slave call timeout")
        rep = slot[0]
        if rep.forward is not None and rep.forward >= 0:
            # the subtree moved mid-protocol: the slave did NOT apply.
            # EAGAIN (not success!) — the caller re-resolves the
            # authority and retries; treating this as success would
            # commit a src removal whose dst insert never happened.
            raise MDSError("EAGAIN",
                           f"slave forwarded to mds.{rep.forward}")
        if rep.result < 0:
            raise MDSError(rep.errno_name or "EIO", op)
        return rep.out

    def _cross_rank_rename(self, msg, a: dict, dst_rank: int) -> None:
        """Two-phase rename into another rank's subtree (ref:
        Server::handle_client_rename:7310 coordinating witnesses
        through the Migrator):

        1. journal a durable INTENT (this rank, the src authority, is
           the transaction coordinator — replay finishes half-done
           renames, see _recover_xrenames);
        2. slave-insert the dentry at the destination authority
           (idempotent: same-ino insert acks success);
        3. journal the src removal + intent clear, evict our caps on
           the moved inode(s) so the new authority grants them fresh.

        The inode record itself (embedded or itable-backed) lives in
        the shared metadata pool, so identity and hardlinks survive
        the move untouched."""
        reply_err = None
        out = None
        try:
            out = self._xrename_run(a, dst_rank)
        except MDSError as e:
            reply_err = e.errno_name
        except Exception as e:      # noqa: BLE001 — reply, never hang
            dout("mds", 0).write("%s: cross-rank rename failed: %r",
                                 self.name, e)
            reply_err = "EIO"
        if reply_err is None:
            reply = MClientReply(tid=msg.tid, result=0, out=out)
        else:
            reply = MClientReply(tid=msg.tid,
                                 result=_ERRNO.get(reply_err, -22),
                                 errno_name=reply_err)
        with self._lock:
            revokes, self._pending_revokes = self._pending_revokes, []
        self.ms.connect(msg.src).send_message(reply)
        for client, cap_msg in revokes:
            self.ms.connect(client).send_message(cap_msg)

    def _xrename_run(self, a: dict, dst_rank: int):
        src = self._norm(a["src"])
        dst = self._norm(a["dst"])
        with self._lock:
            sp, sname, sdent = self._resolve(a["src"])
            if sdent is None:
                raise MDSError("ENOENT", a["src"])
            if dst.startswith(src + "/"):
                raise MDSError("EINVAL", f"{dst} is inside {src}")
            ino = self._dent_ino(sdent)
        # revoke-and-wait BEFORE touching the namespace: EXCL holders
        # flush buffered sizes against the still-existing src path
        # (the xlock-then-rename ordering Server::handle_client_rename
        # gets from the Locker) — evicting after the commit would
        # race their flushes against a vanished dentry
        self._revoke_and_wait(sdent)
        with self._lock:
            sp, sname, sdent = self._resolve(a["src"])
            if sdent is None:
                raise MDSError("ENOENT", a["src"])
            # deterministic per-(rank, ino) key: a client retry after
            # an ambiguous failure re-drives the SAME intent instead
            # of stacking duplicates
            intent_id = f"{self.rank}.{ino}"
            self._journal("xrename_prepare", [
                ("mkobj", XRENAME_OBJ),
                ("set", XRENAME_OBJ, {intent_id: json.dumps({
                    "src": src, "dst": dst, "dent": sdent,
                    "dst_rank": dst_rank})})])
        try:
            self._peer_call(dst_rank, "slave_rename_insert", {
                "dst": dst, "dent": sdent})
        except MDSError as e:
            if e.errno_name == "EAGAIN":
                # AMBIGUOUS: the slave may have applied the insert
                # (slow peer / lost reply).  The intent must survive
                # — aborting here could leave the file visible at
                # BOTH paths with no record to reconcile.  The client
                # retries (same intent key) and boot-time recovery
                # finishes orphans.
                raise
            # definitive refusal (EEXIST/ENOTEMPTY/ENOTDIR): the
            # insert did not happen, dropping the intent is safe
            with self._lock:
                self._journal("xrename_abort", [
                    ("rm", XRENAME_OBJ, [intent_id])])
            raise
        with self._lock:
            self._journal("xrename_commit", [
                ("rm", self._dent_obj(sp, sname), [sname]),
                ("rm", XRENAME_OBJ, [intent_id])])
            self._evict_moved(sdent)
        return sdent

    @staticmethod
    def _dent_ino(dent: dict):
        """A dentry's logical inode number — remote (hardlink)
        dentries carry it as the itable pointer."""
        return dent["remote"] if "remote" in dent else dent["ino"]

    def _inos_under(self, dent: dict) -> list[int]:
        """File inode numbers covered by a dentry (the dentry itself,
        or every file in the realm when it's a directory) — the one
        walk behind pin/rename eviction and revoke-and-wait."""
        if dent.get("type") == "d":
            return [self._dent_ino(d) for _i, ents, _ch in
                    self._walk_realm(dent["ino"])
                    for d in ents.values() if d.get("type") == "f"]
        return [self._dent_ino(dent)]

    def _revoke_and_wait(self, dent: dict,
                         timeout: float | None = None) -> None:
        """Queue revokes for every cap holder under `dent`, send them
        now (we run off the dispatch thread), and wait for the acks —
        unacked holders past the grace are force-dropped by
        _queue_revoke's own timeout machinery."""
        timeout = self.REVOKE_GRACE if timeout is None else timeout
        with self._lock:
            pending = {i for i in self._inos_under(dent)
                       if self._caps.get(i)}
            for i in pending:
                self._queue_revoke(i, list(self._caps.get(i, {})))
            revokes, self._pending_revokes = self._pending_revokes, []
        for client, cap_msg in revokes:
            self.ms.connect(client).send_message(cap_msg)
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            with self._lock:
                if not any(self._caps.get(i) for i in pending):
                    return
            time.sleep(0.02)

    def _evict_moved(self, dent: dict) -> None:
        """Drop cap/open authority for inode(s) that just left our
        subtrees (the set_pin handoff, per-inode): clients re-acquire
        through the destination rank."""
        for ino in self._inos_under(dent):
            holders = list(self._caps.get(ino, {}))
            if holders:
                self._queue_revoke(ino, holders)
            self._caps.pop(ino, None)
            self._opens.pop(ino, None)

    def _op_slave_rename_insert(self, a):
        """Destination-side half of a cross-rank rename: validate and
        journal the dentry insert (ref: the slave request's
        PREPARE/COMMIT collapsed to one idempotent insert — the
        coordinator's durable intent provides the crash story)."""
        if not str(a.get("__client", "")).startswith("mds."):
            raise MDSError("EINVAL", "slave op from non-mds")
        auth = self._authority(a["dst"])
        if auth != self.rank:
            raise MDSForward(auth)   # table moved mid-flight
        dent = a["dent"]
        dp, dname, ddent = self._resolve(a["dst"])
        if not dname:
            raise MDSError("EINVAL", a["dst"])
        if ddent is not None:
            if self._dent_ino(ddent) == self._dent_ino(dent):
                return None          # replayed intent: already landed
            if ddent["type"] == "d":
                if self._readdir(ddent["ino"]):
                    raise MDSError("ENOTEMPTY", a["dst"])
            elif dent["type"] == "d":
                raise MDSError("ENOTDIR", a["dst"])
        deltas = [("set", self._dent_obj(dp, dname), {dname: json.dumps(dent)})]
        if ddent is not None and ddent["type"] == "d":
            deltas.extend(self._dir_rmobj_deltas(ddent["ino"]))
        self._journal("xrename_in", deltas)
        return None

    def _recover_xrenames(self) -> None:
        """Finish cross-rank renames whose coordinator crashed between
        intent and commit: re-drive the (idempotent) slave insert and
        the src removal.  Runs once per boot off-thread; intents that
        still can't complete stay durable for the next boot."""
        try:
            vals, _ = self.meta.get_omap_vals(XRENAME_OBJ)
        except RadosError:
            return
        for intent_id, blob in vals.items():
            try:
                rec = json.loads(blob)
                if not intent_id.startswith(f"{self.rank}."):
                    continue
                self._peer_call(rec["dst_rank"],
                                "slave_rename_insert",
                                {"dst": rec["dst"],
                                 "dent": rec["dent"]})
                with self._lock:
                    sp, sname, sdent = self._resolve(rec["src"])
                    deltas = [("rm", XRENAME_OBJ, [intent_id])]
                    if sdent is not None and self._dent_ino(sdent) \
                            == self._dent_ino(rec["dent"]):
                        deltas.append(("rm", self._dent_obj(sp, sname), [sname]))
                    self._journal("xrename_commit", deltas)
            except (MDSError, RadosError, KeyError, ValueError) as ex:
                dout("mds", 1).write(
                    "%s: xrename intent %s not recovered: %r",
                    self.name, intent_id, ex)

    def _op_setattr(self, a):
        parent, name, dent = self._resolve(a["path"])
        if dent is None:
            raise MDSError("ENOENT", a["path"])
        rec = self._record_of(dent)
        for k in ("size", "mtime"):
            if k in a:
                if k == "size" and a.get("grow_only"):
                    # cap-less writers flush sizes grow-only so a stale
                    # flush can't regress another writer's extension
                    # (ref: the size ordering Locker's xlock provides)
                    rec[k] = max(rec.get(k, 0), a[k])
                else:
                    rec[k] = a[k]
        self._update_record(parent, name, dent, rec, "setattr")
        return rec

    def _op_statfs(self, a):
        def count(ino):
            files = dirs = 0
            for d in self._readdir(ino).values():
                if d["type"] == "d":
                    dirs += 1
                    f2, d2 = count(d["ino"])
                    files, dirs = files + f2, dirs + d2
                else:
                    files += 1
            return files, dirs
        files, dirs = count(ROOT_INO)
        return {"files": files, "dirs": dirs,
                "next_ino": self._next_ino}

    # --------------------------------------------------------- dispatch
    def ms_dispatch(self, msg: Message) -> bool:
        # the liveness worker beats on every message AND every tick
        # (ref: MDSRank heartbeat_reset in _dispatch): a daemon is
        # unhealthy only when both loops stopped past the grace
        self.hbmap.reset_timeout(self._hb_handle)
        if isinstance(msg, MFSMap):
            self._handle_fsmap(msg)
            return True
        if isinstance(msg, MMonCommandAck):
            # only crash posts ride the command channel from an MDS;
            # a successful ack retires the spooled copy
            self.crash_reporter.on_ack(msg.tid, msg.result)
            return True
        if isinstance(msg, MClientCaps):
            self.handle_caps(msg)
            return True
        if isinstance(msg, MClientReply):
            # slave-call reply from a peer rank
            entry = self._peer_pending.pop(msg.tid, None)
            if entry is not None:
                ev, slot = entry
                slot.append(msg)
                ev.set()
            return True
        if not isinstance(msg, MClientRequest):
            return False
        from ..common.options import global_config
        from ..common.tracing import new_trace, trace_scope
        opkey = (msg.src, msg.tid)
        self.op_tracker.start(
            opkey, f"client_request({msg.src} tid={msg.tid} "
                   f"{msg.op})")
        # frontend trace root: a traced metadata op's journal/objecter
        # writes nest under this span via the ambient scope
        ctx = new_trace() if msg.trace is None and \
            global_config()["blkin_trace_all"] else msg.trace
        sp = self.tracer.start_span(ctx, f"mds_op:{msg.op}")
        try:
            with trace_scope(ctx):
                args = dict(msg.args)
                args["__client"] = msg.src
                out = self.handle_op(msg.op, args)
            reply = MClientReply(tid=msg.tid, result=0, out=out)
        except _CrossRankRename as x:
            # two-phase protocol runs off the dispatch thread (the
            # slave reply would otherwise deadlock this thread); the
            # worker sends the client reply itself
            self.op_tracker.finish(opkey, "cross_rank_deferred")
            self.tracer.finish(sp)
            threading.Thread(
                target=self._cross_rank_rename,
                args=(msg, dict(msg.args), x.dst_rank),
                daemon=True).start()
            return True
        except MDSForward as f:
            reply = MClientReply(tid=msg.tid, result=0,
                                 forward=f.rank)
        except MDSError as e:
            reply = MClientReply(tid=msg.tid,
                                 result=_ERRNO.get(e.errno_name, -22),
                                 errno_name=e.errno_name)
        except (KeyError, AttributeError, TypeError, ValueError) as e:
            reply = MClientReply(tid=msg.tid, result=-22,
                                 errno_name="EINVAL")
            dout("mds", 1).write("%s: bad request %s: %s", self.name,
                                 msg.op, e)
        self.op_tracker.finish(
            opkey, "replied" if reply.result == 0
            else f"error:{reply.errno_name}")
        if sp is not None:
            sp.event("replied" if reply.result == 0
                     else f"error:{reply.errno_name}")
            self.tracer.finish(sp)
        # drain cap revokes queued by the op AFTER the reply so the
        # EAGAIN lands first (ref: Locker issues revokes async)
        with self._lock:
            revokes, self._pending_revokes = self._pending_revokes, []
        self.ms.connect(msg.src).send_message(reply)
        for client, cap_msg in revokes:
            self.ms.connect(client).send_message(cap_msg)
        return True


class MDSStandby(Dispatcher):
    """A standby MDS daemon (ref: the standby/standby-replay daemon
    states in src/mds/MDSMap.h + MDSMonitor promotion):

    * registers with the monitor cluster via ``standby`` beacons and
      waits in the pool;
    * optionally warm-tails a target rank's journal
      (``mds_standby_replay``) so a takeover starts from a warm
      cursor;
    * when the monitor assigns its gid to a failed rank (fsmap state
      ``replay``), it boots a full :class:`MDSDaemon` for that rank —
      the daemon's constructor replays the dead holder's journal tail,
      then walks resolve -> active via beacons.

    The promoted daemon binds the rank's entity name (``mds.<rank>``),
    so clients keep addressing ranks the same way before and after a
    failover.
    """

    def __init__(self, network, rados, name: str = "a", mon=(),
                 metadata_pool: str = "cephfs_metadata",
                 data_pool: str = "cephfs_data",
                 standby_replay_rank: int | None = None,
                 keyring=None):
        self.network = network
        self.rados = rados
        self.name = f"mds.{name}"
        self.mons = [mon] if isinstance(mon, str) else list(mon or [])
        self.metadata_pool = metadata_pool
        self.data_pool = data_pool
        self.keyring = keyring
        self.gid = _alloc_gid()
        self.standby_replay_rank = -1 if standby_replay_rank is None \
            else int(standby_replay_rank)
        #: the rank daemon after promotion
        self.active: MDSDaemon | None = None
        self.rank: int | None = None
        #: journal entries warm-tailed while standby (observability)
        self.tailed = 0
        self._tail_pos = (0, 0)
        self._seq = itertools.count(1)
        self._stop = threading.Event()
        self._promoting = False
        self.inject_beacon_mute = False
        self.ms = Messenger.create(network, self.name, threaded=True)
        self.ms.add_dispatcher(self)

    def init(self) -> None:
        self.ms.start()
        threading.Thread(target=self._standby_loop,
                         daemon=True).start()

    def _standby_loop(self) -> None:
        from ..common.options import global_config
        self._send_beacon()
        while not self._stop.wait(
                global_config()["mds_beacon_interval"]):
            self._send_beacon()
            if self.standby_replay_rank >= 0 and \
                    global_config()["mds_standby_replay"]:
                self._tail_journal()

    def _send_beacon(self) -> None:
        if self.inject_beacon_mute or self._promoting:
            return
        msg = MMDSBeacon(gid=self.gid, name=self.name, rank=-1,
                         state="standby", seq=next(self._seq),
                         standby_replay_rank=self.standby_replay_rank)
        for m in self.mons:
            if self.ms.connect(m).send_message(msg):
                return

    def _tail_journal(self) -> None:
        """Warm-follow the target rank's WAL without registering as a
        journal client (a registered-but-lagging follower would pin
        the active's trim; ref: the standby-replay MDS replaying
        MDLog continuously)."""
        try:
            meta = self.rados.open_ioctx(self.metadata_pool)
            jr = Journaler(meta, journal_id(self.standby_replay_rank),
                           client_id=f"standby.{self.gid}")
            if not jr.exists():
                return
            n = [0]
            pos = jr.replay(lambda _t, _e: n.__setitem__(0, n[0] + 1),
                            from_pos=self._tail_pos)
            self._tail_pos = pos
            self.tailed += n[0]
        except Exception as ex:      # noqa: BLE001
            # tailing is an optimization, never fatal — but the skip
            # still leaves a trace (errcheck coverage points here)
            dout("mds", 10).write(
                "%s: standby-replay tail skipped: %s", self.name, ex)

    # ------------------------------------------------------- promotion
    def ms_dispatch(self, msg: Message) -> bool:
        if not isinstance(msg, MFSMap):
            return False
        m = msg.fsmap
        if m is None or self._promoting or self.active is not None:
            return True
        for rank, info in m.ranks.items():
            if info.gid == self.gid and info.state == "replay":
                self._promoting = True
                threading.Thread(target=self._promote, args=(rank,),
                                 daemon=True).start()
                break
        return True

    def _promote(self, rank: int) -> None:
        """Take over the failed rank: boot an MDSDaemon (journal
        replay happens in its constructor, before the rank's entity
        name starts serving)."""
        dout("mds", 1).write("%s: promoting to mds.%d (gid %d)",
                             self.name, rank, self.gid)
        deadline = time.monotonic() + 30.0
        wait = Backoff(base_s=0.1, cap_s=1.0)
        while True:
            d = None
            try:
                d = MDSDaemon(self.network, self.rados, rank=rank,
                              metadata_pool=self.metadata_pool,
                              data_pool=self.data_pool,
                              mon=self.mons, gid=self.gid,
                              keyring=self.keyring)
                d.init()
                break
            except (ValueError, OSError):
                # the dead holder's entity name/port is still
                # unbinding: back off and retry the whole boot
                if d is not None:
                    try:
                        d.kill()
                    except Exception as ex:   # noqa: BLE001
                        dout("mds", 5).write(
                            "promote: teardown of half-booted rank "
                            "daemon failed: %s", ex)
                if time.monotonic() >= deadline:
                    self._promoting = False
                    raise
                wait.sleep()
        self.active = d
        self.rank = rank
        self._stop.set()          # standby beacons end; the rank's own
        #                           beacon loop carries liveness now

    # -------------------------------------------------------- teardown
    def shutdown(self) -> None:
        self._stop.set()
        if self.active is not None:
            self.active.shutdown()
        self.ms.shutdown()

    def kill(self) -> None:
        """Hard stop (no flush) — thrasher model."""
        self._stop.set()
        if self.active is not None:
            self.active.kill()
        self.ms.shutdown()
