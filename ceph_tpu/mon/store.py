"""MonitorStore: the mon's versioned key/value backing store.

Shape of src/mon/MonitorDBStore.h: values live under (prefix, key),
mutations batch into transactions applied atomically, and services
keep versioned entries ("%d" keys) plus first/last_committed markers.
In-memory here (the reference sits on RocksDB); the transaction journal
makes replay/replication possible later.
"""
from __future__ import annotations

import threading

from ..common.lockdep import make_lock
from dataclasses import dataclass, field
from typing import Any, Iterator

from ..msg import encoding as wire


@dataclass
class StoreTransaction:
    """Atomic batch of puts/erases
    (ref: MonitorDBStore.h:51 Transaction)."""
    ops: list[tuple[str, str, str, Any]] = field(default_factory=list)

    def put(self, prefix: str, key: str | int, value: Any) -> None:
        self.ops.append(("put", prefix, str(key), value))

    def erase(self, prefix: str, key: str | int) -> None:
        self.ops.append(("erase", prefix, str(key), None))

    def erase_range(self, prefix: str, first: str | int,
                    last: str | int) -> None:
        """erase [first, last) like compact_prefix trimming."""
        self.ops.append(("erase_range", prefix, str(first), str(last)))

    @property
    def empty(self) -> bool:
        return not self.ops

    def encode(self) -> bytes:
        """Typed wire encoding — paxos BEGIN/COMMIT carry these blobs
        between mons (ref: MonitorDBStore.h Transaction::encode)."""
        return wire.encode(self.ops)

    @classmethod
    def decode(cls, data: bytes) -> "StoreTransaction":
        ops = wire.decode(data)
        if not isinstance(ops, list):
            raise wire.WireError("store transaction must be a list")
        return cls(ops=ops)


class MonitorStore:
    """(prefix, key) -> value with atomic transactions
    (ref: MonitorDBStore.h:161 apply_transaction).

    With a `KeyValueDB` backing (ceph_tpu.kv — the RocksDB slot the
    reference's MonitorDBStore sits on), every transaction writes
    through durably and a restarted mon resumes from its committed
    paxos state instead of bootstrap."""

    def __init__(self, db=None) -> None:
        self._data: dict[tuple[str, str], Any] = {}
        self._lock = make_lock("mon.store")
        self.db = db
        if db is not None:
            self._data = dict(db.all_items())

    @property
    def empty(self) -> bool:
        with self._lock:
            return not self._data

    def apply_transaction(self, tx: StoreTransaction) -> None:
        with self._lock:
            kvt = self.db.transaction() if self.db is not None else None
            for op, prefix, key, value in tx.ops:
                if op == "put":
                    self._data[(prefix, key)] = value
                    if kvt is not None:
                        kvt.set(prefix, key, value)
                elif op == "erase":
                    self._data.pop((prefix, key), None)
                    if kvt is not None:
                        kvt.rmkey(prefix, key)
                elif op == "erase_range":
                    lo, hi = int(key), int(value)
                    # versioned keys are decimal ints
                    for k in [k for k in self._data
                              if k[0] == prefix and k[1].isdigit()
                              and lo <= int(k[1]) < hi]:
                        del self._data[k]
                        if kvt is not None:
                            kvt.rmkey(k[0], k[1])
            if kvt is not None:
                self.db.submit_transaction(kvt)

    def get(self, prefix: str, key: str | int, default: Any = None) -> Any:
        with self._lock:
            return self._data.get((prefix, str(key)), default)

    def exists(self, prefix: str, key: str | int) -> bool:
        with self._lock:
            return (prefix, str(key)) in self._data

    def get_int(self, prefix: str, key: str | int, default: int = 0) -> int:
        v = self.get(prefix, key)
        return default if v is None else int(v)

    def keys(self, prefix: str) -> Iterator[str]:
        with self._lock:
            return iter(sorted(k[1] for k in self._data if k[0] == prefix))

    def export_data(self) -> bytes:
        """Full snapshot for mon full-sync (ref: Monitor.cc sync_*).
        Typed encoding: the blob crosses the wire in MPaxosStoreSync."""
        with self._lock:
            return wire.encode(self._data)

    def import_data(self, blob: bytes) -> None:
        data = wire.decode(blob)
        if not isinstance(data, dict):
            raise wire.WireError("store snapshot must be a dict")
        with self._lock:
            self._data = data
            if self.db is not None:
                # full-sync REPLACES the store: stale keys absent from
                # the snapshot must die in the same transaction, or a
                # restart resurrects diverged paxos/osdmap versions
                kvt = self.db.transaction()
                for prefix in {k[0] for k, _v in self.db.all_items()}:
                    kvt.rmkeys_by_prefix(prefix)
                for (prefix, key), value in data.items():
                    kvt.set(prefix, key, value)
                self.db.submit_transaction(kvt)
