"""restful: the programmatic REST admin API served by the mgr.

The restful module analogue (ref: src/pybind/mgr/restful/module.py +
api/*.py — a JSON HTTP surface over the same mon-command plumbing the
CLI uses, authenticated by API keys).  Endpoints mirror the
reference's resource map:

    GET  /                      endpoint index
    GET  /status                cluster status (mon `status`)
    GET  /health                health checks (mon `health detail`)
    GET  /df                    usage (mon `df`)
    GET  /osd                   osds with up/in/weight (mon `osd dump`)
    GET  /osd/<id>              one osd
    POST /osd/<id>/command      {"command": "down"|"out"|"in"}
    GET  /pool                  pools (mon `osd pool ls` + `get`)
    POST /pool                  {"name": .., "pg_num": ..,
                                 "type": "replicated"|"erasure", ...}
    DELETE /pool/<name>
    GET  /pg                    pg summary (mon `pg stat`)

Auth (ref: restful's api-key store): requests must carry
`Authorization: Bearer <key>`; keys are minted by `create_key()` and
held by the server (the reference persists them in the mon config-key
store; here the mgr process owns the listener, so process-local is
the same trust domain).  A server started with no keys is open —
test/dev mode, like the reference's self-signed bootstrap.
"""
from __future__ import annotations

import json
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _Html(str):
    """Marker type: a route returning _Html is served as text/html.
    An explicit declaration, not content sniffing — a plain string
    payload that happens to start with '<' must still go out as
    JSON."""


class RestfulServer:
    """One HTTP listener bound to a mgr (anything with mon_command)."""

    def __init__(self, mgr, host: str = "127.0.0.1", port: int = 0):
        self.mgr = mgr
        self.keys: dict[str, str] = {}      # key -> name
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, status: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _html(self, status: int, markup: str) -> None:
                body = markup.encode()
                self.send_response(status)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _run(self, method: str) -> None:
                try:
                    if not srv._authorized(self.headers):
                        return self._json(401, {"error": "unauthorized"})
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n)) if n else {}
                    status, payload = srv._route(method,
                                                 self.path, body)
                    if isinstance(payload, _Html):
                        self._html(status, str(payload))
                        return
                    self._json(status, payload)
                except Exception as e:      # noqa: BLE001 — admin API:
                    # every failure must come back as JSON, not a
                    # dropped connection
                    self._json(500, {"error": str(e)})

            def do_GET(self):
                self._run("GET")

            def do_POST(self):
                self._run("POST")

            def do_DELETE(self):
                self._run("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="mgr-restful",
            daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- auth ------------------------------------------------------------
    def create_key(self, name: str = "admin") -> str:
        key = secrets.token_urlsafe(24)
        self.keys[key] = name
        return key

    def delete_key(self, key: str) -> None:
        self.keys.pop(key, None)

    def _authorized(self, headers) -> bool:
        if not self.keys:
            return True                     # open/dev mode
        auth = headers.get("Authorization", "")
        return auth.startswith("Bearer ") and \
            auth[len("Bearer "):] in self.keys

    # -- plumbing --------------------------------------------------------
    def _mon(self, cmd: dict):
        """mon command -> parsed payload; non-zero rc raises (surfaces
        as the handler's JSON 500, carrying the mon's outs text)."""
        rc, outs, outb = self.mgr.mon_command(cmd)
        if rc != 0:
            raise RuntimeError(outs or f"rc={rc}")
        return outb if outb is not None else outs

    def _route(self, method: str, path: str, body: dict):
        parts = [p for p in path.split("?")[0].split("/") if p]
        if not parts:
            return 200, {"endpoints": [
                "/status", "/health", "/df", "/osd", "/osd/<id>",
                "/osd/<id>/command", "/pool", "/pool/<name>", "/pg",
                "/dashboard", "/dashboard?format=html"]}
        head = parts[0]
        if method == "GET":
            if head == "dashboard":
                return self._dashboard(path)
            if head == "status":
                return 200, self._mon({"prefix": "status"})
            if head == "health":
                return 200, self._mon({"prefix": "health detail"})
            if head == "df":
                return 200, self._mon({"prefix": "df"})
            if head == "pg":
                return 200, self._mon({"prefix": "pg stat"})
            if head == "osd":
                dump = self._mon({"prefix": "osd dump"})
                osds = dump.get("osds", dump)
                if len(parts) == 1:
                    return 200, osds
                try:
                    want = int(parts[1])
                except ValueError:
                    # client error, not a 500 from the blanket except
                    return 400, {"error": "bad osd id"}
                for o in osds:
                    if int(o.get("osd", -1)) == want:
                        return 200, o
                return 404, {"error": f"osd.{want} not found"}
            if head == "pool":
                names = self._mon({"prefix": "osd pool ls"})
                out = []
                for nm in names:
                    info = {"pool_name": nm}
                    for var in ("size", "min_size", "pg_num",
                                "erasure_code_profile"):
                        try:
                            got = self._mon({"prefix": "osd pool get",
                                             "pool": nm, "var": var})
                            if isinstance(got, dict):
                                info.update(got)
                            else:
                                info[var] = got
                        except RuntimeError:
                            pass
                    out.append(info)
                if len(parts) == 1:
                    return 200, out
                for p in out:
                    if p["pool_name"] == parts[1]:
                        return 200, p
                return 404, {"error": f"pool {parts[1]} not found"}
        if method == "POST" and head == "osd" and len(parts) == 3 \
                and parts[2] == "command":
            command = body.get("command", "")
            if command not in ("down", "out", "in"):
                return 400, {"error": f"bad command {command!r}"}
            self._mon({"prefix": f"osd {command}",
                       "ids": [parts[1]]})
            return 200, {"ok": True}
        if method == "POST" and head == "pool":
            name = body.get("name", "")
            if not name:
                return 400, {"error": "name required"}
            cmd = {"prefix": "osd pool create", "pool": name,
                   "pg_num": int(body.get("pg_num", 8))}
            if body.get("type"):
                cmd["pool_type"] = body["type"]
            if body.get("erasure_code_profile"):
                cmd["erasure_code_profile"] = \
                    body["erasure_code_profile"]
            self._mon(cmd)
            return 200, {"ok": True, "pool": name}
        if method == "DELETE" and head == "pool" and len(parts) == 2:
            self._mon({"prefix": "osd pool delete",
                       "pool": parts[1],
                       "pool2": parts[1],
                       "yes_i_really_really_mean_it": True})
            return 200, {"ok": True}
        return 404, {"error": f"no route {method} {path}"}

    # -- dashboard (read-only status view; ref: the mgr dashboard
    # module's landing page, src/pybind/mgr/dashboard — collapsed to
    # one JSON document with an HTML rendering over the same data) --
    def _dashboard(self, path: str):
        from urllib.parse import parse_qs, urlparse
        q = {k: v[0] for k, v in
             parse_qs(urlparse(path).query).items()}
        data = self.dashboard_data()
        if q.get("format") == "html":
            return 200, _Html(self._dashboard_html(data))
        return 200, data

    def dashboard_data(self) -> dict:
        """One read-only cluster summary: health, usage, pg states,
        multisite sync lag, recent crashes, slow ops."""
        status = self._mon({"prefix": "status"})
        health = self._mon({"prefix": "health detail"})
        df = self._mon({"prefix": "df"})
        try:
            crashes = self._mon({"prefix": "crash ls-new"}) or []
        except RuntimeError:
            crashes = []
        from ..rgw.multisite import sync_status_all
        slow = health.get("checks", {}).get("SLOW_OPS", {})
        return {
            "health": {"status": health.get("status"),
                       "checks": health.get("checks", {})},
            "osdmap": status.get("osdmap", {}),
            "pg_states": status.get("pgmap", {})
            .get("pgs_by_state", {}),
            "usage": {"total_kb": df.get("total_kb", 0),
                      "used_kb": df.get("used_kb", 0),
                      "avail_kb": df.get("avail_kb", 0),
                      "pools": df.get("pools", {})},
            "sync": sync_status_all(),
            "recent_crashes": [
                {"crash_id": c.get("crash_id"),
                 "entity": c.get("entity"),
                 "timestamp": c.get("timestamp")}
                for c in crashes],
            "slow_ops": {"summary": slow.get("summary", ""),
                         "detail": slow.get("detail", [])},
        }

    @staticmethod
    def _dashboard_html(data: dict) -> str:
        """Server-rendered read-only view — no scripts, one page."""
        from html import escape

        def rows(pairs):
            return "".join(
                f"<tr><th>{escape(str(k))}</th>"
                f"<td>{escape(str(v))}</td></tr>" for k, v in pairs)

        checks = data["health"]["checks"]
        h = ["<!DOCTYPE html><html><head><title>ceph-tpu dashboard"
             "</title><style>body{font-family:monospace}"
             "table{border-collapse:collapse;margin:8px 0}"
             "th,td{border:1px solid #999;padding:2px 8px;"
             "text-align:left}</style></head><body>",
             f"<h1>cluster: {escape(str(data['health']['status']))}"
             "</h1>"]
        if checks:
            h.append("<h2>health checks</h2><table>" + rows(
                (k, v.get("summary", "") if isinstance(v, dict)
                 else v) for k, v in sorted(checks.items()))
                + "</table>")
        h.append("<h2>osds</h2><table>"
                 + rows(sorted(data["osdmap"].items())) + "</table>")
        h.append("<h2>pg states</h2><table>"
                 + rows(sorted(data["pg_states"].items()))
                 + "</table>")
        u = data["usage"]
        h.append("<h2>usage</h2><table>" + rows(
            [("total_kb", u["total_kb"]), ("used_kb", u["used_kb"]),
             ("avail_kb", u["avail_kb"])] +
            [(f"pool {p}", f"{st.get('objects', 0)} objects, "
              f"{st.get('bytes', 0)} bytes")
             for p, st in sorted(u["pools"].items())]) + "</table>")
        if data["sync"]:
            h.append("<h2>multisite sync</h2><table>" + rows(
                (f"{r['zone']} <- {r['source']}",
                 f"lag {r['lag_entries']} entries, "
                 f"{r['behind_shards']} shards behind")
                for r in data["sync"]) + "</table>")
        if data["recent_crashes"]:
            h.append("<h2>recent crashes</h2><table>" + rows(
                (c.get("crash_id", "?"), c.get("entity", "?"))
                for c in data["recent_crashes"]) + "</table>")
        if data["slow_ops"]["summary"]:
            h.append("<h2>slow ops</h2><p>"
                     + escape(data["slow_ops"]["summary"]) + "</p>"
                     "<ul>" + "".join(
                         f"<li>{escape(str(d))}</li>"
                         for d in data["slow_ops"]["detail"])
                     + "</ul>")
        h.append("</body></html>")
        return "".join(h)
